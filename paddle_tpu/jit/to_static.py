"""@to_static: compile the imperative training step into one XLA computation.

The reference reaches whole-program execution via AST transformation →
ProgramDesc → run_program op (`python/paddle/fluid/dygraph/dygraph_to_static/
program_translator.py:759`, `partial_program.py:111`,
`operators/run_program_op.cc:176`). On TPU we get the same result by *tracing*:
the eager Tensor wraps whatever jax hands it, so running the user's python
step function under `jax.jit` with all framework state (parameters, buffers,
optimizer accumulators, RNG key, lr) threaded through as donated inputs turns
`forward(); loss.backward(); opt.step()` into a single compiled, fused,
buffer-aliased XLA program — the "north star" fast path.

Sharding: state tensors carry an optional PartitionSpec (`Tensor.pspec`);
when a mesh is active (fleet.init / paddle_tpu.distributed.set_mesh) state and
inputs are device_put onto NamedShardings before compilation, and GSPMD
inserts the collectives (the analog of the reference's c_allreduce insertion
by fleet meta-optimizers).
"""
import functools
import weakref

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from ..core import state as state_mod
from ..core.tensor import Tensor
from ..observability import tracing as _obs
from ..testing import faults as _faults

_is_tracing = False

# step hooks: callables run inside every traced step body, after the
# framework state swaps to tracers and before the user function — the seam
# ZeRO-3 uses for just-in-time parameter materialization (per-bucket
# all_gather from the sharded carry). A hook returns an optional cleanup
# callable invoked when the body ends (success or error). Held weakly so a
# dead owner (a dropped optimizer) stops contributing ops.
_step_hooks = []


def register_step_hook(hook):
    """Register ``hook() -> cleanup|None`` to run at every step-body trace
    entry. Hooks are held WEAKLY (bound methods via WeakMethod) so the
    hook dies with its owner instead of pinning it — which means a bare
    closure/lambda with no other strong reference is collected before it
    ever fires; pass a bound method or a module-level function.
    Re-registering the same callable is a no-op."""
    for ref in _step_hooks:
        if ref() == hook:
            return hook
    _step_hooks.append(weakref.WeakMethod(hook)
                       if hasattr(hook, "__self__") else weakref.ref(hook))
    return hook


def _run_step_hooks(cleanups):
    """Run every live hook, appending each cleanup to ``cleanups`` AS IT
    IS PRODUCED — if a later hook raises, the caller's finally still
    unwinds the earlier hooks' overrides instead of leaking tracers onto
    live tensors."""
    dead = []
    for ref in _step_hooks:
        h = ref()
        if h is None:
            dead.append(ref)
            continue
        c = h()
        if c is not None:
            cleanups.append(c)
    for ref in dead:
        _step_hooks.remove(ref)


def _data_dependent_errors():
    import jax
    errs = []
    for name in ("TracerBoolConversionError", "ConcretizationTypeError",
                 "TracerIntegerConversionError"):
        e = getattr(jax.errors, name, None)
        if e is not None:
            errs.append(e)
    return tuple(errs)


_DATA_DEPENDENT_ERRORS = _data_dependent_errors()


def in_tracing():
    return _is_tracing


def _is_dynamic(x):
    return isinstance(x, (Tensor, jax.Array, np.ndarray, np.generic))


class _StateSwap:
    """Swap registered state values (and accumulated grads) with tracers for
    the trace duration. Grads thread through like the reference's persistable
    @GRAD vars: accumulated-but-unconsumed gradients survive the compiled
    call (e.g. a step that only runs backward, stepping eagerly later)."""

    def __init__(self, items, values, grads):
        self.items = items
        self.values = values
        self.grads = grads
        self.saved = None

    def __enter__(self):
        global _is_tracing
        self.saved = [(t._value, t._tape_node, t._grad) for _, t in self.items]
        for (_, t), v, g in zip(self.items, self.values, self.grads):
            t._value = v
            t._tape_node = None
            t._grad = g
        self._was_tracing = _is_tracing
        _is_tracing = True
        return self

    def capture(self):
        return ([t._value for _, t in self.items],
                [t._grad for _, t in self.items])

    def __exit__(self, *exc):
        global _is_tracing
        _is_tracing = self._was_tracing
        for (_, t), (v, node, g) in zip(self.items, self.saved):
            t._value = v
            t._tape_node = node
            t._grad = g
        return False


def _leaf_key(x):
    if _is_dynamic(x):
        return ("dyn", tuple(np.shape(x)), np.dtype(
            x.dtype if hasattr(x, "dtype") else type(x)).str)
    try:
        hash(x)
        return ("static", x)
    except TypeError:
        return ("static", repr(x))


def _shard_map():
    if hasattr(jax, "shard_map"):
        return jax.shard_map
    from jax.experimental.shard_map import shard_map
    return shard_map


def jnp_issubdtype(dtype):
    """Inexact leaves are pmean-able; ints (indices, counters) must be
    rank-invariant already and pass through untouched."""
    return np.issubdtype(np.dtype(dtype), np.inexact)


def _abstract_arg(v):
    """ShapeDtypeStruct twin of a call argument (sharding kept for jax
    Arrays) — lets the AOT ``lower().compile()`` stats path re-derive the
    exact program without pinning live HBM buffers in the entry."""
    if isinstance(v, jax.Array):
        try:
            multi = len(v.sharding.device_set) > 1
        except Exception:
            multi = False
        if multi:
            # mesh-resident state keeps its layout; single-device args
            # (host-fed batches) stay unconstrained — mixing their
            # default placement with the mesh's would fail AOT lowering
            return jax.ShapeDtypeStruct(v.shape, v.dtype,
                                        sharding=v.sharding)
        return jax.ShapeDtypeStruct(v.shape, v.dtype)
    arr = np.asarray(v)
    return jax.ShapeDtypeStruct(arr.shape, arr.dtype)


def _is_sharded_spec(spec):
    return spec is not None and any(s is not None for s in spec)


def _axis_sizes(mesh):
    return dict(zip(mesh.axis_names, mesh.devices.shape))


def _local_shape(shape, spec, mesh):
    """Per-rank block shape of a global array under a PartitionSpec."""
    if spec is None:
        return tuple(shape)
    sizes = _axis_sizes(mesh)
    shape = list(shape)
    for d, s in enumerate(spec):
        if s is None:
            continue
        for name in (s if isinstance(s, tuple) else (s,)):
            f = sizes.get(name, 1)
            if shape[d] % f:
                raise ValueError(
                    f"dim {d} of shape {tuple(shape)} is not divisible by "
                    f"mesh axis {name!r} (size {f})")
            shape[d] //= f
    return tuple(shape)


def _global_shape(shape, spec, mesh):
    """Inverse of _local_shape: scale a per-rank block back up."""
    if spec is None:
        return tuple(shape)
    sizes = _axis_sizes(mesh)
    shape = list(shape)
    for d, s in enumerate(spec):
        if s is None:
            continue
        for name in (s if isinstance(s, tuple) else (s,)):
            shape[d] *= sizes.get(name, 1)
    return tuple(shape)


def _analysis_trace(pure_fn, state_vals, dyn_template, grad_vals, n, info):
    """Abstractly trace ``pure_fn(state, dyn, grads)`` and decide which
    state/grad inputs the program actually reads. Fills ``info`` (via the
    trace itself) and returns ``(closed_jaxpr, val_used, grad_used)``.
    ``dyn_template``/``grad_vals`` entries may be ``jax.ShapeDtypeStruct``
    placeholders — only shape/dtype matter here, nothing executes."""
    a_args = (state_vals, dyn_template, grad_vals)
    a_leaves, a_tdef = jax.tree_util.tree_flatten(a_args)
    closed = jax.make_jaxpr(
        lambda *ls: pure_fn(*jax.tree_util.tree_unflatten(a_tdef, ls))
    )(*a_leaves)
    used_vars = set()
    for eqn in closed.jaxpr.eqns:
        # Literals (hasattr .val) may be unhashable; only Vars matter
        used_vars.update(v for v in eqn.invars if not hasattr(v, "val"))
    # an invar returned verbatim in the *user-visible* outputs (fn
    # returns an unmodified param) must stay a runtime input, not be
    # frozen as a constant. Only the first n_out outvars are the user
    # outputs — an invar in its OWN slot of the new_state/new_grads
    # passthrough tail must NOT mark it used (or nothing would ever be
    # skippable), but landing in a DIFFERENT slot (EMA/target-network
    # sync: a.set_value(b) creates no eqn) is a real use.
    used_vars.update(v for v in closed.jaxpr.outvars[:info["n_out"]]
                     if not hasattr(v, "val"))
    invar_slot = {}
    for i in range(n):
        invar_slot[closed.jaxpr.invars[i]] = ("val", i)
    pos_in = n + len(dyn_template)
    for i, g in enumerate(grad_vals):
        if g is not None:
            invar_slot[closed.jaxpr.invars[pos_in]] = ("grad", i)
            pos_in += 1
    pos_out = info["n_out"]
    for j in range(n):  # new_state tail
        v = closed.jaxpr.outvars[pos_out]
        if (not hasattr(v, "val")
                and invar_slot.get(v, ("val", j)) != ("val", j)):
            used_vars.add(v)
        pos_out += 1
    for j, present in enumerate(info["grad_out_mask"]):  # new_grads tail
        if present:
            v = closed.jaxpr.outvars[pos_out]
            if (not hasattr(v, "val")
                    and invar_slot.get(v, ("grad", j)) != ("grad", j)):
                used_vars.add(v)
            pos_out += 1
    leaf_used = [v in used_vars for v in closed.jaxpr.invars]
    # map flat leaves back to (state, dyn, grad) slots; None grads were
    # dropped by tree_flatten, so enumerate in flatten order
    val_used = leaf_used[:n]
    grad_used = {}
    pos = n + len(dyn_template)
    for i, g in enumerate(grad_vals):
        if g is not None:
            grad_used[i] = leaf_used[pos]
            pos += 1
    return closed, val_used, grad_used


class StaticFunction:
    """Callable wrapper with a compile cache keyed on arg shapes/dtypes and
    the framework-state registry version (reference: StaticFunction
    program_translator.py:232 + its program cache).

    ``scan_steps=k`` selects the scan-compiled step program: ``fn`` is the
    SINGLE-step body, the wrapper consumes ``[k, ...]``-stacked dynamic
    inputs, and the body is traced ONCE and rolled with ``jax.lax.scan``
    carrying the full framework state — trace/compile time is ~independent
    of k (the unrolled program's is linear in k), which is what unlocks
    large dispatch-amortization factors. See ``_build_scan``.
    """

    def __init__(self, fn, input_spec=None, donate_state=True,
                 scan_steps=None, dp_axis=None, accumulate_steps=None,
                 xla_flags=None):
        from . import xla_flags as _xla_flags_mod
        self._fn = fn
        self._cache = {}
        self._donate = donate_state
        self._input_spec = input_spec
        # per-program XLA compiler options (latency-hiding A/B knob):
        # resolved once at wrap time (env overlay included), applied to
        # every compiled entry via _jit(). Scan-stepped programs with no
        # explicit request default to the latency-hiding preset IF the
        # backend registers it — judged lazily at first build (probing
        # at wrap time would force backend init at decoration);
        # xla_flags=False opts out (the A/B control arm spelling)
        self._xla_flags = _xla_flags_mod.resolve(xla_flags)
        self._xla_flags_default_pending = (
            xla_flags is None and scan_steps is not None
            and not self._xla_flags)  # env flags outrank the default too
        self._flagged_jits = []
        if scan_steps is not None and int(scan_steps) < 1:
            raise ValueError(f"scan_steps must be >= 1, got {scan_steps}")
        self._scan_steps = int(scan_steps) if scan_steps is not None else None
        if dp_axis is not None and self._scan_steps is None:
            raise ValueError(
                "dp_axis is an option of the scan step program; pass "
                "scan_steps=k (k=1 compiles a single-step scan)")
        self._dp_axis = dp_axis
        self._accumulate_steps = None
        if accumulate_steps is not None:
            a = int(accumulate_steps)
            if self._scan_steps is None:
                raise ValueError(
                    "accumulate_steps is an option of the scan step "
                    "program; pass scan_steps=k")
            if a < 1:
                raise ValueError(
                    f"accumulate_steps must be >= 1, got {accumulate_steps}")
            if a > 1 and self._scan_steps % a:
                raise ValueError(
                    f"scan_steps={self._scan_steps} must be a multiple of "
                    f"accumulate_steps={a} (whole accumulation windows)")
            self._accumulate_steps = a if a > 1 else None
        self._last_aux = None
        functools.update_wrapper(self, fn)

    def _jit(self, fun, **kwargs):
        """``jax.jit`` for one compiled entry, carrying this program's
        XLA compiler options (``jit.xla_flags``): unknown-flag errors
        degrade to an unflagged recompile with the fallback recorded as
        provenance — see :meth:`xla_flags`."""
        from . import xla_flags as _xla_flags_mod
        if self._xla_flags_default_pending:
            self._xla_flags_default_pending = False
            preset = _xla_flags_mod.PRESETS[
                _xla_flags_mod.DEFAULT_SCAN_PRESET]
            if _xla_flags_mod.backend_accepts(preset):
                self._xla_flags = dict(preset)
        flagged = _xla_flags_mod.jit(fun, xla_flags=self._xla_flags,
                                     **kwargs)
        self._flagged_jits.append(flagged)
        return flagged

    def xla_flags(self):
        """Flag provenance of this program: the resolved per-program
        compiler options (env overlay included) and whether the backend
        accepted them — ``applied`` is True once a flagged compile
        succeeded, False after the unknown-flag fallback (with the
        error), None while no compiled entry has been judged yet. The
        value the bench records and runlogs carry next to any A/B
        row."""
        prov = {"flags": dict(self._xla_flags), "applied": None,
                "fallback_error": None}
        if not self._xla_flags:
            prov["applied"] = False  # nothing to apply
            return prov
        for fj in self._flagged_jits:
            if fj.applied is True:
                prov["applied"] = True
            elif fj.applied is False and prov["applied"] is None:
                prov["applied"] = False
            if fj.fallback_error and not prov["fallback_error"]:
                prov["fallback_error"] = fj.fallback_error
        return prov

    # -- sharding helpers -------------------------------------------------
    @staticmethod
    def _mesh():
        from ..distributed import parallel_env
        return parallel_env.current_mesh()

    @staticmethod
    def _place_state(items, mesh):
        """device_put state onto NamedShardings per tensor pspec (committed
        arrays steer GSPMD; donation keeps them in place thereafter). Arrays
        committed to a *different* mesh (stale from an earlier fleet.init)
        are re-placed onto the current one."""
        for _, t in items:
            v = t._value
            spec = t.pspec if t.pspec is not None else PartitionSpec()
            desired = NamedSharding(mesh, spec)

            def _placed(arr):
                if isinstance(arr, jax.Array) and getattr(arr, "committed", False):
                    try:
                        if arr.sharding.is_equivalent_to(desired, arr.ndim):
                            return arr  # already laid out as requested
                    except Exception:
                        pass  # unknown sharding type: re-place
                return jax.device_put(arr, desired)

            t._value = _placed(v)
            if t._grad is not None:  # accumulated grads follow the same layout
                t._grad = _placed(t._grad)

    def __call__(self, *args, **kwargs):
        if _is_tracing:  # nested to_static: inline
            return self._fn(*args, **kwargs)
        if not _obs.enabled("executor"):
            return self._call_impl(args, kwargs)
        # "executor/step": the compiled-program execution span — for the
        # to_static path this wrapper IS the executor of the jitted step
        with _obs.trace_span("executor/step", cat="executor",
                             fn=getattr(self, "__name__", "fn")):
            return self._call_impl(args, kwargs)

    def _call_impl(self, args, kwargs):
        leaves, treedef = jax.tree_util.tree_flatten(
            (args, kwargs), is_leaf=lambda x: isinstance(x, Tensor))
        dyn_idx = [i for i, l in enumerate(leaves) if _is_dynamic(l)]
        dyn_vals = [leaves[i]._value if isinstance(leaves[i], Tensor)
                    else leaves[i] for i in dyn_idx]

        state_items = state_mod.snapshot()
        mesh = self._mesh()
        if mesh is not None:
            self._place_state(state_items, mesh)
            dyn_vals = self._place_args(dyn_vals, mesh)

        # registry version determines membership/order, so uids need not be
        # part of the key; grad presence changes program structure
        key = (treedef, tuple(_leaf_key(l) for l in leaves),
               state_mod.version(),
               tuple(t._grad is not None for _, t in state_items),
               mesh is not None)
        entry = self._cache.get(key)
        if entry is None:
            t0 = _obs.now_ns() if _obs.enabled("jit") else 0
            with _obs.trace_span("jit/compile", cat="jit",
                                 fn=getattr(self, "__name__", "fn"),
                                 cache_size=len(self._cache)):
                try:
                    entry = self._build(treedef, leaves, dyn_idx, state_items)
                except _DATA_DEPENDENT_ERRORS as e:
                    # data-dependent python control flow: fall back to the AST
                    # transformation (reference: program_translator.py always
                    # AST-transforms; here the plain trace is the fast path)
                    if not self._try_ast_fallback(e):
                        raise
                    entry = self._build(treedef, leaves, dyn_idx, state_items)
            if t0:
                # trace/build time only — XLA backend compile happens
                # lazily on first execution and is captured by the
                # jax.monitoring hook into jit_backend_compile_ns
                _obs.count("jit_cache_miss")
                _obs.count("jit_compile_ns", _obs.now_ns() - t0)
            from ..analysis import debug_enabled
            if debug_enabled():
                # analysis debug mode: the fresh build's state partition
                # must be hazard-free before the entry is ever run
                from ..analysis import VerifyError, errors
                bad = errors(self.verify())
                if bad:
                    raise VerifyError(
                        bad, context=f"to_static build of "
                        f"{getattr(self, '__name__', 'fn')!r}")
            self._cache[key] = entry
        else:
            _obs.count("jit_cache_hit", cat="jit")
        compiled, out_wrap, aux = entry
        self._last_aux = aux

        # chaos seam: an injected RESOURCE_EXHAUSTED here simulates a
        # training-step allocation failure on the exact path a real XLA
        # OOM surfaces (the flight recorder classifies and dumps it)
        _faults.kill_point("jit/step")
        out_flat = compiled(dyn_vals)
        return out_wrap(out_flat)

    def _make_aux(self, get_jitted, **meta):
        """Per-entry introspection handle: captures abstract twins of the
        first call's arguments, from which the optimized (post-SPMD) HLO
        AND the executable's XLA memory analysis can be re-derived on
        demand — the sources of truth for in-trace collective byte
        accounting and per-program HBM attribution. The lazy
        ``lower().compile()`` is a second backend compile (abstract
        args: no HBM buffers pinned), paid once per entry on the first
        stats request and shared by every accessor."""
        aux = dict(meta)

        def capture(args):
            if "example_args" not in aux:
                aux["example_args"] = jax.tree_util.tree_map(
                    _abstract_arg, args)

        def _materialize():
            # ONE lazy AOT compile feeds every introspection artifact
            # (HLO text, memory stats, top buffers); the loaded
            # executable itself is NOT retained — on a real backend its
            # generated code occupies device memory, and pinning a
            # duplicate executable per entry for the lifetime of the
            # StaticFunction would double the footprint this layer
            # exists to account for
            if "hlo" in aux:
                return
            ex = aux.get("example_args")
            if ex is None:
                raise RuntimeError(
                    "program has not executed yet; run the step once "
                    "before asking for its compiled HLO")
            from ..observability import memory
            compiled = get_jitted().lower(*ex).compile()
            hlo = compiled.as_text()
            try:
                aux["memory"] = memory.program_stats(compiled)
                aux["memory_buffers"] = memory.top_buffers(hlo)
            except memory.MemoryAttributionError as e:
                # a backend without usable memory_analysis() must not
                # break hlo_text(); memory_stats() re-raises
                aux["memory_error"] = e
            aux["hlo"] = hlo

        def hlo_text():
            _materialize()
            return aux["hlo"]

        def memory_stats():
            # argument/output/temp/alias/generated-code bytes + the
            # top result buffers (what an OOM dump names); cached per
            # entry like the HLO text
            _materialize()
            if "memory" not in aux:
                raise aux["memory_error"]
            return aux["memory"]

        def traced_stats():
            # jaxpr-level liveness meter (observability.jaxpr_mem): the
            # backend-independent structural view that stays honest about
            # rematerialization where the CPU executable meter cannot
            # (XLA CPU strips optimization barriers and CSEs remat away)
            ex = aux.get("example_args")
            if ex is None:
                raise RuntimeError(
                    "program has not executed yet; run the step once "
                    "before asking for its traced memory stats")
            if "traced" not in aux:
                from ..observability import jaxpr_mem
                # donated state (the default) compiles carried stores to
                # in-place updates; the meter models that same aliasing
                aux["traced"] = jaxpr_mem.traced_peak_stats(
                    get_jitted(), *ex, alias_io=self._donate)
            return aux["traced"]

        def schedulable_stats(mesh=None, **cost_kwargs):
            # jaxpr-level emission-order overlap headroom
            # (observability.overlap.schedulable_stats): like the
            # liveness meter, sourced from the traced program — the
            # compiled text's postorder re-sort erases the pipeline
            # structure this measures
            ex = aux.get("example_args")
            if ex is None:
                raise RuntimeError(
                    "program has not executed yet; run the step once "
                    "before asking for its schedulable-overlap stats")
            key = ("schedulable", tuple(sorted(cost_kwargs.items())))
            if key not in aux:
                from ..observability import overlap
                aux[key] = overlap.schedulable_stats(
                    get_jitted(), ex, mesh=mesh, **cost_kwargs)
            return aux[key]

        def traced_jaxpr():
            # the traced program itself (pre-XLA), for structural
            # analyzers that walk equations rather than prices — the
            # sharding checker (analysis.shardcheck) propagates
            # shard_map pspecs over exactly this view
            ex = aux.get("example_args")
            if ex is None:
                raise RuntimeError(
                    "program has not executed yet; run the step once "
                    "before asking for its traced jaxpr")
            if "jaxpr" not in aux:
                fun = get_jitted()
                inner = getattr(fun, "_fun", fun)
                aux["jaxpr"] = jax.make_jaxpr(inner)(*ex)
            return aux["jaxpr"]

        aux["capture"] = capture
        aux["hlo_text"] = hlo_text
        aux["memory_stats"] = memory_stats
        aux["traced_stats"] = traced_stats
        aux["schedulable_stats"] = schedulable_stats
        aux["traced_jaxpr"] = traced_jaxpr
        return aux

    def hlo_text(self):
        """Optimized (post-SPMD-partitioning) HLO of the most recent
        entry — the program XLA actually runs, GSPMD/shard_map collectives
        included."""
        if self._last_aux is None:
            raise RuntimeError("no compiled entry yet; call the step once")
        return self._last_aux["hlo_text"]()

    def collective_stats(self, per_execution=False):
        """In-trace collective accounting of the most recent entry: one
        record per (op, axis) with call count and payload bytes, parsed
        from the compiled HLO (closing the 'in-trace collectives are
        invisible to python timers' gap — see observability.hlo_bytes).
        ``per_execution=True`` multiplies ops inside while-loops by their
        known trip counts, so a k-step scan bills its collectives k times
        — the number that shows gradient accumulation cutting collective
        bytes per program execution ~a×."""
        from ..observability import hlo_bytes
        return hlo_bytes.collective_stats(self.hlo_text(),
                                          mesh=self._mesh(),
                                          per_execution=per_execution)

    def export_collective_bytes(self):
        """Export collective_stats() into the shared monitor registry as
        ``collective_bytes{op=...,axis=...}`` / ``collective_count{...}``
        counters; returns the stats."""
        from ..observability import hlo_bytes
        stats = self.collective_stats()
        hlo_bytes.export_collective_bytes(stats)
        return stats

    def overlap_stats(self, **cost_kwargs):
        """Schedule-level latency-hiding analysis of the most recent
        entry (``observability.overlap``): pairs async collective
        ``-start``/``-done`` ops with the compute scheduled between
        them and prices hidden vs exposed collective time with a static
        cost model, reporting ``collective_overlap_efficiency``,
        ``exposed_collective_frac``, per-op splits, and the
        ``backend_sync_schedule`` flag (XLA:CPU emits mostly-sync
        schedules — efficiency 0.0 there is the honest baseline the
        ``xla_flags`` latency-hiding A/B is judged against on real
        hardware). Cost-model rates (``link_gbps``, ``hbm_gbps``,
        ``peak_flops``) and ``per_execution`` pass through.

        The ``schedulable_overlap`` / ``schedulable_ns`` fields are
        spliced in from the TRACED JAXPR (:meth:`schedulable_stats`)
        when the traced program is reachable: the compiled text's
        dependency-postorder re-sort erases the emission-order pipeline
        structure that score measures, so the text-derived value would
        read 0.0 even for a correctly pipelined step. The text-walk
        numbers remain in each ``pairs`` record."""
        from ..observability import overlap
        per_exec = cost_kwargs.pop("per_execution", True)
        rates = dict(cost_kwargs)
        stats = overlap.overlap_stats(self.hlo_text(), mesh=self._mesh(),
                                      per_execution=per_exec, **rates)
        try:
            sched = self.schedulable_stats(**rates)
        except Exception:
            return stats  # no traced program (e.g. restored dump)
        stats["schedulable_overlap"] = sched["schedulable_overlap"]
        stats["schedulable_ns"] = sched["schedulable_ns"]
        stats["schedulable_pairs"] = sched["pairs"]
        for op, slot in sched["per_op"].items():
            tslot = stats["per_op"].setdefault(
                op, {"hidden_ns": 0.0, "exposed_ns": 0.0,
                     "collective_ns": 0.0, "efficiency": 0.0})
            tslot["schedulable_ns"] = slot["schedulable_ns"]
            tslot["schedulable"] = slot["schedulable"]
        stats["assumptions"]["schedulable_source"] = sched["source"]
        return stats

    def schedulable_stats(self, **cost_kwargs):
        """Backend-independent schedulable-overlap score of the most
        recent entry, measured on its traced jaxpr emission order
        (``observability.overlap.schedulable_stats``): how much
        collective time the program structure leaves hideable, before
        any backend scheduler has its say. The serial on-demand ZeRO-3
        step scores 0.0; the double-buffered prefetch pipeline scores
        > 0 — on every backend, including the CPU smoke mesh."""
        if self._last_aux is None:
            raise RuntimeError("no compiled entry yet; call the step once")
        return self._last_aux["schedulable_stats"](mesh=self._mesh(),
                                                   **cost_kwargs)

    def export_overlap_stats(self, **cost_kwargs):
        """Export :meth:`overlap_stats` onto the gauge board
        (``collective_overlap_efficiency`` per program + per op-kind,
        ``exposed_collective_ns_estimate{op=,axis=}``,
        ``collective_async_pairs_total``/``collective_sync_total``) and
        the active run-log; returns the stats."""
        from ..observability import overlap
        stats = self.overlap_stats(**cost_kwargs)
        overlap.export_overlap_stats(
            stats, program=getattr(self, "__name__", "fn"))
        return stats

    def memory_stats(self):
        """Per-program HBM attribution from the compiled executable's
        XLA ``memory_analysis()`` — one record per compiled entry
        (build order), keyed ``<fn>#<i>:<kind>``::

            {"train_step#0:scan": {"argument_bytes": ..,
                                   "output_bytes": .., "temp_bytes": ..,
                                   "alias_bytes": ..,
                                   "generated_code_bytes": ..,
                                   "peak_bytes": ..}}

        Donated state rides the carry as aliased input/output pairs, so
        ``alias_bytes`` ≈ the carried state and ``peak_bytes`` counts it
        once. Only entries that have executed at least once are
        attributable (the abstract arg twins are captured on first
        call); unexecuted entries are skipped."""
        out = {label: aux["memory_stats"]()
               for label, aux in self._memory_entries()}
        if not out:
            raise RuntimeError(
                "no executed compiled entry yet; call the step once "
                "before asking for its memory attribution")
        return out

    def traced_memory_stats(self):
        """Jaxpr-liveness memory attribution per compiled entry
        (``observability.jaxpr_mem``): the sequential high-water bytes
        of the TRACED step program, keyed like :meth:`memory_stats`.
        Backend-independent and remat-aware — an activation-recompute
        policy shrinks this number even on the CPU smoke host, where
        the compiled-executable meter is blind to rematerialization
        (barriers stripped + CSE). The TPU re-pin captures the
        executable view."""
        out = {label: aux["traced_stats"]()
               for label, aux in self._memory_entries()}
        if not out:
            raise RuntimeError(
                "no executed compiled entry yet; call the step once "
                "before asking for its memory attribution")
        return out

    def _memory_entries(self):
        """``(label, aux)`` per attributable compiled entry — the ONE
        place the ``<fn>#<i>:<kind>`` label scheme lives."""
        name = getattr(self, "__name__", "fn")
        out = []
        for i, (_key, entry) in enumerate(self._cache.items()):
            aux = entry[2]
            if aux.get("example_args") is None:
                continue
            out.append((f"{name}#{i}:{aux.get('kind', 'unrolled')}", aux))
        return out

    def export_memory_stats(self):
        """Export :meth:`memory_stats` as
        ``program_hbm_bytes{entry=,kind=}`` gauges and register each
        entry (with its top buffers) in the process-wide program-memory
        registry the flight recorder snapshots at death; returns the
        stats."""
        from ..observability import memory
        # ONE walk builds and registers: a second _memory_entries()
        # pass could see an entry another thread compiled in between
        stats = {}
        for label, aux in self._memory_entries():
            stats[label] = memory.record_program_memory(
                label, aux["memory_stats"](),
                buffers=aux.get("memory_buffers"))
        if not stats:
            raise RuntimeError(
                "no executed compiled entry yet; call the step once "
                "before asking for its memory attribution")
        return stats

    def _place_args(self, dyn_vals, mesh):
        """Respect explicit input shardings; default: leave placement to jax
        (replicated). DataParallel layers set `_arg_pspec` on the wrapper."""
        specs = getattr(self, "_arg_pspecs", None)
        if specs is None:
            return dyn_vals
        out = []
        for v, spec in zip(dyn_vals, specs):
            if spec is None:
                out.append(v)
            else:
                out.append(jax.device_put(v, NamedSharding(mesh, spec)))
        return out

    def _make_pure_fn(self, treedef, template_leaves, dyn_idx, state_items,
                      out_template, info):
        """The functionalized user step: ``(state, dyn, grads) -> (outs,
        new_state, new_grads)``. Fills ``out_template``/``info`` as a side
        effect of tracing (both build modes share it).

        Under ``dp_axis`` the body runs per-rank inside shard_map: the dp
        axis is published (``parallel_env.current_dp_axis``) so the
        optimizer/AMP layers route gradient reduction through explicit
        collectives, and the user outputs — per-rank partial losses over
        the local microbatch — are pmean'd back to the global value the
        replicated program would have returned."""
        fn = self._fn
        dp_axis = self._dp_axis

        def pure_fn(state_vals, dyn_vals, grad_vals):
            from ..distributed import parallel_env
            leaves = list(template_leaves)
            for i, v in zip(dyn_idx, dyn_vals):
                leaves[i] = Tensor(v)
            args, kwargs = jax.tree_util.tree_unflatten(treedef, leaves)
            with _StateSwap(state_items, state_vals, grad_vals) as swap, \
                    parallel_env.dp_axis_ctx(dp_axis):
                cleanups = []
                try:
                    _run_step_hooks(cleanups)
                    out = fn(*args, **kwargs)
                    out_leaves, out_treedef = jax.tree_util.tree_flatten(
                        out, is_leaf=lambda x: isinstance(x, Tensor))
                    out_vals = [l._value if isinstance(l, Tensor) else l
                                for l in out_leaves]
                    if dp_axis is not None \
                            and parallel_env.axis_bound(dp_axis):
                        out_vals = [
                            jax.lax.pmean(v, dp_axis)
                            if (hasattr(v, "dtype")
                                and jnp_issubdtype(v.dtype)) else v
                            for v in out_vals]
                    out_template["treedef"] = out_treedef
                    new_state, new_grads = swap.capture()
                finally:
                    for c in cleanups:
                        c()
            info["w_val"] = [nv is not ov
                             for nv, ov in zip(new_state, state_vals)]
            info["w_grad"] = [ng is not og
                              for ng, og in zip(new_grads, grad_vals)]
            info["n_out"] = len(jax.tree_util.tree_flatten(out_vals)[0])
            info["grad_out_mask"] = [ng is not None for ng in new_grads]
            return out_vals, new_state, new_grads

        return pure_fn

    def _build(self, treedef, template_leaves, dyn_idx, state_items):
        from . import compile_cache
        compile_cache.ensure_enabled()  # backend is initialized by now
        if self._scan_steps is not None:
            return self._build_scan(treedef, template_leaves, dyn_idx,
                                    state_items)
        return self._build_unrolled(treedef, template_leaves, dyn_idx,
                                    state_items)

    def _build_unrolled(self, treedef, template_leaves, dyn_idx, state_items):
        """Two-phase build.

        Phase A traces the user function once (abstractly) threading *all*
        state, and records which state values / grads the program actually
        writes (object identity of the tracer survives only if untouched)
        and which inputs it reads (jaxpr var usage).

        Phase B compiles the real program threading only what matters:
        written entries are donated inputs + outputs (PJRT aliasing — the
        in-place Variable update of the reference); read-only entries are
        plain inputs (no donation, no passthrough output — XLA would
        otherwise materialize a full copy of every parameter in grad-only
        programs); untouched entries are not passed at all (keeps dispatch
        overhead proportional to the program's real state footprint).
        """
        out_template = {}
        info = {}
        pure_fn = self._make_pure_fn(treedef, template_leaves, dyn_idx,
                                     state_items, out_template, info)
        n = len(state_items)
        state_vals = [t._value for _, t in state_items]
        grad_vals = [t._grad for _, t in state_items]

        # ---- phase A: analysis trace ----
        dyn_template = [l._value if isinstance(l, Tensor) else l
                        for l in (template_leaves[i] for i in dyn_idx)]
        closed, val_used, grad_used = _analysis_trace(
            pure_fn, state_vals, dyn_template, grad_vals, n, info)

        w_val, w_grad = info["w_val"], info["w_grad"]
        don_val_idx = [i for i in range(n) if w_val[i]]
        ro_val_idx = [i for i in range(n)
                      if not w_val[i] and val_used[i]]
        # only *written* grads are donated (their buffers are replaced from
        # the outputs); grads the program merely reads must stay un-donated
        # or XLA may alias them to a same-shaped output and delete the
        # buffer out from under the live Tensor._grad
        don_grad_idx = [i for i in range(n)
                        if grad_vals[i] is not None and w_grad[i]]
        ro_grad_idx = [i for i in range(n)
                       if grad_vals[i] is not None and not w_grad[i]
                       and grad_used.get(i, False)]
        out_grad_idx = [i for i in range(n) if w_grad[i]]
        # skipped entries are only materialized at (re)trace time, read from
        # the live tensors — capturing concrete arrays here would pin stale
        # HBM buffers in the compile cache for the life of the entry
        skip_val_idx = [i for i in range(n)
                        if not w_val[i] and not val_used[i]]
        skip_grad_idx = [i for i in range(n)
                         if i not in don_grad_idx and i not in ro_grad_idx]

        # ---- phase B: the real program ----
        def pure_fn2(don_vals, don_grads, dyn_vals, ro_vals, ro_grads):
            sv = [None] * n
            gv = [None] * n
            for i, v in zip(don_val_idx, don_vals):
                sv[i] = v
            for i, v in zip(ro_val_idx, ro_vals):
                sv[i] = v
            for i in skip_val_idx:  # trace-time read of the live value
                sv[i] = state_items[i][1]._value
            for i, g in zip(don_grad_idx, don_grads):
                gv[i] = g
            for i, g in zip(ro_grad_idx, ro_grads):
                gv[i] = g
            for i in skip_grad_idx:
                gv[i] = state_items[i][1]._grad
            out_vals, new_state, new_grads = pure_fn(sv, dyn_vals, gv)
            return (out_vals,
                    [new_state[i] for i in don_val_idx],
                    [new_grads[i] for i in out_grad_idx])

        donate = (0, 1) if self._donate else ()
        jitted = self._jit(pure_fn2, donate_argnums=donate)

        # introspection (tests / debugging): which state uids ended up where
        uids = [uid for uid, _ in state_items]
        self._last_partition = {
            "donated": [uids[i] for i in don_val_idx],
            "readonly": [uids[i] for i in ro_val_idx],
            "skipped": [uids[i] for i in skip_val_idx],
            "donated_grads": [uids[i] for i in don_grad_idx],
            "readonly_grads": [uids[i] for i in ro_grad_idx],
            "sharded": [uids[i] for i in range(n)
                        if _is_sharded_spec(state_items[i][1].pspec)],
            "carry_optional": [uids[i] for i in range(n)
                               if getattr(state_items[i][1],
                                          "_carry_optional", False)],
            "dp_axis": None,
            "donate": bool(self._donate),
            "state_meta": {uids[i]: {
                "name": getattr(state_items[i][1], "name", None),
                "category": getattr(state_items[i][1],
                                    "_ledger_category", None),
                "pspec": state_items[i][1].pspec,
            } for i in range(n)},
        }

        # direct Tensor references per partition: the per-call hot path
        # touches only the state the program actually uses
        don_ts = [state_items[i][1] for i in don_val_idx]
        ro_ts = [state_items[i][1] for i in ro_val_idx]
        dong_ts = [state_items[i][1] for i in don_grad_idx]
        rog_ts = [state_items[i][1] for i in ro_grad_idx]
        outg_ts = [state_items[i][1] for i in out_grad_idx]

        aux = self._make_aux(lambda: jitted, kind="unrolled")

        def compiled(dyn_vals):
            args = ([t._value for t in don_ts],
                    [t._grad for t in dong_ts],
                    dyn_vals,
                    [t._value for t in ro_ts],
                    [t._grad for t in rog_ts])
            aux["capture"](args)
            out_flat, new_w, new_g = jitted(*args)
            for t, v in zip(don_ts, new_w):
                t._value = v
            for t, g in zip(outg_ts, new_g):
                t._grad = g
            return out_flat

        def out_wrap(out_flat):
            wrapped = [Tensor(v) if isinstance(v, jax.Array) else v
                       for v in out_flat]
            return jax.tree_util.tree_unflatten(out_template["treedef"], wrapped)

        return compiled, out_wrap, aux

    def _build_scan(self, treedef, template_leaves, dyn_idx, state_items):
        """Scan-compiled step program: trace the single-step body once and
        roll it k times with ``jax.lax.scan``.

        The full framework state rides the scan carry — written state
        values (params, optimizer accumulators + fp32 masters, the RNG
        key, a scheduled lr) and written/accumulated grads — so the
        reference's persistable-@GRAD survival semantics hold through the
        carry: a grad accumulated in inner step i is the grad input of
        inner step i+1, and one that survives the last step is written
        back to ``Tensor._grad``. Read-only state enters as plain
        (broadcast) inputs, untouched state is skipped exactly like the
        unrolled build. The stacked ``[k, ...]`` dynamic args are the scan
        ``xs``, so each inner step consumes a fresh microbatch; per-step
        user outputs come back ``[k, ...]``-stacked.

        Grad carry structure must be iteration-invariant, which python
        ``None`` grads are not, so presence is solved to a fixpoint: a
        grad the body CREATES (None at entry, live at exit) joins the
        carry initialized to zeros (additive accumulation makes zeros ≡
        "no grad yet"), and a grad the body CLEARS (opt.clear_grad) flows
        to the next step as zeros and is written back as ``None`` after
        the scan, matching the unrolled program observably.

        ``dp_axis``: the whole scan runs inside ``shard_map`` with that
        mesh axis manual — the body sees per-rank microbatch shards and
        per-rank shards of any PartitionSpec-sharded carry state (the
        ZeRO optimizer stores), gradient reduction happens through the
        explicit collectives the optimizer issues (per-param psum for the
        replicated control, bucketed psum_scatter + all_gather under
        ZeRO), and the grad-presence fixpoint runs over LOCAL (per-shard)
        shapes so the analysis trace matches the shard_map body exactly.
        """
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec

        k = self._scan_steps
        dp_axis = self._dp_axis
        mesh = self._mesh()
        if dp_axis is not None:
            if mesh is None:
                raise RuntimeError(
                    f"dp_axis={dp_axis!r} needs an active device mesh "
                    "(fleet.init or parallel_env.set_mesh)")
            sizes = _axis_sizes(mesh)
            if dp_axis not in sizes:
                raise ValueError(
                    f"mesh axes {list(sizes)} have no {dp_axis!r}")
            for name, size in sizes.items():
                if name != dp_axis and size != 1:
                    raise NotImplementedError(
                        f"the dp-sharded scan step binds every mesh axis "
                        f"manually; axis {name!r} has size {size} — build "
                        "the step mesh with only the dp axis > 1")
            dp = sizes[dp_axis]
        out_template = {}
        info = {}
        pure_fn = self._make_pure_fn(treedef, template_leaves, dyn_idx,
                                     state_items, out_template, info)
        n = len(state_items)
        state_vals = [t._value for _, t in state_items]
        state_specs = [t.pspec for _, t in state_items]

        # single-step abstract templates from the [k, ...]-stacked args
        dyn_stacked = [template_leaves[i]._value
                       if isinstance(template_leaves[i], Tensor)
                       else template_leaves[i] for i in dyn_idx]
        xs_specs = None
        if dp_axis is not None:
            user_specs = getattr(self, "_arg_pspecs", None)
            # default microbatch sharding is only safe when EVERY stacked
            # arg agrees on the dim-1 size (features + labels of one
            # batch); a lone divisible aux input must not get silently
            # split 1/dp — that computes on a fraction of its values
            dim1 = {tuple(np.shape(v))[1] for v in dyn_stacked
                    if len(np.shape(v)) >= 2}
            auto_ok = len(dim1) == 1 and next(iter(dim1)) % dp == 0
            if not auto_ok and user_specs is None and dp > 1:
                import warnings
                warnings.warn(
                    f"dp_axis={dp_axis!r}: stacked inputs disagree on a "
                    f"microbatch dim (dim-1 sizes {sorted(dim1)}); all "
                    "inputs stay REPLICATED per rank — set "
                    "`sfn._arg_pspecs` to shard the batch explicitly")
            xs_specs = []
            for j, v in enumerate(dyn_stacked):
                shape = tuple(np.shape(v))
                if user_specs is not None and j < len(user_specs) \
                        and user_specs[j] is not None:
                    spec = user_specs[j]
                elif auto_ok and len(shape) >= 2:
                    # microbatch dim of the [k, batch, ...] stack
                    spec = PartitionSpec(None, dp_axis)
                else:
                    spec = PartitionSpec()
                if len(spec) > 0 and spec[0] is not None:
                    raise ValueError(
                        f"xs arg {j}: the leading [k] scan dim cannot be "
                        f"sharded (spec {spec})")
                xs_specs.append(spec)
        step_tmpl = []
        for j, v in enumerate(dyn_stacked):
            shape = tuple(np.shape(v))
            if not shape or shape[0] != k:
                raise ValueError(
                    f"scan_steps={k}: every dynamic input must be stacked "
                    f"[k, ...]; got shape {shape}")
            if dp_axis is not None:
                shape = _local_shape(shape, xs_specs[j], mesh)
            step_tmpl.append(jax.ShapeDtypeStruct(shape[1:],
                                                  np.dtype(v.dtype)))

        # analysis templates: sharded state enters the shard_map body as
        # its per-rank block, so the fixpoint must trace local shapes
        if dp_axis is not None:
            a_state = [jax.ShapeDtypeStruct(
                           _local_shape(np.shape(v), spec, mesh),
                           np.dtype(v.dtype))
                       if _is_sharded_spec(spec) else v
                       for v, spec in zip(state_vals, state_specs)]
        else:
            a_state = state_vals

        # accumulation windows trace the SAME body in two phases: "accum"
        # (updates defer, grads survive clear_grad) for the first a-1
        # steps of each window and "fire" (one update over the 1/a-scaled
        # accumulated grads) for the window boundary. The phase is
        # published through parallel_env.accum_ctx, which the
        # optimizer/GradScaler consult.
        a = self._accumulate_steps

        def _phase_fn(phase):
            if a is None:
                return pure_fn

            def wrapped(sv, dv, gv):
                from ..distributed import parallel_env
                with parallel_env.accum_ctx(phase, a):
                    return pure_fn(sv, dv, gv)
            return wrapped

        fire_fn = _phase_fn("fire")
        accum_fn = _phase_fn("accum") if a is not None else None

        # grad-presence fixpoint (presence only grows, so it terminates);
        # grads follow their tensor's layout (localize like the values).
        # With accumulation BOTH body flavors contribute: the carry must
        # cover the union of their written state and surviving grads.
        grad_tmpl = [t._grad for _, t in state_items]
        if dp_axis is not None:
            grad_tmpl = [jax.ShapeDtypeStruct(
                             _local_shape(np.shape(g), spec, mesh),
                             np.dtype(g.dtype))
                         if g is not None and _is_sharded_spec(spec) else g
                         for g, spec in zip(grad_tmpl, state_specs)]
        modes = [("fire", fire_fn)]
        if accum_fn is not None:
            modes.append(("accum", accum_fn))
        mode_res = {}
        for _ in range(2 * (n + 1)):
            grew = False
            for mname, mfn in modes:
                closed, m_used, mg_used = _analysis_trace(
                    mfn, a_state, step_tmpl, grad_tmpl, n, info)
                mode_res[mname] = (dict(info), m_used, mg_used)
                out_avals = list(closed.out_avals)
                pos = info["n_out"] + n
                for i, present in enumerate(info["grad_out_mask"]):
                    if present:
                        if grad_tmpl[i] is None:
                            grad_tmpl[i] = jax.ShapeDtypeStruct(
                                out_avals[pos].shape, out_avals[pos].dtype)
                            grew = True
                        pos += 1
            if not grew:
                break

        fire_info, val_used, grad_used = mode_res["fire"]
        w_val = list(fire_info["w_val"])
        w_grad = list(fire_info["w_grad"])
        val_used = list(val_used)
        grad_used = dict(grad_used)
        if "accum" in mode_res:
            ainfo, a_used, ag_used = mode_res["accum"]
            w_val = [x or y for x, y in zip(w_val, ainfo["w_val"])]
            w_grad = [x or y for x, y in zip(w_grad, ainfo["w_grad"])]
            val_used = [x or y for x, y in zip(val_used, a_used)]
            for i, u in ag_used.items():
                grad_used[i] = grad_used.get(i, False) or u
        # grads written back after the call follow the BOUNDARY body's
        # exit state (the last inner step of the last window fires)
        steady_mask = list(fire_info["grad_out_mask"])
        info.update(fire_info)
        carry_val_idx = [i for i in range(n) if w_val[i]]
        ro_val_idx = [i for i in range(n) if not w_val[i] and val_used[i]]
        skip_val_idx = [i for i in range(n)
                        if not w_val[i] and not val_used[i]]
        carry_grad_idx = [i for i in range(n)
                          if grad_tmpl[i] is not None and w_grad[i]]
        ro_grad_idx = [i for i in range(n)
                       if grad_tmpl[i] is not None and not w_grad[i]
                       and grad_used.get(i, False)]
        skip_grad_idx = [i for i in range(n)
                         if i not in carry_grad_idx and i not in ro_grad_idx]
        # zeros template per carried grad: the scan-carry aval (used both
        # for the initial carry when the live grad is None and for the
        # cleared-inside-the-step substitution). Under dp_axis the body
        # shape is the per-rank block; the init zeros built OUTSIDE the
        # shard_map need the global shape.
        carry_g_sds = {i: (tuple(grad_tmpl[i].shape),
                           np.dtype(grad_tmpl[i].dtype))
                       for i in carry_grad_idx}
        carry_g_init = {
            i: ((_global_shape(shape, state_specs[i], mesh)
                 if dp_axis is not None else shape), dt)
            for i, (shape, dt) in carry_g_sds.items()}

        def pure_fn2(carry_vals, carry_grads, xs_stacked, ro_vals, ro_grads):
            def _mk_body(step_fn):
                def body(carry, xs):
                    c_vals, c_grads = carry
                    sv = [None] * n
                    gv = [None] * n
                    for i, v in zip(carry_val_idx, c_vals):
                        sv[i] = v
                    for i, v in zip(ro_val_idx, ro_vals):
                        sv[i] = v
                    for i in skip_val_idx:  # trace-time read, live value
                        sv[i] = state_items[i][1]._value
                    for i, g in zip(carry_grad_idx, c_grads):
                        gv[i] = g
                    for i, g in zip(ro_grad_idx, ro_grads):
                        gv[i] = g
                    for i in skip_grad_idx:
                        gv[i] = state_items[i][1]._grad
                    out_vals, new_state, new_grads = step_fn(sv, list(xs),
                                                             gv)
                    next_grads = []
                    for i in carry_grad_idx:
                        g = new_grads[i]
                        if g is None:  # cleared: zeros ≡ cleared for i+1
                            shape, dt = carry_g_sds[i]
                            g = jnp.zeros(shape, dt)
                        next_grads.append(g)
                    return ([new_state[i] for i in carry_val_idx],
                            next_grads), tuple(out_vals)
                return body

            init = (list(carry_vals), list(carry_grads))
            if a is None:
                (f_vals, f_grads), ys = jax.lax.scan(
                    _mk_body(fire_fn), init, tuple(xs_stacked), length=k)
                return list(ys), f_vals, f_grads

            # accumulation windows: outer scan over k/a windows, each an
            # inner scan of a-1 deferred micro steps plus the boundary
            # step that fires the update — the per-window collectives
            # appear once in this body instead of once per inner step
            w = k // a
            tmap = jax.tree_util.tree_map
            xs_win = tmap(lambda x: x.reshape((w, a) + x.shape[1:]),
                          tuple(xs_stacked))
            accum_body = _mk_body(accum_fn)
            fire_body = _mk_body(fire_fn)

            def window(carry, xs_w):
                carry, ys_head = jax.lax.scan(
                    accum_body, carry, tmap(lambda x: x[:a - 1], xs_w),
                    length=a - 1)
                carry, ys_last = fire_body(carry,
                                           tmap(lambda x: x[a - 1], xs_w))
                ys_w = tmap(lambda h, l: jnp.concatenate([h, l[None]], 0),
                            ys_head, ys_last)
                return carry, ys_w

            (f_vals, f_grads), ys = jax.lax.scan(window, init, xs_win,
                                                 length=w)
            ys = tmap(lambda y: y.reshape((k,) + y.shape[2:]), ys)
            return list(ys), f_vals, f_grads

        donate = (0, 1) if self._donate else ()
        if dp_axis is not None:
            def _spec(i):
                return (state_specs[i] if state_specs[i] is not None
                        else PartitionSpec())
            cv_specs = [_spec(i) for i in carry_val_idx]
            cg_specs = [_spec(i) for i in carry_grad_idx]
            ro_specs = [_spec(i) for i in ro_val_idx]
            rog_specs = [_spec(i) for i in ro_grad_idx]
            # ys are pmean'd replicated in the body; final carry values
            # reassemble per their PartitionSpec
            smapped = _shard_map()(
                pure_fn2, mesh=mesh,
                in_specs=(cv_specs, cg_specs, list(xs_specs), ro_specs,
                          rog_specs),
                out_specs=(PartitionSpec(), cv_specs, cg_specs),
                check_rep=False)
            jitted = self._jit(smapped, donate_argnums=donate)
        else:
            jitted = self._jit(pure_fn2, donate_argnums=donate)

        uids = [uid for uid, _ in state_items]
        self._last_partition = {
            "donated": [uids[i] for i in carry_val_idx],
            "readonly": [uids[i] for i in ro_val_idx],
            "skipped": [uids[i] for i in skip_val_idx],
            "donated_grads": [uids[i] for i in carry_grad_idx],
            "readonly_grads": [uids[i] for i in ro_grad_idx],
            "sharded": [uids[i] for i in range(n)
                        if _is_sharded_spec(state_specs[i])],
            "carry_optional": [uids[i] for i in range(n)
                               if getattr(state_items[i][1],
                                          "_carry_optional", False)],
            "dp_axis": dp_axis,
            "scan_steps": k,
            "accumulate_steps": a,
            "donate": bool(self._donate),
            "state_meta": {uids[i]: {
                "name": getattr(state_items[i][1], "name", None),
                "category": getattr(state_items[i][1],
                                    "_ledger_category", None),
                "pspec": state_specs[i],
            } for i in range(n)},
        }

        carry_ts = [state_items[i][1] for i in carry_val_idx]
        ro_ts = [state_items[i][1] for i in ro_val_idx]
        cg_ts = [state_items[i][1] for i in carry_grad_idx]
        rog_ts = [state_items[i][1] for i in ro_grad_idx]

        aux = self._make_aux(lambda: jitted, kind="scan", scan_steps=k,
                             dp_axis=dp_axis, accumulate_steps=a)

        def compiled(dyn_vals):
            init_grads = []
            for i, t in zip(carry_grad_idx, cg_ts):
                g = t._grad
                if g is None:
                    shape, dt = carry_g_init[i]
                    g = jnp.zeros(shape, dt)
                init_grads.append(g)
            args = ([t._value for t in carry_ts], init_grads, dyn_vals,
                    [t._value for t in ro_ts], [t._grad for t in rog_ts])
            aux["capture"](args)
            ys, f_vals, f_grads = jitted(*args)
            for t, v in zip(carry_ts, f_vals):
                t._value = v
            for i, t, g in zip(carry_grad_idx, cg_ts, f_grads):
                t._grad = g if steady_mask[i] else None
            return ys

        def out_wrap(out_flat):
            wrapped = [Tensor(v) if isinstance(v, jax.Array) else v
                       for v in out_flat]
            return jax.tree_util.tree_unflatten(out_template["treedef"],
                                                wrapped)

        return compiled, out_wrap, aux

    def _try_ast_fallback(self, cause):
        """Swap self._fn for its dy2static-transformed version once."""
        import types as _types

        if getattr(self._fn, "_jst_transformed", False):
            return False
        _obs.count("jit_ast_fallbacks", cat="jit")
        from .dy2static import convert_to_static
        try:
            fn = self._fn
            if isinstance(fn, _types.MethodType):
                conv = convert_to_static(fn.__func__)
                self._fn = _types.MethodType(conv, fn.__self__)
            else:
                self._fn = convert_to_static(fn)
        except (OSError, TypeError, SyntaxError) as e:
            raise RuntimeError(
                "tracing hit data-dependent python control flow "
                f"({cause!s:.200}) and the AST fallback could not transform "
                f"{self._fn!r} ({e}). Rewrite the condition with "
                "paddle_tpu.nn.control_flow (cond/while_loop), or decorate "
                "a plain `def` (lambdas cannot be AST-transformed).")
        return True

    def verify(self):
        """Static-analysis check of the compiled step's state partition
        (paddle_tpu.analysis.check_static_function): donated /
        read-only / skipped state classes must be disjoint. Returns the
        findings; exported as analysis counters."""
        from ..analysis import _export, check_static_function
        findings = check_static_function(self)
        _export(findings)
        return findings

    # paddle API compat
    @property
    def code(self):
        import inspect
        return inspect.getsource(self._fn)

    def concrete_program(self):
        return None


def to_static(function=None, input_spec=None, build_strategy=None,
              scan_steps=None, dp_axis=None, accumulate_steps=None,
              xla_flags=None, **kwargs):
    """Decorator / wrapper, usable as @to_static or to_static(fn).

    ``scan_steps=k`` compiles ``function`` (the single-step body) as a
    ``jax.lax.scan`` over k inner steps: dynamic args must arrive
    ``[k, ...]``-stacked (one microbatch per inner step) and per-step
    outputs return ``[k, ...]``-stacked. Compile time is ~independent of
    k, vs linear in k for a python-unrolled loop over the body.

    ``dp_axis='dp'`` runs the scan inside ``shard_map`` with that mesh
    axis manual: the microbatch is split 1/dp per rank, gradient
    reduction goes through the explicit collectives the optimizer
    issues — per-param psum for a replicated optimizer, bucketed
    ``psum_scatter`` + param ``all_gather`` after
    ``optimizer._zero_enable()`` (ZeRO; stage 3 adds per-bucket param
    ``all_gather`` before the forward instead, with params riding the
    carry as 1/dp shards) — and PartitionSpec-sharded optimizer state
    rides the donated carry as per-rank shards. User outputs
    (losses/metrics) are pmean'd over the axis.

    ``accumulate_steps=a`` groups the k inner steps into k/a gradient
    accumulation windows: the first a-1 steps of each window run with
    optimizer/scaler updates deferred (gradients accumulate through the
    scan carry — per-param for replicated/ZeRO-1 state, reduced into the
    sharded per-bucket accumulator for ZeRO-2/3) and the window's last
    step fires one update over the 1/a-scaled accumulated gradients, so
    the reduce/update(/all_gather) collectives bill once per window
    instead of once per step.

    ``xla_flags`` passes per-program XLA compiler options (a
    ``jit.xla_flags`` preset name like ``"latency-hiding"``, a
    ``"flag=value ..."`` string, or a dict; the
    ``PADDLE_TPU_XLA_FLAGS`` env var overlays and wins). Flags a
    backend doesn't register fall back to an unflagged compile with
    provenance recorded — see ``StaticFunction.xla_flags()`` and
    ``overlap_stats()`` for the A/B this knob exists for. Scan-stepped
    programs (``scan_steps=k``) with no explicit value DEFAULT to the
    ``"latency-hiding"`` preset when the backend registers it (judged
    once per process — ``jit.xla_flags.backend_accepts``); pass
    ``xla_flags=False`` to opt a program out (the A/B control arm)."""
    if function is None:
        return lambda fn: to_static(fn, input_spec=input_spec,
                                    scan_steps=scan_steps, dp_axis=dp_axis,
                                    accumulate_steps=accumulate_steps,
                                    xla_flags=xla_flags, **kwargs)
    if isinstance(function, StaticFunction):
        return function
    # Layers: wrap forward, keep the layer object semantics
    from ..nn.layer.layers import Layer
    if isinstance(function, Layer):
        layer = function
        static_forward = StaticFunction(layer.forward, input_spec,
                                        scan_steps=scan_steps,
                                        dp_axis=dp_axis,
                                        accumulate_steps=accumulate_steps,
                                        xla_flags=xla_flags, **kwargs)
        layer.forward = static_forward
        return layer
    return StaticFunction(function, input_spec, scan_steps=scan_steps,
                          dp_axis=dp_axis,
                          accumulate_steps=accumulate_steps,
                          xla_flags=xla_flags, **kwargs)


class InputSpec:
    """Shape/dtype declaration (reference: paddle.static.InputSpec)."""

    def __init__(self, shape, dtype="float32", name=None):
        self.shape = tuple(shape)
        self.dtype = dtype
        self.name = name

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype})"


def not_to_static(fn):
    fn._not_to_static = True
    return fn
