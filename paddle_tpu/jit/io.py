"""jit.save / jit.load — inference model export.

Reference: `python/paddle/fluid/dygraph/jit.py:515/876` (save/load →
TranslatedLayer) and `fluid/io.py:1246 save_inference_model`.

Two artifacts are written:
- With `input_spec`: a **process-independent** StableHLO artifact
  (`.pdmodel` zip + `.pdiparams`) via jit/export.py — serveable by
  `paddle_tpu.inference.Predictor` with no access to the model class
  (the analog of the reference's `__model__` ProgramDesc).
- Always: a state_dict archive + best-effort pickled layer
  (`.pdlayer` + `.pdiparams.npz`) for same-codebase training reload.
"""
import os
import pickle
import warnings

import numpy as np

from ..core.tensor import Tensor

_SUFFIX_PARAMS = ".pdiparams"
_SUFFIX_MODEL = ".pdmodel"
_SUFFIX_LAYER = ".pdlayer"


def _save_state_dict_np(state_dict, path):
    arrays = {k: np.asarray(v._value if isinstance(v, Tensor) else v)
              for k, v in state_dict.items()}
    # np.savez needs str keys without '/': keep a name map
    np.savez(path, **{f"t{i}": a for i, a in enumerate(arrays.values())})
    return list(arrays.keys())


def save(layer, path, input_spec=None, **config):
    """Save layer params + spec for later `jit.load` / Predictor serving.

    With `input_spec` (list of InputSpec/Tensors) the forward is additionally
    exported to a StableHLO `.pdmodel` artifact that serves in any process.
    """
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    sd = layer.state_dict()
    names = _save_state_dict_np(sd, path + _SUFFIX_PARAMS + ".npz")
    meta = {
        "names": names,
        "class_module": type(layer).__module__,
        "class_name": type(layer).__qualname__,
        "input_spec": None,
    }
    # Best effort: pickle the layer object itself for exact reload.
    try:
        with open(path + _SUFFIX_LAYER, "wb") as f:
            pickle.dump({"meta": meta, "layer": layer}, f)
    except Exception:
        with open(path + _SUFFIX_LAYER, "wb") as f:
            pickle.dump({"meta": meta, "layer": None}, f)

    specs = input_spec if input_spec is not None else config.get(
        "example_inputs")
    if specs is None:
        warnings.warn(
            "jit.save without input_spec writes only the same-codebase "
            "reload artifact; pass input_spec to export a "
            "process-independent .pdmodel (StableHLO) for serving")
        return
    from .export import save_exported
    # per-sublayer save/restore: a blanket layer.train() would clobber
    # mixed modes (e.g. a frozen .eval() backbone inside a training model)
    modes = [(l, l.training)
             for _, l in layer.named_sublayers(include_self=True)]
    layer.eval()
    try:
        save_exported(path, layer.forward, list(sd.items()), list(specs))
    finally:
        for l, m in modes:
            l.training = m


class TranslatedLayer:
    """Loaded inference layer (reference: TranslatedLayer jit.py)."""

    def __init__(self, layer):
        self._layer = layer
        self._layer.eval()
        from .to_static import StaticFunction
        self._forward = StaticFunction(layer.forward, donate_state=False)

    def __call__(self, *args, **kwargs):
        from ..core.autograd import no_grad
        with no_grad():
            return self._forward(*args, **kwargs)

    def eval(self):
        self._layer.eval()
        return self

    def state_dict(self):
        return self._layer.state_dict()


class ServedLayer:
    """Inference layer backed by a loaded StableHLO artifact — callable like
    the original model, no model class needed (reference: TranslatedLayer
    loaded from __model__ ProgramDesc, jit.py:876)."""

    def __init__(self, served):
        self._served = served

    def __call__(self, *args, **kwargs):
        outs = self._served(*args)
        tensors = [o if isinstance(o, Tensor) else Tensor(o) for o in outs]
        return tensors[0] if len(tensors) == 1 else tuple(tensors)

    forward = __call__

    def eval(self):
        return self

    def state_dict(self):
        return self._served.state_dict()

    @property
    def input_names(self):
        return self._served.input_names

    @property
    def output_names(self):
        return self._served.output_names


def load(path, **config):
    from .export import has_artifact, ServedProgram
    if has_artifact(path):
        return ServedLayer(ServedProgram(path))

    # same-codebase reload path (pickled layer + npz params)
    layer_file = path + _SUFFIX_LAYER
    if not os.path.exists(layer_file):
        layer_file = path + _SUFFIX_MODEL  # pre-StableHLO saves
    with open(layer_file, "rb") as f:
        blob = pickle.load(f)
    layer = blob["layer"]
    if layer is None:
        raise RuntimeError(
            f"{path}: layer class could not be pickled at save time; "
            "reconstruct the layer and use set_state_dict + load_params, or "
            "re-save with input_spec for a class-free StableHLO artifact")
    data = np.load(path + _SUFFIX_PARAMS + ".npz")
    names = blob["meta"]["names"]
    sd = {name: data[f"t{i}"] for i, name in enumerate(names)}
    layer.set_state_dict(sd)
    return TranslatedLayer(layer)
