"""jit.save / jit.load — inference model export.

Reference: `python/paddle/fluid/dygraph/jit.py:515/876` (save/load →
TranslatedLayer) and `fluid/io.py:1246 save_inference_model`. The serialized
artifact here is a state_dict archive + a pickled layer constructor spec; the
serving runner (paddle_tpu.inference.Predictor) loads it and compiles the
forward once. A StableHLO export path is planned for cross-process serving.
"""
import os
import pickle

import numpy as np

from ..core.tensor import Tensor

_SUFFIX_PARAMS = ".pdiparams"
_SUFFIX_MODEL = ".pdmodel"


def _save_state_dict_np(state_dict, path):
    arrays = {k: np.asarray(v._value if isinstance(v, Tensor) else v)
              for k, v in state_dict.items()}
    # np.savez needs str keys without '/': keep a name map
    np.savez(path, **{f"t{i}": a for i, a in enumerate(arrays.values())})
    return list(arrays.keys())


def save(layer, path, input_spec=None, **config):
    """Save layer params + spec for later `jit.load` / Predictor serving."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    sd = layer.state_dict()
    names = _save_state_dict_np(sd, path + _SUFFIX_PARAMS + ".npz")
    meta = {
        "names": names,
        "class_module": type(layer).__module__,
        "class_name": type(layer).__qualname__,
        "input_spec": input_spec,
    }
    # Best effort: pickle the layer object itself for exact reload.
    try:
        with open(path + _SUFFIX_MODEL, "wb") as f:
            pickle.dump({"meta": meta, "layer": layer}, f)
    except Exception:
        with open(path + _SUFFIX_MODEL, "wb") as f:
            pickle.dump({"meta": meta, "layer": None}, f)


class TranslatedLayer:
    """Loaded inference layer (reference: TranslatedLayer jit.py)."""

    def __init__(self, layer):
        self._layer = layer
        self._layer.eval()
        from .to_static import StaticFunction
        self._forward = StaticFunction(layer.forward, donate_state=False)

    def __call__(self, *args, **kwargs):
        from ..core.autograd import no_grad
        with no_grad():
            return self._forward(*args, **kwargs)

    def eval(self):
        self._layer.eval()
        return self

    def state_dict(self):
        return self._layer.state_dict()


def load(path, **config):
    with open(path + _SUFFIX_MODEL, "rb") as f:
        blob = pickle.load(f)
    layer = blob["layer"]
    if layer is None:
        raise RuntimeError(
            f"{path}: layer class could not be pickled at save time; "
            "reconstruct the layer and use set_state_dict + load_params")
    data = np.load(path + _SUFFIX_PARAMS + ".npz")
    names = blob["meta"]["names"]
    sd = {name: data[f"t{i}"] for i, name in enumerate(names)}
    layer.set_state_dict(sd)
    return TranslatedLayer(layer)
