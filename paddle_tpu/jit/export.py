"""StableHLO program export: the process-independent model artifact.

TPU-native analog of the reference's serialized ProgramDesc + params pair
(`/root/reference/python/paddle/fluid/io.py:1246` save_inference_model writes
`__model__` protobuf + persistables; `paddle/fluid/inference/io.cc` reloads it
with no Python in sight). Here the portable program IR is **StableHLO** via
`jax.export`: the forward is traced as a pure function of
`(params_list, *inputs)`, serialized to bytes, and served by deserializing —
no access to the model's Python class is needed at load site.

Artifact layout (matching the reference's two-file convention):
- ``{prefix}.pdmodel``   — zip: ``program.bin`` (jax.export bytes) +
  ``meta.json`` (format version, input/output names, param names, specs).
- ``{prefix}.pdiparams`` — npz of parameter arrays, ``p0..pN`` in meta order.

Batch-size polymorphism: `InputSpec` dims that are None/-1 become symbolic
export dimensions — axis 0 shares one symbol ("batch") across inputs, other
dynamic axes get unique symbols. This is the XLA-native replacement for the
reference's unconstrained feed shapes.
"""
import io as _io
import json
import os
import zipfile

import numpy as np
import jax
import jax.numpy as jnp
from jax import export as jax_export

from ..core import autograd
from ..core.dtype import convert_dtype
from ..core.dispatch import unwrap, bind_values
from ..core.tensor import Tensor

_FORMAT_VERSION = 1
_SUFFIX_PARAMS = ".pdiparams"
_SUFFIX_MODEL = ".pdmodel"


def _input_structs(input_specs):
    """InputSpec/Tensor/array list → jax.ShapeDtypeStruct list (symbolic dims
    for None/-1 entries in InputSpec shapes)."""
    structs, names = [], []
    scope = None
    n_sym = 0
    for i, spec in enumerate(input_specs):
        if isinstance(spec, Tensor):
            structs.append(jax.ShapeDtypeStruct(tuple(spec.shape), spec.dtype))
            names.append(spec.name or f"x{i}")
            continue
        if isinstance(spec, (np.ndarray, jnp.ndarray)):
            structs.append(jax.ShapeDtypeStruct(np.shape(spec), spec.dtype))
            names.append(f"x{i}")
            continue
        shape = list(spec.shape)
        dtype = convert_dtype(spec.dtype) or np.dtype("float32")
        if any(d is None or (isinstance(d, int) and d < 0) for d in shape):
            if scope is None:
                scope = jax_export.SymbolicScope()
            dims = []
            for ax, d in enumerate(shape):
                if d is None or (isinstance(d, int) and d < 0):
                    sym = "batch" if ax == 0 else f"dyn{n_sym}"
                    n_sym += ax != 0
                    dims.append(jax_export.symbolic_shape(sym, scope=scope)[0])
                else:
                    dims.append(d)
            structs.append(jax.ShapeDtypeStruct(tuple(dims), dtype))
        else:
            structs.append(jax.ShapeDtypeStruct(tuple(shape), dtype))
        names.append(getattr(spec, "name", None) or f"x{i}")
    return structs, names


def export_callable(fn, state_items, input_specs, output_names=None):
    """Export `fn(*input_tensors)` as StableHLO.

    `state_items`: [(name, Tensor)] — parameters/buffers the function reads
    (they become the leading `params` argument of the exported program).
    Returns (serialized_bytes, params_arrays, meta_dict).
    """
    names = [n for n, _ in state_items]
    tensors = [t for _, t in state_items]
    params = [np.asarray(unwrap(t)) for t in tensors]
    out_info = {}

    def pure(params_list, *inputs):
        with bind_values(tensors, params_list), autograd.no_grad():
            out = fn(*[Tensor(x) for x in inputs])
        leaves, treedef = jax.tree_util.tree_flatten(
            out, is_leaf=lambda x: isinstance(x, Tensor))
        out_info["n"] = len(leaves)
        return [unwrap(l) for l in leaves]

    in_structs, input_names = _input_structs(input_specs)
    param_structs = [jax.ShapeDtypeStruct(p.shape, p.dtype) for p in params]
    exported = jax_export.export(
        jax.jit(pure), platforms=("cpu", "tpu"))(param_structs, *in_structs)
    blob = exported.serialize()

    n_out = out_info.get("n", 1)
    if output_names is None:
        output_names = [f"output_{i}" for i in range(n_out)]
    from ..core import op_version
    meta = {
        "format_version": _FORMAT_VERSION,
        "op_versions": op_version.snapshot(),
        "param_names": names,
        "input_names": input_names,
        "input_specs": [
            {"shape": [d if isinstance(d, int) else None for d in s.shape],
             "dtype": np.dtype(s.dtype).name} for s in in_structs],
        "output_names": list(output_names)[:n_out],
    }
    return blob, params, meta


def write_artifact(path_prefix, blob, params, meta):
    d = os.path.dirname(path_prefix)
    if d:
        os.makedirs(d, exist_ok=True)
    with zipfile.ZipFile(path_prefix + _SUFFIX_MODEL, "w") as z:
        z.writestr("program.bin", blob)
        z.writestr("meta.json", json.dumps(meta))
    buf = _io.BytesIO()
    np.savez(buf, **{f"p{i}": p for i, p in enumerate(params)})
    with open(path_prefix + _SUFFIX_PARAMS, "wb") as f:
        f.write(buf.getvalue())


def save_exported(path_prefix, fn, state_items, input_specs,
                  output_names=None):
    blob, params, meta = export_callable(fn, state_items, input_specs,
                                         output_names)
    write_artifact(path_prefix, blob, params, meta)


def has_artifact(path_prefix, params_path=None):
    p = path_prefix + _SUFFIX_MODEL
    params = params_path or (path_prefix + _SUFFIX_PARAMS)
    if not (os.path.exists(p) and os.path.exists(params)):
        return False
    try:
        with zipfile.ZipFile(p) as z:
            return "program.bin" in z.namelist()
    except zipfile.BadZipFile:
        return False  # legacy pickle .pdmodel


class ServedProgram:
    """A loaded model artifact: deserialized StableHLO + params. Serves
    without the model's Python class (reference: AnalysisPredictor::Run,
    `analysis_predictor.cc:389` — load __model__, run NaiveExecutor)."""

    def __init__(self, path_prefix, params_path=None):
        with zipfile.ZipFile(path_prefix + _SUFFIX_MODEL) as z:
            blob = z.read("program.bin")
            self.meta = json.loads(z.read("meta.json"))
        from ..core import op_version
        op_version.check_compatible(self.meta.get("op_versions"))
        params_file = params_path or (path_prefix + _SUFFIX_PARAMS)
        if not os.path.exists(params_file):
            raise FileNotFoundError(
                f"params file not found: {params_file} (model: "
                f"{path_prefix + _SUFFIX_MODEL})")
        data = np.load(params_file)
        self.params = [jnp.asarray(data[f"p{i}"])
                       for i in range(len(self.meta["param_names"]))]
        self._exported = jax_export.deserialize(blob)
        self._call = jax.jit(self._exported.call)

    @property
    def input_names(self):
        return list(self.meta["input_names"])

    @property
    def output_names(self):
        return list(self.meta["output_names"])

    def __call__(self, *inputs):
        arrays = [x._value if isinstance(x, Tensor) else jnp.asarray(x)
                  for x in inputs]
        out = self._call(self.params, *arrays)
        return list(out)

    def state_dict(self):
        return {n: Tensor(p) for n, p in
                zip(self.meta["param_names"], self.params)}
