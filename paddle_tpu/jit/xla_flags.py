"""Per-program XLA compiler flags — the latency-hiding A/B knob.

``observability.overlap`` measures whether a compiled step hides its
collective traffic behind compute; this module is the knob that
measurement exists to evaluate: pass XLA's latency-hiding-scheduler /
async-collective flags to ONE program without touching the rest of the
process (the global ``XLA_FLAGS`` env var is process-wide and frozen at
backend init — useless for an in-process A/B).

    step = paddle.jit.to_static(train_step, scan_steps=8, dp_axis="dp",
                                xla_flags="latency-hiding")
    ...
    step.xla_flags()        # {"flags": {...}, "applied": True/False, ...}
    step.overlap_stats()    # did the schedule actually change?

``xla_flags`` accepts a preset name (:data:`PRESETS`), a
``"flag=value flag2=value2"`` string, a dict, or ``False`` (hard off —
no flags, no env overlay, no default; the A/B control spelling). The
``PADDLE_TPU_XLA_FLAGS`` env var overlays (and wins over) the per-call
value, so a runner can A/B a training script without editing it.
Scan-stepped programs that pass nothing default to
:data:`DEFAULT_SCAN_PRESET` when :func:`backend_accepts` says the
backend registers it — the double-buffered ZeRO pipeline is built for
that scheduler, and the smoke CPU (which rejects ``xla_tpu_*``
options) probes once and stays unflagged.

Flags ride ``jax.jit(..., compiler_options=...)``. XLA validates them at
the FIRST CALL (or AOT compile), not at ``jit()`` time, and rejects
options the backend doesn't register — ``xla_tpu_*`` flags on the CPU
smoke mesh raise ``INVALID_ARGUMENT: No such compile option``. That is
expected on the A/B's control host, so :class:`FlaggedJit` degrades
gracefully: the unknown-flag error triggers ONE silent recompile
without the options, and the fallback is recorded as provenance
(``applied=False`` + the error) in :meth:`FlaggedJit.provenance`,
bench-record metadata, and a ``xla_flags_fallback`` run-log event —
the A/B row then says honestly that the treatment never applied,
instead of comparing two identical programs. Any other compile error
propagates.
"""
import os

__all__ = ["PRESETS", "ENV_VAR", "DEFAULT_SCAN_PRESET", "parse_flags",
           "env_flags", "merge", "resolve", "backend_accepts", "jit",
           "FlaggedJit"]

ENV_VAR = "PADDLE_TPU_XLA_FLAGS"

# Preset a scan-compiled step program gets BY DEFAULT when the caller
# passed no xla_flags and the backend registers the options (see
# backend_accepts): the double-buffered ZeRO pipeline emits its
# collectives early precisely so the latency-hiding scheduler can sink
# them under compute — on backends with the scheduler, shipping the
# pipeline without the flags would measure the serial schedule. Opt out
# per program with ``xla_flags=False`` (the A/B control spelling).
DEFAULT_SCAN_PRESET = "latency-hiding"

# Named flag bundles for the standard A/Bs. The tpu-prefixed options
# only exist on TPU backends (falling back on CPU is the designed
# control behavior); both arms are spelled out so a --diff has two real
# configurations to compare.
PRESETS = {
    "latency-hiding": {
        "xla_tpu_enable_latency_hiding_scheduler": True,
        "xla_tpu_enable_async_collective_fusion": True,
        "xla_tpu_enable_async_collective_fusion_fuse_all_gather": True,
    },
    "no-latency-hiding": {
        "xla_tpu_enable_latency_hiding_scheduler": False,
        "xla_tpu_enable_async_collective_fusion": False,
    },
}


def _coerce(value):
    """XLA's compile-option parser rejects string-typed bools ("'false'
    is not a valid bool value"): coerce the textual forms to the python
    types the option registry expects."""
    low = value.lower()
    if low in ("true", "1"):
        return True if low == "true" else 1
    if low in ("false", "0"):
        return False if low == "false" else 0
    try:
        return int(value)
    except ValueError:
        pass
    try:
        return float(value)
    except ValueError:
        return value


def parse_flags(text):
    """``"a=true b=3"`` (space/comma separated; a leading ``--`` per
    token and bare ``flag`` meaning ``flag=true`` both accepted — the
    ``XLA_FLAGS`` spelling pastes in) -> options dict."""
    flags = {}
    for token in text.replace(",", " ").split():
        token = token.lstrip("-")
        if not token:
            continue
        if "=" in token:
            key, value = token.split("=", 1)
            flags[key] = _coerce(value)
        else:
            flags[token] = True
    return flags


def env_flags():
    """Options from ``PADDLE_TPU_XLA_FLAGS`` (preset name or flag
    string; empty dict when unset)."""
    text = os.environ.get(ENV_VAR, "").strip()
    if not text:
        return {}
    if text in PRESETS:
        return dict(PRESETS[text])
    return parse_flags(text)


def merge(*flag_dicts):
    """Left-to-right overlay; later dicts win per key."""
    out = {}
    for d in flag_dicts:
        if d:
            out.update(d)
    return out


def resolve(xla_flags):
    """Normalize a ``to_static(xla_flags=...)`` value — ``None``, a
    preset name, a flag string, or a dict — and overlay the env var
    (env wins: the runner doing the A/B outranks the script).

    ``False`` (or the strings ``"none"``/``"off"``) is the hard off
    switch: no flags, no env overlay, and no scan-body default — the
    spelling an A/B driver uses for its control arm, where "the runner
    outranks the script" must not re-arm the treatment."""
    if xla_flags is False or (isinstance(xla_flags, str)
                              and xla_flags.lower() in ("none", "off")):
        return {}
    if xla_flags is None:
        base = {}
    elif isinstance(xla_flags, dict):
        base = dict(xla_flags)
    elif isinstance(xla_flags, str):
        base = dict(PRESETS[xla_flags]) if xla_flags in PRESETS \
            else parse_flags(xla_flags)
    else:
        raise TypeError(
            f"xla_flags must be None, a preset name, a flag string, or "
            f"a dict; got {type(xla_flags).__name__}")
    return merge(base, env_flags())


def _is_unknown_flag_error(exc):
    msg = str(exc)
    return "No such compile option" in msg or "Unknown flag" in msg


_BACKEND_ACCEPTS = {}  # flag-set key -> bool, cached per process


def backend_accepts(flags):
    """Whether the current backend registers these compile options,
    judged ONCE per process per flag set by compiling a trivial flagged
    program. The scan-body default preset consults this before
    attaching itself: an explicit ``xla_flags=`` request never probes
    (FlaggedJit's per-program fallback records honest provenance
    instead), but a DEFAULT that the backend is known to reject would
    only buy every program a doomed first compile."""
    if not flags:
        return True
    key = tuple(sorted((k, str(v)) for k, v in flags.items()))
    if key not in _BACKEND_ACCEPTS:
        import jax
        import jax.numpy as jnp
        try:
            jax.jit(lambda x: x + 1,
                    compiler_options=dict(flags))(jnp.float32(0))
            _BACKEND_ACCEPTS[key] = True
        except Exception as e:
            if not _is_unknown_flag_error(e):
                raise
            _BACKEND_ACCEPTS[key] = False
    return _BACKEND_ACCEPTS[key]


def _log_fallback(flags, exc):
    from ..observability import runlog
    if runlog.active() is not None:
        runlog.event("xla_flags_fallback", flags=dict(flags),
                     error=str(exc)[:300])


class _FlaggedLowered:
    """AOT half of the fallback contract: ``lower().compile()`` applies
    the same options the call path uses, with the same unknown-flag
    degradation, so introspection (`hlo_text`, `overlap_stats`) sees
    the schedule the flags produced."""

    def __init__(self, lowered, owner):
        self._lowered = lowered
        self._owner = owner

    def compile(self):
        owner = self._owner
        if owner.flags and owner.applied is not False:
            try:
                compiled = self._lowered.compile(
                    compiler_options=dict(owner.flags))
                owner.applied = True
                return compiled
            except Exception as e:
                if not _is_unknown_flag_error(e):
                    raise
                owner._note_fallback(e)
        return self._lowered.compile()

    def __getattr__(self, name):
        return getattr(self._lowered, name)


class FlaggedJit:
    """``jax.jit`` wrapper carrying per-program compiler options with
    unknown-flag fallback and provenance. With empty ``flags`` it is a
    transparent pass-through (provenance still answers)."""

    def __init__(self, fun, flags=None, **jit_kwargs):
        import jax
        self._fun = fun
        self._jit_kwargs = jit_kwargs
        self.flags = dict(flags or {})
        #: True once a flagged compile succeeded, False after the
        #: unknown-flag fallback, None before the backend has judged
        self.applied = None if self.flags else False
        self.fallback_error = None
        if self.flags:
            self._jitted = jax.jit(fun, compiler_options=dict(self.flags),
                                   **jit_kwargs)
        else:
            self._jitted = jax.jit(fun, **jit_kwargs)

    def _note_fallback(self, exc):
        import jax
        self.applied = False
        self.fallback_error = str(exc)[:300]
        _log_fallback(self.flags, exc)
        self._jitted = jax.jit(self._fun, **self._jit_kwargs)

    def __call__(self, *args, **kwargs):
        if self.flags and self.applied is None:
            try:
                out = self._jitted(*args, **kwargs)
                self.applied = True
                return out
            except Exception as e:
                if not _is_unknown_flag_error(e):
                    raise
                self._note_fallback(e)
        return self._jitted(*args, **kwargs)

    def lower(self, *args, **kwargs):
        import jax
        if not self.flags:
            return self._jitted.lower(*args, **kwargs)
        # lower WITHOUT options (lowering is flag-independent), apply
        # them at compile() where the registry validates
        lowered = jax.jit(self._fun,
                          **self._jit_kwargs).lower(*args, **kwargs)
        return _FlaggedLowered(lowered, self)

    def provenance(self):
        """Flag provenance for bench records / runlogs: the resolved
        options, whether the backend accepted them (None = not judged
        yet), and the fallback error when it refused."""
        return {"flags": dict(self.flags), "applied": self.applied,
                "fallback_error": self.fallback_error}


def jit(fun, xla_flags=None, **jit_kwargs):
    """``jax.jit`` with a resolved per-program flag set (see
    :func:`resolve`) — the constructor ``to_static`` routes every
    program build through."""
    return FlaggedJit(fun, flags=xla_flags, **jit_kwargs)
