"""Persistent XLA compilation cache wiring.

The flagship step program costs tens of seconds (TPU) to minutes (large k)
of backend compile on a cold process; jax's persistent compilation cache
(`jax_compilation_cache_dir`) keys the serialized executable on the HLO +
compile options + backend version, so a restart replays the compile from
disk. This module owns the policy:

- ``configure_from_env()`` runs at ``paddle_tpu`` import and only RECORDS
  the policy (env vars below) — it must not touch the backend, because
  ``import paddle_tpu`` stays backend-clean for multi-process init.
- ``ensure_enabled()`` runs at first ``to_static`` build, when the backend
  is initialized anyway: default ON for accelerators, OFF for CPU smoke
  (cache writes would churn on every tiny test program). An explicit env
  dir/switch overrides the backend default in either direction.
- cache effectiveness is observable: jax's ``/jax/compilation_cache/*``
  monitoring events are mirrored into the shared monitor registry
  (``jit_persistent_cache_hits`` / ``_misses`` / ``_saved_ns``) next to
  the ``jit_backend_compile_ns`` counter the tracing hook maintains, so
  the cold/warm compile delta shows up in any metrics scrape.

Env:
    PADDLE_TPU_COMPILE_CACHE       "1"/"on" force-enable (any backend),
                                   "0"/"off" disable.
    PADDLE_TPU_COMPILE_CACHE_DIR   cache directory; setting it implies
                                   enable. Default ~/.cache/paddle_tpu/xla.
"""
import os

__all__ = ["configure_from_env", "ensure_enabled", "enable", "disable",
           "is_enabled", "cache_dir", "DEFAULT_CACHE_DIR"]

DEFAULT_CACHE_DIR = os.path.join(
    os.path.expanduser("~"), ".cache", "paddle_tpu", "xla")

_ENV_SWITCH = "PADDLE_TPU_COMPILE_CACHE"
_ENV_DIR = "PADDLE_TPU_COMPILE_CACHE_DIR"

# policy: None = decide from backend at first compile; True/False = forced
_state = {"policy": None, "dir": DEFAULT_CACHE_DIR, "enabled": False,
          "resolved": False}
_events_installed = [False]


def configure_from_env():
    """Record the env policy (import-time safe: no jax backend access)."""
    d = os.environ.get(_ENV_DIR)
    if d:
        _state["dir"] = d
        _state["policy"] = True
    switch = os.environ.get(_ENV_SWITCH, "").strip().lower()
    if switch in ("1", "on", "true", "yes"):
        _state["policy"] = True
    elif switch in ("0", "off", "false", "no"):
        _state["policy"] = False
    return _state["policy"]


def _install_event_mirror():
    """Count jax persistent-cache events into the monitor registry. jax
    has no unregister-one API, so install once and gate on enabled."""
    if _events_installed[0]:
        return
    try:
        from jax import monitoring as _jm
    except Exception:
        return
    from .. import monitor

    def _on_event(event, **kwargs):
        if not _state["enabled"]:
            return
        if event == "/jax/compilation_cache/cache_hits":
            monitor.stat_add("jit_persistent_cache_hits", 1)
        elif event == "/jax/compilation_cache/cache_misses":
            monitor.stat_add("jit_persistent_cache_misses", 1)

    def _on_duration(event, duration, **kwargs):
        if not _state["enabled"]:
            return
        if event == "/jax/compilation_cache/compile_time_saved_sec":
            monitor.stat_add("jit_persistent_cache_saved_ns",
                             int(duration * 1e9))

    _jm.register_event_listener(_on_event)
    _jm.register_event_duration_secs_listener(_on_duration)
    _events_installed[0] = True


def enable(directory=None, min_compile_time_secs=None):
    """Turn the persistent cache on (explicit API; also used by
    ``ensure_enabled``). ``min_compile_time_secs=0`` caches every program
    — the right setting for tests; the jax default (1s) skips trivial
    programs in production."""
    import jax

    if directory is not None:
        _state["dir"] = directory
    os.makedirs(_state["dir"], exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", _state["dir"])
    if min_compile_time_secs is not None:
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          float(min_compile_time_secs))
        # min_entry_size -1 disables the size floor so tiny smoke programs
        # round-trip too (only consulted when the time floor passes)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    _reset_jax_cache()
    _state["enabled"] = True
    _state["resolved"] = True
    _install_event_mirror()
    return _state["dir"]


def _reset_jax_cache():
    """jax initializes its cache object ONCE per process and never
    re-reads the config after that, so flipping the dir mid-process (a
    long-lived trainer enabling the cache after warmup compiles, or the
    tests) needs an explicit re-init."""
    try:
        from jax.experimental.compilation_cache import (
            compilation_cache as _cc)
        _cc.reset_cache()
    except Exception:
        pass  # pre-reset jax: the import-time config still applies


def disable():
    import jax

    jax.config.update("jax_compilation_cache_dir", None)
    _reset_jax_cache()
    _state["enabled"] = False
    _state["resolved"] = True


def ensure_enabled():
    """Resolve the policy once, at first compile (backend already up):
    accelerators default on, CPU defaults off, env overrides both."""
    if _state["resolved"]:
        return _state["enabled"]
    policy = _state["policy"]
    if policy is None:
        try:
            import jax
            policy = jax.default_backend() != "cpu"
        except Exception:
            policy = False
    if policy:
        enable()
    else:
        _state["resolved"] = True
    return _state["enabled"]


def is_enabled():
    return _state["enabled"]


def cache_dir():
    return _state["dir"] if _state["enabled"] else None
