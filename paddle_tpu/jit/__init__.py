"""paddle_tpu.jit — to_static + save/load (reference: `python/paddle/jit/`)."""
from .to_static import StaticFunction, InputSpec, to_static, not_to_static, in_tracing  # noqa: F401
from .io import save, load, TranslatedLayer  # noqa: F401
from .traced_layer import TracedLayer  # noqa: F401
from . import dy2static  # noqa: F401  (reference: paddle.jit.dy2static)
from . import compile_cache  # noqa: F401  (persistent XLA compile cache)
from . import xla_flags  # noqa: F401  (per-program compiler options)

compile_cache.configure_from_env()  # records env policy only; backend-clean
