"""TracedLayer — trace-and-serve surface over the trace-first compiler.

Reference: `python/paddle/fluid/dygraph/jit.py:1136` TracedLayer (backed
by `paddle/fluid/imperative/jit/program_desc_tracer.h:54`): trace a
dygraph Layer once into a static program, run it, and export an
inference model with feed/fetch index selection.

TPU mapping: `to_static`'s StaticFunction IS a program-desc tracer (one
abstract trace -> one jitted XLA program), so TracedLayer is a thin
veneer: `trace` compiles the layer's forward, `__call__` replays the
compiled program, and `save_inference_model` re-exports through
`jit.save`'s StableHLO artifact with the requested feed/fetch subset.
"""
from ..core.tensor import Tensor
from ..nn.layer.layers import Layer

__all__ = ["TracedLayer"]


class _FeedFetchWrapper(Layer):
    """Forward over the fed subset of the traced example inputs; non-fed
    inputs are frozen at their trace-time values (the reference prunes
    the program to the feed set the same way)."""

    def __init__(self, inner, examples, feed_idx, fetch_idx):
        super().__init__()
        self.inner = inner
        self._examples = list(examples)
        self._feed_idx = list(feed_idx)
        self._fetch_idx = list(fetch_idx)

    def forward(self, *fed):
        full = list(self._examples)
        for i, t in zip(self._feed_idx, fed):
            full[i] = t
        outs = self.inner(*full)
        flat = list(outs) if isinstance(outs, (list, tuple)) else [outs]
        sel = [flat[i] for i in self._fetch_idx]
        return sel[0] if len(sel) == 1 else sel


class TracedLayer:
    """Use :meth:`trace` to construct; do not call ``__init__`` directly
    (reference raises the same way, jit.py:1199)."""

    def __init__(self, layer, static_fn, examples, n_outs):
        self._layer = layer
        self._static = static_fn
        self._examples = examples
        self._n_outs = n_outs

    @staticmethod
    def trace(layer, inputs):
        """Returns ``(outputs, traced_layer)``: outputs of one traced run
        plus the replayable TracedLayer (reference jit.py:1223)."""
        from .to_static import to_static
        if not isinstance(layer, Layer):
            raise TypeError(
                f"TracedLayer.trace expects a Layer, got {type(layer)}")
        # the reference accepts list(Tensor)|tuple(Tensor)|Tensor
        # (jit.py:1198); a bare Tensor must become ONE argument —
        # list(Tensor) would iterate it row-wise via Tensor.__iter__
        if isinstance(inputs, Tensor):
            inputs = [inputs]
        examples = list(inputs)
        static_fn = to_static(lambda *xs: layer(*xs))
        outs = static_fn(*examples)
        n_outs = len(outs) if isinstance(outs, (list, tuple)) else 1
        return outs, TracedLayer(layer, static_fn, examples, n_outs)

    def __call__(self, inputs):
        return self._static(*inputs)

    def set_strategy(self, build_strategy=None, exec_strategy=None):
        """Accepted for API parity; pass scheduling/placement strategy is
        XLA's job on TPU (reference jit.py:1259 wires these into
        ParallelExecutor, which has no analog here — GSPMD + the jit
        cache replace it)."""
        self._build_strategy = build_strategy
        self._exec_strategy = exec_strategy

    def save_inference_model(self, path, feed=None, fetch=None, **config):
        """Export the traced program as a serveable artifact, keeping only
        the ``feed``-indexed inputs and ``fetch``-indexed outputs
        (reference jit.py:1295 prunes the program the same way)."""
        from . import io as jit_io
        feed_idx = list(feed) if feed is not None else \
            list(range(len(self._examples)))
        fetch_idx = list(fetch) if fetch is not None else \
            list(range(self._n_outs))
        for i in feed_idx:
            if not 0 <= i < len(self._examples):
                raise ValueError(
                    f"feed index {i} outside [0, {len(self._examples)})")
        for i in fetch_idx:
            if not 0 <= i < self._n_outs:
                raise ValueError(
                    f"fetch index {i} outside [0, {self._n_outs})")
        wrapper = _FeedFetchWrapper(self._layer, self._examples,
                                    feed_idx, fetch_idx)
        # batch-polymorphic export: feed specs carry a symbolic axis 0
        # (None → jax.export "batch" dim) instead of freezing the
        # trace-time batch size; the reference's saved inference model
        # serves arbitrary batch sizes the same way. Only possible when
        # EVERY input is fed — a partial feed freezes the rest at their
        # concrete trace-time values, and a symbolic batch interacting
        # with a concrete one fails the export trace
        if len(feed_idx) == len(self._examples):
            from .to_static import InputSpec
            specs = []
            for i in feed_idx:
                ex = self._examples[i]
                shape = tuple(ex.shape)
                if len(shape) >= 1:
                    shape = (None,) + shape[1:]
                specs.append(InputSpec(shape, dtype=str(ex.dtype),
                                       name=getattr(ex, "name", None)))
        else:
            specs = [self._examples[i] for i in feed_idx]
        return jit_io.save(wrapper, path, input_spec=specs, **config)
