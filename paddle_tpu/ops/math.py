"""Math / elementwise / reduction / linalg ops.

TPU-native replacement for the reference's dense op library
(`paddle/fluid/operators/*_op.cc`, `elementwise/`, `reduce_ops/`,
`operators/math/blas.h`): every op is a pure jnp lowering — XLA is the kernel
library, fusion comes from the compiler rather than hand-written CUDA.
"""
import jax
import jax.numpy as jnp

from ..core.dispatch import call_op, call_op_nograd, unwrap
from ..core.tensor import Tensor
from ..core.dtype import convert_dtype

# ---------------------------------------------------------------- creation

def to_value(x):
    return unwrap(x)


def full(shape, fill_value, dtype="float32"):
    if isinstance(shape, Tensor):
        shape = [int(s) for s in shape.numpy()]
    if isinstance(shape, int):
        shape = [shape]
    return Tensor(jnp.full(tuple(shape), unwrap(fill_value), dtype=convert_dtype(dtype)))


def zeros(shape, dtype="float32"):
    return full(shape, 0, dtype)


def ones(shape, dtype="float32"):
    return full(shape, 1, dtype)


def zeros_like(x, dtype=None):
    return Tensor(jnp.zeros_like(unwrap(x), dtype=convert_dtype(dtype)))



def empty(shape, dtype="float32"):
    """reference: empty_op.cc — uninitialized-allocation semantics are
    meaningless under XLA's functional arrays; zeros keep the shape/dtype
    contract deterministic."""
    return zeros(shape, dtype)


def empty_like(x, dtype=None):
    return zeros_like(x, dtype)


def is_empty(x):
    """reference: is_empty_op.cc — true iff the tensor has zero elements."""
    return Tensor(jnp.asarray(unwrap(x).size == 0))

def ones_like(x, dtype=None):
    return Tensor(jnp.ones_like(unwrap(x), dtype=convert_dtype(dtype)))


def full_like(x, fill_value, dtype=None):
    return Tensor(jnp.full_like(unwrap(x), fill_value, dtype=convert_dtype(dtype)))


def arange(start=0, end=None, step=1, dtype=None):
    if end is None:
        start, end = 0, start
    start, end, step = unwrap(start), unwrap(end), unwrap(step)
    return Tensor(jnp.arange(start, end, step, dtype=convert_dtype(dtype)))


def linspace(start, stop, num, dtype="float32"):
    return Tensor(jnp.linspace(unwrap(start), unwrap(stop), int(num),
                               dtype=convert_dtype(dtype)))


def eye(num_rows, num_columns=None, dtype="float32"):
    return Tensor(jnp.eye(num_rows, num_columns, dtype=convert_dtype(dtype)))


def tril(x, diagonal=0):
    return call_op(jnp.tril, x, k=diagonal, op_name="tril")


def triu(x, diagonal=0):
    return call_op(jnp.triu, x, k=diagonal, op_name="triu")


def diag(x, offset=0):
    return call_op(jnp.diag, x, k=offset, op_name="diag")


# ------------------------------------------------------------- elementwise

def _unary(fn, x, name):
    return call_op(fn, x, op_name=name)


def exp(x):
    return _unary(jnp.exp, x, "exp")


def log(x):
    return _unary(jnp.log, x, "log")


def log2(x):
    return _unary(jnp.log2, x, "log2")


def log10(x):
    return _unary(jnp.log10, x, "log10")


def log1p(x):
    return _unary(jnp.log1p, x, "log1p")


def sqrt(x):
    return _unary(jnp.sqrt, x, "sqrt")


def rsqrt(x):
    return _unary(jax.lax.rsqrt, x, "rsqrt")


def square(x):
    return _unary(jnp.square, x, "square")


def abs(x):  # noqa: A001 - paddle API name
    return _unary(jnp.abs, x, "abs")


def sign(x):
    return _unary(jnp.sign, x, "sign")


def neg(x):
    return _unary(jnp.negative, x, "neg")


def reciprocal(x):
    return _unary(jnp.reciprocal, x, "reciprocal")


def floor(x):
    return _unary(jnp.floor, x, "floor")


def ceil(x):
    return _unary(jnp.ceil, x, "ceil")


def round(x):  # noqa: A001
    return _unary(jnp.round, x, "round")


def sin(x):
    return _unary(jnp.sin, x, "sin")


def cos(x):
    return _unary(jnp.cos, x, "cos")


def tan(x):
    return _unary(jnp.tan, x, "tan")


def asin(x):
    return _unary(jnp.arcsin, x, "asin")


def acos(x):
    return _unary(jnp.arccos, x, "acos")


def atan(x):
    return _unary(jnp.arctan, x, "atan")


def sinh(x):
    return _unary(jnp.sinh, x, "sinh")


def cosh(x):
    return _unary(jnp.cosh, x, "cosh")


def tanh(x):
    return _unary(jnp.tanh, x, "tanh")


def erf(x):
    return _unary(jax.scipy.special.erf, x, "erf")


def expm1(x):
    return _unary(jnp.expm1, x, "expm1")


def logit(x, eps=None):
    def _logit(v):
        if eps is not None:
            v = jnp.clip(v, eps, 1.0 - eps)
        return jnp.log(v / (1.0 - v))
    return call_op(_logit, x, op_name="logit")


def isnan(x):
    return call_op_nograd(jnp.isnan, x)


def isinf(x):
    return call_op_nograd(jnp.isinf, x)


def isfinite(x):
    return call_op_nograd(jnp.isfinite, x)


def clip(x, min=None, max=None):  # noqa: A002
    return call_op(jnp.clip, x, min=unwrap(min), max=unwrap(max), op_name="clip")


# ------------------------------------------------------------------ binary

def add(x, y):
    return call_op(jnp.add, x, y, op_name="add")


def subtract(x, y):
    return call_op(jnp.subtract, x, y, op_name="subtract")


def multiply(x, y):
    return call_op(jnp.multiply, x, y, op_name="multiply")


def divide(x, y):
    return call_op(jnp.divide, x, y, op_name="divide")


def floor_divide(x, y):
    return call_op_nograd(jnp.floor_divide, x, y)


def mod(x, y):
    return call_op(jnp.mod, x, y, op_name="mod")


def pow(x, y):  # noqa: A001
    return call_op(jnp.power, x, y, op_name="pow")


def maximum(x, y):
    return call_op(jnp.maximum, x, y, op_name="maximum")


def minimum(x, y):
    return call_op(jnp.minimum, x, y, op_name="minimum")


def atan2(x, y):
    return call_op(jnp.arctan2, x, y, op_name="atan2")


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None):
    def _scale(v, s, b):
        out = v * s + b if bias_after_scale else (v + b) * s
        return out
    out = call_op(_scale, x, unwrap(scale), unwrap(bias), op_name="scale")
    if act is not None:
        from ..nn import functional as F
        out = getattr(F, act)(out)
    return out


# -------------------------------------------------------------- comparison

def equal(x, y):
    return call_op_nograd(jnp.equal, x, y)


def not_equal(x, y):
    return call_op_nograd(jnp.not_equal, x, y)


def greater_than(x, y):
    return call_op_nograd(jnp.greater, x, y)


def greater_equal(x, y):
    return call_op_nograd(jnp.greater_equal, x, y)


def less_than(x, y):
    return call_op_nograd(jnp.less, x, y)


def less_equal(x, y):
    return call_op_nograd(jnp.less_equal, x, y)


def logical_and(x, y):
    return call_op_nograd(jnp.logical_and, x, y)


def logical_or(x, y):
    return call_op_nograd(jnp.logical_or, x, y)


def logical_not(x):
    return call_op_nograd(jnp.logical_not, x)


def logical_xor(x, y):
    return call_op_nograd(jnp.logical_xor, x, y)


def allclose(x, y, rtol=1e-5, atol=1e-8, equal_nan=False):
    return call_op_nograd(jnp.allclose, x, y, rtol=rtol, atol=atol,
                          equal_nan=equal_nan)


def equal_all(x, y):
    return call_op_nograd(lambda a, b: jnp.array_equal(a, b), x, y)


def where(condition, x=None, y=None):
    if x is None and y is None:
        return nonzero(condition, as_tuple=True)
    return call_op(lambda c, a, b: jnp.where(c, a, b), unwrap(condition), x, y,
                   op_name="where")


def nonzero(x, as_tuple=False):
    import numpy as np
    arr = np.asarray(unwrap(x))
    idx = np.nonzero(arr)
    if as_tuple:
        return tuple(Tensor(i) for i in idx)
    return Tensor(np.stack(idx, axis=-1))


# -------------------------------------------------------------- reductions

def _norm_axis(axis):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def sum(x, axis=None, dtype=None, keepdim=False):  # noqa: A001
    return call_op(jnp.sum, x, axis=_norm_axis(axis),
                   dtype=convert_dtype(dtype), keepdims=keepdim, op_name="sum")


def mean(x, axis=None, keepdim=False):
    return call_op(jnp.mean, x, axis=_norm_axis(axis), keepdims=keepdim,
                   op_name="mean")


def max(x, axis=None, keepdim=False):  # noqa: A001
    return call_op(jnp.max, x, axis=_norm_axis(axis), keepdims=keepdim,
                   op_name="max")


def min(x, axis=None, keepdim=False):  # noqa: A001
    return call_op(jnp.min, x, axis=_norm_axis(axis), keepdims=keepdim,
                   op_name="min")


def prod(x, axis=None, keepdim=False, dtype=None):
    return call_op(jnp.prod, x, axis=_norm_axis(axis), keepdims=keepdim,
                   dtype=convert_dtype(dtype), op_name="prod")


def std(x, axis=None, unbiased=True, keepdim=False):
    return call_op(jnp.std, x, axis=_norm_axis(axis),
                   ddof=1 if unbiased else 0, keepdims=keepdim, op_name="std")


def var(x, axis=None, unbiased=True, keepdim=False):
    return call_op(jnp.var, x, axis=_norm_axis(axis),
                   ddof=1 if unbiased else 0, keepdims=keepdim, op_name="var")


def logsumexp(x, axis=None, keepdim=False):
    return call_op(jax.scipy.special.logsumexp, x, axis=_norm_axis(axis),
                   keepdims=keepdim, op_name="logsumexp")


def all(x, axis=None, keepdim=False):  # noqa: A001
    return call_op_nograd(jnp.all, x, axis=_norm_axis(axis), keepdims=keepdim)


def any(x, axis=None, keepdim=False):  # noqa: A001
    return call_op_nograd(jnp.any, x, axis=_norm_axis(axis), keepdims=keepdim)


def argmax(x, axis=None, keepdim=False, dtype="int64"):
    return call_op_nograd(jnp.argmax, x, axis=axis, keepdims=keepdim).astype(dtype)


def argmin(x, axis=None, keepdim=False, dtype="int64"):
    return call_op_nograd(jnp.argmin, x, axis=axis, keepdims=keepdim).astype(dtype)


def argsort(x, axis=-1, descending=False):
    def _argsort(v):
        idx = jnp.argsort(v, axis=axis)
        return jnp.flip(idx, axis=axis) if descending else idx
    return call_op_nograd(_argsort, x)


def sort(x, axis=-1, descending=False):
    def _sort(v):
        out = jnp.sort(v, axis=axis)
        return jnp.flip(out, axis=axis) if descending else out
    return call_op(_sort, x, op_name="sort")


def topk(x, k, axis=-1, largest=True, sorted=True):  # noqa: A002
    """Composite: indices in a non-diff pass, values gathered differentiably."""
    v = unwrap(x)
    ax = axis if axis >= 0 else v.ndim + axis

    def _indices(val):
        if not largest:
            val = -val
        moved = jnp.moveaxis(val, ax, -1)
        _, idx = jax.lax.top_k(moved, k)
        return jnp.moveaxis(idx, -1, ax)

    idx = call_op_nograd(_indices, x)

    def _gather(val, i):
        return jnp.take_along_axis(val, i, axis=ax)

    values = call_op(_gather, x, unwrap(idx), op_name="topk_gather")
    return values, idx.astype("int64")


def cumsum(x, axis=None, dtype=None):
    def _cs(v):
        if axis is None:
            return jnp.cumsum(v.reshape(-1), dtype=convert_dtype(dtype))
        return jnp.cumsum(v, axis=axis, dtype=convert_dtype(dtype))
    return call_op(_cs, x, op_name="cumsum")


def cumprod(x, dim=None, dtype=None):
    def _cp(v):
        if dim is None:
            return jnp.cumprod(v.reshape(-1), dtype=convert_dtype(dtype))
        return jnp.cumprod(v, axis=dim, dtype=convert_dtype(dtype))
    return call_op(_cp, x, op_name="cumprod")


# ------------------------------------------------------------------ linalg

def matmul(x, y, transpose_x=False, transpose_y=False):
    def _mm(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim > 1 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim > 1 else b
        return jnp.matmul(a, b)
    return call_op(_mm, x, y, op_name="matmul")


def dot(x, y):
    def _dot(a, b):
        return jnp.sum(a * b, axis=-1)
    return call_op(_dot, x, y, op_name="dot")


def bmm(x, y):
    return call_op(jnp.matmul, x, y, op_name="bmm")


def mm(x, y):
    return call_op(jnp.matmul, x, y, op_name="mm")


def t(x):
    return call_op(lambda v: v.T, x, op_name="t")


def norm(x, p=2, axis=None, keepdim=False):
    def _norm(v):
        if p == "fro" or p == 2:
            return jnp.sqrt(jnp.sum(jnp.square(v), axis=_norm_axis(axis),
                                    keepdims=keepdim))
        if p == 1:
            return jnp.sum(jnp.abs(v), axis=_norm_axis(axis), keepdims=keepdim)
        if p == float("inf"):
            return jnp.max(jnp.abs(v), axis=_norm_axis(axis), keepdims=keepdim)
        return jnp.power(
            jnp.sum(jnp.power(jnp.abs(v), p), axis=_norm_axis(axis),
                    keepdims=keepdim), 1.0 / p)
    return call_op(_norm, x, op_name="norm")


def einsum(equation, *operands):
    return call_op(lambda *ops: jnp.einsum(equation, *ops), *operands,
                   op_name="einsum")


def multiply_sum(x, y):  # helper used by some losses
    return sum(multiply(x, y))


def addmm(input, x, y, beta=1.0, alpha=1.0):  # noqa: A002
    return call_op(lambda i, a, b: beta * i + alpha * jnp.matmul(a, b),
                   input, x, y, op_name="addmm")


def cast(x, dtype):
    dt = convert_dtype(dtype)

    def _cast(v):
        return v.astype(dt)

    from ..core.dtype import is_floating
    if is_floating(dt) and isinstance(x, Tensor) and is_floating(x.dtype):
        return call_op(_cast, x, op_name="cast")
    return call_op_nograd(_cast, x)
