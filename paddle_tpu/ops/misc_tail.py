"""Residual dense-op tail (round 4): segmentation/sequence metrics,
linear-algebra composites, sharding helpers, and vision IO.

References: `operators/mean_iou_op.{cc,h}`, `operators/chunk_eval_op.{cc,h}`,
`operators/diag_embed_op.cc`, `operators/bilinear_tensor_product_op.{cc,h}`,
`operators/shard_index_op.cc`, `operators/sampling_id_op.cc`,
`operators/match_matrix_tensor_op.{cc,h}` and
`python/paddle/vision/ops.py` read_file/decode_jpeg (nvjpeg on the
reference GPU path; PIL-backed host decode here — image IO is input
pipeline work, not TPU work).
"""
import numpy as np

from ..core.dispatch import call_op, call_op_nograd, unwrap, wrap

__all__ = ["mean_iou", "chunk_eval", "diag_embed",
           "bilinear_tensor_product", "shard_index", "sampling_id",
           "read_file", "decode_jpeg", "match_matrix_tensor",
           "add_position_encoding", "batch_fc", "polygon_box_transform",
           "correlation", "sequence_topk_avg_pooling",
           "positive_negative_pair", "similarity_focus"]


def mean_iou(input, label, num_classes):  # noqa: A002
    """Mean intersection-over-union (mean_iou_op.h): per-class
    correct/wrong counts from the prediction/label pair, IoU averaged
    over classes that appear. Returns (mean_iou, out_wrong, out_correct)
    exactly like the reference op."""
    import jax.numpy as jnp

    def _mi(pred, lab):
        pred = pred.reshape(-1).astype(jnp.int32)
        lab = lab.reshape(-1).astype(jnp.int32)
        hit = pred == lab
        correct = jnp.zeros(num_classes, jnp.int32).at[lab].add(
            hit.astype(jnp.int32))
        wrong = jnp.zeros(num_classes, jnp.int32)
        wrong = wrong.at[pred].add((~hit).astype(jnp.int32))
        wrong = wrong.at[lab].add((~hit).astype(jnp.int32))
        denom = correct + wrong
        valid = denom > 0
        iou = jnp.where(valid, correct / jnp.maximum(denom, 1), 0.0)
        miou = jnp.sum(iou) / jnp.maximum(jnp.sum(valid), 1)
        return (miou.astype(jnp.float32), wrong, correct)

    return call_op_nograd(_mi, input, label, op_name="mean_iou")


def _extract_chunks(tags, scheme, num_chunk_types, excluded):
    """Chunk segments as {(begin, end, type)} (chunk_eval_op.h
    ChunkEvalKernel::GetSegments). Tag encoding follows the reference:
    label = chunk_type * tags_per_type + tag_position."""
    chunks = set()
    n = len(tags)
    if scheme == "plain":
        i = 0
        while i < n:
            t = tags[i]
            if 0 <= t < num_chunk_types:
                j = i
                while j + 1 < n and tags[j + 1] == t:
                    j += 1
                chunks.add((i, j, int(t)))
                i = j + 1
            else:
                i += 1
    elif scheme in ("IOB", "IOE"):
        # IOB: type*2 = B, type*2+1 = I;  IOE: type*2 = I, type*2+1 = E
        i = 0
        while i < n:
            t = tags[i]
            ctype, pos = divmod(int(t), 2)
            if not 0 <= ctype < num_chunk_types:
                i += 1
                continue
            j = i
            if scheme == "IOB":
                # chunk starts at B (or stray I, like the reference's
                # lenient begin detection) and runs through same-type I
                while j + 1 < n and tags[j + 1] == ctype * 2 + 1:
                    j += 1
            else:  # IOE: runs through same-type I, ends at E
                while j + 1 < n and tags[j] == ctype * 2 and \
                        tags[j + 1] in (ctype * 2, ctype * 2 + 1):
                    j += 1
            chunks.add((i, j, ctype))
            i = j + 1
    elif scheme == "IOBES":
        i = 0
        while i < n:
            t = tags[i]
            ctype, pos = divmod(int(t), 4)  # B, I, E, S
            if not 0 <= ctype < num_chunk_types:
                i += 1
                continue
            if pos == 3:  # S: singleton
                chunks.add((i, i, ctype))
                i += 1
                continue
            j = i
            while j + 1 < n and tags[j + 1] in (ctype * 4 + 1,
                                                ctype * 4 + 2):
                end_pos = tags[j + 1] % 4
                j += 1
                if end_pos == 2:  # E closes the chunk
                    break
            chunks.add((i, j, ctype))
            i = j + 1
    else:
        raise ValueError(f"unknown chunk_scheme {scheme!r} "
                         f"(IOB, IOE, IOBES, plain)")
    if excluded:
        chunks = {c for c in chunks if c[2] not in excluded}
    return chunks


def chunk_eval(input, label, chunk_scheme, num_chunk_types,  # noqa: A002
               excluded_chunk_types=None, seq_length=None):
    """Chunk detection precision/recall/F1 (chunk_eval_op.cc — the NER
    metric). Host-side like the reference's CPU-only kernel. Returns
    (precision, recall, f1, num_infer_chunks, num_label_chunks,
    num_correct_chunks)."""
    inp = np.asarray(unwrap(input)).astype(np.int64)
    lab = np.asarray(unwrap(label)).astype(np.int64)
    if inp.ndim == 1:
        inp, lab = inp[None, :], lab[None, :]
    excluded = set(excluded_chunk_types or [])
    lengths = (np.asarray(unwrap(seq_length)).astype(np.int64).ravel()
               if seq_length is not None
               else np.full(inp.shape[0], inp.shape[1], np.int64))
    n_infer = n_label = n_correct = 0
    for b in range(inp.shape[0]):
        L = int(lengths[b])
        infer = _extract_chunks(inp[b, :L].tolist(), chunk_scheme,
                                num_chunk_types, excluded)
        gold = _extract_chunks(lab[b, :L].tolist(), chunk_scheme,
                               num_chunk_types, excluded)
        n_infer += len(infer)
        n_label += len(gold)
        n_correct += len(infer & gold)
    precision = n_correct / n_infer if n_infer else 0.0
    recall = n_correct / n_label if n_label else 0.0
    f1 = (2 * precision * recall / (precision + recall)
          if precision + recall else 0.0)
    import jax.numpy as jnp
    mk = lambda v, dt: wrap(jnp.asarray(v, dt))  # noqa: E731
    return (mk(precision, jnp.float32), mk(recall, jnp.float32),
            mk(f1, jnp.float32), mk(n_infer, jnp.int32),
            mk(n_label, jnp.int32), mk(n_correct, jnp.int32))


def diag_embed(input, offset=0, dim1=-2, dim2=-1):  # noqa: A002
    """Embed the last dim as a diagonal of a new square matrix
    (diag_embed_op.cc): output gains one dim; the diagonal at `offset`
    along (dim1, dim2) holds the input."""
    import jax.numpy as jnp

    def _de(x):
        n = x.shape[-1]
        m = n + abs(offset)
        rows = jnp.arange(n) + max(-offset, 0)
        cols = jnp.arange(n) + max(offset, 0)
        out = jnp.zeros(x.shape[:-1] + (m, m), x.dtype)
        out = out.at[..., rows, cols].set(x)
        nd = out.ndim
        d1 = dim1 if dim1 >= 0 else nd + dim1
        d2 = dim2 if dim2 >= 0 else nd + dim2
        return jnp.moveaxis(out, (nd - 2, nd - 1), (d1, d2))

    return call_op(_de, input, op_name="diag_embed")


def bilinear_tensor_product(x, y, weight, bias=None):
    """out[b, k] = x[b]ᵀ W[k] y[b] (+ bias)
    (bilinear_tensor_product_op.h) — one einsum on the MXU instead of
    the reference's per-k GEMM loop."""
    import jax.numpy as jnp

    def _btp(xv, yv, wv, *bv):
        out = jnp.einsum("bi,kij,bj->bk", xv, wv, yv)
        if bv:
            out = out + bv[0]
        return out

    args = (x, y, weight) + ((bias,) if bias is not None else ())
    return call_op(_btp, *args, op_name="bilinear_tensor_product")


def shard_index(input, index_num, nshards, shard_id,  # noqa: A002
                ignore_value=-1):
    """Map global ids onto one shard's local range (shard_index_op.cc):
    ids owned by `shard_id` become `id % shard_size`, others
    `ignore_value`."""
    import jax.numpy as jnp

    if not 0 <= shard_id < nshards:
        raise ValueError(
            f"shard_id {shard_id} outside [0, {nshards})")
    shard_size = (index_num + nshards - 1) // nshards

    def _si(ids):
        owner = ids // shard_size
        return jnp.where(owner == shard_id, ids % shard_size,
                         ignore_value)

    return call_op_nograd(_si, input, op_name="shard_index")


def sampling_id(x, min=0.0, max=1.0, seed=0):  # noqa: A002
    """Sample one column index per row of a probability matrix
    (sampling_id_op.cc): u ~ U(min, max), index = first j with
    cumsum(x[i]) > u. Deterministic under `seed` like the reference's
    seeded engine; seed=0 draws from the global generator."""
    import jax
    import jax.numpy as jnp

    from ..core import random as core_random

    def _sid(xv, key):
        u = jax.random.uniform(key, (xv.shape[0],), jnp.float32,
                               minval=min, maxval=max)
        cs = jnp.cumsum(xv, axis=1)
        idx = jnp.sum((cs <= u[:, None]).astype(jnp.int64), axis=1)
        return jnp.minimum(idx, xv.shape[1] - 1)

    key = jax.random.PRNGKey(seed) if seed else core_random.next_key()
    return call_op_nograd(_sid, x, key, op_name="sampling_id")


def read_file(filename, name=None):
    """File bytes as a uint8 tensor (python/paddle/vision/ops.py
    read_file; the reference reads via CPU tensor too)."""
    import jax.numpy as jnp
    with open(filename, "rb") as f:
        data = f.read()
    return wrap(jnp.asarray(np.frombuffer(data, np.uint8)))


def decode_jpeg(x, mode="unchanged", name=None):
    """Decode a JPEG byte tensor to CHW uint8 (vision/ops.py
    decode_jpeg; nvjpeg on the reference GPU path — host PIL decode
    here, image IO belongs to the input pipeline, not the TPU)."""
    import io

    import jax.numpy as jnp
    from PIL import Image

    raw = bytes(np.asarray(unwrap(x)).astype(np.uint8))
    img = Image.open(io.BytesIO(raw))
    if mode == "gray":
        img = img.convert("L")
    elif mode == "rgb":
        img = img.convert("RGB")
    arr = np.asarray(img, np.uint8)
    if arr.ndim == 2:
        arr = arr[None, :, :]
    else:
        arr = arr.transpose(2, 0, 1)  # HWC -> CHW like the reference
    return wrap(jnp.asarray(arr))


def match_matrix_tensor(x, y, w, x_lens=None, y_lens=None):
    """Semantic-match tensor (match_matrix_tensor_op.h, the text-match
    contrib op): for each pair, out[b, t, i, j] = x[b,i]ᵀ W[t] y[b,j].

    The reference consumes LoD pairs and emits a flattened LoD result;
    the TPU-native form is padded: x (B, Lx, Dx), y (B, Ly, Dy),
    w (Dx, T, Dy) -> (out (B, T, Lx, Ly), mask (B, 1, Lx, Ly)) with the
    mask zeroing padded positions from `x_lens`/`y_lens`.
    """
    import jax.numpy as jnp

    def _mmt(xv, yv, wv):
        return jnp.einsum("bid,dtm,bjm->btij", xv, wv, yv)

    out = call_op(_mmt, x, y, w, op_name="match_matrix_tensor")
    xv = unwrap(x)
    yv = unwrap(y)
    b, lx = xv.shape[0], xv.shape[1]
    ly = yv.shape[1]
    if x_lens is None and y_lens is None:
        mask = jnp.ones((b, 1, lx, ly), jnp.float32)
    else:
        xl = (jnp.asarray(unwrap(x_lens)).reshape(b, 1)
              if x_lens is not None else jnp.full((b, 1), lx))
        yl = (jnp.asarray(unwrap(y_lens)).reshape(b, 1)
              if y_lens is not None else jnp.full((b, 1), ly))
        mx = (jnp.arange(lx)[None, :] < xl).astype(jnp.float32)
        my = (jnp.arange(ly)[None, :] < yl).astype(jnp.float32)
        mask = (mx[:, :, None] * my[:, None, :])[:, None, :, :]
    return out, wrap(mask)


def add_position_encoding(x, alpha=1.0, beta=1.0):
    """out = alpha·x + beta·PE (add_position_encoding_op.h): even feature
    size; first half sin(pos / 10000^(i/half)), second half the matching
    cos — the Transformer sinusoid the reference implements."""
    import jax.numpy as jnp

    def _ape(xv):
        B, L, D = xv.shape
        if D % 2:
            raise ValueError("feature size must be even")
        half = D // 2
        pos = jnp.arange(L, dtype=jnp.float32)[:, None]
        div = jnp.power(10000.0, jnp.arange(half, dtype=jnp.float32)
                        / half)
        pe = jnp.concatenate([jnp.sin(pos / div), jnp.cos(pos / div)],
                             axis=1)
        return alpha * xv + beta * pe[None, :, :]

    return call_op(_ape, x, op_name="add_position_encoding")


def batch_fc(input, w, bias=None):  # noqa: A002
    """Per-slot batched FC (batch_fc_op.cc, the rank-aware CTR layer):
    input (S, B, I) @ w (S, I, O) + bias (S, 1, O) per slot S — one
    batched MXU matmul instead of the reference's per-slot GEMM loop."""
    import jax.numpy as jnp

    def _bfc(xv, wv, *bv):
        out = jnp.einsum("sbi,sio->sbo", xv, wv)
        if bv:
            out = out + bv[0]
        return out

    args = (input, w) + ((bias,) if bias is not None else ())
    return call_op(_bfc, *args, op_name="batch_fc")


def polygon_box_transform(input):  # noqa: A002
    """EAST geometry-map decode (detection/polygon_box_transform_op.cc):
    even channels become 4·x_index − v, odd channels 4·y_index − v."""
    import jax.numpy as jnp

    def _pbt(xv):
        B, G, H, W = xv.shape
        xs = jnp.arange(W, dtype=xv.dtype)[None, None, None, :] * 4.0
        ys = jnp.arange(H, dtype=xv.dtype)[None, None, :, None] * 4.0
        even = jnp.arange(G) % 2 == 0
        grid = jnp.where(even[None, :, None, None], xs, ys)
        return grid - xv

    return call_op(_pbt, input, op_name="polygon_box_transform")


def correlation(x1, x2, pad_size, kernel_size, max_displacement,
                stride1=1, stride2=1):
    """FlowNet correlation volume (operators/correlation_op.cc): mean
    over channels of x1 · shift(x2, d) for every displacement d in the
    (2·max_displacement/stride2 + 1)² window. The displacement loop is a
    compile-time constant, so XLA sees a fixed stack of fused
    multiply-reduce ops (the reference hand-writes a CUDA kernel)."""
    import jax.numpy as jnp

    if kernel_size != 1:
        raise NotImplementedError(
            "correlation with kernel_size != 1 (the common FlowNet "
            "config) is not implemented")
    d = max_displacement // stride2
    shifts = [(dy * stride2, dx * stride2)
              for dy in range(-d, d + 1) for dx in range(-d, d + 1)]

    def _corr(a, b):
        C = a.shape[1]
        outs = []
        for dy, dx in shifts:
            shifted = jnp.roll(b, (dy, dx), axis=(2, 3))
            # zero out wrapped rows/cols (roll is circular; the op pads)
            H, W = a.shape[2], a.shape[3]
            ymask = (jnp.arange(H) >= dy) & (jnp.arange(H) < H + dy)
            xmask = (jnp.arange(W) >= dx) & (jnp.arange(W) < W + dx)
            m = ymask[:, None] & xmask[None, :]
            outs.append(jnp.sum(a * shifted * m[None, None], axis=1) / C)
        out = jnp.stack(outs, axis=1)
        if stride1 > 1:
            out = out[:, :, ::stride1, ::stride1]
        return out

    return call_op(_corr, x1, x2, op_name="correlation")


def sequence_topk_avg_pooling(x, lengths, topks, channel_num=1):
    """Top-k average pooling over the sequence axis (operators/
    sequence_topk_avg_pooling_op.cc, the pyramid text-match pooling).
    Padded form: x (B, C, L) scores with per-sample `lengths`; for each
    k in `topks`, the mean of the top-k in-length scores. Returns
    (B, C, len(topks))."""
    import jax.numpy as jnp

    topks = list(topks)
    kmax = max(topks)

    def _tap(xv, lens):
        L = xv.shape[-1]
        mask = jnp.arange(L)[None, None, :] < lens[:, None, None]
        neg = jnp.asarray(-3.4e38, xv.dtype)
        vals = jnp.where(mask, xv, neg)
        import jax
        top = jax.lax.top_k(vals, kmax)[0]
        outs = []
        for k in topks:
            valid = jnp.minimum(lens, k)[:, None].astype(xv.dtype)
            picked = jnp.where(jnp.arange(kmax)[None, None, :] < valid[
                :, :, None], top, 0.0)
            outs.append(jnp.sum(picked, axis=-1)
                        / jnp.maximum(valid, 1.0))
        return jnp.stack(outs, axis=-1)

    return call_op(_tap, x, lengths, op_name="sequence_topk_avg_pooling")


def positive_negative_pair(score, label, query_id):
    """Ranking-pair metric (operators/positive_negative_pair_op.cc):
    within each query, count ordered pairs where the higher-labeled item
    out-scores the lower one (pos), the reverse (neg), and ties (neu).
    Returns (positive, negative, neutral) float32 scalars."""
    import jax.numpy as jnp

    s = np.asarray(unwrap(score), np.float64).ravel()
    l = np.asarray(unwrap(label), np.float64).ravel()
    q = np.asarray(unwrap(query_id)).ravel()
    pos = neg = neu = 0.0
    for qid in np.unique(q):
        idx = np.nonzero(q == qid)[0]
        for a in range(idx.size):
            for b in range(a + 1, idx.size):
                i, j = idx[a], idx[b]
                if l[i] == l[j]:
                    continue
                hi, lo = (i, j) if l[i] > l[j] else (j, i)
                if s[hi] > s[lo]:
                    pos += 1
                elif s[hi] < s[lo]:
                    neg += 1
                else:
                    neu += 1
    return (wrap(jnp.asarray(pos, jnp.float32)),
            wrap(jnp.asarray(neg, jnp.float32)),
            wrap(jnp.asarray(neu, jnp.float32)))


def similarity_focus(x, axis, indexes):
    """Similarity-focus attention mask (operators/similarity_focus_op.h,
    the text-matching focus layer): for each selected slice along `axis`,
    greedily pick maxima whose two free coordinates are both unused, and
    set the mask 1 across the whole `axis` fiber at those coordinates
    (a greedy bipartite matching over the slice). Host numpy, like the
    reference's CPU-only kernel. x: 4-D (N, d1, d2, d3); axis in 1..3."""
    import jax.numpy as jnp

    xv = np.asarray(unwrap(x), np.float32)
    if xv.ndim != 4:
        raise ValueError("similarity_focus expects a 4-D input")
    if axis not in (1, 2, 3):
        raise ValueError("axis must be 1, 2 or 3")
    if not indexes:
        raise ValueError("indexes must be non-empty")
    if min(indexes) < 0 or max(indexes) >= xv.shape[axis]:
        raise ValueError(
            f"indexes {list(indexes)} out of range for axis {axis} "
            f"(size {xv.shape[axis]}; negatives rejected like the "
            f"reference op)")
    free = [a for a in (1, 2, 3) if a != axis]
    out = np.zeros_like(xv)
    for b in range(xv.shape[0]):
        for index in indexes:
            sl = np.take(xv[b], index, axis=axis - 1)  # (dA, dB)
            dA, dB = sl.shape
            order = np.argsort(-sl.ravel(), kind="stable")
            usedA = np.zeros(dA, bool)
            usedB = np.zeros(dB, bool)
            picked = 0
            for flat in order:
                ia, ib = divmod(int(flat), dB)
                if usedA[ia] or usedB[ib]:
                    continue
                usedA[ia] = usedB[ib] = True
                sel = [b, None, None, None]
                sel[free[0]] = ia
                sel[free[1]] = ib
                sel[axis] = slice(None)
                out[tuple(sel)] = 1.0
                picked += 1
                if picked == min(dA, dB):
                    break
    return wrap(jnp.asarray(out))
