"""Tensor-method library breadth: statistics/manipulation tail.

Reference surface: `python/paddle/tensor/` (math.py/stat.py/search.py/
manipulation.py entries not already in ops/math|manipulation) backed by
`paddle/fluid/operators/` kernels. All lowered through the dispatch seam.
"""
import numpy as np
import jax
import jax.numpy as jnp

from ..core.dispatch import call_op, call_op_nograd, unwrap
from ..core.tensor import Tensor

__all__ = [
    "median", "kthvalue", "mode", "quantile", "nanmedian",
    "histogram", "bincount", "unique_consecutive", "diff",
    "trace", "kron", "outer", "cross", "diagonal", "rot90",
    "searchsorted", "bucketize", "take", "lerp", "trunc", "frac",
    "nanmean", "nansum", "deg2rad", "rad2deg", "gcd", "lcm", "heaviside",
    "digamma", "lgamma", "conj", "real", "imag", "mv", "dist", "increment",
    "unbind", "broadcast_tensors", "multiplex", "crop", "squared_l2_norm",
    "cvm", "data_norm", "fsp_matrix", "partial_concat", "partial_sum",
]


def median(x, axis=None, keepdim=False):
    """reference: operators/median (tensor/stat.py median)."""
    return call_op(lambda v: jnp.median(v, axis=axis, keepdims=keepdim),
                   x, op_name="median")


def nanmedian(x, axis=None, keepdim=False):
    return call_op(lambda v: jnp.nanmedian(v, axis=axis, keepdims=keepdim),
                   x, op_name="nanmedian")


def kthvalue(x, k, axis=-1, keepdim=False):
    """reference: operators/kthvalue_op.cc — (values, indices) of the k-th
    smallest along axis (1-based k); one argsort derives both outputs."""
    idx_full = call_op_nograd(lambda v: jnp.argsort(v, axis=axis), x,
                              op_name="kthvalue_argsort")
    kth_idx = call_op_nograd(
        lambda i: (jnp.expand_dims(jnp.take(i, k - 1, axis=axis), axis)
                   if keepdim else jnp.take(i, k - 1, axis=axis)),
        idx_full, op_name="kthvalue_index")

    def _vals(v, i):
        g = i if keepdim else jnp.expand_dims(i, axis)
        out = jnp.take_along_axis(v, g, axis=axis)
        return out if keepdim else jnp.squeeze(out, axis)

    vals = call_op(_vals, x, unwrap(kth_idx), op_name="kthvalue")
    return vals, kth_idx


def mode(x, axis=-1, keepdim=False):
    """reference: operators/mode_op.cc — most frequent value (+index)."""

    def _mode(v):
        sv = jnp.sort(v, axis=axis)
        n = sv.shape[axis]
        same = jnp.concatenate(
            [jnp.ones_like(jnp.take(sv, jnp.array([0]), axis=axis),
                           dtype=jnp.int32),
             (jnp.diff(sv, axis=axis) == 0).astype(jnp.int32)], axis=axis)
        # run lengths via cumulative reset: count consecutive equals
        def scan_fn(carry, s):
            run = jnp.where(s == 1, carry + 1, 1)
            return run, run
        moved = jnp.moveaxis(same, axis, 0)
        _, runs = jax.lax.scan(scan_fn,
                               jnp.zeros(moved.shape[1:], jnp.int32), moved)
        runs = jnp.moveaxis(runs, 0, axis)
        best = jnp.argmax(runs, axis=axis)
        vals = jnp.take_along_axis(sv, jnp.expand_dims(best, axis),
                                   axis=axis)
        return vals if keepdim else jnp.squeeze(vals, axis)

    vals = call_op_nograd(_mode, x, op_name="mode")

    def _idx(v):
        tgt = unwrap(vals) if not keepdim else jnp.squeeze(
            unwrap(vals), axis)
        eq = v == jnp.expand_dims(tgt, axis)
        idx = jnp.argmax(eq, axis=axis)
        return jnp.expand_dims(idx, axis) if keepdim else idx

    return vals, call_op_nograd(_idx, x, op_name="mode_index")


def quantile(x, q, axis=None, keepdim=False):
    return call_op(lambda v: jnp.quantile(
        v, jnp.asarray(q), axis=axis, keepdims=keepdim),
        x, op_name="quantile")


def histogram(x, bins=100, min=0, max=0):  # noqa: A002
    """reference: operators/histogram_op.cc (min==max==0 → data range)."""

    def _h(v):
        lo, hi = (jnp.min(v), jnp.max(v)) if min == 0 and max == 0 \
            else (jnp.asarray(min, v.dtype), jnp.asarray(max, v.dtype))
        counts = jnp.histogram(v.reshape(-1), bins=bins, range=(lo, hi))[0]
        return counts.astype(jnp.int64)  # reference returns int64 counts

    return call_op_nograd(_h, x, op_name="histogram")


def bincount(x, weights=None, minlength=0):
    """reference: operators/bincount_op.cc."""
    n = int(np.asarray(unwrap(x)).max()) + 1 if np.asarray(
        unwrap(x)).size else 0
    length = max(n, int(minlength))

    def _b(v, *rest):
        w = rest[0] if weights is not None else None
        return jnp.bincount(v.reshape(-1), weights=w, length=length)

    args = (x,) + ((weights,) if weights is not None else ())
    return call_op_nograd(_b, *args, op_name="bincount")


def unique_consecutive(x, return_inverse=False, return_counts=False,
                       axis=None):
    """reference: operators/unique_consecutive_op.cc. Host-side: output
    length is data-dependent. With `axis`, consecutive SLICES along that
    axis dedupe (reference semantics)."""
    v = np.asarray(unwrap(x))
    moved = False
    if axis is None:
        v = v.reshape(-1)
    else:
        v = np.moveaxis(v, axis, 0)
        moved = True
    if v.size == 0:
        keep = np.zeros(0, bool)
    elif v.ndim == 1:
        keep = np.concatenate([[True], v[1:] != v[:-1]])
    else:
        diff = np.any(v[1:] != v[:-1], axis=tuple(range(1, v.ndim)))
        keep = np.concatenate([[True], diff])
    kept = v[keep]
    if moved:
        kept = np.moveaxis(kept, 0, axis)
    out = Tensor(jnp.asarray(kept))
    res = (out,)
    if return_inverse:
        inv = np.cumsum(keep) - 1
        res += (Tensor(jnp.asarray(inv.astype(np.int64))),)
    if return_counts:
        idx = np.flatnonzero(keep)
        counts = np.diff(np.append(idx, len(keep)))
        res += (Tensor(jnp.asarray(counts.astype(np.int64))),)
    return res if len(res) > 1 else out


def diff(x, n=1, axis=-1):
    return call_op(lambda v: jnp.diff(v, n=n, axis=axis), x, op_name="diff")


def trace(x, offset=0, axis1=0, axis2=1):
    return call_op(lambda v: jnp.trace(v, offset=offset, axis1=axis1,
                                       axis2=axis2), x, op_name="trace")


def kron(x, y):
    return call_op(jnp.kron, x, y, op_name="kron")


def outer(x, y):
    return call_op(lambda a, b: jnp.outer(a, b), x, y, op_name="outer")


def cross(x, y, axis=None):
    """reference: operators/cross_op.cc — default axis is the FIRST axis
    of length 3 (not the last)."""
    if axis is None:
        shape = list(np.shape(unwrap(x)))
        try:
            ax = shape.index(3)
        except ValueError:
            raise ValueError(
                f"cross with axis=None needs a dimension of size 3; "
                f"got shape {shape}")
    else:
        ax = axis
    return call_op(lambda a, b: jnp.cross(a, b, axis=ax), x, y,
                   op_name="cross")


def diagonal(x, offset=0, axis1=0, axis2=1):
    return call_op(lambda v: jnp.diagonal(v, offset=offset, axis1=axis1,
                                          axis2=axis2), x, op_name="diagonal")


def rot90(x, k=1, axes=(0, 1)):
    return call_op(lambda v: jnp.rot90(v, k=k, axes=tuple(axes)), x,
                   op_name="rot90")


def searchsorted(sorted_sequence, values, out_int32=False, right=False):
    """reference: operators/searchsorted_op.cc."""

    def _s(seq, v):
        out = jnp.searchsorted(seq, v, side="right" if right else "left")
        return out.astype(jnp.int32 if out_int32 else jnp.int64)

    return call_op_nograd(_s, sorted_sequence, values,
                          op_name="searchsorted")


def bucketize(x, sorted_sequence, out_int32=False, right=False):
    return searchsorted(sorted_sequence, x, out_int32=out_int32, right=right)


def take(x, index, mode="raise"):
    """reference: tensor/math.py take — flat-index gather with wrap/clip."""

    def _t(v, idx):
        flat = v.reshape(-1)
        n = flat.shape[0]
        if mode == "wrap":
            idx2 = jnp.mod(idx, n)
        else:  # raise-mode bounds checking is not expressible in XLA; clip
            idx2 = jnp.clip(idx, -n, n - 1)
        return flat[idx2.reshape(-1)].reshape(idx.shape)

    return call_op(_t, x, unwrap(index), op_name="take")


def lerp(x, y, weight):
    return call_op(lambda a, b, w: a + w * (b - a), x, y,
                   weight if isinstance(weight, Tensor) else
                   jnp.asarray(weight), op_name="lerp")


def trunc(x):
    return call_op(jnp.trunc, x, op_name="trunc")


def frac(x):
    return call_op(lambda v: v - jnp.trunc(v), x, op_name="frac")


def nanmean(x, axis=None, keepdim=False):
    return call_op(lambda v: jnp.nanmean(v, axis=axis, keepdims=keepdim),
                   x, op_name="nanmean")


def nansum(x, axis=None, keepdim=False):
    return call_op(lambda v: jnp.nansum(v, axis=axis, keepdims=keepdim),
                   x, op_name="nansum")


def deg2rad(x):
    return call_op(jnp.deg2rad, x, op_name="deg2rad")


def rad2deg(x):
    return call_op(jnp.rad2deg, x, op_name="rad2deg")


def gcd(x, y):
    return call_op_nograd(jnp.gcd, x, y, op_name="gcd")


def lcm(x, y):
    return call_op_nograd(jnp.lcm, x, y, op_name="lcm")


def heaviside(x, y):
    return call_op(jnp.heaviside, x, y, op_name="heaviside")


# ------------------------------------------------------- math tail (round 2)

def digamma(x):
    """reference: operators/digamma_op.cc."""
    return call_op(lambda v: jax.scipy.special.digamma(v), x,
                   op_name="digamma")


def lgamma(x):
    """reference: operators/lgamma_op.cc."""
    return call_op(lambda v: jax.scipy.special.gammaln(v), x,
                   op_name="lgamma")


def conj(x):
    """reference: operators/conj_op.cc (has conj_grad kernel)."""
    return call_op(lambda v: jnp.conj(v), x, op_name="conj")


def real(x):
    """reference: operators/real_op.cc (has real_grad kernel)."""
    return call_op(lambda v: jnp.real(v), x, op_name="real")


def imag(x):
    """reference: operators/imag_op.cc (has imag_grad kernel)."""
    return call_op(lambda v: jnp.imag(v), x, op_name="imag")


def mv(x, vec):
    """Matrix-vector product (reference: operators/mv_op.cc)."""
    return call_op(lambda m, v: jnp.matmul(m, v), x, vec, op_name="mv")


def dist(x, y, p=2):
    """p-norm of (x - y) (reference: operators/dist_op.cc)."""
    pv = float(p)

    def _dist(a, b):
        d = jnp.abs(a - b)
        if pv == float("inf"):
            return jnp.max(d)
        if pv == float("-inf"):
            return jnp.min(d)
        if pv == 0:
            return jnp.sum((d != 0).astype(a.dtype))
        return jnp.power(jnp.sum(jnp.power(d, pv)), 1.0 / pv)

    return call_op(_dist, x, y, op_name="dist")


def increment(x, value=1.0):
    """reference: operators/increment_op.cc (fluid in-place counter; 2.x
    returns the incremented tensor)."""
    return call_op(lambda v: v + jnp.asarray(value, v.dtype), x,
                   op_name="increment")


def unbind(x, axis=0):
    """Split along axis removing it (reference: operators/unbind_op.cc)."""
    n = jnp.shape(unwrap(x))[axis]

    def _unbind(v):
        return tuple(jnp.squeeze(p, axis=axis)
                     for p in jnp.split(v, n, axis=axis))

    out = call_op(_unbind, x, op_name="unbind")
    return list(out) if isinstance(out, tuple) else [out]


def broadcast_tensors(inputs):
    """reference: operators/broadcast_tensors_op.cc."""
    shapes = [tuple(jnp.shape(unwrap(t))) for t in inputs]
    out_shape = np.broadcast_shapes(*shapes)

    def _bt(*vals):
        return tuple(jnp.broadcast_to(v, out_shape) for v in vals)

    out = call_op(_bt, *inputs, op_name="broadcast_tensors")
    return list(out) if isinstance(out, tuple) else [out]


def multiplex(inputs, index):
    """Row-wise select among candidate tensors: out[i] = inputs[index[i]][i]
    (reference: operators/multiplex_op.cc)."""
    idx = unwrap(index)

    def _mp(*vals):
        stacked = jnp.stack(vals, axis=0)  # [n, batch, ...]
        sel = jnp.reshape(idx, (-1,)).astype(jnp.int32)
        rows = jnp.arange(stacked.shape[1])
        return stacked[sel, rows]

    return call_op(_mp, *inputs, op_name="multiplex")


def crop(x, shape=None, offsets=None):
    """Static slice by offsets/shape (reference: operators/crop_tensor_op.cc).
    -1 in `shape` keeps the remainder of that axis; None offsets = zeros."""
    v = unwrap(x)
    in_shape = tuple(v.shape)
    if shape is None:
        shape = list(in_shape)
    shape = [int(s) for s in (shape.numpy() if hasattr(shape, "numpy")
                              else shape)]
    if offsets is None:
        offsets = [0] * len(in_shape)
    offsets = [int(o) for o in (offsets.numpy() if hasattr(offsets, "numpy")
                                else offsets)]
    shape = [in_shape[i] - offsets[i] if s == -1 else s
             for i, s in enumerate(shape)]
    idx = tuple(slice(o, o + s) for o, s in zip(offsets, shape))
    return call_op(lambda val: val[idx], x, op_name="crop")


def squared_l2_norm(x):
    """reference: operators/squared_l2_norm_op.cc (grad-clip helper)."""
    return call_op(lambda v: jnp.sum(jnp.square(v)), x,
                   op_name="squared_l2_norm")


def cvm(input, cvm_input=None, use_cvm=True):  # noqa: A002
    """Continuous-value-model feature transform (reference:
    operators/cvm_op.h): with use_cvm the first two columns (show, click)
    become log(show+1), log(click+1)-log(show+1); otherwise they are
    dropped."""

    def _cvm(v):
        if use_cvm:
            c0 = jnp.log(v[:, 0:1] + 1.0)
            c1 = jnp.log(v[:, 1:2] + 1.0) - c0
            return jnp.concatenate([c0, c1, v[:, 2:]], axis=1)
        return v[:, 2:]

    return call_op(_cvm, input, op_name="cvm")


def data_norm(input, batch_size, batch_sum, batch_square_sum,  # noqa: A002
              epsilon=1e-4, do_model_average_for_mean_and_var=True,
              update_stats=True, summary_decay_rate=0.9999999):
    """CTR data normalization (reference: operators/data_norm_op.cc):
    y = (x - mean) * scale with mean = batch_sum/batch_size and
    scale = sqrt(batch_size / batch_square_sum), per feature. The three
    summary tensors are framework state (the reference's persistable
    parameters); update_stats accumulates the current minibatch into them
    the way the reference's in-kernel SGD summary update does."""
    def _dn(v, bs, bsum, bsq):
        mean = bsum / bs
        scale = jnp.sqrt(bs / (bsq + epsilon))
        return (v - mean) * scale

    out = call_op(_dn, input, batch_size, batch_sum, batch_square_sum,
                  op_name="data_norm")
    if update_stats:
        v = unwrap(input)
        n = v.shape[0]
        dr = summary_decay_rate
        batch_size.set_value(unwrap(batch_size) * dr + n)
        batch_sum.set_value(unwrap(batch_sum) * dr + v.sum(axis=0))
        batch_square_sum.set_value(
            unwrap(batch_square_sum) * dr + (v ** 2).sum(axis=0))
    return out


def fsp_matrix(x, y):
    """Flow-of-solution-procedure matrix for distillation (reference:
    operators/fsp_op.h): out[n, i, j] = (1/HW) sum_hw x[n,i,h,w]*y[n,j,h,w]."""

    def _fsp(a, b):
        n, c1, h, w = a.shape
        return jnp.einsum("nihw,njhw->nij", a, b) / (h * w)

    return call_op(_fsp, x, y, op_name="fsp_matrix")


def partial_concat(xs, start_index=0, length=-1):
    """Concat a column slice of each input (reference:
    operators/partial_concat_op.cc): take [start, start+length) of axis 1
    from every [N, D] input and concatenate."""
    def _pc(*vals):
        outs = []
        for v in vals:
            st = start_index + v.shape[1] if start_index < 0 else start_index
            end = v.shape[1] if length < 0 else st + length
            outs.append(v[:, st:end])
        return jnp.concatenate(outs, axis=1)
    return call_op(_pc, *xs, op_name="partial_concat")


def partial_sum(xs, start_index=0, length=-1):
    """Sum a column slice of each input (reference:
    operators/partial_sum_op.cc)."""
    def _ps(*vals):
        acc = None
        for v in vals:
            st = start_index + v.shape[1] if start_index < 0 else start_index
            end = v.shape[1] if length < 0 else st + length
            sl = v[:, st:end]
            acc = sl if acc is None else acc + sl
        return acc
    return call_op(_ps, *xs, op_name="partial_sum")
