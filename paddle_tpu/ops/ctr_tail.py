"""CTR / text-matching / tree op tail (reference: the pslib-era contrib set
`python/paddle/fluid/contrib/layers/nn.py` — shuffle_batch:785,
filter_by_instag, search_pyramid_hash:669, rank_attention:1321,
tree_conv:402, var_conv_2d:129, with kernels in
`operators/{shuffle_batch,filter_by_instag,pyramid_hash,rank_attention,
tree_conv,var_conv_2d}_op.*`).

TPU notes: rank_attention / var_conv_2d / shuffle_batch are fully traced
jnp (differentiable, jit-able). filter_by_instag and the tree/patch
construction of tree_conv are HOST ops — their output structure depends on
data values (dynamic row counts, tree shapes), exactly the part the
reference runs on CPU over LoD; the differentiable math (gather + einsum)
stays on device.
"""
import numpy as np

import jax
import jax.numpy as jnp

from ..core.dispatch import call_op, unwrap, wrap
from ..core.tensor import Tensor

__all__ = ["shuffle_batch", "filter_by_instag", "search_pyramid_hash",
           "rank_attention", "tree_conv", "var_conv_2d",
           "bilateral_slice"]


def shuffle_batch(x, seed=None, startup_seed=0):
    """Random row permutation (reference: shuffle_batch_op.cc; returns the
    shuffled tensor like the python front-end, ShuffleIdx retrievable via
    return_index)."""
    from ..core import random as core_random

    n = x.shape[0]
    if seed is not None:
        key = jax.random.PRNGKey(int(unwrap(seed) if isinstance(seed, Tensor)
                                     else seed))
    else:
        key = core_random.next_key()
    perm = jax.random.permutation(key, n)

    def _sh(v):
        return v[perm]

    return call_op(_sh, x, op_name="shuffle_batch")


def filter_by_instag(ins, ins_tag, filter_tag, is_lod=True,
                     out_val_if_empty=0):
    """Keep rows of `ins` whose tag set intersects `filter_tag`
    (reference: filter_by_instag_op.cc). HOST op: the output row count is
    data-dependent. `ins_tag`: list-of-lists (ragged per-row tags) or a
    padded [N, T] array (0 = padding). Returns (out, loss_weight,
    index_map) exactly like the reference outputs Out/LossWeight/IndexMap."""
    ins_np = np.asarray(unwrap(ins))
    ftags = set(int(t) for t in np.asarray(unwrap(filter_tag)).ravel())
    if isinstance(ins_tag, Tensor) or isinstance(ins_tag, np.ndarray):
        tag_np = np.asarray(unwrap(ins_tag))
        rows_tags = [set(int(t) for t in row if int(t) != 0)
                     for row in tag_np]
    else:
        rows_tags = [set(int(t) for t in row) for row in ins_tag]
    keep = [i for i, tags in enumerate(rows_tags) if tags & ftags]
    if keep:
        out = ins_np[keep]
        loss_weight = np.ones((len(keep), 1), np.float32)
        index_map = np.asarray([[i, i] for i in keep], np.int64)
    else:
        # reference: emit one zero row so downstream shapes stay valid
        out = np.full((1,) + ins_np.shape[1:], out_val_if_empty,
                      ins_np.dtype)
        loss_weight = np.zeros((1, 1), np.float32)
        index_map = np.zeros((1, 2), np.int64)
    return wrap(jnp.asarray(out)), wrap(jnp.asarray(loss_weight)), \
        wrap(jnp.asarray(index_map))


def _hash64(a, b):
    """Deterministic splitmix64-style mix (the reference hashes n-grams
    with xxhash — the family differs, the pyramid semantics don't)."""
    x = (np.uint64(a) * np.uint64(0x9E3779B97F4A7C15)
         + np.uint64(b) * np.uint64(0xBF58476D1CE4E5B9))
    x ^= x >> np.uint64(30)
    x = x * np.uint64(0x94D049BB133111EB) & np.uint64(0xFFFFFFFFFFFFFFFF)
    return x ^ (x >> np.uint64(31))


def search_pyramid_hash(input, weight, num_emb, space_len, pyramid_layer=2,  # noqa: A002
                        rand_len=16, drop_out_percent=0.0, is_training=False,
                        seed=0):
    """PyramidHash text embedding (reference: pyramid_hash_op.cc /
    search_pyramid_hash:669): every n-gram of window size 2..pyramid_layer
    is hashed `num_emb // rand_len` times into the [space_len, rand_len]
    table; the concatenated pieces form the n-gram embedding and a
    sequence's embedding is their sum.

    input: int32 [B, T] padded token ids (0 = pad). Returns [B, num_emb].
    """
    assert num_emb % rand_len == 0, "num_emb must divide by rand_len"
    ids = np.asarray(unwrap(input)).astype(np.int64)
    B, T = ids.shape
    pieces = num_emb // rand_len
    # HOST: n-gram hashing (integer mixing over data values); the gather +
    # sum below stay on device and are differentiable wrt the table
    idx_rows = []  # per example: list of [pieces] table rows per ngram
    for b in range(B):
        toks = [t for t in ids[b] if t != 0]
        rows = []
        for w in range(2, pyramid_layer + 1):
            for s in range(0, max(0, len(toks) - w + 1)):
                gram = toks[s:s + w]
                sig = np.uint64(seed)
                for t in gram:
                    sig = _hash64(sig, np.uint64(t))
                rows.append([int(_hash64(sig, np.uint64(j))
                                 % np.uint64(space_len))
                             for j in range(pieces)])
        idx_rows.append(rows)
    max_g = max(1, max(len(r) for r in idx_rows))
    idx = np.zeros((B, max_g, pieces), np.int32)
    mask = np.zeros((B, max_g, 1, 1), np.float32)
    for b, rows in enumerate(idx_rows):
        for g, r in enumerate(rows):
            idx[b, g] = r
            mask[b, g] = 1.0

    def _emb(w):
        # [B, G, pieces, rand_len] -> sum over grams, concat pieces
        g = w[idx] * jnp.asarray(mask)
        summed = jnp.sum(g, axis=1)  # [B, pieces, rand_len]
        return summed.reshape(B, num_emb)

    out = call_op(_emb, weight, op_name="pyramid_hash")
    if is_training and drop_out_percent > 0:
        from ..nn import functional as F
        out = F.dropout(out, p=drop_out_percent, training=True)
    return out


def rank_attention(input, rank_offset, rank_param, max_rank=3, max_size=0):  # noqa: A002
    """Rank attention (reference: rank_attention.cu.h expand kernels):
    rank_offset [N, 1+2K] int32 — col 0 is the instance's own rank
    (1-based, 0 invalid); cols (2k+1, 2k+2) are the k-th related
    instance's rank and its row in `input`. For every instance the K
    related feature rows multiply the param block selected by
    (own_rank, related_rank): out[i] = sum_k X[index_k] @ P[(own-1)*K +
    (rank_k - 1)], with P viewed as [K*K, d, out]."""
    d = input.shape[1]
    out_col = rank_param.shape[1]
    K = max_rank

    def _ra(x, ro, p):
        ro = ro.astype(jnp.int32)
        own = ro[:, 0] - 1                       # [N]
        rel_rank = ro[:, 1::2] - 1               # [N, K]
        rel_idx = ro[:, 2::2]                    # [N, K]
        valid = (own[:, None] >= 0) & (rel_rank >= 0)
        gathered = x[jnp.clip(rel_idx, 0, x.shape[0] - 1)]  # [N, K, d]
        gathered = jnp.where(valid[..., None], gathered, 0.0)
        pb = p.reshape(K * K, d, out_col)
        block = jnp.clip(own[:, None] * K + rel_rank, 0, K * K - 1)
        pg = pb[block]                           # [N, K, d, out]
        pg = jnp.where(valid[..., None, None], pg, 0.0)
        return jnp.einsum("nkd,nkdo->no", gathered, pg)

    return call_op(_ra, input, rank_offset, rank_param,
                   op_name="rank_attention")


def _tree_patches(edges, n_nodes, max_depth):
    """construct_tree + construct_patch (reference: math/tree2col.cc) —
    DFS patches with (eta_t, eta_l, eta_r) continuous-binary-tree
    coefficients. Host structure work; returns (patch_idx [N, P],
    coef [N, P, 3], pmask [N, P])."""
    tr = [[] for _ in range(n_nodes + 2)]
    for u, v in edges:
        if u != 0 and v != 0:
            tr[int(u)].append(int(v))
        else:
            break

    def eta(index, pclen, depth):
        et = (max_depth - depth) / max_depth
        el = (1.0 - et) * (0.5 if pclen == 1
                           else (index - 1.0) / (pclen - 1.0))
        er = (1.0 - et) * (1.0 - (0.5 if pclen == 1 else
                                  (index - 1.0) / (pclen - 1.0)))
        return et, el, er

    patches = []
    for root in range(1, n_nodes + 1):
        patch = [(root, 1, 1, 0)]
        stack = [(root, 1, 1, 0)]
        visited = {root}
        while stack:
            node, _, _, depth = stack[-1]
            end = True
            sz = len(tr[node])
            for i, v in enumerate(tr[node]):
                if v not in visited and depth + 1 < max_depth:
                    visited.add(v)
                    stack.append((v, i, sz, depth + 1))
                    patch.append((v, i + 1, sz, depth + 1))
                    end = False
            if end:
                stack.pop()
        patches.append(patch)
    P = max(len(p) for p in patches)
    idx = np.zeros((n_nodes, P), np.int32)
    coef = np.zeros((n_nodes, P, 3), np.float32)
    pm = np.zeros((n_nodes, P, 1), np.float32)
    for r, patch in enumerate(patches):
        for j, (node, index, pclen, depth) in enumerate(patch):
            idx[r, j] = node - 1
            coef[r, j] = eta(index, pclen, depth)
            pm[r, j] = 1.0
    return idx, coef, pm


def tree_conv(nodes_vector, edge_set, filter, max_depth=2):  # noqa: A002
    """Tree-based convolution (TBCNN, reference: tree_conv_op.cc +
    math/tree2col.*): nodes_vector [B, N, C], edge_set [B, E, 2] int32
    (1-based node ids, 0-padded), filter [C, 3, output_size, num_filters]
    -> [B, N, output_size, num_filters]."""
    edges_np = np.asarray(unwrap(edge_set)).astype(np.int64)
    B, N, C = nodes_vector.shape
    idxs, coefs, masks = [], [], []
    for b in range(B):
        i, c, m = _tree_patches(edges_np[b], N, max_depth)
        idxs.append(i)
        coefs.append(c)
        masks.append(m)
    P = max(i.shape[1] for i in idxs)
    idx = np.zeros((B, N, P), np.int32)
    coef = np.zeros((B, N, P, 3), np.float32)
    pm = np.zeros((B, N, P, 1), np.float32)
    for b in range(B):
        p = idxs[b].shape[1]
        idx[b, :, :p] = idxs[b]
        coef[b, :, :p] = coefs[b]
        pm[b, :, :p] = masks[b]

    def _tc(nodes, w):
        gath = jnp.take_along_axis(
            nodes[:, :, None, :], jnp.asarray(idx)[..., None], axis=1)
        gath = gath * jnp.asarray(pm)            # [B, N, P, C]
        c3 = jnp.asarray(coef)                   # [B, N, P, 3]
        # out[b,n,o,f] = sum_{p,c,e} gath[b,n,p,c] c3[b,n,p,e] w[c,e,o,f]
        return jnp.einsum("bnpc,bnpe,ceof->bnof", gath, c3, w)

    return call_op(_tc, nodes_vector, filter, op_name="tree_conv")


def var_conv_2d(x, rows, cols, filter, input_channel=1, output_channel=1,  # noqa: A002
                stride=(1, 1), kernel_size=(3, 3)):
    """Variable-size 2D convolution (reference: var_conv_2d_op.cc — conv
    over per-sample (row, col) sized images carried in LoD). Padded
    TPU design: x [B, Cin, Hmax, Wmax] with per-sample valid extents
    `rows`/`cols` [B]; invalid area is masked to zero before AND after the
    conv so padding never leaks into valid outputs."""
    from ..nn import functional as F

    rows_np = np.asarray(unwrap(rows)).astype(np.int32)
    cols_np = np.asarray(unwrap(cols)).astype(np.int32)
    B, Cin, H, W = x.shape
    rmask = (np.arange(H)[None, :] < rows_np[:, None])
    cmask = (np.arange(W)[None, :] < cols_np[:, None])
    mask = (rmask[:, None, :, None] & cmask[:, None, None, :])

    def _mask_in(v):
        return jnp.where(jnp.asarray(mask), v, 0.0)

    xm = call_op(_mask_in, x, op_name="var_conv_mask")
    out = F.conv2d(xm, filter, stride=stride,
                   padding=(kernel_size[0] // 2, kernel_size[1] // 2))
    oh = out.shape[2]
    ow = out.shape[3]
    orows = np.minimum((rows_np + stride[0] - 1) // stride[0], oh)
    ocols = np.minimum((cols_np + stride[1] - 1) // stride[1], ow)
    ormask = (np.arange(oh)[None, :] < orows[:, None])
    ocmask = (np.arange(ow)[None, :] < ocols[:, None])
    omask = (ormask[:, None, :, None] & ocmask[:, None, None, :])

    def _mask_out(v):
        return jnp.where(jnp.asarray(omask), v, 0.0)

    return call_op(_mask_out, out, op_name="var_conv_mask_out")


def bilateral_slice(x, guide, grid, has_offset=False):
    """HDRnet bilateral-grid slice-and-apply (reference:
    bilateral_slice_op.cu BilateralSliceCudaForwardKernel): per pixel,
    trilinearly sample affine coefficients from `grid` at
    (gx, gy, guide-value) and apply them to the input channels.

    x [N, Cin, H, W]; guide [N, H, W] in [0,1];
    grid [N, Cg, gd, gh, gw] with Cg = Cout*Cin (+Cout when has_offset).
    Returns [N, Cout, H, W]. Fully traced jnp (differentiable in x,
    guide, grid).
    """
    N, Cin, H, W = x.shape
    Cg = grid.shape[1]
    stride = Cin + (1 if has_offset else 0)
    if Cg % stride:
        raise ValueError(
            f"grid channels {Cg} must be a multiple of Cin+offset "
            f"({stride}); check has_offset against how the grid was built")
    Cout = Cg // stride

    def _bs(xv, gv, grv):
        gd, gh, gw = grv.shape[2], grv.shape[3], grv.shape[4]
        xs = (jnp.arange(W, dtype=jnp.float32) + 0.5) * gw / W
        ys = (jnp.arange(H, dtype=jnp.float32) + 0.5) * gh / H
        gx = jnp.broadcast_to(xs[None, None, :], (N, H, W))
        gy = jnp.broadcast_to(ys[None, :, None], (N, H, W))
        gz = gv.astype(jnp.float32) * gd

        def tri(coords, size):
            f = jnp.floor(coords - 0.5).astype(jnp.int32)
            idx0 = jnp.clip(f, 0, size - 1)
            idx1 = jnp.clip(f + 1, 0, size - 1)
            w1 = jnp.maximum(1.0 - jnp.abs(f + 0.5 - coords), 0.0)
            w2 = jnp.maximum(1.0 - jnp.abs(f + 1.5 - coords), 0.0)
            return (idx0, w1), (idx1, w2)

        corners_x = tri(gx, gw)
        corners_y = tri(gy, gh)
        corners_z = tri(gz, gd)
        bidx = jnp.arange(N)[:, None, None]
        coeff = 0.0
        for ix, wx in corners_x:
            for iy, wy in corners_y:
                for iz, wz in corners_z:
                    # [N, Cg, H, W] gather of the grid cell per pixel
                    cell = grv[bidx, :, iz, iy, ix]          # [N,H,W,Cg]
                    coeff = coeff + cell * (wx * wy * wz)[..., None]
        coeff = jnp.moveaxis(coeff, -1, 1)                   # [N,Cg,H,W]
        co = coeff.reshape(N, Cout, stride, H, W)
        out = jnp.einsum("noshw,nshw->nohw", co[:, :, :Cin], xv)
        if has_offset:
            out = out + co[:, :, Cin]
        return out

    return call_op(_bs, x, guide, grid, op_name="bilateral_slice")
