"""Op library + Tensor method patching.

The analog of the reference's `python/paddle/tensor/` method library plus
`varbase_patch_methods`: math/manipulation/random ops are defined as module
functions and attached to Tensor here, so `x.sum()`, `x + y`, `x[idx]` all
route through the same autograd dispatch.
"""
from . import manipulation, math, random  # noqa: F401
from .math import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .extras import *  # noqa: F401,F403
from .ctr_tail import *  # noqa: F401,F403  (pslib/CTR-serving op tail)
from .tdm import tdm_child, tdm_sampler  # noqa: F401  (tree-index retrieval)
from .misc_tail import *  # noqa: F401,F403  (round-4 residual op tail)
from .random import (rand, randn, randint, randperm, normal, uniform,  # noqa: F401
                     bernoulli, multinomial, truncated_normal)
from . import sequence  # noqa: F401

from ..core.tensor import Tensor


def _patch_tensor():
    T = Tensor

    # arithmetic dunders
    T.__add__ = lambda self, o: math.add(self, o)
    T.__radd__ = lambda self, o: math.add(o, self)
    T.__sub__ = lambda self, o: math.subtract(self, o)
    T.__rsub__ = lambda self, o: math.subtract(o, self)
    T.__mul__ = lambda self, o: math.multiply(self, o)
    T.__rmul__ = lambda self, o: math.multiply(o, self)
    T.__truediv__ = lambda self, o: math.divide(self, o)
    T.__rtruediv__ = lambda self, o: math.divide(o, self)
    T.__floordiv__ = lambda self, o: math.floor_divide(self, o)
    T.__mod__ = lambda self, o: math.mod(self, o)
    T.__pow__ = lambda self, o: math.pow(self, o)
    T.__rpow__ = lambda self, o: math.pow(o, self)
    T.__neg__ = lambda self: math.neg(self)
    T.__abs__ = lambda self: math.abs(self)
    T.__matmul__ = lambda self, o: math.matmul(self, o)

    # comparisons
    T.__eq__ = lambda self, o: math.equal(self, o)
    T.__ne__ = lambda self, o: math.not_equal(self, o)
    T.__lt__ = lambda self, o: math.less_than(self, o)
    T.__le__ = lambda self, o: math.less_equal(self, o)
    T.__gt__ = lambda self, o: math.greater_than(self, o)
    T.__ge__ = lambda self, o: math.greater_equal(self, o)
    T.__invert__ = lambda self: math.logical_not(self)

    # indexing
    T.__getitem__ = lambda self, idx: manipulation.getitem(self, idx)
    T.__setitem__ = lambda self, idx, v: manipulation.setitem(self, idx, v)

    # methods (paddle Tensor API)
    for name in [
        "exp", "log", "log2", "log10", "log1p", "sqrt", "rsqrt", "square",
        "abs", "sign", "reciprocal", "floor", "ceil", "round", "sin", "cos",
        "tan", "asin", "acos", "atan", "sinh", "cosh", "tanh", "erf", "clip",
        "add", "subtract", "multiply", "divide", "mod", "pow", "maximum",
        "minimum", "sum", "mean", "max", "min", "prod", "std", "var",
        "logsumexp", "all", "any", "argmax", "argmin", "argsort", "sort",
        "topk", "cumsum", "cumprod", "matmul", "dot", "bmm", "mm", "norm",
        "cast", "isnan", "isinf", "isfinite", "allclose", "equal_all",
    ]:
        setattr(T, name, _make_method(getattr(math, name)))

    for name in [
        "reshape", "flatten", "transpose", "squeeze", "unsqueeze", "tile",
        "expand", "expand_as", "broadcast_to", "flip", "roll", "gather",
        "gather_nd", "split", "chunk", "unstack", "slice", "strided_slice",
        "index_select", "masked_select", "masked_fill", "unique", "numel",
        "take_along_axis", "put_along_axis", "repeat_interleave", "moveaxis",
    ]:
        setattr(T, name, _make_method(getattr(manipulation, name)))

    from . import extras
    for name in [
        "median", "kthvalue", "mode", "quantile", "nanmedian", "histogram",
        "bincount", "unique_consecutive", "diff", "trace", "kron", "outer",
        "cross", "diagonal", "rot90", "lerp", "trunc", "frac", "nanmean",
        "nansum", "deg2rad", "rad2deg", "gcd", "lcm", "heaviside",
        "digamma", "lgamma", "conj", "real", "imag", "mv", "dist",
        "increment", "unbind",
    ]:
        setattr(T, name, _make_method(getattr(extras, name)))

    T.astype = lambda self, dtype: math.cast(self, dtype)
    T.t = lambda self: math.t(self)
    T.T = property(lambda self: math.t(self))
    T.item = Tensor.item  # keep original
    T.scale = lambda self, scale=1.0, bias=0.0: math.scale(self, scale, bias)
    T.add_ = _make_inplace(math.add)
    T.subtract_ = _make_inplace(math.subtract)
    T.multiply_ = _make_inplace(math.multiply)
    T.scale_ = _make_inplace(math.scale)
    T.clip_ = _make_inplace(math.clip)
    T.zero_ = lambda self: (self.set_value(
        __import__("jax.numpy", fromlist=["zeros_like"]).zeros_like(self._value)), self)[1]
    T.fill_ = lambda self, v: (self.set_value(
        __import__("jax.numpy", fromlist=["full_like"]).full_like(self._value, v)), self)[1]


def _make_method(fn):
    def method(self, *args, **kwargs):
        return fn(self, *args, **kwargs)
    method.__name__ = fn.__name__
    return method


def _make_inplace(fn):
    def method(self, *args, **kwargs):
        out = fn(self, *args, **kwargs)
        self._value = out._value
        self._tape_node = out._tape_node
        self._tape_index = out._tape_index
        self.stop_gradient = out.stop_gradient
        return self
    method.__name__ = fn.__name__ + "_"
    return method


_patch_tensor()
