"""Shape / indexing / combination ops.

Replaces the reference's reshape/transpose/concat/split/gather/scatter op files
under `paddle/fluid/operators/` with jnp lowerings. All shapes are static under
jit (XLA requirement); dynamic-shape reference ops (LoD) are handled by
padding/bucketing at the io layer instead.
"""
import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import call_op, call_op_nograd, unwrap
from ..core.tensor import Tensor


def _shape_list(shape):
    if isinstance(shape, Tensor):
        return [int(s) for s in shape.numpy()]
    if isinstance(shape, (int, np.integer)):
        return [int(shape)]

    def _dim(s):
        try:
            return int(s)
        except Exception:
            from jax import export as _jax_export
            if _jax_export.is_symbolic_dim(s):
                return s  # jax.export shape polymorphism
            raise TypeError(
                f"invalid dimension {s!r} in reshape target shape")

    return [_dim(s) for s in shape]


def reshape(x, shape):
    return call_op(jnp.reshape, x, tuple(_shape_list(shape)), op_name="reshape")


def flatten(x, start_axis=0, stop_axis=-1):
    def _flatten(v):
        nd = v.ndim
        s = start_axis if start_axis >= 0 else nd + start_axis
        e = stop_axis if stop_axis >= 0 else nd + stop_axis
        new_shape = v.shape[:s] + (-1,) + v.shape[e + 1:]
        return jnp.reshape(v, new_shape)
    return call_op(_flatten, x, op_name="flatten")


def transpose(x, perm=None):
    return call_op(jnp.transpose, x, axes=None if perm is None else tuple(perm),
                   op_name="transpose")


def moveaxis(x, source, destination):
    return call_op(jnp.moveaxis, x, source, destination, op_name="moveaxis")


def swapaxes(x, axis0, axis1):
    return call_op(jnp.swapaxes, x, axis0, axis1, op_name="swapaxes")


def squeeze(x, axis=None):
    def _squeeze(v):
        if axis is None:
            return jnp.squeeze(v)
        axes = axis if isinstance(axis, (list, tuple)) else [axis]
        axes = tuple(a for a in axes if v.shape[a] == 1)
        return jnp.squeeze(v, axis=axes) if axes else v
    return call_op(_squeeze, x, op_name="squeeze")


def unsqueeze(x, axis):
    axes = axis if isinstance(axis, (list, tuple)) else [axis]
    return call_op(jnp.expand_dims, x, axis=tuple(axes), op_name="unsqueeze")


def concat(xs, axis=0):
    axis = int(unwrap(axis)) if isinstance(axis, Tensor) else int(axis)
    return call_op(lambda *vs: jnp.concatenate(vs, axis=axis), *xs,
                   op_name="concat")


def stack(xs, axis=0):
    return call_op(lambda *vs: jnp.stack(vs, axis=axis), *xs, op_name="stack")


def unstack(x, axis=0, num=None):
    n = num if num is not None else jnp.shape(unwrap(x))[axis]
    def _unstack(v):
        return tuple(jnp.squeeze(p, axis=axis)
                     for p in jnp.split(v, n, axis=axis))
    out = call_op(_unstack, x, op_name="unstack")
    return list(out) if isinstance(out, tuple) else [out]


def split(x, num_or_sections, axis=0):
    axis = int(unwrap(axis)) if isinstance(axis, Tensor) else int(axis)

    def _split(v):
        if isinstance(num_or_sections, int):
            return tuple(jnp.split(v, num_or_sections, axis=axis))
        sections = list(num_or_sections)
        total = v.shape[axis]
        if any(s == -1 for s in sections):
            known = sum(s for s in sections if s != -1)
            sections = [total - known if s == -1 else s for s in sections]
        offsets = np.cumsum(sections)[:-1].tolist()
        return tuple(jnp.split(v, offsets, axis=axis))

    out = call_op(_split, x, op_name="split")
    return list(out) if isinstance(out, tuple) else [out]


def chunk(x, chunks, axis=0):
    return split(x, chunks, axis)


def tile(x, repeat_times):
    return call_op(jnp.tile, x, tuple(_shape_list(repeat_times)), op_name="tile")


def expand(x, shape):
    target = _shape_list(shape)

    def _expand(v):
        tgt = list(target)
        # paddle allows -1 meaning "keep this dim"
        off = len(tgt) - v.ndim
        for i, s in enumerate(tgt):
            if s == -1:
                tgt[i] = v.shape[i - off]
        return jnp.broadcast_to(v, tuple(tgt))
    return call_op(_expand, x, op_name="expand")


def expand_as(x, y):
    return call_op(lambda v, w: jnp.broadcast_to(v, w.shape), x, unwrap(y),
                   op_name="expand_as")


def broadcast_to(x, shape):
    return call_op(jnp.broadcast_to, x, tuple(_shape_list(shape)),
                   op_name="broadcast_to")


def flip(x, axis):
    axes = axis if isinstance(axis, (list, tuple)) else [axis]
    return call_op(jnp.flip, x, axis=tuple(axes), op_name="flip")


def roll(x, shifts, axis=None):
    return call_op(jnp.roll, x, shifts, axis=axis, op_name="roll")


def slice(x, axes, starts, ends):  # noqa: A001
    def _slice(v):
        slicer = [jnp.s_[:]] * v.ndim
        for ax, st, en in zip(axes, starts, ends):
            slicer[ax] = jnp.s_[st:en]
        return v[tuple(slicer)]
    return call_op(_slice, x, op_name="slice")


def strided_slice(x, axes, starts, ends, strides):
    def _ss(v):
        slicer = [jnp.s_[:]] * v.ndim
        for ax, st, en, sd in zip(axes, starts, ends, strides):
            slicer[ax] = jnp.s_[st:en:sd]
        return v[tuple(slicer)]
    return call_op(_ss, x, op_name="strided_slice")


def gather(x, index, axis=0):
    return call_op(lambda v, i: jnp.take(v, i, axis=axis), x, unwrap(index),
                   op_name="gather")


def gather_nd(x, index):
    def _gather_nd(v, idx):
        return v[tuple(jnp.moveaxis(idx, -1, 0))]
    return call_op(_gather_nd, x, unwrap(index), op_name="gather_nd")


def take_along_axis(x, indices, axis):
    return call_op(lambda v, i: jnp.take_along_axis(v, i, axis=axis),
                   x, unwrap(indices), op_name="take_along_axis")


def scatter(x, index, updates, overwrite=True):
    def _scatter(v, u, i):
        if overwrite:
            return v.at[i].set(u)
        return v.at[i].add(u)
    return call_op(_scatter, x, updates, unwrap(index), op_name="scatter")


def scatter_nd_add(x, index, updates):
    def _snd(v, u, i):
        return v.at[tuple(jnp.moveaxis(i, -1, 0))].add(u)
    return call_op(_snd, x, updates, unwrap(index), op_name="scatter_nd_add")


def put_along_axis(x, indices, values, axis):
    def _put(v, u, i):
        return jnp.put_along_axis(v, i, u, axis=axis, inplace=False)
    return call_op(_put, x, values, unwrap(indices), op_name="put_along_axis")


def index_select(x, index, axis=0):
    return gather(x, index, axis)


def index_sample(x, index):
    def _is(v, i):
        return jnp.take_along_axis(v, i, axis=1)
    return call_op(_is, x, unwrap(index), op_name="index_sample")


def masked_select(x, mask):
    arr = np.asarray(unwrap(x))
    m = np.asarray(unwrap(mask))
    return Tensor(arr[m])


def masked_fill(x, mask, value):
    return call_op(lambda v, m: jnp.where(m, jnp.asarray(value, v.dtype), v),
                   x, unwrap(mask), op_name="masked_fill")


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW"):  # noqa: A002
    def _pad(v):
        p = list(pad)
        if len(p) == v.ndim * 2:
            width = [(p[2 * i], p[2 * i + 1]) for i in range(v.ndim)]
        else:
            # paddle semantics: pad applies to the last len(pad)//2 dims,
            # given innermost-last ordering (like torch.nn.functional.pad)
            n = len(p) // 2
            width = [(0, 0)] * (v.ndim - n)
            trailing = [(p[2 * i], p[2 * i + 1]) for i in range(n)]
            if data_format in ("NCHW", "NCL", "NCDHW") and len(p) in (2, 4, 6):
                width = [(0, 0)] * (v.ndim - n) + trailing
            else:
                width = [(0, 0)] * (v.ndim - n) + trailing
        jmode = {"constant": "constant", "reflect": "reflect",
                 "replicate": "edge", "circular": "wrap"}[mode]
        if jmode == "constant":
            return jnp.pad(v, width, mode=jmode, constant_values=value)
        return jnp.pad(v, width, mode=jmode)
    return call_op(_pad, x, op_name="pad")


def unique(x, return_index=False, return_inverse=False, return_counts=False, axis=None):
    arr = np.asarray(unwrap(x))
    res = np.unique(arr, return_index=return_index,
                    return_inverse=return_inverse, return_counts=return_counts,
                    axis=axis)
    if isinstance(res, tuple):
        return tuple(Tensor(r) for r in res)
    return Tensor(res)


def assign(x, output=None):
    val = unwrap(x)
    if output is None:
        return call_op(lambda v: v + 0 if jnp.issubdtype(jnp.asarray(v).dtype, jnp.floating) else jnp.asarray(v), x, op_name="assign")
    output.set_value(val)
    return output


def numel(x):
    return Tensor(np.asarray(int(np.prod(jnp.shape(unwrap(x)), dtype=np.int64))))


def shape(x):
    return Tensor(np.asarray(jnp.shape(unwrap(x)), dtype=np.int64))


def meshgrid(*xs):
    out = call_op(lambda *vs: tuple(jnp.meshgrid(*vs, indexing="ij")), *xs,
                  op_name="meshgrid")
    return list(out) if isinstance(out, tuple) else [out]


def repeat_interleave(x, repeats, axis=None):
    return call_op(lambda v: jnp.repeat(v, repeats, axis=axis), x,
                   op_name="repeat_interleave")


def one_hot(x, num_classes):
    return call_op_nograd(
        lambda v: jax.nn.one_hot(v, num_classes, dtype=jnp.float32), x)


def getitem(x, idx):
    """Tensor.__getitem__ with differentiable basic+advanced indexing."""
    def _conv(i):
        if isinstance(i, Tensor):
            return i._value
        if isinstance(i, (list, np.ndarray)):
            return jnp.asarray(i)
        return i

    if isinstance(idx, tuple):
        jidx = tuple(_conv(i) for i in idx)
    else:
        jidx = _conv(idx)
    return call_op(lambda v: v[jidx], x, op_name="getitem")


def setitem(x, idx, value):
    """Functional __setitem__: rebind x's value to the updated array."""
    def _conv(i):
        if isinstance(i, Tensor):
            return i._value
        if isinstance(i, (list, np.ndarray)):
            return jnp.asarray(i)
        return i

    if isinstance(idx, tuple):
        jidx = tuple(_conv(i) for i in idx)
    else:
        jidx = _conv(idx)
    out = call_op(lambda v, u: v.at[jidx].set(u.astype(v.dtype) if hasattr(u, "astype") else u),
                  x, value, op_name="setitem")
    x._value = out._value
    x._tape_node = out._tape_node
    x._tape_index = out._tape_index
    x.stop_gradient = out.stop_gradient
    return x
