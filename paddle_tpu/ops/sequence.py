"""Sequence / ragged ops (reference: `paddle/fluid/operators/sequence_ops/`
+ the LoD machinery `framework/lod_tensor.h:57`).

TPU re-design: LoD (ragged offsets) is a host-side concept; on device
everything is padded + length-masked static shapes, which is what XLA needs.
`RaggedBatch` is the LoDTensor analog: a padded dense tensor + lengths
vector, with host converters both ways. The sequence_* functional ops work
on (data, lengths) pairs.
"""
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import call_op, call_op_nograd, unwrap, wrap
from ..core.tensor import Tensor

__all__ = ["RaggedBatch", "sequence_mask", "sequence_pad", "sequence_unpad",
           "sequence_expand", "sequence_reverse", "sequence_softmax",
           "sequence_pool"]


class RaggedBatch:
    """LoDTensor analog: `data` [B, T, ...] padded, `lengths` [B] int32.

    reference: framework/lod_tensor.h:109 (LoDTensor), :57 (LoD offsets).
    The reference keeps ragged rows contiguous with offset tables; on TPU the
    canonical layout is padded-dense so each batch compiles to one static
    shape (bucket T upstream to bound recompilation).
    """

    def __init__(self, data, lengths):
        self.data = data if isinstance(data, Tensor) else Tensor(data)
        self.lengths = lengths if isinstance(lengths, Tensor) else \
            Tensor(np.asarray(lengths, np.int32))

    @classmethod
    def from_list(cls, rows, pad_value=0.0, maxlen=None):
        """Host ragged rows -> padded batch. (LoD construction analog.)"""
        rows = [np.asarray(r) for r in rows]
        lengths = np.asarray([len(r) for r in rows], np.int32)
        T = maxlen or (int(lengths.max()) if len(rows) else 0)
        tail = rows[0].shape[1:] if rows else ()
        out = np.full((len(rows), T) + tail, pad_value,
                      dtype=rows[0].dtype if rows else np.float32)
        for i, r in enumerate(rows):
            out[i, :len(r)] = r[:T]
        return cls(out, lengths)

    def to_list(self):
        d = np.asarray(unwrap(self.data))
        ls = np.asarray(unwrap(self.lengths))
        return [d[i, :ls[i]] for i in range(len(ls))]

    @property
    def shape(self):
        return self.data.shape


def sequence_mask(x, maxlen=None, dtype="int64"):
    """lengths [B] -> mask [B, maxlen] (reference:
    sequence_ops/sequence_mask_op.cc)."""
    lv = unwrap(x)
    T = int(maxlen) if maxlen is not None else int(np.asarray(lv).max())

    def f(lens):
        return (jnp.arange(T)[None, :] < lens[..., None]).astype(dtype)

    return call_op_nograd(f, x, op_name="sequence_mask")


def sequence_pad(x, pad_value=0.0, maxlen=None, name=None):
    """Ragged rows (list or RaggedBatch) -> (padded, lengths) (reference:
    sequence_ops/sequence_pad_op.cc)."""
    if isinstance(x, RaggedBatch):
        return x.data, x.lengths
    rb = RaggedBatch.from_list(x, pad_value, maxlen)
    return rb.data, rb.lengths


def sequence_unpad(x, length, name=None):
    """(padded, lengths) -> host list of rows (reference:
    sequence_ops/sequence_unpad_op.cc)."""
    return RaggedBatch(x, length).to_list()


def sequence_expand(x, lengths, name=None):
    """Repeat row i of x lengths[i] times (reference:
    sequence_ops/sequence_expand_op.cc, ref_level collapsed to row level).
    Host-side restructuring (output length is data-dependent)."""
    xv = np.asarray(unwrap(x))
    lv = np.asarray(unwrap(lengths))
    return wrap(jnp.asarray(np.repeat(xv, lv, axis=0)))


def sequence_reverse(x, lengths=None, name=None):
    """Reverse each row within its valid length (reference:
    sequence_ops/sequence_reverse_op.cc)."""
    if lengths is None:
        return call_op(lambda v: v[:, ::-1], x, op_name="sequence_reverse")

    def f(v, lens):
        T = v.shape[1]
        idx = jnp.arange(T)[None, :]
        rev = lens[:, None] - 1 - idx
        src = jnp.where(idx < lens[:, None], rev, idx)
        return jnp.take_along_axis(
            v, src.reshape(src.shape + (1,) * (v.ndim - 2)).astype(jnp.int32)
            if v.ndim > 2 else src.astype(jnp.int32), axis=1)

    return call_op(f, x, lengths, op_name="sequence_reverse")


def sequence_softmax(x, lengths, name=None):
    """Masked softmax over the time axis (reference:
    sequence_ops/sequence_softmax_op.cc)."""

    def f(v, lens):
        T = v.shape[1]
        mask = jnp.arange(T)[None, :] < lens[:, None]
        neg = jnp.where(mask, v, -jnp.inf)
        m = jnp.max(neg, axis=1, keepdims=True)
        e = jnp.exp(neg - m) * mask
        return e / jnp.maximum(e.sum(axis=1, keepdims=True), 1e-12)

    return call_op(f, x, lengths, op_name="sequence_softmax")


def sequence_pool(x, lengths, pool_type="average", name=None):
    """Masked pool over time (reference: sequence_ops/sequence_pool_op.cc;
    SUM/AVERAGE/MAX/LAST/FIRST/SQRT)."""
    pool_type = pool_type.lower()

    def f(v, lens):
        T = v.shape[1]
        mask = (jnp.arange(T)[None, :] < lens[:, None])
        maskx = mask.reshape(mask.shape + (1,) * (v.ndim - 2))
        cnt = jnp.maximum(lens.astype(v.dtype), 1)
        cnt = cnt.reshape(cnt.shape + (1,) * (v.ndim - 2))
        if pool_type == "sum":
            return jnp.where(maskx, v, 0).sum(axis=1)
        if pool_type == "average":
            return jnp.where(maskx, v, 0).sum(axis=1) / cnt
        if pool_type == "sqrt":
            return jnp.where(maskx, v, 0).sum(axis=1) / jnp.sqrt(cnt)
        if pool_type == "max":
            return jnp.where(maskx, v, -jnp.inf).max(axis=1)
        if pool_type == "first":
            return v[:, 0]
        if pool_type == "last":
            idx = jnp.maximum(lens - 1, 0).astype(jnp.int32)
            return jnp.take_along_axis(
                v, idx.reshape((-1, 1) + (1,) * (v.ndim - 2)),
                axis=1).squeeze(1)
        raise ValueError(f"unknown pool_type {pool_type}")

    return call_op(f, x, lengths, op_name=f"sequence_pool_{pool_type}")
