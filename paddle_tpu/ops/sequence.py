"""Sequence / ragged ops (reference: `paddle/fluid/operators/sequence_ops/`
+ the LoD machinery `framework/lod_tensor.h:57`).

TPU re-design: LoD (ragged offsets) is a host-side concept; on device
everything is padded + length-masked static shapes, which is what XLA needs.
`RaggedBatch` is the LoDTensor analog: a padded dense tensor + lengths
vector, with host converters both ways. The sequence_* functional ops work
on (data, lengths) pairs.
"""
import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import call_op, call_op_nograd, unwrap, wrap
from ..core.tensor import Tensor

__all__ = ["RaggedBatch", "sequence_mask", "sequence_pad", "sequence_unpad",
           "sequence_expand", "sequence_reverse", "sequence_softmax",
           "sequence_pool", "sequence_concat", "sequence_slice",
           "sequence_expand_as", "sequence_first_step", "sequence_last_step",
           "sequence_enumerate", "sequence_erase"]


class RaggedBatch:
    """LoDTensor analog: `data` [B, T, ...] padded, `lengths` [B] int32.

    reference: framework/lod_tensor.h:109 (LoDTensor), :57 (LoD offsets).
    The reference keeps ragged rows contiguous with offset tables; on TPU the
    canonical layout is padded-dense so each batch compiles to one static
    shape (bucket T upstream to bound recompilation).
    """

    def __init__(self, data, lengths):
        self.data = data if isinstance(data, Tensor) else Tensor(data)
        self.lengths = lengths if isinstance(lengths, Tensor) else \
            Tensor(np.asarray(lengths, np.int32))

    @classmethod
    def from_list(cls, rows, pad_value=0.0, maxlen=None):
        """Host ragged rows -> padded batch. (LoD construction analog.)"""
        rows = [np.asarray(r) for r in rows]
        lengths = np.asarray([len(r) for r in rows], np.int32)
        T = maxlen or (int(lengths.max()) if len(rows) else 0)
        tail = rows[0].shape[1:] if rows else ()
        out = np.full((len(rows), T) + tail, pad_value,
                      dtype=rows[0].dtype if rows else np.float32)
        for i, r in enumerate(rows):
            out[i, :len(r)] = r[:T]
        return cls(out, lengths)

    def to_list(self):
        d = np.asarray(unwrap(self.data))
        ls = np.asarray(unwrap(self.lengths))
        return [d[i, :ls[i]] for i in range(len(ls))]

    @property
    def shape(self):
        return self.data.shape


def sequence_mask(x, maxlen=None, dtype="int64"):
    """lengths [B] -> mask [B, maxlen] (reference:
    sequence_ops/sequence_mask_op.cc)."""
    lv = unwrap(x)
    T = int(maxlen) if maxlen is not None else int(np.asarray(lv).max())

    def f(lens):
        return (jnp.arange(T)[None, :] < lens[..., None]).astype(dtype)

    return call_op_nograd(f, x, op_name="sequence_mask")


def sequence_pad(x, pad_value=0.0, maxlen=None, name=None):
    """Ragged rows (list or RaggedBatch) -> (padded, lengths) (reference:
    sequence_ops/sequence_pad_op.cc)."""
    if isinstance(x, RaggedBatch):
        return x.data, x.lengths
    rb = RaggedBatch.from_list(x, pad_value, maxlen)
    return rb.data, rb.lengths


def sequence_unpad(x, length, name=None):
    """(padded, lengths) -> host list of rows (reference:
    sequence_ops/sequence_unpad_op.cc)."""
    return RaggedBatch(x, length).to_list()


def sequence_expand(x, lengths, name=None):
    """Repeat row i of x lengths[i] times (reference:
    sequence_ops/sequence_expand_op.cc, ref_level collapsed to row level).
    Host-side restructuring (output length is data-dependent)."""
    xv = np.asarray(unwrap(x))
    lv = np.asarray(unwrap(lengths))
    return wrap(jnp.asarray(np.repeat(xv, lv, axis=0)))


def sequence_reverse(x, lengths=None, name=None):
    """Reverse each row within its valid length (reference:
    sequence_ops/sequence_reverse_op.cc)."""
    if lengths is None:
        return call_op(lambda v: v[:, ::-1], x, op_name="sequence_reverse")

    def f(v, lens):
        T = v.shape[1]
        idx = jnp.arange(T)[None, :]
        rev = lens[:, None] - 1 - idx
        src = jnp.where(idx < lens[:, None], rev, idx)
        return jnp.take_along_axis(
            v, src.reshape(src.shape + (1,) * (v.ndim - 2)).astype(jnp.int32)
            if v.ndim > 2 else src.astype(jnp.int32), axis=1)

    return call_op(f, x, lengths, op_name="sequence_reverse")


def sequence_softmax(x, lengths, name=None):
    """Masked softmax over the time axis (reference:
    sequence_ops/sequence_softmax_op.cc)."""

    def f(v, lens):
        T = v.shape[1]
        mask = jnp.arange(T)[None, :] < lens[:, None]
        neg = jnp.where(mask, v, -jnp.inf)
        m = jnp.max(neg, axis=1, keepdims=True)
        e = jnp.exp(neg - m) * mask
        return e / jnp.maximum(e.sum(axis=1, keepdims=True), 1e-12)

    return call_op(f, x, lengths, op_name="sequence_softmax")


def sequence_concat(inputs, name=None):
    """Row-wise concatenation of ragged batches: row i of the result is the
    concatenation of row i from every input (reference:
    sequence_ops/sequence_concat_op.cc). Returns a RaggedBatch."""
    rbs = [x if isinstance(x, RaggedBatch) else RaggedBatch.from_list(x)
           for x in inputs]
    rows = [rb.to_list() for rb in rbs]
    merged = [np.concatenate([r[i] for r in rows], axis=0)
              for i in range(len(rows[0]))]
    return RaggedBatch.from_list(merged)


def sequence_slice(x, offset, length, name=None):
    """Per-row slice [offset[i], offset[i]+length[i]) (reference:
    sequence_ops/sequence_slice_op.cc). Output padded to max(length)."""
    rb = x if isinstance(x, RaggedBatch) else RaggedBatch.from_list(x)
    off = np.asarray(unwrap(offset)).reshape(-1)
    ln = np.asarray(unwrap(length)).reshape(-1)
    rows = rb.to_list()
    out = [r[int(o):int(o) + int(l)] for r, o, l in zip(rows, off, ln)]
    return RaggedBatch.from_list(out)


def sequence_expand_as(x, y, name=None):
    """Repeat row i of x so the result aligns with y's row lengths
    (reference: sequence_ops/sequence_expand_as_op.cc)."""
    lengths = y.lengths if isinstance(y, RaggedBatch) else y
    return sequence_expand(x, lengths, name=name)


def sequence_first_step(x, lengths=None, name=None):
    """reference: fluid/layers/sequence_lod.py sequence_first_step →
    sequence_pool FIRST."""
    if isinstance(x, RaggedBatch):
        x, lengths = x.data, x.lengths
    return sequence_pool(x, lengths, pool_type="first", name=name)


def sequence_last_step(x, lengths=None, name=None):
    """reference: sequence_last_step → sequence_pool LAST."""
    if isinstance(x, RaggedBatch):
        x, lengths = x.data, x.lengths
    return sequence_pool(x, lengths, pool_type="last", name=name)


def sequence_enumerate(x, win_size, pad_value=0, name=None):
    """All win_size-length subsequences per row, padded with pad_value past
    each row's end (reference: sequence_ops/sequence_enumerate_op.cc).
    (data [B,T] int, lengths) -> [B, T, win_size]."""
    if isinstance(x, RaggedBatch):
        data, lengths = x.data, x.lengths
    else:
        data, lengths = x, None

    def f(v, *rest):
        lens = rest[0] if rest else jnp.full((v.shape[0],), v.shape[1],
                                             jnp.int32)
        T = v.shape[1]
        pos = jnp.arange(T)[:, None] + jnp.arange(win_size)[None, :]  # [T,W]
        valid = pos[None, :, :] < lens[:, None, None]
        g = v[:, jnp.minimum(pos, T - 1)]  # [B, T, W]
        return jnp.where(valid, g, pad_value)

    args = (data,) + ((lengths,) if lengths is not None else ())
    return call_op_nograd(f, *args, op_name="sequence_enumerate")


def sequence_erase(x, tokens, name=None):
    """Remove the given token values from each row (reference:
    sequence_ops/sequence_erase_op.cc). Host restructuring — output rows are
    data-dependent lengths; returns a RaggedBatch."""
    rb = x if isinstance(x, RaggedBatch) else RaggedBatch.from_list(x)
    toks = set(int(t) for t in np.asarray(tokens).reshape(-1))
    rows = []
    for r in rb.to_list():
        keep = ~np.isin(r, list(toks))
        rows.append(r[keep])
    return RaggedBatch.from_list(rows)


def sequence_pool(x, lengths, pool_type="average", name=None):
    """Masked pool over time (reference: sequence_ops/sequence_pool_op.cc;
    SUM/AVERAGE/MAX/LAST/FIRST/SQRT)."""
    pool_type = pool_type.lower()

    def f(v, lens):
        T = v.shape[1]
        mask = (jnp.arange(T)[None, :] < lens[:, None])
        maskx = mask.reshape(mask.shape + (1,) * (v.ndim - 2))
        cnt = jnp.maximum(lens.astype(v.dtype), 1)
        cnt = cnt.reshape(cnt.shape + (1,) * (v.ndim - 2))
        if pool_type == "sum":
            return jnp.where(maskx, v, 0).sum(axis=1)
        if pool_type == "average":
            return jnp.where(maskx, v, 0).sum(axis=1) / cnt
        if pool_type == "sqrt":
            return jnp.where(maskx, v, 0).sum(axis=1) / jnp.sqrt(cnt)
        if pool_type == "max":
            return jnp.where(maskx, v, -jnp.inf).max(axis=1)
        if pool_type == "first":
            return v[:, 0]
        if pool_type == "last":
            idx = jnp.maximum(lens - 1, 0).astype(jnp.int32)
            return jnp.take_along_axis(
                v, idx.reshape((-1, 1) + (1,) * (v.ndim - 2)),
                axis=1).squeeze(1)
        raise ValueError(f"unknown pool_type {pool_type}")

    return call_op(f, x, lengths, op_name=f"sequence_pool_{pool_type}")


# --------------------------------------------------- decoding tail (round 2)

def gather_tree(ids, parents):
    """Beam-search backtrace (reference: operators/gather_tree_op.cc).
    ids/parents: [max_time, batch, beam] -> full beams re-threaded from the
    final step's parent pointers."""
    import jax

    def _gt(idv, parv):
        T = idv.shape[0]

        def step(parent, t):
            # walking backwards from T-1
            out = jnp.take_along_axis(idv[t], parent, axis=1)
            nxt = jnp.take_along_axis(parv[t], parent, axis=1)
            return nxt, out

        beams = jnp.broadcast_to(jnp.arange(idv.shape[2]), idv.shape[1:])
        _, outs = jax.lax.scan(step, beams, jnp.arange(T - 1, -1, -1))
        return outs[::-1]

    return call_op_nograd(_gt, ids, parents, op_name="gather_tree")


def edit_distance(input, label, normalized=True, input_length=None,  # noqa: A002
                  label_length=None):
    """Levenshtein distance per batch row (reference:
    operators/edit_distance_op.h). Padded [B, T] int tensors + lengths;
    returns (distance [B,1] float, sequence_num)."""
    import jax

    a = unwrap(input)
    b = unwrap(label)
    la = (unwrap(input_length).astype(jnp.int32) if input_length is not None
          else jnp.full((a.shape[0],), a.shape[1], jnp.int32))
    lb = (unwrap(label_length).astype(jnp.int32) if label_length is not None
          else jnp.full((b.shape[0],), b.shape[1], jnp.int32))

    def one(av, bv, na, nb):
        m = bv.shape[0]
        init = jnp.arange(m + 1, dtype=jnp.float32)
        big = jnp.asarray(1e9, jnp.float32)

        def row(prev, i):
            # prev = dp[i-1, :]; compute dp[i, :] with a scan over j
            def cell(left, j):
                up = prev[j + 1]
                diag = prev[j]
                cost = jnp.where(av[i] == bv[j], 0.0, 1.0)
                val = jnp.minimum(jnp.minimum(up + 1.0, left + 1.0),
                                  diag + cost)
                # past label length: carry the boundary value
                val = jnp.where(j < nb, val, big)
                return val, val

            first = jnp.asarray(i + 1, jnp.float32)
            _, rest = jax.lax.scan(cell, first, jnp.arange(m))
            cur = jnp.concatenate([first[None], rest])
            cur = jnp.where(i < na, cur, prev)
            return cur, None

        last, _ = jax.lax.scan(row, init, jnp.arange(av.shape[0]))
        d = last[nb]
        if normalized:
            d = d / jnp.maximum(nb.astype(jnp.float32), 1.0)
        return d

    def _ed(av, bv):
        return jax.vmap(one)(av, bv, la, lb)[:, None].astype(jnp.float32)

    dist = call_op_nograd(_ed, a, b, op_name="edit_distance")
    return dist, wrap(jnp.asarray(a.shape[0], jnp.int32))


def ctc_align(input, input_length=None, blank=0, padding_value=0):  # noqa: A002
    """Merge repeated labels then drop blanks (reference:
    operators/ctc_align_op.h). Padded [B, T]; returns (aligned [B, T] padded
    with padding_value, lengths [B])."""
    x = unwrap(input)
    B, T = x.shape
    ln = (unwrap(input_length).astype(jnp.int32) if input_length is not None
          else jnp.full((B,), T, jnp.int32))

    def _ca(v):
        pos = jnp.arange(T)
        valid = pos[None, :] < ln[:, None]
        prev = jnp.concatenate([jnp.full((B, 1), -1, v.dtype), v[:, :-1]],
                               axis=1)
        keep = (v != blank) & (v != prev) & valid
        # stable compaction: target slot = cumsum(keep)-1
        slot = jnp.cumsum(keep, axis=1) - 1
        slot = jnp.where(keep, slot, T)  # dropped -> out-of-range
        out = jnp.full((B, T + 1), padding_value, v.dtype)
        rows = jnp.arange(B)[:, None].repeat(T, 1)
        out = out.at[rows, slot].set(v, mode="drop")
        return out[:, :T]

    def _lens(v):
        pos = jnp.arange(T)
        valid = pos[None, :] < ln[:, None]
        prev = jnp.concatenate([jnp.full((B, 1), -1, v.dtype), v[:, :-1]],
                               axis=1)
        keep = (v != blank) & (v != prev) & valid
        return jnp.sum(keep, axis=1).astype(jnp.int32)

    return (call_op_nograd(_ca, x, op_name="ctc_align"),
            call_op_nograd(_lens, x, op_name="ctc_align_len"))


def row_conv(input, weight):  # noqa: A002
    """Lookahead row convolution (reference: operators/row_conv_op.cc):
    out[b,t,d] = sum_i x[b,t+i,d] * w[i,d] for the future-context window."""

    def _rc(v, w):
        k = w.shape[0]
        T = v.shape[1]
        pad = jnp.pad(v, ((0, 0), (0, k - 1), (0, 0)))
        out = sum(pad[:, i:i + T] * w[i] for i in range(k))
        return out

    return call_op(_rc, input, weight, op_name="row_conv")


def sequence_conv(x, filter, context_length, context_start=None,  # noqa: A002
                  lengths=None, padding_value=0.0):
    """Context-window convolution over time (reference:
    operators/sequence_ops/sequence_conv_op.cc): each step concatenates
    its [context_start, context_start+context_length) window and applies
    one projection. x: [B, T, D]; filter: [context_length*D, out]."""
    start = (-((context_length - 1) // 2) if context_start is None
             else context_start)
    lens = None if lengths is None else unwrap(lengths)

    def _sc(v, w):
        B, T, D = v.shape
        pre = max(0, -start)
        post = max(0, start + context_length - 1)
        pad = jnp.pad(v, ((0, 0), (pre, post), (0, 0)),
                      constant_values=padding_value)
        if lens is not None:  # zero beyond each sequence's length
            pos = jnp.arange(T + pre + post) - pre
            valid = (pos[None, :] >= 0) & (pos[None, :] < lens[:, None])
            pad = jnp.where(valid[..., None], pad, padding_value)
        # window element i covers input time t + start + i; with `pre`
        # left-padding that is pad index t + (start + i + pre)
        cols = jnp.concatenate(
            [pad[:, start + i + pre:start + i + pre + T]
             for i in range(context_length)], axis=-1)
        return cols @ w

    return call_op(_sc, x, filter, op_name="sequence_conv")


def sequence_reshape(x, new_dim):
    """reference: operators/sequence_ops/sequence_reshape_op.cc — refold
    the feature dim: [B, T, D] -> [B, T*D/new_dim, new_dim]."""

    def _sr(v):
        B, T, D = v.shape
        return v.reshape(B, T * D // new_dim, new_dim)

    return call_op(_sr, x, op_name="sequence_reshape")


def sequence_scatter(x, index, updates):
    """Add updates at per-sequence positions (reference:
    operators/sequence_ops/sequence_scatter_op.cc). x: [B, T];
    index/updates: [B, K]."""
    idx = unwrap(index).astype("int32")

    def _ss(v, u):
        rows = jnp.arange(v.shape[0])[:, None]
        return v.at[rows, idx].add(u)

    return call_op(_ss, x, updates, op_name="sequence_scatter")


def im2sequence(x, filter_size, stride=1, padding=0):
    """Sliding-window patch extraction (reference:
    operators/im2sequence_op.cc): [N, C, H, W] ->
    [N * oh * ow, C * kh * kw] row-major over output positions."""
    kh, kw = ((filter_size, filter_size) if isinstance(filter_size, int)
              else tuple(filter_size))
    sh, sw = (stride, stride) if isinstance(stride, int) else tuple(stride)
    ph, pw = (padding, padding) if isinstance(padding, int) else tuple(padding)

    def _i2s(v):
        # one conv_general_dilated_patches via the shared im2col (unfold):
        # [N, C*kh*kw, oh*ow] with (C, kh, kw)-major columns — the same
        # row layout the reference emits
        N = v.shape[0]
        patches = jax.lax.conv_general_dilated_patches(
            v, filter_shape=(kh, kw), window_strides=(sh, sw),
            padding=[(ph, ph), (pw, pw)],
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        ckk = patches.shape[1]
        cols = patches.reshape(N, ckk, -1).transpose(0, 2, 1)
        return cols.reshape(-1, ckk)

    return call_op(_i2s, x, op_name="im2sequence")
