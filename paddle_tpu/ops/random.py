"""Random ops over the functional key state (see core/random.py).

Replaces the reference's curand-backed samplers (`operators/uniform_random_op.cu`
etc.) with threefry; every draw advances the registered key tensor, so traced
training steps are deterministic and reproducible given `paddle_tpu.seed`.
"""
import jax
import jax.numpy as jnp

from ..core import random as core_random
from ..core.dispatch import unwrap
from ..core.dtype import convert_dtype
from ..core.tensor import Tensor


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in shape.numpy())
    if isinstance(shape, int):
        return (shape,)
    return tuple(int(s) for s in shape)


def rand(shape, dtype="float32"):
    key = core_random.next_key()
    return Tensor(jax.random.uniform(key, _shape(shape), dtype=convert_dtype(dtype)))


def randn(shape, dtype="float32"):
    key = core_random.next_key()
    return Tensor(jax.random.normal(key, _shape(shape), dtype=convert_dtype(dtype)))


def normal(mean=0.0, std=1.0, shape=None):
    key = core_random.next_key()
    return Tensor(jax.random.normal(key, _shape(shape)) * std + mean)


def uniform(shape, dtype="float32", min=-1.0, max=1.0, seed=0):  # noqa: A002
    key = core_random.next_key() if seed == 0 else jax.random.PRNGKey(seed)
    return Tensor(jax.random.uniform(key, _shape(shape),
                                     dtype=convert_dtype(dtype),
                                     minval=min, maxval=max))


def randint(low=0, high=None, shape=(1,), dtype="int64"):
    if high is None:
        low, high = 0, low
    key = core_random.next_key()
    return Tensor(jax.random.randint(key, _shape(shape), low, high,
                                     dtype=convert_dtype(dtype)))


def randperm(n, dtype="int64"):
    key = core_random.next_key()
    return Tensor(jax.random.permutation(key, n).astype(convert_dtype(dtype)))


def shuffle(x, axis=0):
    key = core_random.next_key()
    return Tensor(jax.random.permutation(key, unwrap(x), axis=axis,
                                         independent=False))


def bernoulli(x):
    key = core_random.next_key()
    p = unwrap(x)
    return Tensor(jax.random.bernoulli(key, p).astype(p.dtype))


def multinomial(x, num_samples=1, replacement=False):
    key = core_random.next_key()
    p = unwrap(x)
    logits = jnp.log(jnp.maximum(p, 1e-30))
    if replacement:
        out = jax.random.categorical(key, logits, axis=-1,
                                     shape=(*p.shape[:-1], num_samples))
    else:
        # Gumbel top-k trick for sampling without replacement
        g = jax.random.gumbel(key, p.shape)
        _, out = jax.lax.top_k(logits + g, num_samples)
    return Tensor(out.astype(jnp.int64))


def truncated_normal(shape, mean=0.0, std=1.0, dtype="float32"):
    """reference: truncated_gaussian_random_op.cc — normal draw truncated
    to two standard deviations, rescaled by mean/std."""
    key = core_random.next_key()
    z = jax.random.truncated_normal(key, -2.0, 2.0, _shape(shape),
                                    convert_dtype(dtype))
    return Tensor(z * std + mean)
