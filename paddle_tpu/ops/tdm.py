"""TDM retrieval ops (reference: `operators/tdm_sampler_op.{cc,h}` and
`operators/tdm_child_op.{cc,h}` — the tree-index training/serving pair
behind `fluid.contrib.layers.tdm_sampler/tdm_child`).

Host-side numpy ops by design: they run in the input pipeline (like the
reference's CPU-only kernels) and emit fixed-shape int arrays that feed
the jitted tower step — sampling stays off the TPU, the math on it.
"""
import numpy as np

from ..core.dispatch import unwrap, wrap

__all__ = ["tdm_sampler", "tdm_child"]


def _wrap_ids(arr, dtype):
    """Emit id arrays at the framework's id width. dtype='int64' means
    int64 when JAX x64 is on; otherwise int32 WITH an overflow check —
    ids beyond int32 raise loudly instead of silently truncating (trees
    that large need jax.config.update('jax_enable_x64', True))."""
    import jax
    import jax.numpy as jnp
    if dtype == "int64" and not jax.config.jax_enable_x64:
        if arr.size and int(arr.max()) > np.iinfo(np.int32).max:
            raise ValueError(
                "tdm ids exceed int32 range and JAX x64 is off; enable "
                "jax_enable_x64 for true int64 ids")
        return wrap(jnp.asarray(arr.astype(np.int32)))
    dt = jnp.int64 if dtype == "int64" else jnp.int32
    return wrap(jnp.asarray(arr, dt))


def tdm_sampler(x, neg_samples_num_list, layer_node_num_list, travel,
                layer, layer_offsets=None, output_positive=True, seed=0,
                dtype="int64"):
    """Layer-wise negative sampling over a TDM tree
    (tdm_sampler_op.h:49 TDMSamplerInner).

    ``x``: (batch, 1) or (batch,) leaf ITEM ids.
    ``travel``: (n_items, n_layers) per-item ancestor emb ids, root-side
    first, 0-padded (TreeIndex.travel_array).
    ``layer``/``layer_offsets``: flattened per-layer emb ids + offsets
    (TreeIndex.layer_array); ``layer_node_num_list`` must match the
    per-layer counts, like the reference validates.

    Returns (out, labels, mask), each
    (batch, sum(neg_i + output_positive)): positives carry label 1,
    uniform negatives (resampled on collision, reference's do/while)
    label 0; mask 0 marks padding rows from trees where this item's
    path is shorter.
    """
    x_np = np.asarray(unwrap(x)).astype(np.int64).ravel()
    travel = np.asarray(unwrap(travel)).astype(np.int64)
    layer_flat = np.asarray(unwrap(layer)).astype(np.int64).ravel()
    if layer_offsets is None:
        offsets = np.cumsum([0] + list(layer_node_num_list))
    else:
        offsets = np.asarray(layer_offsets).astype(np.int64)
    n_layers = len(neg_samples_num_list)
    if travel.shape[1] != n_layers or len(offsets) != n_layers + 1:
        raise ValueError(
            f"neg_samples_num_list ({n_layers} layers) must match "
            f"travel width {travel.shape[1]} and layer offsets "
            f"{len(offsets) - 1}")
    for li, want in enumerate(layer_node_num_list):
        have = int(offsets[li + 1] - offsets[li])
        if have != int(want):
            raise ValueError(
                f"layer_node_num_list[{li}]={want} but layer data has "
                f"{have} nodes")
        if int(neg_samples_num_list[li]) > have - 1:
            raise ValueError(
                f"neg_samples_num_list[{li}]={neg_samples_num_list[li]} "
                f"exceeds layer size {have} - 1")
    pos = 1 if output_positive else 0
    per_layer = [int(n) + pos for n in neg_samples_num_list]
    width = int(sum(per_layer))
    batch = x_np.size
    out = np.zeros((batch, width), np.int64)
    labels = np.zeros((batch, width), np.int64)
    mask = np.ones((batch, width), np.int64)
    rng = np.random.RandomState(seed)
    for i, item in enumerate(x_np):
        if not 0 <= item < travel.shape[0]:
            raise ValueError(
                f"tdm_sampler input id {item} outside travel table "
                f"[0, {travel.shape[0]})")
        col = 0
        for li in range(n_layers):
            positive = int(travel[item, li])
            ids = layer_flat[offsets[li]:offsets[li + 1]]
            if positive == 0:  # padded path: emit masked zeros
                w = per_layer[li]
                mask[i, col:col + w] = 0
                col += w
                continue
            if output_positive:
                out[i, col] = positive
                labels[i, col] = 1
                col += 1
            for _ in range(int(neg_samples_num_list[li])):
                neg = positive
                while neg == positive:
                    neg = int(ids[rng.randint(ids.size)])
                out[i, col] = neg
                col += 1
    return (_wrap_ids(out, dtype), _wrap_ids(labels, dtype),
            _wrap_ids(mask, dtype))


def tdm_child(x, tree_info, child_nums, dtype="int64"):
    """Children lookup over a TDM tree (tdm_child_op.h:34 TDMChildInner).

    ``x``: node EMB ids, any shape. ``tree_info``: (n_emb_ids, 3+branch)
    rows of [item_id, layer, parent, child ids...] 0-padded
    (TreeIndex.tree_info_array). Returns (child, leaf_mask) shaped
    ``x.shape + (child_nums,)``: absent children are 0; leaf_mask is 1
    where the child exists AND is a leaf (item_id != 0), matching the
    reference's leaf-flag output.
    """
    x_np = np.asarray(unwrap(x)).astype(np.int64)
    info = np.asarray(unwrap(tree_info)).astype(np.int64)
    branch = info.shape[1] - 3
    if child_nums > branch:
        raise ValueError(
            f"child_nums {child_nums} exceeds tree branch {branch}")
    flat = x_np.ravel()
    child = np.zeros((flat.size, child_nums), np.int64)
    leaf_mask = np.zeros((flat.size, child_nums), np.int64)
    for i, nid in enumerate(flat):
        if not 0 <= nid < info.shape[0]:
            raise ValueError(
                f"tdm_child input id {nid} outside tree_info "
                f"[0, {info.shape[0]})")
        kids = info[nid, 3:3 + child_nums]
        child[i] = kids
        for j, k in enumerate(kids):
            if k != 0 and info[k, 0] != 0:  # exists and is a leaf
                leaf_mask[i, j] = 1
    shape = x_np.shape + (child_nums,)
    return (_wrap_ids(child.reshape(shape), dtype),
            _wrap_ids(leaf_mask.reshape(shape), dtype))
