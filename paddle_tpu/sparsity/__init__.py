"""ASP — automatic structured (N:M) sparsity.

Reference: `python/paddle/fluid/contrib/sparsity/` — `asp.py` (ASPHelper,
prune_model, decorate), `utils.py` (create_mask, check_sparsity,
MaskAlgo/CheckMethod). The reference targets NVIDIA 2:4 sparse tensor cores;
on TPU the same N:M masks serve magnitude-pruning workflows (and XLA folds
the mask-multiply into the matmul's producer fusion).
"""
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import unwrap
from ..core.tensor import Tensor
from ..nn.layer.layers import Layer

__all__ = ["calculate_density", "create_mask", "check_mask_1d",
           "check_sparsity", "prune_model", "decorate", "ASPHelper"]


def calculate_density(x):
    """Fraction of non-zeros (reference: sparsity/utils.py
    calculate_density)."""
    arr = np.asarray(unwrap(x))
    return float((arr != 0).sum() / arr.size)


def _mask_1d_greedy(block, n, m):
    """Keep the n largest-|.| of every m consecutive elements."""
    keep = np.argsort(-np.abs(block))[:n]
    mask = np.zeros(m, block.dtype)
    mask[keep] = 1
    return mask


def create_mask(weight, func_name="mask_1d", n=2, m=4):
    """N:M mask along the last axis (reference: sparsity/utils.py
    create_mask, MaskAlgo.MASK_1D)."""
    w = np.asarray(unwrap(weight))
    flat = w.reshape(-1, w.shape[-1])
    cols = flat.shape[1]
    pad = (-cols) % m
    if pad:
        flat = np.pad(flat, ((0, 0), (0, pad)))
    groups = flat.reshape(flat.shape[0], -1, m)
    mask = np.zeros_like(groups)
    idx = np.argsort(-np.abs(groups), axis=-1)[..., :n]
    np.put_along_axis(mask, idx, 1.0, axis=-1)
    mask = mask.reshape(flat.shape)[:, :cols].reshape(w.shape)
    return mask.astype(w.dtype)


def check_mask_1d(mat, n=2, m=4):
    """True iff every m-group along the last axis has ≤ (m-n) zeros...
    i.e. at most n non-zeros (reference: sparsity/utils.py check_mask_1d)."""
    w = np.asarray(unwrap(mat))
    flat = w.reshape(-1, w.shape[-1])
    cols = flat.shape[1]
    pad = (-cols) % m
    if pad:
        flat = np.pad(flat, ((0, 0), (0, pad)))
    groups = flat.reshape(flat.shape[0], -1, m)
    return bool(((groups != 0).sum(-1) <= n).all())


def check_sparsity(mat, func_name="check_mask_1d", n=2, m=4):
    return check_mask_1d(mat, n, m)


def _supported(p):
    # prune matmul-facing 2-D+ weights only (reference skips biases/norms)
    return not getattr(p, "is_bias", False) and len(p.shape) >= 2


class ASPHelper:
    """Holds masks and re-applies them after optimizer steps (reference:
    sparsity/asp.py ASPHelper — _minimize inserts mask-mul after opt).
    Entries are weakref-verified: id(p) alone would alias a dead parameter's
    mask onto whatever new tensor reuses its id."""

    _masks = {}  # id(param) -> (weakref(param), mask)

    @classmethod
    def prune_model(cls, model, n=2, m=4, mask_algo="mask_1d",
                    with_mask=True):
        import weakref

        for name, p in model.named_parameters():
            if not _supported(p):
                continue
            mask = create_mask(p, mask_algo, n, m)
            key = id(p)
            cls._masks[key] = (
                weakref.ref(p, lambda _r, _k=key: cls._masks.pop(_k, None)),
                mask)
            p.set_value(np.asarray(unwrap(p)) * mask)
        return {k: m for k, (_, m) in cls._masks.items()}

    @classmethod
    def reapply_masks(cls, params):
        for p in params:
            entry = cls._masks.get(id(p))
            if entry is not None and entry[0]() is p:
                p.set_value(np.asarray(unwrap(p)) * entry[1])


def prune_model(model, n=2, m=4, mask_algo="mask_1d", with_mask=True):
    """reference: sparsity/asp.py prune_model."""
    return ASPHelper.prune_model(model, n, m, mask_algo, with_mask)


def decorate(optimizer):
    """Wrap optimizer.step to re-apply masks after each update (reference:
    sparsity/asp.py decorate -> ASPHelper._minimize)."""
    orig_step = optimizer.step

    def step(*a, **k):
        out = orig_step(*a, **k)
        params = [p for g in optimizer._param_groups for p in g["params"]]
        ASPHelper.reapply_masks(params)
        return out

    optimizer.step = step
    return optimizer
