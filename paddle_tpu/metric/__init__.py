"""Metrics (reference: `python/paddle/metric/metrics.py`)."""
import numpy as np

from ..core.tensor import Tensor
from .. import ops


class Metric:
    def __init__(self):
        pass

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        return self._name

    def compute(self, *args):
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        super().__init__()
        self.topk = topk if isinstance(topk, (list, tuple)) else (topk,)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def compute(self, pred, label):
        pred_np = np.asarray(pred.numpy() if isinstance(pred, Tensor) else pred)
        label_np = np.asarray(label.numpy() if isinstance(label, Tensor) else label)
        idx = np.argsort(-pred_np, axis=-1)[..., :self.maxk]
        if label_np.ndim == idx.ndim:
            label_np = label_np.squeeze(-1)
        correct = idx == label_np[..., None]
        return Tensor(correct.astype(np.float32))

    def update(self, correct):
        c = np.asarray(correct.numpy() if isinstance(correct, Tensor) else correct)
        accs = []
        num = int(np.prod(c.shape[:-1]))
        for i, k in enumerate(self.topk):
            n_correct = float(c[..., :k].sum())
            self.total[i] += n_correct
            self.count[i] += num
            accs.append(n_correct / max(num, 1))
        return accs[0] if len(accs) == 1 else accs

    def accumulate(self):
        res = [t / max(c, 1) for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return [self._name]
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name="precision"):
        super().__init__()
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        p = np.asarray(preds.numpy() if isinstance(preds, Tensor) else preds)
        l = np.asarray(labels.numpy() if isinstance(labels, Tensor) else labels)
        p = (p > 0.5).astype(np.int32).reshape(-1)
        l = l.astype(np.int32).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fp += int(((p == 1) & (l == 0)).sum())

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return [self._name]


class Recall(Metric):
    def __init__(self, name="recall"):
        super().__init__()
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        p = np.asarray(preds.numpy() if isinstance(preds, Tensor) else preds)
        l = np.asarray(labels.numpy() if isinstance(labels, Tensor) else labels)
        p = (p > 0.5).astype(np.int32).reshape(-1)
        l = l.astype(np.int32).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fn += int(((p == 0) & (l == 1)).sum())

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return [self._name]


class Auc(Metric):
    """Streaming AUC via thresholded confusion bins (reference:
    `operators/metrics/auc_op.cc`)."""

    def __init__(self, curve="ROC", num_thresholds=4095, name="auc"):
        super().__init__()
        self._name = name
        self.num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        p = np.asarray(preds.numpy() if isinstance(preds, Tensor) else preds)
        l = np.asarray(labels.numpy() if isinstance(labels, Tensor) else labels)
        if p.ndim == 2 and p.shape[1] == 2:
            p = p[:, 1]
        p = p.reshape(-1)
        l = l.reshape(-1)
        bins = np.minimum((p * self.num_thresholds).astype(np.int64),
                          self.num_thresholds)
        for b, y in zip(bins, l):
            if y:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        # integrate TPR over FPR from the highest threshold down
        pos = self._stat_pos[::-1].cumsum()
        neg = self._stat_neg[::-1].cumsum()
        tpr = pos / tot_pos
        fpr = neg / tot_neg
        return float(np.trapezoid(tpr, fpr))

    def name(self):
        return [self._name]


def auc(input, label, num_thresholds=4095, stat_pos=None, stat_neg=None,  # noqa: A002
        curve="ROC", slide_steps=0):
    """Functional AUC op (reference: `operators/metrics/auc_op.cc`): bucket
    predictions by threshold, accumulate pos/neg stats, integrate TPR over
    FPR. Returns (auc_value, stat_pos, stat_neg) — feed the stats back in
    for streaming accumulation, as the reference's persistable stat vars do.
    """
    p = np.asarray(input.numpy() if isinstance(input, Tensor) else input)
    l = np.asarray(label.numpy() if isinstance(label, Tensor) else label)
    if p.ndim == 2 and p.shape[1] == 2:
        p = p[:, 1]
    p = p.reshape(-1)
    l = l.reshape(-1)
    sp = (np.zeros(num_thresholds + 1) if stat_pos is None
          else np.asarray(stat_pos.numpy() if isinstance(stat_pos, Tensor)
                          else stat_pos).copy())
    sn = (np.zeros(num_thresholds + 1) if stat_neg is None
          else np.asarray(stat_neg.numpy() if isinstance(stat_neg, Tensor)
                          else stat_neg).copy())
    bins = np.minimum((p * num_thresholds).astype(np.int64), num_thresholds)
    np.add.at(sp, bins[l.astype(bool)], 1)
    np.add.at(sn, bins[~l.astype(bool)], 1)
    tot_pos, tot_neg = sp.sum(), sn.sum()
    if tot_pos == 0 or tot_neg == 0:
        value = 0.0
    else:
        pos = sp[::-1].cumsum()
        neg = sn[::-1].cumsum()
        value = float(np.trapezoid(pos / tot_pos, neg / tot_neg))
    return (Tensor(np.float32(value)), Tensor(sp.astype(np.int64)),
            Tensor(sn.astype(np.int64)))


def accuracy(input, label, k=1):  # noqa: A002
    """Functional accuracy (reference: `operators/metrics/accuracy_op.cc`)."""
    values, indices = ops.topk(input, k)
    label_np = label
    correct = ops.equal(indices.astype("int64"),
                        ops.reshape(label_np, [-1, 1]).astype("int64"))
    return ops.mean(ops.cast(ops.any(correct, axis=-1), "float32"))
