"""Activation recompute + host offload: the jax.checkpoint policy surface.

ZeRO-3 (ISSUE 5) cut model-state residency to O(params/dp); what binds
batch size and scan depth now is the ACTIVATIONS the backward pass keeps
alive between forward and backward. The reference ships exactly this
lever as ``fleet/utils/recompute.py`` (RecomputeFunction: drop the
segment's intermediate activations, replay the forward in backward with
the RNG state restored) plus the sharding optimizer's
``offload_helper.py`` (park state in host memory). On TPU the same trade
is one primitive — ``jax.checkpoint`` — but models must not call it
directly: which values are worth saving (and WHERE they are parked) is a
backend decision, so it routes through this policy surface
(``analysis/lint.py`` enforces that with the ``raw-remat-outside-policy``
rule).

Policies::

    none       pass-through (the A/B control; no remat region)
    full       recompute everything inside the segment per backward
               (jax default: nothing_saveable) — minimum residency,
               maximum recompute FLOPs
    selective  save matmul/dot outputs, recompute the cheap elementwise
               chain (``jax.checkpoint_policies.checkpoint_dots`` class
               of policy) — the usual sweet spot: dots are the expensive
               ops AND the big activations are mostly elementwise chains
    offload    save dot outputs but park them in PINNED HOST memory
               (``offload_dot_with_no_batch_dims('device',
               'pinned_host')``): device residency of ``full`` with the
               recompute FLOPs of ``selective``, paid in PCIe/ICI
               traffic. Backends without a ``pinned_host`` memory space
               (CPU jaxlib today) FALL BACK LOUDLY to ``selective`` —
               a silent no-op here would fake the memory claim.

Usage (``paddle.recompute`` is this MODULE; the function lives in it)::

    from paddle_tpu.recompute import recompute
    out = recompute(layer_fn, x, policy="selective")   # immediate
    fn  = recompute(layer_fn, policy="full")           # wrapper
    layer.enable_recompute("offload")                  # Layer seam

How it composes with the stack: the segment function is functionalized
with the same ``OpCapture`` + ``bind_values`` seam control-flow lowering
uses — one capture pass discovers the external tensors the segment reads
(parameters, buffers) and the framework state it MUTATES (the RNG key a
dropout advances, BN running stats), then the segment re-runs inside
``jax.checkpoint`` as a pure function whose inputs/outputs thread all of
it explicitly. The whole region dispatches through ``call_op`` as ONE
tape node, so:

- eagerly, the tape holds only the checkpoint's vjp residuals (policy-
  saved values), not the per-op activation chain — real memory savings
  before any jit;
- under ``@to_static(..., scan_steps=k)`` the region stages into the
  step jaxpr as a remat sub-jaxpr: XLA rematerializes in the compiled
  backward, the @GRAD-presence fixpoint sees one op, and the donated
  carry / ZeRO-1/2/3 / accumulation-window machinery is untouched;
- dropout replays BITWISE: the key mathematics (split of the generator
  state) happens INSIDE the remat region on the threaded-in key value,
  so the rematerialized backward re-derives the same keys — the
  reference RecomputeFunction's RNG-state-replay contract, for free.

Cost model: the capture pass runs the segment once per call to discover
its externals/mutations (re-discovered every call on purpose — the
external set can depend on python control flow inside ``fn``, so a
structural cache would silently bind stale parameters). Under
``to_static`` that is trace-time only (the capture ops are dead code
XLA drops). In EAGER training it is a real extra forward per segment
per step — eager recompute trades that and the backward replay for the
dropped residuals; the compiled scan step is the performance path.
"""
import functools
import threading
import warnings

import jax
import numpy as np

from .core import autograd, dispatch
from .core import random as core_random
from .core import state as state_mod
from .core.dispatch import bind_values, call_op
from .core.tensor import Tensor

__all__ = ["recompute", "resolve_policy", "host_offload_available",
           "remat_replay", "is_remat_replay", "POLICIES"]

POLICIES = ("none", "full", "selective", "offload")

# host memory kind used by the offload policy (pjit memory kinds)
OFFLOAD_MEMORY_KIND = "pinned_host"


# -- policy resolution ------------------------------------------------------

_offload_probe = [None]  # cached: None = not probed yet
_probe_lock = threading.Lock()


def host_offload_available():
    """True when the default backend exposes a ``pinned_host`` memory
    space (the pjit host-memory-kind the offload policy parks residuals
    in). Probed once per process; CPU jaxlib today has only
    ``unpinned_host`` and returns False."""
    with _probe_lock:
        if _offload_probe[0] is None:
            try:
                jax.local_devices()[0].memory(OFFLOAD_MEMORY_KIND)
                _offload_probe[0] = True
            except Exception:
                _offload_probe[0] = False
        return _offload_probe[0]


def _reset_offload_probe():
    """Test seam: forget the cached backend probe."""
    with _probe_lock:
        _offload_probe[0] = None


def resolve_policy(policy, strict=False):
    """``(jax_policy_or_None, effective_name)`` for a policy name (or a
    raw ``jax.checkpoint_policies`` callable, passed through for power
    users — prefer the names so backends stay swappable).

    ``offload`` degrades to ``selective`` WITH A WARNING when the
    backend has no ``pinned_host`` memory space; ``strict=True`` raises
    instead (for callers that must not fake the residency claim, e.g. a
    bench row explicitly pinning offload behavior)."""
    if callable(policy):
        return policy, getattr(policy, "__name__", "custom")
    name = str(policy)
    if name not in POLICIES:
        raise ValueError(
            f"unknown recompute policy {policy!r}; pick one of {POLICIES} "
            "(or pass a jax.checkpoint_policies callable)")
    cp = jax.checkpoint_policies
    if name == "none":
        return None, "none"
    if name == "full":
        # jax.checkpoint's default: save nothing, recompute everything
        return cp.nothing_saveable, "full"
    if name == "selective":
        # save dot/matmul outputs without a batch dim (weight-stationary
        # products); recompute the elementwise chains — the
        # checkpoint_dots analog that does not hoard the big batched
        # activations
        return cp.dots_with_no_batch_dims_saveable, "selective"
    # offload
    if host_offload_available():
        return (cp.offload_dot_with_no_batch_dims(
            "device", OFFLOAD_MEMORY_KIND), "offload")
    msg = (f"recompute policy 'offload' needs a {OFFLOAD_MEMORY_KIND!r} "
           f"memory space on the backend "
           f"({jax.default_backend()!r} has none)")
    if strict:
        raise RuntimeError(msg)
    warnings.warn(msg + "; falling back to 'selective' (dot outputs stay "
                  "in device memory)", stacklevel=3)
    return cp.dots_with_no_batch_dims_saveable, "selective"


# -- remat replay marker (static-graph remat structure) ---------------------

def remat_replay(fn):
    """Stamp ``fn`` as a REMAT REPLAY op: a static-graph recompute
    rewrite re-records a segment's forward ops in the backward region,
    writing the SAME slots the originals produced (the reference
    recompute_optimizer's backward-block replay). The graph verifier
    accepts such a re-write as rematerialization instead of flagging
    ``duplicate-slot-write`` — see ``analysis.verifier.check_graph``."""
    fn._remat_replay = True
    return fn


def is_remat_replay(fn):
    return bool(getattr(fn, "_remat_replay", False))


# -- the functionalized checkpoint segment ----------------------------------

class _suspend_static_hook:
    """Run capture/replay passes outside static-program recording so
    probe ops don't leak into a Program (only the fused recompute op is
    recorded) — the same discipline as control-flow lowering."""

    def __enter__(self):
        self._saved = dispatch._STATIC_HOOK[0]
        dispatch._STATIC_HOOK[0] = None
        return self

    def __exit__(self, *exc):
        dispatch._STATIC_HOOK[0] = self._saved
        return False


def _is_tensor(x):
    return isinstance(x, Tensor)


def _flatten_call(args, kwargs):
    leaves, treedef = jax.tree_util.tree_flatten(
        (args, kwargs), is_leaf=_is_tensor)
    t_idx = [i for i, l in enumerate(leaves) if _is_tensor(l)]
    return leaves, treedef, t_idx


_seg_counter = [0]


def _segment_call(fn, args, kwargs, policy):
    """Run ``fn(*args, **kwargs)`` as ONE rematerializable tape op."""
    jpolicy, effective = resolve_policy(policy)

    leaves, treedef, t_idx = _flatten_call(args, kwargs)
    arg_ts = [leaves[i] for i in t_idx]

    # the default generator is created lazily on first dropout; force it
    # to exist NOW so its registration doesn't read as "the segment
    # created new framework state"
    core_random._default()

    # ---- capture pass: discover reads, writes, and output structure ----
    items = state_mod.snapshot()
    version0 = state_mod.version()
    pre_vals = [t._value for _, t in items]
    pre_grads = [t._grad for _, t in items]
    scope_counters = [s.i for s in core_random._scoped_stack]

    cap = dispatch.OpCapture()
    cap.mark_created(arg_ts)
    created = {id(t) for t in arg_ts}
    with dispatch.capture_ops(cap), _suspend_static_hook():
        out = fn(*args, **kwargs)
    out_leaves, out_tdef = jax.tree_util.tree_flatten(out, is_leaf=_is_tensor)
    # a segment may return an external tensor directly (no op reads it);
    # it must become an operand or its capture-time value bakes in
    cap.note_inputs([t for t in out_leaves
                     if _is_tensor(t) and id(t) not in created])

    if state_mod.version() != version0:
        raise RuntimeError(
            "the recompute segment registered NEW framework state "
            "(lazily-built parameters/buffers or a fresh generator): the "
            "replay would register tracer-valued duplicates. Run the "
            "segment once outside recompute() to build its state first.")
    mut_idx = [i for i, (_uid, t) in enumerate(items)
               if t._value is not pre_vals[i]]
    for i, (_uid, t) in enumerate(items):
        if t._grad is not pre_grads[i]:
            raise RuntimeError(
                f"recompute segments must be forward-only, but "
                f"{t.name!r} got a gradient inside the segment — move "
                "backward()/opt.step() outside the recompute region.")
    mut_ts = [items[i][1] for i in mut_idx]
    mut_pre = [pre_vals[i] for i in mut_idx]
    mut_ids = {id(t) for t in mut_ts}

    # roll the capture run back: mutated state returns to its pre-segment
    # value and scoped-key counters rewind, so the ONE functional run
    # below advances state exactly as a plain (non-recompute) call would
    # — this is what makes dropout masks match the control bitwise
    for t, v in zip(mut_ts, mut_pre):
        t._value = v
    for s, i0 in zip(core_random._scoped_stack, scope_counters):
        s.i = i0

    # externals the segment reads that are NOT also mutated state (those
    # thread through the mut lane so each value has ONE binding)
    ext = [t for t in cap.external
           if id(t) not in mut_ids and id(t) not in created]

    n_args, n_ext, n_mut = len(arg_ts), len(ext), len(mut_ts)
    out_slots = {}  # filled by the traced run below

    # ---- the pure segment: (arg, ext, mut_in) -> (outs..., mut_out) ----
    def run(*vals):
        a_vals = vals[:n_args]
        e_vals = vals[n_args:n_args + n_ext]
        m_vals = vals[n_args + n_ext:]

        def seg(a_vals, e_vals, m_vals):
            lv = list(leaves)
            for i, v in zip(t_idx, a_vals):
                lv[i] = Tensor(v)
            a2, k2 = jax.tree_util.tree_unflatten(treedef, lv)
            with bind_values(list(ext) + list(mut_ts),
                             list(e_vals) + list(m_vals)), \
                    autograd.no_grad(), _suspend_static_hook():
                for s, i0 in zip(core_random._scoped_stack, scope_counters):
                    s.i = i0  # replay scoped keys from the same origin
                o = fn(*a2, **k2)
                o_leaves, o_tdef = jax.tree_util.tree_flatten(
                    o, is_leaf=_is_tensor)
                o_vals = [l._value if _is_tensor(l) else l for l in o_leaves]
                new_mut = [t._value for t in mut_ts]
            out_slots["treedef"] = o_tdef
            out_slots["n"] = len(o_vals)
            return tuple(o_vals) + tuple(new_mut)

        if jpolicy is None and effective == "none":
            return seg(a_vals, e_vals, m_vals)
        return jax.checkpoint(seg, policy=jpolicy)(a_vals, e_vals, m_vals)

    run.__name__ = "recompute"
    run._remat_policy = effective
    _seg_counter[0] += 1
    run._remat_segment = _seg_counter[0]

    out_all = call_op(run, *arg_ts, *ext, *mut_ts, op_name="recompute")
    out_all = out_all if isinstance(out_all, tuple) else (out_all,)

    n_out = out_slots.get("n", len(out_all) - n_mut)
    # write mutated state back: values advance exactly one run's worth;
    # side-state (RNG counters, BN stats) carries no gradient, matching
    # the reference recompute contract
    for t, new in zip(mut_ts, out_all[n_out:]):
        t._value = new._value if _is_tensor(new) else new
    wrapped = list(out_all[:n_out])
    return jax.tree_util.tree_unflatten(out_slots.get("treedef", out_tdef),
                                        wrapped)


def recompute(function, *args, policy="full", **kwargs):
    """Run (or wrap) ``function`` as an activation-recompute segment.

    With call arguments, runs immediately (the
    ``paddle.distributed.fleet.utils.recompute`` call shape)::

        y = recompute(block, x, policy="selective")

    Without them, returns a wrapped callable (decorator shape)::

        block = recompute(block.forward, policy="offload")
        y = block(x)

    ``policy`` is one of :data:`POLICIES` (or a raw
    ``jax.checkpoint_policies`` callable). ``policy="none"`` is the
    pass-through control — same dispatch structure, no remat region.
    Segments must be forward-only (no ``backward()``/optimizer inside)
    and must not build new parameters on first call. See the module
    docstring for the composition rules (bitwise dropout replay,
    to_static/ZeRO/accumulation compatibility).
    """
    if not callable(function):
        raise TypeError(f"recompute expects a callable, got {function!r}")
    if not args and not kwargs:
        @functools.wraps(function)
        def wrapped(*a, **k):
            return _segment_call(function, a, k, policy)
        wrapped._recompute_policy = policy
        return wrapped
    return _segment_call(function, args, kwargs, policy)
