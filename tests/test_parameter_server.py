"""Parameter-server stack tests (reference: `test_dist_base.py:744/867` —
pserver subprocesses + trainer subprocesses on localhost, loss parity
against local runs; plus table-level unit tests).
"""
import os
import re
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures",
                       "ps_ctr_runner.py")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _clean_env():
    env = dict(os.environ)
    for k in list(env):
        if k.startswith(("PADDLE_", "JAX_", "PS_")) or k == "XLA_FLAGS":
            env.pop(k)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    return env


def _losses(text):
    return [float(m.group(2)) for m in
            re.finditer(r"LOSS (\d+) ([\d.eE+-]+)", text)]


def _spawn(role, mode, ports, wid=0, n_workers=1, extra=None):
    env = _clean_env()
    if isinstance(ports, int):
        ports = [ports]
    env.update({
        "PS_ROLE": role,
        "PS_MODE": mode,
        "TRAINING_ROLE": "PSERVER" if role == "server" else "TRAINER",
        "PADDLE_PSERVER_ENDPOINTS": ",".join(
            f"127.0.0.1:{p}" for p in ports),
        "PADDLE_PSERVER_ID": str(wid if role == "server" else 0),
        "PADDLE_TRAINER_ID": str(wid),
        "PADDLE_TRAINERS_NUM": str(n_workers),
    })
    if extra:
        env.update(extra)
    script = ("import jax; jax.config.update('jax_platforms','cpu');"
              "import runpy; runpy.run_path(%r, run_name='__main__')"
              % FIXTURE)
    return subprocess.Popen([sys.executable, "-c", script],
                            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                            text=True, env=env, cwd=REPO)


def _run_cluster(mode, n_workers, n_servers=1, extra=None, timeout=420):
    ports = [_free_port() for _ in range(n_servers)]
    servers = [_spawn("server", mode, ports, wid=i, extra=extra)
               for i in range(n_servers)]
    for srv in servers:  # wait for SERVER_READY before starting workers
        line = srv.stdout.readline()
        assert "SERVER_READY" in line, line + srv.stderr.read()[-2000:]
    workers = [_spawn("worker", mode, ports, wid=i, n_workers=n_workers,
                      extra=extra)
               for i in range(n_workers)]
    outs = []
    try:
        for w in workers:
            out, err = w.communicate(timeout=timeout)
            assert w.returncode == 0, f"worker failed:\n{err[-4000:]}"
            outs.append(out)
        for srv in servers:
            srv.wait(timeout=60)
    finally:
        for p in workers + servers:
            if p.poll() is None:
                p.kill()
    return outs


def _run_local(extra=None):
    env = _clean_env()
    env["PS_ROLE"] = "local"
    if extra:
        env.update(extra)
    script = ("import jax; jax.config.update('jax_platforms','cpu');"
              "import runpy; runpy.run_path(%r, run_name='__main__')"
              % FIXTURE)
    r = subprocess.run([sys.executable, "-c", script], capture_output=True,
                       text=True, env=env, cwd=REPO, timeout=420)
    assert r.returncode == 0, r.stderr[-4000:]
    return _losses(r.stdout)


# ---------------------------------------------------------------- unit level

class TestNativeTableService:
    """In-process client/server against the native table store."""

    def _start(self, tables):
        from paddle_tpu.distributed.ps import PsClient, PsServer
        srv = PsServer(tables, port=0)
        port = srv.start()
        cli = PsClient([f"127.0.0.1:{port}"])
        return srv, cli

    def test_sparse_pull_init_matches_python_mirror(self):
        from paddle_tpu.distributed.ps import TableConfig
        from paddle_tpu.distributed.ps.embedding import deterministic_init
        srv, cli = self._start(
            [TableConfig(7, "sparse", 4, "sgd", lr=0.5, init_range=0.2,
                         seed=7)])
        try:
            cli.register_sparse(7, 4)
            keys = np.array([3, 99, 12345], np.uint64)
            got = cli.pull_sparse(7, keys)
            want = deterministic_init(7, keys, 4, 0.2)
            np.testing.assert_allclose(got, want, rtol=1e-6)
            # sgd push applies -lr*g server-side
            g = np.ones((3, 4), np.float32)
            cli.push_sparse_grad(7, keys, g)
            got2 = cli.pull_sparse(7, keys)
            np.testing.assert_allclose(got2, want - 0.5, rtol=1e-5)
            assert cli.sparse_size(7) == 3
        finally:
            cli.stop_servers()
            srv.stop()

    def test_sparse_adam_matches_numpy(self):
        from paddle_tpu.distributed.ps import TableConfig
        srv, cli = self._start(
            [TableConfig(1, "sparse", 3, "adam", lr=0.1, init_range=0.0)])
        try:
            cli.register_sparse(1, 3)
            keys = np.array([5], np.uint64)
            p = np.zeros(3); m = np.zeros(3); v = np.zeros(3)
            for t in range(1, 4):
                g = np.full(3, float(t), np.float32)
                cli.push_sparse_grad(1, keys, g.reshape(1, 3))
                m = 0.9 * m + 0.1 * g
                v = 0.999 * v + 0.001 * g * g
                mh = m / (1 - 0.9 ** t)
                vh = v / (1 - 0.999 ** t)
                p -= 0.1 * mh / (np.sqrt(vh) + 1e-8)
            got = cli.pull_sparse(1, keys)[0]
            np.testing.assert_allclose(got, p, rtol=1e-5)
        finally:
            cli.stop_servers()
            srv.stop()

    def test_dense_init_push_pull_and_delta(self):
        from paddle_tpu.distributed.ps import TableConfig
        srv, cli = self._start(
            [TableConfig(0, "dense", 0, "sgd", lr=0.1)])
        try:
            cli.register_dense(0, 4)
            init = np.arange(4, dtype=np.float32)
            got = cli.pull_dense_init(0, init)
            np.testing.assert_allclose(got, init)
            # second init is ignored (table already initialized)
            got = cli.pull_dense_init(0, np.zeros(4, np.float32))
            np.testing.assert_allclose(got, init)
            cli.push_dense_grad(0, np.ones(4, np.float32))
            np.testing.assert_allclose(cli.pull_dense(0), init - 0.1)
            cli.push_dense_delta(0, np.full(4, 0.5, np.float32))
            np.testing.assert_allclose(cli.pull_dense(0), init + 0.4)
        finally:
            cli.stop_servers()
            srv.stop()

    def test_save_load_roundtrip(self, tmp_path):
        from paddle_tpu.distributed.ps import PsClient, PsServer, TableConfig
        tables = [TableConfig(0, "dense", 0, "sgd", lr=0.1),
                  TableConfig(9, "sparse", 2, "adam", lr=0.05,
                              init_range=0.3, seed=9)]
        srv, cli = self._start(tables)
        keys = np.array([11, 22], np.uint64)
        try:
            cli.register_dense(0, 3)
            cli.register_sparse(9, 2)
            cli.pull_dense_init(0, np.array([1, 2, 3], np.float32))
            cli.push_sparse_grad(9, keys, np.ones((2, 2), np.float32))
            want_sparse = cli.pull_sparse(9, keys)
            want_dense = cli.pull_dense(0)
            cli.save(str(tmp_path / "snap"))
        finally:
            cli.stop_servers()
            srv.stop()
        # fresh server, load the snapshot, state must match (incl. adam t:
        # one more identical push must give identical results server-restart
        # or not)
        srv2, cli2 = self._start(tables)
        try:
            cli2.register_dense(0, 3)
            cli2.register_sparse(9, 2)
            cli2.load(str(tmp_path / "snap"))
            np.testing.assert_allclose(cli2.pull_sparse(9, keys), want_sparse)
            np.testing.assert_allclose(cli2.pull_dense(0), want_dense)
        finally:
            cli2.stop_servers()
            srv2.stop()


# ------------------------------------------------------------ cluster level

class TestPsCluster:
    @pytest.mark.slow  # ~31 s subprocess cluster; geo convergence stays
    def test_geo_single_worker_matches_local(self):  # tier-1-covered by
        """geo k=1, one worker: server state mirrors local SGD exactly
        (the reference's geo-delta semantics)."""  # TestPsGeoMultiWorker
        outs = _run_cluster("geo", 1, extra={"PS_K_STEPS": "1"})
        ps_losses = _losses(outs[0])
        local_losses = _run_local()
        assert len(ps_losses) == len(local_losses) > 0
        np.testing.assert_allclose(ps_losses, local_losses, rtol=2e-3,
                                   atol=2e-4)

    @pytest.mark.slow  # ~26 s; subsumed in tier-1 by the sharded
    def test_sync_two_workers_train(self):  # two-server sync case below
        outs = _run_cluster("sync", 2)
        for out in outs:
            ls = _losses(out)
            assert len(ls) == 200
            assert np.mean(ls[-10:]) < 0.35 < np.mean(ls[:5])

    @pytest.mark.slow  # ~23 s subprocess cluster (PR 11 budget); async
    def test_async_two_workers_train_and_save(self, tmp_path):
        # wire + save coverage stays tier-1 via TestNativeTableService
        # and the Downpour two-thread run
        snap = str(tmp_path / "ps_snap")
        outs = _run_cluster("async", 2, extra={"PS_SAVE": snap})
        for out in outs:
            ls = _losses(out)
            assert len(ls) == 200
            assert np.mean(ls[-10:]) < 0.35 < np.mean(ls[:5])
        assert os.path.exists(snap + ".0")
        m = re.search(r"SPARSE_SIZE (\d+)", outs[0])
        assert m and int(m.group(1)) > 0

    @pytest.mark.slow  # ~26 s subprocess cluster (PR 11 budget); key
    def test_sync_two_workers_two_servers_sharded(self):
        """Sparse keys shard across 2 server processes (key % nservers);
        training still converges and every server holds a partition.
        (Key-range sharding itself stays tier-1 via the async_cache
        write-back range-split tests.)"""
        outs = _run_cluster("sync", 2, n_servers=2)
        for out in outs:
            ls = _losses(out)
            assert len(ls) == 200
            assert np.mean(ls[-10:]) < 0.35 < np.mean(ls[:5])


class TestDownpourTrainer:
    """Multi-threaded DeviceWorker analog (reference: DownpourWorker /
    DistMultiTrainer via train_from_dataset, SURVEY CS5): thread-local
    model replicas over one shared PS client, async push/pull."""

    def test_two_threads_train_from_dataset(self):
        import numpy as np

        import paddle_tpu as paddle
        from paddle_tpu import nn
        from paddle_tpu.distributed import ps
        from paddle_tpu.distributed.ps import (DownpourTrainer, PsClient,
                                               PsServer, TableConfig)

        VOCAB, DIM = 50, 4
        srv = PsServer([
            TableConfig(1000, "sparse", DIM, "sgd", lr=0.2, init_range=0.1,
                        seed=1000),
            TableConfig(0, "dense", 0, "sgd", lr=0.2),
            TableConfig(1, "dense", 0, "sgd", lr=0.2),
            TableConfig(2, "dense", 0, "sgd", lr=0.2),
            TableConfig(3, "dense", 0, "sgd", lr=0.2),
        ], port=0)
        port = srv.start()
        try:
            class Runtime:  # minimal stand-in for PsRuntime on one host
                client = PsClient([f"127.0.0.1:{port}"])

                class role:
                    @staticmethod
                    def worker_num():
                        return 1

            def builder():
                paddle.seed(0)

                class M(nn.Layer):
                    def __init__(self):
                        super().__init__()
                        # EXPLICIT table id: every replica must address
                        # the same server table
                        self.emb = ps.SparseEmbedding([VOCAB, DIM],
                                                      table_id=1000)
                        self.fc1 = nn.Linear(3 * DIM, 8)
                        self.fc2 = nn.Linear(8, 1)

                    def forward(self, ids):
                        e = self.emb(ids)
                        h = paddle.ops.reshape(e, [e.shape[0], 3 * DIM])
                        return self.fc2(
                            paddle.nn.functional.relu(self.fc1(h)))

                return M()

            w_id = np.random.RandomState(42).randn(VOCAB).astype(np.float32)

            def loss_fn(model, batch):
                ids, label = batch
                logits = model(paddle.to_tensor(ids))
                return paddle.nn.functional.\
                    binary_cross_entropy_with_logits(
                        logits, paddle.to_tensor(label))

            def batches(n):
                rng = np.random.RandomState(0)
                for _ in range(n):
                    ids = rng.randint(0, VOCAB, (32, 3)).astype(np.int64)
                    label = (w_id[ids[:, 0]] > 0).astype(
                        np.float32).reshape(-1, 1)
                    yield ids, label

            tr = DownpourTrainer(Runtime, builder, loss_fn, n_threads=2)
            stats = tr.train_from_dataset(batches(250))
            assert stats["batches"] == 250
            assert all(c > 0 for c in stats["per_thread"])  # both worked
            # learned: fresh replica pulled from PS beats chance decisively
            probe = builder()
            from paddle_tpu.distributed.ps import bind_model
            from paddle_tpu.distributed.ps.communicator import SyncCommunicator
            comm = SyncCommunicator(Runtime.client, n_workers=1)
            bind_model(probe, comm)
            comm.pull_dense()
            ids, label = next(batches(1))
            with paddle.no_grad():
                pred = (probe(paddle.to_tensor(ids)).numpy() > 0)
            acc = (pred.ravel() == (label.ravel() > 0.5)).mean()
            assert acc > 0.75, acc
        finally:
            Runtime.client.stop_servers()
            srv.stop()


class TestPsGeoMultiWorker:
    @pytest.mark.slow  # ~24 s subprocess cluster (PR 11 budget); geo
    def test_geo_two_workers_k4_converge(self):  # delta semantics stay
        # tier-1 via the in-process geo wire/communicator unit tests
        """2 workers, geo delta sync every 4 local steps (the reference
        GeoCommunicator's actual operating point): both converge."""
        outs = _run_cluster("geo", 2, extra={"PS_K_STEPS": "4"})
        for out in outs:
            ls = _losses(out)
            assert len(ls) == 200
            assert np.mean(ls[-10:]) < 0.35 < np.mean(ls[:5])


class TestHeterPs:
    """Heterogeneous PS (reference: heter_client.h:67/heter_server.h:151
    + heterxpu_trainer.cc): the worker runs the sparse/embedding stage and
    exchanges activations with a trainer process owning the dense stage;
    activation grads flow back and sparse grads land on the PS."""

    @pytest.mark.slow  # ~12 s two-subprocess pipeline (PR 11 budget);
    def test_heter_worker_trainer_pipeline(self):  # the heter overlap
        # story is tier-1-covered by the async_cache CTR pipeline
        import subprocess
        import sys as _s
        import textwrap

        trainer_code = textwrap.dedent("""
            import jax; jax.config.update('jax_platforms','cpu')
            import numpy as np
            import paddle_tpu as paddle
            from paddle_tpu import nn
            from paddle_tpu.distributed.ps.heter import HeterServer

            paddle.seed(1)
            dense = nn.Sequential(nn.Linear(12, 16), nn.ReLU(),
                                  nn.Linear(16, 1))
            opt = paddle.optimizer.SGD(parameters=dense.parameters(),
                                       learning_rate=0.2)

            def handler(acts, labels):
                a = paddle.to_tensor(acts.astype(np.float32))
                a.stop_gradient = False
                logits = dense(a)
                loss = paddle.nn.functional.\\
                    binary_cross_entropy_with_logits(
                        logits, paddle.to_tensor(labels))
                loss.backward()
                opt.step(); opt.clear_grad()
                return float(loss.numpy()), np.asarray(a.grad.numpy())

            srv = HeterServer(handler, port=int(__import__('sys').argv[1]))
            print("TRAINER_READY", flush=True)
            srv.serve_forever()
        """)
        port = _free_port()
        trainer = subprocess.Popen(
            [_s.executable, "-c", trainer_code, str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
            cwd=REPO, env=_clean_env())
        try:
            line = trainer.stdout.readline()
            assert "TRAINER_READY" in line, line

            # worker side (this process): PS sparse table + embedding stage
            import paddle_tpu as paddle
            from paddle_tpu.distributed import ps
            from paddle_tpu.distributed.ps import (PsClient, PsServer,
                                                   TableConfig)
            from paddle_tpu.distributed.ps.communicator import \
                AsyncCommunicator
            from paddle_tpu.distributed.ps.heter import HeterClient

            VOCAB, DIM = 40, 4
            pss = PsServer([TableConfig(1000, "sparse", DIM, "sgd", lr=0.2,
                                        init_range=0.1, seed=1000)], port=0)
            ps_port = pss.start()
            cli = PsClient([f"127.0.0.1:{ps_port}"])
            comm = AsyncCommunicator(cli, n_workers=1)
            emb = ps.SparseEmbedding([VOCAB, DIM], table_id=1000)
            emb.bind(comm)
            heter = HeterClient(f"127.0.0.1:{port}")

            w_id = np.random.RandomState(42).randn(VOCAB).astype(np.float32)
            rng_l = np.random.RandomState(0)
            losses = []
            for step in range(150):
                ids = rng_l.randint(0, VOCAB, (32, 3)).astype(np.int64)
                labels = (w_id[ids[:, 0]] > 0).astype(
                    np.float32).reshape(-1, 1)
                e = emb(paddle.to_tensor(ids))          # sparse stage (host)
                acts = paddle.ops.reshape(e, [32, 3 * DIM])
                loss, dacts = heter.send_and_recv(
                    np.asarray(acts.numpy()), labels)   # dense stage (trainer)
                acts.backward(paddle.to_tensor(dacts))  # sparse backward
                from paddle_tpu.distributed.ps.embedding import \
                    flush_sparse_grads
                flush_sparse_grads(comm)
                comm.step()
                losses.append(loss)
            assert np.mean(losses[-10:]) < 0.4 < np.mean(losses[:5])
            assert cli.sparse_size(1000) > 0  # sparse grads reached the PS
            heter.stop_server()
            heter.close()
            comm.stop()
            cli.stop_servers()
            pss.stop()
        finally:
            if trainer.poll() is None:
                trainer.kill()


_KILL_SERVER_SCRIPT = """
import sys, time
import jax; jax.config.update('jax_platforms', 'cpu')
from paddle_tpu.distributed.ps import PsServer, TableConfig
tables = [TableConfig(1000, "sparse", 4, "adam", lr=0.05, init_range=0.1,
                      seed=7),
          TableConfig(0, "dense", 0, "adam", lr=0.05)]
srv = PsServer(tables, port=int(sys.argv[1]))
srv.start()
print("SERVER_READY", flush=True)
srv.run()
"""


class TestPsServerKillFaultInjection:
    """Server-side fault injection (reference: brpc_ps_client.cc connect
    retry under FLAGS_pserver_connect_timeout_ms): SIGKILL a pserver
    mid-training, bring up a replacement on the same port, and the worker
    — same PsClient object, never rebuilt — reconnects and resumes from
    the last snapshot. Complements test_launch_elastic_ckpt.py, which
    kills a *worker*."""

    def _spawn_server(self, port):
        srv = subprocess.Popen(
            [sys.executable, "-c", _KILL_SERVER_SCRIPT, str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=_clean_env(), cwd=REPO)
        line = srv.stdout.readline()
        assert "SERVER_READY" in line, line + srv.stderr.read()[-2000:]
        return srv

    def test_worker_reconnects_and_resumes_after_sigkill(self, tmp_path):
        from paddle_tpu.distributed.ps import PsClient
        port = _free_port()
        snap = str(tmp_path / "kill_snap")
        srv = self._spawn_server(port)
        srv2 = None
        cli = PsClient([f"127.0.0.1:{port}"])
        try:
            cli.register_sparse(1000, 4)
            cli.register_dense(0, 6)
            keys = np.array([2, 5, 11], np.uint64)
            rng = np.random.RandomState(3)
            cli.pull_dense_init(0, np.zeros(6, np.float32))
            for _ in range(4):
                cli.push_sparse_grad(1000, keys,
                                     rng.rand(3, 4).astype(np.float32))
                cli.push_dense_grad(0, rng.rand(6).astype(np.float32))
            cli.save(snap)
            trained_sparse = cli.pull_sparse(1000, keys)
            trained_dense = cli.pull_dense(0)

            srv.kill()  # SIGKILL: no graceful shutdown, sockets just die
            srv.wait(timeout=30)
            srv2 = self._spawn_server(port)

            # the SAME client object reconnects: the first pull rides the
            # idempotent retry path over a fresh socket
            fresh = cli.pull_sparse(1000, keys)
            assert not np.allclose(fresh, trained_sparse), \
                "replacement server unexpectedly has trained state"
            cli.load(snap)
            np.testing.assert_allclose(cli.pull_sparse(1000, keys),
                                       trained_sparse)
            np.testing.assert_allclose(cli.pull_dense(0), trained_dense)
            # and training continues against the replacement
            cli.push_dense_grad(0, rng.rand(6).astype(np.float32))
            assert not np.allclose(cli.pull_dense(0), trained_dense)
        finally:
            try:
                cli.stop_servers()
            except (ConnectionError, OSError):
                pass
            cli.close()
            for p in (srv, srv2):
                if p is not None and p.poll() is None:
                    p.kill()

    def test_push_against_dead_server_fails_within_deadline(self):
        """Pushes are idempotent now (request-id dedup server-side), so
        the client MAY retry them — but against a server that never
        comes back the retry budget is bounded: the push fails with a
        ConnectionError subclass (RetriesExhausted/DeadlineExceeded)
        within the policy's deadline instead of hanging or hammering."""
        from paddle_tpu.distributed.ps import PsClient
        from paddle_tpu.distributed.ps.retry import RetryPolicy
        port = _free_port()
        srv = self._spawn_server(port)
        cli = PsClient([f"127.0.0.1:{port}"],
                       retry_policy=RetryPolicy(max_attempts=3,
                                                base_delay_s=0.05,
                                                deadline_s=3.0, seed=5))
        cli.CONNECT_RETRIES = 3
        cli.CONNECT_BACKOFF = 0.05
        try:
            cli.register_dense(0, 6)
            cli.pull_dense_init(0, np.zeros(6, np.float32))  # opens socket
            srv.kill()
            srv.wait(timeout=30)
            t0 = time.monotonic()
            with pytest.raises(ConnectionError):
                cli.push_dense_grad(0, np.ones(6, np.float32))
            assert time.monotonic() - t0 < 10.0  # bounded, not hung
        finally:
            cli.close()
            if srv.poll() is None:
                srv.kill()

    def test_push_retry_across_server_restart_applies_once(self):
        """The push graceful-degradation story end-to-end: the server
        dies, a fresh replacement binds while the client is still inside
        its retry window, and the retried push lands EXACTLY once — the
        replacement's table equals one adam step from zeros (the same
        deterministic reference the original fresh server produced), not
        two."""
        from paddle_tpu.distributed.ps import PsClient
        from paddle_tpu.distributed.ps.retry import RetryPolicy
        port = _free_port()
        srv = self._spawn_server(port)
        srv2 = None
        cli = PsClient([f"127.0.0.1:{port}"],
                       retry_policy=RetryPolicy(max_attempts=20,
                                                base_delay_s=0.2,
                                                max_delay_s=0.5,
                                                deadline_s=60.0, seed=5))
        cli.CONNECT_RETRIES = 40
        cli.CONNECT_BACKOFF = 0.25
        try:
            cli.register_dense(0, 6)
            cli.pull_dense_init(0, np.zeros(6, np.float32))
            cli.push_dense_grad(0, np.ones(6, np.float32))
            base = cli.pull_dense(0)  # one adam step from zeros
            srv.kill()
            srv.wait(timeout=30)

            def revive():
                time.sleep(1.0)
                nonlocal srv2
                srv2 = self._spawn_server(port)

            t = threading.Thread(target=revive)
            t.start()
            # issued while the server is DOWN: rides the retry window
            # until the replacement binds, then applies exactly once on
            # the replacement's fresh (zeros) table
            cli.push_dense_grad(0, np.ones(6, np.float32))
            t.join(timeout=60)
            after = cli.pull_dense(0)
            np.testing.assert_allclose(after, base)
        finally:
            cli.close()
            for p in (srv, srv2):
                if p is not None and p.poll() is None:
                    p.kill()


class TestPsServerRestartResume:
    def test_snapshot_restart_resume_training(self, tmp_path):
        """Server-side fault-tolerance cycle (reference:
        fleet.save_persistables -> server restart -> load -> resume):
        training state survives a full server restart bit-exactly."""
        from paddle_tpu.distributed.ps import (PsClient, PsServer,
                                               TableConfig)
        tables = [TableConfig(1000, "sparse", 4, "adam", lr=0.05,
                              init_range=0.1, seed=1000),
                  TableConfig(0, "dense", 0, "adam", lr=0.05)]
        snap = str(tmp_path / "resume_snap")

        srv = PsServer(tables, port=0)
        port = srv.start()
        cli = PsClient([f"127.0.0.1:{port}"])
        cli.register_sparse(1000, 4)
        cli.register_dense(0, 6)
        keys = np.array([3, 8, 13], np.uint64)
        rng_l = np.random.RandomState(2)
        cli.pull_dense_init(0, np.zeros(6, np.float32))
        for _ in range(5):
            cli.push_sparse_grad(1000, keys,
                                 rng_l.rand(3, 4).astype(np.float32))
            cli.push_dense_grad(0, rng_l.rand(6).astype(np.float32))
        cli.save(snap)
        mid_sparse = cli.pull_sparse(1000, keys)
        mid_dense = cli.pull_dense(0)
        # continue WITHOUT restart: the adam-momentum ground truth
        g_s = rng_l.rand(3, 4).astype(np.float32)
        g_d = rng_l.rand(6).astype(np.float32)
        cli.push_sparse_grad(1000, keys, g_s)
        cli.push_dense_grad(0, g_d)
        want_sparse = cli.pull_sparse(1000, keys)
        want_dense = cli.pull_dense(0)
        cli.stop_servers()
        srv.stop()

        # fresh server process state: load snapshot, apply the SAME next
        # grads — identical result proves optimizer state (m/v/t) resumed
        srv2 = PsServer(tables, port=0)
        port2 = srv2.start()
        cli2 = PsClient([f"127.0.0.1:{port2}"])
        cli2.register_sparse(1000, 4)
        cli2.register_dense(0, 6)
        try:
            cli2.load(snap)
            np.testing.assert_allclose(cli2.pull_sparse(1000, keys),
                                       mid_sparse)
            np.testing.assert_allclose(cli2.pull_dense(0), mid_dense)
            cli2.push_sparse_grad(1000, keys, g_s)
            cli2.push_dense_grad(0, g_d)
            np.testing.assert_allclose(cli2.pull_sparse(1000, keys),
                                       want_sparse, rtol=1e-6)
            np.testing.assert_allclose(cli2.pull_dense(0), want_dense,
                                       rtol=1e-6)
        finally:
            cli2.stop_servers()
            srv2.stop()
