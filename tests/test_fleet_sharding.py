"""ZeRO sharding + meta-optimizer tests on the 8-device virtual CPU mesh.

Mirrors the reference's meta-optimizer tests (SURVEY.md §4: program-inspection
for sharding_optimizer insertions + loss-parity dist tests). TPU form:
inspection = PartitionSpecs on params/accumulators and actually-sharded
jax.Array layouts after a compiled step; parity = sharded run equals
single-device run.
"""
import numpy as np
import pytest

from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.distributed import fleet


@pytest.fixture(autouse=True)
def _reset_mesh():
    yield
    from paddle_tpu.distributed import parallel_env
    parallel_env.set_mesh(None)
    from paddle_tpu.distributed.fleet.base import topology
    topology.set_hybrid_communicate_group(None)


def _mlp(seed):
    paddle.seed(seed)
    return nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 8))


def _init_sharding(degree, stage):
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1,
                               "pp_degree": 1, "sharding_degree": degree}
    strategy.sharding = True
    strategy.sharding_configs = {"stage": stage}
    fleet.init(is_collective=True, strategy=strategy)
    return strategy


def _train(model, opt, x, y, steps=3, pspec=None):
    def step(xb, yb):
        loss = nn.functional.cross_entropy(model(xb), yb)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    sfn = paddle.jit.to_static(step)
    if pspec is not None:
        sfn._arg_pspecs = pspec
    return [float(sfn(paddle.to_tensor(x), paddle.to_tensor(y)).numpy())
            for _ in range(steps)]


X = np.random.RandomState(0).rand(8, 16).astype("float32")
Y = np.random.RandomState(1).randint(0, 8, 8).astype("int64")


def test_zero1_state_sharded_and_parity():
    """Stage-1: the optimizer's moments re-lay-out into flat stores
    sharded over the sharding axis; loss matches the unsharded baseline
    (the check_with_place analog)."""
    # baseline
    m0 = _mlp(3)
    opt0 = paddle.optimizer.Adam(learning_rate=0.05,
                                 parameters=m0.parameters())
    base = _train(m0, opt0, X, Y)

    strategy = _init_sharding(8, stage=1)
    m = _mlp(3)
    m = fleet.distributed_model(m)
    opt = paddle.optimizer.Adam(learning_rate=0.05, parameters=m.parameters())
    opt = fleet.distributed_optimizer(opt, strategy)

    inner = opt._inner._inner  # HybridParallelOptimizer -> DygraphSharding -> Adam
    assert inner._zero is not None and inner._zero["axis"] == "sharding"
    stores = [sd[slot] for sd in inner._zero["stores"] for slot in sd]
    specs = [st.tensor.pspec for st in stores]
    assert specs and all("sharding" in str(s) for s in specs), specs

    losses = _train(m, opt, X, Y)
    np.testing.assert_allclose(base, losses, rtol=2e-5)

    # the moment stores must actually live sharded across the 8 devices
    arr = stores[0].tensor._value
    assert len(arr.sharding.device_set) == 8
    # ... at 1/8 of the store per rank
    assert arr.addressable_shards[0].data.shape[0] == arr.shape[0] // 8


def test_zero3_params_sharded_and_parity():
    """Stage-3: parameters carry the sharding layout; same losses."""
    m0 = _mlp(5)
    opt0 = paddle.optimizer.Adam(learning_rate=0.05,
                                 parameters=m0.parameters())
    base = _train(m0, opt0, X, Y)

    strategy = _init_sharding(8, stage=3)
    m = _mlp(5)
    m = fleet.distributed_model(m)
    from paddle_tpu.distributed.fleet.meta_parallel import ShardingParallel
    assert isinstance(m, ShardingParallel)
    sharded_params = [p for p in m.parameters()
                      if p.pspec is not None and any(p.pspec)]
    assert sharded_params, "no parameter got a sharding spec"

    opt = paddle.optimizer.Adam(learning_rate=0.05, parameters=m.parameters())
    opt = fleet.distributed_optimizer(opt, strategy)
    losses = _train(m, opt, X, Y)
    np.testing.assert_allclose(base, losses, rtol=2e-5)

    arr = sharded_params[0]._value
    assert len(arr.sharding.device_set) == 8


def test_gradient_merge_matches_big_batch():
    """k-step gradient merge (avg) == one step on the k-times batch for SGD
    (the reference gradient_merge semantics)."""
    xs = np.random.RandomState(2).rand(4, 2, 16).astype("float32")
    ys = np.random.RandomState(3).randint(0, 8, (4, 2)).astype("int64")

    # merged: 4 micro-steps of batch 2
    m1 = _mlp(11)
    opt1 = paddle.optimizer.SGD(learning_rate=0.2,
                                parameters=m1.parameters())
    strategy = fleet.DistributedStrategy()
    strategy.gradient_merge = True
    strategy.gradient_merge_configs = {"k_steps": 4, "avg": True}
    opt1 = fleet.distributed_optimizer(opt1, strategy)
    for i in range(4):
        loss = nn.functional.cross_entropy(
            m1(paddle.to_tensor(xs[i])), paddle.to_tensor(ys[i]))
        loss.backward()
        opt1.step()
        opt1.clear_grad()

    # baseline: one step on the full batch (mean loss == mean of micro means)
    m2 = _mlp(11)
    opt2 = paddle.optimizer.SGD(learning_rate=0.2,
                                parameters=m2.parameters())
    loss = nn.functional.cross_entropy(
        m2(paddle.to_tensor(xs.reshape(8, 16))),
        paddle.to_tensor(ys.reshape(8)))
    loss.backward()
    opt2.step()
    opt2.clear_grad()

    for p1, p2 in zip(m1.parameters(), m2.parameters()):
        np.testing.assert_allclose(np.asarray(p1._value),
                                   np.asarray(p2._value), rtol=1e-5,
                                   atol=1e-6)


def test_gradient_merge_holds_params_between_boundaries():
    m = _mlp(13)
    opt = paddle.optimizer.Adam(learning_rate=0.1,
                                parameters=m.parameters())
    from paddle_tpu.distributed.fleet.meta_optimizers import (
        GradientMergeOptimizer,
    )
    opt = GradientMergeOptimizer(opt, k_steps=3, avg=True)
    w0 = np.asarray(m[0].weight._value).copy()
    for i in range(2):  # below the boundary: params must not move
        loss = nn.functional.cross_entropy(
            m(paddle.to_tensor(X)), paddle.to_tensor(Y))
        loss.backward()
        opt.step()
        opt.clear_grad()
    np.testing.assert_array_equal(w0, np.asarray(m[0].weight._value))
    loss = nn.functional.cross_entropy(
        m(paddle.to_tensor(X)), paddle.to_tensor(Y))
    loss.backward()
    opt.step()  # boundary: now they move
    assert not np.allclose(w0, np.asarray(m[0].weight._value))


def test_lookahead_and_ema():
    m = _mlp(17)
    fast = paddle.optimizer.SGD(learning_rate=0.3,
                                parameters=m.parameters())
    opt = paddle.optimizer.LookAhead(fast, alpha=0.5, k=2)
    ema = paddle.optimizer.ExponentialMovingAverage(decay=0.5)
    w0 = np.asarray(m[0].weight._value).copy()
    for _ in range(4):
        loss = nn.functional.cross_entropy(
            m(paddle.to_tensor(X)), paddle.to_tensor(Y))
        loss.backward()
        opt.step()
        opt.clear_grad()
        ema.update(list(m.parameters()))
    w_fast = np.asarray(m[0].weight._value).copy()
    assert not np.allclose(w0, w_fast)
    with ema.apply():
        w_ema = np.asarray(m[0].weight._value).copy()
        assert not np.allclose(w_ema, w_fast)  # shadow differs from live
    np.testing.assert_array_equal(np.asarray(m[0].weight._value), w_fast)


def test_model_average_apply_restore():
    m = _mlp(19)
    sgd = paddle.optimizer.SGD(learning_rate=0.5,
                               parameters=m.parameters())
    # min window larger than the step count: no block restart, so the
    # applied average spans every snapshot (reference average_accumulates
    # semantics: restart only once num_accumulates >= min_average_window)
    ma = paddle.optimizer.ModelAverage(0.15, parameters=m.parameters(),
                                       min_average_window=10)
    snapshots = []
    for _ in range(3):
        loss = nn.functional.cross_entropy(
            m(paddle.to_tensor(X)), paddle.to_tensor(Y))
        loss.backward()
        sgd.step()
        sgd.clear_grad()
        ma.step()
        snapshots.append(np.asarray(m[0].weight._value).copy())
    live = np.asarray(m[0].weight._value).copy()
    with ma.apply():
        avg = np.asarray(m[0].weight._value)
        np.testing.assert_allclose(avg, np.mean(snapshots, axis=0),
                                   rtol=1e-5, atol=1e-6)
    np.testing.assert_array_equal(np.asarray(m[0].weight._value), live)


def test_hybrid_parallel_util_smoke():
    strategy = _init_sharding(8, stage=1)
    hcg = fleet.get_hybrid_communicate_group()
    m = _mlp(23)
    from paddle_tpu.distributed.fleet.utils import hybrid_parallel_util as hpu
    hpu.broadcast_dp_parameters(m, hcg)
    hpu.broadcast_mp_parameters(m, hcg)
    hpu.broadcast_sharding_parameters(m, hcg)
    loss = nn.functional.cross_entropy(
        m(paddle.to_tensor(X)), paddle.to_tensor(Y))
    loss.backward()
    hpu.fused_allreduce_gradients(list(m.parameters()), hcg)
    assert m[0].weight._grad is not None
