"""Collective overlap observability (ISSUE 16): the HLO schedule
analyzer (``observability.overlap``), the async ``-start``/``-done``
billing contract in ``hlo_bytes``, the per-program XLA flag surface
(``jit.xla_flags``), gate direction pins, and ``tools/overlap_view``.

The seeded async-HLO fixtures pin the pairing/interleave math
backend-independently: XLA:CPU never emits async collective pairs, so
these hand-written schedules are the only way the hidden-time path is
exercised on the smoke host — the integration tests then assert the
CPU backend's sync-only schedule is reported honestly (efficiency 0.0,
``backend_sync_schedule=True``), not as an analyzer failure.
"""
import gzip
import json
import os
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.distributed import parallel_env
from paddle_tpu.jit import xla_flags
from paddle_tpu.observability import export as obs_export
from paddle_tpu.observability import gate as gate_mod
from paddle_tpu.observability import hlo_bytes, overlap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DP = 8

rng = np.random.RandomState(16)


@pytest.fixture
def _mesh():
    mesh = parallel_env.make_mesh({"dp": DP})
    parallel_env.set_mesh(mesh)
    yield mesh
    parallel_env.set_mesh(None)


# -- seeded HLO fixtures ---------------------------------------------------
# hand-written post-scheduling HLO snippets: instruction order is the
# schedule. Payloads are sized so collective time dominates (or not)
# by construction.

SYNC_HLO = """HloModule sync, is_scheduled=true

ENTRY %main (p0: f32[1024]) -> f32[8192] {
  %p0 = f32[1024]{0} parameter(0)
  %mul = f32[1024]{0} multiply(f32[1024]{0} %p0, f32[1024]{0} %p0)
  ROOT %ag = f32[8192]{0} all-gather(f32[1024]{0} %mul), channel_id=1, replica_groups={{0,1,2,3,4,5,6,7}}, dimensions={0}, use_global_device_ids=true
}
"""

# the dot between start/done costs far more than the 32KB gather moves
ASYNC_FULL_HLO = """HloModule hidden, is_scheduled=true

ENTRY %main (p0: f32[1024], p1: f32[1024,1024]) -> f32[1024] {
  %p0 = f32[1024]{0} parameter(0)
  %p1 = f32[1024,1024]{1,0} parameter(1)
  %ag-start = (f32[1024]{0}, f32[8192]{0}) all-gather-start(f32[1024]{0} %p0), channel_id=1, replica_groups={{0,1,2,3,4,5,6,7}}, dimensions={0}, use_global_device_ids=true
  %dot = f32[1024]{0} dot(f32[1024]{0} %p0, f32[1024,1024]{1,0} %p1), lhs_contracting_dims={0}, rhs_contracting_dims={0}
  %ag-done = f32[8192]{0} all-gather-done((f32[1024]{0}, f32[8192]{0}) %ag-start)
  ROOT %out = f32[1024]{0} add(f32[1024]{0} %dot, f32[1024]{0} %dot)
}
"""

# only a tiny f32[64] add fits between the pair: a sliver hides
ASYNC_PARTIAL_HLO = """HloModule partial, is_scheduled=true

ENTRY %main (p0: f32[1024], p2: f32[64]) -> f32[8192] {
  %p0 = f32[1024]{0} parameter(0)
  %p2 = f32[64]{0} parameter(1)
  %ag-start = (f32[1024]{0}, f32[8192]{0}) all-gather-start(f32[1024]{0} %p0), channel_id=1, replica_groups={{0,1,2,3,4,5,6,7}}, dimensions={0}, use_global_device_ids=true
  %small = f32[64]{0} add(f32[64]{0} %p2, f32[64]{0} %p2)
  ROOT %ag-done = f32[8192]{0} all-gather-done((f32[1024]{0}, f32[8192]{0}) %ag-start)
}
"""

# an async pair scheduled back-to-back: nothing between -> fully exposed
ASYNC_ADJACENT_HLO = """HloModule adjacent, is_scheduled=true

ENTRY %main (p0: f32[1024]) -> f32[8192] {
  %p0 = f32[1024]{0} parameter(0)
  %ag-start = (f32[1024]{0}, f32[8192]{0}) all-gather-start(f32[1024]{0} %p0), channel_id=1, replica_groups={{0,1,2,3,4,5,6,7}}, dimensions={0}, use_global_device_ids=true
  ROOT %ag-done = f32[8192]{0} all-gather-done((f32[1024]{0}, f32[8192]{0}) %ag-start)
}
"""

# sync all-reduce inside a x3 while inside a x4 while: bills 12 per run
NESTED_SCAN_HLO = """HloModule nested, is_scheduled=true

%inner_body (p: (f32[256])) -> (f32[256]) {
  %p = (f32[256]{0}) parameter(0)
  %gte = f32[256]{0} get-tuple-element((f32[256]{0}) %p), index=0
  %ar = f32[256]{0} all-reduce(f32[256]{0} %gte), channel_id=2, replica_groups={{0,1,2,3,4,5,6,7}}, use_global_device_ids=true, to_apply=%sum
  ROOT %t = (f32[256]{0}) tuple(f32[256]{0} %ar)
}

%inner_cond (p: (f32[256])) -> pred[] {
  %p = (f32[256]{0}) parameter(0)
  ROOT %c = pred[] constant(true)
}

%outer_body (q: (f32[256])) -> (f32[256]) {
  %q = (f32[256]{0}) parameter(0)
  %inner = (f32[256]{0}) while((f32[256]{0}) %q), condition=%inner_cond, body=%inner_body, backend_config={"known_trip_count":{"n":"3"}}
  ROOT %t2 = (f32[256]{0}) tuple(f32[256]{0} %inner)
}

%outer_cond (q: (f32[256])) -> pred[] {
  %q = (f32[256]{0}) parameter(0)
  ROOT %c2 = pred[] constant(true)
}

%sum (a: f32[], b: f32[]) -> f32[] {
  %a = f32[] parameter(0)
  %b = f32[] parameter(1)
  ROOT %s = f32[] add(f32[] %a, f32[] %b)
}

ENTRY %main (p0: f32[256]) -> (f32[256]) {
  %p0 = f32[256]{0} parameter(0)
  %init = (f32[256]{0}) tuple(f32[256]{0} %p0)
  ROOT %outer = (f32[256]{0}) while((f32[256]{0}) %init), condition=%outer_cond, body=%outer_body, backend_config={"known_trip_count":{"n":"4"}}
}
"""


# -- analyzer: pairing + efficiency math ----------------------------------

def test_sync_schedule_zero_efficiency():
    s = overlap.overlap_stats(SYNC_HLO)
    assert s["collective_overlap_efficiency"] == 0.0
    assert s["exposed_collective_frac"] == 1.0
    assert s["async_pairs_total"] == 0
    assert s["sync_total"] == 1
    assert s["backend_sync_schedule"] is True
    assert s["exposed_ns"] == pytest.approx(s["collective_ns"])
    assert s["collective_ns"] > 0


def test_fully_hidden_async_pair():
    s = overlap.overlap_stats(ASYNC_FULL_HLO)
    assert s["async_pairs_total"] == 1
    assert s["sync_total"] == 0
    assert s["collective_overlap_efficiency"] == pytest.approx(1.0)
    assert s["exposed_ns"] == pytest.approx(0.0)
    assert s["backend_sync_schedule"] is False
    (pair,) = s["pairs"]
    assert pair["phase"] == "async"
    # the dot's compute time exceeds the 32KB gather's wire time
    assert pair["overlap_ns"] > pair["collective_ns"]


def test_partial_interleave_fractional():
    s = overlap.overlap_stats(ASYNC_PARTIAL_HLO)
    assert s["async_pairs_total"] == 1
    eff = s["collective_overlap_efficiency"]
    assert 0.0 < eff < 1.0
    assert s["exposed_collective_frac"] == pytest.approx(1.0 - eff)
    (pair,) = s["pairs"]
    # the hidden sliver is exactly the in-between compute estimate
    assert pair["hidden_ns"] == pytest.approx(pair["overlap_ns"])
    assert pair["hidden_ns"] < pair["collective_ns"]


def test_adjacent_async_pair_fully_exposed():
    s = overlap.overlap_stats(ASYNC_ADJACENT_HLO)
    assert s["async_pairs_total"] == 1
    assert s["collective_overlap_efficiency"] == 0.0
    # async with nothing scheduled between is exposed but NOT a sync
    # schedule — the gauge split must keep the two cases apart
    assert s["backend_sync_schedule"] is False


def test_unmatched_start_counts_sync():
    # strip the -done line: the dangling -start blocks like a sync op
    hlo = "\n".join(l for l in ASYNC_FULL_HLO.splitlines()
                    if "ag-done" not in l)
    s = overlap.overlap_stats(hlo)
    assert s["async_pairs_total"] == 0
    assert s["sync_total"] == 1
    assert s["collective_overlap_efficiency"] == 0.0


def test_nested_scan_trip_count_multiplication():
    s = overlap.overlap_stats(NESTED_SCAN_HLO, per_execution=True)
    # 4 outer trips x 3 inner trips x 1 all-reduce
    assert s["sync_total"] == 12
    static = overlap.overlap_stats(NESTED_SCAN_HLO, per_execution=False)
    assert static["sync_total"] == 1
    assert s["collective_ns"] == pytest.approx(12 * static["collective_ns"])


def test_no_collectives_reports_honestly():
    hlo = """HloModule empty, is_scheduled=true

ENTRY %main (p0: f32[8]) -> f32[8] {
  %p0 = f32[8]{0} parameter(0)
  ROOT %m = f32[8]{0} multiply(f32[8]{0} %p0, f32[8]{0} %p0)
}
"""
    s = overlap.overlap_stats(hlo)
    assert s["collective_overlap_efficiency"] == 0.0
    assert s["sync_total"] == 0 and s["async_pairs_total"] == 0
    # no collectives is not a "sync schedule" finding
    assert s["backend_sync_schedule"] is False


def test_assumptions_recorded():
    s = overlap.overlap_stats(SYNC_HLO, link_gbps=50.0, hbm_gbps=400.0)
    assert s["assumptions"]["link_gbps"] == 50.0
    assert s["assumptions"]["hbm_gbps"] == 400.0
    # halving the link bandwidth doubles the collective estimate
    base = overlap.overlap_stats(SYNC_HLO)
    assert s["collective_ns"] == pytest.approx(2 * base["collective_ns"])


def test_per_op_split(_mesh):
    # second computation renamed: computations are keyed by name, and
    # two ENTRY %main blocks would collide
    combined = ASYNC_FULL_HLO + SYNC_HLO.replace(
        "HloModule sync, is_scheduled=true", "").replace(
        "ENTRY %main", "%tail")
    s = overlap.overlap_stats(combined, mesh=_mesh)
    assert "all-gather" in s["per_op"]
    (pair,) = [p for p in s["pairs"] if p["phase"] == "async"]
    assert pair["axis"] == "dp"


# -- hlo_bytes: async billing regression (satellite 1) ---------------------

def test_async_pair_bills_bytes_exactly_once():
    stats = hlo_bytes.collective_stats(ASYNC_FULL_HLO)
    assert len(stats) == 1
    (rec,) = stats
    assert rec["op"] == "all-gather"
    assert rec["count"] == 1  # one pair, one op — not two
    # the -start result tuple repeats the operand buffer next to the
    # full result; the payload is the LARGEST shape, once
    assert rec["bytes"] == 8192 * 4


def test_done_line_never_matches_op_regex():
    done_only = ("  %ag-done = f32[8192]{0} all-gather-done("
                 "(f32[1024]{0}, f32[8192]{0}) %ag-start)")
    assert hlo_bytes.collective_stats(done_only) == []
    assert hlo_bytes._OP_RE.search(done_only) is None
    # ... including when an operand NAME carries the op substring
    tricky = ("  %x = f32[8]{0} all-gather-done((f32[1]{0}, f32[8]{0}) "
              "%all-gather-start.1)")
    assert hlo_bytes._OP_RE.search(tricky) is None


# -- hlo_bytes: iota replica-group resolution (satellite 2) ----------------

def test_replica_group_forms_resolve_same_axis(_mesh):
    brace = SYNC_HLO
    iota = SYNC_HLO.replace("replica_groups={{0,1,2,3,4,5,6,7}}",
                            "replica_groups=[8]<=[8]")
    (b,) = hlo_bytes.collective_stats(brace, mesh=_mesh)
    (i,) = hlo_bytes.collective_stats(iota, mesh=_mesh)
    assert b["axis"] == "dp"
    assert i["axis"] == "dp"  # used to fall back to size1
    assert b["bytes"] == i["bytes"]


def test_iota_form_multi_group():
    mesh = parallel_env.make_mesh({"dp": 4, "mp": 2})
    try:
        parallel_env.set_mesh(mesh)
        hlo = SYNC_HLO.replace("replica_groups={{0,1,2,3,4,5,6,7}}",
                               "replica_groups=[4,2]<=[4,2]")
        (rec,) = hlo_bytes.collective_stats(hlo, mesh=mesh)
        assert rec["axis"] == "mp"  # 4 groups of size 2 -> the size-2 axis
        # permuted iota bounds parse the same (dims product, not order)
        hlo2 = SYNC_HLO.replace("replica_groups={{0,1,2,3,4,5,6,7}}",
                                "replica_groups=[2,4]<=[2,4]")
        (rec2,) = hlo_bytes.collective_stats(hlo2, mesh=mesh)
        assert rec2["axis"] == "dp"  # 2 groups of size 4
    finally:
        parallel_env.set_mesh(None)


def test_group_size_parsing_unit():
    assert hlo_bytes._group_size("replica_groups={{0,1,2}}") == 3
    assert hlo_bytes._group_size("replica_groups=[8]<=[8]") == 8
    assert hlo_bytes._group_size("replica_groups=[8]<=[2,4]") == 8
    assert hlo_bytes._group_size("replica_groups=[4,2]<=[8]") == 2
    assert hlo_bytes._group_size("no groups here") is None


# -- jit.xla_flags ---------------------------------------------------------

def test_parse_flags_coercion():
    flags = xla_flags.parse_flags(
        "--xla_a=true xla_b=false xla_c=3 xla_d=1.5 xla_e xla_f=text")
    assert flags == {"xla_a": True, "xla_b": False, "xla_c": 3,
                     "xla_d": 1.5, "xla_e": True, "xla_f": "text"}


def test_resolve_accepts_preset_string_dict():
    preset = xla_flags.resolve("latency-hiding")
    assert preset["xla_tpu_enable_latency_hiding_scheduler"] is True
    parsed = xla_flags.resolve("xla_x=2")
    assert parsed == {"xla_x": 2}
    passthru = xla_flags.resolve({"xla_y": False})
    assert passthru == {"xla_y": False}
    assert xla_flags.resolve(None) == {}
    with pytest.raises(TypeError):
        xla_flags.resolve(42)


def test_env_overlay_wins(monkeypatch):
    monkeypatch.setenv(xla_flags.ENV_VAR, "xla_x=9 xla_z=true")
    flags = xla_flags.resolve({"xla_x": 1, "xla_y": 2})
    assert flags == {"xla_x": 9, "xla_y": 2, "xla_z": True}
    monkeypatch.setenv(xla_flags.ENV_VAR, "no-latency-hiding")
    assert xla_flags.resolve(None) == \
        xla_flags.PRESETS["no-latency-hiding"]


def test_resolve_false_is_hard_off(monkeypatch):
    """False / "none" / "off" mean NO flags — and unlike None, the env
    overlay does not re-arm them (the A/B control arm must stay the
    control even under a runner's PADDLE_TPU_XLA_FLAGS)."""
    assert xla_flags.resolve(False) == {}
    assert xla_flags.resolve("none") == {}
    assert xla_flags.resolve("off") == {}
    monkeypatch.setenv(xla_flags.ENV_VAR, "xla_x=9")
    assert xla_flags.resolve(False) == {}
    assert xla_flags.resolve(None) == {"xla_x": 9}


def test_backend_accepts_probes_once():
    """The scan-default probe: CPU rejects the xla_tpu_* preset (judged
    by one trivial flagged compile), accepts an empty set trivially,
    and caches the verdict per flag set."""
    preset = xla_flags.PRESETS["latency-hiding"]
    assert xla_flags.backend_accepts(preset) is False
    key = tuple(sorted((k, str(v)) for k, v in preset.items()))
    assert xla_flags._BACKEND_ACCEPTS[key] is False
    assert xla_flags.backend_accepts({}) is True
    assert xla_flags.backend_accepts(
        {"xla_cpu_enable_xprof_traceme": True}) is True


def test_flagged_jit_unknown_flag_fallback():
    fj = xla_flags.jit(lambda x: x * 2,
                       xla_flags={"xla_tpu_enable_latency_hiding_scheduler":
                                  True})
    out = fj(np.float32(3.0))
    assert float(out) == 6.0
    assert fj.applied is False
    assert "No such compile option" in fj.fallback_error
    prov = fj.provenance()
    assert prov["applied"] is False and prov["flags"]


def test_flagged_jit_valid_flag_applies():
    fj = xla_flags.jit(lambda x: x + 1,
                       xla_flags={"xla_cpu_enable_xprof_traceme": True})
    assert float(fj(np.float32(1.0))) == 2.0
    assert fj.applied is True
    assert fj.provenance()["fallback_error"] is None


def test_flagged_jit_lower_compile_fallback():
    import jax
    fj = xla_flags.jit(lambda x: x * 3,
                       xla_flags={"xla_tpu_enable_latency_hiding_scheduler":
                                  True})
    compiled = fj.lower(jax.ShapeDtypeStruct((4,), np.float32)).compile()
    assert "f32[4]" in compiled.as_text()
    assert fj.applied is False


def test_flagged_jit_real_error_propagates():
    import jax.numpy as jnp
    fj = xla_flags.jit(lambda x: jnp.dot(x, jnp.zeros((3, 3))),  # shape err
                       xla_flags={"xla_x": True})
    with pytest.raises(Exception) as e:
        fj(np.zeros(4, np.float32))
    assert "No such compile option" not in str(e.value)


# -- StaticFunction surface (zero3 scan, 8-device mesh) --------------------

def _zero3_step(k=2):
    paddle.seed(7)
    m = nn.Sequential(nn.Linear(64, 128), nn.ReLU(), nn.Linear(128, 32))
    opt = paddle.optimizer.AdamW(parameters=m.parameters(),
                                 learning_rate=0.05)
    opt._zero_enable(axis="dp", stage=3)

    def one(xb, yb):
        loss = nn.functional.cross_entropy(m(xb), yb)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss
    x = paddle.to_tensor(rng.rand(k, 16, 64).astype("float32"))
    y = paddle.to_tensor(rng.randint(0, 32, (k, 16)).astype("int64"))
    return one, x, y


def test_static_function_overlap_stats(_mesh):
    one, x, y = _zero3_step()
    step = paddle.jit.to_static(one, scan_steps=2, dp_axis="dp")
    step(x, y)
    s = step.overlap_stats()
    # the zero3 step REALLY issues collectives; CPU schedules them sync
    assert s["sync_total"] > 0
    assert s["backend_sync_schedule"] is True
    assert s["collective_overlap_efficiency"] == 0.0
    assert {"all-gather", "reduce-scatter"} <= set(s["per_op"])
    assert all(p["axis"] == "dp" for p in s["pairs"])


def test_static_function_export_overlap_gauges(_mesh):
    obs_export.clear_gauges()
    one, x, y = _zero3_step()
    step = paddle.jit.to_static(one, scan_steps=2, dp_axis="dp")
    step(x, y)
    step.export_overlap_stats()
    g = obs_export.gauges()
    per_prog = [k for k in g if k.startswith(
        "collective_overlap_efficiency{") and "op=" not in k]
    assert per_prog and g[per_prog[0]] == 0.0
    assert any(k.startswith("exposed_collective_ns_estimate{")
               and 'axis="dp"' in k for k in g)
    assert any(k.startswith("collective_sync_total{") for k in g)
    assert any(k.startswith("collective_async_pairs_total{") for k in g)
    obs_export.clear_gauges()


def test_static_function_xla_flags_provenance(_mesh):
    one, x, y = _zero3_step()
    step = paddle.jit.to_static(one, scan_steps=2, dp_axis="dp",
                                xla_flags="latency-hiding")
    step(x, y)
    prov = step.xla_flags()
    assert prov["flags"] == xla_flags.PRESETS["latency-hiding"]
    assert prov["applied"] is False  # CPU rejects xla_tpu_* options
    assert "No such compile option" in prov["fallback_error"]
    # the fallback still produced a working program + introspection
    assert step.overlap_stats()["sync_total"] > 0


def test_static_function_no_flags_provenance(_mesh):
    one, x, y = _zero3_step()
    # xla_flags=False: the explicit opt-out (scan programs otherwise
    # DEFAULT to the latency-hiding preset where the backend takes it)
    step = paddle.jit.to_static(one, scan_steps=2, dp_axis="dp",
                                xla_flags=False)
    step(x, y)
    prov = step.xla_flags()
    assert prov == {"flags": {}, "applied": False,
                    "fallback_error": None}
    assert step._xla_flags_default_pending is False


def test_scan_default_latency_hiding_preset(_mesh, monkeypatch):
    """A scan program with no xla_flags defaults to the latency-hiding
    preset exactly when the backend registers it: on this CPU host the
    probe says no and the program compiles unflagged; with the probe
    forced to yes the preset attaches and provenance reports it."""
    one, x, y = _zero3_step()
    step = paddle.jit.to_static(one, scan_steps=2, dp_axis="dp")
    assert step._xla_flags_default_pending is True
    step(x, y)  # first build resolves the default via the probe
    assert step._xla_flags_default_pending is False
    assert step.xla_flags()["flags"] == {}  # CPU rejects xla_tpu_*

    monkeypatch.setattr(xla_flags, "backend_accepts", lambda flags: True)
    one2, x2, y2 = _zero3_step()
    step2 = paddle.jit.to_static(one2, scan_steps=2, dp_axis="dp")
    assert step2._xla_flags_default_pending is True
    step2(x2, y2)
    prov = step2.xla_flags()
    assert prov["flags"] == xla_flags.PRESETS["latency-hiding"]
    assert prov["applied"] is False  # ...and the compile still fell back
    # an explicit empty-ish request (False) or env flags suppress it
    step3 = paddle.jit.to_static(lambda v: v, scan_steps=2)
    assert step3._xla_flags_default_pending is True
    monkeypatch.setenv(xla_flags.ENV_VAR, "xla_x=1")
    step4 = paddle.jit.to_static(lambda v: v, scan_steps=2)
    assert step4._xla_flags_default_pending is False
    assert step4._xla_flags == {"xla_x": 1}


# -- gate direction pins ---------------------------------------------------

def test_gate_direction_pins():
    assert gate_mod.higher_is_better(
        {"metric": "mlp_zero3_overlap_efficiency", "unit": "frac"}) is True
    assert gate_mod.higher_is_better(
        {"metric": "mlp_zero3_exposed_collective_frac",
         "unit": "frac"}) is False
    # an explicit per-record pin still outranks the suffix
    assert gate_mod.higher_is_better(
        {"metric": "x_overlap_efficiency", "direction": "lower"}) is False


def test_gate_exposed_frac_regresses_upward():
    base = {"m_exposed_collective_frac":
            {"metric": "m_exposed_collective_frac", "value": 0.5,
             "unit": "frac", "backend": "cpu"}}
    worse = {"m_exposed_collective_frac":
             {"metric": "m_exposed_collective_frac", "value": 0.9,
              "unit": "frac", "backend": "cpu"}}
    ok, report = gate_mod.compare(base, worse)
    assert not ok and report[0]["status"] == "REGRESSION"
    better = {"m_exposed_collective_frac":
              {"metric": "m_exposed_collective_frac", "value": 0.2,
               "unit": "frac", "backend": "cpu"}}
    ok2, report2 = gate_mod.compare(base, better)
    assert ok2 and report2[0]["status"] == "IMPROVED"


def test_baseline_presence_pins_overlap_rows():
    baseline = gate_mod.load_results(
        os.path.join(REPO, "BASELINE_PERF.json"))
    for metric in ("mlp_zero3_overlap_efficiency",
                   "mlp_zero3_exposed_collective_frac"):
        assert metric in baseline
        assert baseline[metric]["gate"] == "presence"
    current = {m: dict(baseline[m]) for m in
               ("mlp_zero3_overlap_efficiency",
                "mlp_zero3_exposed_collective_frac")}
    ok, report = gate_mod.compare(
        {m: baseline[m] for m in current}, current)
    assert ok
    assert all(e["status"] == "PRESENT" for e in report)


# -- tools/overlap_view ----------------------------------------------------

def _overlap_view():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import overlap_view
    return overlap_view


def test_overlap_view_hlo_gantt(tmp_path, capsys):
    ov = _overlap_view()
    hlo = tmp_path / "step.hlo"
    hlo.write_text(ASYNC_FULL_HLO + SYNC_HLO.replace(
        "HloModule sync, is_scheduled=true", "").replace(
        "ENTRY %main", "%tail"))
    rc = ov.main(["--hlo", str(hlo)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "schedule timeline" in out
    assert "#" in out and "=" in out  # hidden + exposed bar cells
    assert "(async)" in out and "(sync)" in out


def test_overlap_view_diff_shape(tmp_path, capsys):
    ov = _overlap_view()
    a = {"programs": {"step": overlap.overlap_stats(SYNC_HLO)}}
    b = {"programs": {"step": overlap.overlap_stats(ASYNC_FULL_HLO)}}
    pa, pb = tmp_path / "off.json", tmp_path / "on.json"
    pa.write_text(json.dumps(a))
    pb.write_text(json.dumps(b))
    rc = ov.main(["--diff", str(pa), str(pb)])
    out = capsys.readouterr().out
    assert rc == 0
    lines = out.splitlines()
    assert "d_eff" in lines[1] and "d_exposed_us" in lines[1]
    row = [l for l in lines if l.startswith("step")][0]
    assert "+1.000" in row  # 0.0 -> 1.0 efficiency
    assert "0->1" in row  # async pair appeared


def test_overlap_view_diff_schedulable_delta(tmp_path, capsys):
    """Seeded prefetch-on/off captures: --diff must surface the
    schedulable-overlap delta per entry — for HLO-priced entries from
    ``schedulable_overlap``, and for ladder-twin entries (identity
    stand-in collectives, nothing priced) from the record-level
    ``sequence_schedulable`` the captures carry."""
    ov = _overlap_view()
    sa = overlap.overlap_stats(SYNC_HLO)
    sb = overlap.overlap_stats(ASYNC_FULL_HLO)
    twin = {"collective_overlap_efficiency": 0.0, "exposed_ns": 0.0,
            "exposed_collective_frac": 1.0, "async_pairs_total": 0,
            "sync_total": 0}
    a = {"programs": {"step": sa,
                      "zero3_twin": dict(twin, sequence_schedulable=0.5)}}
    b = {"programs": {"step": sb,
                      "zero3_twin": dict(twin, sequence_schedulable=1.0)}}
    pa, pb = tmp_path / "off.json", tmp_path / "on.json"
    pa.write_text(json.dumps(a))
    pb.write_text(json.dumps(b))
    rc = ov.main(["--diff", str(pa), str(pb)])
    out = capsys.readouterr().out
    assert rc == 0
    header = out.splitlines()[1]
    assert "sched(A)" in header and "d_sched" in header
    step = [l for l in out.splitlines() if l.startswith("step")][0]
    d = sb["schedulable_overlap"] - sa["schedulable_overlap"]
    assert f"{d:+.3f}" in step
    twin_row = [l for l in out.splitlines()
                if l.startswith("zero3_twin")][0]
    assert "0.500" in twin_row and "1.000" in twin_row
    assert "+0.500" in twin_row
    # the plain table view carries the sched column too
    assert "sched" in ov.format_program_table(
        {"zero3_twin": dict(twin, sequence_schedulable=1.0)})


def test_overlap_view_out_capture_roundtrip(tmp_path, capsys):
    ov = _overlap_view()
    hlo = tmp_path / "step.hlo"
    hlo.write_text(ASYNC_FULL_HLO)
    cap = tmp_path / "cap.json"
    rc = ov.main(["--hlo", str(hlo), "--out", str(cap)])
    capsys.readouterr()
    assert rc == 0
    data = json.loads(cap.read_text())
    (stats,) = data["programs"].values()
    assert stats["collective_overlap_efficiency"] == pytest.approx(1.0)


def test_overlap_view_trace_correlation(tmp_path, capsys):
    ov = _overlap_view()
    prof = tmp_path / "prof" / "plugins" / "profile" / "run1"
    prof.mkdir(parents=True)
    trace = {"traceEvents": [
        {"name": "all-gather-start.1", "dur": 5.0, "ph": "X"},
        {"name": "fusion.7", "dur": 100.0, "ph": "X"},
        {"name": "all-reduce.2", "dur": 2.5, "ph": "X"},
    ]}
    with gzip.open(prof / "host.trace.json.gz", "wt") as f:
        json.dump(trace, f)
    corr = ov.correlate_trace(str(tmp_path / "prof"),
                              {"collective_ns": 1000.0})
    assert corr["events"] == 2
    assert corr["measured_collective_ns"] == pytest.approx(7.5e3)
    assert corr["measured_over_estimate"] == pytest.approx(7.5)
    # empty dir reports "no spans", not a crash
    empty = tmp_path / "empty"
    empty.mkdir()
    assert ov.correlate_trace(str(empty), {"collective_ns": 1.0}) is None
    hlo = tmp_path / "step.hlo"
    hlo.write_text(SYNC_HLO)
    rc = ov.main(["--hlo", str(hlo), "--trace",
                  str(tmp_path / "prof")])
    out = capsys.readouterr().out
    assert rc == 0
    assert "trace correlation: measured collective wall-time" in out


def test_overlap_view_source_validation(capsys):
    ov = _overlap_view()
    with pytest.raises(SystemExit):
        ov.main([])
    capsys.readouterr()


# -- ladder attribution contract -------------------------------------------

@pytest.mark.slow
def test_ladder_attribute_overlap_zero3():
    from paddle_tpu.analysis import ladder
    rows = ladder.attribute_overlap(configs=["zero3"])["zero3"]
    assert rows
    for s in rows:
        assert "error" not in s, s
        # twins use identity stand-in collectives: honest zero report
        assert s["collective_overlap_efficiency"] == 0.0
        assert s["async_pairs_total"] == 0
