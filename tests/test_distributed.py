"""Distributed tests on the 8-device virtual CPU mesh.

Reference strategy (SURVEY.md §4): multi-process-on-localhost loss-parity
tests (test_dist_base.py check_with_place) + program-inspection tests for
meta-optimizers. TPU mapping: single-controller mesh; parity = sharded-vs-
single-device loss equality; inspection = sharding specs on params/opt state
and compiled HLO containing collectives.
"""
import numpy as np
import pytest

import jax
from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.distributed import fleet
import paddle_tpu.distributed as dist


@pytest.fixture(autouse=True)
def _reset_mesh():
    yield
    from paddle_tpu.distributed import parallel_env
    parallel_env.set_mesh(None)
    from paddle_tpu.distributed.fleet.base import topology
    topology.set_hybrid_communicate_group(None)


def _mlp(seed):
    paddle.seed(seed)
    return nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 4))


def test_dp_loss_parity():
    """Data-parallel training must match single-device training bit-for-bit
    math (the dist_mnist-style check)."""
    x = np.random.RandomState(0).rand(8, 16).astype("float32")
    y = np.random.RandomState(1).randint(0, 4, 8).astype("int64")

    def run(dp_degree):
        from paddle_tpu.distributed import parallel_env
        parallel_env.set_mesh(None)
        m = _mlp(7)
        if dp_degree > 1:
            strategy = fleet.DistributedStrategy()
            strategy.hybrid_configs = {"dp_degree": dp_degree, "mp_degree": 1,
                                       "pp_degree": 1, "sharding_degree": 1}
            fleet.init(is_collective=True, strategy=strategy)
            m = fleet.distributed_model(m)
        inner = m
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=m.parameters())

        def step(xb, yb):
            loss = nn.functional.cross_entropy(inner(xb), yb)
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss

        sfn = paddle.jit.to_static(step)
        if dp_degree > 1:
            sfn._arg_pspecs = [P("dp"), P("dp")]
        losses = []
        for _ in range(3):
            losses.append(float(sfn(paddle.to_tensor(x),
                                    paddle.to_tensor(y)).numpy()))
        return losses

    single = run(1)
    parallel = run(4)
    np.testing.assert_allclose(single, parallel, rtol=1e-5)


def test_mp_matches_unsharded():
    """Megatron column/row pair under GSPMD must equal the dense math."""
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 4,
                               "pp_degree": 1, "sharding_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)
    from paddle_tpu.distributed.fleet.meta_parallel import (
        ColumnParallelLinear, RowParallelLinear)

    paddle.seed(3)
    col = ColumnParallelLinear(16, 32, gather_output=False)
    row = RowParallelLinear(32, 8, input_is_parallel=True)

    x = paddle.to_tensor(np.random.RandomState(2).rand(4, 16).astype("float32"))

    def fwd(xb):
        return row(col(xb))

    out = paddle.jit.to_static(fwd)(x).numpy()
    ref = (x.numpy() @ col.weight.numpy() + col.bias.numpy()) \
        @ row.weight.numpy() + row.bias.numpy()
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


def test_mp_grads_match_unsharded():
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 4,
                               "pp_degree": 1, "sharding_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)
    from paddle_tpu.distributed.fleet.meta_parallel import ColumnParallelLinear

    paddle.seed(5)
    layer = ColumnParallelLinear(8, 16, gather_output=True)
    w0 = layer.weight.numpy().copy()
    x = np.random.RandomState(4).rand(4, 8).astype("float32")

    def step(xb):
        loss = layer(xb).square().mean()
        loss.backward()
        return loss

    sfn = paddle.jit.to_static(step)
    sfn(paddle.to_tensor(x))
    g_sharded = layer.weight.grad
    assert g_sharded is not None

    # dense reference
    xt = paddle.to_tensor(x)
    w = paddle.Parameter(w0)
    b = paddle.Parameter(layer.bias.numpy().copy())
    loss = (paddle.matmul(xt, w) + b).square().mean()
    loss.backward()
    np.testing.assert_allclose(np.asarray(g_sharded.numpy()),
                               w.grad.numpy(), rtol=1e-4, atol=1e-6)


def test_sharding_zero_specs_applied():
    """ZeRO: distributed_optimizer must shard opt state over dp — the
    flat stores carry PartitionSpec('dp', None) and live 1/8 per rank
    (program-inspection analog of sharding meta-optimizer tests)."""
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 8, "mp_degree": 1,
                               "pp_degree": 1, "sharding_degree": 1}
    strategy.sharding = True
    fleet.init(is_collective=True, strategy=strategy)
    m = nn.Linear(64, 64)
    opt = fleet.distributed_optimizer(
        paddle.optimizer.Adam(parameters=m.parameters()))
    zero = opt._inner._zero
    assert zero is not None and zero["axis"] == "dp"
    specs = [sd[s].tensor.pspec for sd in zero["stores"] for s in sd]
    assert specs and all(sp == P("dp", None) for sp in specs), specs

    # and the sharded step still trains correctly
    def step(xb):
        loss = m(xb).square().mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    sfn = paddle.jit.to_static(step)
    x = paddle.to_tensor(np.random.rand(8, 64).astype("float32"))
    l0 = float(sfn(x).numpy())
    for _ in range(3):
        l1 = float(sfn(x).numpy())
    assert l1 < l0


def test_dp_hlo_contains_allreduce():
    """The compiled dp train step must contain a gradient all-reduce
    (HLO-inspection: the c_allreduce_sum analog GSPMD inserts)."""
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 8, "mp_degree": 1,
                               "pp_degree": 1, "sharding_degree": 1}
    hcg = fleet.init(is_collective=True, strategy=strategy)
    m = nn.Linear(8, 8)
    for p in m.parameters():
        p.pspec = P()
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=m.parameters())

    import jax.numpy as jnp
    from jax.sharding import NamedSharding

    mesh = hcg.mesh
    w_val = m.weight._value

    def pure_step(w, xb):
        w = jax.lax.with_sharding_constraint(w, NamedSharding(mesh, P()))
        loss = jnp.square(xb @ w).mean()
        g = jax.grad(lambda wv: jnp.square(xb @ wv).mean())(w)
        return loss, w - 0.1 * g

    x = jax.device_put(np.random.rand(8, 8).astype("float32"),
                       NamedSharding(mesh, P("dp")))
    lowered = jax.jit(pure_step).lower(w_val, x)
    hlo = lowered.compile().as_text()
    assert "all-reduce" in hlo, "dp step must all-reduce gradients"


def test_collective_functional_in_shard_map():
    """The c_* functional API lowers to lax collectives inside shard_map."""
    mesh = dist.make_mesh({"dp": 8})
    dist.set_mesh(mesh)
    group = dist.new_group(axis_name="dp")

    from paddle_tpu.core.tensor import Tensor

    def body(x):
        t = Tensor(x)
        dist.all_reduce(t, group=group)
        return t._value

    out = jax.shard_map(body, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"))(
        np.arange(8, dtype=np.float32))
    np.testing.assert_allclose(np.asarray(out), np.full(8, 28.0))


def test_broadcast_and_p2p_in_shard_map():
    """broadcast is mask+psum (one copy over the wire); p2p_transfer moves
    src's shard to dst via one ppermute; send/recv raise loudly in SPMD."""
    mesh = dist.make_mesh({"dp": 8})
    dist.set_mesh(mesh)
    group = dist.new_group(axis_name="dp")

    from paddle_tpu.core.tensor import Tensor

    def bcast_body(x):
        t = Tensor(x)
        dist.broadcast(t, src=3, group=group)
        return t._value

    x = np.arange(8, dtype=np.float32)
    out = jax.shard_map(bcast_body, mesh=mesh, in_specs=P("dp"),
                        out_specs=P("dp"))(x)
    np.testing.assert_allclose(np.asarray(out), np.full(8, 3.0))

    def p2p_body(x):
        return dist.p2p_transfer(Tensor(x), src=2, dst=5, group=group)._value

    out = jax.shard_map(p2p_body, mesh=mesh, in_specs=P("dp"),
                        out_specs=P("dp"))(x)
    want = np.zeros(8, np.float32)
    want[5] = 2.0
    np.testing.assert_allclose(np.asarray(out), want)

    def send_body(x):
        dist.send(Tensor(x), dst=1, group=group)
        return x

    import pytest
    with pytest.raises(Exception, match="p2p_transfer"):
        jax.shard_map(send_body, mesh=mesh, in_specs=P("dp"),
                      out_specs=P("dp"))(x)


def test_pipeline_layer_segmentation():
    from paddle_tpu.distributed.fleet.meta_parallel import (
        LayerDesc, PipelineLayer)

    descs = [LayerDesc(nn.Linear, 8, 8) for _ in range(8)]
    pp = PipelineLayer(descs, num_stages=4)
    assert pp._segments == [0, 2, 4, 6, 8]
    x = paddle.to_tensor(np.random.rand(2, 8).astype("float32"))
    out = pp(x)
    assert out.shape == [2, 8]
    # stage-wise execution equals full execution
    h = x
    for s in range(4):
        h = pp.forward_stage(s, h)
    np.testing.assert_allclose(h.numpy(), out.numpy(), rtol=1e-6)


def test_pipeline_parallel_train_batch_matches_plain():
    """1F1B microbatch accumulation == one big batch (grad-accum parity)."""
    from paddle_tpu.distributed.fleet.meta_parallel import (
        LayerDesc, PipelineLayer, PipelineParallel)

    x = np.random.RandomState(0).rand(8, 8).astype("float32")
    y = np.random.RandomState(1).rand(8, 4).astype("float32")

    def loss_fn(out, label):
        return nn.functional.mse_loss(out, label)

    # pipeline with 4 microbatches
    paddle.seed(9)
    pp_layer = PipelineLayer([LayerDesc(nn.Linear, 8, 16),
                              LayerDesc(nn.Linear, 16, 4)],
                             num_stages=2, loss_fn=loss_fn)
    strategy = fleet.DistributedStrategy()
    strategy.pipeline_configs = {"accumulate_steps": 4,
                                 "micro_batch_size": 2,
                                 "schedule_mode": "1F1B"}
    pp = PipelineParallel(pp_layer, None, strategy)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=pp_layer.parameters())
    pp.train_batch((paddle.to_tensor(x), paddle.to_tensor(y)), opt)
    w_pp = pp_layer.layers[0].weight.numpy().copy()

    # plain single-batch reference
    paddle.seed(9)
    ref = nn.Sequential(nn.Linear(8, 16), nn.Linear(16, 4))
    opt2 = paddle.optimizer.SGD(learning_rate=0.1, parameters=ref.parameters())
    loss = loss_fn(ref(paddle.to_tensor(x)), paddle.to_tensor(y))
    loss.backward()
    opt2.step()
    np.testing.assert_allclose(w_pp, ref[0].weight.numpy(), rtol=1e-5)


def test_vocab_parallel_embedding_spec():
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 8,
                               "pp_degree": 1, "sharding_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)
    from paddle_tpu.distributed.fleet.meta_parallel import VocabParallelEmbedding
    emb = VocabParallelEmbedding(64, 16)
    assert emb.weight.pspec == P("mp", None)
    idx = paddle.to_tensor(np.array([[1, 5, 63]], np.int64))
    out = paddle.jit.to_static(lambda i: emb(i))(idx)
    np.testing.assert_allclose(out.numpy(), emb.weight.numpy()[[1, 5, 63]][None],
                               rtol=1e-6)


def test_rng_tracker():
    from paddle_tpu.distributed.fleet.meta_parallel import (
        get_rng_state_tracker, model_parallel_random_seed)
    model_parallel_random_seed(1234)
    tracker = get_rng_state_tracker()
    a = paddle.ops.rand([4]).numpy()
    with tracker.rng_state():
        b = paddle.ops.rand([4]).numpy()
    c = paddle.ops.rand([4]).numpy()
    assert not np.allclose(a, b)
    assert not np.allclose(a, c)


def test_topology_ranks():
    from paddle_tpu.distributed.fleet.base.topology import CommunicateTopology
    topo = CommunicateTopology(dims=(2, 2, 1, 2))
    assert topo.world_size() == 8
    r = topo.get_rank(data=1, pipe=0, sharding=0, model=1)
    coord = topo.get_coord(r)
    assert coord["data"] == 1 and coord["model"] == 1


def test_subgroup_ranks_rejected_in_shard_map():
    """Group(ranks=subset) inside shard_map cannot ride a full named-axis
    collective — must raise, not silently span the whole axis."""
    import jax
    import numpy as np
    import pytest
    from jax.sharding import PartitionSpec as P

    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist

    mesh = dist.make_mesh({"x": 4})
    g = dist.new_group(ranks=[0, 1], axis_name="x")

    def f(v):
        t = paddle.to_tensor(v)
        dist.all_reduce(t, group=g)
        return t._value if hasattr(t, "_value") else t

    with pytest.raises(NotImplementedError, match="proper subset"):
        jax.jit(jax.shard_map(
            f, mesh=mesh, in_specs=P("x"), out_specs=P("x")))(
                np.ones((4,), np.float32))
