"""Detection + sequence op families vs numpy references.

Mirrors reference OpTest cases: test_yolo_box_op.py, test_prior_box_op.py,
test_box_coder_op.py, test_multiclass_nms_op.py, test_roi_align_op.py,
test_sequence_* from fluid/tests/unittests.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.ops.sequence import (
    RaggedBatch, sequence_mask, sequence_pad, sequence_pool,
    sequence_reverse, sequence_softmax, sequence_unpad, sequence_expand,
)
from paddle_tpu.vision import ops as V


def test_yolo_box_shapes_and_range():
    np.random.seed(0)
    an, cls, H, W = 3, 4, 5, 5
    x = np.random.randn(2, an * (5 + cls), H, W).astype(np.float32)
    img = np.array([[320, 320], [640, 480]], np.int32)
    boxes, scores = V.yolo_box(paddle.to_tensor(x), paddle.to_tensor(img),
                               anchors=[10, 13, 16, 30, 33, 23],
                               class_num=cls, conf_thresh=0.01,
                               downsample_ratio=32)
    b = np.asarray(boxes.numpy())
    s = np.asarray(scores.numpy())
    assert b.shape == (2, H * W * an, 4)
    assert s.shape == (2, H * W * an, cls)
    # clipped to image bounds
    assert b[0, :, [0, 2]].max() <= 320 and b[0, :, [1, 3]].max() <= 320
    assert b.min() >= 0
    assert (s >= 0).all() and (s <= 1).all()


def test_yolo_box_decode_value():
    """Single-cell hand check of the decode math."""
    an, cls = 1, 1
    x = np.zeros((1, an * (5 + cls), 1, 1), np.float32)  # all logits 0
    img = np.array([[100, 100]], np.int32)
    boxes, scores = V.yolo_box(paddle.to_tensor(x), paddle.to_tensor(img),
                               anchors=[32, 32], class_num=cls,
                               conf_thresh=0.0, downsample_ratio=32,
                               clip_bbox=False)
    b = np.asarray(boxes.numpy())[0, 0]
    # sigmoid(0)=0.5 -> center (0.5, 0.5) of 1x1 grid -> 50px; exp(0)*32/32=1
    # -> w=h=100px -> box (0,0,100,100)
    np.testing.assert_allclose(b, [0.0, 0.0, 100.0, 100.0], atol=1e-4)
    np.testing.assert_allclose(np.asarray(scores.numpy())[0, 0],
                               [0.25], atol=1e-5)  # conf*cls = 0.5*0.5


def test_prior_box():
    inp = paddle.to_tensor(np.zeros((1, 8, 4, 4), np.float32))
    img = paddle.to_tensor(np.zeros((1, 3, 64, 64), np.float32))
    boxes, variances = V.prior_box(inp, img, min_sizes=[16.0],
                                   aspect_ratios=[1.0, 2.0], flip=True,
                                   clip=True)
    b = np.asarray(boxes.numpy())
    v = np.asarray(variances.numpy())
    assert b.shape == v.shape == (4, 4, 3, 4)  # ar 1, 2, 1/2
    assert b.min() >= 0 and b.max() <= 1
    # center of first cell = (0.5*16, 0.5*16) = (8, 8); min_size 16 square
    np.testing.assert_allclose(b[0, 0, 0], [0.0, 0.0, 1.0 / 4, 1.0 / 4],
                               atol=1e-6)
    np.testing.assert_allclose(v[0, 0, 0], [0.1, 0.1, 0.2, 0.2], atol=1e-7)


def test_box_coder_roundtrip():
    np.random.seed(1)
    priors = np.abs(np.random.rand(5, 4).astype(np.float32))
    priors[:, 2:] = priors[:, :2] + 0.5 + priors[:, 2:]
    targets = np.abs(np.random.rand(3, 4).astype(np.float32))
    targets[:, 2:] = targets[:, :2] + 0.5 + targets[:, 2:]
    var = np.full((5, 4), 0.5, np.float32)
    enc = V.box_coder(paddle.to_tensor(priors), paddle.to_tensor(var),
                      paddle.to_tensor(targets), "encode_center_size")
    dec = V.box_coder(paddle.to_tensor(priors), paddle.to_tensor(var),
                      enc, "decode_center_size")
    d = np.asarray(dec.numpy())
    for t in range(3):
        np.testing.assert_allclose(d[t, 0], targets[t], rtol=1e-4, atol=1e-4)


def test_nms():
    boxes = np.array([[0, 0, 10, 10], [1, 1, 11, 11], [50, 50, 60, 60]],
                     np.float32)
    scores = np.array([0.9, 0.8, 0.7], np.float32)
    keep = np.asarray(V.nms(paddle.to_tensor(boxes), 0.5,
                            paddle.to_tensor(scores)).numpy())
    assert list(keep) == [0, 2]  # box 1 overlaps box 0 heavily


def test_multiclass_nms():
    bboxes = np.array([[[0, 0, 10, 10], [1, 1, 11, 11], [50, 50, 60, 60]]],
                      np.float32)
    scores = np.zeros((1, 3, 3), np.float32)
    scores[0, 1] = [0.9, 0.85, 0.1]   # class 1
    scores[0, 2] = [0.05, 0.05, 0.8]  # class 2
    out, counts = V.multiclass_nms(paddle.to_tensor(bboxes),
                                   paddle.to_tensor(scores),
                                   score_threshold=0.3, nms_top_k=10,
                                   keep_top_k=10, nms_threshold=0.5)
    o = np.asarray(out.numpy())
    assert int(np.asarray(counts.numpy())[0]) == 2
    # highest: class1 box0 (0.9); then class2 box2 (0.8); class1 box1 suppressed
    assert o[0][0] == 1 and abs(o[0][1] - 0.9) < 1e-6
    assert o[1][0] == 2 and abs(o[1][1] - 0.8) < 1e-6


def test_roi_align_constant_field():
    """On a constant feature map every aligned bin must equal the constant."""
    x = np.full((1, 2, 8, 8), 3.0, np.float32)
    rois = np.array([[0, 0, 4, 4], [2, 2, 6, 6]], np.float32)
    out = V.roi_align(paddle.to_tensor(x), paddle.to_tensor(rois),
                      paddle.to_tensor(np.array([2], np.int32)),
                      output_size=2, spatial_scale=1.0)
    o = np.asarray(out.numpy())
    assert o.shape == (2, 2, 2, 2)
    np.testing.assert_allclose(o, 3.0, rtol=1e-5)


def test_roi_align_linear_field():
    """Bilinear interpolation reproduces a linear ramp exactly."""
    H = W = 8
    ramp = np.arange(W, dtype=np.float32)[None, None, None, :].repeat(H, 2)
    rois = np.array([[1.0, 1.0, 5.0, 5.0]], np.float32)
    out = V.roi_align(paddle.to_tensor(np.ascontiguousarray(ramp)),
                      paddle.to_tensor(rois),
                      paddle.to_tensor(np.array([1], np.int32)),
                      output_size=2, spatial_scale=1.0, sampling_ratio=2,
                      aligned=False)
    o = np.asarray(out.numpy())[0, 0]
    # bins span x in [1,3] and [3,5]; mean of linear ramp = bin center x
    np.testing.assert_allclose(o[0], [2.0, 4.0], rtol=1e-5)


def test_roi_align_grad():
    x = paddle.to_tensor(np.random.rand(1, 1, 6, 6).astype(np.float32))
    x.stop_gradient = False
    rois = paddle.to_tensor(np.array([[0, 0, 4, 4]], np.float32))
    out = V.roi_align(x, rois, paddle.to_tensor(np.array([1], np.int32)), 2)
    out.sum().backward()
    g = np.asarray(x.grad.numpy())
    assert g.shape == tuple(x.shape) and np.abs(g).sum() > 0


def test_distribute_fpn_proposals():
    rois = np.array([[0, 0, 10, 10], [0, 0, 100, 100], [0, 0, 500, 500]],
                    np.float32)
    outs, restore = V.distribute_fpn_proposals(
        paddle.to_tensor(rois), min_level=2, max_level=5, refer_level=4,
        refer_scale=224)
    sizes = [len(np.asarray(o.numpy())) for o in outs]
    assert sum(sizes) == 3 and len(outs) == 4
    r = np.asarray(restore.numpy())
    cat = np.concatenate([np.asarray(o.numpy()) for o in outs])[r]
    np.testing.assert_allclose(cat, rois)


# ---------------- sequence ops ----------------

def test_ragged_batch_roundtrip():
    rows = [np.arange(3, dtype=np.float32), np.arange(5, dtype=np.float32)]
    rb = RaggedBatch.from_list(rows, pad_value=-1.0)
    assert tuple(rb.data.shape) == (2, 5)
    back = rb.to_list()
    np.testing.assert_array_equal(back[0], rows[0])
    np.testing.assert_array_equal(back[1], rows[1])


def test_sequence_mask():
    m = sequence_mask(paddle.to_tensor(np.array([1, 3], np.int32)), maxlen=4)
    np.testing.assert_array_equal(np.asarray(m.numpy()),
                                  [[1, 0, 0, 0], [1, 1, 1, 0]])


def test_sequence_pad_unpad():
    rows = [np.ones((2, 3), np.float32), np.ones((4, 3), np.float32) * 2]
    padded, lens = sequence_pad(rows)
    assert tuple(padded.shape) == (2, 4, 3)
    back = sequence_unpad(padded, lens)
    assert back[0].shape == (2, 3) and back[1].shape == (4, 3)


def test_sequence_reverse_masked():
    x = np.array([[1, 2, 3, 0], [1, 2, 3, 4]], np.float32)
    lens = np.array([3, 4], np.int32)
    r = sequence_reverse(paddle.to_tensor(x), paddle.to_tensor(lens))
    np.testing.assert_allclose(np.asarray(r.numpy()),
                               [[3, 2, 1, 0], [4, 3, 2, 1]])


def test_sequence_softmax_masked():
    x = np.array([[1.0, 1.0, 1.0, 99.0]], np.float32)
    lens = np.array([3], np.int32)
    s = np.asarray(sequence_softmax(paddle.to_tensor(x),
                                    paddle.to_tensor(lens)).numpy())
    np.testing.assert_allclose(s[0, :3], [1 / 3] * 3, rtol=1e-5)
    assert s[0, 3] == 0


def test_sequence_pool_variants():
    x = np.array([[[1.0], [2.0], [9.0]], [[4.0], [5.0], [6.0]]], np.float32)
    lens = np.array([2, 3], np.int32)
    xt, lt = paddle.to_tensor(x), paddle.to_tensor(lens)
    np.testing.assert_allclose(
        np.asarray(sequence_pool(xt, lt, "sum").numpy()).ravel(), [3, 15])
    np.testing.assert_allclose(
        np.asarray(sequence_pool(xt, lt, "average").numpy()).ravel(),
        [1.5, 5.0])
    np.testing.assert_allclose(
        np.asarray(sequence_pool(xt, lt, "max").numpy()).ravel(), [2, 6])
    np.testing.assert_allclose(
        np.asarray(sequence_pool(xt, lt, "last").numpy()).ravel(), [2, 6])
    np.testing.assert_allclose(
        np.asarray(sequence_pool(xt, lt, "first").numpy()).ravel(), [1, 4])


def test_sequence_expand():
    x = np.array([[1.0, 2.0], [3.0, 4.0]], np.float32)
    out = sequence_expand(paddle.to_tensor(x),
                          paddle.to_tensor(np.array([2, 1], np.int64)))
    np.testing.assert_allclose(np.asarray(out.numpy()),
                               [[1, 2], [1, 2], [3, 4]])


def test_sequence_pool_grad():
    x = paddle.to_tensor(np.random.rand(2, 4, 3).astype(np.float32))
    x.stop_gradient = False
    lens = paddle.to_tensor(np.array([2, 4], np.int32))
    out = sequence_pool(x, lens, "average")
    out.sum().backward()
    g = np.asarray(x.grad.numpy())
    # padding positions receive zero grad
    assert np.all(g[0, 2:] == 0) and np.all(g[0, :2] != 0)
