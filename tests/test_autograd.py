"""Autograd engine tests (reference model: imperative BasicEngine tests,
`test_imperative_basic.py`)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import ops

rng = np.random.RandomState(3)


def test_simple_backward():
    x = paddle.to_tensor(rng.rand(3, 3).astype("float32"), stop_gradient=False)
    y = (x * x).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), 2 * x.numpy(), rtol=1e-6)


def test_chain_and_accumulate():
    w = paddle.Parameter(np.ones((2, 2), np.float32))
    x = paddle.to_tensor(np.ones((2, 2), np.float32))
    for _ in range(2):  # two backward passes accumulate
        loss = ops.matmul(x, w).sum()
        loss.backward()
    np.testing.assert_allclose(w.grad.numpy(), 2 * 2 * np.ones((2, 2)))
    w.clear_grad()
    assert w.grad is None


def test_stop_gradient_blocks():
    x = paddle.to_tensor(rng.rand(2, 2).astype("float32"), stop_gradient=False)
    d = x.detach()
    assert d.stop_gradient
    y = (x * 2).sum()
    y.backward()
    assert x.grad is not None


def test_no_grad_context():
    w = paddle.Parameter(np.ones((2,), np.float32))
    with paddle.no_grad():
        y = (w * 3).sum()
    assert y._tape_node is None
    y2 = (w * 3).sum()
    assert y2._tape_node is not None


def test_grad_api():
    x = paddle.to_tensor(np.array([2.0, 3.0], np.float32), stop_gradient=False)
    y = (x ** 2).sum()
    (gx,) = paddle.grad([y], [x])
    np.testing.assert_allclose(gx.numpy(), 2 * x.numpy())
    assert x.grad is None  # paddle.grad must not pollute .grad


def test_grad_unused():
    x = paddle.to_tensor(np.ones(2, np.float32), stop_gradient=False)
    z = paddle.to_tensor(np.ones(2, np.float32), stop_gradient=False)
    y = (x * 2).sum()
    with pytest.raises(RuntimeError):
        paddle.grad([y], [z])
    gz = paddle.grad([y], [z], allow_unused=True)
    assert gz[0] is None


def test_multi_output_op_grad():
    x = paddle.to_tensor(rng.rand(4).astype("float32"), stop_gradient=False)
    parts = ops.split(x, 2)
    loss = parts[0].sum() * 2 + parts[1].sum() * 3
    loss.backward()
    np.testing.assert_allclose(x.grad.numpy(), [2, 2, 3, 3])


def test_retain_graph():
    x = paddle.to_tensor(np.array([1.0], np.float32), stop_gradient=False)
    y = x * 3
    loss = y.sum()
    loss.backward(retain_graph=True)
    loss.backward(retain_graph=False)
    np.testing.assert_allclose(x.grad.numpy(), [6.0])


def test_non_leaf_grad_retention():
    x = paddle.to_tensor(np.ones(2, np.float32), stop_gradient=False)
    h = x * 2
    h.retain_grads()
    (h * 3).sum().backward()
    np.testing.assert_allclose(h.grad.numpy(), [3, 3])


def test_pylayer():
    from paddle_tpu.autograd import PyLayer

    class Double(PyLayer):
        @staticmethod
        def forward(ctx, x):
            ctx.save_for_backward(x)
            return x * 2

        @staticmethod
        def backward(ctx, grad):
            return grad * 2

    x = paddle.to_tensor(np.array([1.0, 2.0], np.float32),
                         stop_gradient=False)
    y = Double.apply(x)
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [2, 2])


def test_recompute():
    from paddle_tpu.distributed.fleet.utils import recompute

    w = paddle.Parameter(np.ones((3, 3), np.float32))
    x = paddle.to_tensor(rng.rand(2, 3).astype("float32"))

    def block(inp):
        return ops.matmul(inp, w).exp()

    # baseline
    out_ref = block(x)
    loss_ref = out_ref.sum()
    loss_ref.backward()
    g_ref = w.grad.numpy().copy()
    w.clear_grad()

    out = recompute(block, x)
    np.testing.assert_allclose(out.numpy(), out_ref.numpy(), rtol=1e-6)
    out.sum().backward()
    np.testing.assert_allclose(w.grad.numpy(), g_ref, rtol=1e-5)
