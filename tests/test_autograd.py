"""Autograd engine tests (reference model: imperative BasicEngine tests,
`test_imperative_basic.py`)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import ops
from paddle_tpu.core.tensor import Tensor

rng = np.random.RandomState(3)


def test_simple_backward():
    x = paddle.to_tensor(rng.rand(3, 3).astype("float32"), stop_gradient=False)
    y = (x * x).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), 2 * x.numpy(), rtol=1e-6)


def test_chain_and_accumulate():
    w = paddle.Parameter(np.ones((2, 2), np.float32))
    x = paddle.to_tensor(np.ones((2, 2), np.float32))
    for _ in range(2):  # two backward passes accumulate
        loss = ops.matmul(x, w).sum()
        loss.backward()
    np.testing.assert_allclose(w.grad.numpy(), 2 * 2 * np.ones((2, 2)))
    w.clear_grad()
    assert w.grad is None


def test_stop_gradient_blocks():
    x = paddle.to_tensor(rng.rand(2, 2).astype("float32"), stop_gradient=False)
    d = x.detach()
    assert d.stop_gradient
    y = (x * 2).sum()
    y.backward()
    assert x.grad is not None


def test_no_grad_context():
    w = paddle.Parameter(np.ones((2,), np.float32))
    with paddle.no_grad():
        y = (w * 3).sum()
    assert y._tape_node is None
    y2 = (w * 3).sum()
    assert y2._tape_node is not None


def test_grad_api():
    x = paddle.to_tensor(np.array([2.0, 3.0], np.float32), stop_gradient=False)
    y = (x ** 2).sum()
    (gx,) = paddle.grad([y], [x])
    np.testing.assert_allclose(gx.numpy(), 2 * x.numpy())
    assert x.grad is None  # paddle.grad must not pollute .grad


def test_grad_unused():
    x = paddle.to_tensor(np.ones(2, np.float32), stop_gradient=False)
    z = paddle.to_tensor(np.ones(2, np.float32), stop_gradient=False)
    y = (x * 2).sum()
    with pytest.raises(RuntimeError):
        paddle.grad([y], [z])
    gz = paddle.grad([y], [z], allow_unused=True)
    assert gz[0] is None


def test_multi_output_op_grad():
    x = paddle.to_tensor(rng.rand(4).astype("float32"), stop_gradient=False)
    parts = ops.split(x, 2)
    loss = parts[0].sum() * 2 + parts[1].sum() * 3
    loss.backward()
    np.testing.assert_allclose(x.grad.numpy(), [2, 2, 3, 3])


def test_retain_graph():
    x = paddle.to_tensor(np.array([1.0], np.float32), stop_gradient=False)
    y = x * 3
    loss = y.sum()
    loss.backward(retain_graph=True)
    loss.backward(retain_graph=False)
    np.testing.assert_allclose(x.grad.numpy(), [6.0])


def test_non_leaf_grad_retention():
    x = paddle.to_tensor(np.ones(2, np.float32), stop_gradient=False)
    h = x * 2
    h.retain_grads()
    (h * 3).sum().backward()
    np.testing.assert_allclose(h.grad.numpy(), [3, 3])


def test_pylayer():
    from paddle_tpu.autograd import PyLayer

    class Double(PyLayer):
        @staticmethod
        def forward(ctx, x):
            ctx.save_for_backward(x)
            return x * 2

        @staticmethod
        def backward(ctx, grad):
            return grad * 2

    x = paddle.to_tensor(np.array([1.0, 2.0], np.float32),
                         stop_gradient=False)
    y = Double.apply(x)
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [2, 2])


def test_recompute():
    from paddle_tpu.distributed.fleet.utils import recompute

    w = paddle.Parameter(np.ones((3, 3), np.float32))
    x = paddle.to_tensor(rng.rand(2, 3).astype("float32"))

    def block(inp):
        return ops.matmul(inp, w).exp()

    # baseline
    out_ref = block(x)
    loss_ref = out_ref.sum()
    loss_ref.backward()
    g_ref = w.grad.numpy().copy()
    w.clear_grad()

    out = recompute(block, x)
    np.testing.assert_allclose(out.numpy(), out_ref.numpy(), rtol=1e-6)
    out.sum().backward()
    np.testing.assert_allclose(w.grad.numpy(), g_ref, rtol=1e-5)


class TestCreateGraph:
    """paddle.grad(create_graph=True): differentiable backward (reference:
    imperative/partial_grad_engine.cc create_graph path)."""

    def test_second_order(self):
        x = Tensor(np.array([2.0, 3.0], np.float32), stop_gradient=False)
        y = (x * x * x).sum()
        (g,) = paddle.grad(y, [x], create_graph=True)
        np.testing.assert_allclose(g.numpy(), 3 * np.array([4.0, 9.0]))
        assert not g.stop_gradient
        (g2,) = paddle.grad(g.sum(), [x])
        np.testing.assert_allclose(g2.numpy(), 6 * np.array([2.0, 3.0]))

    def test_gradient_penalty_backward(self):
        """d/dw of ||dy/dx||^2 flows through .backward() into w.grad."""
        w = Tensor(np.array([1.0, 2.0], np.float32), stop_gradient=False)
        x = Tensor(np.array([3.0, 4.0], np.float32), stop_gradient=False)
        y = (w * x * x).sum()
        (gx,) = paddle.grad(y, [x], create_graph=True)  # 2 w x
        (gx * gx).sum().backward()  # sum 4 w^2 x^2 -> d/dw = 8 w x^2
        np.testing.assert_allclose(
            w.grad.numpy(), 8 * np.array([1.0, 2.0]) * np.array([9.0, 16.0]))

    def test_third_order(self):
        x = Tensor(np.array([2.0], np.float32), stop_gradient=False)
        y = (x * x * x * x).sum()  # x^4
        (g1,) = paddle.grad(y, [x], create_graph=True)   # 4x^3
        (g2,) = paddle.grad(g1.sum(), [x], create_graph=True)  # 12x^2
        (g3,) = paddle.grad(g2.sum(), [x])               # 24x
        np.testing.assert_allclose(g3.numpy(), [48.0])

    def test_create_graph_through_layers(self):
        paddle.seed(0)
        import paddle_tpu.nn as nn
        lin = nn.Linear(3, 1)
        x = Tensor(np.random.RandomState(0).rand(2, 3).astype(np.float32),
                   stop_gradient=False)
        y = paddle.tanh(lin(x)).sum()
        (gx,) = paddle.grad(y, [x], create_graph=True)
        penalty = (gx * gx).sum()
        penalty.backward()
        assert lin.weight._grad is not None
        assert np.isfinite(np.asarray(lin.weight._grad)).all()

    def test_create_graph_with_amp(self):
        """AMP-recorded ops must replay with their traced dtypes outside
        the auto_cast scope (caught by review)."""
        x = Tensor(np.random.RandomState(0).rand(2, 3).astype(np.float32),
                   stop_gradient=False)
        w = Tensor(np.random.RandomState(1).rand(3, 2).astype(np.float32),
                   stop_gradient=False)
        with paddle.amp.auto_cast(enable=True, dtype="bfloat16"):
            y = paddle.matmul(x, w).sum()
        (gx,) = paddle.grad(y, [x], create_graph=True)
        assert str(gx.dtype) == "float32"
        (gw,) = paddle.grad((gx * gx).sum(), [w])
        assert np.isfinite(np.asarray(gw.numpy())).all()

    def test_backward_frees_pure_fn(self):
        """retain_graph=False must drop the forward closure too, or every
        activation stays alive through it (caught by review)."""
        x = Tensor(np.ones(3, np.float32), stop_gradient=False)
        y = (x * x).sum()
        n = y._tape_node
        y.backward()
        assert n.vjp_fn is None and n.inputs == () and n.pure_fn is None

    def test_create_graph_retain_false_frees(self):
        """Explicit retain_graph=False frees the forward graph (memory
        contract); re-walking it for a second-order pass then fails loudly
        — which is why the default keeps it (retain_graph=create_graph,
        reference semantics)."""
        x = Tensor(np.ones(3, np.float32), stop_gradient=False)
        y = (x * x).sum()
        n = y._tape_node
        (g,) = paddle.grad(y, [x], create_graph=True, retain_graph=False)
        np.testing.assert_allclose(g.numpy(), [2.0, 2.0, 2.0])
        assert n.pure_fn is None and n.vjp_fn is None
        with pytest.raises(RuntimeError, match="freed"):
            paddle.grad(g.sum(), [x])
