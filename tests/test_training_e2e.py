"""End-to-end training (reference model: tests/book/ 'book' e2e suite +
test_mnist dygraph tests): LeNet must actually learn the synthetic MNIST."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.io import DataLoader
from paddle_tpu.vision.datasets import MNIST
from paddle_tpu.vision.models import LeNet


def test_lenet_learns():
    paddle.seed(1)
    train = MNIST(mode="train")
    loader = DataLoader(train, batch_size=64, shuffle=True, drop_last=True)
    model = LeNet()
    opt = paddle.optimizer.Adam(parameters=model.parameters(),
                                learning_rate=1e-3)

    @paddle.jit.to_static
    def step(x, y):
        logits = model(x)
        loss = nn.functional.cross_entropy(logits, y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss, logits

    first_loss = None
    last_acc = 0.0
    for epoch in range(2):
        for i, (x, y) in enumerate(loader):
            loss, logits = step(x, y)
            if first_loss is None:
                first_loss = float(loss.numpy())
            if i >= 20:
                break
        pred = logits.numpy().argmax(-1)
        last_acc = (pred == y.numpy().reshape(-1)).mean()
    assert float(loss.numpy()) < first_loss
    assert last_acc > 0.5, f"accuracy {last_acc} too low: model not learning"


def test_hapi_model_fit():
    paddle.seed(2)
    train = MNIST(mode="train")
    model = paddle.Model(LeNet())
    model.prepare(
        optimizer=paddle.optimizer.Adam(parameters=model.parameters()),
        loss=nn.CrossEntropyLoss(),
        metrics=paddle.metric.Accuracy())
    hist = model.fit(train, batch_size=128, epochs=1, verbose=0)
    res = model.evaluate(train, batch_size=256)
    assert "acc" in res
    assert res["acc"] > 0.3


def test_hapi_predict_save_load(tmp_path):
    model = paddle.Model(LeNet())
    model.prepare(optimizer=paddle.optimizer.SGD(
        learning_rate=0.1, parameters=model.parameters()),
        loss=nn.CrossEntropyLoss())
    ds = MNIST(mode="test")
    out = model.predict(ds, batch_size=64)
    assert out[0][0].shape[-1] == 10
    model.save(str(tmp_path / "ckpt"))
    model2 = paddle.Model(LeNet())
    model2.prepare(optimizer=paddle.optimizer.SGD(
        learning_rate=0.1, parameters=model2.parameters()),
        loss=nn.CrossEntropyLoss())
    model2.load(str(tmp_path / "ckpt"))
    for (k1, v1), (k2, v2) in zip(sorted(model.network.state_dict().items()),
                                  sorted(model2.network.state_dict().items())):
        np.testing.assert_allclose(v1.numpy(), v2.numpy())


def test_resnet18_smoke():
    from paddle_tpu.vision.models import resnet18
    m = resnet18(num_classes=10)
    x = paddle.to_tensor(np.random.rand(2, 3, 32, 32).astype("float32"))
    out = m(x)
    assert out.shape == [2, 10]
    loss = out.sum()
    loss.backward()
    assert m.conv1.weight.grad is not None
