"""Optimizer tests vs torch.optim references (reference model:
unittests/test_adam_op.py etc., but checked against torch semantics)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn

rng = np.random.RandomState(21)


def _run_steps(opt_cls, torch_cls, kwargs_mine, kwargs_torch, steps=5):
    import torch
    w0 = rng.rand(4, 3).astype("float32")
    x = rng.rand(8, 4).astype("float32")

    p = paddle.Parameter(w0.copy())
    opt = opt_cls(parameters=[p], **kwargs_mine)
    tp = torch.nn.Parameter(torch.tensor(w0.copy()))
    topt = torch_cls([tp], **kwargs_torch)

    for _ in range(steps):
        loss = paddle.matmul(paddle.to_tensor(x), p).square().mean()
        loss.backward()
        opt.step()
        opt.clear_grad()

        tloss = (torch.tensor(x) @ tp).square().mean()
        topt.zero_grad()
        tloss.backward()
        topt.step()

    np.testing.assert_allclose(p.numpy(), tp.detach().numpy(), rtol=2e-4,
                               atol=2e-5)


def test_sgd():
    import torch
    _run_steps(paddle.optimizer.SGD, torch.optim.SGD,
               {"learning_rate": 0.1}, {"lr": 0.1})


def test_momentum():
    import torch
    _run_steps(paddle.optimizer.Momentum, torch.optim.SGD,
               {"learning_rate": 0.1, "momentum": 0.9},
               {"lr": 0.1, "momentum": 0.9})


def test_adam():
    import torch
    _run_steps(paddle.optimizer.Adam, torch.optim.Adam,
               {"learning_rate": 0.01}, {"lr": 0.01})


def test_adamw():
    import torch
    _run_steps(paddle.optimizer.AdamW, torch.optim.AdamW,
               {"learning_rate": 0.01, "weight_decay": 0.1},
               {"lr": 0.01, "weight_decay": 0.1})


def test_rmsprop():
    import torch
    _run_steps(paddle.optimizer.RMSProp, torch.optim.RMSprop,
               {"learning_rate": 0.01, "rho": 0.9, "epsilon": 1e-8},
               {"lr": 0.01, "alpha": 0.9, "eps": 1e-8})


def test_adagrad():
    import torch
    _run_steps(paddle.optimizer.Adagrad, torch.optim.Adagrad,
               {"learning_rate": 0.05, "epsilon": 1e-10},
               {"lr": 0.05, "eps": 1e-10})


def test_weight_decay_l2():
    p = paddle.Parameter(np.ones((2,), np.float32))
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=[p],
                               weight_decay=paddle.L2Decay(0.5))
    (p * np.float32(0.0)).sum().backward()  # zero grad, decay only
    opt.step()
    np.testing.assert_allclose(p.numpy(), 1 - 0.1 * 0.5 * np.ones(2),
                               rtol=1e-6)


def test_lamb_runs():
    p = paddle.Parameter(rng.rand(3, 3).astype("float32"))
    opt = paddle.optimizer.Lamb(learning_rate=0.01, parameters=[p])
    before = p.numpy().copy()
    p.sum().backward()
    opt.step()
    assert not np.allclose(p.numpy(), before)


def test_optimizer_state_roundtrip():
    p = paddle.Parameter(rng.rand(2, 2).astype("float32"))
    opt = paddle.optimizer.Adam(parameters=[p])
    p.sum().backward()
    opt.step()
    opt.clear_grad()
    sd = opt.state_dict()
    opt2 = paddle.optimizer.Adam(parameters=[p])
    opt2.set_state_dict({k: v for k, v in sd.items()})
    m1 = opt._get_accumulator("moment1", p).numpy()
    m2 = opt2._get_accumulator("moment1", p).numpy()
    np.testing.assert_allclose(m1, m2)


@pytest.mark.parametrize("sched_fn,expected", [
    (lambda: paddle.optimizer.lr.StepDecay(0.1, step_size=2, gamma=0.1),
     [0.1, 0.1, 0.01]),
    (lambda: paddle.optimizer.lr.MultiStepDecay(0.1, milestones=[1, 2]),
     [0.1, 0.01, 0.001]),
    (lambda: paddle.optimizer.lr.ExponentialDecay(0.1, gamma=0.5),
     [0.1, 0.05, 0.025]),
])
def test_lr_schedulers(sched_fn, expected):
    sched = sched_fn()
    got = [sched.last_lr]
    for _ in range(len(expected) - 1):
        sched.step()
        got.append(sched.last_lr)
    np.testing.assert_allclose(got, expected, rtol=1e-6)


def test_linear_warmup():
    sched = paddle.optimizer.lr.LinearWarmup(
        learning_rate=0.1, warmup_steps=4, start_lr=0.0, end_lr=0.1)
    lrs = [sched.last_lr]
    for _ in range(5):
        sched.step()
        lrs.append(sched.last_lr)
    np.testing.assert_allclose(lrs[:5], [0.0, 0.025, 0.05, 0.075, 0.1],
                               rtol=1e-6)


def test_cosine_annealing():
    sched = paddle.optimizer.lr.CosineAnnealingDecay(0.1, T_max=10)
    assert abs(sched.last_lr - 0.1) < 1e-8
    for _ in range(10):
        sched.step()
    assert sched.last_lr < 1e-8


def test_noam():
    sched = paddle.optimizer.lr.NoamDecay(d_model=64, warmup_steps=10)
    lrs = []
    for _ in range(20):
        sched.step()
        lrs.append(sched.last_lr)
    peak = int(np.argmax(lrs))
    assert 8 <= peak + 1 <= 11  # peaks at warmup boundary


def test_fuse_accumulators_parity_and_state_dict():
    """Coalesced accumulator buffers must train bit-identically to
    per-param accumulators and round-trip through state_dict."""
    import paddle_tpu as paddle
    from paddle_tpu import nn

    def run(fused):
        paddle.seed(3)
        m = nn.Sequential(nn.Linear(8, 33), nn.Tanh(), nn.Linear(33, 5))
        opt = paddle.optimizer.AdamW(parameters=m.parameters(),
                                     learning_rate=1e-2,
                                     fuse_accumulators=fused)

        @paddle.jit.to_static
        def step(x):
            loss = m(x).square().mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss

        x = paddle.to_tensor(
            np.random.RandomState(0).rand(4, 8).astype("float32"))
        losses = [float(step(x).numpy()) for _ in range(6)]
        return losses, m, opt

    l0, _, _ = run(False)
    l1, m1, opt1 = run(True)
    np.testing.assert_allclose(l0, l1, rtol=1e-6)
    # state_dict materializes flat views per param and round-trips
    sd = opt1.state_dict()
    mom_keys = [k for k in sd if k.endswith(".moment1")]
    assert len(mom_keys) == 4  # 2 weights + 2 biases
    l2, m2, opt2 = run(True)
    sd2 = opt2.state_dict()
    mom_keys2 = [k for k in sd2 if k.endswith(".moment1")]
    # param auto-names differ between runs; identical training makes the
    # accumulator VALUES equal position-by-position
    for k1, k2 in zip(mom_keys, mom_keys2):
        np.testing.assert_allclose(sd2[k2].numpy(), sd[k1].numpy(),
                                   rtol=1e-6)
    # and a round-trip restore through set_state_dict sticks
    renamed = {k2: sd[k1] for k1, k2 in zip(mom_keys, mom_keys2)}
    opt2.set_state_dict(renamed)
    for k2 in mom_keys2:
        np.testing.assert_allclose(opt2.state_dict()[k2].numpy(),
                                   renamed[k2].numpy(), rtol=1e-6)


def test_fuse_accumulators_unsupported_compositions_raise():
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.distributed.fleet.meta_optimizers.gradient_merge import (
        GradientMergeOptimizer)

    m = nn.Linear(2, 2)
    opt = paddle.optimizer.Adam(parameters=m.parameters(),
                                fuse_accumulators=True)
    import pytest as _pytest
    with _pytest.raises(NotImplementedError):
        GradientMergeOptimizer(opt, k_steps=2)
    from paddle_tpu.distributed.fleet.meta_optimizers.sharding import (
        shard_optimizer_state)
    with _pytest.raises(NotImplementedError):
        shard_optimizer_state(opt, mesh=None)


def test_adamw_multi_precision_master_weights():
    """multi_precision (reference: adamw op's master-weight path): bf16
    params update through an fp32 master, so tiny updates that bf16
    rounding would swallow still accumulate."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import nn

    paddle.seed(0)
    m = nn.Linear(8, 8)
    m.to("bfloat16")
    opt = paddle.optimizer.AdamW(parameters=m.parameters(),
                                 learning_rate=1e-4, multi_precision=True)
    masters = [k for k in opt._accumulators if k[0] == "master"]
    assert len(masters) == 2  # weight + bias
    x = paddle.to_tensor(np.ones((4, 8), np.float32))
    w_before_master = np.asarray(
        opt._accumulators[("master", id(m.weight))]._value)
    for _ in range(3):
        out = m(x.astype("bfloat16"))
        loss = (out.astype("float32") ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
    master = np.asarray(opt._accumulators[("master", id(m.weight))]._value)
    # master moved in fp32 and param is its bf16 cast
    assert master.dtype == np.float32
    assert not np.array_equal(master, w_before_master)
    assert m.weight.dtype == paddle.bfloat16
    np.testing.assert_array_equal(
        np.asarray(m.weight._value.astype("float32")),
        np.asarray(paddle.to_tensor(master).astype("bfloat16")._value
                   .astype("float32")))
