"""OpTest sweep part 3: statistics/manipulation tail + linalg.

References: python/paddle/tensor/{stat,search,math,linalg}.py and the
corresponding operators/ kernels.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import linalg
from paddle_tpu.core.tensor import Tensor
from op_test import check_output, check_grad

rng = np.random.RandomState(9)

A23 = rng.rand(2, 3).astype("float32") + 0.1
A34 = rng.rand(3, 4).astype("float32")
V6 = rng.rand(6).astype("float32")
SQ = (rng.rand(3, 3).astype("float32") - 0.5)
SPD = (lambda m: (m @ m.T + 3 * np.eye(3)).astype("float32"))(
    rng.rand(3, 3).astype("float32"))
V3 = rng.rand(3).astype("float32")
V3b = rng.rand(3).astype("float32")

OPS = [
    ("median", paddle.median, np.median, [A23], {}, True),
    ("quantile", lambda x: paddle.quantile(x, 0.5),
     lambda x: np.quantile(x, 0.5).astype("float32"), [A23], {}, False),
    ("nanmean", paddle.nanmean, np.nanmean, [A23], {}, True),
    ("nansum", paddle.nansum, np.nansum, [A23], {}, True),
    ("diff", paddle.diff, lambda x: np.diff(x), [A23], {}, True),
    ("trace", paddle.trace, np.trace, [SQ], {}, True),
    ("kron", paddle.kron, np.kron, [A23, A34[:2, :2]], {}, True),
    ("outer", paddle.outer, np.outer, [V3, V3b], {}, True),
    ("cross", paddle.cross, lambda x, y: np.cross(x, y), [V3, V3b], {},
     True),
    ("diagonal", paddle.diagonal, lambda x: np.diagonal(x), [SQ], {}, True),
    ("rot90", paddle.rot90, lambda x: np.rot90(x), [SQ], {}, True),
    ("lerp", lambda x, y: paddle.lerp(x, y, 0.3),
     lambda x, y: x + 0.3 * (y - x), [V3, V3b], {}, True),
    ("trunc", paddle.trunc, np.trunc, [SQ * 4], {}, False),
    ("frac", paddle.frac, lambda x: x - np.trunc(x), [SQ * 4], {}, True),
    ("deg2rad", paddle.deg2rad, np.deg2rad, [A23 * 90], {}, True),
    ("rad2deg", paddle.rad2deg, np.rad2deg, [A23], {}, True),
    ("heaviside", paddle.heaviside, np.heaviside, [SQ, A34[:3, :3]], {},
     False),
    # linalg
    ("cholesky", linalg.cholesky, np.linalg.cholesky, [SPD], {}, True),
    ("inv", linalg.inv, np.linalg.inv, [SPD], {}, True),
    ("det", linalg.det, np.linalg.det, [SPD], {}, True),
    ("solve", linalg.solve, np.linalg.solve, [SPD, V3], {}, True),
    ("matrix_power", lambda x: linalg.matrix_power(x, 3),
     lambda x: np.linalg.matrix_power(x, 3), [SQ * 0.5], {}, True),
    ("pinv", linalg.pinv, np.linalg.pinv, [A23], {}, False),
    ("multi_dot", lambda a, b: linalg.multi_dot([a, b]),
     lambda a, b: a @ b, [A23, A34], {}, True),
]


@pytest.mark.parametrize("name,op,ref,inputs,kwargs",
                         [(n, o, r, i, k) for n, o, r, i, k, _ in OPS],
                         ids=[o[0] for o in OPS])
def test_output(name, op, ref, inputs, kwargs):
    check_output(op, ref, inputs, kwargs=kwargs, atol=1e-4, rtol=1e-4)


GRADS = [(n, o, i, k) for n, o, r, i, k, g in OPS if g]


@pytest.mark.parametrize("name,op,inputs,kwargs", GRADS,
                         ids=[g[0] for g in GRADS])
def test_grad(name, op, inputs, kwargs):
    check_grad(op, inputs, kwargs=kwargs)


class TestStructured:
    def test_kthvalue(self):
        v, idx = paddle.kthvalue(Tensor(V6), 2)
        s = np.sort(V6)
        np.testing.assert_allclose(np.asarray(v.numpy()), s[1])

    def test_mode(self):
        x = np.array([[1.0, 2.0, 2.0, 3.0], [4.0, 4.0, 4.0, 5.0]],
                     np.float32)
        v, idx = paddle.mode(Tensor(x))
        np.testing.assert_allclose(np.asarray(v.numpy()), [2.0, 4.0])

    def test_histogram_bincount(self):
        x = np.array([0, 1, 1, 2, 2, 2], np.int64)
        h = paddle.histogram(Tensor(x.astype(np.float32)), bins=3, min=0,
                             max=3)
        np.testing.assert_array_equal(np.asarray(h.numpy()), [1, 2, 3])
        b = paddle.bincount(Tensor(x))
        np.testing.assert_array_equal(np.asarray(b.numpy()), [1, 2, 3])

    def test_unique_consecutive(self):
        x = Tensor(np.array([1, 1, 2, 2, 2, 3, 1], np.int64))
        out, inv, counts = paddle.unique_consecutive(
            x, return_inverse=True, return_counts=True)
        np.testing.assert_array_equal(np.asarray(out.numpy()), [1, 2, 3, 1])
        np.testing.assert_array_equal(np.asarray(counts.numpy()),
                                      [2, 3, 1, 1])
        np.testing.assert_array_equal(np.asarray(inv.numpy()),
                                      [0, 0, 1, 1, 1, 2, 3])

    def test_searchsorted_take(self):
        seq = Tensor(np.array([1.0, 3.0, 5.0, 7.0], np.float32))
        vals = Tensor(np.array([2.0, 5.0], np.float32))
        out = paddle.searchsorted(seq, vals)
        np.testing.assert_array_equal(np.asarray(out.numpy()), [1, 2])
        x = Tensor(np.arange(6, dtype=np.float32).reshape(2, 3))
        t = paddle.take(x, Tensor(np.array([0, 4], np.int64)))
        np.testing.assert_allclose(np.asarray(t.numpy()), [0.0, 4.0])

    def test_svd_qr_eigh(self):
        u, s, vt = linalg.svd(Tensor(A34))
        rec = np.asarray(u.numpy()) @ np.diag(np.asarray(s.numpy())) @ \
            np.asarray(vt.numpy())
        np.testing.assert_allclose(rec, A34, rtol=1e-4, atol=1e-5)
        q, r = linalg.qr(Tensor(A34))
        np.testing.assert_allclose(np.asarray(q.numpy()) @
                                   np.asarray(r.numpy()), A34, rtol=1e-4,
                                   atol=1e-5)
        w, v = linalg.eigh(Tensor(SPD))
        np.testing.assert_allclose(np.sort(np.asarray(w.numpy())),
                                   np.sort(np.linalg.eigvalsh(SPD)),
                                   rtol=1e-4)

    def test_slogdet_rank_cond(self):
        out = linalg.slogdet(Tensor(SPD))
        sign, logabs = np.asarray(out.numpy())
        s0, l0 = np.linalg.slogdet(SPD)
        assert abs(sign - s0) < 1e-5 and abs(logabs - l0) < 1e-4
        assert int(np.asarray(linalg.matrix_rank(Tensor(SPD)).numpy())) == 3

    def test_triangular_and_cholesky_solve(self):
        L = np.linalg.cholesky(SPD).astype(np.float32)
        b = V3.reshape(3, 1)
        out = linalg.triangular_solve(Tensor(L), Tensor(b), upper=False)
        np.testing.assert_allclose(L @ np.asarray(out.numpy()), b,
                                   rtol=1e-4, atol=1e-5)
        out2 = linalg.cholesky_solve(Tensor(b), Tensor(L), upper=False)
        np.testing.assert_allclose(SPD @ np.asarray(out2.numpy()), b,
                                   rtol=1e-3, atol=1e-4)

    def test_lstsq(self):
        sol, _, rank, _ = linalg.lstsq(Tensor(A34[:, :2]), Tensor(V3))
        want = np.linalg.lstsq(A34[:, :2], V3, rcond=None)[0]
        np.testing.assert_allclose(np.asarray(sol.numpy()), want, rtol=1e-3,
                                   atol=1e-4)

    def test_cross_default_first_axis_of_3(self):
        a = rng.rand(3, 4).astype("float32")
        b = rng.rand(3, 4).astype("float32")
        out = paddle.cross(Tensor(a), Tensor(b))
        np.testing.assert_allclose(np.asarray(out.numpy()),
                                   np.cross(a, b, axis=0), rtol=1e-5)

    def test_unique_consecutive_axis(self):
        x = np.array([[1, 1], [1, 1], [2, 2], [1, 1]], np.int64)
        out = paddle.unique_consecutive(Tensor(x), axis=0)
        np.testing.assert_array_equal(np.asarray(out.numpy()),
                                      [[1, 1], [2, 2], [1, 1]])

    def test_histogram_dtype_int64(self):
        h = paddle.histogram(Tensor(np.array([1.0, 2.0], np.float32)),
                             bins=2, min=0, max=3)
        assert "int" in str(h.dtype)


def test_complex_ops_have_gradients():
    """conj/real/imag have grad kernels in the reference (conj_grad etc.);
    complex dtypes must be selected as differentiable by dispatch."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import ops

    z = paddle.to_tensor(np.array([1 + 2j, 3 - 1j], dtype=np.complex64))
    z.stop_gradient = False
    ops.real(z).backward()
    assert z.grad is not None
    np.testing.assert_allclose(z.grad.numpy(), [1 + 0j, 1 + 0j])

    z2 = paddle.to_tensor(np.array([1 + 2j, 3 - 1j], dtype=np.complex64))
    z2.stop_gradient = False
    ops.conj(z2).backward()
    assert z2.grad is not None
