"""Scan-compiled step program (to_static(fn, scan_steps=k)), persistent
XLA compile cache, and device-prefetch dataloading — the PR-2 perf stack.

The scan program must be OBSERVABLY identical to the python-unrolled
control: same per-inner-step losses from the same seed, same final
params, same @GRAD survival semantics through the carry.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import monitor, nn
from paddle_tpu.io import DataLoader, Dataset

rng = np.random.RandomState(11)


def _adamw_linear(seed=42):
    paddle.seed(seed)
    m = nn.Linear(4, 2)
    opt = paddle.optimizer.AdamW(parameters=m.parameters(),
                                 learning_rate=0.1)
    return m, opt


def test_scan_matches_unrolled_linear():
    k = 3
    xs = rng.rand(k, 8, 4).astype("float32")
    ys = rng.rand(k, 8, 2).astype("float32")

    m1, opt1 = _adamw_linear()

    @paddle.jit.to_static
    def unrolled(xb, yb):
        losses = []
        for i in range(k):
            loss = nn.functional.mse_loss(m1(xb[i]), yb[i])
            loss.backward()
            opt1.step()
            opt1.clear_grad()
            losses.append(loss)
        return losses

    ref = [float(l.numpy()) for l in
           unrolled(paddle.to_tensor(xs), paddle.to_tensor(ys))]

    m2, opt2 = _adamw_linear()

    def one(xb, yb):
        loss = nn.functional.mse_loss(m2(xb), yb)
        loss.backward()
        opt2.step()
        opt2.clear_grad()
        return loss

    sstep = paddle.jit.to_static(one, scan_steps=k)
    got = sstep(paddle.to_tensor(xs), paddle.to_tensor(ys)).numpy()
    assert got.shape == (k,)  # per-inner-step losses, [k]-stacked
    np.testing.assert_allclose(ref, got, rtol=1e-5)
    np.testing.assert_allclose(m1.weight.numpy(), m2.weight.numpy(),
                               rtol=1e-5)
    # the compiled program carries params + both AdamW moments (+ lr/beta
    # accumulators); the scan partition must say so
    assert sstep._last_partition["scan_steps"] == k
    assert len(sstep._last_partition["donated"]) >= 6


@pytest.mark.slow  # ~22 s (the k=2 UNROLL compile dominates); scan
# equivalence itself is tier-1-covered at toy scale in this file
def test_scan_matches_unrolled_bert_cpu_small():
    """Acceptance: scan-vs-unrolled loss equivalence on the CPU-small
    BERT config (k=2, same seed, allclose) — the bench.py program
    structure A/B in miniature."""
    import jax.lax as lax
    from paddle_tpu.models import (BertConfig, BertForPretraining,
                                   synthetic_mlm_batch)

    k, batch, seq = 2, 2, 64
    cfg_kw = dict(vocab_size=512, hidden_size=64, num_layers=2, num_heads=4,
                  intermediate_size=128, max_position_embeddings=seq,
                  hidden_dropout=0.0, attention_dropout=0.0)
    ids, tok, labels, nsp = synthetic_mlm_batch(batch, seq, vocab_size=512)
    stack = lambda a: np.broadcast_to(a, (k,) + a.shape).copy()

    def build():
        paddle.seed(0)
        model = BertForPretraining(BertConfig(**cfg_kw))
        opt = paddle.optimizer.AdamW(parameters=model.parameters(),
                                     learning_rate=1e-3)
        params = list(model.parameters())

        def one_step(i, t, l, n):
            with paddle.amp.auto_cast(enable=True, dtype="bfloat16"):
                logits, nsp_logits = model(i, t)
                loss = model.loss(logits, nsp_logits, l, n)
            loss.backward()
            withg = [p for p in params if p._grad is not None]
            barred = lax.optimization_barrier(
                tuple(p._grad for p in withg))
            for p, v in zip(withg, barred):
                p._grad = v
            opt.step()
            opt.clear_grad()
            return loss

        return model, one_step

    model_u, one_u = build()

    @paddle.jit.to_static
    def unrolled(i, t, l, n):
        return [one_u(i, t, l, n) for _ in range(k)]

    ref = [float(x.numpy()) for x in unrolled(
        *(paddle.to_tensor(a) for a in (ids, tok, labels, nsp)))]

    model_s, one_s = build()
    sstep = paddle.jit.to_static(one_s, scan_steps=k)
    got = sstep(*(paddle.to_tensor(stack(a))
                  for a in (ids, tok, labels, nsp))).numpy()
    np.testing.assert_allclose(ref, got, rtol=2e-3)
    for pu, ps in zip(model_u.parameters(), model_s.parameters()):
        np.testing.assert_allclose(np.asarray(pu.numpy(), np.float32),
                                   np.asarray(ps.numpy(), np.float32),
                                   rtol=2e-3, atol=1e-5)


def test_scan_grad_accumulation_survives_carry():
    """@GRAD survival: a grad accumulated (not consumed) inside the body
    threads through the scan carry and keeps accumulating across
    program calls — the persistable-@GRAD semantics of the reference."""
    k = 4
    xs = rng.rand(k, 5, 3).astype("float32")

    paddle.seed(1)
    m1 = nn.Linear(3, 2)
    for i in range(k):
        m1(paddle.to_tensor(xs[i])).mean().backward()
    g_eager = m1.weight.grad.numpy()

    paddle.seed(1)
    m2 = nn.Linear(3, 2)

    def one(xb):
        loss = m2(xb).mean()
        loss.backward()
        return loss

    sstep = paddle.jit.to_static(one, scan_steps=k)
    sstep(paddle.to_tensor(xs))
    np.testing.assert_allclose(g_eager, m2.weight.grad.numpy(), rtol=1e-5)
    # grads live across program calls: a second scan doubles them
    sstep(paddle.to_tensor(xs))
    np.testing.assert_allclose(2 * g_eager, m2.weight.grad.numpy(),
                               rtol=1e-5)


def test_scan_rng_advances_per_inner_step():
    paddle.seed(3)
    drop = nn.Dropout(0.5)
    k = 4
    d = paddle.jit.to_static(lambda xb: drop(xb), scan_steps=k)
    outs = d(paddle.to_tensor(np.ones((k, 2, 16), np.float32))).numpy()
    masks = {tuple((outs[i] != 0).ravel()) for i in range(k)}
    assert len(masks) > 1, "dropout masks identical across inner steps"


def test_scan_rejects_unstacked_inputs():
    m = nn.Linear(4, 2)
    step = paddle.jit.to_static(lambda x: m(x).mean(), scan_steps=3)
    with pytest.raises(ValueError, match=r"stacked \[k, \.\.\.\]"):
        step(paddle.to_tensor(rng.rand(8, 4).astype("float32")))


def test_scan_steps_validation():
    with pytest.raises(ValueError, match="scan_steps"):
        paddle.jit.to_static(lambda x: x, scan_steps=0)


# -- persistent compile cache ----------------------------------------------

def test_persistent_cache_warm_start(tmp_path):
    """Acceptance: with the persistent cache on, a second StaticFunction
    over the same fn hits the disk cache instead of re-running the
    backend compile (restart-shaped workload, one process)."""
    from paddle_tpu.jit import compile_cache

    def fn(x):
        return (x * 2.0 + 1.0).sum()

    x = paddle.to_tensor(rng.rand(16, 16).astype("float32") + 7.0)
    compile_cache.enable(str(tmp_path / "xla"), min_compile_time_secs=0)
    try:
        for c in ("jit_persistent_cache_hits",
                  "jit_persistent_cache_misses"):
            monitor.stat_reset(c)
        cold = paddle.jit.to_static(fn)
        cold(x)
        assert monitor.stat_get("jit_persistent_cache_misses") >= 1
        misses_after_cold = monitor.stat_get("jit_persistent_cache_misses")
        warm = paddle.jit.to_static(fn)  # fresh StaticFunction + jax.jit
        warm(x)
        assert monitor.stat_get("jit_persistent_cache_hits") >= 1
        # the warm build added no new backend compiles to the cache
        assert (monitor.stat_get("jit_persistent_cache_misses")
                == misses_after_cold)
        assert compile_cache.is_enabled()
        assert compile_cache.cache_dir() == str(tmp_path / "xla")
    finally:
        compile_cache.disable()


def test_compile_cache_env_policy(monkeypatch):
    from paddle_tpu.jit import compile_cache

    monkeypatch.setenv("PADDLE_TPU_COMPILE_CACHE", "off")
    assert compile_cache.configure_from_env() is False
    monkeypatch.setenv("PADDLE_TPU_COMPILE_CACHE", "1")
    assert compile_cache.configure_from_env() is True
    monkeypatch.delenv("PADDLE_TPU_COMPILE_CACHE")
    monkeypatch.setenv("PADDLE_TPU_COMPILE_CACHE_DIR", "/tmp/x")
    assert compile_cache.configure_from_env() is True
    # restore the ambient policy for the rest of the suite
    monkeypatch.delenv("PADDLE_TPU_COMPILE_CACHE_DIR")
    compile_cache._state["policy"] = None


# -- stacked-batch device prefetch -----------------------------------------

class _PairDataset(Dataset):
    def __init__(self, n):
        self.n = n

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        return (np.full((3, 2), i, np.float32),
                np.int64(i))


def test_stacked_prefetch_to_device_round_trip():
    """Acceptance: DataLoader(stack_steps=k, prefetch_to_device=True)
    yields [k, batch, ...] device-resident batches whose shapes, dtypes
    and values round-trip exactly."""
    import jax

    k, bs, n = 3, 2, 14
    loader = DataLoader(_PairDataset(n), batch_size=bs, shuffle=False,
                        stack_steps=k, prefetch_to_device=True)
    assert len(loader) == (n // bs) // k  # incomplete k-groups drop
    seen = 0
    idx = 0
    for feats, labels in loader:
        assert tuple(feats.shape) == (k, bs, 3, 2)
        assert tuple(labels.shape) == (k, bs)
        assert str(feats.dtype) in ("float32", "paddle.float32")
        # device-resident: the leaf value is a committed jax array
        assert isinstance(feats._value, jax.Array)
        for s in range(k):
            for b in range(bs):
                assert float(feats.numpy()[s, b, 0, 0]) == idx
                assert int(labels.numpy()[s, b]) == idx
                idx += 1
        seen += 1
    assert seen == len(loader)


def test_stack_steps_without_device_prefetch():
    k, bs, n = 2, 2, 8
    loader = DataLoader(_PairDataset(n), batch_size=bs, stack_steps=k)
    batches = list(loader)
    assert len(batches) == 2
    feats, labels = batches[0]
    assert tuple(feats.shape) == (k, bs, 3, 2)
    np.testing.assert_array_equal(labels.numpy(), [[0, 1], [2, 3]])


def test_stack_steps_implies_drop_last():
    """A smaller trailing batch must never land inside a k-group: 10
    samples / batch 4 leaves a 2-sample tail that would break np.stack —
    stack_steps forces drop_last so stacking always sees uniform
    shapes."""
    loader = DataLoader(_PairDataset(10), batch_size=4, stack_steps=2)
    assert loader.drop_last
    (batches,) = list(loader)  # [4,4] stack; the 2-sample tail dropped
    assert tuple(batches[0].shape) == (2, 4, 3, 2)


class _DictDataset(Dataset):
    def __len__(self):
        return 8

    def __getitem__(self, i):
        return {"x": np.full((2,), i, np.float32), "y": np.int64(i)}


def test_stack_steps_nested_containers():
    loader = DataLoader(_DictDataset(), batch_size=2, stack_steps=2)
    batch = next(iter(loader))
    assert tuple(batch["x"].shape) == (2, 2, 2)
    assert tuple(batch["y"].shape) == (2, 2)
    np.testing.assert_array_equal(batch["y"].numpy(), [[0, 1], [2, 3]])


def test_scan_program_consumes_dataloader_stacks():
    """End-to-end: stacked loader batches feed a scan-compiled step."""
    k, bs = 2, 2
    paddle.seed(5)
    m = nn.Linear(6, 2)
    opt = paddle.optimizer.SGD(learning_rate=0.05,
                               parameters=m.parameters())

    def one(feats, labels):
        loss = nn.functional.mse_loss(
            m(feats.reshape([bs, 6])),
            paddle.cast(labels, "float32").reshape([bs, 1]).expand([bs, 2]))
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    step = paddle.jit.to_static(one, scan_steps=k)
    loader = DataLoader(_PairDataset(8), batch_size=bs, stack_steps=k,
                        prefetch_to_device=True)
    losses = []
    for feats, labels in loader:
        losses.extend(step(feats, labels).numpy().tolist())
    assert len(losses) == 4 and all(np.isfinite(losses))
