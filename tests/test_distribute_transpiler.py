"""Static-program PS transpilation (reference:
`transpiler/distribute_transpiler.py:256` + the legacy
`fluid/incubate/fleet/parameter_server` API; driven the way
test_dist_transpiler.py + test_dist_base.py exercise the reference:
transpile, serve, train the trainer half, loss parity vs local)."""
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.static as static

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _build_program(seed=0, optimizer="sgd"):
    paddle.seed(seed)
    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", [None, 4], "float32")
        y = static.data("y", [None, 1], "float32")
        w = static.create_parameter([4, 8], "float32", name="w")
        w2 = static.create_parameter([8, 1], "float32", name="w2")
        h = paddle.ops.matmul(x, w)
        out = paddle.ops.matmul(paddle.nn.functional.relu(h), w2)
        loss = ((out - y) ** 2).mean()
        opt = (paddle.optimizer.SGD(learning_rate=0.1)
               if optimizer == "sgd"
               else paddle.optimizer.Adam(learning_rate=0.05))
        opt.minimize(loss)
    return prog, loss


def _batches(n, seed=5):
    rng = np.random.RandomState(seed)
    w_true = np.random.RandomState(1).randn(4, 1).astype(np.float32)
    out = []
    for _ in range(n):
        x = rng.rand(8, 4).astype(np.float32)
        out.append((x, x @ w_true))
    return out


def _train_local(steps, optimizer="sgd"):
    prog, loss = _build_program(optimizer=optimizer)
    exe = static.Executor()
    losses = []
    for x, y in _batches(steps):
        (lv,) = exe.run(prog, feed={"x": x, "y": y}, fetch_list=[loss])
        losses.append(float(np.asarray(lv)))
    return losses


_SERVER_SCRIPT = """
import sys
import jax; jax.config.update('jax_platforms', 'cpu')
import numpy as np
import paddle_tpu as paddle
import paddle_tpu.static as static
sys.path.insert(0, %r)
import test_distribute_transpiler as T
out = getattr(T, %r)(optimizer=%r)\nprog, loss = out[0], out[1]
t = static.DistributeTranspiler()
t.transpile(trainer_id=0, program=prog, pservers="127.0.0.1:%%d" %% int(sys.argv[1]),
            trainers=1)
srv = t.get_pserver_program("127.0.0.1:" + sys.argv[1])
srv.start()
print("SERVER_READY", flush=True)
srv.run_server()
"""


def _build_bn_program(seed=0, optimizer="sgd"):
    paddle.seed(seed)
    prog = static.Program()
    bn = paddle.nn.BatchNorm1D(4)
    with static.program_guard(prog):
        x = static.data("x", [None, 4], "float32")
        w = static.create_parameter([4, 1], "float32", name="w")
        h = bn(x)
        loss = (paddle.ops.matmul(h, w) ** 2).mean()
        paddle.optimizer.SGD(learning_rate=0.01).minimize(loss)
    return prog, loss, bn


class TestDistributeTranspiler:
    def _spawn_server(self, port, optimizer="sgd",
                      builder="_build_program"):
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        env["JAX_PLATFORMS"] = "cpu"
        script = _SERVER_SCRIPT % (os.path.join(REPO, "tests"), builder,
                                   optimizer)
        p = subprocess.Popen([sys.executable, "-c", script, str(port)],
                             stdout=subprocess.PIPE,
                             stderr=subprocess.PIPE, text=True, env=env,
                             cwd=REPO)
        line = p.stdout.readline()
        assert "SERVER_READY" in line, line + p.stderr.read()[-2000:]
        return p

    @pytest.mark.parametrize("optimizer", ["sgd", "adam"])
    def test_trainer_program_loss_parity_vs_local(self, optimizer):
        """exe.run(trainer_program) against a live pserver must produce
        the SAME losses as the untranspiled local program (single
        trainer, sync mode) — the transpile is a placement change, not a
        numerics change."""
        from test_parameter_server import _free_port

        local = _train_local(12, optimizer=optimizer)

        port = _free_port()
        srv = self._spawn_server(port, optimizer=optimizer)
        try:
            prog, loss = _build_program(optimizer=optimizer)
            t = static.DistributeTranspiler()
            t.transpile(trainer_id=0, program=prog,
                        pservers=f"127.0.0.1:{port}", trainers=1)
            trainer_prog = t.get_trainer_program()
            assert trainer_prog._optimizer is None  # update moved away
            exe = static.Executor()
            exe.run(t.get_startup_program())
            losses = []
            for x, y in _batches(12):
                (lv,) = exe.run(trainer_prog, feed={"x": x, "y": y},
                                fetch_list=[loss])
                losses.append(float(np.asarray(lv)))
            np.testing.assert_allclose(losses, local, rtol=2e-4)
            assert np.mean(losses[-3:]) < np.mean(losses[:3])
        finally:
            if trainer_prog._ps_ctx is not None:
                trainer_prog._ps_ctx.stop()
            srv.wait(timeout=30)
            if srv.poll() is None:
                srv.kill()

    def test_transpile_requires_optimizer_and_endpoints(self):
        prog, loss = _build_program()
        t = static.DistributeTranspiler()
        with pytest.raises(ValueError, match="endpoint"):
            t.transpile(0, program=prog, pservers="")
        prog2 = static.Program()
        with pytest.raises(RuntimeError, match="optimizer"):
            static.DistributeTranspiler().transpile(
                0, program=prog2, pservers="127.0.0.1:1")

    def test_adamw_rejected_loudly(self):
        paddle.seed(0)
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [None, 4], "float32")
            w = static.create_parameter([4, 1], "float32", name="w")
            loss = paddle.ops.matmul(x, w).mean()
            paddle.optimizer.AdamW(learning_rate=0.1).minimize(loss)
        with pytest.raises(NotImplementedError, match="AdamW"):
            static.DistributeTranspiler().transpile(
                0, program=prog, pservers="127.0.0.1:1")


class TestFleet1xFacade:
    def test_legacy_flow_worker_side(self):
        """The fleet-1.x call shape drives the transpiler end-to-end
        (reference: incubate/fleet/parameter_server usage)."""
        from test_parameter_server import _free_port

        from paddle_tpu.incubate.fleet import fleet

        port = _free_port()
        srv = TestDistributeTranspiler()._spawn_server(port)
        old_env = {}
        try:
            for k, v in {
                    "TRAINING_ROLE": "TRAINER",
                    "PADDLE_TRAINER_ID": "0",
                    "PADDLE_TRAINERS_NUM": "1",
                    "PADDLE_PSERVER_ENDPOINTS": f"127.0.0.1:{port}",
            }.items():
                old_env[k] = os.environ.get(k)
                os.environ[k] = v
            from paddle_tpu.distributed.fleet.base.role_maker import \
                PaddleCloudRoleMaker
            fleet.init(PaddleCloudRoleMaker(is_collective=False))
            assert fleet.is_worker() and not fleet.is_server()
            prog, loss = _build_program()
            # legacy shape: wrap the (already-minimized) optimizer; the
            # facade transpiles on minimize, so rebuild with the wrapper
            paddle.seed(0)
            prog = static.Program()
            with static.program_guard(prog):
                x = static.data("x", [None, 4], "float32")
                y = static.data("y", [None, 1], "float32")
                w = static.create_parameter([4, 8], "float32", name="w")
                w2 = static.create_parameter([8, 1], "float32",
                                             name="w2")
                h = paddle.ops.matmul(x, w)
                out = paddle.ops.matmul(paddle.nn.functional.relu(h), w2)
                loss = ((out - y) ** 2).mean()
                opt = fleet.distributed_optimizer(
                    paddle.optimizer.SGD(learning_rate=0.1))
                opt.minimize(loss)
            fleet.init_worker()
            exe = static.Executor()
            losses = []
            for xb, yb in _batches(8):
                (lv,) = exe.run(fleet.main_program(),
                                feed={"x": xb, "y": yb},
                                fetch_list=[loss])
                losses.append(float(np.asarray(lv)))
            assert losses[-1] < losses[0]
            fleet.stop_worker()
        finally:
            for k, v in old_env.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
            srv.wait(timeout=30)
            if srv.poll() is None:
                srv.kill()


class TestTranspilerEdgeCases:
    def test_bn_running_stats_update_through_transpiled_program(self):
        """BatchNorm running stats must keep moving on the transpiled
        trainer exactly like the local executor's buffer write-back."""
        from test_parameter_server import _free_port

        prog, loss, bn = _build_bn_program()
        assert prog._buffer_updates  # BN recorded its stat updates
        port = _free_port()
        srv = TestDistributeTranspiler()._spawn_server(
            port, builder="_build_bn_program")
        try:
            t = static.DistributeTranspiler()
            t.transpile(0, program=prog, pservers=f"127.0.0.1:{port}",
                        trainers=1)
            exe = static.Executor()
            rm_before = np.asarray(bn._mean.numpy()).copy()
            rng = np.random.RandomState(0)
            for _ in range(3):
                exe.run(t.get_trainer_program(),
                        feed={"x": rng.rand(8, 4).astype(np.float32)
                              + 3.0},
                        fetch_list=[loss])
            rm_after = np.asarray(bn._mean.numpy())
            assert not np.allclose(rm_after, rm_before), \
                "running_mean frozen on the transpiled path"
        finally:
            if prog._ps_ctx is not None:
                prog._ps_ctx.stop()
            srv.wait(timeout=30)
            if srv.poll() is None:
                srv.kill()

    def test_lr_scheduler_rejected_loudly(self):
        paddle.seed(0)
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [None, 4], "float32")
            w = static.create_parameter([4, 1], "float32", name="w")
            loss = (paddle.ops.matmul(x, w) ** 2).mean()
            sched = paddle.optimizer.lr.StepDecay(learning_rate=0.1,
                                                  step_size=2)
            paddle.optimizer.SGD(learning_rate=sched).minimize(loss)
        with pytest.raises(NotImplementedError, match="LRScheduler"):
            static.DistributeTranspiler().transpile(
                0, program=prog, pservers="127.0.0.1:1")


_WORKER_SCRIPT = """
import os, sys
import jax; jax.config.update('jax_platforms', 'cpu')
import numpy as np
import paddle_tpu as paddle
import paddle_tpu.static as static
sys.path.insert(0, %r)
import test_distribute_transpiler as T
wid = int(os.environ["PADDLE_TRAINER_ID"])
prog, loss = T._build_program()
t = static.DistributeTranspiler()
t.transpile(trainer_id=wid, program=prog,
            pservers=os.environ["PADDLE_PSERVER_ENDPOINTS"], trainers=2)
exe = static.Executor()
for step, (x, y) in enumerate(T._batches(10, seed=100 + wid)):
    (lv,) = exe.run(t.get_trainer_program(), feed={"x": x, "y": y},
                    fetch_list=[loss])
    print("LOSS %%d %%.6f" %% (step, float(np.asarray(lv))), flush=True)
prog._ps_ctx.comm.client.barrier(2)
if wid == 0:
    prog._ps_ctx.stop()
"""


class TestTwoTrainerCluster:
    @pytest.mark.slow  # ~20 s two-process cluster; the transpiled
    # program's numerics stay tier-1-covered by the loss-parity cases
    def test_two_sync_trainers_converge(self):
        """2 trainer processes x 1 pserver: sync-mode transpiled training
        runs the push/2 + barrier + pull protocol across real processes
        and both workers converge on shared parameters."""
        from test_parameter_server import _free_port

        port = _free_port()
        srv = TestDistributeTranspiler()._spawn_server(port)
        workers = []
        try:
            for wid in range(2):
                env = dict(os.environ)
                env["PYTHONPATH"] = (REPO + os.pathsep
                                     + env.get("PYTHONPATH", ""))
                env["JAX_PLATFORMS"] = "cpu"
                env["PADDLE_TRAINER_ID"] = str(wid)
                env["PADDLE_PSERVER_ENDPOINTS"] = f"127.0.0.1:{port}"
                workers.append(subprocess.Popen(
                    [sys.executable, "-c",
                     _WORKER_SCRIPT % os.path.join(REPO, "tests")],
                    stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                    text=True, env=env, cwd=REPO))
            outs = []
            for w in workers:
                out, err = w.communicate(timeout=300)
                assert w.returncode == 0, err[-3000:]
                outs.append(out)
            for out in outs:
                losses = [float(line.split()[2])
                          for line in out.splitlines()
                          if line.startswith("LOSS")]
                assert len(losses) == 10
                assert losses[-1] < losses[0]  # shared params converge
        finally:
            for p in workers:
                if p.poll() is None:
                    p.kill()
            try:
                srv.wait(timeout=30)
            except subprocess.TimeoutExpired:
                pass  # worker failed before STOP: kill below and keep
                # the original assertion as the reported error
            if srv.poll() is None:
                srv.kill()
