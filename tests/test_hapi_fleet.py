"""hapi Model.fit composed with fleet data parallelism (reference:
`python/paddle/tests/dist_hapi_mnist_dynamic.py` — the high-level API must
train distributed, with loss parity against single-device fit).
"""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.distributed.fleet as fleet
from paddle_tpu.distributed import parallel_env
from paddle_tpu.io import TensorDataset


def _data():
    rng = np.random.RandomState(0)
    x = rng.rand(32, 8).astype("float32")
    y = rng.randint(0, 4, (32, 1)).astype("int64")
    return x, y


def _mlp():
    paddle.seed(7)
    return nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))


def _fit(distributed):
    parallel_env.set_mesh(None)
    x, y = _data()
    net = _mlp()
    if distributed:
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 4, "mp_degree": 1,
                                   "pp_degree": 1, "sharding_degree": 1}
        fleet.init(is_collective=True, strategy=strategy)
        net = fleet.distributed_model(net)
    model = paddle.Model(net)
    model.prepare(
        optimizer=paddle.optimizer.SGD(learning_rate=0.1,
                                       parameters=net.parameters()),
        loss=nn.CrossEntropyLoss())
    ds = TensorDataset([paddle.to_tensor(x), paddle.to_tensor(y)])
    model.fit(ds, batch_size=8, epochs=2, verbose=0, shuffle=False)
    out = model.evaluate(ds, batch_size=8, verbose=0)
    parallel_env.set_mesh(None)
    return out


def test_fit_under_fleet_dp_matches_single():
    single = _fit(distributed=False)
    dist4 = _fit(distributed=True)
    s = single.get("loss", single)
    d = dist4.get("loss", dist4)
    np.testing.assert_allclose(np.ravel(np.asarray(s, dtype=np.float64)),
                               np.ravel(np.asarray(d, dtype=np.float64)),
                               rtol=1e-4, atol=1e-5)
