"""Shardcheck: whole-program sharding & collective-budget analysis.

Two proof obligations, mirrored from the analyzer's contract:

1. CLEAN — the default build grid (zero{0,1,3} x scan k x accumulation
   x prefetch on/off) passes the full verifier with zero shardcheck
   findings: the budget predictor's table matches what XLA actually
   compiled, layout inference recovers (stage, buckets, prefetch) from
   the partition alone, and the ZeRO stores measure 1/dp resident.
2. SEEDED — each rule demonstrably fires on a program carrying exactly
   its defect: a >=1MB replicated shard_map input (replication-blowup),
   two gathered values escaping the region (materialization-window), an
   un-donated sharded carry (donation-leak), a bucket-count lie against
   the compiled schedule (collective-budget-mismatch), and a
   record-level twin that reduce-scatters but never re-gathers.

The export/suppression seams (analysis_findings label-cardinality
guard, `# lint:` suppression round-trip) and the --write-baseline
refusal gate are covered here too — shardcheck routes through the same
finding plumbing as every other checker.
"""
import importlib.util
import json
import os
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import monitor, nn
from paddle_tpu.analysis import shardcheck
from paddle_tpu.analysis.findings import ERROR, INFO, WARNING, errors
from paddle_tpu.distributed import parallel_env

DP = 8
COMM_MB = 0.003  # layer-aligned 2 buckets on the 16->32->8 MLP
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# every rule shardcheck owns — the clean grid must emit NONE of these
# (other tests' leaked optimizers may legitimately produce unrelated
# sharded-state-skipped warnings in a shared pytest process)
SHARD_RULES = frozenset({
    "replication-blowup", "materialization-window", "donation-leak",
    "collective-budget-mismatch", "zero-residency",
})


@pytest.fixture(autouse=True)
def _mesh():
    mesh = parallel_env.make_mesh({"dp": DP})
    parallel_env.set_mesh(mesh)
    yield mesh
    parallel_env.set_mesh(None)
    from paddle_tpu.distributed.fleet.base import topology
    topology.set_hybrid_communicate_group(None)


rng = np.random.RandomState(55)


def _mlp():
    return nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 8))


def _build(stage, k, acc=None, prefetch=None, donate=True, seed=11):
    paddle.seed(seed)
    m = _mlp()
    opt = paddle.optimizer.AdamW(parameters=m.parameters(),
                                 learning_rate=0.05)
    if stage:
        opt._zero_enable(axis="dp", stage=stage, comm_buffer_mb=COMM_MB,
                         prefetch=prefetch)

    def one(xb, yb):
        loss = nn.functional.cross_entropy(m(xb), yb)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    step = paddle.jit.to_static(one, scan_steps=k, dp_axis="dp",
                                accumulate_steps=acc, donate_state=donate)
    return step, m, opt


def _batches(k, batch=16):
    x = rng.rand(k, batch, 16).astype("float32")
    y = rng.randint(0, 8, (k, batch)).astype("int64")
    return paddle.to_tensor(x), paddle.to_tensor(y)


def _shard_map():
    import jax
    if hasattr(jax, "shard_map"):
        return jax.shard_map
    from jax.experimental.shard_map import shard_map
    return shard_map


# -- the budget predictor table ---------------------------------------------

def test_predict_budget_table():
    """The (stage, k, a, nb, prefetch) -> multiset table, pinned
    value-by-value (these are the counts the compiled-schedule diffs in
    the clean grid below hold the real programs to)."""
    P = shardcheck.predict_collective_budget
    ag, rs = ("all-gather", "dp"), ("reduce-scatter", "dp")
    # stage 0: nothing to budget
    assert P(0, scan_steps=4, n_buckets=2) == {}
    # stage 1: one rs+ag pair per bucket per update window
    assert P(1, scan_steps=4, n_buckets=2) == {ag: 8, rs: 8}
    assert P(1, scan_steps=4, accumulate_steps=2, n_buckets=2) == \
        {ag: 4, rs: 4}
    # stage 2: rs every micro step into the sharded accumulator, ag per
    # window; without accumulation it collapses to the stage-1 schedule
    assert P(2, scan_steps=4, accumulate_steps=2, n_buckets=2) == \
        {ag: 4, rs: 8}
    assert P(2, scan_steps=4, n_buckets=2) == P(1, scan_steps=4,
                                                n_buckets=2)
    # stage 3: rs and ag per micro step; the warm prefetch slot elides
    # the bucket-0 re-gather on each intra-window micro step
    assert P(3, scan_steps=4, n_buckets=2) == {ag: 8, rs: 8}
    assert P(3, scan_steps=4, accumulate_steps=2, n_buckets=2,
             prefetch=False) == {ag: 8, rs: 8}
    assert P(3, scan_steps=4, accumulate_steps=2, n_buckets=2,
             prefetch=True) == {ag: 6, rs: 8}
    # prefetch without accumulation elides nothing (every step is a
    # window boundary)
    assert P(3, scan_steps=4, n_buckets=2, prefetch=True) == \
        {ag: 8, rs: 8}


def test_predict_budget_mesh_axes_gating():
    """The mesh-axes tuple is the extension seam: an axis outside it is
    unbudgeted (returns {}), widening the tuple makes it land as data —
    the hybrid-mesh tp axis needs no new code here."""
    P = shardcheck.predict_collective_budget
    assert P(1, scan_steps=2, n_buckets=1, axis="tp") == {}
    got = P(1, scan_steps=2, n_buckets=1, axis="tp",
            mesh_axes=("dp", "tp"))
    assert got == {("all-gather", "tp"): 2, ("reduce-scatter", "tp"): 2}


# -- the clean grid ---------------------------------------------------------

GRID = [
    (0, 1, None, None), (0, 4, None, None), (0, 4, 2, None),
    (1, 1, None, None), (1, 4, None, None), (1, 4, 2, None),
    (3, 1, None, False), (3, 4, None, False), (3, 4, 2, False),
    (3, 1, None, True), (3, 4, None, True), (3, 4, 2, True),
]


@pytest.mark.parametrize("stage,k,acc,pf", GRID,
                         ids=[f"z{s}_k{k}_a{a or 1}_pf{int(bool(p))}"
                              for s, k, a, p in GRID])
def test_clean_grid_no_shardcheck_findings(stage, k, acc, pf):
    """Acceptance bar: the default build grid verifies clean — layout
    inference agrees with the optimizer's own zero_layout(), the
    compiled collective multiset sits exactly on the predicted budget,
    the stores are 1/dp resident, and the jaxpr pass flags nothing."""
    s, _m, opt = _build(stage, k, acc=acc, prefetch=pf)
    x, y = _batches(k)
    s(x, y)
    findings = s.verify()
    assert errors(findings) == []
    assert [f for f in findings if f.rule in SHARD_RULES] == []
    layout = shardcheck.infer_zero_layout(s)
    if stage == 0:
        assert layout is None
    else:
        assert layout["stage"] == stage
        assert layout["n_buckets"] == 2
        assert layout["scan_steps"] == k
        assert layout["accumulate_steps"] == (acc or 1)
        if stage == 3:
            assert layout["prefetch"] == bool(pf)
        zl = opt.zero_layout()
        assert zl["stage"] == stage
        assert zl["n_buckets"] == layout["n_buckets"]
        assert shardcheck.check_collective_budget(s) == []
        assert shardcheck.check_zero_residency(opt) == []


# -- seeded defects: one per rule -------------------------------------------

def test_seeded_replication_blowup(_mesh):
    """A >=1MB input entering a shard_map region replicated while the
    region threads dp-sharded values is the full-parameter residency
    regression — WARNING naming the shape and byte size."""
    import jax
    from jax.sharding import PartitionSpec as P
    big = np.ones((512, 1024), np.float32)  # 2 MiB, replicated
    xs = np.ones((DP, 4), np.float32)

    def f(b, x):
        return (x * b[0, 0]).sum(axis=1)

    fn = _shard_map()(f, mesh=_mesh, in_specs=(P(), P("dp")),
                      out_specs=P("dp"))
    jx = jax.make_jaxpr(fn)(big, xs)
    fs, stats = shardcheck.analyze_jaxpr(jx)
    hits = [f for f in fs if f.rule == "replication-blowup"]
    assert hits and hits[0].severity == WARNING
    assert "2097152 bytes" in hits[0].message
    assert stats["shard_map_regions"] == 1
    # the same program below the threshold is clean
    fs2, _ = shardcheck.analyze_jaxpr(
        jx, replication_threshold=4 << 20)
    assert [f for f in fs2 if f.rule == "replication-blowup"] == []


def test_seeded_materialization_window(_mesh):
    """Two all-gathered full values escaping the region boundary (a
    widened prefetch-slot live range: the gathered params ride out of
    the step instead of dying at their last consumer) blow the one-
    bucket budget — ERROR; a budget of 2 or None tolerates."""
    import jax
    from jax.sharding import PartitionSpec as P
    a = np.ones((DP, 4), np.float32)
    b = np.ones((DP, 4), np.float32)

    def f(u, v):
        return (jax.lax.all_gather(u, "dp", tiled=True),
                jax.lax.all_gather(v, "dp", tiled=True))

    fn = _shard_map()(f, mesh=_mesh, in_specs=(P("dp"), P("dp")),
                      out_specs=(P(), P()), check_rep=False)
    jx = jax.make_jaxpr(fn)(a, b)
    fs, stats = shardcheck.analyze_jaxpr(jx, budget=1)
    hits = [f for f in fs if f.rule == "materialization-window"]
    assert hits and hits[0].severity == ERROR
    assert "2 all-gathered" in hits[0].message
    assert stats["n_gathered"] == 2
    assert stats["escaped_gathered"] == 2
    # widening the budget (the stage-1/2 replicated-param contract) or
    # disabling the rule tolerates the same escapes
    assert shardcheck.analyze_jaxpr(jx, budget=2)[0] == []
    assert shardcheck.analyze_jaxpr(jx, budget=None)[0] == []


def test_seeded_donation_leak():
    """donate_state=False with ZeRO stores riding the carry silently
    doubles the 1/dp residency claim — ERROR from the default verify
    entry point; a replicated (non-ZeRO) un-donated carry is the
    legitimate-while-debugging WARNING."""
    k = 2
    x, y = _batches(k)
    s, _m, _opt = _build(1, k, donate=False)
    s(x, y)
    findings = s.verify()
    hits = [f for f in findings if f.rule == "donation-leak"]
    assert hits and hits[0].severity == ERROR
    assert "donate_state=False" in hits[0].message
    # replicated carry: warning, and verify() still has no errors
    s0, _m0, _o0 = _build(0, k, donate=False)
    s0(x, y)
    f0 = s0.verify()
    hits0 = [f for f in f0 if f.rule == "donation-leak"]
    assert hits0 and hits0[0].severity == WARNING
    assert errors(f0) == []


def test_seeded_collective_budget_mismatch():
    """Lying about the bucket count makes the compiled schedule carry
    surplus collectives vs the budget — one ERROR per op naming the
    count delta (the 'extra all-gather' acceptance defect: got > the
    single-bucket budget)."""
    k = 2
    s, _m, _opt = _build(1, k)
    x, y = _batches(k)
    s(x, y)
    layout = dict(shardcheck.infer_zero_layout(s))
    assert layout["n_buckets"] == 2  # the truth...
    layout["n_buckets"] = 1          # ...and the lie
    fs = shardcheck.check_collective_budget(s, layout=layout)
    assert fs and all(f.rule == "collective-budget-mismatch"
                      and f.severity == ERROR for f in fs)
    by_op = {f.op_name: f for f in fs}
    assert set(by_op) == {"all-gather", "reduce-scatter"}
    ag = by_op["all-gather"]
    assert ag.slot == "dp"
    assert f"budgets {k}" in ag.message      # nb=1 -> k expected
    assert f"(+{k})" in ag.message           # 2*k compiled -> +k extra
    # the honest layout diffs clean
    assert shardcheck.check_collective_budget(s) == []


def test_record_level_rs_without_ag():
    """Record-level twins: an axis whose gradients reduce-scatter but
    whose params are never re-gathered starves every rank's replicas —
    ERROR; adding the gather back clears it; the stamped multiset
    summarizes for the ladder's shard= column."""
    from paddle_tpu import static
    from paddle_tpu.core.dispatch import call_op

    def prog_with(ops):
        prog = static.Program()
        with static.program_guard(prog):
            g = static.data("g", [4], "float32")
            out = g
            for op_name in ops:
                def _c(v):
                    return v
                _c._collective_axis = "dp"
                _c._collective_nbytes = 16
                out = call_op(_c, out, op_name=op_name)
            paddle.sum(out)
        return prog

    bad = prog_with(["c_reducescatter"])
    fs = shardcheck.check_program_sharding(bad)
    assert fs and fs[0].rule == "collective-budget-mismatch"
    assert fs[0].severity == ERROR
    good = prog_with(["c_reducescatter", "c_allgather"])
    assert shardcheck.check_program_sharding(good) == []
    stats = shardcheck.program_shard_stats(good)
    assert stats["collectives"] == 2
    assert stats["axes"]["dp"] == {"reduce-scatter": 1, "all-gather": 1}
    assert shardcheck.format_shard_stats(stats) == "dp:ag1+rs1"
    assert shardcheck.format_shard_stats(
        shardcheck.program_shard_stats(prog_with([]))) == "-"


# -- the baseline gate ------------------------------------------------------

def _load_script(name, path):
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_write_baseline_refuses_on_shardcheck_error(tmp_path, monkeypatch):
    """run_all.py --write-baseline re-verifies the ladder first; a
    shardcheck ERROR in a twin (rs-without-ag) refuses the pin (exit 1,
    no baseline file) with the refusal printed."""
    from paddle_tpu import static
    from paddle_tpu.analysis import ladder
    from paddle_tpu.core.dispatch import call_op

    def _bad_ranks():
        prog = static.Program()
        with static.program_guard(prog):
            g = static.data("g", [4], "float32")

            def _rs(v):
                return v
            _rs._collective_axis = "dp"
            _rs._collective_nbytes = 16
            out = call_op(_rs, g, op_name="c_reducescatter")
            tgt = paddle.sum(out)
        return [(prog, [tgt])]

    monkeypatch.setattr(ladder, "LADDER_BUILDERS",
                        {"zero_bad": _bad_ranks})
    results = tmp_path / "results.json"
    results.write_text(json.dumps(
        {"results": [{"metric": "x", "value": 1.0, "backend": "cpu"}]}))
    out = tmp_path / "baseline.json"
    run_all = _load_script("run_all_under_test",
                           os.path.join(REPO, "benchmarks", "run_all.py"))
    monkeypatch.setattr(sys, "argv", [
        "run_all.py", "--results", str(results),
        "--write-baseline", str(out)])
    rc = run_all.main()
    assert rc == 1
    assert not out.exists()


def test_lint_program_default_sweep_clean(capsys):
    """The full default lint_program sweep (ladder + source +
    concurrency, shardcheck riding verify_ladder and the shard= column
    in the ladder rows) reports zero ERROR findings on the repo as it
    ships."""
    lp = _load_script("lint_program_under_test",
                      os.path.join(REPO, "tools", "lint_program.py"))
    rc = lp.main([])
    outp = capsys.readouterr().out
    assert rc == 0, outp
    assert "0 error(s)" in outp
    # the shard= column renders the stamped multiset per zero twin
    assert "shard=" in outp
    assert "dp:ag" in outp


# -- export & suppression seams ---------------------------------------------

def test_analysis_findings_label_cardinality_guard(monkeypatch):
    """analysis_findings rides format_labels' per-metric cardinality
    guard: past the cap, new rule/severity combinations collapse to the
    __overflow__ series and bump metrics_label_overflow_total instead
    of growing the registry without bound."""
    from paddle_tpu import analysis
    from paddle_tpu.analysis.findings import Finding
    from paddle_tpu.observability import export
    monkeypatch.setenv("PADDLE_TPU_MAX_LABEL_SETS", "2")
    export.clear_label_sets()
    try:
        for key in (
                'analysis_findings{rule="shardtest-a",severity="warning"}',
                'analysis_findings{rule="shardtest-b",severity="warning"}',
                'analysis_findings{rule="__overflow__",'
                'severity="__overflow__"}',
                "metrics_label_overflow_total"):
            monitor.stat_reset(key)
        analysis._export([
            Finding("shardtest-a", WARNING, "m"),
            Finding("shardtest-b", WARNING, "m"),
            Finding("shardtest-c", WARNING, "m"),
        ])
        assert monitor.stat_get(
            'analysis_findings{rule="shardtest-a",severity="warning"}') == 1
        assert monitor.stat_get(
            'analysis_findings{rule="shardtest-b",severity="warning"}') == 1
        # the third distinct combination overflowed
        assert monitor.stat_get(
            'analysis_findings{rule="__overflow__",'
            'severity="__overflow__"}') == 1
        assert monitor.stat_get("metrics_label_overflow_total") >= 1
    finally:
        export.clear_label_sets()  # don't cap later tests' label sets


def test_suppression_roundtrip_shardcheck_rule(tmp_path):
    """A shardcheck finding carrying a loc demotes through the PR-15
    structured-suppression syntax like any other rule: `# lint:
    collective-budget-mismatch <reason>` on the flagged line turns the
    ERROR into an auditable INFO with the reason attached; other rules
    on the same line stay loud."""
    from paddle_tpu.analysis.concurrency import (apply_suppressions,
                                                 parse_suppressions)
    from paddle_tpu.analysis.findings import Finding
    src = ("def step():\n"
           "    gather()  # lint: collective-budget-mismatch"
           " tp axis lands with the hybrid mesh\n")
    p = tmp_path / "mod.py"
    p.write_text(src)
    sup = parse_suppressions(src)
    assert sup[2][0] == "collective-budget-mismatch"
    f = Finding("collective-budget-mismatch", ERROR,
                "all-gather on axis 'tp': 2 executed, layout budgets 0",
                loc=f"{p}:2")
    out = apply_suppressions([f], sup)
    assert out[0].severity == INFO
    assert out[0].message.startswith(
        "suppressed (tp axis lands with the hybrid mesh): ")
    # an unmatched rule on the same line is untouched
    g = Finding("materialization-window", ERROR, "x", loc=f"{p}:2")
    assert apply_suppressions([g], sup)[0].severity == ERROR
