"""1F1B pipeline parallelism (reference: section_worker.cc:148-175).

Asserts the two 1F1B contracts the reference schedule exists for:
loss/grad parity with sequential execution (incl. non-uniform embed/head
stages), and O(S) — not O(M) — activation liveness.
"""
import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import paddle_tpu.distributed as dist
from paddle_tpu.parallel import spmd_pipeline_1f1b, ring_buffer_size

rng = np.random.RandomState(7)


def _pipeline_fn(mesh, first_fn=None):
    def run(stage_params, last_params, first_params, micro, labels):
        return jax.shard_map(
            lambda sp, lp, fp, x, y: spmd_pipeline_1f1b(
                _stage, _head_loss, sp, lp, x, y,
                first_fn=first_fn, first_params=fp, axis_name="pp"),
            mesh=mesh,
            in_specs=(jax.tree_util.tree_map(lambda _: P("pp"), stage_params),
                      P(), P(), P(None), P(None)),
            out_specs=(P(), jax.tree_util.tree_map(lambda _: P("pp"),
                                                   stage_params), P(), P()),
        )(stage_params, last_params, first_params, micro, labels)
    return run


def _stage(params, h):
    w, b = params
    return jnp.tanh(h @ w + b)


def _head_loss(head_w, h, y):
    logits = h @ head_w
    return jnp.mean((logits - y) ** 2)


class TestRingBuffer:
    def test_liveness_is_O_S_not_O_M(self):
        # GPipe stores M activations; 1F1B must be bounded by the stage count
        assert ring_buffer_size(n_stages=2, n_micro=64) == 3
        assert ring_buffer_size(n_stages=4, n_micro=64) == 7
        assert ring_buffer_size(n_stages=4, n_micro=128) == 7  # M-independent
        assert ring_buffer_size(n_stages=4, n_micro=2) == 2  # small M capped


class TestParity:
    def test_uniform_stages_loss_and_grads(self):
        mesh = dist.make_mesh({"pp": 4})
        S, M, mb, dim = 4, 8, 2, 16
        w = (rng.randn(S, dim, dim) * 0.2).astype(np.float32)
        b = (rng.randn(S, dim) * 0.1).astype(np.float32)
        head = (rng.randn(dim, dim) * 0.2).astype(np.float32)
        x = rng.randn(M, mb, dim).astype(np.float32)
        y = rng.randn(M, mb, dim).astype(np.float32)

        loss, gP, gF, gL = _pipeline_fn(mesh)((w, b), head,
                                              jnp.zeros((), jnp.float32),
                                              x, y)

        def ref_loss(params, head_w):
            w_, b_ = params
            losses = []
            for m in range(M):
                h = x[m]
                for s in range(S):
                    h = jnp.tanh(h @ w_[s] + b_[s])
                losses.append(_head_loss(head_w, h, y[m]))
            return jnp.mean(jnp.stack(losses))

        ref_v, (g_wb, g_head) = jax.value_and_grad(
            ref_loss, argnums=(0, 1))((w, b), head)
        np.testing.assert_allclose(float(loss), float(ref_v), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(gP[0]), np.asarray(g_wb[0]),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(gP[1]), np.asarray(g_wb[1]),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(gL), np.asarray(g_head),
                                   rtol=1e-4, atol=1e-5)

    def test_nonuniform_embed_and_head_stages(self):
        """The lifted restriction: stage 0 embeds int token ids (raw input
        shape ≠ hidden shape), the last stage computes the loss."""
        mesh = dist.make_mesh({"pp": 4})
        S, M, mb, T, V, dim = 4, 8, 2, 6, 32, 16
        emb = (rng.randn(V, dim) * 0.1).astype(np.float32)
        w = (rng.randn(S, dim, dim) * 0.2).astype(np.float32)
        b = (rng.randn(S, dim) * 0.1).astype(np.float32)
        head = (rng.randn(dim, dim) * 0.2).astype(np.float32)
        ids = rng.randint(0, V, size=(M, mb, T)).astype(np.int32)
        y = rng.randn(M, mb, T, dim).astype(np.float32)

        def embed(e, token_ids):
            return e[token_ids]

        loss, gP, gE, gL = _pipeline_fn(mesh, first_fn=embed)(
            (w, b), head, emb, ids, y)

        def ref_loss(params, head_w, e):
            w_, b_ = params
            losses = []
            for m in range(M):
                h = e[ids[m]]
                for s in range(S):
                    h = jnp.tanh(h @ w_[s] + b_[s])
                losses.append(_head_loss(head_w, h, y[m]))
            return jnp.mean(jnp.stack(losses))

        ref_v, (g_wb, g_head, g_emb) = jax.value_and_grad(
            ref_loss, argnums=(0, 1, 2))((w, b), head, emb)
        np.testing.assert_allclose(float(loss), float(ref_v), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(gP[0]), np.asarray(g_wb[0]),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(gE), np.asarray(g_emb),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(gL), np.asarray(g_head),
                                   rtol=1e-4, atol=1e-5)

    def test_nan_safe_loss_in_warmup(self):
        """Out-of-window backward runs on garbage (zero) activations; a
        log-based loss must not poison gradients via 0*NaN."""
        mesh = dist.make_mesh({"pp": 2})
        S, M, mb, dim = 2, 4, 2, 8
        w = (rng.randn(S, dim, dim) * 0.2).astype(np.float32)
        b = np.zeros((S, dim), np.float32)
        head = (rng.randn(dim, dim) * 0.2).astype(np.float32)
        x = np.abs(rng.randn(M, mb, dim)).astype(np.float32) + 0.5
        y = rng.randint(0, dim, size=(M, mb)).astype(np.int32)

        def log_loss(head_w, h, labels):
            logits = h @ head_w
            logp = jax.nn.log_softmax(logits, axis=-1)
            picked = jnp.take_along_axis(logp, labels[..., None],
                                         axis=-1)
            return -jnp.mean(picked)

        def run(sp, lp, fp, xx, yy):
            return spmd_pipeline_1f1b(_stage, log_loss, sp, lp, xx, yy,
                                      first_params=fp, axis_name="pp")

        loss, gP, _, gL = jax.shard_map(
            run, mesh=mesh,
            in_specs=((P("pp"), P("pp")), P(), P(), P(None), P(None)),
            out_specs=(P(), (P("pp"), P("pp")), P(), P()),
        )((w, b), head, jnp.zeros((), jnp.float32), x, y)
        assert np.isfinite(float(loss))
        assert np.isfinite(np.asarray(gP[0])).all()
        assert np.isfinite(np.asarray(gL)).all()

    def test_more_microbatches_than_buffer(self):
        """M >> 2S-1: the ring reuses slots; results must stay exact."""
        mesh = dist.make_mesh({"pp": 2})
        S, M, mb, dim = 2, 12, 2, 8
        w = (rng.randn(S, dim, dim) * 0.2).astype(np.float32)
        b = np.zeros((S, dim), np.float32)
        head = (rng.randn(dim, dim) * 0.2).astype(np.float32)
        x = rng.randn(M, mb, dim).astype(np.float32)
        y = rng.randn(M, mb, dim).astype(np.float32)
        assert ring_buffer_size(S, M) == 3 < M

        loss, gP, _, gL = _pipeline_fn(mesh)((w, b), head,
                                             jnp.zeros((), jnp.float32), x, y)

        def ref_loss(params, head_w):
            w_, b_ = params
            losses = []
            for m in range(M):
                h = x[m]
                for s in range(S):
                    h = jnp.tanh(h @ w_[s] + b_[s])
                losses.append(_head_loss(head_w, h, y[m]))
            return jnp.mean(jnp.stack(losses))

        ref_v, (g_wb, g_head) = jax.value_and_grad(
            ref_loss, argnums=(0, 1))((w, b), head)
        np.testing.assert_allclose(float(loss), float(ref_v), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(gP[0]), np.asarray(g_wb[0]),
                                   rtol=1e-4, atol=1e-5)
