"""dygraph→static AST fallback (reference: dygraph_to_static/
ifelse_transformer.py + loop_transformer.py, exercised the way
unittests/dygraph_to_static/test_ifelse.py and test_seq2seq.py drive the
reference: data-dependent python control flow under @to_static with no
manual rewrite)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn

rng = np.random.RandomState(11)


class BranchyNet(nn.Layer):
    """Data-dependent `if` over a tensor predicate inside forward."""

    def __init__(self):
        super().__init__()
        self.pos = nn.Linear(4, 4)
        self.neg = nn.Linear(4, 4)

    def forward(self, x):
        if x.mean() > 0:
            y = self.pos(x) * 2.0
        else:
            y = self.neg(x) + 1.0
        return y.sum()


def test_data_dependent_if_compiles_and_matches_eager():
    m = BranchyNet()
    xs = [rng.rand(2, 4).astype("float32") + 0.5,
          -(rng.rand(2, 4).astype("float32") + 0.5)]

    eager = [float(m(paddle.to_tensor(x)).numpy()) for x in xs]

    def step(t):
        return m(t)

    static = paddle.jit.to_static(step)
    got = [float(static(paddle.to_tensor(x)).numpy()) for x in xs]
    np.testing.assert_allclose(got, eager, rtol=1e-5)
    # one cache entry serves both branches: the predicate is IN the program
    assert len(static._cache) == 1


def test_data_dependent_if_gradients():
    m = BranchyNet()
    x = rng.rand(2, 4).astype("float32") + 0.5  # positive branch

    t = paddle.to_tensor(x)
    loss = m(t)
    loss.backward()
    eager_g = m.pos.weight.grad.numpy().copy()
    m.pos.weight.clear_grad()

    def step(v):
        loss = m(v)
        loss.backward()
        return loss

    static = paddle.jit.to_static(step)
    static(paddle.to_tensor(x))
    np.testing.assert_allclose(m.pos.weight.grad.numpy(), eager_g,
                               rtol=1e-4, atol=1e-5)


def test_while_greedy_decode():
    """seq2seq-style decode loop: `while` over a traced predicate with a
    carried step counter and state (reference: test_seq2seq pattern)."""
    proj = nn.Linear(8, 8)
    for p in proj.parameters():
        p.stop_gradient = True

    def decode(h):
        i = paddle.to_tensor(0)
        acc = h * 0.0
        while i < 5 and acc.sum() < 50.0:
            acc = acc + paddle.nn.functional.relu(proj(h)) + 1.0
            i = i + 1
        return acc.sum(), i

    h = paddle.to_tensor(rng.rand(2, 8).astype("float32"))
    with paddle.no_grad():
        eager_val, eager_i = decode(h)
        static = paddle.jit.to_static(decode)
        got_val, got_i = static(h)
    np.testing.assert_allclose(float(got_val.numpy()),
                               float(eager_val.numpy()), rtol=1e-5)
    assert int(got_i.numpy()) == int(eager_i.numpy())


def test_nested_layer_data_dependent_if():
    """The tensor-predicate `if` lives in a SUB-layer called from the
    compiled function — convert_call must recurse into it."""

    class Gate(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 4)

        def forward(self, x):
            if x.sum() > 0:
                return self.fc(x)
            return x * 0.5

    class Outer(nn.Layer):
        def __init__(self):
            super().__init__()
            self.gate = Gate()

        def forward(self, x):
            return self.gate(x).sum()

    m = Outer()
    xs = [rng.rand(2, 4).astype("float32"),
          -rng.rand(2, 4).astype("float32")]
    eager = [float(m(paddle.to_tensor(x)).numpy()) for x in xs]

    def step(t):
        return m(t)

    static = paddle.jit.to_static(step)
    got = [float(static(paddle.to_tensor(x)).numpy()) for x in xs]
    np.testing.assert_allclose(got, eager, rtol=1e-5)


def test_for_over_tensor_range():
    def body(n, x):
        acc = x * 0.0
        for i in range(n):
            acc = acc + x * 1.0
        return acc.sum()

    x = paddle.to_tensor(rng.rand(3).astype("float32"))
    with paddle.no_grad():
        static = paddle.jit.to_static(body)
        got = static(paddle.to_tensor(4), x)
    np.testing.assert_allclose(float(got.numpy()),
                               4 * float(x.numpy().sum()), rtol=1e-5)


def test_transformed_eager_semantics_preserved():
    """convert_to_static output run OUTSIDE tracing keeps python
    semantics: short-circuit bool ops, branch-local names, plain loops."""
    from paddle_tpu.jit.dy2static import convert_to_static

    def f(a, flag):
        if flag:
            b = a + 1
        else:
            b = a - 1
        n = 0
        while n < 3:
            b = b * 2
            n += 1
        return b, (flag and n) or -1

    g = convert_to_static(f)
    assert g(1, True) == f(1, True)
    assert g(1, False) == f(1, False)


def test_untransformable_entry_reports_clear_error():
    m = BranchyNet()
    static = paddle.jit.to_static(lambda t: m(t))
    # a lambda entry cannot be AST-transformed: the failure must point at
    # the fallback path with guidance, not be a raw tracer error
    with pytest.raises(RuntimeError, match="AST fallback"):
        static(paddle.to_tensor(rng.rand(2, 4).astype("float32") + 0.5))


def test_seq2seq_greedy_decode_model():
    """Full seq2seq-shaped decode under @to_static: a while loop carrying
    (state, last-token, step, buffer), argmax emission, put_along_axis
    buffer writes — no manual control-flow rewrite (reference:
    dygraph_to_static/test_seq2seq.py)."""
    B, H, V, L = 2, 8, 12, 6
    cell = nn.Linear(H, H)
    head = nn.Linear(H, V)
    emb = nn.Embedding(V, H)
    for layer in (cell, head, emb):
        for p in layer.parameters():
            p.stop_gradient = True

    def greedy(h):
        tokens = paddle.zeros([B, L], dtype="int32")
        tok = paddle.zeros([B], dtype="int32")
        i = paddle.to_tensor(0)
        while i < L:
            h = paddle.ops.tanh(cell(h) + emb(tok))
            tok = paddle.ops.argmax(head(h), axis=-1).astype("int32")
            idx = paddle.ops.full([B, 1], 0, "int64") + i.astype("int64")
            tokens = paddle.ops.put_along_axis(
                tokens, idx, paddle.ops.reshape(tok, [B, 1]), axis=1)
            i = i + 1
        return tokens, h

    h0 = paddle.to_tensor(rng.rand(B, H).astype("float32"))
    with paddle.no_grad():
        eager_tokens, eager_h = greedy(h0)
        static = paddle.jit.to_static(greedy)
        got_tokens, got_h = static(h0)
    np.testing.assert_array_equal(got_tokens.numpy(), eager_tokens.numpy())
    np.testing.assert_allclose(got_h.numpy(), eager_h.numpy(), rtol=1e-5)


def test_for_negative_step_and_loop_var_semantics():
    from paddle_tpu.jit.dy2static import convert_to_static

    def f(x):
        acc = 0
        for i in range(5, 0, -1):
            acc += i
        return acc, i  # python: i == 1 after the loop

    g = convert_to_static(f)
    assert g(0) == f(0) == (15, 1)


def test_while_with_break_traced():
    """Conditional `break` in a traced while: lowered to a loop-carried
    flag (reference: break_continue_transformer)."""
    def f(x):
        i = paddle.to_tensor(0)
        acc = x * 0.0
        while i < 10:
            acc = acc + x
            if acc.sum() > 2.5:
                break
            i = i + 1
        return acc.sum(), i

    x = paddle.to_tensor(np.ones(1, np.float32))
    with paddle.no_grad():
        ev, ei = f(x)
        static = paddle.jit.to_static(f)
        gv, gi = static(x)
    assert float(gv.numpy()) == float(ev.numpy()) == 3.0
    assert int(gi.numpy()) == int(ei.numpy()) == 2


def test_for_with_continue_traced():
    def f(x):
        acc = x * 0.0
        n = paddle.to_tensor(6)
        for i in range(n):
            if i % 2 == 1:
                continue
            acc = acc + x * float(1.0)
        return acc.sum()

    # NOTE: `i % 2 == 1` over the traced induction var is a traced pred;
    # the continue lowers to a cont-flag guard inside the loop body
    x = paddle.to_tensor(np.ones(2, np.float32))
    with paddle.no_grad():
        ev = f(x)
        static = paddle.jit.to_static(f)
        gv = static(x)
    np.testing.assert_allclose(float(gv.numpy()), float(ev.numpy()))
    assert float(gv.numpy()) == 6.0  # 3 even iterations x sum(x)=2


def test_break_continue_eager_semantics():
    from paddle_tpu.jit.dy2static import convert_to_static

    def f(n):
        total = 0
        for i in range(n):
            if i == 2:
                continue
            if i == 5:
                break
            total += i
        return total, i

    g = convert_to_static(f)
    assert g(8) == f(8) == (1 + 3 + 4, 5)
    assert g(2) == f(2)


def test_while_true_with_traced_break():
    """Concrete `while True:` whose ONLY exit is a traced break: the
    eager dispatch must hand over to lax lowering once the lowered break
    flag turns traced (review regression)."""
    def f(x):
        acc = x * 0.0
        while True:
            acc = acc + x
            if acc.sum() > 2.5:
                break
        return acc.sum()

    x = paddle.to_tensor(np.ones(1, np.float32))
    with paddle.no_grad():
        ev = float(f(x).numpy())
        static = paddle.jit.to_static(f)
        gv = float(static(x).numpy())
    assert gv == ev == 3.0


def test_break_inside_with_does_not_recurse():
    """break under a `with` in the loop body must either transform or
    degrade to plain python — never RecursionError (review regression)."""
    from paddle_tpu.jit.dy2static import convert_to_static

    def f(n):
        import contextlib
        total = 0
        for i in range(n):
            with contextlib.nullcontext():
                if i == 3:
                    break
                total += i
        return total

    g = convert_to_static(f)
    assert g(6) == f(6) == 0 + 1 + 2


# ---------------------------------------------------------------- round 4:
# return-in-loop, for-over-tensor, while...else rejection (reference:
# return_transformer.py RETURN_VALUE flags, loop_transformer.py
# convert_enumerate/iter)

def test_return_inside_while_early_exit_on_eos():
    """Decode loop that RETURNS from inside the loop when EOS is hit —
    the return lowers to a capture + break and an `if flag: return`
    continuation, all inside one traced program."""
    def decode(h, eos_at):
        i = paddle.to_tensor(0)
        acc = h * 0.0
        while i < 8:
            acc = acc + h
            if acc.sum() > eos_at:
                return acc.sum() * 10.0, i   # early return, traced pred
            i = i + 1
        return acc.sum(), i

    h = paddle.to_tensor(np.ones((2, 2), np.float32))
    with paddle.no_grad():
        static = paddle.jit.to_static(decode)
        for eos in (6.0, 1e9):  # early-return path and run-to-end path
            ev, ei = decode(h, paddle.to_tensor(eos))
            gv, gi = static(h, paddle.to_tensor(eos))
            np.testing.assert_allclose(float(gv.numpy()),
                                       float(ev.numpy()), rtol=1e-5)
            assert int(gi.numpy()) == int(ei.numpy())
        assert len(static._cache) == 1  # both paths share one program


def test_return_inside_for_range():
    def f(x, n):
        for i in range(n):
            x = x + 1.0
            if x.sum() > 5.0:
                return x * 100.0
        return x

    x = paddle.to_tensor(np.zeros(2, np.float32))
    with paddle.no_grad():
        static = paddle.jit.to_static(f)
        for n in (2, 10):
            np.testing.assert_allclose(
                np.asarray(static(x, paddle.to_tensor(n)).numpy()),
                np.asarray(f(x, n).numpy()), rtol=1e-5)


def test_for_over_tensor_rows_matches_eager():
    """`for row in tensor:` iterates the leading dim through the while
    lowering and matches eager row-by-row accumulation."""
    proj = nn.Linear(4, 4)
    for p in proj.parameters():
        p.stop_gradient = True

    def fold(xs):
        acc = paddle.to_tensor(np.zeros(4, np.float32))
        for row in xs:
            acc = acc + paddle.nn.functional.relu(proj(row))
        return acc.sum()

    xs = paddle.to_tensor(rng.rand(6, 4).astype("float32"))
    with paddle.no_grad():
        ev = float(fold(xs).numpy())
        static = paddle.jit.to_static(fold)
        gv = float(static(xs).numpy())
    np.testing.assert_allclose(gv, ev, rtol=1e-5)


def test_for_over_tensor_with_traced_break():
    def first_big(xs, thresh):
        total = paddle.to_tensor(0.0)
        for row in xs:
            if row.sum() > thresh:
                break
            total = total + row.sum()
        return total

    xs = paddle.to_tensor(rng.rand(5, 3).astype("float32"))
    th = paddle.to_tensor(1.2)
    with paddle.no_grad():
        ev = float(first_big(xs, th).numpy())
        static = paddle.jit.to_static(first_big)
        gv = float(static(xs, th).numpy())
    np.testing.assert_allclose(gv, ev, rtol=1e-5)


def test_for_over_python_list_still_works_transformed():
    from paddle_tpu.jit.dy2static import convert_to_static

    def f(items):
        total = 0
        for x in items:
            total += x
        return total

    g = convert_to_static(f)
    assert g([1, 2, 3]) == 6
    assert g((4, 5)) == 9


def test_while_else_rejected_loudly_when_traced():
    """while...else stays plain python; a traced condition must raise an
    actionable NotImplementedError, not an opaque tracer error."""
    def f(x):
        i = 0
        while x.sum() > 0:
            x = x - 1.0
            i += 1
        else:
            i = -1
        return x, i

    x = paddle.to_tensor(np.ones(2, np.float32))
    static = paddle.jit.to_static(f)
    with pytest.raises(NotImplementedError, match="while...else"):
        with paddle.no_grad():
            static(x)


def test_while_else_concrete_still_runs():
    from paddle_tpu.jit.dy2static import convert_to_static

    def f(n):
        i = 0
        while i < n:
            i += 1
        else:
            i = i + 100
        return i

    g = convert_to_static(f)
    assert g(3) == f(3) == 103


def test_for_over_generator_stays_lazy():
    """Generators must NOT be materialized up front: an early break
    stops pulling, and an unbounded generator terminates."""
    from paddle_tpu.jit.dy2static import convert_to_static

    pulled = []

    def gen():
        i = 0
        while True:  # unbounded
            pulled.append(i)
            yield i
            i += 1

    def f():
        total = 0
        for x in gen():
            if x >= 3:
                break
            total += x
        return total

    g = convert_to_static(f)
    assert g() == 0 + 1 + 2
    assert len(pulled) <= 5  # lazy: did not try to drain the stream
