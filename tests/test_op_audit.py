"""The op-coverage audit must stay clean: every operator type the
reference registers maps to a verified symbol, a delegation, or a
documented deferral (tools/op_audit.py; VERDICT r3 item #5)."""
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REF = "/root/reference/paddle/fluid/operators"


@pytest.mark.skipif(not os.path.isdir(REF),
                    reason="reference tree not available")
def test_audit_has_zero_unmapped_ops():
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "op_audit.py")],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=600)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "UNMAPPED" not in r.stdout
    # the mapped-symbol count is the real coverage claim — keep it honest
    assert "symbol=4" in r.stdout or "symbol=5" in r.stdout, r.stdout
