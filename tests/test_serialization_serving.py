"""Program serialization + process-independent serving.

Mirrors the reference's save/load_inference_model + AnalysisPredictor tests
(`fluid/io.py:1246`, `analysis_predictor.cc:389`): the saved artifact must
serve in a process that has no access to the model's Python class.
"""
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.jit.io import save as jit_save, load as jit_load
from paddle_tpu.jit.to_static import InputSpec


def _make_local_model():
    """Defined inside a function: unpicklable and unimportable elsewhere —
    the load site cannot cheat by reconstructing the class."""

    class LocalMLP(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc1 = nn.Linear(8, 16)
            self.fc2 = nn.Linear(16, 4)

        def forward(self, x):
            return self.fc2(paddle.tanh(self.fc1(x)))

    return LocalMLP()


class TestStableHLOArtifact:
    def test_save_load_same_process_no_class(self, tmp_path):
        model = _make_local_model()
        model.eval()
        x = np.random.RandomState(0).randn(2, 8).astype(np.float32)
        want = model(Tensor(x)).numpy()

        prefix = str(tmp_path / "m")
        jit_save(model, prefix, input_spec=[InputSpec([None, 8], "float32")])
        assert os.path.exists(prefix + ".pdmodel")
        assert os.path.exists(prefix + ".pdiparams")

        served = jit_load(prefix)  # StableHLO path: never touches LocalMLP
        got = served(Tensor(x)).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    def test_dynamic_batch(self, tmp_path):
        model = _make_local_model()
        model.eval()
        prefix = str(tmp_path / "m")
        jit_save(model, prefix, input_spec=[InputSpec([None, 8], "float32")])
        served = jit_load(prefix)
        for bs in (1, 3, 7):
            x = np.ones((bs, 8), np.float32)
            assert served(Tensor(x)).numpy().shape == (bs, 4)

    def test_predictor_handles(self, tmp_path):
        model = _make_local_model()
        model.eval()
        x = np.random.RandomState(1).randn(3, 8).astype(np.float32)
        want = model(Tensor(x)).numpy()

        prefix = str(tmp_path / "m")
        jit_save(model, prefix,
                 input_spec=[InputSpec([None, 8], "float32", name="feat")])

        from paddle_tpu.inference import Config, create_predictor
        pred = create_predictor(Config(prefix + ".pdmodel",
                                       prefix + ".pdiparams"))
        assert pred.get_input_names() == ["feat"]
        pred.get_input_handle("feat").copy_from_cpu(x)
        pred.run()
        out = pred.get_output_handle(pred.get_output_names()[0]).copy_to_cpu()
        np.testing.assert_allclose(out, want, rtol=1e-5, atol=1e-6)

    def test_fresh_process_serving(self, tmp_path):
        """The headline reference behavior: load+serve in a new process with
        no access to the training code (analysis_predictor.cc:389)."""
        model = _make_local_model()
        model.eval()
        x = np.random.RandomState(2).randn(2, 8).astype(np.float32)
        want = model(Tensor(x)).numpy()
        prefix = str(tmp_path / "m")
        jit_save(model, prefix, input_spec=[InputSpec([None, 8], "float32")])
        np.save(tmp_path / "x.npy", x)
        np.save(tmp_path / "want.npy", want)

        script = textwrap.dedent(f"""
            import numpy as np
            from paddle_tpu.inference import Config, create_predictor
            pred = create_predictor(Config({prefix + '.pdmodel'!r},
                                           {prefix + '.pdiparams'!r}))
            x = np.load({str(tmp_path / 'x.npy')!r})
            name = pred.get_input_names()[0]
            pred.get_input_handle(name).copy_from_cpu(x)
            outs = pred.run()
            want = np.load({str(tmp_path / 'want.npy')!r})
            # parent computed `want` on TPU, child serves on CPU: platform
            # matmul precision differs (bf16 MXU passes) — structural parity
            # is the assertion, not bit equality
            np.testing.assert_allclose(outs[0], want, rtol=0.05, atol=0.01)
            print("SERVED_OK")
        """)
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        r = subprocess.run([sys.executable, "-c", script], cwd="/root/repo",
                           capture_output=True, text=True, env=env,
                           timeout=300)
        assert r.returncode == 0, r.stderr[-2000:]
        assert "SERVED_OK" in r.stdout


class TestFlagshipServing:
    def test_bert_tiny_artifact_roundtrip(self, tmp_path):
        """Transformer with int inputs + symbolic batch through the
        class-free artifact (the BASELINE config-3 model family served the
        reference way: save_inference_model → AnalysisPredictor)."""
        from paddle_tpu.models import BertConfig, BertModel
        paddle.seed(1)
        cfg = BertConfig(vocab_size=128, hidden_size=32, num_layers=2,
                         num_heads=2, intermediate_size=64,
                         max_position_embeddings=32, hidden_dropout=0.0,
                         attention_dropout=0.0)
        model = BertModel(cfg)
        model.eval()
        ids = np.random.RandomState(0).randint(0, 128, (2, 16)).astype("int32")
        seq, pooled = model(Tensor(ids))
        prefix = str(tmp_path / "bert")
        jit_save(model, prefix,
                 input_spec=[InputSpec([None, 16], "int32", name="ids")])
        served = jit_load(prefix)
        s2, p2 = served(Tensor(ids))
        np.testing.assert_allclose(s2.numpy(), seq.numpy(), rtol=2e-2,
                                   atol=1e-3)
        np.testing.assert_allclose(p2.numpy(), pooled.numpy(), rtol=2e-2,
                                   atol=1e-3)
        # symbolic batch: other batch sizes serve from the same artifact
        ids5 = np.random.RandomState(1).randint(0, 128, (5, 16)).astype("int32")
        s5, p5 = served(Tensor(ids5))
        assert list(s5.shape) == [5, 16, 32] and list(p5.shape) == [5, 32]


class TestStaticSaveInferenceModel:
    def test_static_roundtrip(self, tmp_path):
        import paddle_tpu.static as static
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("img", [None, 6], "float32")
            w = static.create_parameter([6, 3], "float32")
            out = paddle.matmul(x, w)
        exe = static.Executor()
        feed = np.random.RandomState(3).randn(4, 6).astype(np.float32)
        (want,) = exe.run(prog, feed={"img": feed}, fetch_list=[out])

        prefix = str(tmp_path / "s")
        static.save_inference_model(prefix, [x], [out], exe, program=prog)
        layer, feed_names, fetch_names = static.load_inference_model(
            prefix, exe)
        assert feed_names == ["img"]
        got = layer(Tensor(feed))
        got = got.numpy() if isinstance(got, Tensor) else np.asarray(got)
        np.testing.assert_allclose(
            got, want.numpy() if isinstance(want, Tensor) else want,
            rtol=1e-5, atol=1e-6)


class TestLegacyReload:
    def test_picklable_layer_roundtrip(self, tmp_path):
        # module-level class: pickled layer reload path still works
        model = nn.Sequential(nn.Linear(4, 4), nn.ReLU())
        prefix = str(tmp_path / "leg")
        with pytest.warns(UserWarning, match="input_spec"):
            jit_save(model, prefix)
        loaded = jit_load(prefix)
        x = np.ones((2, 4), np.float32)
        np.testing.assert_allclose(loaded(Tensor(x)).numpy(),
                                   model(Tensor(x)).numpy(), rtol=1e-6)
