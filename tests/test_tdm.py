"""TDM tree-index retrieval tests (reference:
`distributed/index_dataset/index_wrapper.cc` TreeIndex,
`index_sampler.cc` LayerWiseSampler, `operators/tdm_sampler_op.cc`,
`operators/tdm_child_op.cc`; driven like the reference's
test_tdm_sampler_op / test_tdm_child_op + the tree-based retrieval
demo flow)."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.distributed.fleet import LayerWiseSampler, TreeIndex


def _tree(n_items=8, branch=2):
    return TreeIndex.from_items(np.arange(1, n_items + 1), branch=branch)


class TestTreeIndex:
    def test_structure_and_code_arithmetic(self):
        t = _tree(8)  # items 1..8 -> complete binary tree, height 4
        assert t.height == 4
        assert t.branch == 2
        assert t.get_all_leafs() == list(range(1, 9))
        # travel path: leaf -> root, one code per layer
        codes = t.get_travel_codes(1)
        assert len(codes) == 4 and codes[-1] == 0
        for deeper, upper in zip(codes, codes[1:]):
            assert (deeper - 1) // 2 == upper
        # layer codes partition the tree
        total = sum(len(t.get_layer_codes(lv)) for lv in range(t.height))
        assert total == 8 + 4 + 2 + 1
        # children of the root cover layer 1
        assert t.get_children_codes(0) == t.get_layer_codes(1)
        # ancestors at level: walking item 1 and item 8 to layer 1 lands
        # on different subtrees
        a1, a8 = t.get_ancestor_codes([1, 8], 1)
        assert a1 != a8
        assert {a1, a8} <= set(t.get_layer_codes(1))
        # emb ids: leaves keep item ids, internals are fresh
        leaf_ids = t.get_nodes(t.get_layer_codes(3))
        assert leaf_ids == list(range(1, 9))
        internal = t.get_nodes(t.get_layer_codes(1))
        assert all(i > 8 for i in internal)

    def test_uneven_item_count_pads_layers(self):
        t = _tree(5)
        assert t.height == 4
        assert len(t.get_layer_codes(3)) == 5
        assert sorted(t.get_all_leafs()) == [1, 2, 3, 4, 5]

    def test_layerwise_sampler_matches_contract(self):
        t = _tree(8)
        s = LayerWiseSampler(t, layer_counts=[1, 1, 2],
                             start_sample_layer=1, seed=3)
        rows = s.sample([[7], [9]], [3, 6])
        # per target: (1+1) + (1+1) + (1+2) = 7 rows
        assert rows.shape == (14, 3)
        for tgt_i, tgt in enumerate((3, 6)):
            block = rows[tgt_i * 7:(tgt_i + 1) * 7]
            path = t.get_nodes(t.get_travel_codes(tgt, 1))[::-1]
            # positives appear in order with label 1
            positives = block[block[:, 2] == 1]
            assert positives[:, 1].tolist() == path
            # negatives: right layer, never the positive
            negs = block[block[:, 2] == 0]
            for row in negs:
                lvl = next(lv for lv in range(1, t.height)
                           if row[1] in t.get_nodes(t.get_layer_codes(lv)))
                assert row[1] != path[lvl - 1]
        # determinism
        rows2 = LayerWiseSampler(t, [1, 1, 2], 1, seed=3).sample(
            [[7], [9]], [3, 6])
        np.testing.assert_array_equal(rows, rows2)


class TestTdmOps:
    def test_tdm_sampler_labels_negatives_and_determinism(self):
        t = _tree(8)
        travel = t.travel_array(start_level=1)
        layer_flat, offsets = t.layer_array(start_level=1)
        counts = np.diff(offsets).tolist()
        negs = [1, 2, 3]
        x = np.array([[1], [5], [8]], np.int64)
        out, labels, mask = paddle.ops.tdm_sampler(
            paddle.to_tensor(x), negs, counts, travel, layer_flat,
            layer_offsets=offsets, seed=7)
        out, labels, mask = (np.asarray(v.numpy())
                             for v in (out, labels, mask))
        width = sum(n + 1 for n in negs)
        assert out.shape == (3, width)
        np.testing.assert_array_equal(mask, np.ones_like(mask))
        col = 0
        for li, n in enumerate(negs):
            ids = set(layer_flat[offsets[li]:offsets[li + 1]].tolist())
            for bi, item in enumerate(x.ravel()):
                pos = travel[item, li]
                assert out[bi, col] == pos and labels[bi, col] == 1
                for j in range(1, n + 1):
                    assert out[bi, col + j] in ids
                    assert out[bi, col + j] != pos
                    assert labels[bi, col + j] == 0
            col += n + 1
        out2 = paddle.ops.tdm_sampler(
            paddle.to_tensor(x), negs, counts, travel, layer_flat,
            layer_offsets=offsets, seed=7)[0]
        np.testing.assert_array_equal(out, np.asarray(out2.numpy()))

    def test_tdm_sampler_padded_path_masks(self):
        t = _tree(5)  # uneven tree: some layers padded in travel
        travel = t.travel_array(start_level=1)
        # give item 1 a hole at the deepest layer to simulate a shorter
        # path (the reference masks rows whose travel id is 0)
        travel = travel.copy()
        travel[1, -1] = 0
        layer_flat, offsets = t.layer_array(start_level=1)
        counts = np.diff(offsets).tolist()
        out, labels, mask = paddle.ops.tdm_sampler(
            paddle.to_tensor(np.array([1], np.int64)), [1, 1, 1], counts,
            travel, layer_flat, layer_offsets=offsets, seed=0)
        mask = np.asarray(mask.numpy())
        assert mask[0, -2:].tolist() == [0, 0]  # padded deepest layer
        assert mask[0, :-2].tolist() == [1] * (mask.shape[1] - 2)

    def test_tdm_child_children_and_leaf_mask(self):
        t = _tree(8)
        info = t.tree_info_array()
        root_emb = t.get_nodes([0])[0]
        child, leaf = paddle.ops.tdm_child(
            paddle.to_tensor(np.array([root_emb], np.int64)), info, 2)
        child = np.asarray(child.numpy())
        leaf = np.asarray(leaf.numpy())
        want = t.get_nodes(t.get_children_codes(0))
        assert child[0].tolist() == want
        assert leaf[0].tolist() == [0, 0]  # layer-1 nodes: not leaves
        # a parent of leaves reports leaf_mask 1
        parent_code = t.get_travel_codes(3)[1]
        parent_emb = t.get_nodes([parent_code])[0]
        child2, leaf2 = paddle.ops.tdm_child(
            paddle.to_tensor(np.array([parent_emb], np.int64)), info, 2)
        kids = np.asarray(child2.numpy())[0]
        assert 3 in kids.tolist()
        assert np.asarray(leaf2.numpy())[0].tolist() == [1, 1]


class TestTdmRetrievalEndToEnd:
    def test_two_tower_trains_and_beam_retrieves(self):
        """TDM training loop: user tower dot node embeddings, BCE over
        tdm_sampler positives/negatives, then beam retrieval down the
        tree via tdm_child recovers each user's preferred item."""
        n_items, dim = 16, 8
        t = TreeIndex.from_items(np.arange(1, n_items + 1), branch=2)
        travel = t.travel_array(start_level=1)
        layer_flat, offsets = t.layer_array(start_level=1)
        counts = np.diff(offsets).tolist()
        negs = [min(2, c - 1) for c in counts]
        info = t.tree_info_array()
        n_emb = t.emb_id_count()

        paddle.seed(0)
        node_emb = nn.Embedding(n_emb, dim)
        user_emb = nn.Embedding(n_items + 1, dim)
        opt = paddle.optimizer.Adam(
            parameters=list(node_emb.parameters())
            + list(user_emb.parameters()), learning_rate=0.05)

        # each user u prefers item u (identity ground truth)
        users = np.arange(1, n_items + 1, dtype=np.int64)
        losses = []
        for step in range(60):
            batch = users.copy()
            out, labels, mask = paddle.ops.tdm_sampler(
                paddle.to_tensor(batch[:, None]), negs, counts, travel,
                layer_flat, layer_offsets=offsets, seed=step)
            u = user_emb(paddle.to_tensor(batch))          # (B, d)
            nodes = node_emb(out)                          # (B, W, d)
            logits = paddle.ops.sum(nodes * u.unsqueeze(1), axis=-1)
            m = mask.astype("float32")
            loss = paddle.ops.sum(
                paddle.nn.functional.binary_cross_entropy_with_logits(
                    logits, labels.astype("float32"), reduction="none")
                * m) / paddle.ops.sum(m)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])

        # beam-search retrieval (beam 4) down the tree
        def retrieve(uid):
            uv = np.asarray(user_emb(
                paddle.to_tensor(np.array([uid]))).numpy())[0]
            ne = np.asarray(node_emb.weight.numpy())
            frontier = np.asarray(t.get_nodes(t.get_children_codes(0)),
                                  np.int64)
            while True:
                child, leaf = paddle.ops.tdm_child(
                    paddle.to_tensor(frontier), info, 2)
                child = np.asarray(child.numpy()).ravel()
                leaf = np.asarray(leaf.numpy()).ravel()
                kids = child[child != 0]
                if kids.size == 0:
                    break
                scores = ne[kids] @ uv
                keep = kids[np.argsort(-scores)[:4]]
                if leaf[child != 0].all():
                    return keep
                frontier = keep
            return frontier

        hits = sum(1 for uid in users[:8] if uid in retrieve(int(uid)))
        assert hits >= 6, f"retrieval hits {hits}/8"


class TestTreeIndexValidation:
    def test_rejects_bad_inputs(self):
        import pytest
        with pytest.raises(ValueError, match="positive"):
            TreeIndex.from_items([0, 1, 2])
        with pytest.raises(ValueError, match="branch"):
            TreeIndex.from_items([1, 2], branch=1)
        with pytest.raises(ValueError, match="duplicate"):
            TreeIndex.from_items([1, 1, 2])
        with pytest.raises(ValueError, match="densify"):
            TreeIndex.from_items([5, 10**9])
        t = _tree(4)
        with pytest.raises(ValueError, match="never terminate"):
            # start at the root layer (size 1): no negative exists
            LayerWiseSampler(t, [1, 1, 1], start_sample_layer=0,
                             seed=0).sample([[1]], [2])
