"""TestDistBase model fixture (reference: the dist_mnist.py-style trainer
scripts run by `test_dist_base.py:744` — train a fixed model, print per-step
losses to stdout for the harness to compare across world sizes).

Runs the full framework path: init_parallel_env (JAX coordination service
bootstrap in multi-process mode) → fleet.init → distributed_model
(DataParallel over the global 'dp' mesh) → @to_static compiled train step
with dp-sharded batches.
"""
import os
import sys

import numpy as np


def main():
    import os
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    import paddle_tpu.distributed as dist
    import paddle_tpu.distributed.fleet as fleet
    from jax.sharding import PartitionSpec as P
    import jax

    dist.init_parallel_env()
    world = jax.device_count()
    mode = os.environ.get("DIST_FIXTURE_MODE", "dp")

    paddle.seed(42)
    if mode == "mp" and world > 1:
        # megatron pair: column-parallel then row-parallel linear over 'mp'
        model = nn.Sequential(nn.Linear(16, 32), nn.ReLU(),
                              nn.Linear(32, 4))
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": world,
                                   "pp_degree": 1, "sharding_degree": 1}
        fleet.init(is_collective=True, strategy=strategy)
        model[0].weight.pspec = P(None, "mp")
        model[0].bias.pspec = P("mp")
        model[2].weight.pspec = P("mp", None)
        model[2].bias.pspec = P()
        model = fleet.distributed_model(model)
    elif mode == "hybrid" and world > 1:
        # multi-host hybrid: dp axis spans the PROCESS boundary (the DCN
        # analog), mp shards megatron-style within each process (ICI)
        import jax as _jax
        procs = _jax.process_count()
        mp = world // procs
        model = nn.Sequential(nn.Linear(16, 32), nn.ReLU(),
                              nn.Linear(32, 4))
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": procs, "mp_degree": mp,
                                   "pp_degree": 1, "sharding_degree": 1}
        fleet.init(is_collective=True, strategy=strategy)
        model[0].weight.pspec = P(None, "mp")
        model[0].bias.pspec = P("mp")
        model[2].weight.pspec = P("mp", None)
        model[2].bias.pspec = P()
        model = fleet.distributed_model(model)
    else:
        model = nn.Sequential(nn.Linear(16, 32), nn.ReLU(),
                              nn.Linear(32, 4))
        if world > 1:
            strategy = fleet.DistributedStrategy()
            strategy.hybrid_configs = {"dp_degree": world, "mp_degree": 1,
                                       "pp_degree": 1, "sharding_degree": 1}
            fleet.init(is_collective=True, strategy=strategy)
            model = fleet.distributed_model(model)
    inner = model
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=model.parameters())

    def step(xb, yb):
        loss = nn.functional.mse_loss(inner(xb), yb)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    sfn = paddle.jit.to_static(step)
    if world > 1 and mode == "dp":
        sfn._arg_pspecs = [P("dp"), P("dp")]
    elif world > 1 and mode == "hybrid":
        sfn._arg_pspecs = [P("dp"), P("dp")]  # batch over dp, mp replicated

    rng = np.random.RandomState(7)
    for i in range(5):
        # every process feeds the identical GLOBAL batch (single-controller
        # global-view semantics; GSPMD keeps only the local dp shard)
        x = rng.rand(8, 16).astype(np.float32)
        y = rng.rand(8, 4).astype(np.float32)
        loss = sfn(paddle.to_tensor(x), paddle.to_tensor(y))
        print(f"LOSS {i} {float(np.asarray(loss._value)):.8f}", flush=True)
    print("DONE", flush=True)


if __name__ == "__main__":
    main()
