"""Eager multi-process collective fixture: every op here used to be a
silent identity across processes (round-2 weakness) — now they are REAL
cross-process collectives or loud errors. Run under the launcher with 2
processes; prints CHECK lines the parent asserts on."""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.distributed as dist

dist.init_parallel_env()
rank = dist.get_rank()
world = dist.get_world_size()
assert world == 2, world

# -- all_reduce: ranks hold different values; both must see the sum -------
t = paddle.to_tensor(np.full((3,), float(rank + 1), np.float32))
dist.all_reduce(t)
print(f"CHECK allreduce {t.numpy().tolist()}", flush=True)

# -- broadcast from rank 1 ------------------------------------------------
b = paddle.to_tensor(np.full((2,), float(rank * 10), np.float32))
dist.broadcast(b, src=1)
print(f"CHECK broadcast {b.numpy().tolist()}", flush=True)

# -- all_gather -----------------------------------------------------------
lst = []
dist.all_gather(lst, paddle.to_tensor(np.float32(rank + 5)))
print(f"CHECK allgather {[float(x.numpy()) for x in lst]}", flush=True)

# -- subgroup: ranks=[0] — member reduces over itself, non-member no-op ---
g = dist.new_group(ranks=[0])
s = paddle.to_tensor(np.float32(rank + 1))
dist.all_reduce(s, group=g)
print(f"CHECK subgroup {float(s.numpy())}", flush=True)

# -- barrier is a real rendezvous ----------------------------------------
dist.barrier()
print("CHECK barrier done", flush=True)

# -- send/recv still raise loudly eagerly --------------------------------
try:
    dist.send(t, dst=1)
    print("CHECK send no-error", flush=True)
except NotImplementedError:
    print("CHECK send raises", flush=True)
