"""CTR-style PS training fixture (reference model: the dist_ctr /
dist_fleet_ctr test fixtures of `test_dist_base.py` — a sparse-embedding
model trained against 1 server + N workers on localhost).

Modes via env:
  PS_ROLE=server|worker|local
  PS_MODE=sync|async|geo
  PS_ENDPOINTS, PADDLE_TRAINER_ID, PADDLE_TRAINERS_NUM, PADDLE_PSERVER_ID
Prints "LOSS <step> <value>" lines; local mode emulates geo k=1 exactly.
"""
import os
import sys

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed import ps
from paddle_tpu.distributed.fleet.base.role_maker import PaddleCloudRoleMaker

VOCAB, DIM, SLOTS, BATCH, STEPS = 100, 8, 3, 64, 200
LR = 0.2


_ID_WEIGHTS = np.random.RandomState(42).randn(VOCAB).astype(np.float32)


def synth_batch(step, worker_id, n_workers):
    """Deterministic synthetic CTR batch. The label is an additive
    function of per-id weights — exactly the structure a sparse-embedding
    + linear model can learn (memorize per-id scores)."""
    rng = np.random.RandomState(1234 + step * 17 + worker_id)
    ids = rng.randint(0, VOCAB, (BATCH, SLOTS)).astype(np.int64)
    # label keyed on the first slot's id alone: each embedding row can
    # directly memorize its label, so a few epochs converge decisively
    label = _ID_WEIGHTS[ids[:, 0]] > 0.0
    return ids, label.astype(np.float32).reshape(-1, 1)


class CtrModel(nn.Layer):
    def __init__(self, sparse=True):
        super().__init__()
        if sparse:
            self.emb = ps.SparseEmbedding([VOCAB, DIM], init_range=0.1)
        else:
            self.emb = None
        self.fc1 = nn.Linear(SLOTS * DIM, 16)
        self.fc2 = nn.Linear(16, 1)

    def forward(self, ids, emb_out=None):
        if self.emb is not None:
            e = self.emb(ids)
        else:
            e = emb_out
        h = paddle.ops.reshape(e, [e.shape[0], SLOTS * DIM])
        h = paddle.nn.functional.relu(self.fc1(h))
        return self.fc2(h)


def loss_fn(logits, label):
    return paddle.nn.functional.binary_cross_entropy_with_logits(
        logits, paddle.to_tensor(label))


def run_server():
    role = PaddleCloudRoleMaker(is_collective=False)
    strategy = make_strategy()
    fleet.init(role, strategy=strategy)
    paddle.seed(0)
    model = CtrModel()  # registers the sparse table + dense shapes
    fleet.init_server(model)
    print("SERVER_READY", flush=True)
    fleet.run_server()


def make_strategy():
    s = fleet.DistributedStrategy()
    mode = os.environ.get("PS_MODE", "sync")
    s.a_sync = mode != "sync"
    s.a_sync_configs = {"learning_rate": LR}
    if mode == "geo":
        s.a_sync_configs["k_steps"] = int(os.environ.get("PS_K_STEPS", "1"))
    return s


def run_worker():
    role = PaddleCloudRoleMaker(is_collective=False)
    strategy = make_strategy()
    fleet.init(role, strategy=strategy)
    paddle.seed(0)
    model = CtrModel()
    fleet.init_worker(model)
    mode = os.environ.get("PS_MODE", "sync")
    wid = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    nw = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    opt = (paddle.optimizer.SGD(parameters=model.parameters(),
                                learning_rate=LR)
           if mode == "geo" else None)
    for step in range(STEPS):
        ids, label = synth_batch(step, wid, nw)
        logits = model(paddle.to_tensor(ids))
        loss = loss_fn(logits, label)
        loss.backward()
        fleet.ps_step(opt)
        print(f"LOSS {step} {float(loss.numpy()):.6f}", flush=True)
    fleet.barrier_worker()  # all workers done training
    if wid == 0 and os.environ.get("PS_SAVE"):
        fleet.save_persistables(dirname=os.environ["PS_SAVE"])
        size = fleet.ps_runtime().client.sparse_size(model.emb.table_id)
        print(f"SPARSE_SIZE {size}", flush=True)
    fleet.barrier_worker()  # save complete before anyone tears down
    fleet.stop_worker()
    if wid == 0:
        fleet.shutdown_servers()


def run_local():
    """Pure-local emulation of geo k=1: full embedding matrix initialized
    with the server's deterministic per-key rule, plain SGD."""
    from paddle_tpu.distributed.ps.embedding import deterministic_init

    paddle.seed(0)
    model = CtrModel(sparse=False)
    table = paddle.to_tensor(
        deterministic_init(1000, np.arange(VOCAB, dtype=np.uint64), DIM, 0.1))
    table.stop_gradient = False
    params = list(model.parameters())
    opt = paddle.optimizer.SGD(parameters=params, learning_rate=LR)
    for step in range(STEPS):
        ids, label = synth_batch(step, 0, 1)
        emb = paddle.ops.gather(table, paddle.to_tensor(ids.ravel()))
        emb = paddle.ops.reshape(emb, [BATCH, SLOTS, DIM])
        logits = model(None, emb_out=emb)
        loss = loss_fn(logits, label)
        loss.backward()
        opt.step()
        opt.clear_grad()
        # manual SGD on the embedding table leaf
        if table._grad is not None:
            import jax.numpy as jnp
            table._value = table._value - LR * jnp.asarray(table._grad)
            table._grad = None
        print(f"LOSS {step} {float(loss.numpy()):.6f}", flush=True)


if __name__ == "__main__":
    role = os.environ.get("PS_ROLE", "local")
    if role == "server":
        run_server()
    elif role == "worker":
        run_worker()
    else:
        run_local()
