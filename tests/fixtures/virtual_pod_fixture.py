"""Virtual-pod dp training fixture (run under testing.virtual_pod).

One rank of a data-parallel pod: deterministic per-step batches are
sharded over the CURRENT pod world, gradients (and the loss) cross the
process boundary through the coordinator's float64 allreduce, and the
model/optimizer state checkpoints through the rank-0-committed
multi-process checkpoint. On a peer's death: detect (RankFailedError /
a failed pod save), re-form at the smaller world size, elastically
restore from the last pod checkpoint, and continue — losses must stay
within 1e-6 of a single-process control run of the same fixture.

The HEAL half (``POD_FIX_TARGET_WORLD``): at every step boundary the
ranks agree (an allreduce of each rank's lobby observation, so no rank
reforms alone) on whether replacement joiners are parked at the
coordinator; when one is, every rank commits the current state
(``mgr.save``), calls ``pod.reform()`` — the world GROWS, the joiner is
admitted — and every rank (incumbents and the replacement alike)
restores from that checkpoint, so the grown world resumes from one
consistent step. From ``POD_FIX_HEAL_BY_STEP`` onward a rank that finds
itself below the target world BLOCKS at the boundary until a joiner
arrives (bounded by ``POD_FIX_HEAL_TIMEOUT``) — the tail steps of the
run are guaranteed to execute at full world, which is what the
1e-6-vs-control acceptance needs.

The forward/backward math is hand-written numpy float64 against the
framework-held float32 params: the mean-of-shard-means the pod computes
and the full-batch mean the control computes then agree to ~1e-15
before the float32 grad cast, so "within 1e-6 of control" is a real
invariant, not tolerance slack absorbing reduction-order noise. The
UPDATE itself (Momentum) runs through the real optimizer, and the
checkpoint round-trips the real framework state.

Stdout protocol (the test parses these):
  POD_READY rank=R world=W gen=G
  PS_OK rank=R n=N                     (optional PS client demo)
  LOSS <step> <loss>
  CKPT <step>
  FAILURE_DETECTED t=<wall> failed=[..] err=<ExcType>
  REFORMED rank=R world=W gen=G dir=<shrink|grow|steady> t=<wall>
  RESUME_FROM <step> t=<wall>
  HEAL_TIMEOUT step=<step>             (degraded: no joiner arrived)
  DONE rank=R world=W
"""
import os
import sys
import time

import numpy as np

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402

import paddle_tpu as paddle  # noqa: E402
import paddle_tpu.observability as obs  # noqa: E402
from paddle_tpu import nn  # noqa: E402
from paddle_tpu.checkpoint.multihost import PodCheckpointManager  # noqa: E402
from paddle_tpu.distributed.pod import (BarrierTimeoutError,  # noqa: E402
                                        PodRuntime, RankFailedError)
from paddle_tpu.checkpoint.multihost import PodCheckpointError  # noqa: E402
from paddle_tpu.testing import faults  # noqa: E402

STEPS = int(os.environ.get("POD_FIX_STEPS", "8"))
CKPT_EVERY = int(os.environ.get("POD_FIX_CKPT_EVERY", "3"))
BATCH = int(os.environ.get("POD_FIX_BATCH", "8"))
ROOT = os.environ["POD_FIX_CKPT_ROOT"]
# heal knobs: 0/-1 = never wait for replacements (PR-11 behavior)
TARGET_WORLD = int(os.environ.get("POD_FIX_TARGET_WORLD", "0"))
HEAL_BY_STEP = int(os.environ.get("POD_FIX_HEAL_BY_STEP", "-1"))
HEAL_TIMEOUT = float(os.environ.get("POD_FIX_HEAL_TIMEOUT", "60"))
IN_DIM, HID = 8, 16


def _data(step):
    rng = np.random.RandomState(1000 + step)
    return (rng.rand(BATCH, IN_DIM),          # float64
            rng.rand(BATCH, 1))


def _forward_backward(params, x, y):
    """Hand float64 MLP (Linear-ReLU-Linear, MSE): per-shard SUMS —
    squared-error sum and sum-gradients in the params' order
    [W1, b1, W2, b2]. The caller allreduces the sums and scales by the
    GLOBAL batch, so the pod result equals the full-batch mean exactly
    (to float64 addition order, ~1e-16) for ANY sharding — equal or
    ragged — and any world size."""
    W1, b1, W2, b2 = [np.asarray(p, dtype=np.float64) for p in params]
    h = x @ W1 + b1
    hr = np.maximum(h, 0.0)
    out = hr @ W2 + b2
    d = out - y
    sq = float(np.sum(d * d))
    dout = 2.0 * d  # unscaled: the global 1/N applies after allreduce
    gW2 = hr.T @ dout
    gb2 = dout.sum(axis=0)
    dhr = dout @ W2.T
    dh = dhr * (h > 0.0)
    gW1 = x.T @ dh
    gb1 = dh.sum(axis=0)
    return sq, [gW1, gb1, gW2, gb2]


def main():
    obs.enable()  # runlog + flight recorder arm from the pod env
    pod = PodRuntime.from_env()
    pod.init()
    print(f"POD_READY rank={pod.rank} world={pod.world_size} "
          f"gen={pod.gen}", flush=True)

    ps_ep = os.environ.get("POD_FIX_PS_ENDPOINT")
    if ps_ep:
        # the cross-process client demo: every pod rank pulls from the
        # (parent-hosted) PS over the real wire before training
        from paddle_tpu.distributed.ps.client import PsClient
        cli = PsClient([ps_ep])
        cli.register_dense(0, 4)
        vals = cli.pull_dense_init(0, np.zeros(4, np.float32))
        print(f"PS_OK rank={pod.rank} n={int(np.asarray(vals).size)}",
              flush=True)

    paddle.seed(7)
    model = nn.Sequential(nn.Linear(IN_DIM, HID), nn.ReLU(),
                          nn.Linear(HID, 1))
    opt = paddle.optimizer.Momentum(parameters=model.parameters(),
                                    learning_rate=0.05, momentum=0.9)
    mgr = PodCheckpointManager(ROOT, pod=pod, timeout=60.0)
    mgr.add_model(model).add_optimizer(opt)
    params = list(model.parameters())

    meta = mgr.restore()
    step = (int(meta["step"]) + 1) if meta else 0
    if meta:
        print(f"RESUME_FROM {step} t={time.time():.3f}", flush=True)

    def reform_and_restore():
        nonlocal step, meta
        old_w = pod.world_size
        pod.reform(timeout=30.0)
        d = ("grow" if pod.world_size > old_w
             else "shrink" if pod.world_size < old_w else "steady")
        print(f"REFORMED rank={pod.rank} world={pod.world_size} "
              f"gen={pod.gen} dir={d} t={time.time():.3f}", flush=True)
        meta = mgr.restore()
        step = (int(meta["step"]) + 1) if meta else 0
        print(f"RESUME_FROM {step} t={time.time():.3f}", flush=True)

    while step < STEPS:
        try:
            # -- window boundary: learn of parked joiners and grow back.
            # The decision MUST be collective: each rank's lobby glimpse
            # can differ (a joiner landing between two polls), and a
            # rank that reforms alone while its peer enters the step
            # barrier deadlocks both — so the observed count is
            # allreduced and every rank acts on the SAME total.
            attempt = 0
            wait_t0 = None
            while True:
                joiners = len(pod.pending_joiners())
                agreed = pod.allreduce(
                    [float(joiners)],
                    name=f"lobby{step}.{attempt}.g{pod.gen}",
                    timeout=30.0)[0]
                attempt += 1
                if agreed > 0:
                    # commit the pre-grow state so EVERY rank of the
                    # grown world (incumbents + replacement) restores
                    # to the same step from the same checkpoint
                    if step > 0:
                        mgr.save(step - 1)
                    reform_and_restore()
                    # the admitted replacement starts ITS boundary loop
                    # at attempt 0 — reset so the next lobby allreduce
                    # name matches across incumbents and replacements
                    attempt = 0
                    wait_t0 = None
                    continue  # more joiners may be parked already
                if TARGET_WORLD and 0 <= HEAL_BY_STEP <= step \
                        and pod.world_size < TARGET_WORLD:
                    # from HEAL_BY_STEP on, a degraded world blocks at
                    # the boundary for its replacement (bounded): the
                    # tail of the run must execute at full world
                    wait_t0 = time.time() if wait_t0 is None else wait_t0
                    if time.time() - wait_t0 > HEAL_TIMEOUT:
                        print(f"HEAL_TIMEOUT step={step}", flush=True)
                        break
                    time.sleep(0.25)
                    continue
                break

            faults.kill_point("pod/before_barrier")
            pod.barrier(f"step{step}.g{pod.gen}", timeout=30.0)
            x, y = _data(step)
            lo, hi = pod.shard_range(BATCH)
            host = [np.asarray(p._value) for p in params]
            sq, grads = _forward_backward(host, x[lo:hi], y[lo:hi])
            faults.kill_point("pod/mid_step")
            flat = np.concatenate([g.ravel() for g in grads]
                                  + [np.array([sq])])
            # allreduce SUMS, then scale by the GLOBAL batch: exact for
            # ragged shards too (a 3-survivor world splits 8 as 3/3/2)
            mean = pod.allreduce(flat, name=f"grads{step}.g{pod.gen}",
                                 timeout=30.0) / float(BATCH)
            print(f"LOSS {step} {mean[-1]:.12e}", flush=True)
            off = 0
            for p, g in zip(params, grads):
                n = g.size
                p._grad = jnp.asarray(
                    mean[off:off + n].reshape(g.shape).astype(np.float32))
                off += n
            opt.step()
            opt.clear_grad()
            if (step + 1) % CKPT_EVERY == 0:
                mgr.save(step)
                obs.memory.runlog_snapshot(rank=pod.origin, export=True)
                print(f"CKPT {step}", flush=True)
            step += 1
        except (RankFailedError, BarrierTimeoutError,
                PodCheckpointError) as e:
            print(f"FAILURE_DETECTED t={time.time():.3f} "
                  f"failed={getattr(e, 'ranks', [])} "
                  f"err={type(e).__name__}", flush=True)
            reform_and_restore()

    obs.memory.runlog_snapshot(rank=pod.origin, export=True)
    print(f"DONE rank={pod.rank} world={pod.world_size}", flush=True)
    pod.shutdown()
    obs.stop_run()
    return 0


if __name__ == "__main__":
    sys.exit(main())
