"""Elastic trainer node for the fault-injection test (reference flow:
`fleet/elastic.py` watch:316 — nodes register in the job KV, train with
auto-checkpoint, and on membership change re-rank + relaunch + resume).

env: ELASTIC_ENDPOINT, PADDLE_ELASTIC_KV_ENDPOINT, PADDLE_ELASTIC_NP,
PADDLE_AUTO_CHECKPOINT_DIR, PADDLE_JOB_ID, VICTIM_EPOCH (die mid-epoch).
Prints: RANK r nodes=n | EPOCH e | INTERRUPTED | RESUME_FROM e | DONE
"""
import os
import sys
import time

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.distributed.fleet.elastic import ElasticManager
from paddle_tpu.incubate.auto_checkpoint import TrainEpochRange

ENDPOINT = os.environ["ELASTIC_ENDPOINT"]
NP = int(os.environ.get("PADDLE_ELASTIC_NP", "2"))
VICTIM_EPOCH = int(os.environ.get("VICTIM_EPOCH", "-1"))
MAX_EPOCH = 10

em = ElasticManager(ENDPOINT, np=NP, ttl=3, heartbeat_interval=0.5)
em.register()
assert em.wait_ready(60), "cluster never became whole"

paddle.seed(0)
model = paddle.nn.Linear(4, 2)
opt = paddle.optimizer.Adam(parameters=model.parameters())

while True:
    rank = em.rank()
    nodes = em.live_nodes()
    print(f"RANK {rank} nodes={len(nodes)}", flush=True)
    baseline = list(nodes)
    tr = TrainEpochRange(MAX_EPOCH, "elastic_demo").add_model(
        model).add_optimizer(opt)
    if rank != 0:
        tr._save = lambda epoch: None  # one writer per job checkpoint
    interrupted = False
    first = None
    for epoch in tr:
        if first is None:
            first = epoch
            print(f"RESUME_FROM {epoch}", flush=True)
        print(f"EPOCH {epoch}", flush=True)
        x = paddle.to_tensor(np.ones((2, 4), np.float32))
        loss = model(x).sum()
        loss.backward()
        opt.step()
        opt.clear_grad()
        if VICTIM_EPOCH >= 0 and epoch == VICTIM_EPOCH:
            os._exit(1)  # fault injection: die mid-epoch, no save
        time.sleep(0.6)
        if em.live_nodes() != baseline:
            print("INTERRUPTED", flush=True)
            interrupted = True
            break
    if not interrupted:
        print("DONE", flush=True)
        # completion rendezvous: keep heartbeating until every slot has a
        # done flag, or the peer would see our exit as a fault
        em.store.put(f"{em.job_id}/done/{ENDPOINT}", "1")
        deadline = time.time() + 60
        while time.time() < deadline:
            if len(em.store.list(f"{em.job_id}/done/")) >= NP:
                break
            time.sleep(0.2)
        break
    # hold until the scheduler brings the cluster back to np, then
    # re-rank and resume from the auto-checkpoint (relaunch-in-place)
    assert em.wait_ready(60), "replacement never arrived"

em.exit()
sys.exit(0)
