"""ONNX export (reference: python/paddle/onnx/export.py). The exporter
maps the layer's JAXPR onto ONNX ops and serializes standard protobuf
wire format with no onnx package; verified with the bundled reader."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.jit.to_static import InputSpec
from paddle_tpu.onnx import export, read_model


def test_export_mlp(tmp_path):
    m = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    path = export(m, str(tmp_path / "mlp"),
                  input_spec=[InputSpec([None, 4], "float32", name="feat")])
    mm = read_model(path)
    ops = [n[0] for n in mm["nodes"]]
    assert ops.count("MatMul") == 2
    assert "Max" in ops  # relu = max(x, 0)
    assert mm["inputs"] == ["feat"]
    assert len(mm["outputs"]) == 1
    assert mm["producer"] == "paddle_tpu"
    assert mm["opset"] == 13
    # both weight matrices land as initializers with the right dims
    dims = sorted(tuple(d) for _, d in mm["initializers"]
                  if len(d) == 2)
    assert (4, 8) in dims and (8, 2) in dims


def test_export_convnet(tmp_path):
    m = nn.Sequential(nn.Conv2D(3, 4, 3, padding=1, stride=2), nn.ReLU())
    path = export(m, str(tmp_path / "conv"),
                  input_spec=[InputSpec([None, 3, 8, 8], "float32")])
    ops = [n[0] for n in read_model(path)["nodes"]]
    assert "Conv" in ops


def test_export_softmax_tanh_graph(tmp_path):
    class M(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 4)

        def forward(self, x):
            import paddle_tpu.nn.functional as F
            return F.softmax(F.tanh(self.fc(x)), axis=-1)

    path = export(M(), str(tmp_path / "smax"),
                  input_spec=[InputSpec([None, 4], "float32")])
    ops = [n[0] for n in read_model(path)["nodes"]]
    assert "Tanh" in ops
    assert "Exp" in ops and "Div" in ops  # softmax decomposition


def test_unsupported_primitive_is_loud(tmp_path):
    class Weird(nn.Layer):
        def forward(self, x):
            import paddle_tpu.ops as ops
            return ops.cumsum(x, axis=0)

    with pytest.raises(NotImplementedError, match="cumsum"):
        export(Weird(), str(tmp_path / "weird"),
               input_spec=[InputSpec([4], "float32")])


def test_wire_format_roundtrip(tmp_path):
    """The writer emits valid protobuf wire format: a field-level reparse
    of the file reproduces the node/initializer structure exactly."""
    from paddle_tpu.onnx._proto import parse_fields

    m = nn.Sequential(nn.Linear(3, 3))
    path = export(m, str(tmp_path / "p"),
                  input_spec=[InputSpec([None, 3], "float32")])
    with open(path, "rb") as f:
        fields = parse_fields(f.read())
    field_nums = [f for f, _, _ in fields]
    assert 1 in field_nums  # ir_version
    assert 7 in field_nums  # graph
    assert 8 in field_nums  # opset_import
