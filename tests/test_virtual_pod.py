"""Multi-host virtual pod runtime (ISSUE 11) + elastic scale-UP
(ISSUE 12).

The contract under test: a pod of REAL localhost processes survives a
REAL SIGKILL of one rank mid-step — the failure is detected within the
configured window and named, the survivors re-form at the smaller world
size, elastically restore from the rank-0-committed multi-process
checkpoint (per-rank shard files, one manifest), continue with losses
within 1e-6 of a single-process control, and `tools/trace_view.py`
merges every rank's run-log — the dead rank's included — into one
trace. ISSUE 12 closes the loop UPWARD: the supervisor RESPAWNS the
reaped rank under a budgeted-backoff RestartPolicy, the replacement
parks in the coordinator's lobby, the survivors' next reform GROWS the
world back, and every rank restores from the latest pod checkpoint —
kill -> shrink -> heal -> grow, generations strictly monotone, losses
still within 1e-6 of the uninterrupted control; three consecutive
kill/heal cycles (one killing a replacement DURING its own restore)
never deadlock. Plus the coordinator/runtime unit semantics
(rendezvous, lobby admission, barrier-with-timeout, lease-expiry +
straggler detection, deterministic allreduce, re-formation up and
down), the pod checkpoint partition/merge, and the satellites
(pod-failure flight dumps, respawn lint, reform timeline, shared
restart policy).
"""
import io
import json
import os
import re
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from paddle_tpu.distributed.pod import (BarrierTimeoutError, PodCoordinator,
                                        PodRuntime, RankFailedError,
                                        RestartPolicy, start_coordinator)
from paddle_tpu.testing import faults
from paddle_tpu.testing.virtual_pod import VirtualPod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures",
                       "virtual_pod_fixture.py")

sys.path.insert(0, os.path.join(REPO, "tools"))


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


# ---------------------------------------------------------------- unit level

class TestCoordinator:
    """In-process pod semantics: threads as ranks against a real
    coordinator server (the TCP path, minus the process boundary)."""

    def _pod(self, ep, n, r, **kw):
        kw.setdefault("heartbeat_interval", 0.1)
        kw.setdefault("barrier_timeout", 10.0)
        return PodRuntime(ep, n, r, **kw)

    def test_join_is_a_uniqueid_exchange(self):
        coord, ep = start_coordinator(expected=2, lease_ttl=5.0)
        try:
            got = {}

            def run(r):
                pod = self._pod(ep, 2, r).init()
                got[r] = (pod.uid, pod.gen, pod.rank, pod.world_size)
                pod.shutdown()

            ts = [threading.Thread(target=run, args=(r,)) for r in (0, 1)]
            [t.start() for t in ts]
            [t.join(30) for t in ts]
            # every rank got the SAME minted uid (the NCCL-uniqueId
            # analog) and a consistent roster
            assert got[0][0] == got[1][0] == coord.uid
            assert got[0][1:] == (0, 0, 2) and got[1][1:] == (0, 1, 2)
        finally:
            coord.close()

    def test_barrier_timeout_names_absent_rank(self):
        coord, ep = start_coordinator(expected=2, lease_ttl=30.0)
        try:
            pods = {}

            def run(r):
                pods[r] = self._pod(ep, 2, r).init()

            ts = [threading.Thread(target=run, args=(r,)) for r in (0, 1)]
            [t.start() for t in ts]
            [t.join(30) for t in ts]
            # rank 1 keeps heartbeating (stays live) but never arrives
            with pytest.raises(BarrierTimeoutError) as ei:
                pods[0].barrier("never", timeout=0.8)
            assert ei.value.waiting == [1]
            assert "never" in str(ei.value)
        finally:
            for p in pods.values():
                p.shutdown()
            coord.close()

    def test_barrier_fails_loudly_on_marked_death(self):
        coord, ep = start_coordinator(expected=2, lease_ttl=30.0)
        try:
            pods = {}

            def run(r):
                pods[r] = self._pod(ep, 2, r).init()

            ts = [threading.Thread(target=run, args=(r,)) for r in (0, 1)]
            [t.start() for t in ts]
            [t.join(30) for t in ts]
            err = {}

            def waiter():
                try:
                    pods[0].barrier("b", timeout=10.0)
                except RankFailedError as e:
                    err["e"] = e

            t = threading.Thread(target=waiter)
            t.start()
            time.sleep(0.2)
            coord.mark_failed(1, "killed by SIGKILL (supervisor)")
            t.join(10)
            assert err["e"].ranks == [1]
            assert "SIGKILL" in str(err["e"])
        finally:
            for p in pods.values():
                p.shutdown()
            coord.close()

    def test_lease_expiry_detection_is_bounded(self):
        """No supervisor: a silently dead rank (heartbeat stops) is
        detected within lease_ttl + one monitor sweep."""
        ttl = 0.8
        coord, ep = start_coordinator(expected=2, lease_ttl=ttl)
        try:
            pods = {}

            def run(r):
                pods[r] = self._pod(ep, 2, r).init()

            ts = [threading.Thread(target=run, args=(r,)) for r in (0, 1)]
            [t.start() for t in ts]
            [t.join(30) for t in ts]
            pods[1]._hb_stop.set()  # the silent death
            t0 = time.time()
            with pytest.raises(RankFailedError) as ei:
                pods[0].barrier("b", timeout=10.0)
            detect = time.time() - t0
            assert ei.value.ranks == [1]
            assert "lease expired" in str(ei.value)
            assert detect < ttl + 1.5, f"detection took {detect:.2f}s"
        finally:
            for p in pods.values():
                p.shutdown()
            coord.close()

    def test_allreduce_rank_sorted_deterministic_sum(self):
        coord, ep = start_coordinator(expected=3, lease_ttl=10.0)
        try:
            out = {}

            def run(r):
                pod = self._pod(ep, 3, r).init()
                out[r] = pod.allreduce(np.full(4, float(r + 1)),
                                       timeout=10.0)
                pod.shutdown()

            ts = [threading.Thread(target=run, args=(r,))
                  for r in (0, 1, 2)]
            [t.start() for t in ts]
            [t.join(30) for t in ts]
            for r in (0, 1, 2):
                np.testing.assert_array_equal(out[r], np.full(4, 6.0))
        finally:
            coord.close()

    def test_reform_shrinks_world_and_redenses_ranks(self):
        coord, ep = start_coordinator(expected=3, lease_ttl=30.0)
        try:
            pods = {}

            def run(r):
                pods[r] = self._pod(ep, 3, r).init()

            ts = [threading.Thread(target=run, args=(r,))
                  for r in (0, 1, 2)]
            [t.start() for t in ts]
            [t.join(30) for t in ts]
            coord.mark_failed(1, "killed")
            views = {}

            def ref(r):
                views[r] = pods[r].reform(timeout=10.0)

            ts = [threading.Thread(target=ref, args=(r,)) for r in (0, 2)]
            [t.start() for t in ts]
            [t.join(30) for t in ts]
            # dense re-rank: survivor 0 stays 0, survivor 2 becomes 1
            assert views[0] == {"gen": 1, "rank": 0, "world_size": 2}
            assert views[2] == {"gen": 1, "rank": 1, "world_size": 2}
            # data re-shards under the new world automatically
            assert pods[2].shard_range(8) == (4, 8)
            # a stale-generation op is rejected, not deadlocked
            resp = coord.handle_req({"op": "barrier", "rank": 0,
                                     "gen": 0, "name": "x",
                                     "timeout": 1.0})
            assert resp == {"ok": False, "error": "stale_gen", "gen": 1}
        finally:
            for p in pods.values():
                p.shutdown()
            coord.close()

    def test_lease_detection_survives_a_reform(self):
        """The re-formed pod must keep lease enforcement at the SMALLER
        world size: a second silent death after the first reform is
        still detected within the ttl (without any supervisor mark)."""
        ttl = 0.8
        coord, ep = start_coordinator(expected=3, lease_ttl=ttl)
        try:
            pods = {}

            def run(r):
                pods[r] = self._pod(ep, 3, r).init()

            ts = [threading.Thread(target=run, args=(r,))
                  for r in (0, 1, 2)]
            [t.start() for t in ts]
            [t.join(30) for t in ts]
            pods[2]._hb_stop.set()  # first silent death
            with pytest.raises(RankFailedError):
                pods[0].barrier("b0", timeout=10.0)
            views = {}

            def ref(r):
                try:
                    pods[r].check_failures()
                except RankFailedError:
                    pass
                views[r] = pods[r].reform(timeout=10.0)

            ts = [threading.Thread(target=ref, args=(r,)) for r in (0, 1)]
            [t.start() for t in ts]
            [t.join(30) for t in ts]
            assert views[0]["world_size"] == views[1]["world_size"] == 2
            pods[1]._hb_stop.set()  # SECOND silent death, post-reform
            t0 = time.time()
            with pytest.raises(RankFailedError) as ei:
                pods[0].barrier("b1", timeout=10.0)
            assert time.time() - t0 < ttl + 1.5
            assert "lease expired" in str(ei.value)
        finally:
            for p in pods.values():
                p.shutdown()
            coord.close()

    def test_join_skew_longer_than_ttl_still_forms(self):
        """Leases must not bind during RENDEZVOUS: a peer that takes
        longer than lease_ttl to start (cold interpreter under CI load)
        must not get the early joiner falsely marked dead — formation
        re-stamps every lease and enforcement starts there."""
        ttl = 0.5
        coord, ep = start_coordinator(expected=2, lease_ttl=ttl)
        try:
            got = {}

            def run(r, delay):
                time.sleep(delay)
                pod = self._pod(ep, 2, r).init()
                pod.barrier("formed", timeout=10.0)
                got[r] = pod.world_size
                pod.shutdown()

            ts = [threading.Thread(target=run, args=(0, 0.0)),
                  threading.Thread(target=run, args=(1, 3 * ttl))]
            [t.start() for t in ts]
            [t.join(30) for t in ts]
            assert got == {0: 2, 1: 2}
            assert coord.state()["failed"] == {}
        finally:
            coord.close()

    def test_from_env(self, monkeypatch):
        monkeypatch.setenv("PADDLE_POD_COORDINATOR", "127.0.0.1:1234")
        monkeypatch.setenv("PADDLE_TRAINERS_NUM", "4")
        monkeypatch.setenv("PADDLE_TRAINER_ID", "2")
        monkeypatch.setenv("PADDLE_POD_BARRIER_TIMEOUT", "12.5")
        monkeypatch.setenv("PADDLE_POD_JOIN_TIMEOUT", "90")
        pod = PodRuntime.from_env()
        assert (pod.coordinator, pod.num_processes, pod.origin,
                pod.barrier_timeout, pod.join_timeout) == \
            ("127.0.0.1:1234", 4, 2, 12.5, 90.0)

    def test_lobby_join_and_reform_up(self):
        """The kill->shrink->heal->grow lifecycle in-process: a
        post-formation joiner parks in the LOBBY (running generation
        undisturbed), survivors see it via pending_joiners(), and the
        next reform GROWS the world — gen+1, the replacement admitted
        at the appended rank, collectives spanning the new world, stale
        generations still rejected loudly."""
        coord, ep = start_coordinator(expected=2, lease_ttl=30.0)
        pods, rep = {}, {}
        try:
            def run(r):
                pods[r] = self._pod(ep, 2, r).init()

            ts = [threading.Thread(target=run, args=(r,)) for r in (0, 1)]
            [t.start() for t in ts]
            [t.join(30) for t in ts]
            coord.mark_failed(1, "killed by SIGKILL (supervisor)")
            with pytest.raises(RankFailedError):
                pods[0].barrier("b", timeout=10.0)
            assert pods[0].reform(timeout=10.0) == {
                "gen": 1, "rank": 0, "world_size": 1}
            assert pods[0].pending_joiners() == []

            # the replacement joins: parked, NOT a member yet, and the
            # survivor's generation does not move
            def join_rep():
                rep["pod"] = self._pod(ep, 2, 1,
                                       join_timeout=30.0).init()

            t = threading.Thread(target=join_rep)
            t.start()
            deadline = time.time() + 10
            while pods[0].pending_joiners() != [1] \
                    and time.time() < deadline:
                time.sleep(0.05)
            assert pods[0].pending_joiners() == [1]
            assert pods[0].gen == 1 and pods[0].world_size == 1
            assert coord.state()["members"] == {0: coord.state()
                                                ["members"][0]}

            # reform-up: the survivor keeps rank 0 (committer stays an
            # incumbent), the joiner appends as rank 1, world grows
            view = pods[0].reform(timeout=10.0)
            t.join(15)
            assert view == {"gen": 2, "rank": 0, "world_size": 2}
            assert (rep["pod"].rank, rep["pod"].world_size,
                    rep["pod"].gen) == (1, 2, 2)
            assert rep["pod"].uid == coord.uid
            assert pods[0].pending_joiners() == []

            out = {}

            def ar(p, r):
                out[r] = p.allreduce(np.full(3, float(r + 1)),
                                     name="healed", timeout=10.0)

            ts = [threading.Thread(target=ar, args=(pods[0], 0)),
                  threading.Thread(target=ar, args=(rep["pod"], 1))]
            [t.start() for t in ts]
            [t.join(15) for t in ts]
            np.testing.assert_array_equal(out[0], np.full(3, 3.0))
            np.testing.assert_array_equal(out[1], np.full(3, 3.0))
            # the shrunk generation is history: its ops are rejected
            resp = coord.handle_req({"op": "barrier", "rank": 0,
                                     "gen": 1, "name": "x",
                                     "timeout": 1.0})
            assert resp == {"ok": False, "error": "stale_gen", "gen": 2}
        finally:
            for p in list(pods.values()) + list(rep.values()):
                p.shutdown()
            coord.close()

    def test_net_new_rank_scales_out_beyond_original_world(self):
        """The lobby is not only for replacements: a NET-NEW origin
        joining a healthy formed pod is admitted at the next reform and
        the world grows past the launch size (scale-out)."""
        coord, ep = start_coordinator(expected=2, lease_ttl=30.0)
        pods, new = {}, {}
        try:
            def run(r):
                pods[r] = self._pod(ep, 2, r).init()

            ts = [threading.Thread(target=run, args=(r,)) for r in (0, 1)]
            [t.start() for t in ts]
            [t.join(30) for t in ts]

            def join_new():
                new["pod"] = self._pod(ep, 2, 7,
                                       join_timeout=30.0).init()

            t = threading.Thread(target=join_new)
            t.start()
            deadline = time.time() + 10
            while pods[0].pending_joiners() != [7] \
                    and time.time() < deadline:
                time.sleep(0.05)
            views = {}

            def ref(r):
                views[r] = pods[r].reform(timeout=10.0)

            ts = [threading.Thread(target=ref, args=(r,)) for r in (0, 1)]
            [t.start() for t in ts]
            [t.join(30) for t in ts]
            t.join(15)
            assert views[0]["world_size"] == views[1]["world_size"] == 3
            assert (new["pod"].rank, new["pod"].world_size,
                    new["pod"].gen) == (2, 3, 1)
            # data re-shards over the grown world
            assert new["pod"].shard_range(9) == (6, 9)
        finally:
            for p in list(pods.values()) + list(new.values()):
                p.shutdown()
            coord.close()

    def test_replacement_joining_before_reform_parks_not_bounces(self):
        """The race the supervisor creates on every fast respawn: the
        dead rank is marked failed but the survivors have NOT reformed
        yet (mid-step), so its origin still sits in the roster. The
        replacement's join must PARK in the lobby (a failed member no
        longer owns its origin) — bouncing it as duplicate_origin would
        burn one RestartPolicy attempt per incarnation until the budget
        dies and the pod stays degraded forever. A single reform then
        does shrink+grow in one transition: dead rank out, replacement
        in, world size preserved."""
        coord, ep = start_coordinator(expected=2, lease_ttl=30.0)
        pods, rep = {}, {}
        try:
            def run(r):
                pods[r] = self._pod(ep, 2, r).init()

            ts = [threading.Thread(target=run, args=(r,)) for r in (0, 1)]
            [t.start() for t in ts]
            [t.join(30) for t in ts]
            coord.mark_failed(1, "killed by SIGKILL (supervisor)")
            # NO reform yet — the dead rank is still in the roster

            def join_rep():
                rep["pod"] = self._pod(ep, 2, 1,
                                       join_timeout=30.0).init()

            t = threading.Thread(target=join_rep)
            t.start()
            deadline = time.time() + 10
            while pods[0].pending_joiners() != [1] \
                    and time.time() < deadline:
                time.sleep(0.05)
            assert pods[0].pending_joiners() == [1]  # parked, not bounced
            # the survivor learns of the death within a heartbeat
            with pytest.raises(RankFailedError):
                while time.time() < deadline:
                    pods[0].check_failures()
                    time.sleep(0.05)
                raise AssertionError("failure never surfaced")
            view = pods[0].reform(timeout=10.0)
            t.join(15)
            assert view == {"gen": 1, "rank": 0, "world_size": 2}
            assert (rep["pod"].rank, rep["pod"].world_size,
                    rep["pod"].gen) == (1, 2, 1)
        finally:
            for p in list(pods.values()) + list(rep.values()):
                p.shutdown()
            coord.close()

    def test_duplicate_origin_rejected_from_lobby(self):
        """A live origin cannot be shadowed by a lobby joiner — only a
        REPLACEMENT (predecessor marked failed) may reuse the id."""
        coord, ep = start_coordinator(expected=2, lease_ttl=30.0)
        pods = {}
        try:
            def run(r):
                pods[r] = self._pod(ep, 2, r).init()

            ts = [threading.Thread(target=run, args=(r,)) for r in (0, 1)]
            [t.start() for t in ts]
            [t.join(30) for t in ts]
            from paddle_tpu.distributed.pod import PodError
            with pytest.raises(PodError, match="duplicate_origin"):
                self._pod(ep, 2, 1, join_timeout=5.0).init()
        finally:
            for p in pods.values():
                p.shutdown()
            coord.close()

    def test_straggler_detection_before_failure(self, tmp_path):
        """A slow-but-alive rank (heartbeat gap past the straggler
        threshold but under the lease ttl) surfaces in stragglers(),
        heartbeat_stats percentiles, pod_rank_heartbeat_ms gauges, and
        an edge-triggered pod_straggler run-log event — BEFORE it ever
        becomes a failure."""
        from paddle_tpu.observability import export, runlog
        log_path = str(tmp_path / "sup.jsonl")
        runlog.start_run(path=log_path, rank=0, run_id="strag")
        coord = PodCoordinator(("127.0.0.1", 0), expected=2,
                               lease_ttl=30.0, monitor_interval=0.1,
                               straggler_threshold=0.3)
        serve = threading.Thread(target=coord.serve_forever, daemon=True)
        serve.start()
        ep = coord.endpoint
        pods = {}
        try:
            def run(r, hb):
                pods[r] = PodRuntime(ep, 2, r, heartbeat_interval=hb,
                                     barrier_timeout=10.0).init()

            ts = [threading.Thread(target=run, args=(0, 0.05)),
                  threading.Thread(target=run, args=(1, 1.2))]
            [t.start() for t in ts]
            [t.join(30) for t in ts]
            # rank 1 beats every 1.2s: its gap spends most of its time
            # past the 0.3s threshold; rank 0 (50ms) never does
            deadline = time.time() + 10
            seen = set()
            while time.time() < deadline:
                seen.update(coord.stragglers())
                if 1 in seen:
                    break
                time.sleep(0.05)
            assert 1 in seen and 0 not in seen
            # the runtime-side query agrees
            assert pods[0].stragglers(threshold=0.3) in ([], [1])
            # gap HISTORY needs rank 1's first (late) heartbeat to land
            stats = coord.heartbeat_stats()
            while "max_ms" not in stats.get(1, {}) \
                    and time.time() < deadline:
                time.sleep(0.05)
                stats = coord.heartbeat_stats()
            assert stats[1]["max_ms"] > 300 > stats[0]["p95_ms"]
            gauges = export.gauges()
            assert any(k.startswith('pod_rank_heartbeat_ms{rank="1"')
                       for k in gauges), sorted(gauges)
            # the lease never expired: no failure, only the warning
            assert coord.state()["failed"] == {}
        finally:
            for p in pods.values():
                p.shutdown()
            coord.close()
            runlog.stop_run()
        with open(log_path) as f:
            events = [json.loads(line) for line in f]
        strag = [e for e in events if e.get("event") == "pod_straggler"]
        assert strag and strag[0]["origin"] == 1
        assert strag[0]["gap_ms"] > 300
        # edge-triggered: at most one event per 1.2s heartbeat episode,
        # NOT one per 0.1s monitor sweep (the sweeps outnumber the
        # episodes ~12:1 — an un-edge-triggered emitter would spam)
        assert len(strag) <= 8


# ------------------------------------------------------- pod checkpointing

class TestPodCheckpoint:
    """Per-rank shard files + rank-0 manifest commit + elastic merge,
    in-process (the subprocess path is covered by the e2e below)."""

    def _train_one(self):
        import paddle_tpu as paddle
        from paddle_tpu import nn
        paddle.seed(3)
        m = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 1))
        opt = paddle.optimizer.Momentum(parameters=m.parameters(),
                                        learning_rate=0.05, momentum=0.9)
        rng = np.random.RandomState(0)
        x = paddle.to_tensor(rng.rand(4, 8).astype("float32"))
        y = paddle.to_tensor(rng.rand(4, 1).astype("float32"))
        loss = nn.functional.mse_loss(m(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return m, opt, (x, y)

    def _save_world2(self, root, m, opt, timeout=60.0):
        from paddle_tpu.checkpoint.multihost import PodCheckpointManager
        errs = []

        def save(r):
            try:
                PodCheckpointManager(root, rank=r, world=2,
                                     timeout=timeout).add_model(
                    m).add_optimizer(opt).save(1)
            except Exception as e:  # surfaced by the caller
                errs.append(e)

        t = threading.Thread(target=save, args=(1,))
        t.start()
        save(0)
        t.join(30)
        return errs

    def test_entry_sharded_roundtrip_is_bitwise(self, tmp_path):
        import paddle_tpu as paddle
        from paddle_tpu import nn
        from paddle_tpu.checkpoint import core as ckpt_core
        from paddle_tpu.checkpoint.multihost import (PodCheckpointManager,
                                                     split_pod_payloads)
        root = str(tmp_path)
        m, opt, _ = self._train_one()
        assert self._save_world2(root, m, opt) == []
        ref = [np.asarray(p._value).copy() for p in m.parameters()]

        # the manifest (rank-0 commit) covers BOTH ranks' shard files,
        # and each rank's payload really is a partial shard
        step, payloads, meta = ckpt_core.read_checkpoint(root)
        by_rank = split_pod_payloads(payloads)
        assert sorted(by_rank) == [0, 1]
        assert meta["pod"]["world"] == 2

        # fresh objects at a different seed + SMALLER world: restore
        # merges every rank's shards from the shared filesystem
        paddle.seed(99)
        m2 = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 1))
        opt2 = paddle.optimizer.Momentum(parameters=m2.parameters(),
                                         learning_rate=0.05, momentum=0.9)
        rng = np.random.RandomState(0)
        x = paddle.to_tensor(rng.rand(4, 8).astype("float32"))
        y = paddle.to_tensor(rng.rand(4, 1).astype("float32"))
        loss = nn.functional.mse_loss(m2(x), y)
        loss.backward()
        opt2.step()
        opt2.clear_grad()
        got = PodCheckpointManager(root, rank=0, world=1).add_model(
            m2).add_optimizer(opt2).restore()
        assert got is not None and got["step"] == 1
        for p, want in zip(m2.parameters(), ref):
            np.testing.assert_array_equal(np.asarray(p._value), want)

    @pytest.mark.chaos
    def test_kill_before_commit_never_leaves_torn_checkpoint(self,
                                                             tmp_path):
        """Both ranks' shards written, committer killed BEFORE the
        manifest: restore must see NOTHING (or the previous step), never
        a half-checkpoint; a later re-save of the same step succeeds."""
        from paddle_tpu.checkpoint import core as ckpt_core
        from paddle_tpu.checkpoint.multihost import PodCheckpointError
        root = str(tmp_path)
        m, opt, _ = self._train_one()
        faults.inject("checkpoint/pod_before_commit",
                      exc=PodCheckpointError)
        errs = self._save_world2(root, m, opt, timeout=3.0)
        faults.clear()
        # committer died at the kill-point; the non-committer timed out
        # waiting for a publish that never came — both LOUD
        assert len(errs) == 2
        assert ckpt_core.read_checkpoint(root) is None
        assert ckpt_core.valid_steps(root) == []
        # the pod staging debris does not block a successful retry
        assert self._save_world2(root, m, opt) == []
        assert ckpt_core.valid_steps(root) == [1]

    def test_missing_rank_shard_fails_loudly(self, tmp_path):
        from paddle_tpu.checkpoint import multihost, state
        rec = {"state": {f"p{i}": np.full((2,), i, np.float32)
                         for i in range(5)}, "zero3_params": []}
        parts = [multihost.partition_model(rec, r, 2) for r in (0, 1)]
        merged = multihost.merge_model(parts)
        assert sorted(merged["state"]) == sorted(rec["state"])
        with pytest.raises(state.StateMismatchError, match="missing"):
            multihost.merge_model(parts[:1])  # rank 1's file absent

    def test_zero_store_reflatten_across_rank_files(self, tmp_path):
        """The PR-7 elastic path across the process boundary: a ZeRO
        optimizer's flat stores saved as TWO ranks' row-slices restore
        into a DIFFERENT in-process dp degree bitwise (shards list ->
        state._restore_store concat -> re-pad -> re-place)."""
        import gc

        import jax

        import paddle_tpu as paddle
        from paddle_tpu import nn
        from paddle_tpu.checkpoint import state
        from paddle_tpu.checkpoint.multihost import PodCheckpointManager
        from paddle_tpu.distributed import parallel_env
        root = str(tmp_path)
        K = 2
        rngd = np.random.RandomState(7)
        X = rngd.rand(K, 16, 16).astype("float32")
        Y = rngd.randint(0, 8, (K, 16)).astype("int64")

        def build(dp, seed):
            mesh = parallel_env.make_mesh({"dp": dp},
                                          devices=jax.devices()[:dp])
            parallel_env.set_mesh(mesh)
            paddle.seed(seed)
            m = nn.Sequential(nn.Linear(16, 32), nn.ReLU(),
                              nn.Linear(32, 8))
            opt = paddle.optimizer.AdamW(parameters=m.parameters(),
                                         learning_rate=0.05)
            opt._zero_enable(axis="dp", stage=1)
            return m, opt

        def store_rows(opt):
            out = {}
            for zb, sdict in zip(opt._zero["buckets"],
                                 opt._zero["stores"]):
                for slot, store in sdict.items():
                    sh, _ = state._store_shards(store)
                    full = (np.concatenate(sh, 0) if len(sh) > 1
                            else sh[0])
                    out[(zb.index, slot)] = (
                        full[:zb.rows - zb.pad_rows].copy())
            return out

        try:
            m, opt = build(8, seed=11)

            def one(xb, yb):
                loss = nn.functional.cross_entropy(m(xb), yb)
                loss.backward()
                opt.step()
                opt.clear_grad()
                return loss

            stepf = paddle.jit.to_static(one, scan_steps=K, dp_axis="dp")
            stepf(paddle.to_tensor(X), paddle.to_tensor(Y))
            ref = store_rows(opt)
            errs = []

            def save(r):
                try:
                    PodCheckpointManager(root, rank=r, world=2,
                                         timeout=60.0).add_model(
                        m).add_optimizer(opt).save(5)
                except Exception as e:
                    errs.append(e)

            t = threading.Thread(target=save, args=(1,))
            t.start()
            save(0)
            t.join(60)
            assert errs == []
            del stepf, m, opt
            gc.collect()
            parallel_env.set_mesh(None)

            m2, opt2 = build(4, seed=55)  # ELASTIC: dp8 -> dp4
            meta = PodCheckpointManager(root, rank=0, world=1).add_model(
                m2).add_optimizer(opt2).restore()
            assert meta is not None and meta["step"] == 5
            got = store_rows(opt2)
            assert sorted(got) == sorted(ref)
            for key in ref:
                np.testing.assert_array_equal(got[key], ref[key])
        finally:
            parallel_env.set_mesh(None)
            gc.collect()


# ----------------------------------------------------- process kill-points

def test_process_kill_point_sigkills_this_rank(tmp_path):
    """The cross-process analog of faults.inject: the armed rank
    SIGKILLs itself at the named point's nth hit — uncatchable, leaving
    only the flushed run-log event behind."""
    code = (
        "from paddle_tpu.testing import faults\n"
        "import paddle_tpu.observability as obs\n"
        "obs.start_run(dir=%r, rank=3)\n"
        "faults.kill_point('demo/point')\n"
        "faults.kill_point('demo/point')\n"
        "print('UNREACHABLE')\n" % str(tmp_path))
    env = {**os.environ, "PADDLE_TPU_PROCESS_KILL": "demo/point@3#2",
           "PADDLE_TRAINER_ID": "3", "JAX_PLATFORMS": "cpu",
           "PYTHONPATH": REPO}
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, env=env, timeout=120, cwd=REPO)
    assert r.returncode == -signal.SIGKILL, (r.returncode, r.stderr[-500:])
    assert "UNREACHABLE" not in r.stdout
    logs = [f for f in os.listdir(tmp_path) if f.endswith(".jsonl")]
    assert len(logs) == 1
    with open(os.path.join(tmp_path, logs[0])) as f:
        recs = [json.loads(line) for line in f]
    kills = [rec for rec in recs if rec.get("event") == "process_kill"]
    assert kills and kills[0]["point"] == "demo/point" \
        and kills[0]["rank"] == "3"


def test_process_kill_other_rank_spec_is_inert(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_PROCESS_KILL", "demo/p@7#1")
    monkeypatch.setenv("PADDLE_TRAINER_ID", "0")
    faults.reset()  # re-read env
    assert faults.process_kills() == {}
    faults.kill_point("demo/p")  # must not kill the test process
    assert faults.hits("demo/p") >= 1


# -------------------------------------------------------------- satellites

def _spawn_suicide_worker(arg):
    """Module-level for pickling: rank 1 SIGKILLs itself, rank 0 would
    wait forever on a join-like sleep."""
    import os as _os
    import signal as _sig
    import time as _time
    if _os.environ.get("PADDLE_TRAINER_ID") == "1":
        _os.kill(_os.getpid(), _sig.SIGKILL)
    _time.sleep(120)  # the survivor "hangs" on the dead peer
    return arg


def test_spawn_join_reaps_signal_death_quickly():
    """spawn()._Context.join must reap-and-raise (naming the signal)
    when a child dies by signal instead of hanging out the full
    timeout while the survivors deadlock."""
    from paddle_tpu.distributed.spawn import spawn
    t0 = time.time()
    with pytest.raises(RuntimeError, match="SIGKILL"):
        spawn(_spawn_suicide_worker, args=(1,), nprocs=2, backend="cpu",
              timeout=300)
    took = time.time() - t0
    assert took < 60, f"join took {took:.0f}s — it hung instead of reaping"


def test_watch_local_trainers_grace_lets_sigterm_hook_run(tmp_path):
    """On a trainer death the launcher tears the pod down with SIGTERM +
    grace before SIGKILL — a survivor's SIGTERM hook (the flight
    recorder's dump path) gets to run; the error names the death."""
    from paddle_tpu.distributed import launch
    victim = tmp_path / "victim.py"
    victim.write_text("import os, signal\n"
                      "os.kill(os.getpid(), signal.SIGKILL)\n")
    survivor = tmp_path / "survivor.py"
    survivor.write_text(
        "import os, signal, sys, time\n"
        "def h(sig, frame):\n"
        "    open(os.environ['TERM_PROOF'], 'w').write('dumped')\n"
        "    sys.exit(0)\n"
        "signal.signal(signal.SIGTERM, h)\n"
        "open(os.environ['READY_PROOF'], 'w').write('up')\n"
        "time.sleep(120)\n")
    proof = tmp_path / "term_proof"
    ready = tmp_path / "ready_proof"
    eps = ["127.0.0.1:6470", "127.0.0.1:6471"]
    cluster = launch.get_cluster(["127.0.0.1"], "127.0.0.1", eps, 2)
    # rank 0 runs the survivor script, rank 1 the victim
    wrapper = tmp_path / "main.py"
    wrapper.write_text(
        "import os, runpy\n"
        "r = os.environ['PADDLE_TRAINER_ID']\n"
        "runpy.run_path(%r if r == '0' else %r, run_name='__main__')\n"
        % (str(survivor), str(victim)))
    procs = launch.start_local_trainers(
        cluster, cluster.pods[0], str(wrapper), [],
        envs={"TERM_PROOF": str(proof), "READY_PROOF": str(ready)})
    deadline = time.time() + 30
    while not ready.exists() and time.time() < deadline:
        time.sleep(0.05)
    with pytest.raises(RuntimeError, match="died by signal SIGKILL"):
        while time.time() < deadline:
            procs = launch.watch_local_trainers(procs, grace_s=10.0)
            if not procs:
                break
            time.sleep(0.1)
    assert proof.exists(), \
        "SIGTERM hook never ran — teardown skipped the grace period"


def test_barrier_without_timeout_lint_rule(tmp_path):
    from paddle_tpu.analysis import lint_source
    bad = tmp_path / "bad.py"
    bad.write_text(
        "def sync(pod, client, n):\n"
        "    pod.barrier('step')\n"          # bare -> warning
        "    client.barrier(n)\n"            # bare -> warning
        "    pod.barrier('b', timeout=30)\n"        # kwarg evidence
        "    d = 5.0\n"
        "    deadline = d\n"
        "    client.barrier(n, deadline)\n"  # deadline-named arg\n
    )
    found = [f for f in lint_source(paths=[str(bad)])
             if f.rule == "barrier-without-timeout"]
    assert len(found) == 2
    assert all(f.severity == "warning" for f in found)
    assert {f.loc.rsplit(":", 1)[1] for f in found} == {"2", "3"}
    # the default sweep covers distributed/ and stays clean (the PS
    # barrier call sites carry explicit timeouts now)
    assert [f for f in lint_source()
            if f.rule == "barrier-without-timeout"] == []


class TestRestartPolicy:
    """The shared budgeted-backoff policy (distributed/restart.py) —
    the pod supervisor's respawn pacing and fleet/elastic.py's relaunch
    pacing are this one object."""

    def test_budget_bounds_and_reset_reopens(self):
        p = RestartPolicy(max_restarts=3, base_delay=0.1, jitter=0.0)
        delays = [p.schedule("r1") for _ in range(5)]
        assert all(d is not None for d in delays[:3])
        assert delays[3] is None and delays[4] is None
        assert p.attempts("r1") == 3
        # keys are independent budgets
        assert p.schedule("r2") is not None
        p.reset("r1")
        assert p.schedule("r1") is not None

    def test_exponential_backoff_capped(self):
        p = RestartPolicy(max_restarts=6, base_delay=0.2, factor=2.0,
                          max_delay=1.0, jitter=0.0)
        got = [p.schedule("k") for _ in range(5)]
        assert got == [0.2, 0.4, 0.8, 1.0, 1.0]

    def test_jitter_is_seeded_and_bounded(self):
        a = [RestartPolicy(max_restarts=4, base_delay=1.0, jitter=0.25,
                           seed=7).schedule("k") for _ in range(1)]
        b = RestartPolicy(max_restarts=4, base_delay=1.0, jitter=0.25,
                          seed=7)
        c = RestartPolicy(max_restarts=4, base_delay=1.0, jitter=0.25,
                          seed=8)
        assert a[0] == b.schedule("k")          # same seed replays
        assert b.schedule("k") != c.schedule("k")
        assert 0.75 <= a[0] <= 1.25             # symmetric, bounded

    def test_sliding_window_ages_out_attempts(self):
        p = RestartPolicy(max_restarts=2, base_delay=0.1, jitter=0.0,
                          window_s=10.0)
        assert p.schedule("k", now=0.0) is not None
        assert p.schedule("k", now=1.0) is not None
        assert p.schedule("k", now=5.0) is None     # budget spent
        assert p.schedule("k", now=20.0) is not None  # window aged out


class _FakeProc:
    def __init__(self, rc_script):
        self._rc = rc_script  # callable() -> poll value
        self.terminated = False

    def poll(self):
        return self._rc()

    def terminate(self):
        self.terminated = True


def test_elastic_relaunch_shares_restart_policy(tmp_path):
    """fleet/elastic.py's KV-relaunch path (the reference's
    watch->restart loop) paces itself through the SAME RestartPolicy
    the pod supervisor uses: a dead child is relaunched after backoff,
    a clean exit under stable membership completes, and an exhausted
    budget EXITS instead of crash-looping."""
    from paddle_tpu.distributed.fleet.elastic import (ElasticManager,
                                                      ElasticStatus,
                                                      FileKVStore)
    store = FileKVStore(str(tmp_path))
    mgr = ElasticManager("n1:1", np=1, job_id="j", store=store, ttl=30,
                         heartbeat_interval=0.2)
    mgr.register()
    try:
        spawned = []

        def spawn_dies_then_completes():
            rc = (lambda: 1) if not spawned else (lambda: 0)
            proc = _FakeProc(rc)
            spawned.append(proc)
            return proc

        policy = RestartPolicy(max_restarts=2, base_delay=0.01,
                               jitter=0.0, seed=0)
        status, proc = mgr.relaunch(spawn_dies_then_completes,
                                    policy=policy, watch_interval=0.05)
        assert status == ElasticStatus.COMPLETED
        assert len(spawned) == 2 and proc is spawned[1]
        assert policy.attempts(mgr.endpoint) == 1

        # budget exhaustion: every child dies -> EXIT, bounded spawns
        spawned.clear()

        def spawn_always_dies():
            proc = _FakeProc(lambda: 1)
            spawned.append(proc)
            return proc

        status, proc = mgr.relaunch(
            spawn_always_dies,
            policy=RestartPolicy(max_restarts=2, base_delay=0.01,
                                 jitter=0.0),
            watch_interval=0.05)
        assert status == ElasticStatus.EXIT and proc is None
        assert len(spawned) == 3  # initial + exactly max_restarts
    finally:
        mgr.exit()


def test_pod_failure_triggers_flight_dump(tmp_path):
    """Satellite: RankFailedError and BarrierTimeoutError each leave an
    atomic flight dump (reason="pod_failure") naming the dead/absent
    origin ranks BEFORE any reform — the post-mortem exists even though
    the survivor recovers and keeps running."""
    from paddle_tpu.observability import flight
    flight.install(str(tmp_path))
    coord, ep = start_coordinator(expected=2, lease_ttl=30.0)
    pods = {}
    try:
        def run(r):
            pods[r] = PodRuntime(ep, 2, r, heartbeat_interval=0.1,
                                 barrier_timeout=10.0).init()

        ts = [threading.Thread(target=run, args=(r,)) for r in (0, 1)]
        [t.start() for t in ts]
        [t.join(30) for t in ts]

        # absent rank -> BarrierTimeoutError dump
        with pytest.raises(BarrierTimeoutError):
            pods[0].barrier("never", timeout=0.5)
        with open(flight.latest_dump()) as f:
            dump = json.load(f)
        assert dump["reason"] == "pod_failure"
        assert dump["pod_failure"]["absent_ranks"] == [1]
        assert dump["pod_failure"]["op"] == "never"
        assert dump["exception"]["type"] == "BarrierTimeoutError"

        # dead rank -> RankFailedError dump
        coord.mark_failed(1, "killed by SIGKILL (supervisor)")
        with pytest.raises(RankFailedError):
            pods[0].barrier("b", timeout=10.0)
        with open(flight.latest_dump()) as f:
            dump = json.load(f)
        assert dump["reason"] == "pod_failure"
        assert dump["pod_failure"]["failed_ranks"] == [1]
        assert dump["pod_failure"]["gen"] == 0
        # the survivor reforms and keeps running — the dump persists
        assert pods[0].reform(timeout=10.0)["world_size"] == 1
    finally:
        flight.uninstall()
        for p in pods.values():
            p.shutdown()
        coord.close()


def test_respawn_without_backoff_lint_rule(tmp_path):
    from paddle_tpu.analysis import lint_source
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import subprocess, time\n"
        "def keep_alive(cmd):\n"
        "    while True:\n"                       # unpaced keep-alive
        "        proc = subprocess.Popen(cmd)\n"
        "        proc.wait()\n"
        "def bounded(spawn_fn):\n"
        "    for _ in range(5):\n"                # bounded but unpaced
        "        try:\n"
        "            spawn_fn()\n"
        "        except OSError:\n"
        "            pass\n"
        "def fanout(trainers, spawn_trainer):\n"
        "    for t in trainers:\n"                # one spawn per item
        "        spawn_trainer(t)\n"
        "def good(policy, spawn_fn):\n"
        "    while True:\n"
        "        delay = policy.schedule('k')\n"
        "        if delay is None:\n"
        "            return\n"
        "        time.sleep(delay)\n"
        "        spawn_fn()\n")
    found = [f for f in lint_source(paths=[str(bad)])
             if f.rule == "respawn-without-backoff"]
    assert len(found) == 2
    assert all(f.severity == "error" for f in found)
    assert {f.loc.rsplit(":", 1)[1] for f in found} == {"3", "7"}
    # the default sweep (distributed/ + fleet/elastic.py + the
    # supervisor) is clean: every real respawn loop rides RestartPolicy
    assert [f for f in lint_source()
            if f.rule == "respawn-without-backoff"] == []


def test_trace_view_reform_timeline(tmp_path):
    """Satellite: pod_reform events (direction, worlds, gen) from every
    rank's run-log collapse into one ordered reform timeline in
    trace_view --stats."""
    from paddle_tpu.observability import runlog
    import trace_view

    paths = []
    for r in (0, 1):
        p = str(tmp_path / f"pod.rank{r}.jsonl")
        runlog.start_run(path=p, rank=r, run_id="heal")
        runlog.event("pod_reform", rank=0 if r == 0 else 1, world=1,
                     gen=1, direction="shrink", old_world=2, new_world=1,
                     took_s=0.21)
        if r == 0:
            runlog.event("pod_reform", rank=0, world=2, gen=2,
                         direction="grow", old_world=1, new_world=2,
                         took_s=0.35)
        runlog.stop_run()
        paths.append(p)
    events, n_bad = trace_view.load_events(paths)
    assert n_bad == 0
    timeline = trace_view.reform_timeline(events)
    assert [(e["gen"], e["direction"], e["old_world"], e["new_world"])
            for e in timeline] == [(1, "shrink", 2, 1), (2, "grow", 1, 2)]
    assert timeline[1]["took_s"] == 0.35
    buf = io.StringIO()
    trace_view.print_stats(events, n_bad, file=buf)
    out = buf.getvalue()
    assert "reform timeline:" in out
    assert re.search(r"gen 1: shrink\s+world 2->1", out)
    assert re.search(r"gen 2: grow\s+world 1->2", out)


def test_trace_view_stats_sums_ledger_across_ranks(tmp_path):
    """Satellite: per-rank state-ledger snapshots in each rank's runlog
    sum into a pod-wide residency line in trace_view --stats."""
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.observability import memory, runlog
    import trace_view

    paddle.seed(0)
    _model = nn.Linear(16, 8)  # some resident state to ledger
    paths = []
    for r in (0, 1):
        p = str(tmp_path / f"pod.rank{r}.jsonl")
        runlog.start_run(path=p, rank=r, run_id="podrun")
        memory.runlog_snapshot(rank=r)
        runlog.stop_run()
        paths.append(p)
    events, n_bad = trace_view.load_events(paths)
    assert n_bad == 0
    cats, n_ranks = trace_view.state_residency(events)
    assert n_ranks == 2
    # both ranks ledger the same process state here: exact 2x one rank
    one = memory.state_ledger()["categories"]["param"]["bytes"]
    assert cats["param"] == 2 * one
    buf = io.StringIO()
    trace_view.print_stats(events, n_bad, file=buf)
    out = buf.getvalue()
    assert "state residency" in out and "summed over 2 rank(s)" in out


# ------------------------------------------------------------- end to end

_CONTROL = {}


def _losses_by_step(text):
    """{step: loss} keeping the LAST occurrence (post-restore re-runs
    supersede pre-crash prints)."""
    out = {}
    for m in re.finditer(r"LOSS (\d+) ([\d.eE+-]+)", text):
        out[int(m.group(1))] = float(m.group(2))
    return out


def _control_losses(tmp_factory):
    """Single-process control of the SAME fixture (one pod rank, no
    kill), cached for the session."""
    if "losses" not in _CONTROL:
        wd = str(tmp_factory.mktemp("pod_control"))
        pod = VirtualPod(1, FIXTURE, workdir=wd,
                         env={"POD_FIX_CKPT_ROOT": os.path.join(wd, "ck")})
        exits = pod.run(timeout=150)
        assert exits[0].returncode == 0, pod.tail_logs()
        _CONTROL["losses"] = _losses_by_step(pod.log(0))
        assert len(_CONTROL["losses"]) == 8
    return _CONTROL["losses"]


def _assert_no_torn_checkpoint(root):
    """Every published step dir must fully validate; staging debris is
    allowed (restore never reads it), torn manifests are not."""
    from paddle_tpu.checkpoint import core as ckpt_core
    steps = [int(m.group(1)) for name in os.listdir(root)
             for m in [re.match(r"^step_(\d+)$", name)] if m]
    for s in steps:
        got = ckpt_core.read_checkpoint(root, step=s)
        assert got is not None, f"step {s} published but torn"


LEASE_TTL = 2.0


def test_pod_sigkill_midstep_elastic_recovery(tmp_path_factory):
    """THE acceptance run: 2 real processes, rank 1 SIGKILLed mid-step
    (step 4, after the step-2 checkpoint), PS pulls crossing the
    process boundary; detection within the window, reform to world 1,
    elastic restore, losses within 1e-6 of control, merged trace with
    the dead rank's track."""
    import jax

    import trace_view
    from paddle_tpu.distributed.ps import PsServer, TableConfig
    jax.config.update("jax_platforms", "cpu")

    control = _control_losses(tmp_path_factory)
    wd = str(tmp_path_factory.mktemp("pod_e2e"))
    root = os.path.join(wd, "ck")
    srv = PsServer([TableConfig(0, "dense", 4)], port=0)
    ps_port = srv.start()
    try:
        pod = VirtualPod(2, FIXTURE, workdir=wd,
                         kill=(1, "pod/mid_step", 5),
                         lease_ttl=LEASE_TTL,
                         env={"POD_FIX_CKPT_ROOT": root,
                              "POD_FIX_PS_ENDPOINT":
                                  f"127.0.0.1:{ps_port}"})
        exits = pod.run(timeout=180)
    finally:
        srv.stop()

    # the kill was real and the survivor finished
    assert exits[1].signal == "SIGKILL", exits
    assert exits[0].returncode == 0, pod.tail_logs()
    log0, log1 = pod.log(0), pod.log(1)

    # cross-process PS demo ran on BOTH ranks
    assert "PS_OK rank=0 n=4" in log0 and "PS_OK rank=1 n=4" in log1

    # detection: named, and within the configured window of the death
    m = re.search(r"FAILURE_DETECTED t=([\d.]+) failed=\[1\] "
                  r"err=(RankFailedError|BarrierTimeoutError)", log0)
    assert m, log0
    detect_delay = float(m.group(1)) - exits[1].t_reaped
    assert detect_delay < LEASE_TTL + 2.0, \
        f"detected {detect_delay:.2f}s after the reap (window {LEASE_TTL}s)"

    # elastic recovery: world shrank, restore resumed from the step-2
    # checkpoint (not from scratch)
    assert "REFORMED rank=0 world=1 gen=1" in log0
    assert re.search(r"RESUME_FROM 3\b", log0)
    assert "DONE rank=0 world=1" in log0
    assert "DONE" not in log1  # the victim never finished

    # losses: every step within 1e-6 of the single-process control —
    # before the kill (dp split across processes) AND after recovery
    got = _losses_by_step(log0)
    assert sorted(got) == sorted(control)
    for s in sorted(control):
        assert abs(got[s] - control[s]) < 1e-6, \
            (s, got[s], control[s])

    # the published checkpoints all validate — no torn manifest
    _assert_no_torn_checkpoint(root)

    # trace merge: every rank's run-log (the DEAD one included) lands
    # on its own process track; the kill left its runlog evidence
    paths = pod.runlog_paths()
    assert len(paths) == 2
    events, _ = trace_view.load_events(paths)
    trace = trace_view.build_chrome_trace(events)
    tracks = {e["args"]["name"] for e in trace["traceEvents"]
              if e.get("ph") == "M"}
    assert len(tracks) == 2 and any("rank1" in t for t in tracks), tracks
    ev_names = {e.get("event") for e in events if e.get("kind") == "event"}
    assert {"process_kill", "pod_reform", "checkpoint_publish",
            "checkpoint_restore"} <= ev_names
    # per-rank ledger snapshots summed in --stats (satellite 1)
    cats, n_ranks = trace_view.state_residency(events)
    assert n_ranks == 2 and cats.get("param", 0) > 0


@pytest.mark.chaos
@pytest.mark.parametrize("victim,point,nth", [
    (0, "pod/before_barrier", 4),
    (1, "checkpoint/pod_shard_written", 2),
])
def test_pod_kill_sweep_2proc(tmp_path, victim, point, nth):
    """Tier-1 chaos subset: SIGKILL each rank id at the remaining named
    points (mid_step rides the acceptance test above) — detection +
    re-formation + elastic restore + no torn checkpoint. The committer
    (rank 0) dying during a checkpoint is the hard case: the survivor
    re-ranks to 0 and becomes the committer."""
    root = os.path.join(str(tmp_path), "ck")
    pod = VirtualPod(2, FIXTURE, workdir=str(tmp_path),
                     kill=(victim, point, nth), lease_ttl=LEASE_TTL,
                     env={"POD_FIX_CKPT_ROOT": root})
    exits = pod.run(timeout=180)
    survivor = 1 - victim
    assert exits[victim].signal == "SIGKILL", exits
    assert exits[survivor].returncode == 0, pod.tail_logs()
    log = pod.log(survivor)
    assert f"FAILURE_DETECTED" in log and f"failed=[{victim}]" in log, log
    assert "REFORMED rank=0 world=1 gen=1" in log
    assert "DONE rank=0 world=1" in log
    _assert_no_torn_checkpoint(root)


def _reformed_transitions(log):
    """[(world, gen, dir)] in print order from a rank's log."""
    return [(int(m.group(1)), int(m.group(2)), m.group(3))
            for m in re.finditer(
                r"REFORMED rank=\d+ world=(\d+) gen=(\d+) dir=(\w+)", log)]


def test_pod_kill_heal_grow_back_to_full_world(tmp_path_factory):
    """THE scale-UP acceptance run: 2 real processes, rank 1 SIGKILLed
    mid-step -> shrink to world 1 -> the supervisor RESPAWNS it
    (RestartPolicy backoff) -> the replacement parks in the lobby ->
    reform-up back to world 2 -> both ranks restore from the latest pod
    checkpoint -> the tail of the run executes at FULL world, and every
    step's loss is within 1e-6 of the uninterrupted control. The merged
    runlogs carry the shrink AND grow pod_reform events (direction,
    worlds, generations strictly monotone)."""
    import trace_view

    control = _control_losses(tmp_path_factory)
    wd = str(tmp_path_factory.mktemp("pod_heal"))
    root = os.path.join(wd, "ck")
    pod = VirtualPod(2, FIXTURE, workdir=wd,
                     kill=(1, "pod/mid_step", 5), lease_ttl=LEASE_TTL,
                     restart=RestartPolicy(max_restarts=2,
                                           base_delay=0.2, seed=0),
                     env={"POD_FIX_CKPT_ROOT": root,
                          "POD_FIX_TARGET_WORLD": "2",
                          "POD_FIX_HEAL_BY_STEP": "6"})
    exits = pod.run(timeout=240)

    # the kill was real — and the LAST incarnation of rank 1 finished
    kills = [e for e in pod.exit_history
             if e.rank == 1 and e.signal == "SIGKILL"]
    assert len(kills) == 1 and kills[0].incarnation == 1
    assert exits[0].returncode == 0, pod.tail_logs()
    assert exits[1].returncode == 0 and exits[1].incarnation == 2, \
        pod.tail_logs()

    log0, log1 = pod.log(0), pod.log(1)
    # detection within the window, then the full lifecycle in order:
    # shrink to 1 (gen 1), grow back to 2 (gen 2)
    m = re.search(r"FAILURE_DETECTED t=([\d.]+) failed=\[1\]", log0)
    assert m, log0
    assert float(m.group(1)) - kills[0].t_reaped < LEASE_TTL + 2.0
    assert _reformed_transitions(log0) == [(1, 1, "shrink"),
                                           (2, 2, "grow")]
    assert "DONE rank=0 world=2" in log0
    # the replacement joined the SAME log (append), re-formed at gen 2,
    # resumed from the shared checkpoint and finished at full world
    assert log1.count("POD_READY rank=1") == 2
    assert "POD_READY rank=1 world=2 gen=2" in log1
    assert "DONE rank=1 world=2" in log1

    # losses: every step within 1e-6 of the single-process control —
    # pre-kill at world 2, degraded at world 1, healed at world 2
    for log in (log0, log1):
        got = _losses_by_step(log)
        for s, v in got.items():
            assert abs(v - control[s]) < 1e-6, (s, v, control[s])
    assert sorted(_losses_by_step(log0)) == sorted(control)
    # the healed tail REALLY ran at world 2: the replacement computed
    # the final steps too
    assert {6, 7} <= set(_losses_by_step(log1))

    _assert_no_torn_checkpoint(root)

    # merged trace: 3 process logs (rank0, rank1, rank1's replacement),
    # reform timeline shrink->grow with strictly monotone generations
    paths = pod.runlog_paths()
    assert len(paths) == 3
    events, _ = trace_view.load_events(paths)
    timeline = trace_view.reform_timeline(events)
    assert [(e["gen"], e["direction"]) for e in timeline] == \
        [(1, "shrink"), (2, "grow")]
    gens = [e["gen"] for e in timeline]
    assert gens == sorted(gens) and len(set(gens)) == len(gens)
    ev_names = {e.get("event") for e in events if e.get("kind") == "event"}
    assert {"process_kill", "pod_reform", "checkpoint_publish",
            "checkpoint_restore", "pod_join"} <= ev_names
    # the replacement's own log records that it came in via the lobby
    lobby_joins = [e for e in events if e.get("event") == "pod_join"
                   and e.get("via") == "lobby"]
    assert lobby_joins and lobby_joins[0]["gen"] == 2


@pytest.mark.slow
@pytest.mark.chaos
def test_pod_three_kill_heal_cycles_monotone_generations(
        tmp_path_factory):
    """Chaos acceptance (slow tier — ISSUE 13's tier-1 budget squeeze:
    ~24 s, the heavier of the two heal-and-grow e2e cases; the single
    kill->shrink->heal->grow lifecycle keeps tier-1 coverage in
    test_pod_kill_heal_grow_back_to_full_world): THREE consecutive
    kill/heal cycles on one pod —
    the original rank 1 killed mid-step, its first replacement killed
    DURING ITS OWN ELASTIC RESTORE (checkpoint/pod_restore), the second
    replacement killed mid-step again, the third replacement finishing
    clean. No deadlock, generations strictly monotone
    (0->1->2->3->4->5->6), no torn checkpoint, and the final losses
    still match the uninterrupted control at every step."""
    control = _control_losses(tmp_path_factory)
    wd = str(tmp_path_factory.mktemp("pod_3cycle"))
    root = os.path.join(wd, "ck")
    pod = VirtualPod(
        2, FIXTURE, workdir=wd,
        kill=(1, "pod/mid_step", 5),
        respawn_kills={1: [("checkpoint/pod_restore", 1),
                           ("pod/mid_step", 2), None]},
        lease_ttl=LEASE_TTL,
        restart=RestartPolicy(max_restarts=4, base_delay=0.2, seed=0),
        env={"POD_FIX_CKPT_ROOT": root, "POD_FIX_TARGET_WORLD": "2",
             "POD_FIX_HEAL_BY_STEP": "6"})
    exits = pod.run(timeout=300)

    kills = [e for e in pod.exit_history
             if e.rank == 1 and e.signal == "SIGKILL"]
    assert [k.incarnation for k in kills] == [1, 2, 3], pod.exit_history
    assert exits[0].returncode == 0, pod.tail_logs()
    assert exits[1].returncode == 0 and exits[1].incarnation == 4

    log0 = pod.log(0)
    trans = _reformed_transitions(log0)
    gens = [g for _w, g, _d in trans]
    assert gens == sorted(gens) and len(set(gens)) == len(gens), trans
    assert gens[-1] == 6, trans  # 3 shrinks + 3 grows
    assert [d for _w, _g, d in trans] == \
        ["shrink", "grow"] * 3, trans
    assert trans[-1][0] == 2  # healed back to full world at the end
    assert "DONE rank=0 world=2" in log0
    assert "DONE rank=1 world=2" in pod.log(1)

    # the mid-restore kill really happened at the restore point
    import trace_view
    events, _ = trace_view.load_events(pod.runlog_paths())
    kill_points = {e.get("point") for e in events
                   if e.get("event") == "process_kill"}
    assert {"pod/mid_step", "checkpoint/pod_restore"} <= kill_points

    _assert_no_torn_checkpoint(root)
    for s, v in _losses_by_step(log0).items():
        assert abs(v - control[s]) < 1e-6, (s, v, control[s])


@pytest.mark.slow
@pytest.mark.chaos
@pytest.mark.parametrize("victim", [0, 3])
def test_pod_kill_heal_4proc(tmp_path, victim):
    """The 4-process heal sweep (slow tier): kill the committer (0) and
    the last rank (3) mid-step — three survivors shrink to world 3,
    the supervisor respawns the victim, the pod grows back to world 4,
    and all four ranks finish the 8-step trajectory at full world."""
    root = os.path.join(str(tmp_path), "ck")
    pod = VirtualPod(
        4, FIXTURE, workdir=str(tmp_path),
        kill=(victim, "pod/mid_step", 5), lease_ttl=LEASE_TTL,
        restart=RestartPolicy(max_restarts=2, base_delay=0.2, seed=0),
        env={"POD_FIX_CKPT_ROOT": root, "POD_FIX_TARGET_WORLD": "4",
             "POD_FIX_HEAL_BY_STEP": "6"})
    exits = pod.run(timeout=300)
    kills = [e for e in pod.exit_history
             if e.rank == victim and e.signal == "SIGKILL"]
    assert len(kills) == 1 and kills[0].incarnation == 1
    done = 0
    final = {}
    for r in range(4):
        assert exits[r].returncode == 0, pod.tail_logs()
        log = pod.log(r)
        if re.search(r"DONE rank=\d world=4", log):
            done += 1
        losses = _losses_by_step(log)
        if losses:
            final[r] = losses
    assert done == 4, pod.tail_logs()
    survivor = 1 if victim == 0 else 0
    trans = _reformed_transitions(pod.log(survivor))
    assert (3, 1, "shrink") in trans and (4, 2, "grow") in trans, trans
    base = final[survivor]
    assert sorted(base) == list(range(8))
    for r, losses in final.items():
        for s, v in losses.items():
            assert abs(v - base[s]) < 1e-9, (r, s)
    _assert_no_torn_checkpoint(root)


@pytest.mark.slow
@pytest.mark.chaos
@pytest.mark.parametrize("victim", [0, 1, 2, 3])
@pytest.mark.parametrize("point,nth", [
    ("pod/before_barrier", 4),
    ("pod/mid_step", 5),
    ("checkpoint/pod_shard_written", 2),
])
def test_pod_kill_sweep_4proc(tmp_path, victim, point, nth):
    """The full sweep at world 4: kill EVERY rank id at every named
    point; the three survivors re-form at world 3 (a RAGGED 3/3/2 batch
    split — the sum-allreduce keeps losses exact) and finish within
    1e-6 of the 8-step control trajectory."""
    root = os.path.join(str(tmp_path), "ck")
    pod = VirtualPod(4, FIXTURE, workdir=str(tmp_path),
                     kill=(victim, point, nth), lease_ttl=LEASE_TTL,
                     env={"POD_FIX_CKPT_ROOT": root})
    exits = pod.run(timeout=240)
    assert exits[victim].signal == "SIGKILL", exits
    survivors = [r for r in range(4) if r != victim]
    for r in survivors:
        assert exits[r].returncode == 0, pod.tail_logs()
    done = ranks_reformed = 0
    final = {}
    for r in survivors:
        log = pod.log(r)
        if "REFORMED" in log:
            ranks_reformed += 1
            assert re.search(r"REFORMED rank=\d world=3 gen=1", log), log
        if re.search(r"DONE rank=\d world=3", log):
            done += 1
        losses = _losses_by_step(log)
        if losses:
            final[r] = losses
    assert ranks_reformed == 3 and done == 3
    # survivors agree on the full 8-step trajectory
    base = final[survivors[0]]
    assert sorted(base) == list(range(8))
    for r in survivors[1:]:
        for s, v in final[r].items():
            assert abs(v - base[s]) < 1e-9
    _assert_no_torn_checkpoint(root)
