"""Round-4 residual op tail (reference: mean_iou_op.cc, chunk_eval_op.cc,
diag_embed_op.cc, bilinear_tensor_product_op.cc, shard_index_op.cc,
sampling_id_op.cc, match_matrix_tensor_op.cc, vision read_file/
decode_jpeg) — numpy-mirror OpTest-style cases."""
import io

import numpy as np
import pytest

import paddle_tpu as paddle

rng = np.random.RandomState(4)


class TestMeanIou:
    def test_matches_confusion_mirror(self):
        C = 4
        pred = rng.randint(0, C, (6, 5)).astype(np.int64)
        lab = rng.randint(0, C, (6, 5)).astype(np.int64)
        miou, wrong, correct = paddle.ops.mean_iou(
            paddle.to_tensor(pred), paddle.to_tensor(lab), C)
        w = np.zeros(C, np.int64)
        c = np.zeros(C, np.int64)
        for p, l in zip(pred.ravel(), lab.ravel()):
            if p == l:
                c[l] += 1
            else:
                w[p] += 1
                w[l] += 1
        denom = w + c
        valid = denom > 0
        want = (c[valid] / denom[valid]).mean()
        np.testing.assert_allclose(float(miou.numpy()), want, rtol=1e-6)
        np.testing.assert_array_equal(np.asarray(wrong.numpy()), w)
        np.testing.assert_array_equal(np.asarray(correct.numpy()), c)

    def test_perfect_prediction(self):
        lab = rng.randint(0, 3, (4, 4)).astype(np.int64)
        miou, _, _ = paddle.ops.mean_iou(
            paddle.to_tensor(lab), paddle.to_tensor(lab), 3)
        assert float(miou.numpy()) == pytest.approx(1.0)


class TestChunkEval:
    def test_iob_ner_case(self):
        # 2 chunk types; IOB: type*2=B, type*2+1=I, 4=Outside
        #         B0 I0 O  B1 I1 I1 O
        label = [[0, 1, 4, 2, 3, 3, 4]]
        #         B0 I0 O  B1 O  O  B0   (2nd chunk cut short + spurious)
        infer = [[0, 1, 4, 2, 4, 4, 0]]
        p, r, f1, ni, nl, nc = paddle.ops.chunk_eval(
            paddle.to_tensor(np.array(infer, np.int64)),
            paddle.to_tensor(np.array(label, np.int64)),
            "IOB", 2)
        assert int(ni.numpy()) == 3
        assert int(nl.numpy()) == 2
        assert int(nc.numpy()) == 1  # only the B0 I0 chunk matches
        assert float(p.numpy()) == pytest.approx(1 / 3)
        assert float(r.numpy()) == pytest.approx(1 / 2)
        assert float(f1.numpy()) == pytest.approx(2 * (1/3) * 0.5 / (1/3 + 0.5))

    def test_plain_scheme_and_seq_length(self):
        label = [[0, 0, 1, 1, 2, 2]]
        infer = [[0, 0, 1, 1, 2, 2]]
        # truncate at 4: the type-2 chunk is outside the sequence
        p, r, f1, ni, nl, nc = paddle.ops.chunk_eval(
            paddle.to_tensor(np.array(infer, np.int64)),
            paddle.to_tensor(np.array(label, np.int64)),
            "plain", 3, seq_length=paddle.to_tensor(
                np.array([4], np.int64)))
        assert int(ni.numpy()) == int(nl.numpy()) == int(nc.numpy()) == 2
        assert float(f1.numpy()) == pytest.approx(1.0)

    def test_iobes_singletons_and_excluded(self):
        # type 0: B=0 I=1 E=2 S=3; type 1: B=4 I=5 E=6 S=7; O=8
        label = [[3, 8, 4, 5, 6, 8, 7]]
        infer = [[3, 8, 4, 5, 6, 8, 8]]
        _, _, _, ni, nl, nc = paddle.ops.chunk_eval(
            paddle.to_tensor(np.array(infer, np.int64)),
            paddle.to_tensor(np.array(label, np.int64)), "IOBES", 2)
        assert int(nl.numpy()) == 3 and int(ni.numpy()) == 2
        assert int(nc.numpy()) == 2
        # excluding type 1 drops its chunks from all counts
        _, _, _, ni2, nl2, nc2 = paddle.ops.chunk_eval(
            paddle.to_tensor(np.array(infer, np.int64)),
            paddle.to_tensor(np.array(label, np.int64)), "IOBES", 2,
            excluded_chunk_types=[1])
        assert int(nl2.numpy()) == 1 and int(nc2.numpy()) == 1


class TestDiagEmbed:
    @pytest.mark.parametrize("offset", [0, 1, -2])
    def test_matches_torch_semantics(self, offset):
        import torch
        x = rng.randn(2, 3, 4).astype(np.float32)
        got = np.asarray(paddle.ops.diag_embed(
            paddle.to_tensor(x), offset=offset).numpy())
        want = torch.diag_embed(torch.tensor(x), offset=offset).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-6)

    def test_dims_and_grad(self):
        x = paddle.to_tensor(rng.randn(3).astype(np.float32))
        x.stop_gradient = False
        out = paddle.ops.diag_embed(x, offset=0, dim1=0, dim2=1)
        assert tuple(out.shape) == (3, 3)
        paddle.ops.sum(out * out).backward()
        np.testing.assert_allclose(np.asarray(x._grad),
                                   2 * np.asarray(x.numpy()), rtol=1e-6)


class TestBilinearTensorProduct:
    def test_matches_einsum_mirror_with_grad(self):
        B, I, J, K = 4, 3, 5, 2
        x = paddle.to_tensor(rng.randn(B, I).astype(np.float32))
        y = paddle.to_tensor(rng.randn(B, J).astype(np.float32))
        w = paddle.to_tensor(rng.randn(K, I, J).astype(np.float32))
        b = paddle.to_tensor(rng.randn(K).astype(np.float32))
        for t in (x, y, w, b):
            t.stop_gradient = False
        out = paddle.ops.bilinear_tensor_product(x, y, w, b)
        want = np.einsum("bi,kij,bj->bk", np.asarray(x.numpy()),
                         np.asarray(w.numpy()), np.asarray(y.numpy())) \
            + np.asarray(b.numpy())
        np.testing.assert_allclose(np.asarray(out.numpy()), want,
                                   rtol=1e-5)
        paddle.ops.sum(out).backward()
        assert x._grad is not None and w._grad is not None


class TestShardIndex:
    def test_reference_example(self):
        # shard_index_op doc example: 20 ids, 2 shards
        ids = np.array([[1], [6], [12], [19]], np.int64)
        got0 = np.asarray(paddle.ops.shard_index(
            paddle.to_tensor(ids), 20, 2, 0).numpy())
        got1 = np.asarray(paddle.ops.shard_index(
            paddle.to_tensor(ids), 20, 2, 1).numpy())
        np.testing.assert_array_equal(got0, [[1], [6], [-1], [-1]])
        np.testing.assert_array_equal(got1, [[-1], [-1], [2], [9]])
        with pytest.raises(ValueError):
            paddle.ops.shard_index(paddle.to_tensor(ids), 20, 2, 5)


class TestSamplingId:
    def test_deterministic_and_distributed(self):
        probs = np.tile(np.array([[0.05, 0.05, 0.8, 0.1]], np.float32),
                        (512, 1))
        out = np.asarray(paddle.ops.sampling_id(
            paddle.to_tensor(probs), seed=3).numpy())
        out2 = np.asarray(paddle.ops.sampling_id(
            paddle.to_tensor(probs), seed=3).numpy())
        np.testing.assert_array_equal(out, out2)
        assert out.min() >= 0 and out.max() <= 3
        # the 0.8 column dominates
        assert (out == 2).mean() > 0.6

    def test_degenerate_onehot(self):
        probs = np.eye(4, dtype=np.float32)
        out = np.asarray(paddle.ops.sampling_id(
            paddle.to_tensor(probs), seed=1).numpy())
        np.testing.assert_array_equal(out, [0, 1, 2, 3])


class TestVisionIO:
    def test_read_file_and_decode_jpeg(self, tmp_path):
        from PIL import Image
        # smooth gradient: JPEG-friendly content (noise is the codec's
        # worst case and would fail any content check)
        yy, xx = np.mgrid[0:10, 0:12]
        img = np.stack([yy * 20, xx * 20, (yy + xx) * 10],
                       axis=-1).astype(np.uint8)
        path = str(tmp_path / "t.jpg")
        Image.fromarray(img).save(path, quality=95)
        raw = paddle.ops.read_file(path)
        assert raw.dtype == paddle.uint8
        decoded = np.asarray(paddle.ops.decode_jpeg(raw).numpy())
        assert decoded.shape == (3, 10, 12)
        # lossy codec: approximate content match
        assert np.abs(decoded.transpose(1, 2, 0).astype(int)
                      - img.astype(int)).mean() < 12
        gray = np.asarray(paddle.ops.decode_jpeg(raw, mode="gray").numpy())
        assert gray.shape == (1, 10, 12)


class TestMatchMatrixTensor:
    def test_matches_mirror_and_masks(self):
        B, Lx, Ly, Dx, Dy, T = 2, 4, 5, 3, 3, 2
        x = rng.randn(B, Lx, Dx).astype(np.float32)
        y = rng.randn(B, Ly, Dy).astype(np.float32)
        w = rng.randn(Dx, T, Dy).astype(np.float32)
        xl = np.array([4, 2], np.int64)
        yl = np.array([5, 3], np.int64)
        out, mask = paddle.ops.match_matrix_tensor(
            paddle.to_tensor(x), paddle.to_tensor(y), paddle.to_tensor(w),
            x_lens=paddle.to_tensor(xl), y_lens=paddle.to_tensor(yl))
        want = np.einsum("bid,dtm,bjm->btij", x, w, y)
        np.testing.assert_allclose(np.asarray(out.numpy()), want,
                                   rtol=1e-4, atol=1e-5)
        m = np.asarray(mask.numpy())
        assert m.shape == (B, 1, Lx, Ly)
        assert m[1, 0, 2:, :].sum() == 0 and m[1, 0, :, 3:].sum() == 0
        assert m[0].sum() == Lx * Ly


class TestNewOptimizers:
    def _train(self, opt_cls, steps=5, **kw):
        import paddle_tpu.nn as nn
        paddle.seed(0)
        m = nn.Linear(6, 1)
        opt = opt_cls(learning_rate=0.1, parameters=m.parameters(), **kw)
        x = paddle.to_tensor(rng.rand(16, 6).astype(np.float32))
        y = paddle.to_tensor(rng.rand(16, 1).astype(np.float32))
        losses = []
        for _ in range(steps):
            loss = ((m(x) - y) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.numpy()))
        return losses

    @pytest.mark.parametrize("name,kw", [
        ("DecayedAdagrad", {}), ("ProximalGD", {"l1": 0.001}),
        ("ProximalAdagrad", {"l1": 0.001}), ("Ftrl", {"l1": 0.001}),
        ("Dpsgd", {"clip": 100.0, "sigma": 0.0}),
    ])
    def test_reduces_loss(self, name, kw):
        import paddle_tpu.optimizer as O
        losses = self._train(getattr(O, name), steps=12, **kw)
        assert losses[-1] < losses[0], (name, losses)

    def test_ftrl_matches_numpy_rule(self):
        import paddle_tpu.optimizer as O
        from paddle_tpu.core.tensor import Parameter
        import jax.numpy as jnp
        p0 = np.array([0.5, -0.4, 0.3], np.float32)
        param = Parameter(jnp.asarray(p0))
        opt = O.Ftrl(learning_rate=0.1, l1=0.01, l2=0.02,
                     parameters=[param])
        gseq = [rng.randn(3).astype(np.float32) for _ in range(3)]
        # numpy mirror of ftrl_op.h (lr_power=-0.5)
        p, sq, lin = p0.copy(), np.zeros(3), np.zeros(3)
        lr = 0.1
        for g in gseq:
            new_sq = sq + g * g
            sigma = (np.sqrt(new_sq) - np.sqrt(sq)) / lr
            lin = lin + g - sigma * p
            x = 0.01 * np.sign(lin) - lin
            y = np.sqrt(new_sq) / lr + 2 * 0.02
            p = np.where(np.abs(lin) > 0.01, x / y, 0.0)
            sq = new_sq
        for g in gseq:
            param._grad = jnp.asarray(g)
            opt.step()
            opt.clear_grad()
        np.testing.assert_allclose(np.asarray(param.numpy()), p,
                                   rtol=1e-5, atol=1e-6)


class TestDetectionTail:
    def test_bipartite_match_greedy(self):
        import paddle_tpu.vision.ops as V
        d = np.array([[[0.9, 0.2, 0.1],
                       [0.8, 0.7, 0.3]]], np.float32)
        idx, dist = V.bipartite_match(paddle.to_tensor(d))
        # global max 0.9 binds (row0,col0); next best among remaining is
        # (row1,col1)=0.7; col2 unmatched
        np.testing.assert_array_equal(np.asarray(idx.numpy()),
                                      [[0, 1, -1]])
        idx2, _ = V.bipartite_match(paddle.to_tensor(d),
                                    match_type="per_prediction",
                                    dist_threshold=0.25)
        np.testing.assert_array_equal(np.asarray(idx2.numpy()),
                                      [[0, 1, 1]])

    def test_target_assign_gather_and_weights(self):
        import paddle_tpu.vision.ops as V
        x = paddle.to_tensor(rng.rand(1, 3, 4).astype(np.float32))
        match = paddle.to_tensor(np.array([[2, -1, 0]], np.int64))
        out, wt = V.target_assign(x, match, mismatch_value=0)
        xn = np.asarray(x.numpy())
        np.testing.assert_allclose(np.asarray(out.numpy())[0, 0], xn[0, 2])
        np.testing.assert_array_equal(np.asarray(out.numpy())[0, 1],
                                      np.zeros(4))
        np.testing.assert_array_equal(np.asarray(wt.numpy()), [[1, 0, 1]])

    def test_density_prior_box_geometry(self):
        import paddle_tpu.vision.ops as V
        boxes, var = V.density_prior_box(
            paddle.to_tensor(np.zeros((1, 3, 2, 2), np.float32)),
            paddle.to_tensor(np.zeros((1, 3, 64, 64), np.float32)),
            densities=[2], fixed_sizes=[8.0], fixed_ratios=[1.0])
        b = np.asarray(boxes.numpy())
        assert b.shape == (2, 2, 4, 4)
        # away from edges nothing clips: all widths = fixed_size/img
        w = b[1, 1, :, 2] - b[1, 1, :, 0]
        np.testing.assert_allclose(w, 8.0 / 64.0, rtol=1e-5)
        # density 2 puts 4 distinct centers per cell on a half-step grid
        cx = (b[0, 0, :, 0] + b[0, 0, :, 2]) / 2
        cy = (b[0, 0, :, 1] + b[0, 0, :, 3]) / 2
        assert len({(round(float(a), 5), round(float(c), 5))
                    for a, c in zip(cx, cy)}) == 4
        v = np.asarray(var.numpy())
        np.testing.assert_allclose(v[0, 0, 0], [0.1, 0.1, 0.2, 0.2])

    def test_matrix_nms_suppresses_duplicates(self):
        import paddle_tpu.vision.ops as V
        # two near-identical boxes + one distant: the duplicate's score
        # must decay hard, the distant box must survive untouched
        bb = np.array([[[0, 0, 10, 10], [0, 0, 10, 10.5],
                        [50, 50, 60, 60]]], np.float32)
        sc = np.array([[[0.9, 0.85, 0.8]]], np.float32)
        out, num = V.matrix_nms(paddle.to_tensor(bb),
                                paddle.to_tensor(sc), 0.01,
                                background_label=-1)
        o = np.asarray(out.numpy())
        assert int(num.numpy()[0]) == 3
        s = np.sort(o[:, 2])
        assert np.isclose(s[-1], 0.9, atol=1e-5)   # top box untouched
        assert np.isclose(s[-2], 0.8, atol=1e-5)   # distant box kept
        assert s[0] < 0.2                          # duplicate decayed


class TestMiscTailOps:
    def test_add_position_encoding_mirror(self):
        x = rng.rand(2, 5, 8).astype(np.float32)
        got = np.asarray(paddle.ops.add_position_encoding(
            paddle.to_tensor(x), alpha=0.5, beta=2.0).numpy())
        half = 4
        pos = np.arange(5)[:, None]
        div = 10000.0 ** (np.arange(half) / half)
        pe = np.concatenate([np.sin(pos / div), np.cos(pos / div)], 1)
        np.testing.assert_allclose(got, 0.5 * x + 2.0 * pe[None],
                                   rtol=1e-5)

    def test_batch_fc_mirror(self):
        x = rng.rand(3, 4, 5).astype(np.float32)
        w = rng.rand(3, 5, 6).astype(np.float32)
        b = rng.rand(3, 1, 6).astype(np.float32)
        got = np.asarray(paddle.ops.batch_fc(
            paddle.to_tensor(x), paddle.to_tensor(w),
            paddle.to_tensor(b)).numpy())
        np.testing.assert_allclose(got,
                                   np.einsum("sbi,sio->sbo", x, w) + b,
                                   rtol=1e-5)

    def test_polygon_box_transform_formula(self):
        x = rng.rand(1, 2, 3, 4).astype(np.float32)
        got = np.asarray(paddle.ops.polygon_box_transform(
            paddle.to_tensor(x)).numpy())
        xs = np.arange(4)[None, None, None, :] * 4.0
        ys = np.arange(3)[None, None, :, None] * 4.0
        np.testing.assert_allclose(got[:, 0], (xs - x[:, 0:1])[:, 0],
                                   rtol=1e-6)
        np.testing.assert_allclose(got[:, 1], (ys - x[:, 1:2])[:, 0],
                                   rtol=1e-6)

    def test_correlation_center_is_mean_product(self):
        a = rng.rand(1, 4, 6, 6).astype(np.float32)
        b = rng.rand(1, 4, 6, 6).astype(np.float32)
        out = np.asarray(paddle.ops.correlation(
            paddle.to_tensor(a), paddle.to_tensor(b), 2, 1, 2).numpy())
        assert out.shape == (1, 25, 6, 6)
        # center displacement (0,0) = channel-mean of a*b
        np.testing.assert_allclose(out[0, 12], (a * b).mean(1)[0],
                                   rtol=1e-5)

    def test_sequence_topk_avg_pooling_mirror(self):
        x = rng.rand(2, 3, 7).astype(np.float32)
        lens = np.array([7, 4], np.int64)
        got = np.asarray(paddle.ops.sequence_topk_avg_pooling(
            paddle.to_tensor(x), paddle.to_tensor(lens), [1, 3]).numpy())
        for bi in range(2):
            L = lens[bi]
            for c in range(3):
                vals = np.sort(x[bi, c, :L])[::-1]
                np.testing.assert_allclose(got[bi, c, 0], vals[:1].mean(),
                                           rtol=1e-5)
                np.testing.assert_allclose(
                    got[bi, c, 1], vals[:min(3, L)].mean(), rtol=1e-5)

    def test_positive_negative_pair_counts(self):
        s = paddle.to_tensor(np.array([0.9, 0.1, 0.8, 0.2], np.float32))
        l = paddle.to_tensor(np.array([1, 0, 0, 1], np.float32))
        q = paddle.to_tensor(np.array([0, 0, 1, 1]))
        pos, neg, neu = paddle.ops.positive_negative_pair(s, l, q)
        assert (float(pos.numpy()), float(neg.numpy()),
                float(neu.numpy())) == (1.0, 1.0, 0.0)

    def test_truncated_normal_bounds(self):
        v = np.asarray(paddle.ops.truncated_normal(
            [5000], mean=1.0, std=0.5).numpy())
        assert v.min() >= 1.0 - 2 * 0.5 - 1e-5
        assert v.max() <= 1.0 + 2 * 0.5 + 1e-5
        assert abs(v.mean() - 1.0) < 0.05


def test_reduce_scatter_on_mesh():
    """reduce_scatter lowers to psum_scatter inside shard_map (the
    c_reducescatter analog): each rank ends with the rank-th elementwise
    sum of the per-rank lists."""
    import jax
    from jax.sharding import PartitionSpec as P

    import paddle_tpu.distributed as dist
    from paddle_tpu.core.tensor import Tensor

    mesh = dist.make_mesh({"dp": 8})
    dist.set_mesh(mesh)
    group = dist.new_group(axis_name="dp")

    def body(x):
        # every rank contributes a list of 8 chunks; chunk r of the
        # result = sum over ranks of their r-th chunk
        t = Tensor(x[:1] * 0.0)
        lst = [Tensor(x[:1] + float(r)) for r in range(8)]
        dist.reduce_scatter(t, lst, group=group)
        return t._value

    x = np.arange(8, dtype=np.float32)
    out = jax.shard_map(body, mesh=mesh, in_specs=P("dp"),
                        out_specs=P("dp"))(x)
    # rank k holds x[k]; chunk r result = sum_k (x[k] + r) = 28 + 8r;
    # rank r keeps chunk r
    np.testing.assert_allclose(np.asarray(out),
                               28.0 + 8.0 * np.arange(8))


def test_reduce_scatter_eager_wrong_length_raises():
    """Eager reduce_scatter validates len(tensor_list) against the
    group's nranks (broadcast's convention): a divergent list must raise
    instead of silently selecting the wrong shard."""
    import paddle_tpu.distributed as dist

    t = paddle.to_tensor(np.zeros(3, np.float32))
    lst2 = [paddle.to_tensor(np.full(3, float(r), np.float32))
            for r in range(2)]
    with pytest.raises(ValueError, match="group size"):
        dist.reduce_scatter(t, lst2)
    # the correct single-process length (world size 1) is the identity
    src = np.arange(3, dtype=np.float32)
    dist.reduce_scatter(t, [paddle.to_tensor(src)])
    np.testing.assert_allclose(np.asarray(t.numpy()), src)


def test_matrix_nms_gaussian_and_keep_all():
    import paddle_tpu.vision.ops as V
    bb = np.array([[[0, 0, 10, 10], [0, 0, 10, 10.5],
                    [50, 50, 60, 60]]], np.float32)
    sc = np.array([[[0.9, 0.85, 0.8]]], np.float32)
    out, num = V.matrix_nms(paddle.to_tensor(bb), paddle.to_tensor(sc),
                            0.01, background_label=-1, use_gaussian=True,
                            gaussian_sigma=2.0, nms_top_k=-1,
                            keep_top_k=-1)
    o = np.asarray(out.numpy())
    assert int(num.numpy()[0]) == 3  # -1 = keep all
    s = np.sort(o[:, 2])
    # gaussian decay with sigma MULTIPLYING: near-duplicate crushed
    assert s[0] < 0.2 and np.isclose(s[-1], 0.9, atol=1e-5)


class TestDetectionTraining:
    def test_rpn_target_assign_contract(self):
        import paddle_tpu.vision.ops as V
        anchors = np.array([
            [0, 0, 10, 10],     # ~gt0
            [1, 1, 11, 11],     # high overlap with gt0
            [40, 40, 50, 50],   # ~gt1
            [100, 100, 110, 110],  # background
            [200, 200, 210, 210],  # background
        ], np.float32)
        gts = np.array([[0, 0, 10, 10], [40, 40, 50, 50]], np.float32)
        loc, score, tgt, lab = V.rpn_target_assign(
            paddle.to_tensor(anchors), paddle.to_tensor(gts),
            rpn_batch_size_per_im=4, rpn_positive_overlap=0.7,
            rpn_negative_overlap=0.3)
        loc = np.asarray(loc.numpy())
        lab = np.asarray(lab.numpy())
        # exact-match anchors are positive (best-per-gt rule)
        assert 0 in loc and 2 in loc
        # backgrounds fill the rest of the budget as label 0
        assert (lab == 1).sum() == len(loc)
        assert (lab == 0).sum() >= 1
        # perfect-overlap positives have ~zero regression targets
        t = np.asarray(tgt.numpy())
        row0 = list(loc).index(0)
        np.testing.assert_allclose(t[row0], np.zeros(4), atol=1e-5)

    def test_mine_hard_examples_max_negative(self):
        import paddle_tpu.vision.ops as V
        loss = np.array([[0.1, 0.9, 0.5, 0.8, 0.2, 0.7]], np.float32)
        match = np.array([[0, -1, -1, -1, 1, -1]], np.int64)  # 2 pos
        neg = V.mine_hard_examples(paddle.to_tensor(loss),
                                   paddle.to_tensor(match),
                                   neg_pos_ratio=1.5)
        got = np.asarray(neg.numpy())[0]
        got = got[got >= 0]
        # budget = 1.5 * 2 = 3 hardest negatives: losses 0.9, 0.8, 0.7
        assert set(got.tolist()) == {1, 3, 5}

    def test_detection_map_perfect_and_partial(self):
        import paddle_tpu.vision.ops as V
        gt = np.array([
            [0, 1, 0, 0, 0, 10, 10],
            [0, 2, 0, 20, 20, 30, 30],
            [1, 1, 0, 5, 5, 15, 15],
        ], np.float32)
        perfect = np.array([
            [0, 1, 0.9, 0, 0, 10, 10],
            [0, 2, 0.8, 20, 20, 30, 30],
            [1, 1, 0.7, 5, 5, 15, 15],
        ], np.float32)
        m = V.detection_map(paddle.to_tensor(perfect),
                            paddle.to_tensor(gt), class_num=3)
        assert float(m.numpy()) == pytest.approx(1.0)
        # one class fully missed -> its AP 0; mAP = mean(1, 0) = 0.5
        partial = perfect[perfect[:, 1] == 1]
        m2 = V.detection_map(paddle.to_tensor(partial),
                             paddle.to_tensor(gt), class_num=3)
        assert float(m2.numpy()) == pytest.approx(0.5)
        # 11point mode agrees on the perfect case
        m3 = V.detection_map(paddle.to_tensor(perfect),
                             paddle.to_tensor(gt), class_num=3,
                             ap_version="11point")
        assert float(m3.numpy()) == pytest.approx(1.0)


class TestDetectionTrainingRegressions:
    def test_rpn_off_grid_gt_does_not_poison(self):
        import paddle_tpu.vision.ops as V
        anchors = np.array([[0, 0, 10, 10], [40, 40, 50, 50]], np.float32)
        gts = np.array([[0, 0, 10, 10],
                        [1000, 1000, 1010, 1010]], np.float32)
        loc, score, tgt, lab = V.rpn_target_assign(
            paddle.to_tensor(anchors), paddle.to_tensor(gts),
            rpn_batch_size_per_im=4)
        loc = np.asarray(loc.numpy())
        assert 0 in loc and 1 not in loc  # off-grid gt labels nothing
        lab = np.asarray(lab.numpy())
        assert (lab == 0).sum() >= 1      # negatives still sampled

    def test_mine_hard_examples_zero_positives(self):
        import paddle_tpu.vision.ops as V
        loss = np.array([[0.9, 0.8, 0.7]], np.float32)
        match = np.array([[-1, -1, -1]], np.int64)
        neg = np.asarray(V.mine_hard_examples(
            paddle.to_tensor(loss), paddle.to_tensor(match),
            neg_pos_ratio=3.0).numpy())[0]
        assert (neg >= 0).sum() == 0  # no positives -> no negatives

    def test_detection_map_difficult_skipped_not_fp(self):
        import paddle_tpu.vision.ops as V
        gt = np.array([[0, 1, 0, 0, 0, 10, 10],
                       [0, 1, 1, 20, 20, 30, 30]], np.float32)  # 2nd hard
        det = np.array([[0, 1, 0.9, 20, 20, 30, 30],   # matches difficult
                        [0, 1, 0.8, 0, 0, 10, 10]], np.float32)
        m = V.detection_map(paddle.to_tensor(det), paddle.to_tensor(gt),
                            class_num=2, evaluate_difficult=False)
        assert float(m.numpy()) == pytest.approx(1.0)
        with pytest.raises(ValueError, match="class_num"):
            V.detection_map(paddle.to_tensor(det), paddle.to_tensor(gt),
                            class_num=1)


def test_similarity_focus_axis1_mirror():
    """Greedy row/column-exclusive maxima across the selected channel
    (similarity_focus_op.h axis=1 loop), fiber set across all channels."""
    x = np.zeros((1, 2, 2, 3), np.float32)
    x[0, 0] = [[0.9, 0.1, 0.2],
               [0.3, 0.8, 0.1]]
    out = np.asarray(paddle.ops.similarity_focus(
        paddle.to_tensor(x), axis=1, indexes=[0]).numpy())
    # maxima: (0,0)=0.9 then (1,1)=0.8 (rows/cols exclusive) -> mask at
    # those (h,w) across BOTH channels
    want = np.zeros((2, 3), np.float32)
    want[0, 0] = want[1, 1] = 1.0
    np.testing.assert_array_equal(out[0, 0], want)
    np.testing.assert_array_equal(out[0, 1], want)
    with pytest.raises(ValueError, match="out of range"):
        paddle.ops.similarity_focus(paddle.to_tensor(x), 1, [5])
    with pytest.raises(ValueError, match="out of range"):
        paddle.ops.similarity_focus(paddle.to_tensor(x), 1, [-1])
