"""Latency-hiding ZeRO step: the double-buffered bucket prefetch.

The contract: ``_zero_enable(prefetch=True)`` (the default) restructures
the compiled step so collectives are EMITTED with schedulable slack —
bucket i+1's param all-gather rides bucket i's compute, bucket i's grad
reduce-scatter rides bucket i+1's update, and the step's tail re-gathers
bucket 0 into the prefetch carry slot so step N+1's forward starts warm
— while staying BITWISE-equal to the serial (``prefetch=False``)
schedule: per-bucket op order is unchanged, only emission position
moves. The schedulable-overlap meter (``overlap.schedulable_stats``,
sourced from the traced jaxpr — the compiled text's dependency postorder
erases emission structure) is the backend-independent referee that the
pipeline exists; the jaxpr-liveness meter referees its memory price
(one bucket: the carry slot).

Bucket configs here use ``comm_buffer_mb=0.003``: on the 16->32->8 MLP
that is LAYER-ALIGNED (bucket0={w1,b1}, bucket1={w2,b2}), which makes
the serial schedule's score exactly 0.0 — every gather's first consumer
is adjacent. Per-param buckets would give the serial arm a tiny honest
score (a bias gather rides the matmul that only needs the weight), which
is correct but not the 0-vs->0 A/B these tests pin.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.distributed import parallel_env

DP = 8
COMM_MB = 0.003  # layer-aligned buckets on the 16->32->8 MLP


@pytest.fixture(autouse=True)
def _mesh():
    mesh = parallel_env.make_mesh({"dp": DP})
    parallel_env.set_mesh(mesh)
    yield mesh
    parallel_env.set_mesh(None)
    from paddle_tpu.distributed.fleet.base import topology
    topology.set_hybrid_communicate_group(None)


rng = np.random.RandomState(77)


def _mlp(bf16=False):
    m = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 8))
    if bf16:
        m.to("bfloat16")
    return m


def _build(stage, k, bf16=False, prefetch=None, accumulate=None,
           grad_clip=None, seed=11):
    paddle.seed(seed)
    m = _mlp(bf16)
    opt = paddle.optimizer.AdamW(parameters=m.parameters(),
                                 learning_rate=0.05,
                                 multi_precision=bf16,
                                 grad_clip=grad_clip)
    if stage:
        opt._zero_enable(axis="dp", stage=stage, comm_buffer_mb=COMM_MB,
                         prefetch=prefetch)
    def one(xb, yb):
        loss = nn.functional.cross_entropy(m(xb), yb)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    step = paddle.jit.to_static(one, scan_steps=k, dp_axis="dp",
                                accumulate_steps=accumulate)
    return step, m, opt


def _batches(k, batch=16):
    x = rng.rand(k, batch, 16).astype("float32")
    y = rng.randint(0, 8, (k, batch)).astype("int64")
    return paddle.to_tensor(x), paddle.to_tensor(y)


def _params_bytes(m):
    return [np.asarray(p._value).tobytes() for p in m.parameters()]


# -- bitwise parity matrix -------------------------------------------------

@pytest.mark.parametrize("stage", [1, 3])
@pytest.mark.parametrize("k,acc", [(1, None), (4, None), (4, 2)],
                         ids=["k1", "k4", "k4_acc2"])
@pytest.mark.parametrize("bf16", [False, True], ids=["fp32", "bf16_master"])
def test_prefetch_bitwise_equals_serial(stage, k, acc, bf16):
    """Acceptance bar: the pipelined step is bitwise-equal to the serial
    step across zero{1,3} x scan k x accumulation x dtype — same losses
    on BOTH program calls (the second exercises the warm carry slot
    threaded through the donated state) and identical final params."""
    x, y = _batches(k)
    s_off, m_off, _ = _build(stage, k, bf16, prefetch=False,
                             accumulate=acc)
    s_on, m_on, _ = _build(stage, k, bf16, prefetch=True, accumulate=acc)
    assert s_off(x, y).numpy().tobytes() == s_on(x, y).numpy().tobytes()
    # second call: step N's tail prefetch feeds step N+1's forward
    assert s_off(x, y).numpy().tobytes() == s_on(x, y).numpy().tobytes()
    for b_off, b_on, p in zip(_params_bytes(m_off), _params_bytes(m_on),
                              m_on.parameters()):
        assert b_off == b_on, p.name


@pytest.mark.parametrize("stage", [1, 3])
@pytest.mark.parametrize("k", [1, 4])
@pytest.mark.parametrize("bf16", [False, True], ids=["fp32", "bf16_master"])
def test_prefetch_bitwise_matches_replicated_control(stage, k, bf16):
    """And the pipelined step vs the replicated (non-ZeRO) control:
    the full transitive chain control == serial == pipelined, bitwise."""
    x, y = _batches(k)
    s0, m0, _ = _build(0, k, bf16)
    s1, m1, _ = _build(stage, k, bf16, prefetch=True)
    assert s0(x, y).numpy().tobytes() == s1(x, y).numpy().tobytes()
    assert s0(x, y).numpy().tobytes() == s1(x, y).numpy().tobytes()
    for b0, b1, p in zip(_params_bytes(m0), _params_bytes(m1),
                         m1.parameters()):
        assert b0 == b1, p.name


def test_prefetch_global_norm_clip_parity():
    """ClipGradByGlobalNorm is a two-pass barrier (every shard's square
    sum before any update): the reduce side stays serial, but the
    forward all-gather pipeline still runs — parity holds at the same
    tolerance as the serial clip path, and the program still scores
    schedulable overlap from the gather side."""
    k = 2
    x, y = _batches(k)
    clip = paddle.nn.ClipGradByGlobalNorm(0.02)
    s_off, m_off, _ = _build(3, k, prefetch=False, grad_clip=clip)
    clip2 = paddle.nn.ClipGradByGlobalNorm(0.02)
    s_on, m_on, _ = _build(3, k, prefetch=True, grad_clip=clip2)
    assert s_off(x, y).numpy().tobytes() == s_on(x, y).numpy().tobytes()
    for b_off, b_on, p in zip(_params_bytes(m_off), _params_bytes(m_on),
                              m_on.parameters()):
        assert b_off == b_on, p.name
    assert s_on.schedulable_stats()["schedulable_overlap"] > 0.0


# -- the schedulable-overlap referee ---------------------------------------

def test_schedulable_overlap_pipelined_vs_serial():
    """The value gate: layer-aligned serial zero3 scores EXACTLY 0.0
    (every collective's first consumer is adjacent in emission order);
    the pipelined program scores > 0, with the prefetched gather, the
    deferred reduce-scatter, and the tail gather each given a real
    compute window."""
    k = 4
    x, y = _batches(k)
    s_off, _, _ = _build(3, k, prefetch=False)
    s_off(x, y)
    s_on, _, _ = _build(3, k, prefetch=True)
    s_on(x, y)
    off = s_off.schedulable_stats()
    on = s_on.schedulable_stats()
    assert off["source"] == on["source"] == "traced-jaxpr"
    assert off["schedulable_overlap"] == 0.0
    assert on["schedulable_overlap"] > 0.0
    # at least: the prefetched next-bucket gather, the bucket-0 tail
    # gather (rides the apply of later buckets), and one reduce-scatter
    # (rides the previous bucket's apply) have non-zero windows
    windowed = [p for p in on["pairs"] if p["available_ns"] > 0]
    assert len(windowed) >= 3, on["pairs"]
    assert any(p["op"] == "all-gather" for p in windowed)
    assert any(p["op"] == "reduce-scatter" for p in windowed)
    # overlap_stats() splices the jaxpr-sourced score into the
    # compiled-text report (the value the bench rows export)
    spliced = s_on.overlap_stats()
    assert spliced["schedulable_overlap"] == on["schedulable_overlap"]
    assert spliced["assumptions"]["schedulable_source"] == "traced-jaxpr"


def test_schedulable_overlap_accumulation_window():
    """The pipeline composes with accumulation windows: boundary-step
    reduce/update pipelining still scores with accumulate_steps=2."""
    k, a = 4, 2
    x, y = _batches(k)
    s_on, _, _ = _build(3, k, prefetch=True, accumulate=a)
    s_on(x, y)
    assert s_on.schedulable_stats()["schedulable_overlap"] > 0.0


# -- collective schedule shape ---------------------------------------------

def test_prefetch_keeps_collective_counts():
    """Pipelining must not add wire traffic: per-execution collective
    counts and bytes match the serial schedule exactly (the tail gather
    of bucket 0 REPLACES the next step's forward gather — the warm slot
    elides it)."""
    k = 2
    x, y = _batches(k)
    s_off, _, o_off = _build(3, k, prefetch=False)
    s_off(x, y)
    s_on, _, o_on = _build(3, k, prefetch=True)
    s_on(x, y)
    off = {s["op"]: s for s in s_off.collective_stats(per_execution=True)}
    on = {s["op"]: s for s in s_on.collective_stats(per_execution=True)}
    for op in ("all-gather", "reduce-scatter"):
        assert on[op]["count"] == off[op]["count"], (op, off[op], on[op])
        assert on[op]["bytes"] == off[op]["bytes"], (op, off[op], on[op])
    # both schedules sit exactly on shardcheck's predicted budget (the
    # predictor models the warm-slot elision, so prefetch=True is not
    # just "same as serial" but independently priced)
    from paddle_tpu.analysis import check_collective_budget
    assert check_collective_budget(s_off) == []
    assert check_collective_budget(s_on) == []


def test_prefetch_slot_carry_and_verifier():
    """The carry slot is real donated state: it rides the scan carry
    (replicated, carry-optional so prefetch=False builds skip it
    without a verifier warning) and the analysis pass accepts the
    pipelined build."""
    from paddle_tpu import analysis
    k = 2
    s_on, _, opt = _build(3, k, prefetch=True)
    x, y = _batches(k)
    s_on(x, y)
    slot = opt._zero["prefetch_slot"]
    part = s_on._last_partition
    assert slot._state_uid in set(part["donated"])
    assert analysis.errors(s_on.verify()) == []


# -- the memory referee ----------------------------------------------------

def test_prefetch_peak_within_one_bucket():
    """Acceptance bar: the jaxpr-liveness peak of the pipelined step
    stays within ONE bucket's bytes of the serial step's (the carry
    slot is the double-buffer's whole price; the meter models the
    donated-carry aliasing XLA compiles, so the slot's boundary
    crossings don't triple-bill)."""
    k = 4
    x, y = _batches(k)
    s_off, _, _ = _build(3, k, prefetch=False)
    s_off(x, y)
    s_on, _, opt = _build(3, k, prefetch=True)
    s_on(x, y)
    slot = opt._zero["prefetch_slot"]
    slot_bytes = int(np.prod(slot._value.shape)
                     * np.dtype(slot._value.dtype).itemsize)
    off = next(iter(s_off.traced_memory_stats().values()))
    on = next(iter(s_on.traced_memory_stats().values()))
    assert on["alias_io"] and off["alias_io"]
    delta = on["peak_bytes"] - off["peak_bytes"]
    assert 0 <= delta <= slot_bytes, (delta, slot_bytes, off, on)
    # the boundary grows by exactly the slot on each side
    assert on["argument_bytes"] - off["argument_bytes"] == slot_bytes
    assert on["output_bytes"] - off["output_bytes"] == slot_bytes


# -- checkpoint interplay --------------------------------------------------

def test_prefetch_checkpoint_restore_refreshes_slot():
    """restore_optimizer writes the bucket-0 param store directly (no
    flush), so it must re-derive the carry slot — a restored run and an
    uninterrupted run stay bitwise-equal through the prefetched
    forward."""
    from paddle_tpu.checkpoint import state as ckpt_state
    k = 2
    x, y = _batches(k)
    s_a, m_a, o_a = _build(3, k, prefetch=True, seed=19)
    s_a(x, y)
    rec = ckpt_state.loads(ckpt_state.dumps(
        ckpt_state.capture_optimizer(o_a)))
    ref = s_a(x, y).numpy().tobytes()  # uninterrupted second call
    ref_params = _params_bytes(m_a)

    s_b, m_b, o_b = _build(3, k, prefetch=True, seed=19)
    s_b(x, y)
    # poison then restore: the slot must come back from the restored
    # store, not survive as the stale derived cache
    o_b._zero["prefetch_slot"]._value = \
        o_b._zero["prefetch_slot"]._value * 0.0
    ckpt_state.restore_optimizer(o_b, rec)
    assert s_b(x, y).numpy().tobytes() == ref
    for got, want, p in zip(_params_bytes(m_b), ref_params,
                            m_b.parameters()):
        assert got == want, p.name
