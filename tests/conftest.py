"""Test config: force an 8-device virtual CPU mesh (the reference's
multi-process-on-localhost simulation strategy, SURVEY.md §4, mapped to
jax's host-platform device-count flag)."""
import os

os.environ.setdefault("XLA_FLAGS",
                      "--xla_force_host_platform_device_count=8")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running; excluded from the tier-1 run")
    config.addinivalue_line(
        "markers", "chaos: deterministic fault-injection tests "
        "(testing.faults kill-points); the fast subset runs in tier-1, "
        "run `pytest -m chaos` to select the whole family")


@pytest.fixture(autouse=True)
def _seed():
    import paddle_tpu as paddle
    paddle.seed(102)
    yield
