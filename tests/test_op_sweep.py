"""Parametrized OpTest sweep — every public op through check_output (+ a
numeric-vs-analytic check_grad for the differentiable ones), the TPU analog
of the reference's per-op test files under
`python/paddle/fluid/tests/unittests/test_*_op.py` driven by OpTest:270.

Each OPS entry: (name, op_fn, np_fn, inputs, kwargs, grad) — `grad=True`
runs central-difference vs tape gradients on the first input; inputs stay
tiny so the O(numel) numeric sweep is cheap. bf16 output parity runs for a
dtype-robust subset (BF16_OPS) with widened tolerances, mirroring the
reference's op_accuracy_white_list.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from op_test import check_output, check_grad

rng = np.random.RandomState(42)

A23 = rng.rand(2, 3).astype("float32") + 0.1
B23 = rng.rand(2, 3).astype("float32") + 0.1
A23n = (rng.rand(2, 3) - 0.5).astype("float32")
A34 = rng.rand(3, 4).astype("float32")
M23 = rng.rand(2, 3).astype("float32")
M34 = rng.rand(3, 4).astype("float32")
V3 = rng.rand(3).astype("float32") + 0.1
V3b = rng.rand(3).astype("float32") + 0.1
SQ = rng.rand(3, 3).astype("float32")
SEP = (np.arange(6, dtype="float32").reshape(2, 3) * 0.37 + 0.05)[::-1].copy()
POS = rng.rand(2, 3).astype("float32") * 0.8 + 0.1  # in (0.1, 0.9)
B223 = rng.rand(2, 2, 3).astype("float32")
B234 = rng.rand(2, 3, 4).astype("float32")
B243 = rng.rand(2, 4, 3).astype("float32")
IMG = rng.rand(1, 2, 6, 6).astype("float32")
IDX = np.array([0, 2], dtype="int64")
LBL = np.array([1, 0], dtype="int64")


def _softmax_np(x, axis=-1):
    e = np.exp(x - x.max(axis=axis, keepdims=True))
    return e / e.sum(axis=axis, keepdims=True)


def _erf_np(x):
    # Abramowitz-Stegun 7.1.26 (enough for 1e-5 with float64 inputs)
    import math
    v = np.vectorize(math.erf)
    return v(x.astype("float64")).astype(x.dtype)


# (name, op_fn, np_fn, inputs, kwargs, grad)
OPS = [
    # ---- unary math --------------------------------------------------
    ("exp", paddle.exp, np.exp, [A23n], {}, True),
    ("log", paddle.log, np.log, [POS], {}, True),
    ("log2", paddle.log2, np.log2, [POS], {}, True),
    ("log10", paddle.log10, np.log10, [POS], {}, True),
    ("log1p", paddle.log1p, np.log1p, [POS], {}, True),
    ("expm1", paddle.expm1, np.expm1, [A23n], {}, True),
    ("sqrt", paddle.sqrt, np.sqrt, [POS], {}, True),
    ("rsqrt", paddle.rsqrt, lambda x: 1 / np.sqrt(x), [POS], {}, True),
    ("square", paddle.square, np.square, [A23n], {}, True),
    ("abs", paddle.abs, np.abs, [A23], {}, True),
    ("sign", paddle.sign, np.sign, [A23n], {}, False),
    ("neg", paddle.neg, np.negative, [A23n], {}, True),
    ("reciprocal", paddle.reciprocal, np.reciprocal, [POS], {}, True),
    ("floor", paddle.floor, np.floor, [A23n * 3], {}, False),
    ("ceil", paddle.ceil, np.ceil, [A23n * 3], {}, False),
    ("round", paddle.round, np.round, [A23n * 3], {}, False),
    ("sin", paddle.sin, np.sin, [A23n], {}, True),
    ("cos", paddle.cos, np.cos, [A23n], {}, True),
    ("tan", paddle.tan, np.tan, [A23n], {}, True),
    ("asin", paddle.asin, np.arcsin, [POS - 0.5], {}, True),
    ("acos", paddle.acos, np.arccos, [POS - 0.5], {}, True),
    ("atan", paddle.atan, np.arctan, [A23n], {}, True),
    ("sinh", paddle.sinh, np.sinh, [A23n], {}, True),
    ("cosh", paddle.cosh, np.cosh, [A23n], {}, True),
    ("tanh", paddle.tanh, np.tanh, [A23n], {}, True),
    ("erf", paddle.erf, _erf_np, [A23n], {}, True),
    ("logit", paddle.logit, lambda x: np.log(x / (1 - x)), [POS], {}, True),
    ("isnan", paddle.isnan, np.isnan, [A23n], {}, False),
    ("isinf", paddle.isinf, np.isinf, [A23n], {}, False),
    ("isfinite", paddle.isfinite, np.isfinite, [A23n], {}, False),
    ("clip", paddle.clip, lambda x, min, max: np.clip(x, min, max),
     [A23n], {"min": -0.2, "max": 0.2}, True),
    ("cast", lambda x: paddle.cast(x, "float64"),
     lambda x: x.astype("float64"), [A23], {}, False),
    ("scale", paddle.scale, lambda x, scale, bias: x * scale + bias,
     [A23], {"scale": 2.0, "bias": 1.0}, True),
    # ---- binary ------------------------------------------------------
    ("add", paddle.add, np.add, [A23, B23], {}, True),
    ("subtract", paddle.subtract, np.subtract, [A23, B23], {}, True),
    ("multiply", paddle.multiply, np.multiply, [A23, B23], {}, True),
    ("divide", paddle.divide, np.divide, [A23, POS], {}, True),
    ("floor_divide", paddle.floor_divide, np.floor_divide,
     [A23 * 5, POS], {}, False),
    ("mod", paddle.mod, np.mod, [A23 * 5, POS], {}, False),
    ("pow", paddle.pow, np.power, [POS, B23], {}, True),
    ("maximum", paddle.maximum, np.maximum, [A23, B23], {}, True),
    ("minimum", paddle.minimum, np.minimum, [A23, B23], {}, True),
    ("atan2", paddle.atan2, np.arctan2, [A23, B23], {}, True),
    ("broadcast_add", paddle.add, np.add, [A23, V3], {}, True),
    # ---- comparison / logical ---------------------------------------
    ("equal", paddle.equal, np.equal, [A23, A23], {}, False),
    ("not_equal", paddle.not_equal, np.not_equal, [A23, B23], {}, False),
    ("greater_than", paddle.greater_than, np.greater, [A23, B23], {}, False),
    ("greater_equal", paddle.greater_equal, np.greater_equal,
     [A23, B23], {}, False),
    ("less_than", paddle.less_than, np.less, [A23, B23], {}, False),
    ("less_equal", paddle.less_equal, np.less_equal, [A23, B23], {}, False),
    ("logical_and", paddle.logical_and, np.logical_and,
     [A23 > 0.5, B23 > 0.5], {}, False),
    ("logical_or", paddle.logical_or, np.logical_or,
     [A23 > 0.5, B23 > 0.5], {}, False),
    ("logical_not", paddle.logical_not, np.logical_not, [A23 > 0.5], {},
     False),
    ("logical_xor", paddle.logical_xor, np.logical_xor,
     [A23 > 0.5, B23 > 0.5], {}, False),
    ("where", paddle.where, np.where, [A23 > 0.5, A23, B23], {}, False),
    # ---- reductions --------------------------------------------------
    ("sum", paddle.sum, np.sum, [A23], {}, True),
    ("sum_axis", lambda x: paddle.sum(x, axis=1),
     lambda x: np.sum(x, axis=1), [A23], {}, True),
    ("mean", paddle.mean, np.mean, [A23], {}, True),
    ("max", paddle.max, np.max, [SEP], {}, True),
    ("min", paddle.min, np.min, [SEP], {}, True),
    ("prod", paddle.prod, np.prod, [POS], {}, True),
    ("std", paddle.std, lambda x: np.std(x, ddof=1), [A23], {}, True),
    ("var", paddle.var, lambda x: np.var(x, ddof=1), [A23], {}, True),
    ("logsumexp", paddle.logsumexp,
     lambda x: np.log(np.sum(np.exp(x))), [A23n], {}, True),
    ("all", paddle.all, np.all, [A23 > 0.05], {}, False),
    ("any", paddle.any, np.any, [A23 > 0.9], {}, False),
    ("argmax", paddle.argmax, np.argmax, [A23], {}, False),
    ("argmin", paddle.argmin, np.argmin, [A23], {}, False),
    ("cumsum", paddle.cumsum, lambda x: np.cumsum(x), [A23], {}, True),
    ("cumsum_axis", lambda x: paddle.cumsum(x, axis=1),
     lambda x: np.cumsum(x, axis=1), [A23], {}, True),
    ("cumprod", lambda x: paddle.cumprod(x, dim=1),
     lambda x: np.cumprod(x, axis=1), [POS], {}, True),
    ("amax_axis", lambda x: paddle.max(x, axis=0),
     lambda x: np.max(x, axis=0), [A23], {}, True),
    # ---- linalg ------------------------------------------------------
    ("matmul", paddle.matmul, np.matmul, [M23, M34], {}, True),
    ("bmm", paddle.bmm, np.matmul, [B234, B243], {}, True),
    ("mm", paddle.mm, np.matmul, [M23, M34], {}, True),
    ("dot", paddle.dot, np.dot, [V3, V3b], {}, True),
    ("t", paddle.t, np.transpose, [M23], {}, True),
    ("norm_fro", paddle.norm, lambda x: np.linalg.norm(x), [A23], {}, True),
    ("addmm", paddle.addmm,
     lambda inp, x, y: inp + x @ y, [rng.rand(2, 4).astype("float32"),
                                     M23, M34], {}, True),
    ("einsum_ij", lambda x, y: paddle.einsum("ij,jk->ik", x, y),
     lambda x, y: np.einsum("ij,jk->ik", x, y), [M23, M34], {}, True),
    # ---- manipulation ------------------------------------------------
    ("reshape", lambda x: paddle.reshape(x, [3, 2]),
     lambda x: x.reshape(3, 2), [A23], {}, True),
    ("flatten", paddle.flatten, lambda x: x.reshape(-1), [B223], {}, True),
    ("flatten_axes", lambda x: paddle.flatten(x, start_axis=1),
     lambda x: x.reshape(2, -1), [B223], {}, True),
    ("transpose", lambda x: paddle.transpose(x, [1, 0]),
     lambda x: x.transpose(1, 0), [A23], {}, True),
    ("moveaxis", lambda x: paddle.moveaxis(x, 0, 1),
     lambda x: np.moveaxis(x, 0, 1), [A23], {}, True),
    ("swapaxes", lambda x: paddle.swapaxes(x, 0, 1),
     lambda x: np.swapaxes(x, 0, 1), [A23], {}, True),
    ("squeeze", paddle.squeeze, np.squeeze,
     [rng.rand(2, 1, 3).astype("float32")], {}, True),
    ("unsqueeze", lambda x: paddle.unsqueeze(x, 1),
     lambda x: np.expand_dims(x, 1), [A23], {}, True),
    ("concat", lambda x, y: paddle.concat([x, y], axis=0),
     lambda x, y: np.concatenate([x, y], 0), [A23, B23], {}, True),
    ("stack", lambda x, y: paddle.stack([x, y], axis=0),
     lambda x, y: np.stack([x, y], 0), [A23, B23], {}, True),
    ("split", lambda x: paddle.split(x, 3, axis=1)[1],
     lambda x: np.split(x, 3, 1)[1], [A23], {}, True),
    ("chunk", lambda x: paddle.chunk(x, 3, axis=1)[2],
     lambda x: np.array_split(x, 3, 1)[2], [A23], {}, True),
    ("tile", lambda x: paddle.tile(x, [2, 1]),
     lambda x: np.tile(x, (2, 1)), [A23], {}, True),
    ("expand", lambda x: paddle.expand(x, [2, 3]),
     lambda x: np.broadcast_to(x, (2, 3)), [V3], {}, True),
    ("broadcast_to", lambda x: paddle.broadcast_to(x, [2, 3]),
     lambda x: np.broadcast_to(x, (2, 3)), [V3], {}, True),
    ("flip", lambda x: paddle.flip(x, axis=[0]),
     lambda x: np.flip(x, 0), [A23], {}, True),
    ("roll", lambda x: paddle.roll(x, 1, axis=0),
     lambda x: np.roll(x, 1, 0), [A23], {}, True),
    ("pad", lambda x: paddle.nn.functional.pad(x, [1, 1], value=0.0),
     lambda x: np.pad(x, [(0, 0), (1, 1)]), [A23], {}, True),
    ("gather", lambda x: paddle.gather(x, paddle.to_tensor(IDX), axis=1),
     lambda x: x[:, IDX], [A23], {}, True),
    ("index_select",
     lambda x: paddle.index_select(x, paddle.to_tensor(IDX), axis=1),
     lambda x: x[:, IDX], [A23], {}, True),
    ("gather_nd",
     lambda x: paddle.gather_nd(x, paddle.to_tensor(
         np.array([[0, 1], [1, 2]], "int64"))),
     lambda x: x[[0, 1], [1, 2]], [A23], {}, True),
    ("take_along_axis",
     lambda x: paddle.take_along_axis(
         x, paddle.to_tensor(np.array([[0], [1]], "int64")), 1),
     lambda x: np.take_along_axis(x, np.array([[0], [1]]), 1), [A23], {},
     True),
    ("masked_select",
     lambda x: paddle.masked_select(x, paddle.to_tensor(A23 > 0.5)),
     lambda x: x[A23 > 0.5], [A23], {}, False),
    ("masked_fill",
     lambda x: paddle.masked_fill(x, paddle.to_tensor(A23 > 0.5), 0.0),
     lambda x: np.where(A23 > 0.5, 0.0, x), [A23], {}, True),
    ("unstack", lambda x: paddle.unstack(x, axis=0)[0],
     lambda x: x[0], [A23], {}, True),
    ("one_hot", lambda: paddle.nn.functional.one_hot(
        paddle.to_tensor(LBL), 3),
     lambda: np.eye(3, dtype="float32")[LBL], [], {}, False),
    ("unique", lambda: paddle.unique(paddle.to_tensor(
        np.array([1, 3, 1, 2], "int64"))),
     lambda: np.unique(np.array([1, 3, 1, 2], "int64")), [], {}, False),
    ("repeat_interleave", lambda x: paddle.repeat_interleave(x, 2, axis=0),
     lambda x: np.repeat(x, 2, 0), [A23], {}, True),
    ("slice_basic", lambda x: x[0:1, 1:3], lambda x: x[0:1, 1:3],
     [A23], {}, True),
    ("index_sample",
     lambda x: paddle.index_sample(x, paddle.to_tensor(
         np.array([[0, 1], [2, 0]], "int64"))),
     lambda x: np.take_along_axis(x, np.array([[0, 1], [2, 0]]), 1),
     [A23], {}, True),
    # ---- creation ----------------------------------------------------
    ("zeros", lambda: paddle.zeros([2, 3]),
     lambda: np.zeros((2, 3), "float32"), [], {}, False),
    ("ones", lambda: paddle.ones([2, 3]),
     lambda: np.ones((2, 3), "float32"), [], {}, False),
    ("full", lambda: paddle.full([2, 2], 7.0),
     lambda: np.full((2, 2), 7.0, "float32"), [], {}, False),
    ("arange", lambda: paddle.arange(0, 10, 2),
     lambda: np.arange(0, 10, 2), [], {}, False),
    ("linspace", lambda: paddle.linspace(0, 1, 5),
     lambda: np.linspace(0, 1, 5, dtype="float32"), [], {}, False),
    ("eye", lambda: paddle.eye(3), lambda: np.eye(3, dtype="float32"),
     [], {}, False),
    ("tril", paddle.tril, np.tril, [SQ], {}, True),
    ("triu", paddle.triu, np.triu, [SQ], {}, True),
    ("diag", paddle.diag, np.diag, [V3], {}, False),
    ("zeros_like", paddle.zeros_like, np.zeros_like, [A23], {}, False),
    ("ones_like", paddle.ones_like, np.ones_like, [A23], {}, False),
    ("full_like", lambda x: paddle.full_like(x, 3.0),
     lambda x: np.full_like(x, 3.0), [A23], {}, False),
    ("meshgrid", lambda x, y: paddle.meshgrid(x, y)[0],
     lambda x, y: np.meshgrid(x, y, indexing="ij")[0], [V3, V3b], {}, False),
    # ---- sort family ---------------------------------------------------
    ("sort", lambda x: paddle.sort(x, axis=1),
     lambda x: np.sort(x, axis=1), [A23], {}, True),
    ("argsort", lambda x: paddle.argsort(x, axis=1),
     lambda x: np.argsort(x, axis=1, kind="stable"), [A23], {}, False),
    ("topk", lambda x: paddle.topk(x, 2, axis=1)[0],
     lambda x: np.sort(x, axis=1)[:, ::-1][:, :2], [SEP], {}, True),
    # ---- activations (nn.functional) ---------------------------------
    ("relu", F.relu, lambda x: np.maximum(x, 0), [A23n], {}, True),
    ("relu6", F.relu6, lambda x: np.clip(x, 0, 6), [A23n * 8], {}, True),
    ("sigmoid", F.sigmoid, lambda x: 1 / (1 + np.exp(-x)), [A23n], {}, True),
    ("softmax", F.softmax, _softmax_np, [A23n], {}, True),
    ("log_softmax", F.log_softmax,
     lambda x: np.log(_softmax_np(x)), [A23n], {}, True),
    ("gelu", F.gelu,
     lambda x: x * 0.5 * (1 + _erf_np(x / np.sqrt(2.0))), [A23n],
     {}, True),
    ("leaky_relu", F.leaky_relu,
     lambda x: np.where(x > 0, x, 0.01 * x), [A23n], {}, True),
    ("elu", F.elu, lambda x: np.where(x > 0, x, np.expm1(x)), [A23n], {},
     True),
    ("selu", F.selu,
     lambda x: 1.0507009873554805 * np.where(
         x > 0, x, 1.6732632423543772 * np.expm1(x)), [A23n], {}, True),
    ("softplus", F.softplus, lambda x: np.log1p(np.exp(x)), [A23n], {}, True),
    ("softsign", F.softsign, lambda x: x / (1 + np.abs(x)), [A23n], {}, True),
    ("hardtanh", F.hardtanh, lambda x: np.clip(x, -1, 1), [A23n * 4], {},
     True),
    ("hardsigmoid", F.hardsigmoid,
     lambda x: np.clip(x / 6 + 0.5, 0, 1), [A23n * 8], {}, True),
    ("hardswish", F.hardswish,
     lambda x: x * np.clip(x + 3, 0, 6) / 6, [A23n * 4], {}, True),
    ("silu", F.silu, lambda x: x / (1 + np.exp(-x)), [A23n], {}, True),
    ("mish", F.mish,
     lambda x: x * np.tanh(np.log1p(np.exp(x))), [A23n], {}, True),
    ("swish", F.swish, lambda x: x / (1 + np.exp(-x)), [A23n], {}, True),
    ("tanhshrink", F.tanhshrink, lambda x: x - np.tanh(x), [A23n], {}, True),
    ("softshrink", lambda x: F.softshrink(x, 0.1),
     lambda x: np.where(x > 0.1, x - 0.1, np.where(x < -0.1, x + 0.1, 0.0)),
     [A23n], {}, True),
    ("hardshrink", lambda x: F.hardshrink(x, 0.1),
     lambda x: np.where(np.abs(x) > 0.1, x, 0.0), [A23n], {}, True),
    ("prelu", lambda x: F.prelu(x, paddle.to_tensor(
        np.array([0.25], "float32"))),
     lambda x: np.where(x > 0, x, 0.25 * x), [A23n], {}, True),
    # ---- losses --------------------------------------------------------
    ("mse_loss", F.mse_loss, lambda x, y: np.mean((x - y) ** 2),
     [A23, B23], {}, True),
    ("l1_loss", F.l1_loss, lambda x, y: np.mean(np.abs(x - y)),
     [A23, B23], {}, True),
    ("smooth_l1", lambda x, y: F.smooth_l1_loss(x, y),
     lambda x, y: np.mean(np.where(np.abs(x - y) < 1.0,
                                   0.5 * (x - y) ** 2,
                                   np.abs(x - y) - 0.5)),
     [A23 * 3, B23], {}, True),
    ("bce_loss", F.binary_cross_entropy,
     lambda x, y: np.mean(-(y * np.log(x) + (1 - y) * np.log(1 - x))),
     [POS, (B23 > 0.5).astype("float32")], {}, True),
    ("bce_with_logits", F.binary_cross_entropy_with_logits,
     lambda x, y: np.mean(
         np.maximum(x, 0) - x * y + np.log1p(np.exp(-np.abs(x)))),
     [A23n, (B23 > 0.5).astype("float32")], {}, True),
    ("kl_div", lambda x, y: F.kl_div(paddle.log(x), y, reduction="mean"),
     lambda x, y: np.mean(y * (np.log(y) - np.log(x))),
     [_softmax_np(A23n), _softmax_np(B23)], {}, True),
    ("cross_entropy",
     lambda x: F.cross_entropy(x, paddle.to_tensor(LBL)),
     lambda x: -np.mean(np.log(_softmax_np(x)[np.arange(2), LBL])),
     [A23n], {}, True),
    ("nll_loss",
     lambda x: F.nll_loss(paddle.log(x), paddle.to_tensor(LBL)),
     lambda x: -np.mean(np.log(x)[np.arange(2), LBL]),
     [_softmax_np(A23n)], {}, True),
    # ---- nn structure ops ----------------------------------------------
    ("linear", lambda x: F.linear(x, paddle.to_tensor(M34),
                                  paddle.to_tensor(V3b[:4].copy()
                                                   if len(V3b) >= 4 else
                                                   np.zeros(4, "float32"))),
     lambda x: x @ M34 + (V3b[:4] if len(V3b) >= 4
                          else np.zeros(4, "float32")),
     [M23], {}, True),
    ("avg_pool2d", lambda x: F.avg_pool2d(x, 2),
     lambda x: x.reshape(1, 2, 3, 2, 3, 2).mean(axis=(3, 5)), [IMG], {},
     True),
    ("max_pool2d", lambda x: F.max_pool2d(x, 2),
     lambda x: x.reshape(1, 2, 3, 2, 3, 2).max(axis=(3, 5)), [IMG], {},
     True),
    ("embedding",
     lambda: F.embedding(paddle.to_tensor(IDX), paddle.to_tensor(M34)),
     lambda: M34[IDX], [], {}, False),
    # ---- sequence ops (LoD analog, reference sequence_ops/) -----------
    ("sequence_mask",
     lambda: paddle.ops.sequence.sequence_mask(
         paddle.to_tensor(np.array([2, 3], "int64")), maxlen=4),
     lambda: (np.arange(4)[None, :] < np.array([[2], [3]])),
     [], {}, False),
]


GRAD_OPS = [(n, op, ins, kw) for n, op, _, ins, kw, g in OPS if g]


@pytest.mark.parametrize("name,op_fn,np_fn,inputs,kwargs",
                         [(n, o, r, i, k) for n, o, r, i, k, _ in OPS],
                         ids=[o[0] for o in OPS])
def test_output(name, op_fn, np_fn, inputs, kwargs):
    check_output(op_fn, np_fn, inputs, kwargs=kwargs)


@pytest.mark.parametrize("name,op_fn,inputs,kwargs", GRAD_OPS,
                         ids=[o[0] for o in GRAD_OPS])
def test_grad(name, op_fn, inputs, kwargs):
    check_grad(op_fn, inputs, kwargs=kwargs)


# bf16 parity subset (tolerances per the reference threshold white list)
BF16_OPS = ["exp", "sqrt", "square", "abs", "tanh", "add", "subtract",
            "multiply", "maximum", "minimum", "sum", "mean", "matmul",
            "relu", "sigmoid", "softmax", "gelu"]


@pytest.mark.parametrize("name", BF16_OPS)
def test_bf16_output(name):
    entry = next(o for o in OPS if o[0] == name)
    _, op_fn, np_fn, inputs, kwargs, _ = entry
    check_output(op_fn, np_fn, inputs, dtype="bfloat16", kwargs=kwargs)
