"""Dygraph PipelineParallel: stage placement + host 1F1B schedule.

Reference behaviors under test (`fleet/meta_parallel/pipeline_parallel.py`,
`section_worker.cc:148-175`): stage parameters actually live on distinct
devices along the 'pp' mesh axis; training matches single-device execution;
the 1F1B order bounds in-flight microbatch graphs by S, not M.
"""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.distributed as dist
import paddle_tpu.distributed.fleet as fleet
from paddle_tpu.distributed.fleet.meta_parallel import (
    LayerDesc, PipelineLayer, PipelineParallel)
from paddle_tpu.distributed import parallel_env


def _loss_fn(out, label):
    return nn.functional.mse_loss(out, label)


def _make_pp(num_stages, accumulate_steps, seed=9):
    paddle.seed(seed)
    pp_layer = PipelineLayer(
        [LayerDesc(nn.Linear, 8, 16), LayerDesc(nn.Linear, 16, 16),
         LayerDesc(nn.Linear, 16, 16), LayerDesc(nn.Linear, 16, 4)],
        num_stages=num_stages, loss_fn=_loss_fn)
    strategy = fleet.DistributedStrategy()
    strategy.pipeline_configs = {"accumulate_steps": accumulate_steps,
                                 "schedule_mode": "1F1B"}
    return pp_layer, PipelineParallel(pp_layer, None, strategy)


class TestStagePlacement:
    def test_params_live_on_distinct_devices(self):
        mesh = parallel_env.set_mesh(dist.make_mesh({"pp": 4}))
        try:
            pp_layer, pp = _make_pp(num_stages=4, accumulate_steps=2)
            x = paddle.to_tensor(np.random.rand(4, 8).astype("float32"))
            pp(x)  # triggers placement
            assert pp._stage_devs is not None
            seen = set()
            for s in range(4):
                for kind, item in pp_layer.get_stage_layers(s):
                    for p in item.parameters():
                        (dev,) = p._value.devices()
                        assert dev == pp._stage_devs[s]
                        seen.add(dev)
            assert len(seen) == 4  # four distinct devices
        finally:
            parallel_env.set_mesh(None)

    def test_placed_training_matches_single_device(self):
        x = np.random.RandomState(0).rand(8, 8).astype("float32")
        y = np.random.RandomState(1).rand(8, 4).astype("float32")

        mesh = parallel_env.set_mesh(dist.make_mesh({"pp": 4}))
        try:
            pp_layer, pp = _make_pp(num_stages=4, accumulate_steps=4)
            opt = paddle.optimizer.SGD(learning_rate=0.1,
                                       parameters=pp_layer.parameters())
            loss_pp = pp.train_batch(
                (paddle.to_tensor(x), paddle.to_tensor(y)), opt)
            w_pp = pp_layer.layers[0].weight.numpy().copy()
            assert pp._stage_devs is not None  # really ran placed
        finally:
            parallel_env.set_mesh(None)

        paddle.seed(9)
        ref = nn.Sequential(nn.Linear(8, 16), nn.Linear(16, 16),
                            nn.Linear(16, 16), nn.Linear(16, 4))
        opt2 = paddle.optimizer.SGD(learning_rate=0.1,
                                    parameters=ref.parameters())
        loss = _loss_fn(ref(paddle.to_tensor(x)), paddle.to_tensor(y))
        loss.backward()
        opt2.step()
        np.testing.assert_allclose(w_pp, ref[0].weight.numpy(), rtol=1e-5,
                                   atol=1e-6)
        np.testing.assert_allclose(float(loss_pp.numpy()),
                                   float(loss.numpy()), rtol=1e-5)


class TestScheduleLiveness:
    def test_1f1b_bounds_in_flight_by_S(self):
        """M=8 microbatches over S=2 stages: F-then-B would hold 8 graphs;
        1F1B must hold ≤ S."""
        x = np.random.RandomState(0).rand(16, 8).astype("float32")
        y = np.random.RandomState(1).rand(16, 4).astype("float32")
        pp_layer, pp = _make_pp(num_stages=2, accumulate_steps=8)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=pp_layer.parameters())
        pp.train_batch((paddle.to_tensor(x), paddle.to_tensor(y)), opt)
        assert len(pp._last_schedule) == 16  # 8 F + 8 B
        assert pp.max_in_flight() <= 2
        # and the schedule interleaves: the first B happens before the last F
        kinds = [k for k, _ in pp._last_schedule]
        assert kinds.index("B") < len(kinds) - 1 - kinds[::-1].index("F")

    def test_backward_order_is_fifo(self):
        x = np.random.RandomState(0).rand(8, 8).astype("float32")
        y = np.random.RandomState(1).rand(8, 4).astype("float32")
        pp_layer, pp = _make_pp(num_stages=2, accumulate_steps=4)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=pp_layer.parameters())
        pp.train_batch((paddle.to_tensor(x), paddle.to_tensor(y)), opt)
        f_order = [m for k, m in pp._last_schedule if k == "F"]
        b_order = [m for k, m in pp._last_schedule if k == "B"]
        assert f_order == sorted(f_order)
        assert b_order == sorted(b_order)  # oldest-first backward


def test_param_size_segmentation_balances_stages():
    """seg_method='param_size': boundaries at the quantiles of cumulative
    parameter counts, so a fat embedding doesn't share a stage with half
    the blocks (reference: later-release SegmentLayers param balancing)."""
    import numpy as np
    from paddle_tpu import nn
    from paddle_tpu.distributed.fleet.meta_parallel import (LayerDesc,
                                                            PipelineLayer)

    descs = [LayerDesc(nn.Linear, 4, 400),   # fat
             LayerDesc(nn.Linear, 4, 4),
             LayerDesc(nn.Linear, 4, 4),
             LayerDesc(nn.Linear, 4, 4)]
    pp = PipelineLayer(descs, num_stages=2, seg_method="param_size")
    s0 = pp.get_stage_layers(0)
    s1 = pp.get_stage_layers(1)
    assert len(s0) == 1 and len(s1) == 3  # fat layer alone on stage 0

    import pytest
    with pytest.raises(ValueError, match="unknown seg_method"):
        PipelineLayer(descs, num_stages=2, seg_method="typo")
