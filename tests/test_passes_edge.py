"""static/passes.py edge cases: pruning through _buffer_updates,
delete_dropout on dropout-free programs, pass composition order
(ISSUE 3 satellite)."""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.analysis as analysis
from paddle_tpu import nn, static


def _bn_prog():
    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", [2, 4, 3, 3], "float32")
        conv = nn.Conv2D(4, 4, 1)
        pre = conv(x)
        bn = nn.BatchNorm2D(4)
        post = bn(pre)
        loss = paddle.mean(post)
    return prog, bn, pre, post, loss


class TestPruneThroughBufferUpdates:
    def test_prune_to_post_bn_keeps_updates(self):
        prog, bn, _pre, post, _loss = _bn_prog()
        pruned = static.prune(prog, [post])
        assert "batch_norm_stat_update" in [op.name for op in pruned.ops]
        assert pruned._buffer_updates  # aliases survive with the producer
        assert analysis.verify(pruned, targets=[post]) == []
        # executing the pruned program still write-backs the buffers
        before = np.asarray(bn._mean.numpy()).copy()
        exe = static.Executor()
        exe.run(pruned,
                feed={"x": np.random.RandomState(0)
                      .rand(2, 4, 3, 3).astype(np.float32)},
                fetch_list=[post])
        assert not np.allclose(before, np.asarray(bn._mean.numpy()))

    def test_prune_to_pre_bn_drops_updates(self):
        prog, _bn, pre, _post, _loss = _bn_prog()
        pruned = static.prune(prog, [pre])
        names = [op.name for op in pruned.ops]
        assert "batch_norm" not in names
        assert "batch_norm_stat_update" not in names
        # no dangling aliases left behind (the seeded-defect class)
        assert pruned._buffer_updates == {}
        assert analysis.verify(pruned, targets=[pre]) == []


class TestPassEdgeCases:
    def test_delete_dropout_without_dropout(self):
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [2, 4], "float32")
            y = paddle.tanh(x)
        out = static.apply_pass(prog, "delete_dropout_op_pass")
        assert out is not prog  # contract: always a new Program
        assert out.op_names() == prog.op_names()
        exe = static.Executor()
        (got,) = exe.run(out, feed={"x": np.ones((2, 4), np.float32)},
                         fetch_list=[y])
        np.testing.assert_allclose(np.asarray(got),
                                   np.tanh(np.ones((2, 4))), rtol=1e-6)

    def test_pass_composition_order(self):
        def build():
            prog = static.Program()
            prog.random_seed = 0
            with static.program_guard(prog):
                x = static.data("x", [2, 4, 3, 3], "float32")
                bn = nn.BatchNorm2D(4)
                h = bn(x)
                h = nn.functional.dropout(h, p=0.5, training=True)
                paddle.mean(h)
            return prog

        a = static.apply_pass(
            build(), ["delete_dropout_op_pass", "remove_stat_update_pass"])
        b = static.apply_pass(
            build(), ["remove_stat_update_pass", "delete_dropout_op_pass"])
        assert a.op_names() == b.op_names()
        assert a._buffer_updates == {} and b._buffer_updates == {}
        assert "batch_norm_stat_update" not in a.op_names()
        assert analysis.verify(a) == [] and analysis.verify(b) == []

    def test_pass_output_independent_compile_cache(self):
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [2, 4], "float32")
            y = nn.functional.dropout(x, p=0.5, training=True)
        exe = static.Executor()
        feed = {"x": np.ones((2, 4), np.float32)}
        exe.run(prog, feed=feed, fetch_list=[y])
        n_compiled = len(prog._compiled)
        assert n_compiled >= 1
        out = static.apply_pass(prog, "delete_dropout_op_pass")
        assert out._compiled == {}  # rewritten clone never reuses stale exe
        (got,) = exe.run(out, feed=feed, fetch_list=[y])
        np.testing.assert_allclose(np.asarray(got), np.ones((2, 4)))
        assert len(prog._compiled) == n_compiled  # original cache intact


class TestPassKeepsTrainingIdentity:
    def test_apply_pass_program_still_trains(self):
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [2, 4], "float32")
            w = static.create_parameter([4, 1], "float32")
            loss = paddle.mean(paddle.matmul(x, w))
            opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=[w])
            opt.minimize(loss)
        assert prog._optimizer is not None
        out = static.apply_pass(prog, "remove_stat_update_pass")
        # the rewritten clone keeps the training identity...
        assert out._optimizer is prog._optimizer
        assert out._loss_slot == prog._loss_slot
        before = np.asarray(w.numpy()).copy()
        exe = static.Executor()
        exe.run(out, feed={"x": np.ones((2, 4), np.float32)},
                fetch_list=[loss])
        # ...and actually updates parameters when run
        assert not np.allclose(before, np.asarray(w.numpy()))

    def test_prune_away_from_loss_drops_training(self):
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [2, 4], "float32")
            w = static.create_parameter([4, 1], "float32")
            h = paddle.matmul(x, w)
            loss = paddle.mean(h)
            opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=[w])
            opt.minimize(loss)
        # slicing to the loss keeps training; slicing away from it is an
        # inference slice and must not keep a dangling loss slot
        assert static.prune(prog, [loss])._optimizer is not None
        pruned = static.prune(prog, [h])
        assert pruned._optimizer is None and pruned._loss_slot is None
        assert analysis.verify(pruned, targets=[h]) == []
