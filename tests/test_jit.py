"""to_static tests (reference model: dygraph_to_static test suite)."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn, ops

rng = np.random.RandomState(5)


def test_to_static_forward_equivalence():
    m = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    m.eval()
    x = paddle.to_tensor(rng.rand(3, 4).astype("float32"))
    eager = m(x).numpy()
    static_fwd = paddle.jit.to_static(lambda t: m(t))
    np.testing.assert_allclose(static_fwd(x).numpy(), eager, rtol=1e-5)


def test_to_static_training_matches_eager():
    def make():
        paddle.seed(42)
        m = nn.Linear(4, 2)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=m.parameters())
        return m, opt

    x = rng.rand(8, 4).astype("float32")
    y = rng.rand(8, 2).astype("float32")

    # eager
    m1, opt1 = make()
    losses_eager = []
    for _ in range(5):
        loss = nn.functional.mse_loss(m1(paddle.to_tensor(x)),
                                      paddle.to_tensor(y))
        loss.backward()
        opt1.step()
        opt1.clear_grad()
        losses_eager.append(float(loss.numpy()))

    # jitted
    m2, opt2 = make()

    @paddle.jit.to_static
    def step(xb, yb):
        loss = nn.functional.mse_loss(m2(xb), yb)
        loss.backward()
        opt2.step()
        opt2.clear_grad()
        return loss

    losses_jit = [float(step(paddle.to_tensor(x), paddle.to_tensor(y)).numpy())
                  for _ in range(5)]
    np.testing.assert_allclose(losses_eager, losses_jit, rtol=1e-4)
    np.testing.assert_allclose(m1.weight.numpy(), m2.weight.numpy(), rtol=1e-4)


def test_to_static_bn_buffers_update():
    m = nn.BatchNorm1D(3, data_format="NCL")

    @paddle.jit.to_static
    def fwd(x):
        return m(x)

    before = m._mean.numpy().copy()
    fwd(paddle.to_tensor(rng.rand(4, 3, 5).astype("float32") + 2.0))
    after = m._mean.numpy()
    assert not np.allclose(before, after), "running mean must update in jit"


def test_to_static_rng_advances():
    drop = nn.Dropout(0.5)

    @paddle.jit.to_static
    def fwd(x):
        return drop(x)

    x = paddle.to_tensor(np.ones((64, 64), np.float32))
    a = fwd(x).numpy()
    b = fwd(x).numpy()
    assert not np.allclose(a, b), "dropout mask must differ across jit calls"


def test_to_static_recompiles_on_shape_change():
    m = nn.Linear(4, 2)
    fwd = paddle.jit.to_static(lambda t: m(t))
    out1 = fwd(paddle.to_tensor(rng.rand(2, 4).astype("float32")))
    out2 = fwd(paddle.to_tensor(rng.rand(6, 4).astype("float32")))
    assert out1.shape == [2, 2] and out2.shape == [6, 2]
    assert len(fwd._cache) == 2


def test_to_static_adam_scaler_pipeline():
    m = nn.Linear(8, 8)
    opt = paddle.optimizer.AdamW(parameters=m.parameters(), learning_rate=1e-2)
    scaler = paddle.amp.GradScaler(enable=False)

    @paddle.jit.to_static
    def step(x):
        with paddle.amp.auto_cast(enable=True, dtype="bfloat16"):
            out = m(x)
            loss = out.square().mean()
        scaler.scale(loss).backward()
        scaler.step(opt)
        opt.clear_grad()
        return loss

    x = paddle.to_tensor(rng.rand(4, 8).astype("float32"))
    l0 = float(step(x).numpy())
    for _ in range(5):
        l1 = float(step(x).numpy())
    assert l1 < l0


def test_jit_save_load(tmp_path):
    m = nn.Sequential(nn.Linear(4, 4), nn.Tanh(), nn.Linear(4, 2))
    m.eval()
    x = paddle.to_tensor(rng.rand(2, 4).astype("float32"))
    ref = m(x).numpy()
    path = str(tmp_path / "model")
    paddle.jit.save(m, path)
    loaded = paddle.jit.load(path)
    np.testing.assert_allclose(loaded(x).numpy(), ref, rtol=1e-6)


def test_paddle_save_load(tmp_path):
    m = nn.Linear(3, 3)
    path = str(tmp_path / "m.pdparams")
    paddle.save(m.state_dict(), path)
    sd = paddle.load(path)
    m2 = nn.Linear(3, 3)
    m2.set_state_dict(sd)
    np.testing.assert_allclose(m.weight.numpy(), m2.weight.numpy())


def test_lr_scheduler_no_retrace():
    m = nn.Linear(2, 2)
    sched = paddle.optimizer.lr.StepDecay(learning_rate=0.1, step_size=1,
                                          gamma=0.5)
    opt = paddle.optimizer.SGD(learning_rate=sched, parameters=m.parameters())

    @paddle.jit.to_static
    def step(x):
        loss = m(x).sum()
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    x = paddle.to_tensor(np.ones((2, 2), np.float32))
    step(x)
    w_after_1 = m.weight.numpy().copy()
    sched.step()  # lr 0.1 -> 0.05
    assert abs(opt.get_lr() - 0.05) < 1e-7
    step(x)
    assert len(step._cache) == 1, "lr change must not retrace"
