"""to_static tests (reference model: dygraph_to_static test suite)."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn, ops

rng = np.random.RandomState(5)


def test_to_static_forward_equivalence():
    m = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    m.eval()
    x = paddle.to_tensor(rng.rand(3, 4).astype("float32"))
    eager = m(x).numpy()
    static_fwd = paddle.jit.to_static(lambda t: m(t))
    np.testing.assert_allclose(static_fwd(x).numpy(), eager, rtol=1e-5)


def test_to_static_training_matches_eager():
    def make():
        paddle.seed(42)
        m = nn.Linear(4, 2)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=m.parameters())
        return m, opt

    x = rng.rand(8, 4).astype("float32")
    y = rng.rand(8, 2).astype("float32")

    # eager
    m1, opt1 = make()
    losses_eager = []
    for _ in range(5):
        loss = nn.functional.mse_loss(m1(paddle.to_tensor(x)),
                                      paddle.to_tensor(y))
        loss.backward()
        opt1.step()
        opt1.clear_grad()
        losses_eager.append(float(loss.numpy()))

    # jitted
    m2, opt2 = make()

    @paddle.jit.to_static
    def step(xb, yb):
        loss = nn.functional.mse_loss(m2(xb), yb)
        loss.backward()
        opt2.step()
        opt2.clear_grad()
        return loss

    losses_jit = [float(step(paddle.to_tensor(x), paddle.to_tensor(y)).numpy())
                  for _ in range(5)]
    np.testing.assert_allclose(losses_eager, losses_jit, rtol=1e-4)
    np.testing.assert_allclose(m1.weight.numpy(), m2.weight.numpy(), rtol=1e-4)


def test_to_static_bn_buffers_update():
    m = nn.BatchNorm1D(3, data_format="NCL")

    @paddle.jit.to_static
    def fwd(x):
        return m(x)

    before = m._mean.numpy().copy()
    fwd(paddle.to_tensor(rng.rand(4, 3, 5).astype("float32") + 2.0))
    after = m._mean.numpy()
    assert not np.allclose(before, after), "running mean must update in jit"


def test_to_static_rng_advances():
    drop = nn.Dropout(0.5)

    @paddle.jit.to_static
    def fwd(x):
        return drop(x)

    x = paddle.to_tensor(np.ones((64, 64), np.float32))
    a = fwd(x).numpy()
    b = fwd(x).numpy()
    assert not np.allclose(a, b), "dropout mask must differ across jit calls"


def test_to_static_recompiles_on_shape_change():
    m = nn.Linear(4, 2)
    fwd = paddle.jit.to_static(lambda t: m(t))
    out1 = fwd(paddle.to_tensor(rng.rand(2, 4).astype("float32")))
    out2 = fwd(paddle.to_tensor(rng.rand(6, 4).astype("float32")))
    assert out1.shape == [2, 2] and out2.shape == [6, 2]
    assert len(fwd._cache) == 2


def test_to_static_adam_scaler_pipeline():
    m = nn.Linear(8, 8)
    opt = paddle.optimizer.AdamW(parameters=m.parameters(), learning_rate=1e-2)
    scaler = paddle.amp.GradScaler(enable=False)

    @paddle.jit.to_static
    def step(x):
        with paddle.amp.auto_cast(enable=True, dtype="bfloat16"):
            out = m(x)
            loss = out.square().mean()
        scaler.scale(loss).backward()
        scaler.step(opt)
        opt.clear_grad()
        return loss

    x = paddle.to_tensor(rng.rand(4, 8).astype("float32"))
    l0 = float(step(x).numpy())
    for _ in range(5):
        l1 = float(step(x).numpy())
    assert l1 < l0


def test_jit_save_load(tmp_path):
    m = nn.Sequential(nn.Linear(4, 4), nn.Tanh(), nn.Linear(4, 2))
    m.eval()
    x = paddle.to_tensor(rng.rand(2, 4).astype("float32"))
    ref = m(x).numpy()
    path = str(tmp_path / "model")
    paddle.jit.save(m, path)
    loaded = paddle.jit.load(path)
    np.testing.assert_allclose(loaded(x).numpy(), ref, rtol=1e-6)


def test_paddle_save_load(tmp_path):
    m = nn.Linear(3, 3)
    path = str(tmp_path / "m.pdparams")
    paddle.save(m.state_dict(), path)
    sd = paddle.load(path)
    m2 = nn.Linear(3, 3)
    m2.set_state_dict(sd)
    np.testing.assert_allclose(m.weight.numpy(), m2.weight.numpy())


def test_lr_scheduler_no_retrace():
    m = nn.Linear(2, 2)
    sched = paddle.optimizer.lr.StepDecay(learning_rate=0.1, step_size=1,
                                          gamma=0.5)
    opt = paddle.optimizer.SGD(learning_rate=sched, parameters=m.parameters())

    @paddle.jit.to_static
    def step(x):
        loss = m(x).sum()
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    x = paddle.to_tensor(np.ones((2, 2), np.float32))
    step(x)
    w_after_1 = m.weight.numpy().copy()
    sched.step()  # lr 0.1 -> 0.05
    assert abs(opt.get_lr() - 0.05) < 1e-7
    step(x)
    assert len(step._cache) == 1, "lr change must not retrace"


def test_to_static_selective_state_threading():
    """Grad-only programs must not donate/copy read-only params, must skip
    untouched state entirely, and must never donate grads they only read."""
    lin1 = nn.Linear(4, 4)
    lin2 = nn.Linear(4, 4)
    unused = nn.Linear(8, 8)  # registered state the program never touches

    x = paddle.to_tensor(np.ones((2, 4), np.float32))
    loss1 = lin1(x).sum()
    loss1.backward()  # lin1 now has accumulated grads
    g_before = lin1.weight.grad.numpy().copy()

    @paddle.jit.to_static
    def fn(inp):
        # reads lin1's accumulated grad (grad-norm logging style) while
        # training lin2 — lin1's grads are read-only, lin2's are written
        gn = (lin1.weight.grad * lin1.weight.grad).sum()
        out = (lin2(inp).sum() + 0.0 * gn)
        out.backward()
        return out

    fn(x)
    part = fn._last_partition
    uid = {id(t): u for u, t in
           __import__("paddle_tpu.core.state", fromlist=["x"]).snapshot()}
    # lin1.weight's VALUE is never read (only its grad) -> skipped
    assert uid[id(lin1.weight)] in part["skipped"]
    # params read but not written -> readonly, not donated
    assert uid[id(lin2.weight)] in part["readonly"]
    assert not part["donated"] or uid[id(lin2.weight)] not in part["donated"]
    # untouched layer skipped entirely
    assert uid[id(unused.weight)] in part["skipped"]
    assert uid[id(unused.bias)] in part["skipped"]
    # lin1's read-only grad must not be donated...
    assert uid[id(lin1.weight)] in part["readonly_grads"]
    assert uid[id(lin1.weight)] not in part["donated_grads"]
    # ...and its buffer survives, unchanged, after the call
    np.testing.assert_allclose(lin1.weight.grad.numpy(), g_before)
    # lin2 got real grads out of the compiled program
    assert lin2.weight.grad is not None
    # second call reuses the cache and still works
    fn(x)
    np.testing.assert_allclose(lin1.weight.grad.numpy(), g_before)


def test_to_static_passthrough_sync_not_frozen():
    """EMA/target-network sync: a.set_value(b) creates no jaxpr eqn; b must
    still be a runtime input, not a build-time constant."""
    a = nn.Linear(3, 3)
    b = nn.Linear(3, 3)

    @paddle.jit.to_static
    def sync():
        a.weight.set_value(b.weight)
        a.bias.set_value(b.bias)

    sync()
    np.testing.assert_allclose(a.weight.numpy(), b.weight.numpy())
    # update source eagerly; the cached program must see the new value
    b.weight.set_value(np.full((3, 3), 7.0, np.float32))
    sync()
    assert len(sync._cache) == 1
    np.testing.assert_allclose(a.weight.numpy(), np.full((3, 3), 7.0))


def test_spectral_norm_power_iteration_live_under_to_static():
    paddle.seed(0)
    sn = nn.SpectralNorm([4, 5], dim=0, power_iters=1)
    w = paddle.to_tensor(np.random.RandomState(0).randn(4, 5).astype("float32"))
    u0 = sn.weight_u.numpy().copy()

    @paddle.jit.to_static
    def fwd(x):
        return sn(x)

    fwd(w)
    u1 = sn.weight_u.numpy().copy()
    assert not np.allclose(u0, u1), "power iteration frozen under to_static"
    fwd(w)
    u2 = sn.weight_u.numpy().copy()
    # converges towards the leading singular vector: keeps moving, bounded
    assert np.isfinite(u2).all() and abs(np.linalg.norm(u2) - 1.0) < 1e-3


def test_to_static_rejects_traced_attr_stash():
    """A traced Tensor stashed on a plain Layer attribute must raise at
    assignment (it would be a dead tracer after compilation); a registered
    buffer threads through instead (regression: the MoE aux-loss leak)."""
    import pytest

    class Stasher(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 4)

        def forward(self, x):
            y = self.fc(x)
            self.diag = y.mean()  # plain attribute: must be rejected
            return y

    m = Stasher()
    x = paddle.to_tensor(rng.rand(2, 4).astype("float32"))
    step = paddle.jit.to_static(lambda t: m(t).sum())
    with pytest.raises(RuntimeError, match="register_buffer"):
        step(x)

    class Buffered(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 4)
            self.register_buffer("diag", paddle.zeros([1]),
                                 persistable=False)

        def forward(self, x):
            y = self.fc(x)
            self.diag = y.mean().reshape([1])
            return y

    m2 = Buffered()
    step2 = paddle.jit.to_static(lambda t: m2(t).sum())
    step2(x)
    step2(x)
    got = float(m2.diag.numpy()[0])
    want = float(m2.fc(x).mean().numpy())
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_traced_layer_trace_replay_and_bare_tensor():
    import pytest

    m = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    m.eval()
    x = paddle.to_tensor(rng.rand(3, 4).astype("float32"))
    eager = m(x).numpy()
    outs, traced = paddle.jit.TracedLayer.trace(m, inputs=[x])
    np.testing.assert_allclose(outs.numpy(), eager, rtol=1e-5)
    np.testing.assert_allclose(traced([x]).numpy(), eager, rtol=1e-5)
    # a bare Tensor input is ONE argument (reference jit.py:1198 accepts
    # Tensor|list|tuple) — without normalization list(Tensor) would
    # iterate it row-wise and trace a 3-input forward
    outs2, traced2 = paddle.jit.TracedLayer.trace(m, inputs=x)
    np.testing.assert_allclose(outs2.numpy(), eager, rtol=1e-5)
    np.testing.assert_allclose(traced2([x]).numpy(), eager, rtol=1e-5)
    with pytest.raises(TypeError):
        paddle.jit.TracedLayer.trace(lambda t: t, x)


def test_traced_layer_save_inference_model_batch_polymorphic(tmp_path):
    m = nn.Sequential(nn.Linear(4, 4), nn.Tanh(), nn.Linear(4, 2))
    m.eval()
    x = paddle.to_tensor(rng.rand(3, 4).astype("float32"))
    _, traced = paddle.jit.TracedLayer.trace(m, inputs=x)
    path = str(tmp_path / "traced")
    traced.save_inference_model(path)
    served = paddle.jit.load(path)
    # feed specs carry a symbolic batch axis: the artifact serves batch
    # sizes the trace never saw, not just the trace-time 3
    for b in (1, 3, 5):
        xb = paddle.to_tensor(rng.rand(b, 4).astype("float32"))
        np.testing.assert_allclose(served(xb).numpy(), m(xb).numpy(),
                                   rtol=1e-5, atol=1e-6)


def test_traced_layer_save_inference_model_partial_feed(tmp_path):
    """A partial feed freezes the non-fed inputs at their trace-time
    values, so the export must fall back to concrete (trace-batch) feed
    specs — a symbolic batch axis interacting with the frozen concrete
    batch would fail the export trace."""
    class TwoIn(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 4)

        def forward(self, a, b):
            return self.fc(a) + b

    m = TwoIn()
    m.eval()
    a = paddle.to_tensor(rng.rand(3, 4).astype("float32"))
    b = paddle.to_tensor(rng.rand(3, 4).astype("float32"))
    _, traced = paddle.jit.TracedLayer.trace(m, inputs=[a, b])
    path = str(tmp_path / "partial")
    traced.save_inference_model(path, feed=[0])
    served = paddle.jit.load(path)
    a2 = paddle.to_tensor(rng.rand(3, 4).astype("float32"))
    np.testing.assert_allclose(served(a2).numpy(), m(a2, b).numpy(),
                               rtol=1e-5, atol=1e-6)
