"""Unified observability layer tests: spans, counters, exporters,
StepTimer, hot-path instrumentation (executor / jit cache / dataloader /
collectives / PS RPC), and the perf-regression gate."""
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.observability as obs
from paddle_tpu import _native, monitor, profiler
from paddle_tpu.io import DataLoader
from paddle_tpu.io.dataset import TensorDataset
from paddle_tpu.observability import export as export_mod
from paddle_tpu.observability import gate as gate_mod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def tracing():
    """Clean tracing session: fresh event buffer + gauges, always
    disabled afterwards (observability state is process-global)."""
    profiler.reset()
    export_mod.clear_gauges()
    obs.enable()
    try:
        yield obs
    finally:
        obs.disable()
        profiler.reset()
        export_mod.clear_gauges()


def _trace_names(tmp_path, name="trace.json"):
    p = str(tmp_path / name)
    obs.export_chrome_trace(p)
    with open(p) as f:
        return [e["name"] for e in json.load(f)["traceEvents"]]


def _reset(*counters):
    for c in counters:
        monitor.stat_reset(c)


# -- span API --------------------------------------------------------------

def test_span_nesting_records_and_exports(tracing, tmp_path):
    with obs.trace_span("outer", cat="user", k=1) as outer:
        assert obs.current_span() is outer
        with obs.trace_span("inner", cat="user") as inner:
            assert obs.current_span() is inner
        assert obs.current_span() is outer
    assert obs.current_span() is None
    names = _trace_names(tmp_path)
    assert "outer" in names and "inner" in names


def test_disabled_tracing_is_guard_only(tmp_path):
    obs.disable()
    profiler.reset()
    # no allocation, no recording: the shared null span comes back and
    # the event buffer stays empty
    s = obs.trace_span("never", cat="user")
    assert s is obs.tracing.NULL_SPAN
    with s:
        pass
    monitor.stat_reset("never_counter")
    obs.count("never_counter")
    assert monitor.stat_get("never_counter") == 0
    assert obs.export_chrome_trace(str(tmp_path / "t.json")) == 0


def test_category_toggle_and_unknown_category(tmp_path):
    profiler.reset()
    obs.enable(categories=["executor"])
    try:
        assert obs.enabled("executor")
        assert not obs.enabled("dataloader")
        assert obs.trace_span("x", cat="dataloader") is obs.tracing.NULL_SPAN
        assert obs.trace_span("y", cat="executor") is not obs.tracing.NULL_SPAN
    finally:
        obs.disable()
    with pytest.raises(ValueError):
        obs.enable(categories=["nonsense"])
    obs.disable()


# -- hot-path instrumentation ---------------------------------------------

def test_jit_cache_counters_and_compile_span(tracing, tmp_path):
    _reset("jit_cache_hit", "jit_cache_miss", "jit_compile_ns")
    f = paddle.jit.to_static(lambda x: x * 3.0)
    x = paddle.to_tensor(np.ones((2, 2), np.float32))
    f(x)
    assert monitor.stat_get("jit_cache_miss") == 1
    assert monitor.stat_get("jit_cache_hit") == 0
    assert monitor.stat_get("jit_compile_ns") > 0
    f(x)
    assert monitor.stat_get("jit_cache_hit") == 1
    # shape change -> second miss
    f(paddle.to_tensor(np.ones((3, 2), np.float32)))
    assert monitor.stat_get("jit_cache_miss") == 2
    names = _trace_names(tmp_path)
    assert "jit/compile" in names
    assert "executor/step" in names


def test_jax_backend_compile_hook_counts(tracing):
    _reset("jit_backend_compile_ns", "jit_backend_compiles")
    f = paddle.jit.to_static(lambda x: x + 7.0)
    f(paddle.to_tensor(np.ones((4,), np.float32)))
    assert monitor.stat_get("jit_backend_compiles") >= 1
    assert monitor.stat_get("jit_backend_compile_ns") > 0


def test_executor_run_spans_and_compile_counters(tracing, tmp_path):
    _reset("executor_compile_miss", "executor_compile_hit",
           "executor_runs", "program_record_ops")
    main = paddle.static.Program()
    with paddle.static.program_guard(main):
        x = paddle.static.data("x", [2, 3])
        y = paddle.ops.scale(x, 2.0)
    assert monitor.stat_get("program_record_ops") >= 1
    exe = paddle.static.Executor()
    feed = {"x": np.ones((2, 3), np.float32)}
    out1 = exe.run(main, feed=feed, fetch_list=[y])
    out2 = exe.run(main, feed=feed, fetch_list=[y])
    np.testing.assert_allclose(out1[0], np.full((2, 3), 2.0))
    np.testing.assert_allclose(out1[0], out2[0])
    assert monitor.stat_get("executor_runs") == 2
    assert monitor.stat_get("executor_compile_miss") == 1
    assert monitor.stat_get("executor_compile_hit") == 1
    names = _trace_names(tmp_path)
    assert "executor/run" in names
    assert "executor/compile" in names


def test_dataloader_counters_sync_and_prefetch(tracing, tmp_path):
    _reset("dataloader_batches", "dataloader_wait_ns",
           "dataloader_worker_batch_ns")
    ds = TensorDataset([np.arange(8, dtype=np.float32).reshape(8, 1),
                        np.arange(8, dtype=np.int64)])
    n = sum(1 for _ in DataLoader(ds, batch_size=2))
    assert n == 4
    assert monitor.stat_get("dataloader_batches") == 4
    assert monitor.stat_get("dataloader_wait_ns") > 0
    # threaded prefetch path (shared memory off -> _PrefetchIter)
    n = sum(1 for _ in DataLoader(ds, batch_size=2, num_workers=1,
                                  use_shared_memory=False))
    assert n == 4
    assert monitor.stat_get("dataloader_batches") == 8
    assert monitor.stat_get("dataloader_worker_batch_ns") > 0
    names = _trace_names(tmp_path)
    assert "dataloader/batch" in names
    assert "dataloader/wait" in names


def test_collective_counters(tracing):
    import paddle_tpu.distributed as dist
    _reset("collective_all_reduce_calls", "collective_all_reduce_bytes",
           "collective_all_reduce_ns", "collective_broadcast_calls")
    t = paddle.to_tensor(np.ones((8,), np.float32))
    dist.all_reduce(t)
    dist.all_reduce(t)
    dist.broadcast(t, src=0)
    assert monitor.stat_get("collective_all_reduce_calls") == 2
    assert monitor.stat_get("collective_all_reduce_bytes") == 2 * 32
    assert monitor.stat_get("collective_all_reduce_ns") > 0
    assert monitor.stat_get("collective_broadcast_calls") == 1


@pytest.mark.skipif(_native.lib() is None, reason="needs native runtime")
def test_ps_rpc_counters(tracing, tmp_path):
    from paddle_tpu.distributed.ps import PsClient, PsServer, TableConfig
    _reset("ps_client_calls", "ps_client_bytes_out", "ps_client_bytes_in",
           "ps_client_rtt_ns", "ps_client_pull_sparse_calls")
    srv = PsServer([TableConfig(700, "sparse", 4, "sgd", lr=0.1,
                                init_range=0.1, seed=7)], port=0)
    port = srv.start()
    cli = PsClient([f"127.0.0.1:{port}"])
    cli.register_sparse(700, 4)
    try:
        rows = cli.pull_sparse(700, np.array([1, 2, 3], np.uint64))
        assert rows.shape == (3, 4)
        cli.push_sparse_grad(700, np.array([1, 2, 3], np.uint64),
                             np.ones((3, 4), np.float32))
    finally:
        cli.stop_servers()
        srv.stop()
    assert monitor.stat_get("ps_client_pull_sparse_calls") == 1
    assert monitor.stat_get("ps_client_calls") >= 2  # pull + push (+stop)
    assert monitor.stat_get("ps_client_bytes_out") > 0
    assert monitor.stat_get("ps_client_bytes_in") > 0
    assert monitor.stat_get("ps_client_rtt_ns") > 0
    assert "ps/pull_sparse" in _trace_names(tmp_path)


def test_sampled_dispatch_observer(tracing, tmp_path):
    obs.disable()
    profiler.reset()
    monitor.stat_reset("dispatch_sampled_ops")
    obs.enable(categories=["dispatch"], dispatch_sample_rate=1.0)
    try:
        x = paddle.to_tensor(np.ones((2,), np.float32))
        for _ in range(3):
            x = x + x
    finally:
        obs.disable()
    assert monitor.stat_get("dispatch_sampled_ops") >= 3
    assert any(n.startswith("op/") for n in _trace_names(tmp_path))


def test_reenable_without_dispatch_removes_sampler(tmp_path):
    profiler.reset()
    monitor.stat_reset("dispatch_sampled_ops")
    obs.enable(categories=["dispatch"], dispatch_sample_rate=1.0)
    obs.enable()  # default categories: dispatch must be torn down
    try:
        x = paddle.to_tensor(np.ones((2,), np.float32))
        x = x + x
    finally:
        obs.disable()
    assert monitor.stat_get("dispatch_sampled_ops") == 0
    assert not any(n.startswith("op/") for n in _trace_names(tmp_path))


def test_event_buffer_cap_drops_not_grows(tracing, tmp_path):
    old_max = profiler._MAX_EVENTS
    profiler.reset()
    profiler._MAX_EVENTS = 5
    try:
        for i in range(8):
            with obs.trace_span(f"s{i}", cat="user"):
                pass
        # 5 admitted (native or fallback buffer), 3 counted as dropped
        assert profiler.export_chrome_tracing(str(tmp_path / "c.json")) == 5
        assert profiler.dropped_events() == 3
        profiler.reset()  # reset clears the cap accounting too
        assert profiler.dropped_events() == 0
    finally:
        profiler._MAX_EVENTS = old_max
        profiler.reset()


# -- step telemetry --------------------------------------------------------

def test_step_timer_window_rates(tracing):
    _reset("dataloader_wait_ns", "jit_compile_ns", "executor_compile_ns",
           "jit_backend_compile_ns")
    timer = obs.StepTimer(window=4, publish_as="ttest").start()
    assert timer.step(tokens=100, examples=10) is not None or True
    for _ in range(3):
        monitor.stat_add("dataloader_wait_ns", 2_000_000)  # 2ms fake wait
        time.sleep(0.01)
        t = timer.step(tokens=100, examples=10)
    assert t["window_steps"] >= 3
    assert t["tokens_per_s"] > 0
    assert t["examples_per_s"] > 0
    assert 0 < t["data_wait_frac"] <= 1
    assert t["step_time_ms"] > 0
    # published onto the gauge board for the scraper
    g = export_mod.gauges()
    assert g["ttest_tokens_per_s"] > 0


def test_step_timer_mfu_estimate():
    timer = obs.StepTimer(window=2, flops_per_step=1e9, peak_flops=1e12)
    t = timer.step()
    assert t is None  # first step() without start() only anchors the window
    time.sleep(0.005)
    t = timer.step()
    assert "mfu" in t and t["mfu"] > 0


def test_step_timer_flops_per_token_override():
    """The per-model flops_per_token override drives MFU from the
    window's actual token throughput and beats the flops_per_step
    estimate when both are given."""
    timer = obs.StepTimer(window=4, flops_per_step=1e20,  # would be absurd
                          flops_per_token=1e6, peak_flops=1e12,
                          publish_as=None).start()
    time.sleep(0.005)
    t = timer.step(tokens=1000)
    # achieved = 1e6 * 1000 / dt; dt >= 5ms -> mfu <= 0.2, far below the
    # absurd flops_per_step estimate (which would exceed 1e4)
    assert 0 < t["mfu"] < 1.0
    # without token counts the override cannot apply; falls back
    timer2 = obs.StepTimer(window=2, flops_per_token=1e6,
                           flops_per_step=1e7, peak_flops=1e12,
                           publish_as=None).start()
    time.sleep(0.002)
    t2 = timer2.step()
    assert t2["mfu"] > 0  # flops_per_step fallback path


# -- exporters -------------------------------------------------------------

def test_prometheus_and_json_exporters(tracing, tmp_path):
    monitor.stat_reset("obs_test_counter")
    monitor.stat_add("obs_test_counter", 5)
    export_mod.publish("obs_test", {"rate": 1.5, "skipme": None})
    text = export_mod.prometheus_text()
    assert "# TYPE paddle_tpu_obs_test_counter counter" in text
    assert "paddle_tpu_obs_test_counter 5" in text
    assert "paddle_tpu_obs_test_rate 1.5" in text
    assert "skipme" not in text
    data = export_mod.write_json(str(tmp_path / "t.json"))
    assert data["counters"]["obs_test_counter"] == 5
    assert data["gauges"]["obs_test_rate"] == 1.5
    on_disk = json.load(open(tmp_path / "t.json"))
    assert on_disk["counters"]["obs_test_counter"] == 5


def test_metrics_http_server(tracing):
    from urllib.request import urlopen
    monitor.stat_reset("obs_http_counter")
    monitor.stat_add("obs_http_counter", 3)
    server = export_mod.start_http_server(port=0)
    try:
        body = urlopen(
            f"http://127.0.0.1:{server.port}/metrics", timeout=10).read()
        assert b"paddle_tpu_obs_http_counter 3" in body
        tele = json.loads(urlopen(
            f"http://127.0.0.1:{server.port}/telemetry.json",
            timeout=10).read())
        assert tele["counters"]["obs_http_counter"] == 3
    finally:
        server.stop()


@pytest.mark.skipif(not _native.AVAILABLE, reason="native runtime not built")
def test_ps_server_per_table_op_latency_export():
    """The native PS server's per-(table, op) service-side latencies show
    up as labeled counters in both exporters, per table."""
    from paddle_tpu.distributed.ps import PsClient, PsServer, TableConfig

    srv = PsServer([TableConfig(41, "sparse", 4, "sgd", lr=0.1,
                                init_range=0.1, seed=1),
                    TableConfig(42, "sparse", 4, "sgd", lr=0.1,
                                init_range=0.1, seed=1)], port=0)
    port = srv.start()
    cli = PsClient([f"127.0.0.1:{port}"])
    try:
        cli.register_sparse(41, 4)
        cli.register_sparse(42, 4)
        keys = np.arange(20, dtype=np.uint64)
        for table in (41, 42):
            rows = cli.pull_sparse(table, keys)
            cli.push_sparse_grad(table, keys, np.ones_like(rows))
        stats = {(r["table"], r["op"]): r for r in srv.stats()}
        for table in (41, 42):
            for op in ("pull_sparse", "push_sparse_grad"):
                r = stats[(table, op)]
                assert r["calls"] >= 1 and r["ns"] > 0
        text = export_mod.prometheus_text()
        assert ('paddle_tpu_ps_server_op_ns{table="41",op="pull_sparse"}'
                in text)
        assert ('paddle_tpu_ps_server_op_calls{table="42",'
                'op="push_sparse_grad"}' in text)
        tele = export_mod.telemetry_dict()
        assert any(k.startswith("ps_server_op_ns") for k in
                   tele["collected"])
    finally:
        cli.stop_servers()
        srv.stop()


def test_collector_errors_do_not_kill_scrape():
    def broken():
        raise RuntimeError("collector exploded")

    export_mod.register_collector("obs_test_broken", broken)
    try:
        text = export_mod.prometheus_text()  # must not raise
        assert "obs_test_broken_collector_errors" in text
    finally:
        export_mod.unregister_collector("obs_test_broken")


# -- perf gate -------------------------------------------------------------

def _rec(metric, value, unit):
    return {"metric": metric, "value": value, "unit": unit}


def test_gate_compare_directions_and_tolerance():
    base = {"a": _rec("a", 100.0, "img/s"), "b": _rec("b", 50.0, "ms")}
    ok, rep = gate_mod.compare(base, {"a": _rec("a", 95.0, "img/s"),
                                      "b": _rec("b", 54.0, "ms")},
                               tolerance=0.10)
    assert ok and all(e["status"] == "OK" for e in rep)
    # throughput drop beyond tolerance fails
    ok, rep = gate_mod.compare(base, {"a": _rec("a", 80.0, "img/s"),
                                      "b": _rec("b", 50.0, "ms")})
    assert not ok
    assert [e for e in rep if e["metric"] == "a"][0]["status"] == "REGRESSION"
    # latency increase beyond tolerance fails
    ok, rep = gate_mod.compare(base, {"a": _rec("a", 100.0, "img/s"),
                                      "b": _rec("b", 70.0, "ms")})
    assert not ok
    # improvements pass
    ok, rep = gate_mod.compare(base, {"a": _rec("a", 150.0, "img/s"),
                                      "b": _rec("b", 30.0, "ms")})
    assert ok


def test_gate_missing_metric_fails_and_new_is_informational():
    base = {"a": _rec("a", 100.0, "img/s")}
    cur = {"b": _rec("b", 1.0, "x")}
    ok, rep = gate_mod.compare(base, cur)
    assert not ok
    statuses = {e["metric"]: e["status"] for e in rep}
    assert statuses["a"] == "MISSING"
    assert statuses["b"] == "NEW"
    # errored current record also fails
    ok, _ = gate_mod.compare(base, {"a": {"metric": "a", "error": "boom"}})
    assert not ok
    # errored baseline entry is skipped, not gated
    ok, rep = gate_mod.compare({"a": {"metric": "a", "error": "boom"}}, cur)
    assert ok
    assert rep[0]["status"] == "SKIP"


def test_gate_backend_mismatch_checks_presence_only():
    """A TPU-pinned baseline gated on a CPU smoke host: values are not
    comparable, so the gate demands metric PRESENCE (a usable record)
    and nothing else."""
    base = {"a": dict(_rec("a", 5000.0, "img/s"), backend="tpu")}
    # wildly lower CPU value still passes — PRESENT, not REGRESSION
    ok, rep = gate_mod.compare(
        base, {"a": dict(_rec("a", 3.0, "img/s"), backend="cpu")})
    assert ok
    assert rep[0]["status"] == "PRESENT"
    # but an errored/absent record still fails: presence means PRESENT
    ok, rep = gate_mod.compare(base, {"a": {"metric": "a", "error": "x"}})
    assert not ok and rep[0]["status"] == "MISSING"
    # same backend -> real value gating
    ok, rep = gate_mod.compare(
        base, {"a": dict(_rec("a", 3.0, "img/s"), backend="tpu")})
    assert not ok and rep[0]["status"] == "REGRESSION"


def test_gate_presence_pin_skips_value_compare():
    base = {"n": dict(_rec("n", 3.0, "x"), backend="cpu",
                      gate="presence")}
    cur = {"n": dict(_rec("n", 0.5, "x"), backend="cpu")}
    ok, rep = gate_mod.compare(base, cur)  # 6x "regression" — ignored
    assert ok and rep[0]["status"] == "PRESENT"
    assert "PRESENT" in gate_mod.format_report(rep)


def test_write_baseline_drops_errored_records(tmp_path, capsys):
    recs = [_rec("good", 1.0, "x"), {"metric": "bad", "error": "boom"}]
    p = str(tmp_path / "base.json")
    n = gate_mod.write_baseline(recs, p)
    assert n == 1
    assert set(gate_mod.load_results(p)) == {"good"}
    assert "bad" in capsys.readouterr().err  # dropped LOUDLY, not silently


def test_gate_load_results_formats(tmp_path):
    recs = [_rec("m1", 1.0, "x"), _rec("m2", 2.0, "ms")]
    p1 = tmp_path / "obj.json"
    p1.write_text(json.dumps({"results": recs}))
    p2 = tmp_path / "arr.json"
    p2.write_text(json.dumps(recs))
    p3 = tmp_path / "lines.json"
    p3.write_text("\n".join(json.dumps(r) for r in recs))
    for p in (p1, p2, p3):
        loaded = gate_mod.load_results(str(p))
        assert set(loaded) == {"m1", "m2"}


def test_run_all_gate_exits_nonzero_on_regression(tmp_path):
    """Acceptance: `benchmarks/run_all.py --gate` exits non-zero against a
    synthetically regressed baseline (current results fed from a file so
    no benches run)."""
    cur = [_rec("resnet50_train_img_per_s_per_chip", 100.0, "img/s")]
    good = [_rec("resnet50_train_img_per_s_per_chip", 95.0, "img/s")]
    bad = [_rec("resnet50_train_img_per_s_per_chip", 200.0, "img/s")]
    (tmp_path / "cur.json").write_text(json.dumps({"results": cur}))
    (tmp_path / "good.json").write_text(json.dumps({"results": good}))
    (tmp_path / "bad.json").write_text(json.dumps({"results": bad}))
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}

    def run(baseline):
        return subprocess.run(
            [sys.executable, os.path.join(REPO, "benchmarks", "run_all.py"),
             "--results", str(tmp_path / "cur.json"), "--gate",
             str(tmp_path / baseline)],
            capture_output=True, text=True, cwd=REPO, timeout=300, env=env)

    r = run("bad.json")
    assert r.returncode == 2, r.stdout + r.stderr
    assert "REGRESSION" in r.stdout
    r = run("good.json")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "PERF GATE: PASS" in r.stdout


def test_perf_gate_tool_roundtrip(tmp_path):
    cur = [_rec("m", 10.0, "tokens/s")]
    (tmp_path / "cur.json").write_text(json.dumps({"results": cur}))
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    # pin a baseline from the current file, then gate against it: PASS
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "perf_gate.py"),
         "--current", str(tmp_path / "cur.json"),
         "--write-baseline", str(tmp_path / "base.json")],
        capture_output=True, text=True, cwd=REPO, timeout=300, env=env)
    assert r.returncode == 0, r.stdout + r.stderr
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "perf_gate.py"),
         "--baseline", str(tmp_path / "base.json"),
         "--current", str(tmp_path / "cur.json")],
        capture_output=True, text=True, cwd=REPO, timeout=300, env=env)
    assert r.returncode == 0, r.stdout + r.stderr


# -- end-to-end acceptance -------------------------------------------------

def test_fit_three_steps_exports_trace_and_telemetry(tracing, tmp_path):
    """Acceptance: a 3-step hapi.Model.fit with tracing on exports a
    chrome trace holding executor step spans, dataloader spans, and a
    compile-cache event; the Prometheus exporter carries the step
    telemetry (tokens/s, data-wait fraction)."""
    _reset("jit_cache_miss", "dataloader_wait_ns")
    paddle.seed(0)
    xs = np.random.RandomState(0).rand(6, 4).astype(np.float32)
    ys = np.random.RandomState(1).randint(0, 3, (6, 1)).astype(np.int64)
    ds = TensorDataset([xs, ys])
    model = paddle.Model(nn.Linear(4, 3))
    model.prepare(
        optimizer=paddle.optimizer.SGD(learning_rate=0.1,
                                       parameters=model.parameters()),
        loss=nn.CrossEntropyLoss())
    telem = paddle.hapi.callbacks.TelemetryCallback(
        tokens_per_batch=8, examples_per_batch=2, window=4, export_freq=1,
        prom_path=str(tmp_path / "metrics.prom"),
        json_path=str(tmp_path / "telemetry.json"))
    model.fit(ds, batch_size=2, epochs=1, verbose=0, shuffle=False,
              callbacks=[telem])

    names = _trace_names(tmp_path)
    assert "executor/step" in names, names  # compiled train-step runs
    assert any(n.startswith("dataloader/") for n in names), names
    assert any(n in ("jit/compile", "jax/backend_compile")
               for n in names), names  # >=1 compile-cache event
    assert "hapi/train_batch" in names

    # 3 steps -> telemetry window has data; exporter text carries it
    t = telem.last_telemetry
    assert t is not None and t["window_steps"] >= 2
    assert t["tokens_per_s"] > 0
    assert "data_wait_frac" in t
    prom = (tmp_path / "metrics.prom").read_text()
    assert "paddle_tpu_step_tokens_per_s" in prom
    assert "paddle_tpu_step_data_wait_frac" in prom
    tele = json.loads((tmp_path / "telemetry.json").read_text())
    assert tele["gauges"]["step_tokens_per_s"] > 0
    # the run's own counters made it into the same scrape payload
    assert tele["counters"].get("jit_cache_miss", 0) >= 1
