"""Native runtime (C++ libpaddle_tpu_rt) + profiler/flags/monitor fronts.

Mirrors the reference's platform-layer tests (profiler_test.cc,
monitor coverage, nan_inf checks via FLAGS_check_nan_inf).
"""
import json
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import _native, monitor, profiler


def test_native_library_builds():
    # the toolchain is baked into the image; the native runtime must be real
    assert _native.AVAILABLE, f"native build failed: {_native._build_err}"
    assert _native.lib().pt_runtime_version() == 1


def test_monitor_counters():
    monitor.stat_reset("STAT_test_total")
    monitor.stat_add("STAT_test_total", 5)
    monitor.stat_add("STAT_test_total", 7)
    assert monitor.stat_get("STAT_test_total") == 12
    assert monitor.stats()["STAT_test_total"] == 12
    monitor.stat_reset("STAT_test_total")
    assert monitor.stat_get("STAT_test_total") == 0


def test_flags_roundtrip():
    paddle.set_flags({"FLAGS_paddle_num_threads": 4})
    assert paddle.get_flags(["FLAGS_paddle_num_threads"]) == {
        "FLAGS_paddle_num_threads": 4}
    # unknown-but-set flags round-trip as strings
    paddle.set_flags({"FLAGS_custom_thing": "abc"})
    assert paddle.get_flags("FLAGS_custom_thing")["FLAGS_custom_thing"] == "abc"


def test_profiler_records_ops(tmp_path):
    profiler.reset()
    x = paddle.to_tensor(np.ones((8, 8), np.float32))
    with profiler.profiler():
        y = paddle.matmul(x, x)
        z = paddle.add(y, x)
        _ = z.numpy()
    path = str(tmp_path / "trace.json")
    n = profiler.export_chrome_tracing(path)
    assert n >= 2
    trace = json.load(open(path))
    names = {e["name"] for e in trace["traceEvents"]}
    assert any("matmul" in s for s in names)
    table = profiler.summary()
    assert "matmul" in table
    profiler.reset()


def test_record_event_user_scope(tmp_path):
    profiler.reset()
    profiler.start_profiler()
    with profiler.RecordEvent("my_scope"):
        pass
    profiler.stop_profiler()
    path = str(tmp_path / "t.json")
    profiler.export_chrome_tracing(path)
    names = {e["name"] for e in json.load(open(path))["traceEvents"]}
    assert "my_scope" in names
    profiler.reset()


def test_check_nan_inf_flag():
    paddle.set_flags({"FLAGS_check_nan_inf": 1})
    try:
        x = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
        _ = paddle.add(x, x)  # finite: fine
        bad = paddle.to_tensor(np.array([1.0, np.inf], np.float32))
        with pytest.raises(FloatingPointError, match="divide|add|NaN/Inf"):
            _ = paddle.add(bad, bad)
    finally:
        paddle.set_flags({"FLAGS_check_nan_inf": 0})
    # disabled again: no raise
    bad = paddle.to_tensor(np.array([np.nan], np.float32))
    _ = paddle.add(bad, bad)


def test_nonfinite_scanners_native():
    if not _native.AVAILABLE:
        pytest.skip("no native lib")
    L = _native.lib()
    a32 = np.array([1, np.nan, np.inf, -np.inf, 0], np.float32)
    assert L.pt_count_nonfinite_f32(a32.ctypes.data, a32.size) == 3
    a64 = a32.astype(np.float64)
    assert L.pt_count_nonfinite_f64(a64.ctypes.data, a64.size) == 3
    import jax.numpy as jnp
    b16 = np.asarray(jnp.array(a32, dtype=jnp.bfloat16)).view(np.uint16)
    b16 = np.ascontiguousarray(b16)
    assert L.pt_count_nonfinite_bf16(b16.ctypes.data, b16.size) == 3
    f16 = a32.astype(np.float16).view(np.uint16)
    assert L.pt_count_nonfinite_f16(np.ascontiguousarray(f16).ctypes.data,
                                    f16.size) == 3


def test_shm_ring_roundtrip():
    if not _native.AVAILABLE:
        pytest.skip("no native lib")
    L = _native.lib()
    import ctypes
    name = f"/pt_ring_test_{os.getpid()}".encode()
    r = L.pt_ring_create(name, 1 << 16)
    assert r
    try:
        payload = np.arange(100, dtype=np.float32).tobytes()
        assert L.pt_ring_write(r, payload, len(payload), 1000) == 0
        n = L.pt_ring_next_len(r, 1000)
        assert n == len(payload)
        buf = ctypes.create_string_buffer(n)
        assert L.pt_ring_read(r, buf, n) == n
        out = np.frombuffer(buf.raw, np.float32)
        np.testing.assert_array_equal(out, np.arange(100, dtype=np.float32))
        # close-producer drains to -2
        L.pt_ring_close_producer(r)
        assert L.pt_ring_next_len(r, 100) == -2
    finally:
        L.pt_ring_free(r, 1)


def test_shm_ring_cross_process():
    if not _native.AVAILABLE:
        pytest.skip("no native lib")
    L = _native.lib()
    import ctypes
    name = f"/pt_ring_xp_{os.getpid()}".encode()
    r = L.pt_ring_create(name, 1 << 20)
    pid = os.fork()
    if pid == 0:  # child: producer
        try:
            Lc = _native.lib()
            rc = Lc.pt_ring_open(name)
            for i in range(10):
                msg = np.full(1000, i, np.int64).tobytes()
                Lc.pt_ring_write(rc, msg, len(msg), 5000)
            Lc.pt_ring_close_producer(rc)
            Lc.pt_ring_free(rc, 0)
        finally:
            os._exit(0)
    try:
        got = []
        while True:
            n = L.pt_ring_next_len(r, 5000)
            if n == -2:
                break
            assert n == 8000
            buf = ctypes.create_string_buffer(n)
            L.pt_ring_read(r, buf, n)
            got.append(int(np.frombuffer(buf.raw, np.int64)[0]))
        assert got == list(range(10))
    finally:
        os.waitpid(pid, 0)
        L.pt_ring_free(r, 1)
