"""OpTest harness — the TPU analog of the reference's
`python/paddle/fluid/tests/unittests/op_test.py` (OpTest:270): declarative
op checks against a numpy reference, with numeric-vs-analytic gradient checks
per dtype (bf16 tolerances widened as the reference's white_list does).
"""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor

DEFAULT_TOL = {"float32": 1e-5, "bfloat16": 2e-2, "float16": 1e-2}


def check_output(op_fn, np_fn, inputs, atol=None, rtol=None, dtype="float32",
                 kwargs=None):
    """Run op_fn(Tensors) and np_fn(arrays); compare."""
    kwargs = kwargs or {}
    tol = atol if atol is not None else DEFAULT_TOL[dtype]
    tensors = [Tensor(np.asarray(a, dtype=dtype)) for a in inputs]
    out = op_fn(*tensors, **kwargs)
    ref = np_fn(*[np.asarray(a, dtype=dtype) for a in inputs], **kwargs)
    outs = out if isinstance(out, (tuple, list)) else [out]
    refs = ref if isinstance(ref, (tuple, list)) else [ref]
    for o, r in zip(outs, refs):
        np.testing.assert_allclose(
            np.asarray(o.numpy(), np.float64), np.asarray(r, np.float64),
            atol=tol, rtol=rtol or tol)


def check_grad(op_fn, inputs, grad_index=0, eps=1e-3, atol=2e-2,
               kwargs=None, reduce_to_scalar=True):
    """Numeric gradient (central differences) vs tape gradient, mirroring
    OpTest.check_grad_with_place → _get_gradient."""
    kwargs = kwargs or {}
    arrays = [np.asarray(a, dtype="float64").astype("float32") for a in inputs]

    def scalar_loss(arrs):
        tensors = [Tensor(a) for a in arrs]
        for t in tensors:
            t.stop_gradient = False
        out = op_fn(*tensors, **kwargs)
        if isinstance(out, (tuple, list)):
            out = out[0]
        return out.sum() if reduce_to_scalar else out, tensors

    # analytic
    loss, tensors = scalar_loss(arrays)
    for t in tensors:
        t._retain_grads = True
    loss.backward()
    analytic = tensors[grad_index].grad.numpy().astype("float64")

    # numeric
    target = arrays[grad_index]
    numeric = np.zeros_like(target, dtype="float64")
    flat = target.reshape(-1)
    num_flat = numeric.reshape(-1)
    for i in range(flat.size):
        orig = flat[i]
        flat[i] = orig + eps
        lp, _ = scalar_loss(arrays)
        lp = float(lp.numpy())
        flat[i] = orig - eps
        lm, _ = scalar_loss(arrays)
        lm = float(lm.numpy())
        flat[i] = orig
        num_flat[i] = (lp - lm) / (2 * eps)

    np.testing.assert_allclose(analytic, numeric, atol=atol, rtol=atol)
