"""Async pipelined HBM embedding cache (ISSUE 9): the prefetch pipeline
(CachePrefetcher/WindowPlan), the bounded background write-back queue
(coalescing, backpressure, chaos kill + exactly-once restart), the
telemetry-driven adaptive eviction watermark, and the CTR acceptance —
cached scan-window training bitwise-equal with prefetch on/off and at
loss parity with the uncached per-batch PS path.
"""
import json
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import monitor, nn
from paddle_tpu.distributed import ps
from paddle_tpu.distributed.ps import (CachePrefetcher, HbmEmbeddingCache,
                                       PsClient, PsServer, TableConfig,
                                       WriteBackQueue)
from paddle_tpu.distributed.ps.communicator import SyncCommunicator
from paddle_tpu.distributed.ps.embedding import (deterministic_init,
                                                 flush_sparse_grads,
                                                 reset_registry)
from paddle_tpu.models.ctr import (WideAndDeep, synthetic_ctr_batches,
                                   train_ctr_windows)
from paddle_tpu.testing import faults

DIM = 4


def _start_server(tables):
    srv = PsServer(tables, port=0)
    port = srv.start()
    cli = PsClient([f"127.0.0.1:{port}"])
    return srv, cli


def _sparse_setup(capacity, table_id=1000, lr=0.1, writeback=None):
    srv, cli = _start_server(
        [TableConfig(table_id, "sparse", DIM, "sgd", lr=lr,
                     init_range=0.1, seed=table_id)])
    cli.register_sparse(table_id, DIM)
    cache = HbmEmbeddingCache(cli, table_id, DIM, capacity,
                              optimizer="sgd", lr=lr, writeback=writeback)
    return srv, cli, cache


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


class _FakeClient:
    """Recording PsClient stand-in for WriteBackQueue unit tests; an
    optional gate blocks push_sparse_delta so producers can observe
    backpressure deterministically."""

    def __init__(self, gate=None, fail_times=0):
        self.pushes = []          # (table, keys, deltas) as pushed
        self.gate = gate
        self.fail_times = fail_times
        self._mu = threading.Lock()

    def push_sparse_delta(self, table, keys, deltas):
        if self.gate is not None:
            self.gate.wait()
        with self._mu:
            if self.fail_times > 0:
                self.fail_times -= 1
                raise ConnectionError("injected push failure")
            self.pushes.append((table, np.array(keys, copy=True),
                                np.array(deltas, copy=True)))


class TestWriteBackQueue:
    def test_coalesces_duplicate_keys_by_summation(self):
        gate = threading.Event()
        cli = _FakeClient(gate=gate)
        wb = WriteBackQueue(cli, range_bits=32)
        try:
            # wedge the worker on a sacrificial batch so the two real
            # batches are guaranteed to be taken TOGETHER (coalesced)
            wb.put(9, [0], np.zeros((1, DIM), np.float32))
            deadline = time.monotonic() + 10
            while not wb._inflight and time.monotonic() < deadline:
                time.sleep(0.005)
            wb.put(7, [1, 2], np.ones((2, DIM), np.float32))
            wb.put(7, [2, 3], 2 * np.ones((2, DIM), np.float32))
            gate.set()
            wb.flush()
        finally:
            gate.set()
            wb.stop(flush=False)
        merged = {}
        for t, keys, deltas in cli.pushes:
            if t != 7:
                continue
            for k, d in zip(keys.tolist(), deltas):
                # exactly-once per key across every wire push
                assert k not in merged
                merged[k] = d
        np.testing.assert_array_equal(merged[1], np.ones(DIM))
        np.testing.assert_array_equal(merged[2], 3 * np.ones(DIM))
        np.testing.assert_array_equal(merged[3], 2 * np.ones(DIM))
        assert wb.pushed_rows == 5 and wb.coalesced_rows == 1

    def test_key_range_split_and_row_cap(self):
        cli = _FakeClient()
        # range_bits=2 -> ranges of 4 keys; cap 3 rows per wire push
        wb = WriteBackQueue(cli, range_bits=2, max_rows_per_push=3)
        try:
            keys = np.array([0, 1, 2, 3, 4, 5, 100], np.uint64)
            wb.put(1, keys, np.ones((keys.size, DIM), np.float32))
            wb.flush()
        finally:
            wb.stop(flush=False)
        for _t, k, _d in cli.pushes:
            assert k.size <= 3
            assert np.unique(k >> np.uint64(2)).size == 1  # one range each
        got = np.sort(np.concatenate([k for _t, k, _d in cli.pushes]))
        np.testing.assert_array_equal(got, keys)

    def test_backpressure_blocks_put_at_high_watermark(self):
        gate = threading.Event()
        cli = _FakeClient(gate=gate)
        wb = WriteBackQueue(cli, max_pending_rows=8)
        monitor.stat_reset("hbm_writeback_backpressure")
        try:
            wb.put(1, np.arange(8, dtype=np.uint64),
                   np.ones((8, DIM), np.float32))
            # worker is now wedged in push (gate closed); the next put
            # would exceed the watermark -> must BLOCK, not grow memory
            done = threading.Event()

            def producer():
                wb.put(1, np.arange(8, 12, dtype=np.uint64),
                       np.ones((4, DIM), np.float32))
                done.set()

            th = threading.Thread(target=producer, daemon=True)
            th.start()
            assert not done.wait(timeout=1.0), \
                "put returned while the queue sat at its watermark"
            assert monitor.stat_get("hbm_writeback_backpressure") >= 1
            assert wb.pending_rows <= 12  # enqueued + in-flight, bounded
            gate.set()
            assert done.wait(timeout=10.0)
            wb.flush()
        finally:
            gate.set()
            wb.stop(flush=False)
        assert wb.pushed_rows == 12

    @pytest.mark.filterwarnings(
        "ignore::pytest.PytestUnhandledThreadExceptionWarning")
    def test_worker_death_requeues_then_restart_pushes_once(self):
        cli = _FakeClient(fail_times=1)
        wb = WriteBackQueue(cli)
        try:
            wb.put(1, [5], np.ones((1, DIM), np.float32))
            deadline = time.monotonic() + 10
            while wb._error is None and time.monotonic() < deadline:
                time.sleep(0.01)
            assert wb._error is not None
            # nothing lost: the batch is requeued, put/flush surface it
            assert wb.pending_rows == 1
            with pytest.raises(RuntimeError, match="restart"):
                wb.put(1, [6], np.ones((1, DIM), np.float32))
            with pytest.raises(RuntimeError, match="restart"):
                wb.flush()
            wb.restart()
            wb.flush()
        finally:
            wb.stop(flush=False)
        assert len(cli.pushes) == 1 and wb.pushed_rows == 1

    def test_put_after_stop_raises_and_restart_revives(self):
        cli = _FakeClient()
        wb = WriteBackQueue(cli)
        wb.stop()
        # no worker will drain a stopped queue — enqueueing silently
        # would strand the deltas until flush() times out
        with pytest.raises(RuntimeError, match="stopped"):
            wb.put(1, [1], np.ones((1, DIM), np.float32))
        wb.restart()  # clears the stop flag too, not just errors
        try:
            wb.put(1, [1], np.ones((1, DIM), np.float32))
            wb.flush()
        finally:
            wb.stop(flush=False)
        assert wb.pushed_rows == 1 and len(cli.pushes) == 1

    def test_has_pending_is_read_your_writes_signal(self):
        gate = threading.Event()
        cli = _FakeClient(gate=gate)
        wb = WriteBackQueue(cli)
        try:
            wb.put(3, [10, 11], np.ones((2, DIM), np.float32))
            assert wb.has_pending(3, [11])
            assert not wb.has_pending(3, [12])
            assert not wb.has_pending(4, [11])  # other table
            gate.set()
            wb.flush()
            assert not wb.has_pending(3, [11])
        finally:
            gate.set()
            wb.stop(flush=False)


class TestWriteBackChaos:
    """ISSUE 9 satellite: a kill inside the write-back thread must leave
    a flight-recorder dump, lose no delta, and — thanks to the PR-7
    request-id dedup — apply each delta exactly once after restart."""

    @pytest.fixture(autouse=True)
    def _flight(self, tmp_path):
        import paddle_tpu.observability as obs
        from paddle_tpu import profiler
        from paddle_tpu.observability import flight
        profiler.reset()
        flight.clear()
        obs.enable()
        flight.install(str(tmp_path / "flight"))
        yield flight
        obs.disable()
        flight.uninstall()
        flight.clear()
        profiler.reset()

    @pytest.mark.chaos
    @pytest.mark.filterwarnings(
        "ignore::pytest.PytestUnhandledThreadExceptionWarning")
    def test_kill_dumps_and_restart_applies_exactly_once(self, _flight):
        srv, cli, cache = _sparse_setup(capacity=16)
        wb = WriteBackQueue(cli)
        try:
            keys = np.array([2, 4], np.uint64)
            before = cli.pull_sparse(1000, keys)
            faults.inject("ps/writeback", times=1)
            delta = np.full((2, DIM), 0.5, np.float32)
            wb.put(1000, keys, delta)
            deadline = time.monotonic() + 10
            while wb._error is None and time.monotonic() < deadline:
                time.sleep(0.01)
            assert isinstance(wb._error, faults.FaultInjected)
            wb._thread.join(timeout=10)  # let the excepthook dump land
            # no delta reached the wire, none was dropped
            assert wb.pending_rows == 2
            np.testing.assert_array_equal(
                cli.pull_sparse(1000, keys), before)
            # the armed flight recorder dumped TWICE: at the kill site
            # (before the exception unwound) and again when the worker
            # thread died with it unhandled
            import os
            d = os.path.dirname(_flight.latest_dump())
            recs = [json.load(open(os.path.join(d, f)))
                    for f in sorted(os.listdir(d)) if f.endswith(".json")]
            kp = [r for r in recs if r["reason"] == "kill_point"]
            assert kp and kp[-1]["kill_point"] == "ps/writeback"
            assert kp[-1]["spans"][-1]["name"] == "fault/ps/writeback"
            assert kp[-1]["faults"]["fired"]["ps/writeback"] == 1
            th = [r for r in recs
                  if r["reason"] == "unhandled_thread_exception"]
            assert th and th[-1]["exception"]["type"] == "FaultInjected"
            assert th[-1]["thread"] == "hbm-cache-writeback"
            # restart: the requeued batch pushes; exactly one apply
            wb.restart()
            wb.flush()
            np.testing.assert_allclose(
                cli.pull_sparse(1000, keys), before + 0.5,
                rtol=1e-6, atol=1e-7)
        finally:
            wb.stop(flush=False)
            cli.stop_servers()
            srv.stop()


class TestPrefetcher:
    def test_plans_in_order_while_consumer_computes(self):
        srv, cli, cache = _sparse_setup(capacity=64)
        pf = CachePrefetcher(cache, depth=2, bucket=8)
        try:
            wins = [np.arange(i * 8, i * 8 + 8, dtype=np.int64)
                    .reshape(2, 4) for i in range(3)]
            for w in wins:
                pf.submit(w)
            mirror = deterministic_init(
                1000, np.arange(64, dtype=np.uint64), DIM, 0.1)
            for w in wins:
                plan = pf.take()
                slots_t, inv_t = plan.feeds()
                slots = np.asarray(slots_t.numpy())   # [k, W]
                inv = np.asarray(inv_t.numpy())       # [k, 2, 4] -> flat
                tbl = np.asarray(cache.table)
                got = np.stack(
                    [tbl[slots[i]][inv[i].reshape(-1)].reshape(4, DIM)
                     for i in range(2)])
                np.testing.assert_allclose(got, mirror[w], rtol=1e-5,
                                           atol=1e-7)
                cache.drain_window(plan)
            assert pf.windows == 3 and pf.pull_s > 0.0
            assert 0.0 <= pf.overlap_efficiency() <= 1.0
        finally:
            pf.close()
            cli.stop_servers()
            srv.stop()

    def test_planner_error_surfaces_on_take_then_submit(self):
        # window working set (9 uniques) larger than capacity-1 rows
        srv, cli, cache = _sparse_setup(capacity=8)
        pf = CachePrefetcher(cache, depth=1)
        try:
            pf.submit(np.arange(9, dtype=np.int64).reshape(1, 9))
            with pytest.raises(RuntimeError, match="prefetcher failed"):
                pf.take(timeout=10)
            with pytest.raises(RuntimeError, match="prefetcher failed"):
                pf.submit(np.zeros((1, 1), np.int64))
        finally:
            cli.stop_servers()
            srv.stop()

    def test_close_releases_unconsumed_plans_and_blocked_worker(self):
        # the consumer abandons the pipeline with the worker BLOCKED on
        # the full depth-bounded output queue; close() must drain it so
        # the join can't stall, and every unconsumed plan's eviction
        # pins must drop with it
        srv, cli, cache = _sparse_setup(capacity=64)
        pf = CachePrefetcher(cache, depth=1)
        try:
            for i in range(3):
                pf.submit(np.arange(i * 4, i * 4 + 4, dtype=np.int64)
                          .reshape(1, 4))
            # wait for the worker to finish plan 1 -> it is now blocked
            # putting it (plan 0 already fills the depth-1 queue)
            deadline = time.monotonic() + 10
            while pf.windows < 2 and time.monotonic() < deadline:
                time.sleep(0.01)
            assert pf.windows >= 2
            t0 = time.monotonic()
            pf.close()
            assert time.monotonic() - t0 < 15, \
                "close() sat out the join timeout on a blocked worker"
            assert not pf._thread.is_alive()
            assert not cache._plan_pins
            with pytest.raises(RuntimeError, match="closed"):
                pf.take(timeout=1)
        finally:
            cli.stop_servers()
            srv.stop()

    def test_window_pins_block_eviction_until_release(self):
        # capacity 9 = scratch + 8 rows; a planned-but-unconsumed window
        # owns 4 of them and must survive later faulting pressure
        srv, cli, cache = _sparse_setup(capacity=9)
        try:
            plan = cache.plan_window(
                np.array([[1, 2, 3, 4]], np.int64), bucket=4)
            out = cache.lookup(paddle.to_tensor(
                np.array([[10, 11, 12, 13]], np.int64)))
            del out
            # the four new keys evicted nothing pinned
            assert {1, 2, 3, 4} <= set(cache._slots)
            # demanding 5 more slots than the unpinned pool can yield
            # fails LOUDLY instead of stealing the planned window's rows
            with pytest.raises(RuntimeError, match="pinned"):
                cache.lookup(paddle.to_tensor(
                    np.array([[20, 21, 22, 23, 24]], np.int64)))
            # the FAILED eviction left every candidate resident
            assert {1, 2, 3, 4, 10, 11, 12, 13} <= set(cache._slots)
            plan.release()
            # released pins free exactly the planned window's 4 rows
            # (10..13 still hold un-applied grads and stay protected)
            out = cache.lookup(paddle.to_tensor(
                np.array([[20, 21, 22, 23]], np.int64)))
            assert cache.stats["evict"] >= 4
            assert not ({1, 2, 3, 4} & set(cache._slots))
            assert {10, 11, 12, 13} <= set(cache._slots)
        finally:
            cli.stop_servers()
            srv.stop()


class TestDeferredEvictResurrection:
    """A dirty key deferred-evicted by one plan and re-planned before
    the flush must NOT be re-pulled from the PS (the server has not
    seen its delta yet): its still-intact device rows relocate to the
    new slot and the un-pushed delta rides along (read-your-writes on
    the planner path, where WriteBackQueue.has_pending can't see the
    parked delta)."""

    def test_replanned_dirty_key_keeps_local_training(self):
        # capacity 7 = scratch + 6 usable rows
        srv, cli, cache = _sparse_setup(capacity=7)
        try:
            # train keys 1, 2 -> two dirty resident rows (delta -0.1)
            out = cache.lookup(paddle.to_tensor(
                np.array([[1, 2]], np.int64)))
            paddle.ops.sum(out).backward()
            cache.apply_grads()
            # plan1: 5 misses onto 4 free slots -> the planner defers
            # the eviction of dirty key 1 (LRU front); its old slot is
            # handed straight to one of plan1's pending installs
            plan1 = cache.plan_window(
                np.array([[3, 4, 5, 6, 7]], np.int64), bucket=8)
            assert 1 not in cache._slots and cache._pending_evict
            # plan2 re-plans key 1 BEFORE any flush: resurrection. Its
            # new slot comes from deferred-evicting dirty key 2 — the
            # copy's destination is another deferred victim's freed
            # slot, so the flush MUST order deltas -> copies -> installs
            plan2 = cache.plan_window(np.array([[1]], np.int64),
                                      bucket=2)
            assert 1 in cache._slots and cache._pending_copy
            plan2.feeds()  # one flush applies all three stages
            assert not cache._pending_copy
            assert not cache._pending_install_slots
            mirror = deterministic_init(
                1000, np.arange(8, dtype=np.uint64), DIM, 0.1)
            # key 1's row is its TRAINED value, not the stale server
            # value a re-pull would have installed
            s1 = cache._slots[1]
            np.testing.assert_allclose(np.asarray(cache.table)[s1],
                                       mirror[1] - 0.1, rtol=1e-5)
            # key 2's delta went out with the flush (sync path) ...
            np.testing.assert_allclose(
                cli.pull_sparse(1000, np.array([2], np.uint64))[0],
                mirror[2] - 0.1, rtol=1e-5)
            # ... and key 1 is STILL dirty: end_pass pushes its delta
            # exactly once — server equals device afterwards
            cache.end_pass()
            np.testing.assert_allclose(
                cli.pull_sparse(1000, np.array([1], np.uint64))[0],
                mirror[1] - 0.1, rtol=1e-5)
            plan1.release()
            plan2.release()
        finally:
            cli.stop_servers()
            srv.stop()


class TestAdaptiveWatermark:
    def test_free_target_tracks_latency_and_miss_pressure(self):
        srv, cli, cache = _sparse_setup(capacity=100)
        try:
            cache.watermark_min_frac, cache.watermark_max_frac = 0.0, 0.2
            # no history yet -> lazy floor
            assert cache.free_target() == 0
            # cheap loopback pulls -> stay lazy even under misses
            cache._pull_ms_ema = 0.05
            cache._hit_ema, cache._miss_ema = 50.0, 50.0
            assert cache.free_target() == 0
            # expensive pulls + real miss pressure -> evict ahead, hard
            cache._pull_ms_ema = 50.0
            assert cache.free_target() == 20
            # expensive pulls but the working set fits (no misses) ->
            # nothing to prepare for
            cache._hit_ema, cache._miss_ema = 100.0, 0.0
            assert cache.free_target() == 0
            # mid latency, mid pressure -> between the bounds
            cache._pull_ms_ema = 1.0
            cache._hit_ema, cache._miss_ema = 90.0, 10.0
            assert 0 < cache.free_target() <= 20
        finally:
            cli.stop_servers()
            srv.stop()

    def test_evict_ahead_frees_dirty_rows_through_writeback(self):
        monitor.stat_reset("hbm_cache_evict")
        srv, cli, _ = _sparse_setup(capacity=17)
        wb = WriteBackQueue(cli)
        cache = HbmEmbeddingCache(cli, 1000, DIM, 17, optimizer="sgd",
                                  lr=0.1, writeback=wb,
                                  watermark=(0.0, 0.5))
        try:
            ids = np.arange(16, dtype=np.int64).reshape(1, 16)
            out = cache.lookup(paddle.to_tensor(ids))
            paddle.ops.sum(out).backward()
            cache.apply_grads()  # 16 dirty resident rows, 0 free
            assert len(cache._free) == 0
            # simulate an expensive PS under miss pressure
            cache._pull_ms_ema = 50.0
            cache._hit_ema, cache._miss_ema = 50.0, 50.0
            target = cache.free_target()
            assert target == 8  # 0.5 * 17 rounded down
            freed = cache.evict_ahead()
            assert freed == 8 and len(cache._free) >= target
            # victims' trained deltas went through the background queue
            wb.flush()
            mirror = deterministic_init(
                1000, np.arange(16, dtype=np.uint64), DIM, 0.1)
            evicted = [k for k in range(16) if k not in cache._slots]
            assert len(evicted) == 8
            got = cli.pull_sparse(1000, np.asarray(evicted, np.uint64))
            np.testing.assert_allclose(got, mirror[evicted] - 0.1,
                                       rtol=1e-5)
            # lazy regime: a cheap PS stops the ahead-of-time eviction
            cache._pull_ms_ema = 0.01
            assert cache.evict_ahead() == 0
        finally:
            wb.stop(flush=False)
            cli.stop_servers()
            srv.stop()


class TestCtrPipelineParity:
    """ISSUE 9 acceptance: cached CTR training at loss parity with the
    uncached PS path — bitwise with prefetch disabled, ≤1e-6 final-loss
    delta with the async pipeline on."""

    K, NB, BATCH, SLOTS, VOCAB, EDIM = 4, 16, 64, 4, 2000, 8

    def _setup(self, cached, writeback=None):
        reset_registry()
        paddle.seed(0)
        tables = [TableConfig(1000, "sparse", self.EDIM, "sgd", lr=0.05,
                              init_range=0.05, seed=1000),
                  TableConfig(1001, "sparse", 1, "sgd", lr=0.05,
                              init_range=0.05, seed=1001)]
        srv, cli = _start_server(tables)
        model = WideAndDeep(self.VOCAB, dim=self.EDIM, slots=self.SLOTS,
                            hidden=(16,), cached=cached, capacity=1 << 10,
                            optimizer="sgd", lr=0.05, writeback=writeback)
        comm = SyncCommunicator(cli, n_workers=1)
        ps.bind_model(model, comm)
        opt = paddle.optimizer.Adam(parameters=model.parameters(),
                                    learning_rate=0.001)
        batches = synthetic_ctr_batches(self.NB, batch_size=self.BATCH,
                                        slots=self.SLOTS,
                                        vocab=self.VOCAB, seed=3)
        return srv, cli, model, comm, opt, batches

    def _run_cached(self, prefetch, use_writeback=True):
        srv, cli, model, comm, opt, batches = self._setup(True)
        wb = WriteBackQueue(cli) if use_writeback else None
        if wb is not None:
            for c in model.caches():
                c.writeback = wb
        try:
            r = train_ctr_windows(model, opt, batches, k=self.K,
                                  prefetch=prefetch, flush=True)
            return np.asarray(r["losses"]), r
        finally:
            if wb is not None:
                wb.stop(flush=False)
            cli.stop_servers()
            srv.stop()

    def _run_uncached_window(self):
        """The uncached PS baseline with the SAME window structure the
        scan pipeline trains under: per-batch pulls read the server rows
        as of the last window boundary, per-step sparse grads defer and
        push once per window (sgd is linear — the deferred sum IS the
        sequential result), dense params step eagerly."""
        srv, cli, model, comm, opt, batches = self._setup(False)
        try:
            losses = []
            for w in range(self.NB // self.K):
                for i in range(self.K):
                    ids, label = batches[w * self.K + i]
                    logit = model(paddle.to_tensor(ids))
                    loss = nn.functional.binary_cross_entropy_with_logits(
                        logit, paddle.to_tensor(label))
                    loss.backward()
                    flush_sparse_grads(comm)
                    opt.step()
                    opt.clear_grad()
                    losses.append(float(loss.numpy()))
                for table_id, keys, grads in comm._sparse_push:
                    cli.push_sparse_grad(table_id, keys, grads)
                comm._sparse_push.clear()
            return np.asarray(losses)
        finally:
            cli.stop_servers()
            srv.stop()

    def test_prefetch_on_equals_off_bitwise_and_learns(self):
        on, r_on = self._run_cached(prefetch=True)
        off, r_off = self._run_cached(prefetch=False)
        np.testing.assert_array_equal(on, off)
        assert np.mean(on[-self.K:]) < np.mean(on[:self.K])
        assert r_off["overlap_efficiency"] == 0.0
        assert 0.0 <= r_on["overlap_efficiency"] <= 1.0

    @pytest.mark.slow  # ~18 s (PR 11 budget); cached-vs-uncached parity
    def test_cached_pipeline_matches_uncached_ps_path(self):
        # stays tier-1 at smaller scale via
        # test_prefetch_on_equals_off_bitwise_and_learns above
        cached, _ = self._run_cached(prefetch=True)
        uncached = self._run_uncached_window()
        assert abs(cached[-1] - uncached[-1]) <= 1e-6
        np.testing.assert_allclose(cached, uncached, atol=1e-6)

    def test_scan_step_program_verifies_clean(self):
        """The compiled CTR window program passes the analysis verifier
        (tentpole contract: scan-integrated cache lookups are legal,
        shape-stable, hazard-free programs)."""
        from paddle_tpu import analysis
        from paddle_tpu.models.ctr import build_ctr_scan_step

        srv, cli, model, comm, opt, batches = self._setup(True)
        try:
            step = build_ctr_scan_step(model, opt, self.K)
            r = train_ctr_windows(model, opt, batches[:2 * self.K],
                                  k=self.K, prefetch=False, step=step)
            assert len(r["losses"]) == 2 * self.K
            assert analysis.errors(step.verify()) == []
        finally:
            cli.stop_servers()
            srv.stop()
