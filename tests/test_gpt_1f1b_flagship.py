"""Config-4 flagship: the real GPT model through the fused dp x pp 1F1B
pipeline, loss+grad parity against the model's own eager tape path.
"""
import numpy as np
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.models.gpt import (GPTConfig, GPTForCausalLM,
                                   build_gpt_1f1b_step)


def _model():
    paddle.seed(5)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=4, num_heads=2,
                    max_seq_len=16, hidden_dropout=0.0, attention_dropout=0.0)
    m = GPTForCausalLM(cfg)
    m.eval()  # deterministic (no dropout) for parity
    return m


def _batches(M, mb, T, vocab):
    rng = np.random.RandomState(0)
    ids = rng.randint(0, vocab, (M, mb, T)).astype(np.int32)
    return ids


class TestGPT1F1BFlagship:
    @pytest.mark.slow  # ~17 s (PR 11 budget); 1F1B parity stays tier-1
    def test_loss_and_grads_match_eager(self):  # via the dropout-replay
        # parity case below and test_pipeline_1f1b's parity matrix
        m = _model()
        mesh = dist.make_mesh({"pp": 4})
        step, (stacked, first_p, last_p, leaf_names) = build_gpt_1f1b_step(
            m, mesh)
        M, mb, T = 4, 2, 8
        ids = _batches(M, mb, T, m.config.vocab_size)

        loss, (gP, gF, gL) = step(ids, ids)
        loss_pp = float(np.asarray(loss))

        # eager reference: same model, same microbatches, tape autograd
        losses = []
        for i in range(M):
            logits = m(Tensor(ids[i]))
            l = m.loss(logits, Tensor(ids[i])) / M
            l.backward()
            losses.append(float(np.asarray(l._value)) * M)
        loss_ref = float(np.mean(losses))
        np.testing.assert_allclose(loss_pp, loss_ref, rtol=1e-4)

        # block grads: stacked [pp, per, ...] vs per-block tape grads
        per = m.config.num_layers // 4
        qkv_idx = leaf_names.index("qkv.weight")
        for s in range(4):
            for i in range(per):
                blk = m.gpt.blocks[s * per + i]
                np.testing.assert_allclose(
                    np.asarray(gP[qkv_idx][s, i]),
                    np.asarray(blk.qkv.weight._grad), rtol=2e-3, atol=1e-5)

        # tied embedding: first-stage + head contributions
        wte_g = np.asarray(gF[0]) + np.asarray(gL[2])
        np.testing.assert_allclose(wte_g,
                                   np.asarray(m.gpt.wte.weight._grad),
                                   rtol=2e-3, atol=1e-5)

    def test_params_snapshot_tracks_updates(self):
        """step must see updated weights when given a fresh snapshot (the
        build-time snapshot is immutable by design)."""
        m = _model()
        mesh = dist.make_mesh({"pp": 4})
        step, _ = build_gpt_1f1b_step(m, mesh)
        ids = _batches(2, 2, 8, m.config.vocab_size)
        l0 = float(np.asarray(step(ids, ids)[0]))
        # perturb a block weight, re-snapshot
        blk = m.gpt.blocks[1]
        blk.qkv.weight.set_value(np.asarray(blk.qkv.weight.numpy()) * 2.0)
        l_stale = float(np.asarray(step(ids, ids)[0]))
        l_fresh = float(np.asarray(
            step(ids, ids, params=step.snapshot_params())[0]))
        assert l_stale == l0  # stale snapshot: unchanged (documented)
        assert l_fresh != l0  # fresh snapshot sees the update

    def test_train_mode_dropout_deterministic_per_key(self):
        """Train-mode dropout is supported via RNG-key threading (was a
        hard error before round 3): the same rng_key reproduces the same
        loss, a different key draws different masks."""
        import jax
        paddle.seed(5)
        from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=4,
                        num_heads=2, max_seq_len=16, hidden_dropout=0.1,
                        attention_dropout=0.0)
        m = GPTForCausalLM(cfg)  # train mode, dropout>0
        mesh = dist.make_mesh({"pp": 4})
        step, _ = build_gpt_1f1b_step(m, mesh)
        ids = _batches(4, 2, 8, cfg.vocab_size)
        l1 = float(np.asarray(step(ids, ids,
                                   rng_key=jax.random.PRNGKey(1))[0]))
        l2 = float(np.asarray(step(ids, ids,
                                   rng_key=jax.random.PRNGKey(1))[0]))
        l3 = float(np.asarray(step(ids, ids,
                                   rng_key=jax.random.PRNGKey(2))[0]))
        assert l1 == l2
        assert l1 != l3

    def test_hybrid_dp_pp(self):
        m = _model()
        mesh = dist.make_mesh({"dp": 2, "pp": 4})
        step, _ = build_gpt_1f1b_step(m, mesh, axis_dp="dp")
        ids = _batches(4, 2, 8, m.config.vocab_size)
        loss, (gP, gF, gL) = step(ids, ids)
        assert np.isfinite(float(np.asarray(loss)))
        assert np.isfinite(np.asarray(gP[0]).sum())


class TestGPT1F1BDropoutReplay:
    """Train-mode dropout through the fused 1F1B pipeline: the recompute
    backward replays the forward's masks from threaded threefry keys
    (reference semantics: fleet/utils/recompute.py:63 RNG-state replay).
    Parity target: an eager tape run drawing masks with the IDENTICAL
    per-(microbatch, stage, layer) key schedule."""

    def test_train_dropout_loss_and_grad_parity(self):
        import jax
        from paddle_tpu.core import random as core_random

        paddle.seed(5)
        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=4,
                        num_heads=2, max_seq_len=16,
                        hidden_dropout=0.1, attention_dropout=0.1)
        m = GPTForCausalLM(cfg)
        m.train()
        pp, M, mb, T = 4, 4, 2, 8
        per = cfg.num_layers // pp
        mesh = dist.make_mesh({"pp": pp})
        step, (stacked, first_p, last_p, leaf_names) = build_gpt_1f1b_step(
            m, mesh)
        ids = _batches(M, mb, T, cfg.vocab_size)

        base = jax.random.PRNGKey(123)
        loss, (gP, gF, gL) = step(ids, ids, rng_key=base)
        loss_pp = float(np.asarray(loss))

        # eager replica with the pipeline's exact key derivation
        keys = jax.random.split(base, M)
        p = cfg.hidden_dropout
        losses = []
        for i in range(M):
            k0 = jax.random.fold_in(keys[i], 0)
            x = m.gpt.wte(Tensor(ids[i]))
            pos = Tensor(np.arange(T, dtype=np.int32))
            x = x + m.gpt.wpe(pos)
            with core_random.scoped_key(jax.random.fold_in(k0, 997)):
                x = m.gpt.drop(x)  # same impl + key as the pipeline
            h = x
            for s in range(pp):
                ks = jax.random.fold_in(keys[i], s)
                for j in range(per):
                    with core_random.scoped_key(jax.random.fold_in(ks, j)):
                        h = m.gpt.blocks[s * per + j](h)
            norm = m.gpt.ln_f(h)
            import paddle_tpu.ops as _ops
            logits = _ops.matmul(norm, m.gpt.wte.weight, transpose_y=True)
            l = m.loss(logits, Tensor(ids[i])) / M
            l.backward()
            losses.append(float(np.asarray(l._value)) * M)
        loss_ref = float(np.mean(losses))
        np.testing.assert_allclose(loss_pp, loss_ref, rtol=1e-4)

        qkv_idx = leaf_names.index("qkv.weight")
        for s in range(pp):
            for j in range(per):
                blk = m.gpt.blocks[s * per + j]
                np.testing.assert_allclose(
                    np.asarray(gP[qkv_idx][s, j]),
                    np.asarray(blk.qkv.weight._grad), rtol=2e-3, atol=1e-5)
        # tied embedding grad: first (lookup scatter) + last (head matmul)
        tied = np.asarray(gF[0]) + np.asarray(gL[2])
        np.testing.assert_allclose(tied, np.asarray(m.gpt.wte.weight._grad),
                                   rtol=2e-3, atol=1e-5)
