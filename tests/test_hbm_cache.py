"""HBM-resident embedding cache + PsTpuTrainer tests (reference:
`framework/fleet/ps_gpu_wrapper.cc` BuildTask/EndPass semantics,
`framework/trainer.h:250` PSGPUTrainer; test model mirrors the dist_ctr
fixtures of `test_dist_base.py`)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import monitor, nn
from paddle_tpu.distributed import ps
from paddle_tpu.distributed.ps import (CachedSparseEmbedding,
                                       HbmEmbeddingCache, PsClient,
                                       PsServer, PsTpuTrainer, TableConfig)
from paddle_tpu.distributed.ps.communicator import SyncCommunicator
from paddle_tpu.distributed.ps.embedding import (deterministic_init,
                                                 reset_registry)

VOCAB, DIM = 50, 4


def _start_server(tables):
    srv = PsServer(tables, port=0)
    port = srv.start()
    cli = PsClient([f"127.0.0.1:{port}"])
    return srv, cli


def _reset_cache_stats():
    for k in ("hit", "miss", "evict", "staged", "writeback_rows"):
        monitor.stat_reset(f"hbm_cache_{k}")


class TestHbmCacheUnit:
    def _sgd_setup(self, capacity):
        srv, cli = _start_server(
            [TableConfig(1000, "sparse", DIM, "sgd", lr=0.1,
                         init_range=0.1, seed=1000)])
        cli.register_sparse(1000, DIM)
        cache = HbmEmbeddingCache(cli, 1000, DIM, capacity,
                                  optimizer="sgd", lr=0.1)
        return srv, cli, cache

    def test_lookup_update_writeback_matches_numpy(self):
        _reset_cache_stats()
        srv, cli, cache = self._sgd_setup(capacity=16)
        try:
            ids = np.array([[3, 7, 3], [9, 7, 11]], np.int64)
            mirror = deterministic_init(
                1000, np.arange(VOCAB, dtype=np.uint64), DIM, 0.1)
            out = cache.lookup(paddle.to_tensor(ids))
            np.testing.assert_allclose(np.asarray(out.numpy()),
                                       mirror[ids], rtol=1e-5, atol=1e-7)
            # duplicate ids must accumulate into one row update, exactly
            # like the server-side rule on merged pushes
            loss = paddle.ops.sum(out)
            loss.backward()
            cache.apply_grads()
            for k in (3, 7, 9, 11):
                dup = 2 if k in (3, 7) else 1
                np.testing.assert_allclose(
                    np.asarray(cache.table)[cache._slots[k]],
                    mirror[k] - 0.1 * dup, rtol=1e-5)
            # EndPass: server rows must equal device rows afterwards
            cache.end_pass()
            got = cli.pull_sparse(1000, np.array([3, 7, 9, 11], np.uint64))
            for i, k in enumerate((3, 7, 9, 11)):
                np.testing.assert_allclose(
                    got[i], np.asarray(cache.table)[cache._slots[k]],
                    rtol=1e-5, atol=1e-7)
            s = cache.stats
            assert s["miss"] == 4 and s["writeback_rows"] == 4
        finally:
            cli.stop_servers()
            srv.stop()

    def test_lru_eviction_writes_back_and_refaults(self):
        _reset_cache_stats()
        # capacity 5 = scratch + 4 usable rows; touch 6 keys to force
        # eviction of the least recently used
        srv, cli, cache = self._sgd_setup(capacity=5)
        try:
            first = np.array([[1, 2, 3, 4]], np.int64)
            out = cache.lookup(paddle.to_tensor(first))
            paddle.ops.sum(out).backward()
            cache.apply_grads()  # rows 1..4 now dirty
            # keys 5,6 must evict LRU keys 1,2 — their trained deltas go
            # back to the server BEFORE the slots are reused
            out2 = cache.lookup(paddle.to_tensor(np.array([[5, 6]],
                                                          np.int64)))
            assert cache.stats["evict"] == 2
            assert 1 not in cache._slots and 2 not in cache._slots
            mirror = deterministic_init(
                1000, np.arange(VOCAB, dtype=np.uint64), DIM, 0.1)
            got = cli.pull_sparse(1000, np.array([1, 2], np.uint64))
            np.testing.assert_allclose(got, mirror[[1, 2]] - 0.1,
                                       rtol=1e-5)
            # re-faulting an evicted key returns its trained value
            out3 = cache.lookup(paddle.to_tensor(np.array([[1]], np.int64)))
            np.testing.assert_allclose(np.asarray(out3.numpy())[0, 0],
                                       mirror[1] - 0.1, rtol=1e-5)
            del out, out2, out3
        finally:
            cli.stop_servers()
            srv.stop()

    def test_lru_refresh_ordering(self):
        """A lookup hit refreshes the key's recency: the next eviction
        must take the true least-recently-used keys, not the oldest
        inserted ones."""
        _reset_cache_stats()
        srv, cli, cache = self._sgd_setup(capacity=5)
        try:
            out = cache.lookup(paddle.to_tensor(
                np.array([[1, 2, 3, 4]], np.int64)))
            paddle.ops.sum(out).backward()
            cache.apply_grads()
            # touch key 1: inserted first but now most recently used
            out2 = cache.lookup(paddle.to_tensor(np.array([[1]], np.int64)))
            paddle.ops.sum(out2).backward()
            cache.apply_grads()
            # 2 new keys need 2 slots -> victims are 2,3 (LRU front), NOT
            # insertion-ordered 1,2
            out3 = cache.lookup(paddle.to_tensor(np.array([[5, 6]],
                                                          np.int64)))
            assert cache.stats["evict"] == 2
            assert 1 in cache._slots and 4 in cache._slots
            assert 2 not in cache._slots and 3 not in cache._slots
            del out, out2, out3
        finally:
            cli.stop_servers()
            srv.stop()

    def test_pending_slots_never_evicted(self):
        """A second lookup before apply_grads must not reuse slots whose
        gradient is still pending — that would train the new keys with
        the old keys' grads (regression for the eviction/pending race)."""
        srv, cli, cache = self._sgd_setup(capacity=5)
        try:
            out = cache.lookup(paddle.to_tensor(
                np.array([[1, 2, 3, 4]], np.int64)))
            # all 4 resident slots now hold un-applied-grad candidates;
            # a lookup needing eviction must refuse, not corrupt
            with pytest.raises(RuntimeError, match="un-applied"):
                cache.lookup(paddle.to_tensor(np.array([[5, 6]],
                                                       np.int64)))
            del out
        finally:
            cli.stop_servers()
            srv.stop()

    def test_over_capacity_batch_fails_loudly(self):
        srv, cli, cache = self._sgd_setup(capacity=3)
        try:
            with pytest.raises(RuntimeError, match="capacity"):
                cache.lookup(paddle.to_tensor(
                    np.array([[1, 2, 3, 4, 5]], np.int64)))
        finally:
            cli.stop_servers()
            srv.stop()

    def test_adam_cache_matches_server_adam_exactly(self):
        """Device adam (optimizer.cuh.h analog) must track the server's
        adam rule bit-for-bit: push identical grad sequences through both
        paths and compare rows."""
        srv, cli = _start_server(
            [TableConfig(1000, "sparse", DIM, "adam", lr=0.05,
                         init_range=0.1, seed=1000),
             TableConfig(1001, "sparse", DIM, "adam", lr=0.05,
                         init_range=0.1, seed=1000)])
        try:
            cli.register_sparse(1000, DIM)
            cli.register_sparse(1001, DIM)
            cache = HbmEmbeddingCache(cli, 1001, DIM, 16,
                                      optimizer="adam", lr=0.05)
            keys = np.array([2, 5, 9], np.uint64)
            rng = np.random.RandomState(0)
            for _ in range(4):
                g = rng.randn(3, DIM).astype(np.float32)
                cli.push_sparse_grad(1000, keys, g)  # server-side adam
                out = cache.lookup(paddle.to_tensor(
                    keys.astype(np.int64)[None, :]))
                # drive the same grad through the cache's backward path
                loss = paddle.ops.sum(
                    out * paddle.to_tensor(g[None, :, :]))
                loss.backward()
                cache.apply_grads()
            want = cli.pull_sparse(1000, keys)
            slots = [cache._slots[int(k)] for k in keys]
            np.testing.assert_allclose(np.asarray(cache.table)[slots],
                                       want, rtol=1e-5, atol=1e-7)
        finally:
            cli.stop_servers()
            srv.stop()


def _make_ctr(embed_cls, **emb_kw):
    class Ctr(nn.Layer):
        def __init__(self):
            super().__init__()
            self.emb = embed_cls([VOCAB, DIM], init_range=0.1, **emb_kw)
            self.fc = nn.Linear(3 * DIM, 1)

        def forward(self, ids):
            e = self.emb(ids)
            h = paddle.ops.reshape(e, [e.shape[0], 3 * DIM])
            return self.fc(h)

    return Ctr()


def _batches(n, seed=7):
    rng = np.random.RandomState(seed)
    w = np.random.RandomState(1).randn(VOCAB).astype(np.float32)
    out = []
    for _ in range(n):
        ids = rng.randint(0, VOCAB, (16, 3)).astype(np.int64)
        label = (w[ids[:, 0]] > 0).astype(np.float32).reshape(-1, 1)
        out.append((ids, label))
    return out


def _train(model, comm, batches):
    losses = []
    for ids, label in batches:
        logits = model(paddle.to_tensor(ids))
        loss = paddle.nn.functional.binary_cross_entropy_with_logits(
            logits, paddle.to_tensor(label))
        loss.backward()
        from paddle_tpu.distributed.ps.embedding import flush_sparse_grads
        for sub in model.sublayers(include_self=True):
            if isinstance(sub, CachedSparseEmbedding):
                sub.cache.apply_grads()
        flush_sparse_grads(comm)
        comm.step()
        losses.append(float(loss.numpy()))
    return losses


class TestCachedTrainingParity:
    def _run_path(self, cached, mesh=None, steps=30):
        reset_registry()
        paddle.seed(0)
        tables = [TableConfig(1000, "sparse", DIM, "sgd", lr=0.1,
                              init_range=0.1, seed=1000),
                  TableConfig(0, "dense", 0, "sgd", lr=0.1),
                  TableConfig(1, "dense", 0, "sgd", lr=0.1)]
        srv, cli = _start_server(tables)
        try:
            if cached:
                kw = dict(capacity=56, optimizer="sgd", lr=0.1,
                          table_id=1000)
                if mesh is not None:
                    kw.update(mesh=mesh, mesh_axis="mp")
                model = _make_ctr(CachedSparseEmbedding, **kw)
            else:
                model = _make_ctr(ps.SparseEmbedding, table_id=1000)
            comm = SyncCommunicator(cli, n_workers=1)
            ps.bind_model(model, comm)
            comm.init_params()
            losses = _train(model, comm, _batches(steps))
            cli2 = None
            for sub in model.sublayers(include_self=True):
                if isinstance(sub, CachedSparseEmbedding):
                    sub.cache.end_pass()
            return losses
        finally:
            cli.stop_servers()
            srv.stop()

    def test_cached_loss_parity_vs_direct_ps(self):
        """The cache must be a pure perf feature: identical losses to the
        per-batch TCP pull path (single worker, sync, sgd)."""
        direct = self._run_path(cached=False)
        cached = self._run_path(cached=True)
        np.testing.assert_allclose(cached, direct, rtol=2e-4)
        # and it actually learns
        assert np.mean(direct[-5:]) < np.mean(direct[:5])

    def test_cached_parity_on_8dev_mesh(self):
        """Row-sharded cache over the 8-device mesh: same numbers, table
        physically distributed (heter_comm.h inter-card story via XLA)."""
        from paddle_tpu import distributed as dist
        mesh = dist.make_mesh({"mp": 8})
        direct = self._run_path(cached=False)
        cached = self._run_path(cached=True, mesh=mesh)
        np.testing.assert_allclose(cached, direct, rtol=2e-4)


class TestFusedPass:
    """run_fused_pass: a whole staged pass as ONE lax.scan program must
    produce the same numbers as the eager per-batch path."""

    def _mk(self, table_id, optimizer):
        cache_kw = dict(optimizer=optimizer, lr=0.05)
        tables = [TableConfig(table_id, "sparse", DIM, "sgd", lr=0.05,
                              init_range=0.1, seed=1000)]
        srv = PsServer(tables, port=0)
        port = srv.start()
        cli = PsClient([f"127.0.0.1:{port}"])
        cli.register_sparse(table_id, DIM)
        return srv, cli, HbmEmbeddingCache(cli, table_id, DIM, 32,
                                           **cache_kw)

    @pytest.mark.parametrize("optimizer", ["sgd", "adam"])
    def test_fused_matches_eager(self, optimizer):
        import jax.numpy as jnp

        rng = np.random.RandomState(5)
        batches = [rng.randint(0, 20, (4, 3)).astype(np.int64)
                   for _ in range(6)]
        all_keys = np.concatenate([b.ravel() for b in batches])

        srv, cli, cache_e = self._mk(1000, optimizer)
        try:
            cache_f = HbmEmbeddingCache(cli, 1000, DIM, 32,
                                        optimizer=optimizer, lr=0.05)
            cache_e.build_pass(all_keys)
            cache_f.build_pass(all_keys)
            eager_losses = []
            for ids in batches:
                out = cache_e.lookup(paddle.to_tensor(ids))
                loss = paddle.ops.sum(out * out)
                loss.backward()
                cache_e.apply_grads()
                eager_losses.append(float(loss.numpy()))
            fused_losses = cache_f.run_fused_pass(
                batches, lambda e: jnp.sum(e * e))
            np.testing.assert_allclose(fused_losses, eager_losses,
                                       rtol=1e-5)
            # identical final rows too
            for k in np.unique(all_keys):
                np.testing.assert_allclose(
                    np.asarray(cache_f.table)[cache_f._slots[int(k)]],
                    np.asarray(cache_e.table)[cache_e._slots[int(k)]],
                    rtol=1e-5, atol=1e-7)
            # second fused pass reuses the compiled program
            assert len(cache_f._fused_progs) == 1
            cache_f.run_fused_pass(batches, next(iter(
                [k[0] for k in cache_f._fused_progs])))
            assert len(cache_f._fused_progs) == 1
        finally:
            cli.stop_servers()
            srv.stop()

    def test_fused_requires_staging(self):
        srv, cli, cache = self._mk(1000, "sgd")
        try:
            import jax.numpy as jnp
            with pytest.raises(RuntimeError, match="staged"):
                cache.run_fused_pass(
                    [np.array([[1, 2]], np.int64)],
                    lambda e: jnp.sum(e))
        finally:
            cli.stop_servers()
            srv.stop()


class TestPsTpuTrainerPass:
    def test_two_pass_training_with_warm_cache(self):
        _reset_cache_stats()
        reset_registry()
        paddle.seed(0)
        srv, cli = _start_server(
            [TableConfig(1000, "sparse", DIM, "sgd", lr=0.1,
                         init_range=0.1, seed=1000),
             TableConfig(0, "dense", 0, "sgd", lr=0.1),
             TableConfig(1, "dense", 0, "sgd", lr=0.1)])
        try:
            model = _make_ctr(CachedSparseEmbedding, capacity=56,
                              optimizer="sgd", lr=0.1, table_id=1000)
            comm = SyncCommunicator(cli, n_workers=1)
            ps.bind_model(model, comm)
            comm.init_params()

            def loss_fn(m, batch):
                ids, label = batch
                return paddle.nn.functional \
                    .binary_cross_entropy_with_logits(
                        m(paddle.to_tensor(ids)), paddle.to_tensor(label))

            trainer = PsTpuTrainer(model, loss_fn, comm)
            r1 = trainer.train_pass(_batches(10))
            staged_pass1 = cache_stats = trainer.caches[0].stats["staged"]
            assert r1["batches"] == 10
            # pass 2: every row is already resident (warm cache) — the
            # BuildTask stages nothing and lookups are pure hits
            monitor.stat_reset("hbm_cache_miss")
            r2 = trainer.train_pass(_batches(10))
            assert trainer.caches[0].stats["miss"] == 0
            assert trainer.caches[0].stats["hit"] > 0
            # warm-cache pass 2 must continue training from pass 1's
            # trained rows: compare PASS MEANS, not two single-batch
            # endpoint losses — after 20 barely-moving sgd steps the
            # endpoints are dominated by per-batch noise and flip on
            # init numerics (the long-standing tier-1 environment
            # flake); the 10-batch means decrease for every init
            assert np.mean(r2["losses"]) < np.mean(r1["losses"])
            # write-back happened: server sees trained values
            slot_of = trainer.caches[0]._slots
            some_key = next(iter(slot_of))
            got = cli.pull_sparse(1000, np.array([some_key], np.uint64))
            np.testing.assert_allclose(
                got[0],
                np.asarray(trainer.caches[0].table)[slot_of[some_key]],
                rtol=1e-5)
        finally:
            cli.stop_servers()
            srv.stop()
