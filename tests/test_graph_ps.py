"""Graph PS tests (reference: `table/common_graph_table.cc` graph shards
+ sampling, `service/graph_brpc_server.cc` handlers,
`service/graph_py_service.cc` python bring-up, and the
`test_dist_graph_*` fixtures' cluster pattern)."""
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.distributed.ps import (GraphPsClient, PsClient, PsServer,
                                       TableConfig)
from paddle_tpu.distributed.ps.graph import deterministic_sample_indices

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FEAT = 8


def _start(n_feat=FEAT):
    srv = PsServer([TableConfig(7, "graph", n_feat)], port=0)
    port = srv.start()
    cli = PsClient([f"127.0.0.1:{port}"])
    return srv, cli, GraphPsClient(cli, 7, n_feat)


class TestGraphTableUnit:
    def test_nodes_edges_feat_roundtrip(self):
        srv, cli, g = _start()
        try:
            ids = np.arange(10, dtype=np.uint64)
            feats = np.random.RandomState(0).randn(10, FEAT).astype(
                np.float32)
            g.add_nodes(ids, feats)
            g.add_edges([0, 0, 1, 2], [1, 2, 3, 0])
            np.testing.assert_allclose(g.node_feat(ids), feats)
            assert g.node_count() == 10
            # missing node -> zero features, zero neighbors
            got = g.node_feat(np.array([99], np.uint64))
            np.testing.assert_array_equal(got, np.zeros((1, FEAT)))
            _n, _w, cnt = g.sample_neighbors(np.array([99], np.uint64), 3)
            assert cnt[0] == 0
        finally:
            cli.stop_servers()
            srv.stop()

    def test_sampling_matches_python_mirror(self):
        """The server's Fisher–Yates/xorshift sampler must match the
        documented python mirror bit-for-bit (determinism contract)."""
        srv, cli, g = _start()
        try:
            nbrs_of_5 = np.array([10, 11, 12, 13, 14, 15, 16], np.uint64)
            g.add_nodes(np.array([5], np.uint64))
            g.add_edges(np.full(7, 5, np.uint64), nbrs_of_5,
                        np.arange(7, dtype=np.float32))
            for seed in (0, 1, 12345):
                nbrs, w, cnt = g.sample_neighbors(
                    np.array([5], np.uint64), 3, seed=seed)
                want_idx = deterministic_sample_indices(seed, 5, 7, 3)
                np.testing.assert_array_equal(nbrs[0], nbrs_of_5[want_idx])
                np.testing.assert_allclose(
                    w[0], np.arange(7, dtype=np.float32)[want_idx])
                assert cnt[0] == 3
                # repeat call -> identical sample
                nbrs2, _, _ = g.sample_neighbors(
                    np.array([5], np.uint64), 3, seed=seed)
                np.testing.assert_array_equal(nbrs, nbrs2)
            # degree < k returns the whole neighborhood
            nbrs, _, cnt = g.sample_neighbors(np.array([5], np.uint64),
                                              99, seed=3)
            assert cnt[0] == 7
            assert set(nbrs[0, :7].tolist()) == set(nbrs_of_5.tolist())
        finally:
            cli.stop_servers()
            srv.stop()

    def test_pull_list_random_nodes_and_walks(self):
        srv, cli, g = _start()
        try:
            ids = np.arange(20, dtype=np.uint64)
            g.add_nodes(ids)
            # ring graph: i -> i+1
            g.add_edges(ids, (ids + 1) % 20)
            got = g.pull_graph_list(0, 0, 7)
            np.testing.assert_array_equal(got, ids[:7])  # insertion order
            got2 = g.pull_graph_list(0, 15, 99)
            np.testing.assert_array_equal(got2, ids[15:])
            r1 = g.random_sample_nodes(0, 5, seed=9)
            r2 = g.random_sample_nodes(0, 5, seed=9)
            np.testing.assert_array_equal(r1, r2)
            assert len(set(r1.tolist())) == 5
            # ring walk is fully deterministic: i -> i+1 -> i+2 ...
            walks = g.random_walk(np.array([0, 5], np.uint64), 4, seed=1)
            np.testing.assert_array_equal(walks[0], [0, 1, 2, 3, 4])
            np.testing.assert_array_equal(walks[1], [5, 6, 7, 8, 9])
        finally:
            cli.stop_servers()
            srv.stop()

    def test_snapshot_roundtrip_preserves_graph(self, tmp_path):
        """Graph tables ride the same save/load snapshots as the dense/
        sparse tables (the_one_ps save_persistables analog)."""
        snap = str(tmp_path / "graph_snap")
        srv, cli, g = _start()
        try:
            ids = np.arange(12, dtype=np.uint64)
            feats = np.random.RandomState(3).randn(12, FEAT).astype(
                np.float32)
            g.add_nodes(ids, feats)
            g.add_edges(ids, (ids + 3) % 12)
            before = g.sample_neighbors(ids, 2, seed=4)
            cli.save(snap)
        finally:
            cli.stop_servers()
            srv.stop()
        srv2 = PsServer([TableConfig(7, "graph", FEAT)], port=0)
        port2 = srv2.start()
        cli2 = PsClient([f"127.0.0.1:{port2}"])
        g2 = GraphPsClient(cli2, 7, FEAT)
        try:
            cli2.load(snap)
            assert g2.node_count() == 12
            np.testing.assert_allclose(g2.node_feat(ids), feats)
            after = g2.sample_neighbors(ids, 2, seed=4)
            for a, b in zip(before, after):
                np.testing.assert_array_equal(a, b)
        finally:
            cli2.stop_servers()
            srv2.stop()


_GRAPH_SERVER_SCRIPT = """
import sys
import jax; jax.config.update('jax_platforms', 'cpu')
from paddle_tpu.distributed.ps import PsServer, TableConfig
srv = PsServer([TableConfig(7, "graph", %d)], port=int(sys.argv[1]))
srv.start()
print("SERVER_READY", flush=True)
srv.run()
""" % FEAT


class TestGraphCluster:
    """2-server subprocess cluster: nodes shard by id%%2 across real
    processes (the graph_brpc_server deployment shape)."""

    def _spawn(self, port):
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        env["JAX_PLATFORMS"] = "cpu"
        p = subprocess.Popen(
            [sys.executable, "-c", _GRAPH_SERVER_SCRIPT, str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env, cwd=REPO)
        line = p.stdout.readline()
        assert "SERVER_READY" in line, line + p.stderr.read()[-2000:]
        return p

    def test_sharded_build_and_khop(self):
        from test_parameter_server import _free_port

        ports = [_free_port(), _free_port()]
        procs = [self._spawn(p) for p in ports]
        cli = PsClient([f"127.0.0.1:{p}" for p in ports])
        g = GraphPsClient(cli, 7, FEAT)
        try:
            rng = np.random.RandomState(0)
            ids = np.arange(40, dtype=np.uint64)
            feats = rng.randn(40, FEAT).astype(np.float32)
            g.add_nodes(ids, feats)
            src = rng.randint(0, 40, 200).astype(np.uint64)
            dst = rng.randint(0, 40, 200).astype(np.uint64)
            g.add_edges(src, dst)
            assert g.node_count() == 40
            # per-shard counts split by id parity (id % 2 == server)
            even = g.pull_graph_list(0, 0, 100)
            odd = g.pull_graph_list(1, 0, 100)
            assert set(even.tolist()) == set(range(0, 40, 2))
            assert set(odd.tolist()) == set(range(1, 40, 2))
            np.testing.assert_allclose(g.node_feat(ids), feats)
            # k-hop expansion is deterministic and neighbors really come
            # from the adjacency
            adj = {}
            for s, d in zip(src.tolist(), dst.tolist()):
                adj.setdefault(s, []).append(d)
            hops = g.sample_khop(np.array([1, 2], np.uint64), [3, 2],
                                 seed=5)
            hops2 = g.sample_khop(np.array([1, 2], np.uint64), [3, 2],
                                  seed=5)
            for (a, aw, ac), (b, bw, bc) in zip(hops, hops2):
                np.testing.assert_array_equal(a, b)
                np.testing.assert_array_equal(ac, bc)
            nbrs, _w, cnt = hops[0]
            for row, nid in enumerate((1, 2)):
                real = set(adj.get(nid, []))
                for j in range(cnt[row]):
                    assert int(nbrs[row, j]) in real
        finally:
            cli.stop_servers()
            cli.close()
            for p in procs:
                p.wait(timeout=30)
                if p.poll() is None:
                    p.kill()


class TestGraphSageEndToEnd:
    def test_graphsage_trains_on_sampled_neighborhoods(self):
        """GraphSage-style training: [self_feat ; mean(sampled neighbor
        feats)] -> MLP, labels follow community structure. Sampling +
        feature pull ride the graph PS; the classifier trains to strong
        separation (loss parity with a local numpy mirror is covered by
        the determinism tests above)."""
        srv, cli, g = _start()
        try:
            rng = np.random.RandomState(0)
            n_per, comm = 30, 2
            ids = np.arange(n_per * comm, dtype=np.uint64)
            community = (ids >= n_per).astype(np.float32)
            feats = (rng.randn(ids.size, FEAT) * 1.5).astype(np.float32)
            feats[:, 0] += 2.0 * (community * 2 - 1)  # weak signal
            g.add_nodes(ids, feats)
            # dense intra-community edges: aggregation denoises feature 0
            src, dst = [], []
            for c in range(comm):
                base = c * n_per
                for i in range(n_per):
                    nbrs = rng.choice(n_per, 8, replace=False)
                    src.extend([base + i] * 8)
                    dst.extend((base + nbrs).tolist())
            g.add_edges(np.array(src, np.uint64), np.array(dst, np.uint64))

            class Sage(nn.Layer):
                def __init__(self):
                    super().__init__()
                    self.fc1 = nn.Linear(2 * FEAT, 16)
                    self.fc2 = nn.Linear(16, 1)

                def forward(self, self_f, nbr_f):
                    h = paddle.ops.concat([self_f, nbr_f], axis=-1)
                    return self.fc2(paddle.nn.functional.relu(
                        self.fc1(h)))

            paddle.seed(0)
            model = Sage()
            opt = paddle.optimizer.Adam(parameters=model.parameters(),
                                        learning_rate=0.01)
            losses = []
            for step in range(60):
                batch = rng.choice(ids.size, 32, replace=False).astype(
                    np.uint64)
                nbrs, _w, _c = g.sample_neighbors(batch, 5, seed=step)
                self_f = g.node_feat(batch)
                nbr_f = g.node_feat(nbrs.ravel()).reshape(32, 5, FEAT)
                nbr_mean = nbr_f.mean(axis=1)
                label = community[batch.astype(np.int64)].reshape(-1, 1)
                logits = model(paddle.to_tensor(self_f),
                               paddle.to_tensor(nbr_mean))
                loss = paddle.nn.functional \
                    .binary_cross_entropy_with_logits(
                        logits, paddle.to_tensor(label))
                loss.backward()
                opt.step()
                opt.clear_grad()
                losses.append(float(loss.numpy()))
            assert np.mean(losses[-10:]) < 0.25, losses[-10:]
            assert np.mean(losses[-10:]) < np.mean(losses[:10]) * 0.6
        finally:
            cli.stop_servers()
            srv.stop()
