"""Expert-parallel MoE: all_to_all dispatch parity vs dense, capacity
drops, load-balance loss, gradients. Runs on the 8-device virtual CPU
mesh from conftest."""
import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import paddle_tpu.distributed as dist
from paddle_tpu.parallel import moe_ffn, switch_route

rng = np.random.RandomState(0)


def _weights(E, D, F):
    gw = rng.randn(D, E).astype(np.float32)
    w1 = rng.randn(E, D, F).astype(np.float32) * 0.2
    b1 = np.zeros((E, F), np.float32)
    w2 = rng.randn(E, F, D).astype(np.float32) * 0.2
    b2 = np.zeros((E, D), np.float32)
    return gw, w1, b1, w2, b2


def test_ep_matches_dense():
    T, D, F, E, ep = 32, 8, 16, 4, 4
    x = rng.randn(T * ep, D).astype(np.float32) * 0.5
    gw, w1, b1, w2, b2 = _weights(E, D, F)
    y_ref, aux_ref = moe_ffn(jnp.asarray(x), jnp.asarray(gw),
                             jnp.asarray(w1), jnp.asarray(b1),
                             jnp.asarray(w2), jnp.asarray(b2),
                             capacity_factor=100.0)
    mesh = dist.make_mesh({"ep": ep}, devices=jax.devices()[:ep])
    f = jax.jit(jax.shard_map(
        lambda *a: moe_ffn(*a, axis_name="ep", capacity_factor=100.0),
        mesh=mesh,
        in_specs=(P("ep"), P(), P("ep"), P("ep"), P("ep"), P("ep")),
        out_specs=(P("ep"), P())))
    y_ep, aux_ep = f(x, gw, w1, b1, w2, b2)
    np.testing.assert_allclose(np.asarray(y_ep), np.asarray(y_ref),
                               rtol=1e-5, atol=1e-6)
    # global load-balance objective identical on both paths
    np.testing.assert_allclose(float(aux_ep), float(aux_ref), rtol=1e-5)


def test_ep_gradients_flow():
    T, D, F, E, ep = 16, 8, 16, 4, 4
    x = rng.randn(T * ep, D).astype(np.float32) * 0.5
    gw, w1, b1, w2, b2 = _weights(E, D, F)
    mesh = dist.make_mesh({"ep": ep}, devices=jax.devices()[:ep])
    f = jax.jit(jax.shard_map(
        lambda *a: moe_ffn(*a, axis_name="ep", capacity_factor=100.0),
        mesh=mesh,
        in_specs=(P("ep"), P(), P("ep"), P("ep"), P("ep"), P("ep")),
        out_specs=(P("ep"), P())))

    def loss(w1_, gw_):
        y, aux = f(x, gw_, w1_, b1, w2, b2)
        return jnp.sum(y * y) + 0.01 * aux

    g1, gg = jax.grad(loss, argnums=(0, 1))(jnp.asarray(w1), jnp.asarray(gw))
    for g in (g1, gg):
        assert np.isfinite(np.asarray(g)).all()
        assert np.abs(np.asarray(g)).sum() > 0


def test_capacity_drops_tokens():
    # capacity 1 with many tokens routed to one expert: overflow tokens
    # emit zeros (residual semantics), kept tokens pass through the FFN
    T, D, F, E = 8, 4, 8, 2
    x = np.ones((T, D), np.float32)
    gw = np.zeros((D, E), np.float32)
    gw[:, 0] = 1.0  # everyone routes to expert 0
    _, w1, b1, w2, b2 = _weights(E, D, F)
    y, aux = moe_ffn(jnp.asarray(x), jnp.asarray(gw), jnp.asarray(w1),
                     jnp.asarray(b1), jnp.asarray(w2), jnp.asarray(b2),
                     capacity_factor=0.25)  # cap = 1 slot
    yn = np.asarray(y)
    nonzero_rows = (np.abs(yn).sum(axis=1) > 0).sum()
    assert nonzero_rows == 1  # only the first token kept


def test_switch_route_slots_unique():
    x = rng.randn(32, 8).astype(np.float32)
    gw = rng.randn(8, 4).astype(np.float32)
    expert, pos, prob, probs = switch_route(jnp.asarray(x), jnp.asarray(gw),
                                        4, capacity=8)
    e, p = np.asarray(expert), np.asarray(pos)
    kept = p >= 0
    pairs = set(zip(e[kept].tolist(), p[kept].tolist()))
    assert len(pairs) == kept.sum()  # no slot collisions
    assert (np.asarray(prob) > 0).all()


class TestMoELayer:
    def test_moe_layer_trains(self):
        import paddle_tpu as paddle
        from paddle_tpu.incubate.moe import MoELayer
        paddle.seed(0)
        layer = MoELayer(16, 32, 4)
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(2, 8, 16).astype("float32"))
        opt = paddle.optimizer.AdamW(parameters=layer.parameters(),
                                     learning_rate=1e-3)

        @paddle.jit.to_static
        def step(v):
            out = layer(v)
            loss = out.square().mean() + 0.01 * layer.aux_loss
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss

        l0 = float(step(x).numpy())
        for _ in range(5):
            l1 = float(step(x).numpy())
        assert l1 < l0
        assert float(layer.aux_loss.numpy()) > 0

    def test_moe_layer_shard_experts_annotates(self):
        from paddle_tpu.incubate.moe import MoELayer
        from jax.sharding import PartitionSpec as P
        layer = MoELayer(8, 16, 4).shard_experts("ep")
        assert layer.w1.pspec == P("ep")
        assert layer.gate_weight.pspec is None  # gate stays replicated
