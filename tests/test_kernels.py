"""Pallas kernel tests (interpret mode on CPU; compiled path covered by the
on-TPU bench). Reference model: operators/fused/ unit tests."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.kernels.flash_attention import flash_attention_bshd
from paddle_tpu.parallel.ring_attention import _full_attention

rng = np.random.RandomState(4)


def _mk(b, s, h, d):
    return (jnp.asarray(rng.randn(b, s, h, d).astype("float32")),
            jnp.asarray(rng.randn(b, s, h, d).astype("float32")),
            jnp.asarray(rng.randn(b, s, h, d).astype("float32")))


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("s", [128, 384, 200])
def test_flash_forward(causal, s):
    q, k, v = _mk(2, s, 2, 64)
    out = flash_attention_bshd(q, k, v, causal=causal, interpret=True)
    ref = _full_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_backward(causal):
    q, k, v = _mk(1, 256, 2, 32)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention_bshd(q, k, v, causal=causal,
                                            interpret=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(_full_attention(q, k, v, causal=causal) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_flash_bf16():
    q, k, v = _mk(1, 256, 2, 64)
    out = flash_attention_bshd(q.astype(jnp.bfloat16), k.astype(jnp.bfloat16),
                               v.astype(jnp.bfloat16), causal=True,
                               interpret=True)
    ref = _full_attention(q, k, v, causal=True)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, np.float32), np.asarray(ref),
                               rtol=5e-2, atol=5e-2)


def test_flash_cross_attention_lengths():
    q = jnp.asarray(rng.randn(1, 128, 2, 32).astype("float32"))
    k = jnp.asarray(rng.randn(1, 320, 2, 32).astype("float32"))
    v = jnp.asarray(rng.randn(1, 320, 2, 32).astype("float32"))
    out = flash_attention_bshd(q, k, v, interpret=True)
    ref = _full_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
