"""Static-graph autodiff handles (append_backward/gradients → fetchable
@GRAD vars), Block/Operator introspection, HDFS client (fake-hadoop shim),
gated ONNX export.

References: backward.py:1377/:1972, framework.py Block:2522/Operator:1921,
fleet/utils/fs.py HDFSClient, python/paddle/onnx.
"""
import os
import stat
import textwrap

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.static as static


class TestStaticGradients:
    def _build(self):
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [None, 4], "float32")
            w = static.create_parameter([4, 3], "float32")
            out = paddle.matmul(x, w)
            loss = paddle.mean(out * out)
        return prog, x, w, out, loss

    def test_gradients_wrt_param_and_feed(self):
        prog, x, w, out, loss = self._build()
        gw, gx = static.gradients(loss, [w, x])
        assert gw.name == w.name + "@GRAD"
        exe = static.Executor()
        feed = np.random.RandomState(0).rand(2, 4).astype(np.float32)
        loss_v, gw_v, gx_v = exe.run(prog, feed={"x": feed},
                                     fetch_list=[loss, gw, gx])
        # analytic: d mean((xw)^2) / dw = 2 x^T (xw) / numel
        xw = feed @ w.numpy()
        np.testing.assert_allclose(
            np.asarray(gw_v), 2 * feed.T @ xw / xw.size, rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(gx_v), 2 * xw @ w.numpy().T / xw.size, rtol=1e-5)

    def test_append_backward_pairs(self):
        prog, x, w, out, loss = self._build()
        with static.program_guard(prog):
            pairs = static.append_backward(loss)
        assert len(pairs) == 1
        p, g = pairs[0]
        assert p is w and g.name == w.name + "@GRAD"
        exe = static.Executor()
        feed = np.ones((2, 4), np.float32)
        (gv,) = exe.run(prog, feed={"x": feed}, fetch_list=[g])
        assert np.abs(np.asarray(gv)).sum() > 0

    def test_grad_fetch_with_optimizer_rejected(self):
        """@GRAD fetch must not silently skip the fused train step."""
        from paddle_tpu.core.enforce import UnimplementedError
        prog, x, w, out, loss = self._build()
        with static.program_guard(prog):
            pairs = static.append_backward(loss)
            opt = paddle.optimizer.SGD(learning_rate=0.1)
            opt.minimize(loss)
        exe = static.Executor()
        with pytest.raises(UnimplementedError, match="train step"):
            exe.run(prog, feed={"x": np.ones((2, 4), np.float32)},
                    fetch_list=[loss, pairs[0][1]])

    def test_intermediate_activation_source(self):
        """d(loss)/d(out) for an INTERMEDIATE var (reference backward.py
        gradients:1972 allows any var as input)."""
        prog, x, w, out, loss = self._build()
        (g_out,) = static.gradients(loss, [out])
        exe = static.Executor()
        feed = np.random.RandomState(1).rand(2, 4).astype(np.float32)
        (gv,) = exe.run(prog, feed={"x": feed}, fetch_list=[g_out])
        xw = feed @ w.numpy()
        np.testing.assert_allclose(np.asarray(gv), 2 * xw / xw.size,
                                   rtol=1e-5)

    def test_target_gradients_seeding(self):
        """Custom output cotangent: grad of <out, seed> wrt w == x^T seed."""
        prog, x, w, out, loss = self._build()
        seed = np.random.RandomState(2).rand(2, 3).astype(np.float32)
        (gw,) = static.gradients([out], [w], target_gradients=[seed])
        exe = static.Executor()
        feed = np.random.RandomState(3).rand(2, 4).astype(np.float32)
        (gv,) = exe.run(prog, feed={"x": feed}, fetch_list=[gw])
        np.testing.assert_allclose(np.asarray(gv), feed.T @ seed, rtol=1e-5)

    def test_multiple_targets_sum(self):
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [None, 4], "float32")
            w = static.create_parameter([4, 3], "float32")
            out = paddle.matmul(x, w)
            t1 = paddle.mean(out)
            t2 = paddle.mean(out * out)
        (gw_both,) = static.gradients([t1, t2], [w])
        (gw_1,) = static.gradients([t1], [w])
        (gw_2,) = static.gradients([t2], [w])
        exe = static.Executor()
        feed = np.random.RandomState(4).rand(2, 4).astype(np.float32)
        (v_both,) = exe.run(prog, feed={"x": feed}, fetch_list=[gw_both])
        (v_1,) = exe.run(prog, feed={"x": feed}, fetch_list=[gw_1])
        (v_2,) = exe.run(prog, feed={"x": feed}, fetch_list=[gw_2])
        np.testing.assert_allclose(np.asarray(v_both),
                                   np.asarray(v_1) + np.asarray(v_2),
                                   rtol=1e-5)

    def test_no_grad_set_blocks_path(self):
        """A var in no_grad_set is a constant: grads through it vanish."""
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [None, 4], "float32")
            w = static.create_parameter([4, 4], "float32")
            h = paddle.matmul(x, w)     # path 1 through w
            y = paddle.matmul(h, w)     # path 2 through w again
            loss = paddle.mean(y)
        (g_blocked,) = static.gradients(loss, [w], no_grad_set=[h])
        (g_full,) = static.gradients(loss, [w])
        exe = static.Executor()
        feed = np.random.RandomState(5).rand(2, 4).astype(np.float32)
        (vb,) = exe.run(prog, feed={"x": feed}, fetch_list=[g_blocked])
        (vf,) = exe.run(prog, feed={"x": feed}, fetch_list=[g_full])
        # blocking h removes the first-matmul contribution: d(mean(h w))/dw
        # with h constant == h^T ones / n
        h_v = feed @ w.numpy()
        n = h_v.shape[0] * w.numpy().shape[1]
        np.testing.assert_allclose(np.asarray(vb),
                                   h_v.T @ np.ones((2, 4), np.float32) / n,
                                   rtol=1e-5)
        assert not np.allclose(np.asarray(vb), np.asarray(vf))

    def test_mixed_targets_rejected(self):
        prog, x, w, out, loss = self._build()
        with static.program_guard(prog):
            g1 = static.gradients(loss, [w])[0]
            g2 = static.gradients(out, [w])[0]
        exe = static.Executor()
        from paddle_tpu.core.enforce import InvalidArgumentError
        with pytest.raises(InvalidArgumentError, match="same target"):
            # note: multi-target in ONE gradients() call is supported; what
            # stays rejected is MIXING handles with different target sigs
            exe.run(prog, feed={"x": np.ones((2, 4), np.float32)},
                    fetch_list=[g1, g2])


class TestBlockOperator:
    def test_introspection(self):
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [2, 4], "float32")
            w = static.create_parameter([4, 3], "float32")
            y = paddle.matmul(x, w)
            z = paddle.tanh(y)
        block = prog.global_block()
        assert block.idx == 0 and prog.num_blocks() == 1
        types = [op.type for op in block.ops]
        assert "matmul" in types and "tanh" in types
        mm = block.ops[types.index("matmul")]
        assert len(mm.input_arg_names()) == 2
        assert len(mm.output_arg_names()) == 1
        assert block.var(w.name) is w
        assert w in block.all_parameters()
        with pytest.raises(ValueError):
            block.var("nope")


class TestPasses:
    def test_delete_dropout_pass(self):
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [4, 8], "float32")
            out = nn.functional.dropout(x, p=0.5, training=True)
        rewritten = static.apply_pass(prog, "delete_dropout_op_pass")
        exe = static.Executor()
        feed = np.ones((4, 8), np.float32)
        (r,) = exe.run(rewritten, feed={"x": feed}, fetch_list=[out])
        np.testing.assert_allclose(np.asarray(r), feed)
        # original program untouched (still drops)
        (r0,) = exe.run(prog, feed={"x": feed}, fetch_list=[out])
        assert (np.asarray(r0) == 0).any()

    def test_unknown_pass_raises(self):
        import pytest as _pytest
        with _pytest.raises(KeyError, match="unknown pass"):
            static.apply_pass(static.Program(), "nope_pass")
        assert "delete_dropout_op_pass" in static.list_passes()

    def test_prune_backward_slice(self):
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [2, 4], "float32")
            a = paddle.tanh(x)          # contributes to fetched `b`
            b = paddle.mean(a)
            c = paddle.exp(x)           # dead branch for this fetch
            d = paddle.sum(c)
        pruned = static.prune(prog, [b])
        kept = [op.name for op in pruned.ops]
        assert "tanh" in kept and "mean" in kept
        assert "exp" not in kept and "sum" not in kept
        exe = static.Executor()
        feed = np.random.RandomState(0).rand(2, 4).astype(np.float32)
        (want,) = exe.run(prog, feed={"x": feed}, fetch_list=[b])
        (got,) = exe.run(pruned, feed={"x": feed}, fetch_list=[b])
        np.testing.assert_allclose(np.asarray(got), np.asarray(want))

    def test_prune_unknown_target(self):
        import pytest as _pytest
        prog = static.Program()
        stray = paddle.to_tensor(np.ones(2, np.float32))
        with _pytest.raises(ValueError, match="not.*recorded"):
            static.prune(prog, [stray])


FAKE_HADOOP = textwrap.dedent("""\
    #!/bin/bash
    # fake `hadoop fs` shim over a local root (for hermetic HDFSClient tests)
    ROOT="$FAKE_HDFS_ROOT"
    shift  # drop 'fs'
    cmd="$1"; shift
    case "$cmd" in
      -test)
        flag="$1"; p="$ROOT/$2"
        if [ "$flag" = "-e" ]; then [ -e "$p" ]; exit $?; fi
        if [ "$flag" = "-d" ]; then [ -d "$p" ]; exit $?; fi
        exit 1;;
      -mkdir) [ "$1" = "-p" ] && shift; mkdir -p "$ROOT/$1";;
      -put) [ "$1" = "-f" ] && shift; cp "$1" "$ROOT/$2";;
      -get) cp "$ROOT/$1" "$2";;
      -rm) while [[ "$1" == -* ]]; do shift; done; rm -rf "$ROOT/$1";;
      -mv) mv "$ROOT/$1" "$ROOT/$2";;
      -ls)
        p="$ROOT/$1"
        for f in "$p"/*; do
          [ -e "$f" ] || continue
          if [ -d "$f" ]; then perm="drwxr-xr-x"; else perm="-rw-r--r--"; fi
          echo "$perm 1 u g 0 2026-01-01 00:00 $1/$(basename "$f")"
        done;;
      *) echo "unknown $cmd" >&2; exit 2;;
    esac
""")


class TestHDFSClient:
    @pytest.fixture()
    def client(self, tmp_path, monkeypatch):
        from paddle_tpu.distributed.fleet.utils.fs import HDFSClient
        home = tmp_path / "hadoop_home"
        (home / "bin").mkdir(parents=True)
        shim = home / "bin" / "hadoop"
        shim.write_text(FAKE_HADOOP)
        shim.chmod(shim.stat().st_mode | stat.S_IEXEC)
        root = tmp_path / "hdfs_root"
        root.mkdir()
        monkeypatch.setenv("FAKE_HDFS_ROOT", str(root))
        return HDFSClient(hadoop_home=str(home)), tmp_path

    def test_roundtrip(self, client):
        fs, tmp = client
        assert not fs.is_exist("data")
        fs.mkdirs("data/sub")
        assert fs.is_exist("data") and fs.is_dir("data")
        local = tmp / "f.txt"
        local.write_text("hello hdfs")
        fs.upload(str(local), "data/f.txt")
        assert fs.is_file("data/f.txt")
        dirs, files = fs.ls_dir("data")
        assert dirs == ["sub"] and files == ["f.txt"]
        back = tmp / "back.txt"
        fs.download("data/f.txt", str(back))
        assert back.read_text() == "hello hdfs"
        fs.mv("data/f.txt", "data/g.txt")
        assert fs.is_file("data/g.txt") and not fs.is_exist("data/f.txt")
        fs.delete("data")
        assert not fs.is_exist("data")

    def test_missing_binary_message(self):
        from paddle_tpu.distributed.fleet.utils.fs import HDFSClient
        fs = HDFSClient(hadoop_home="/nonexistent")
        with pytest.raises(RuntimeError, match="hadoop binary not found"):
            fs.is_exist("/x")


class TestOnnxGate:
    def test_export_is_real_and_requires_input_spec(self, tmp_path):
        """The round-2 gated stub became a real exporter in round 3
        (tests/test_onnx_export.py covers the graph mapping); the one
        contract kept here: input_spec is required."""
        import paddle_tpu.onnx as ponnx
        m = nn.Sequential(nn.Linear(4, 2))
        with pytest.raises(ValueError, match="input_spec"):
            ponnx.export(m, str(tmp_path / "x"))


class TestStaticGradientsEdge:
    """Regressions from review: fresh seeds must not hit a stale jit cache;
    duplicate sources must both receive real grads."""

    def test_fresh_target_gradients_not_cached(self):
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [None, 4], "float32")
            w = static.create_parameter([4, 3], "float32")
            out = paddle.matmul(x, w)
        feed = np.random.RandomState(6).rand(2, 4).astype(np.float32)
        exe = static.Executor()
        sa = np.ones((2, 3), np.float32)
        sb = np.full((2, 3), 2.0, np.float32)
        (ga,) = static.gradients([out], [w], target_gradients=[sa])
        (va,) = exe.run(prog, feed={"x": feed}, fetch_list=[ga])
        (gb,) = static.gradients([out], [w], target_gradients=[sb])
        (vb,) = exe.run(prog, feed={"x": feed}, fetch_list=[gb])
        np.testing.assert_allclose(np.asarray(vb), 2 * np.asarray(va),
                                   rtol=1e-5)

    def test_duplicate_sources_both_real(self):
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [None, 4], "float32")
            w = static.create_parameter([4, 3], "float32")
            out = paddle.matmul(x, w)
            loss = paddle.mean(out * out)
        (g1,) = static.gradients(loss, [out])
        (g2,) = static.gradients(loss, [out])
        exe = static.Executor()
        feed = np.random.RandomState(7).rand(2, 4).astype(np.float32)
        v1, v2 = exe.run(prog, feed={"x": feed}, fetch_list=[g1, g2])
        assert np.abs(np.asarray(v1)).sum() > 0
        np.testing.assert_allclose(np.asarray(v1), np.asarray(v2))

    def test_unrecorded_source_clear_error(self):
        from paddle_tpu.core.enforce import InvalidArgumentError
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [None, 4], "float32")
            w = static.create_parameter([4, 3], "float32")
            loss = paddle.mean(paddle.matmul(x, w))
        stray = paddle.ones([4])
        (g,) = static.gradients(loss, [stray])
        exe = static.Executor()
        with pytest.raises(InvalidArgumentError, match="never used"):
            exe.run(prog, feed={"x": np.ones((2, 4), np.float32)},
                    fetch_list=[g])
