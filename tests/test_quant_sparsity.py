"""QAT/PTQ quantization + ASP N:M sparsity.

Mirrors reference tests: slim/tests/test_imperative_qat.py,
test_post_training_quantization_*.py, asp/test_asp_pruning_1d.py,
asp/test_asp_optimize.py.
"""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, quantization, sparsity
from paddle_tpu.quantization import (
    ImperativeQuantAware, PTQ, QuantizedLinear, fake_quant,
)


def test_fake_quant_forward_levels():
    x = paddle.to_tensor(np.linspace(-1, 1, 11).astype(np.float32))
    q = np.asarray(fake_quant(x, scale=1.0, bits=8).numpy())
    # quantized to the 127-level grid
    np.testing.assert_allclose(q * 127, np.round(q * 127), atol=1e-4)
    np.testing.assert_allclose(q, np.asarray(x.numpy()), atol=1.0 / 127)


def test_fake_quant_ste_gradient():
    x = paddle.to_tensor(np.array([0.3, 2.0, -0.5], np.float32))
    x.stop_gradient = False
    y = fake_quant(x, scale=1.0, bits=8)
    y.sum().backward()
    g = np.asarray(x.grad.numpy())
    # STE: grad 1 inside [-scale, scale], 0 outside
    np.testing.assert_allclose(g, [1.0, 0.0, 1.0])


def test_imperative_qat_swaps_layers():
    class M(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc1 = nn.Linear(8, 8)
            self.inner = nn.Sequential(nn.Linear(8, 4), nn.ReLU())
            self.conv = nn.Conv2D(1, 2, 3)

        def forward(self, x):
            return self.inner(self.fc1(x))

    m = M()
    ImperativeQuantAware().quantize(m)
    assert isinstance(m.fc1, QuantizedLinear)
    assert isinstance(m.inner[0], QuantizedLinear)
    assert type(m.conv).__name__ == "QuantizedConv2D"
    x = paddle.to_tensor(np.random.rand(2, 8).astype(np.float32))
    out = m(x)
    assert tuple(out.shape) == (2, 4)


def test_qat_output_close_to_float():
    paddle.seed(0)
    lin = nn.Linear(16, 16)
    x = paddle.to_tensor(np.random.randn(4, 16).astype(np.float32))
    ref = np.asarray(lin(x).numpy())
    qlin = QuantizedLinear(lin)
    got = np.asarray(qlin(x).numpy())
    # int8 simulation error is small relative to activation magnitude
    assert np.abs(got - ref).max() < 0.15 * np.abs(ref).max() + 0.05


def test_qat_trains():
    """QAT on a toy regression must still converge (grad flows through STE)."""
    paddle.seed(0)
    np.random.seed(0)
    lin = nn.Linear(4, 1)
    ImperativeQuantAware().quantize(model := nn.Sequential(lin))
    opt = paddle.optimizer.Adam(parameters=model.parameters(),
                                learning_rate=0.05)
    w_true = np.array([[1.0], [-2.0], [0.5], [3.0]], np.float32)
    first = last = None
    for i in range(60):
        xb = np.random.randn(32, 4).astype(np.float32)
        yb = xb @ w_true
        loss = paddle.nn.functional.mse_loss(
            model(paddle.to_tensor(xb)), paddle.to_tensor(yb))
        loss.backward()
        opt.step()
        opt.clear_grad()
        if first is None:
            first = float(loss.numpy())
    last = float(loss.numpy())
    assert last < first * 0.1, (first, last)


def test_ptq_absmax_calibration():
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(8, 8), nn.ReLU(), nn.Linear(8, 2))

    def loader():
        rng = np.random.RandomState(0)
        for _ in range(4):
            yield (paddle.to_tensor(rng.randn(16, 8).astype(np.float32)),)

    PTQ(algo="abs_max").quantize(model, loader())
    q0 = model[0]
    assert q0._frozen and q0._act_scale_initialized
    assert q0._act_scale > 0
    # frozen: scale stops moving
    s = q0._act_scale
    model(paddle.to_tensor(np.random.randn(4, 8).astype(np.float32) * 100))
    assert q0._act_scale == s


def test_ptq_percentile_calibration():
    model = nn.Sequential(nn.Linear(8, 4))

    def loader():
        rng = np.random.RandomState(1)
        for _ in range(4):
            yield (paddle.to_tensor(rng.randn(64, 8).astype(np.float32)),)

    PTQ(algo="percentile", percentile=0.99).quantize(model, loader())
    q = model[0]
    # 99th percentile of |N(0,1)| is ~2.58, well below abs max over 256 samples
    assert 2.0 < q._act_scale < 3.2


# ---------------- ASP ----------------

def test_create_mask_2_4():
    w = paddle.to_tensor(np.random.randn(8, 12).astype(np.float32))
    mask = sparsity.create_mask(w, n=2, m=4)
    assert sparsity.check_mask_1d(mask, 2, 4)
    assert mask.sum() == 8 * 12 // 2  # exactly half kept
    # kept entries are the largest-|.| of each group
    wv = np.asarray(w.numpy()).reshape(8, 3, 4)
    mv = mask.reshape(8, 3, 4)
    for r in range(8):
        for g in range(3):
            kept = set(np.where(mv[r, g] == 1)[0])
            top2 = set(np.argsort(-np.abs(wv[r, g]))[:2])
            assert kept == top2


def test_create_mask_nondivisible_cols():
    w = paddle.to_tensor(np.random.randn(4, 10).astype(np.float32))
    mask = sparsity.create_mask(w, n=2, m=4)
    assert mask.shape == (4, 10)
    assert sparsity.check_sparsity(mask, n=2, m=4)


def test_prune_model_and_density():
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(16, 16), nn.ReLU(), nn.Linear(16, 8))
    sparsity.prune_model(model, n=2, m=4)
    for _, p in model.named_parameters():
        if len(p.shape) >= 2:
            assert sparsity.check_mask_1d(p, 2, 4)
            assert abs(sparsity.calculate_density(p) - 0.5) < 1e-6


def test_asp_decorated_optimizer_keeps_masks():
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(8, 8))
    opt = sparsity.decorate(
        paddle.optimizer.SGD(parameters=model.parameters(),
                             learning_rate=0.1))
    sparsity.prune_model(model, n=2, m=4)
    zero_positions = np.asarray(model[0].weight.numpy()) == 0
    for _ in range(3):
        x = paddle.to_tensor(np.random.randn(4, 8).astype(np.float32))
        loss = model(x).sum()
        loss.backward()
        opt.step()
        opt.clear_grad()
    w = np.asarray(model[0].weight.numpy())
    # the pruned slots stay zero through optimizer updates
    assert (w[zero_positions] == 0).all()
    assert sparsity.check_mask_1d(w, 2, 4)


def test_channel_wise_weight_scales_beat_per_tensor():
    """channel_wise_abs_max: per-output-channel scales quantize a weight
    with wildly different column magnitudes far better than one tensor
    scale (reference: fake_quantize_op.cc FakeChannelWiseQuantizeAbsMax)."""
    from paddle_tpu.quantization import ImperativeQuantAware

    rng_l = np.random.RandomState(0)
    w = rng_l.randn(8, 4).astype(np.float32)
    w[:, 0] *= 100.0  # one loud column drowns the per-tensor scale
    x = rng_l.rand(5, 8).astype(np.float32)

    def build(channel):
        m = paddle.nn.Linear(8, 4)
        m.weight.set_value(w)
        m.bias.set_value(np.zeros(4, np.float32))
        qt = "channel_wise_abs_max" if channel else "abs_max"
        ImperativeQuantAware(weight_quantize_type=qt).quantize(
            nn_wrap := paddle.nn.Sequential(m))
        return nn_wrap

    ref = x @ w
    err_t = np.abs(np.asarray(build(False)(paddle.to_tensor(x)).numpy())
                   - ref)[:, 1:].mean()
    err_c = np.abs(np.asarray(build(True)(paddle.to_tensor(x)).numpy())
                   - ref)[:, 1:].mean()
    assert err_c < err_t / 4


def test_quantized_embedding_swap_and_forward():
    from paddle_tpu.quantization import ImperativeQuantAware, \
        QuantizedEmbedding

    m = paddle.nn.Sequential(paddle.nn.Embedding(16, 8))
    ImperativeQuantAware(
        quantizable_layer_type=("Embedding",)).quantize(m)
    assert isinstance(m[0], QuantizedEmbedding)
    ids = paddle.to_tensor(np.array([1, 5, 9], np.int64))
    out = m(ids)
    assert out.shape == [3, 8]


def test_output_scales_and_sidecar(tmp_path):
    from paddle_tpu.quantization import (ImperativeQuantAware,
                                         load_quant_scales)
    from paddle_tpu.jit.to_static import InputSpec

    model = paddle.nn.Sequential(paddle.nn.Linear(4, 8), paddle.nn.ReLU(),
                                 paddle.nn.Linear(8, 2))
    q = ImperativeQuantAware()
    q.quantize(model)
    for _ in range(3):
        model(paddle.to_tensor(np.random.RandomState(1)
                               .rand(2, 4).astype(np.float32)))
    prefix = str(tmp_path / "qmodel")
    q.save_quantized_model(model, prefix,
                           input_spec=[InputSpec([None, 4], "float32")])
    scales = load_quant_scales(prefix)
    assert len(scales) == 2  # two quantized Linears
    for rec in scales.values():
        assert rec["act_scale"] > 0 and rec["out_scale"] > 0
        assert rec["weight_bits"] == 8


@pytest.mark.slow  # ~20 s resnet PTQ + artifact round-trip; quant op
# semantics stay tier-1-covered by the per-op cases in this file
def test_ptq_resnet_serving_accuracy_delta(tmp_path):
    """The VERDICT bar: PTQ a ResNet, serve the saved artifact through
    the Predictor in-process, assert the quantized predictions track the
    float model (top-1 agreement)."""
    from paddle_tpu.inference import Config, create_predictor
    from paddle_tpu.jit.to_static import InputSpec
    from paddle_tpu.quantization import PTQ, ImperativeQuantAware
    from paddle_tpu.vision.models import resnet18

    paddle.seed(7)
    rng_l = np.random.RandomState(3)
    imgs = rng_l.rand(8, 3, 32, 32).astype(np.float32)

    float_model = resnet18(num_classes=10)
    float_model.eval()
    float_logits = np.asarray(
        float_model(paddle.to_tensor(imgs)).numpy())

    calib = [(paddle.to_tensor(imgs[i:i + 2]),) for i in range(0, 8, 2)]
    qmodel = PTQ(algo="abs_max").quantize(float_model, calib)
    prefix = str(tmp_path / "resnet_q")
    ImperativeQuantAware.save_quantized_model(
        qmodel, prefix,
        input_spec=[InputSpec([None, 3, 32, 32], "float32")])
    assert os.path.exists(prefix + ".quant.json")

    pred = create_predictor(Config(prefix + ".pdmodel",
                                   prefix + ".pdiparams"))
    name = pred.get_input_names()[0]
    pred.get_input_handle(name).copy_from_cpu(imgs)
    pred.run()
    served = pred.get_output_handle(pred.get_output_names()[0]).copy_to_cpu()

    agree = (served.argmax(-1) == float_logits.argmax(-1)).mean()
    assert agree >= 0.75, agree
    # logits deviation bounded (8-bit fake-quant on a float backbone)
    rel = np.abs(served - float_logits).mean() / (
        np.abs(float_logits).mean() + 1e-6)
    assert rel < 0.5, rel
