"""OpTest sweep part 2: conv / norm / pool / shape nn-functionals with
numpy references and grad checks (complements tests/test_op_sweep.py's
elementwise/reduction/manipulation coverage).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from op_test import check_output, check_grad

rng = np.random.RandomState(7)

IMG = rng.rand(1, 2, 6, 6).astype("float32")
SEQ = rng.rand(1, 2, 8).astype("float32")
W2D = rng.rand(3, 2, 3, 3).astype("float32") * 0.5
W1D = rng.rand(3, 2, 3).astype("float32") * 0.5
WT2D = rng.rand(2, 3, 3, 3).astype("float32") * 0.5
X24 = rng.rand(2, 4).astype("float32")
X243 = rng.rand(2, 4, 3).astype("float32")


def _conv2d_np(x, w, stride=1, pad=0):
    n, ci, h, wd = x.shape
    co, _, kh, kw = w.shape
    xp = np.pad(x, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    oh = (h + 2 * pad - kh) // stride + 1
    ow = (wd + 2 * pad - kw) // stride + 1
    out = np.zeros((n, co, oh, ow), np.float32)
    for i in range(oh):
        for j in range(ow):
            patch = xp[:, :, i * stride:i * stride + kh,
                       j * stride:j * stride + kw]
            out[:, :, i, j] = np.einsum("ncij,ocij->no", patch, w)
    return out


def _ln_np(x, axis=-1, eps=1e-5):
    m = x.mean(axis=axis, keepdims=True)
    v = x.var(axis=axis, keepdims=True)
    return (x - m) / np.sqrt(v + eps)


class TestConv:
    def test_conv2d_output(self):
        check_output(lambda x, w: F.conv2d(x, w),
                     lambda x, w: _conv2d_np(x, w), [IMG, W2D])

    def test_conv2d_stride_pad(self):
        check_output(lambda x, w: F.conv2d(x, w, stride=2, padding=1),
                     lambda x, w: _conv2d_np(x, w, stride=2, pad=1),
                     [IMG, W2D])

    def test_conv2d_grads(self):
        check_grad(lambda x, w: F.conv2d(x, w), [IMG, W2D], grad_index=0)
        check_grad(lambda x, w: F.conv2d(x, w), [IMG, W2D], grad_index=1)

    def test_conv1d_output(self):
        def ref(x, w):
            return _conv2d_np(x[:, :, None, :], w[:, :, None, :])[:, :, 0]
        check_output(lambda x, w: F.conv1d(x, w), ref, [SEQ, W1D])

    def test_conv2d_transpose_shape_and_grad(self):
        out = F.conv2d_transpose(paddle.to_tensor(IMG),
                                 paddle.to_tensor(WT2D), stride=2)
        assert list(out.shape)[:2] == [1, 3]
        check_grad(lambda x: F.conv2d_transpose(
            x, paddle.to_tensor(WT2D), stride=2), [IMG])

    def test_depthwise_groups(self):
        wg = rng.rand(2, 1, 3, 3).astype("float32")
        out = F.conv2d(paddle.to_tensor(IMG), paddle.to_tensor(wg), groups=2)
        want = np.stack([
            _conv2d_np(IMG[:, i:i + 1], wg[i:i + 1])[:, 0]
            for i in range(2)], axis=1)
        np.testing.assert_allclose(np.asarray(out.numpy()), want, rtol=1e-4,
                                   atol=1e-5)


class TestNorms:
    def test_layer_norm(self):
        check_output(lambda x: F.layer_norm(x, 3),
                     lambda x: _ln_np(x), [X243])
        check_grad(lambda x: F.layer_norm(x, 3), [X243])

    def test_rms_norm(self):
        def ref(x):
            return x / np.sqrt((x ** 2).mean(-1, keepdims=True) + 1e-5)
        w = np.ones(3, np.float32)
        check_output(lambda x: F.rms_norm(x, paddle.to_tensor(w)),
                     lambda x: ref(x), [X243], atol=1e-4)

    def test_batch_norm_eval(self):
        m = np.array([0.2, 0.4], np.float32)
        v = np.array([1.5, 2.0], np.float32)
        def op(x):
            return F.batch_norm(x, paddle.to_tensor(m.copy()),
                                paddle.to_tensor(v.copy()), training=False)
        def ref(x):
            return (x - m[None, :, None, None]) / np.sqrt(
                v[None, :, None, None] + 1e-5)
        check_output(op, ref, [IMG])

    def test_group_norm(self):
        def ref(x):
            g = x.reshape(1, 2, 1, 6, 6)  # 2 groups of 1 channel
            return _ln_np(g.reshape(1, 2, -1)).reshape(x.shape)
        check_output(lambda x: F.group_norm(x, num_groups=2),
                     lambda x: ref(x), [IMG], atol=1e-4)

    def test_instance_norm(self):
        def ref(x):
            return _ln_np(x.reshape(1, 2, -1)).reshape(x.shape)
        check_output(F.instance_norm, ref, [IMG], atol=1e-4)

    def test_normalize(self):
        check_output(lambda x: F.normalize(x, axis=1),
                     lambda x: x / np.maximum(
                         np.linalg.norm(x, axis=1, keepdims=True), 1e-12),
                     [X24])
        check_grad(lambda x: F.normalize(x, axis=1), [X24])


class TestPoolShape:
    def test_adaptive_avg_pool2d(self):
        check_output(lambda x: F.adaptive_avg_pool2d(x, 3),
                     lambda x: x.reshape(1, 2, 3, 2, 3, 2).mean((3, 5)),
                     [IMG])
        check_grad(lambda x: F.adaptive_avg_pool2d(x, 3), [IMG])

    def test_adaptive_max_pool2d(self):
        check_output(lambda x: F.adaptive_max_pool2d(x, 3),
                     lambda x: x.reshape(1, 2, 3, 2, 3, 2).max((3, 5)),
                     [IMG])

    def test_interpolate_nearest(self):
        check_output(
            lambda x: F.interpolate(x, scale_factor=2, mode="nearest"),
            lambda x: x.repeat(2, axis=2).repeat(2, axis=3), [IMG])

    def test_pixel_shuffle(self):
        x = rng.rand(1, 4, 3, 3).astype("float32")
        out = F.pixel_shuffle(paddle.to_tensor(x), 2)
        assert list(out.shape) == [1, 1, 6, 6]
        # element check: output (0, 0, i*2+di, j*2+dj) = x[0, di*2+dj, i, j]
        o = np.asarray(out.numpy())
        for di in range(2):
            for dj in range(2):
                np.testing.assert_allclose(o[0, 0, di::2, dj::2],
                                           x[0, di * 2 + dj])

    def test_unfold(self):
        x = rng.rand(1, 2, 4, 4).astype("float32")
        out = F.unfold(paddle.to_tensor(x), kernel_sizes=2)
        assert list(out.shape) == [1, 2 * 2 * 2, 9]

    def test_cosine_similarity(self):
        a = rng.rand(2, 4).astype("float32")
        b = rng.rand(2, 4).astype("float32")
        check_output(F.cosine_similarity,
                     lambda x, y: (x * y).sum(-1)
                     / (np.linalg.norm(x, axis=-1)
                        * np.linalg.norm(y, axis=-1)), [a, b])

    def test_label_smooth(self):
        oh = np.eye(4, dtype="float32")[[0, 2]]
        check_output(lambda x: F.label_smooth(x, epsilon=0.1),
                     lambda x: x * 0.9 + 0.1 / 4, [oh])
