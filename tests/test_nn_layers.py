"""Layer tests (reference model: unittests/test_layers.py)."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn
import paddle_tpu.nn.functional as F

rng = np.random.RandomState(11)


def test_linear():
    layer = nn.Linear(4, 3)
    x = paddle.to_tensor(rng.rand(2, 4).astype("float32"))
    out = layer(x)
    ref = x.numpy() @ layer.weight.numpy() + layer.bias.numpy()
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-5)


def test_conv2d_shapes():
    layer = nn.Conv2D(3, 8, 3, stride=2, padding=1)
    x = paddle.to_tensor(rng.rand(2, 3, 16, 16).astype("float32"))
    assert layer(x).shape == [2, 8, 8, 8]
    layer = nn.Conv2D(4, 4, 3, groups=4, padding=1)  # depthwise
    x = paddle.to_tensor(rng.rand(1, 4, 8, 8).astype("float32"))
    assert layer(x).shape == [1, 4, 8, 8]


def test_conv2d_vs_torch_semantics():
    import torch
    import torch.nn.functional as tF
    x = rng.rand(2, 3, 8, 8).astype("float32")
    w = rng.rand(5, 3, 3, 3).astype("float32")
    b = rng.rand(5).astype("float32")
    mine = F.conv2d(paddle.to_tensor(x), paddle.to_tensor(w),
                    paddle.to_tensor(b), stride=1, padding=1)
    ref = tF.conv2d(torch.tensor(x), torch.tensor(w), torch.tensor(b),
                    stride=1, padding=1).numpy()
    np.testing.assert_allclose(mine.numpy(), ref, rtol=1e-4, atol=1e-4)


def test_conv2d_transpose_vs_torch():
    import torch
    import torch.nn.functional as tF
    x = rng.rand(2, 4, 8, 8).astype("float32")
    w = rng.rand(4, 6, 3, 3).astype("float32")  # [in, out, kh, kw]
    mine = F.conv2d_transpose(paddle.to_tensor(x), paddle.to_tensor(w),
                              stride=2, padding=1)
    ref = tF.conv_transpose2d(torch.tensor(x), torch.tensor(w), stride=2,
                              padding=1).numpy()
    np.testing.assert_allclose(mine.numpy(), ref, rtol=1e-4, atol=1e-4)


def test_pooling_vs_torch():
    import torch
    import torch.nn.functional as tF
    x = rng.rand(2, 3, 8, 8).astype("float32")
    mine = F.max_pool2d(paddle.to_tensor(x), 2, 2)
    ref = tF.max_pool2d(torch.tensor(x), 2, 2).numpy()
    np.testing.assert_allclose(mine.numpy(), ref)
    mine = F.avg_pool2d(paddle.to_tensor(x), 2, 2)
    ref = tF.avg_pool2d(torch.tensor(x), 2, 2).numpy()
    np.testing.assert_allclose(mine.numpy(), ref, rtol=1e-6)
    mine = F.adaptive_avg_pool2d(paddle.to_tensor(x), 1)
    ref = tF.adaptive_avg_pool2d(torch.tensor(x), 1).numpy()
    np.testing.assert_allclose(mine.numpy(), ref, rtol=1e-6)


def test_batch_norm_train_eval():
    bn = nn.BatchNorm2D(4)
    x = paddle.to_tensor(rng.rand(8, 4, 5, 5).astype("float32") * 3 + 1)
    bn.train()
    out = bn(x)
    # normalized output: ~zero mean, unit var per channel
    o = out.numpy()
    assert abs(o.mean()) < 1e-4
    assert abs(o.std() - 1.0) < 1e-2
    # running stats moved toward batch stats
    assert not np.allclose(bn._mean.numpy(), 0)
    bn.eval()
    out_eval = bn(x)
    assert out_eval.shape == out.shape


def test_batch_norm_grad_flows():
    bn = nn.BatchNorm1D(3, data_format="NCL")
    x = paddle.to_tensor(rng.rand(4, 3, 5).astype("float32"))
    out = bn(x)
    out.sum().backward()
    assert bn.weight.grad is not None
    assert bn.bias.grad is not None


def test_layer_norm_vs_torch():
    import torch
    ln = nn.LayerNorm(6)
    x = rng.rand(4, 6).astype("float32")
    mine = ln(paddle.to_tensor(x)).numpy()
    tln = torch.nn.LayerNorm(6)
    with torch.no_grad():
        tln.weight.copy_(torch.tensor(ln.weight.numpy()))
        tln.bias.copy_(torch.tensor(ln.bias.numpy()))
    ref = tln(torch.tensor(x)).detach().numpy()
    np.testing.assert_allclose(mine, ref, rtol=1e-4, atol=1e-5)


def test_embedding():
    emb = nn.Embedding(10, 4, padding_idx=0)
    idx = paddle.to_tensor(np.array([[1, 0, 3]], np.int64))
    out = emb(idx)
    assert out.shape == [1, 3, 4]
    np.testing.assert_allclose(out.numpy()[0, 1], np.zeros(4))
    out.sum().backward()
    assert emb.weight.grad is not None


def test_dropout_modes():
    drop = nn.Dropout(0.5)
    x = paddle.to_tensor(np.ones((100, 100), np.float32))
    drop.train()
    y = drop(x)
    frac = float((y.numpy() == 0).mean())
    assert 0.4 < frac < 0.6
    kept = y.numpy()[y.numpy() != 0]
    np.testing.assert_allclose(kept, 2.0 * np.ones_like(kept))  # upscale
    drop.eval()
    np.testing.assert_allclose(drop(x).numpy(), x.numpy())


def test_activations_match_torch():
    import torch
    import torch.nn.functional as tF
    x = rng.randn(3, 4).astype("float32")
    pairs = [
        (F.relu, tF.relu), (F.gelu, tF.gelu), (F.silu, tF.silu),
        (F.sigmoid, torch.sigmoid), (F.softplus, tF.softplus),
        (F.elu, tF.elu), (F.leaky_relu, tF.leaky_relu),
        (F.hardswish, tF.hardswish), (F.log_sigmoid, tF.logsigmoid),
    ]
    for mine_fn, ref_fn in pairs:
        mine = mine_fn(paddle.to_tensor(x)).numpy()
        ref = ref_fn(torch.tensor(x)).numpy()
        np.testing.assert_allclose(mine, ref, rtol=1e-4, atol=1e-5)
    mine = F.softmax(paddle.to_tensor(x), axis=-1).numpy()
    ref = tF.softmax(torch.tensor(x), dim=-1).numpy()
    np.testing.assert_allclose(mine, ref, rtol=1e-5, atol=1e-6)


def test_sequential_and_containers():
    m = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    x = paddle.to_tensor(rng.rand(3, 4).astype("float32"))
    assert m(x).shape == [3, 2]
    assert len(list(m.named_parameters())) == 4
    ll = nn.LayerList([nn.Linear(2, 2) for _ in range(3)])
    ll.append(nn.Linear(2, 2))
    assert len(ll) == 4
    pl = nn.ParameterList([paddle.Parameter(np.zeros((2, 2), np.float32))])
    assert len(pl) == 1


def test_state_dict_roundtrip():
    m = nn.Sequential(nn.Linear(4, 4), nn.BatchNorm1D(4, data_format="NCL"))
    sd = m.state_dict()
    assert any("weight" in k for k in sd)
    assert any("_mean" in k for k in sd)
    m2 = nn.Sequential(nn.Linear(4, 4), nn.BatchNorm1D(4, data_format="NCL"))
    m2.set_state_dict({k: v.numpy() for k, v in sd.items()})
    for (k1, v1), (k2, v2) in zip(sorted(m.state_dict().items()),
                                  sorted(m2.state_dict().items())):
        np.testing.assert_allclose(v1.numpy(), v2.numpy())


def test_losses_vs_torch():
    import torch
    import torch.nn.functional as tF
    logits = rng.randn(5, 7).astype("float32")
    labels = rng.randint(0, 7, 5).astype("int64")
    mine = F.cross_entropy(paddle.to_tensor(logits),
                           paddle.to_tensor(labels)).numpy()
    ref = tF.cross_entropy(torch.tensor(logits), torch.tensor(labels)).numpy()
    np.testing.assert_allclose(mine, ref, rtol=1e-5)

    pred = rng.rand(4, 3).astype("float32")
    tgt = rng.rand(4, 3).astype("float32")
    np.testing.assert_allclose(
        F.mse_loss(paddle.to_tensor(pred), paddle.to_tensor(tgt)).numpy(),
        tF.mse_loss(torch.tensor(pred), torch.tensor(tgt)).numpy(), rtol=1e-6)
    np.testing.assert_allclose(
        F.binary_cross_entropy_with_logits(
            paddle.to_tensor(pred), paddle.to_tensor(tgt)).numpy(),
        tF.binary_cross_entropy_with_logits(
            torch.tensor(pred), torch.tensor(tgt)).numpy(), rtol=1e-5)


def test_cross_entropy_ignore_index_and_smoothing():
    import torch
    import torch.nn.functional as tF
    logits = rng.randn(6, 5).astype("float32")
    labels = np.array([0, 1, -100, 3, -100, 2], np.int64)
    mine = F.cross_entropy(paddle.to_tensor(logits), paddle.to_tensor(labels),
                           ignore_index=-100).numpy()
    ref = tF.cross_entropy(torch.tensor(logits), torch.tensor(labels),
                           ignore_index=-100).numpy()
    np.testing.assert_allclose(mine, ref, rtol=1e-5)


def test_rnn_lstm_gru():
    for cls, states in [(nn.SimpleRNN, 1), (nn.LSTM, 2), (nn.GRU, 1)]:
        rnn = cls(4, 8, num_layers=2)
        x = paddle.to_tensor(rng.rand(2, 5, 4).astype("float32"))
        out, h = rnn(x)
        assert out.shape == [2, 5, 8]
        if states == 2:
            assert h[0].shape == [2, 2, 8]
        out.sum().backward()
        assert rnn.weight_ih_l0.grad is not None


def test_lstm_vs_torch():
    import torch
    lstm = nn.LSTM(3, 5)
    tl = torch.nn.LSTM(3, 5, batch_first=True)
    with torch.no_grad():
        tl.weight_ih_l0.copy_(torch.tensor(lstm.weight_ih_l0.numpy()))
        tl.weight_hh_l0.copy_(torch.tensor(lstm.weight_hh_l0.numpy()))
        tl.bias_ih_l0.copy_(torch.tensor(lstm.bias_ih_l0.numpy()))
        tl.bias_hh_l0.copy_(torch.tensor(lstm.bias_hh_l0.numpy()))
    x = rng.rand(2, 7, 3).astype("float32")
    mine, (h, c) = lstm(paddle.to_tensor(x))
    ref, (th, tc) = tl(torch.tensor(x))
    np.testing.assert_allclose(mine.numpy(), ref.detach().numpy(),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(h.numpy(), th.detach().numpy(),
                               rtol=1e-4, atol=1e-5)


def test_bidirectional_rnn():
    rnn = nn.GRU(4, 6, direction="bidirect")
    x = paddle.to_tensor(rng.rand(2, 5, 4).astype("float32"))
    out, h = rnn(x)
    assert out.shape == [2, 5, 12]
    assert h.shape == [2, 2, 6]


def test_transformer_encoder():
    layer = nn.TransformerEncoderLayer(d_model=16, nhead=4,
                                       dim_feedforward=32, dropout=0.0)
    enc = nn.TransformerEncoder(layer, num_layers=2)
    x = paddle.to_tensor(rng.rand(2, 6, 16).astype("float32"))
    out = enc(x)
    assert out.shape == [2, 6, 16]
    out.sum().backward()
    grads = [p.grad for p in enc.parameters()]
    assert all(g is not None for g in grads)


def test_mha_cache():
    mha = nn.MultiHeadAttention(16, 4)
    x = paddle.to_tensor(rng.rand(2, 4, 16).astype("float32"))
    cache = mha.gen_cache(x)
    out, new_cache = mha(x, x, x, cache=cache)
    assert out.shape == [2, 4, 16]
    assert new_cache.k.shape[1] == 4
    step = paddle.to_tensor(rng.rand(2, 1, 16).astype("float32"))
    out2, cache2 = mha(step, step, step, cache=new_cache)
    assert cache2.k.shape[1] == 5


def test_grad_clip():
    clip = nn.ClipGradByGlobalNorm(0.5)
    w = paddle.Parameter(np.ones((4,), np.float32))
    (w * np.float32(100.0)).sum().backward()
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=[w],
                               grad_clip=clip)
    before = np.linalg.norm(w.grad.numpy())
    assert before > 0.5
    opt.step()
    # after clipping the applied update is bounded by lr * clip_norm
    delta = np.linalg.norm(w.numpy() - np.ones(4))
    assert delta <= 0.1 * 0.5 * 1.01


def test_conv_amp_backward():
    """AMP'd conv must be differentiable (the preferred_element_type=f32
    transpose broke with mixed bf16/f32 operands; caught by the ResNet
    bench)."""
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    paddle.seed(0)
    conv = nn.Conv2D(3, 8, 3)
    x = paddle.to_tensor(np.random.rand(2, 3, 8, 8).astype("float32"))
    with paddle.amp.auto_cast(enable=True, dtype="bfloat16"):
        loss = conv(x).sum()
    loss.backward()
    g = conv.weight._grad
    assert g is not None and np.isfinite(np.asarray(g)).all()
