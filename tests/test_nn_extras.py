"""Layer-class tail: RNN/BiRNN wrappers, SpectralNorm, CTC loss (vs brute
force), and the thin class fronts.
"""
import itertools

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.core.tensor import Tensor


def _ctc_brute(logits, labels, blank=0):
    """Enumerate all alignments for one sequence: logits [T, C],
    labels [S]."""
    T, C = logits.shape
    e = np.exp(logits - logits.max(-1, keepdims=True))
    p = e / e.sum(-1, keepdims=True)

    def collapse(path):
        out = []
        prev = None
        for s in path:
            if s != prev:
                if s != blank:
                    out.append(s)
                prev = s
        return tuple(out)

    total = 0.0
    for path in itertools.product(range(C), repeat=T):
        if collapse(path) == tuple(labels):
            total += np.prod([p[t, s] for t, s in enumerate(path)])
    return -np.log(total)


class TestCTCLoss:
    def test_matches_brute_force(self):
        rng = np.random.RandomState(0)
        T, B, C = 4, 2, 3
        logits = rng.randn(T, B, C).astype(np.float32)
        labels = np.array([[1, 2], [2, 1]], np.int64)
        out = F.ctc_loss(Tensor(logits), Tensor(labels),
                         Tensor(np.array([T, T], np.int64)),
                         Tensor(np.array([2, 2], np.int64)),
                         blank=0, reduction="none")
        got = np.asarray(out.numpy())
        want = [_ctc_brute(logits[:, b], labels[b]) for b in range(B)]
        np.testing.assert_allclose(got, want, rtol=1e-4)

    def test_ragged_lengths(self):
        rng = np.random.RandomState(1)
        T, B, C = 5, 2, 3
        logits = rng.randn(T, B, C).astype(np.float32)
        labels = np.array([[1, 0], [2, 1]], np.int64)  # row 0: one label
        out = F.ctc_loss(Tensor(logits), Tensor(labels),
                         Tensor(np.array([3, 5], np.int64)),
                         Tensor(np.array([1, 2], np.int64)),
                         reduction="none")
        got = np.asarray(out.numpy())
        want0 = _ctc_brute(logits[:3, 0], [1])
        want1 = _ctc_brute(logits[:5, 1], [2, 1])
        np.testing.assert_allclose(got, [want0, want1], rtol=1e-4)

    def test_differentiable_and_class(self):
        rng = np.random.RandomState(2)
        logits = Tensor(rng.randn(4, 2, 3).astype(np.float32),
                        stop_gradient=False)
        loss = nn.CTCLoss(blank=0)(
            logits, Tensor(np.array([[1, 2], [2, 1]], np.int64)),
            Tensor(np.array([4, 4], np.int64)),
            Tensor(np.array([2, 2], np.int64)))
        loss.backward()
        g = np.asarray(logits.grad.numpy())
        assert np.isfinite(g).all() and np.abs(g).sum() > 0


class TestRNNWrappers:
    def test_rnn_cell_wrapper_matches_manual(self):
        paddle.seed(0)
        cell = nn.SimpleRNNCell(4, 5)
        rnn = nn.RNN(cell)
        x = Tensor(np.random.RandomState(0).rand(2, 3, 4).astype(np.float32))
        y, st = rnn(x)
        assert list(y.shape) == [2, 3, 5]
        # manual unroll
        h = None
        for t in range(3):
            o, h = cell(x[:, t], h)
        np.testing.assert_allclose(np.asarray(y[:, -1].numpy()),
                                   np.asarray(o.numpy()), rtol=1e-5)

    def test_birnn_concats(self):
        paddle.seed(0)
        rnn = nn.BiRNN(nn.GRUCell(4, 5), nn.GRUCell(4, 5))
        x = Tensor(np.random.RandomState(1).rand(2, 3, 4).astype(np.float32))
        y, (sf, sb) = rnn(x)
        assert list(y.shape) == [2, 3, 10]


class TestSpectralNorm:
    def test_normalizes_spectral_radius(self):
        rng = np.random.RandomState(3)
        w = rng.randn(6, 4).astype(np.float32) * 3.0
        sn = nn.SpectralNorm(w.shape, power_iters=30)
        out = sn(Tensor(w))
        sigma = np.linalg.svd(np.asarray(out.numpy()), compute_uv=False)[0]
        np.testing.assert_allclose(sigma, 1.0, rtol=1e-3)


class TestThinFronts:
    def test_unfold_alpha_upsampling(self):
        x = Tensor(np.random.RandomState(4).rand(1, 2, 4, 4)
                   .astype(np.float32))
        assert list(nn.Unfold(2)(x).shape) == [1, 8, 9]
        up = nn.UpsamplingNearest2D(scale_factor=2)(x)
        assert list(up.shape) == [1, 2, 8, 8]
        ad = nn.AlphaDropout(p=0.3)
        ad.eval()
        np.testing.assert_allclose(np.asarray(ad(x).numpy()),
                                   np.asarray(x.numpy()))

    def test_embedding_losses(self):
        a = Tensor(np.random.RandomState(5).rand(4, 8).astype(np.float32))
        b = Tensor(np.random.RandomState(6).rand(4, 8).astype(np.float32))
        y = Tensor(np.array([1, -1, 1, -1], np.int64))
        out = nn.CosineEmbeddingLoss(margin=0.1)(a, b, y)
        assert np.isfinite(float(out.numpy()))
        n = Tensor(np.random.RandomState(7).rand(4, 8).astype(np.float32))
        out2 = nn.TripletMarginLoss()(a, b, n)
        assert np.isfinite(float(out2.numpy()))


class TestReviewFixes:
    def test_ctc_mean_normalizes_by_label_length(self):
        rng = np.random.RandomState(8)
        T, B, C = 4, 2, 3
        logits = rng.randn(T, B, C).astype(np.float32)
        labels = np.array([[1, 0], [2, 1]], np.int64)
        lb = np.array([1, 2], np.int64)
        per = np.asarray(F.ctc_loss(
            Tensor(logits), Tensor(labels), Tensor(np.array([T, T])),
            Tensor(lb), reduction="none").numpy())
        mean = float(F.ctc_loss(
            Tensor(logits), Tensor(labels), Tensor(np.array([T, T])),
            Tensor(lb), reduction="mean").numpy())
        np.testing.assert_allclose(mean, np.mean(per / lb), rtol=1e-5)

    def test_bilinear_align_corners(self):
        x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
        out = nn.UpsamplingBilinear2D(size=[7, 7])(Tensor(x))
        o = np.asarray(out.numpy())[0, 0]
        # corners map exactly onto input corners
        np.testing.assert_allclose([o[0, 0], o[0, -1], o[-1, 0], o[-1, -1]],
                                   [0.0, 3.0, 12.0, 15.0], atol=1e-5)
        # center of a linear ramp stays linear
        np.testing.assert_allclose(o[0, 3], 1.5, atol=1e-5)

    def test_reverse_rnn_respects_sequence_length(self):
        paddle.seed(1)
        cell = nn.SimpleRNNCell(3, 4)
        rnn = nn.RNN(cell, is_reverse=True)
        rng = np.random.RandomState(9)
        x = rng.rand(2, 5, 3).astype(np.float32)
        x_pad = x.copy()
        x_pad[0, 3:] = 99.0  # garbage in the padding of sequence 0 (len 3)
        y, st = rnn(Tensor(x_pad),
                    sequence_length=Tensor(np.array([3, 5], np.int64)))
        # reference: run reversed over ONLY the valid region
        h = None
        for t in range(2, -1, -1):
            o, h = cell(Tensor(x[0:1, t]), h)
        np.testing.assert_allclose(np.asarray(y[0, 0].numpy()),
                                   np.asarray(o.numpy())[0], rtol=1e-5)
        # outputs past the valid length are zeroed
        assert np.abs(np.asarray(y[0, 3:].numpy())).sum() == 0

    def test_align_corners_linear_trilinear_nhwc(self):
        # 1-D linear, NCW: endpoints of a ramp map onto input endpoints
        ramp = np.arange(5, dtype=np.float32).reshape(1, 1, 5)
        o = np.asarray(F.interpolate(Tensor(ramp), size=[9], mode="linear",
                                     align_corners=True,
                                     data_format="NCW").numpy())[0, 0]
        np.testing.assert_allclose(o, np.linspace(0, 4, 9), atol=1e-5)
        # 3-D trilinear, NCDHW
        x = np.arange(8, dtype=np.float32).reshape(1, 1, 2, 2, 2)
        o3 = np.asarray(F.interpolate(Tensor(x), size=[3, 3, 3],
                                      mode="trilinear", align_corners=True,
                                      data_format="NCDHW").numpy())[0, 0]
        np.testing.assert_allclose(
            [o3[0, 0, 0], o3[-1, -1, -1], o3[1, 1, 1]],
            [0.0, 7.0, 3.5], atol=1e-5)
        # 2-D bilinear, NHWC layout
        xh = np.arange(16, dtype=np.float32).reshape(1, 4, 4, 1)
        oh = np.asarray(F.interpolate(Tensor(xh), size=[7, 7],
                                      mode="bilinear", align_corners=True,
                                      data_format="NHWC").numpy())[0, :, :, 0]
        np.testing.assert_allclose(
            [oh[0, 0], oh[0, -1], oh[-1, 0], oh[-1, -1]],
            [0.0, 3.0, 12.0, 15.0], atol=1e-5)

    def test_interpolate_scale_factor_channels_first_1d_3d(self):
        # NCW with scale_factor: size must derive from W, not C
        x = np.arange(10, dtype=np.float32).reshape(1, 2, 5)
        o = np.asarray(F.interpolate(Tensor(x), scale_factor=2, mode="linear",
                                     align_corners=True,
                                     data_format="NCW").numpy())
        assert o.shape == (1, 2, 10), o.shape
        np.testing.assert_allclose(o[0, 0, [0, -1]], [0.0, 4.0], atol=1e-5)
        # NCDHW nearest with scale_factor
        x3 = np.ones((1, 3, 2, 4, 4), np.float32)
        o3 = np.asarray(F.interpolate(Tensor(x3), scale_factor=2,
                                      mode="nearest",
                                      data_format="NCDHW").numpy())
        assert o3.shape == (1, 3, 4, 8, 8), o3.shape

    def test_beam_search_decoder_optimal_path(self):
        V = 4
        trans = np.log(np.array([
            [.05, .55, .4, 0.0],
            [.01, .01, .08, .9],
            [.01, .01, .01, .97],
            [1e-9, 1e-9, 1e-9, 1.0],
        ], np.float32) + 1e-12)

        class ToyCell:
            def __call__(self, inputs, states):
                tok = np.asarray(inputs.numpy()).astype(int)
                return Tensor(trans[tok]), states

        dec = nn.BeamSearchDecoder(ToyCell(), start_token=0, end_token=3,
                                   beam_size=3)
        init = Tensor(np.zeros((2, 1), np.float32))
        (ids, scores), _, lens = nn.dynamic_decode(dec, init, max_step_num=6)
        # brute-force optimum from token 0 is path (1, 3)
        np.testing.assert_array_equal(ids.numpy()[0, :2, 0], [1, 3])
        np.testing.assert_allclose(scores.numpy()[0, 0],
                                   trans[0, 1] + trans[1, 3], rtol=1e-5)

    def test_beam_search_with_gru_cell(self):
        paddle.seed(0)
        cell = nn.GRUCell(8, 16)
        emb = nn.Embedding(12, 8)
        proj = nn.Linear(16, 12)
        dec = nn.BeamSearchDecoder(cell, start_token=1, end_token=2,
                                   beam_size=4, embedding_fn=emb,
                                   output_fn=proj)
        init = Tensor(np.zeros((3, 16), np.float32))
        (ids, scores), states, lens = nn.dynamic_decode(dec, init,
                                                        max_step_num=5)
        assert ids.shape[0] == 3 and ids.shape[2] == 4
        assert np.isfinite(scores.numpy()).all()
        # scores sorted descending per batch row
        s = scores.numpy()
        assert (np.diff(s, axis=1) <= 1e-5).all()

    def test_beam_search_lengths_follow_parents(self):
        # beams reorder across steps; lengths must track each surviving
        # beam's parent chain and count the end-emitting step
        V = 3  # {a=0, b=1, END=2}
        step_logits = [
            np.log(np.array([[.6, .39, .01]] * 2, np.float32)),
            np.log(np.array([[.1, .1, .8],    # from beam following a
                             [.45, .45, .1]] , np.float32)),
            np.log(np.array([[.1, .1, .8]] * 2, np.float32)),
            np.log(np.array([[.05, .05, .9]] * 2, np.float32)),
        ]

        class SeqCell:
            def __init__(self):
                self.t = 0

            def __call__(self, inputs, states):
                tok = np.asarray(inputs.numpy()).astype(int) % 2
                out = step_logits[min(self.t, 3)][tok]
                self.t += 1
                return Tensor(out), states

        dec = nn.BeamSearchDecoder(SeqCell(), start_token=0, end_token=2,
                                   beam_size=2)
        init = Tensor(np.zeros((1, 1), np.float32))
        (ids, scores), _, lens = nn.dynamic_decode(dec, init, max_step_num=4)
        idv = ids.numpy()[0]          # [T, beam]
        lnv = lens.numpy()[0]         # [beam]
        # every beam's reported length equals its actual token count
        # through (and including) the first END in the backtraced path
        for b in range(2):
            path = idv[:, b]
            end_pos = np.where(path == 2)[0]
            true_len = (end_pos[0] + 1) if len(end_pos) else len(path)
            assert lnv[b] == true_len, (path, lnv[b], true_len)
