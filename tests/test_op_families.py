"""New op families: sequence tail ops, psroi_pool / generate_proposals,
SelectedRows sparse gradients, functional auc.

References: operators/sequence_ops/, detection/psroi_pool_op.cc,
detection/generate_proposals_op.cc, framework/selected_rows.h:41,
operators/optimizers (sparse branches), operators/metrics/auc_op.cc.
"""
import numpy as np
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.core.selected_rows import SelectedRows
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.ops import sequence as seq


class TestSequenceTail:
    def _rb(self, rows):
        return seq.RaggedBatch.from_list([np.asarray(r, np.float32)
                                          for r in rows])

    def test_sequence_concat(self):
        a = self._rb([[1, 2], [3]])
        b = self._rb([[4], [5, 6, 7]])
        out = seq.sequence_concat([a, b]).to_list()
        np.testing.assert_allclose(out[0], [1, 2, 4])
        np.testing.assert_allclose(out[1], [3, 5, 6, 7])

    def test_sequence_slice(self):
        x = self._rb([[1, 2, 3, 4], [5, 6, 7]])
        out = seq.sequence_slice(x, np.array([1, 0]), np.array([2, 1]))
        rows = out.to_list()
        np.testing.assert_allclose(rows[0], [2, 3])
        np.testing.assert_allclose(rows[1], [5])

    def test_sequence_expand_as(self):
        x = Tensor(np.array([[1.0], [2.0]], np.float32))
        y = self._rb([[0, 0, 0], [0, 0]])
        out = seq.sequence_expand_as(x, y)
        np.testing.assert_allclose(np.asarray(out.numpy()).reshape(-1),
                                   [1, 1, 1, 2, 2])

    def test_first_last_step(self):
        x = self._rb([[1, 2, 3], [4, 5]])
        first = seq.sequence_first_step(x)
        last = seq.sequence_last_step(x)
        np.testing.assert_allclose(first.numpy(), [1, 4])
        np.testing.assert_allclose(last.numpy(), [3, 5])

    def test_sequence_enumerate(self):
        data = Tensor(np.array([[1, 2, 3, 4]], np.int32))
        lens = Tensor(np.array([4], np.int32))
        out = seq.sequence_enumerate(
            seq.RaggedBatch(data, lens), win_size=2, pad_value=0)
        got = np.asarray(out.numpy())[0]
        np.testing.assert_array_equal(
            got, [[1, 2], [2, 3], [3, 4], [4, 0]])

    def test_sequence_erase(self):
        x = seq.RaggedBatch.from_list(
            [np.array([1, 2, 2, 3], np.int64), np.array([2, 4], np.int64)])
        out = seq.sequence_erase(x, [2]).to_list()
        np.testing.assert_array_equal(out[0], [1, 3])
        np.testing.assert_array_equal(out[1], [4])


class TestPSRoIPool:
    def test_matches_numpy_reference(self):
        rng = np.random.RandomState(0)
        ph = pw = 2
        c_out = 3
        x = rng.rand(1, c_out * ph * pw, 8, 8).astype(np.float32)
        boxes = np.array([[0.0, 0.0, 4.0, 4.0], [2.0, 2.0, 7.0, 6.0]],
                         np.float32)
        out = paddle.vision.ops.psroi_pool(
            Tensor(x), Tensor(boxes), Tensor(np.array([2], np.int32)),
            output_size=2, spatial_scale=1.0)
        got = np.asarray(out.numpy())
        assert got.shape == (2, c_out, ph, pw)

        # independent numpy reference (psroi_pool_op.cc math)
        want = np.zeros_like(got)
        for r, (x1, y1, x2, y2) in enumerate(boxes):
            rh = max(y2 - y1, 0.1) / ph
            rw = max(x2 - x1, 0.1) / pw
            for c in range(c_out):
                for i in range(ph):
                    for j in range(pw):
                        hs = int(np.clip(np.floor(y1 + i * rh), 0, 8))
                        he = int(np.clip(np.ceil(y1 + (i + 1) * rh), 0, 8))
                        ws = int(np.clip(np.floor(x1 + j * rw), 0, 8))
                        we = int(np.clip(np.ceil(x1 + (j + 1) * rw), 0, 8))
                        ch = c * ph * pw + i * pw + j
                        region = x[0, ch, hs:he, ws:we]
                        area = max((he - hs) * (we - ws), 1)
                        want[r, c, i, j] = region.sum() / area
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    def test_gradients_flow(self):
        x = Tensor(np.random.RandomState(1).rand(1, 4, 4, 4)
                   .astype(np.float32), stop_gradient=False)
        boxes = Tensor(np.array([[0.0, 0.0, 3.0, 3.0]], np.float32))
        out = paddle.vision.ops.psroi_pool(
            x, boxes, Tensor(np.array([1], np.int32)), output_size=2)
        out.sum().backward()
        assert x.grad is not None
        assert np.abs(x.grad.numpy()).sum() > 0


class TestGenerateProposals:
    def test_shapes_and_ordering(self):
        rng = np.random.RandomState(2)
        N, A, H, W = 1, 3, 4, 4
        scores = rng.rand(N, A, H, W).astype(np.float32)
        deltas = (rng.rand(N, 4 * A, H, W).astype(np.float32) - 0.5) * 0.2
        # simple dense anchors
        ys, xs = np.meshgrid(np.arange(H), np.arange(W), indexing="ij")
        anchors = np.stack([xs * 4, ys * 4, xs * 4 + 8, ys * 4 + 8],
                           axis=-1).astype(np.float32)
        anchors = np.repeat(anchors[:, :, None, :], A, axis=2)
        variances = np.ones_like(anchors)
        rois, s, num = paddle.vision.ops.generate_proposals(
            Tensor(scores), Tensor(deltas),
            Tensor(np.array([[16.0, 16.0]], np.float32)),
            Tensor(anchors), Tensor(variances),
            pre_nms_top_n=20, post_nms_top_n=5, nms_thresh=0.7,
            min_size=1.0, return_rois_num=True)
        r = np.asarray(rois.numpy())
        sv = np.asarray(s.numpy())
        n0 = int(np.asarray(num.numpy())[0])
        assert r.shape == (1, 5, 4) and sv.shape == (1, 5)
        assert 1 <= n0 <= 5
        kept = sv[0, :n0]
        assert np.all(np.diff(kept) <= 1e-6)  # score-descending
        # boxes clipped to the image
        assert r.min() >= 0 and r.max() <= 15.0


class TestSelectedRows:
    def test_merge_add_and_to_dense(self):
        sr = SelectedRows(np.array([1, 3, 1]),
                          np.array([[1.0, 1.0], [2.0, 2.0], [3.0, 3.0]],
                                   np.float32), height=5)
        merged = sr.merge_add()
        dense = np.asarray(merged.to_dense())
        want = np.zeros((5, 2), np.float32)
        want[1] = [4.0, 4.0]
        want[3] = [2.0, 2.0]
        np.testing.assert_allclose(dense, want)

    def test_sparse_embedding_grad_is_selected_rows(self):
        w = Tensor(np.random.RandomState(0).rand(10, 4).astype(np.float32),
                   stop_gradient=False)
        ids = Tensor(np.array([1, 3, 1], np.int64))
        out = F.embedding(ids, w, sparse=True)
        out.sum().backward()
        assert isinstance(w._grad, SelectedRows)
        dense = np.asarray(w._grad.to_dense())
        want = np.zeros((10, 4), np.float32)
        want[1] = 2.0  # id 1 looked up twice
        want[3] = 1.0
        np.testing.assert_allclose(dense, want)

    def test_sparse_matches_dense_gradient(self):
        rng = np.random.RandomState(3)
        wv = rng.rand(8, 4).astype(np.float32)
        ids = np.array([0, 2, 2, 5], np.int64)
        for sparse in (False, True):
            w = Tensor(wv.copy(), stop_gradient=False)
            out = F.embedding(Tensor(ids), w, sparse=sparse)
            (out * out).sum().backward()
            g = w._grad.to_dense() if sparse else w._grad
            if sparse:
                got_sparse = np.asarray(g)
            else:
                got_dense = np.asarray(g)
        np.testing.assert_allclose(got_sparse, got_dense, rtol=1e-5)

    def test_lazy_adam_touches_only_looked_up_rows(self):
        """reference: adam_op.h lazy_mode — untouched rows (and moments)
        must not move."""
        rng = np.random.RandomState(4)
        wv = rng.rand(6, 3).astype(np.float32)
        w = Tensor(wv.copy(), stop_gradient=False)
        w.persistable = True
        opt = paddle.optimizer.Adam(learning_rate=0.1, parameters=[w])
        out = F.embedding(Tensor(np.array([1, 4], np.int64)), w, sparse=True)
        out.sum().backward()
        opt.step()
        got = np.asarray(w._value)
        changed = np.abs(got - wv).sum(axis=1) > 0
        np.testing.assert_array_equal(changed,
                                      [False, True, False, False, True,
                                       False])
        # moment accumulators: only rows 1 and 4 move
        m1 = np.asarray(opt._get_accumulator("moment1", w)._value)
        assert np.abs(m1[[0, 2, 3, 5]]).sum() == 0
        assert np.abs(m1[[1, 4]]).sum() > 0

    def test_row0_with_duplicates_not_clobbered(self):
        """merge_add's padding rows map to index 0 on the gather side; the
        scatter must DROP them or row 0's update gets overwritten with its
        stale value (caught by review; ids [0, 4, 4])."""
        rng = np.random.RandomState(11)
        wv = rng.rand(6, 3).astype(np.float32)
        ids = np.array([0, 4, 4], np.int64)
        results = {}
        for sparse in (False, True):
            w = Tensor(wv.copy(), stop_gradient=False)
            opt = paddle.optimizer.SGD(learning_rate=0.5, parameters=[w])
            out = F.embedding(Tensor(ids), w, sparse=sparse)
            out.sum().backward()
            opt.step()
            results[sparse] = np.asarray(w._value)
        np.testing.assert_allclose(results[True], results[False], rtol=1e-6)

    def test_sparse_grads_respect_global_norm_clip(self):
        """ClipGradByGlobalNorm must bound sparse updates too."""
        w = Tensor(np.zeros((6, 3), np.float32), stop_gradient=False)
        clip = nn.ClipGradByGlobalNorm(0.001)
        opt = paddle.optimizer.SGD(learning_rate=1.0, parameters=[w],
                                   grad_clip=clip)
        out = F.embedding(Tensor(np.array([1], np.int64)), w, sparse=True)
        (out * 100.0).sum().backward()
        opt.step()
        assert np.abs(np.asarray(w._value)).max() <= 0.002

    def test_sparse_grads_with_grad_scaler(self):
        """AMP GradScaler.unscale_ must handle SelectedRows grads."""
        w = Tensor(np.random.RandomState(12).rand(6, 3).astype(np.float32),
                   stop_gradient=False)
        opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=[w])
        scaler = paddle.amp.GradScaler(init_loss_scaling=128.0)
        out = F.embedding(Tensor(np.array([2, 5], np.int64)), w, sparse=True)
        loss = out.sum()
        w0 = np.asarray(w._value).copy()
        scaler.scale(loss).backward()
        scaler.step(opt)
        assert not np.allclose(np.asarray(w._value), w0)

    def test_sparse_sgd_matches_dense_sgd(self):
        rng = np.random.RandomState(5)
        wv = rng.rand(6, 3).astype(np.float32)
        ids = np.array([1, 4, 1], np.int64)

        results = {}
        for sparse in (False, True):
            w = Tensor(wv.copy(), stop_gradient=False)
            opt = paddle.optimizer.SGD(learning_rate=0.5, parameters=[w])
            out = F.embedding(Tensor(ids), w, sparse=sparse)
            (out * 2.0).sum().backward()
            opt.step()
            results[sparse] = np.asarray(w._value)
        np.testing.assert_allclose(results[True], results[False], rtol=1e-6)


class TestAucOp:
    def test_perfect_and_streaming(self):
        preds = np.array([0.9, 0.8, 0.2, 0.1], np.float32)
        labels = np.array([1, 1, 0, 0], np.int64)
        val, sp, sn = paddle.metric.auc(preds, labels)
        assert abs(float(val.numpy()) - 1.0) < 1e-6
        # streaming: feed stats back with the inverse batch → AUC 0.5
        val2, _, _ = paddle.metric.auc(preds, 1 - labels,
                                       stat_pos=sp, stat_neg=sn)
        assert abs(float(val2.numpy()) - 0.5) < 1e-6

    def test_matches_metric_class(self):
        rng = np.random.RandomState(6)
        preds = rng.rand(200).astype(np.float32)
        labels = (rng.rand(200) > 0.5).astype(np.int64)
        m = paddle.metric.Auc()
        m.update(preds, labels)
        val, _, _ = paddle.metric.auc(preds, labels,
                                      num_thresholds=m.num_thresholds)
        np.testing.assert_allclose(float(val.numpy()), m.accumulate(),
                                   rtol=1e-6)
