"""paddle.distribution + text/classic datasets + metrics.

Mirrors reference tests test_distribution.py, text dataset tests, and
metric tests from fluid/tests/unittests.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distribution import Categorical, Normal, Uniform


def test_normal_log_prob_entropy_kl():
    n = Normal(0.0, 1.0)
    x = paddle.to_tensor(np.array([0.0, 1.0, -2.0], np.float32))
    lp = np.asarray(n.log_prob(x).numpy())
    expect = -0.5 * np.array([0.0, 1.0, 4.0]) - 0.5 * np.log(2 * np.pi)
    np.testing.assert_allclose(lp, expect, rtol=1e-5)
    ent = float(np.asarray(n.entropy().numpy()))
    np.testing.assert_allclose(ent, 0.5 * np.log(2 * np.pi) + 0.5, rtol=1e-5)
    m = Normal(1.0, 2.0)
    kl = float(np.asarray(n.kl_divergence(m).numpy()))
    # closed form: log(s1/s0) + (s0^2 + (m0-m1)^2)/(2 s1^2) - 1/2
    expect_kl = np.log(2.0) + (1.0 + 1.0) / 8.0 - 0.5
    np.testing.assert_allclose(kl, expect_kl, rtol=1e-5)


def test_normal_sample_statistics():
    paddle.seed(0)
    n = Normal(3.0, 0.5)
    s = np.asarray(n.sample((20000,)).numpy())
    assert abs(s.mean() - 3.0) < 0.02
    assert abs(s.std() - 0.5) < 0.02


def test_uniform_log_prob_and_sample():
    u = Uniform(1.0, 3.0)
    x = paddle.to_tensor(np.array([2.0, 0.0], np.float32))
    lp = np.asarray(u.log_prob(x).numpy())
    np.testing.assert_allclose(lp[0], -np.log(2.0), rtol=1e-6)
    assert lp[1] == -np.inf
    paddle.seed(1)
    s = np.asarray(u.sample((5000,)).numpy())
    assert s.min() >= 1.0 and s.max() < 3.0
    assert abs(s.mean() - 2.0) < 0.05
    ent = float(np.asarray(u.entropy().numpy()))
    np.testing.assert_allclose(ent, np.log(2.0), rtol=1e-6)


def test_categorical():
    logits = np.log(np.array([0.1, 0.2, 0.7], np.float32))
    c = Categorical(logits)
    probs = np.exp(np.asarray(
        c.log_prob(paddle.to_tensor(np.arange(3))).numpy()))
    np.testing.assert_allclose(probs, [0.1, 0.2, 0.7], rtol=1e-5)
    ent = float(np.asarray(c.entropy().numpy()))
    np.testing.assert_allclose(
        ent, -(0.1 * np.log(0.1) + 0.2 * np.log(0.2) + 0.7 * np.log(0.7)),
        rtol=1e-5)
    c2 = Categorical(np.zeros(3, np.float32))
    kl = float(np.asarray(c.kl_divergence(c2).numpy()))
    assert kl > 0
    paddle.seed(0)
    s = np.asarray(c.sample((4000,)).numpy())
    freq = np.bincount(s, minlength=3) / len(s)
    np.testing.assert_allclose(freq, [0.1, 0.2, 0.7], atol=0.03)


def test_distribution_grad_flows():
    mu = paddle.to_tensor(np.float32(0.5))
    mu.stop_gradient = False
    n = Normal(mu, 1.0)
    lp = n.log_prob(paddle.to_tensor(np.float32(1.5)))
    lp.backward()
    # d/dmu log N(x|mu,1) = (x - mu) = 1.0
    np.testing.assert_allclose(float(np.asarray(mu.grad.numpy())), 1.0,
                               rtol=1e-5)


def test_text_datasets_schema():
    from paddle_tpu.text import Imdb, Imikolov, UCIHousing, WMT16, Conll05st
    imdb = Imdb(mode="train")
    doc, label = imdb[0]
    assert doc.dtype == np.int64 and label in (0, 1)
    ng = Imikolov(mode="test", window_size=5)
    assert len(ng[0]) == 5
    uci = UCIHousing(mode="train")
    x, y = uci[0]
    assert x.shape == (13,) and y.shape == (1,)
    wmt = WMT16(mode="test")
    src, trg, nxt = wmt[0]
    assert trg[0] == 0 and nxt[-1] == 1 and len(trg) == len(nxt)
    srl = Conll05st(mode="train")
    w, p, l = srl[0]
    assert len(w) == len(p) == len(l)


def test_classic_dataset_readers():
    from paddle_tpu import dataset
    r = dataset.uci_housing.train()()
    x, y = next(iter(r))
    assert x.shape == (13,)
    r10 = dataset.cifar.test10()()
    img, label = next(iter(r10))
    assert img.shape[0] == 3


def test_uci_housing_trains():
    """A linear regressor must fit the synthetic housing data (signal check)."""
    from paddle_tpu.text import UCIHousing
    ds = UCIHousing(mode="train")
    lin = paddle.nn.Linear(13, 1)
    opt = paddle.optimizer.Adam(parameters=lin.parameters(),
                                learning_rate=0.05)
    loader = paddle.io.DataLoader(ds, batch_size=64, shuffle=True)
    first = last = None
    for epoch in range(12):
        for x, y in loader:
            loss = paddle.nn.functional.mse_loss(lin(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            if first is None:
                first = float(loss.numpy())
    last = float(loss.numpy())
    assert last < first * 0.2, (first, last)


def test_viterbi_decode():
    from paddle_tpu.text import viterbi_decode
    # hand-checkable 2-tag chain
    pot = np.array([[[1.0, 0.0], [0.0, 1.0], [1.0, 0.0]]], np.float32)
    trans = np.array([[1.0, 0.0], [0.0, 1.0]], np.float32)
    score, path = viterbi_decode(paddle.to_tensor(pot),
                                 paddle.to_tensor(trans),
                                 include_bos_eos_tag=False)
    path = np.asarray(path.numpy())[0]
    assert path.shape == (3,)
    # brute-force check
    best, best_p = -1e9, None
    for a in (0, 1):
        for b in (0, 1):
            for c in (0, 1):
                s = pot[0, 0, a] + pot[0, 1, b] + pot[0, 2, c] \
                    + trans[a, b] + trans[b, c]
                if s > best:
                    best, best_p = s, [a, b, c]
    assert list(path) == best_p
    np.testing.assert_allclose(float(np.asarray(score.numpy())[0]), best,
                               rtol=1e-5)


def test_viterbi_decode_lengths_and_bos_eos():
    from paddle_tpu.text import viterbi_decode
    rng = np.random.RandomState(7)
    B, T, N = 3, 6, 5  # tags 3,4 are BOS/EOS when include_bos_eos_tag
    pot = rng.randn(B, T, N).astype(np.float32)
    trans = rng.randn(N, N).astype(np.float32)
    lens = np.array([6, 3, 1], np.int64)

    def brute(b, L, with_tag):
        import itertools
        best, best_p = -1e18, None
        for tags in itertools.product(range(N), repeat=L):
            s = pot[b, 0, tags[0]]
            if with_tag:
                s += trans[N - 2, tags[0]]
            for t in range(1, L):
                s += trans[tags[t - 1], tags[t]] + pot[b, t, tags[t]]
            if with_tag:
                s += trans[tags[L - 1], N - 1]
            if s > best:
                best, best_p = s, list(tags)
        return best, best_p

    for with_tag in (False, True):
        score, path = viterbi_decode(paddle.to_tensor(pot),
                                     paddle.to_tensor(trans),
                                     lengths=paddle.to_tensor(lens),
                                     include_bos_eos_tag=with_tag)
        score = np.asarray(score.numpy())
        path = np.asarray(path.numpy())
        for b in range(B):
            L = int(lens[b])
            want_s, want_p = brute(b, L, with_tag)
            np.testing.assert_allclose(score[b], want_s, rtol=1e-5)
            assert list(path[b, :L]) == want_p, (b, with_tag)


def test_metrics_auc_precision_recall():
    from paddle_tpu.metric import Auc, Precision, Recall
    preds = np.array([[0.2, 0.8], [0.9, 0.1], [0.4, 0.6], [0.7, 0.3]],
                     np.float32)
    labels = np.array([[1], [0], [1], [1]], np.int64)
    auc = Auc()
    auc.update(preds, labels)
    assert 0.0 <= auc.accumulate() <= 1.0
    p = Precision()
    p.update(preds[:, 1], labels[:, 0])
    assert 0.0 <= p.accumulate() <= 1.0
    r = Recall()
    r.update(preds[:, 1], labels[:, 0])
    assert 0.0 <= r.accumulate() <= 1.0


def test_crf_decoding_masks_padded_slots():
    """reference crf_decoding_op.h:63-70 forces 0 beyond each sequence
    length — both in the decoded path and in label-comparison mode."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import text

    rng = np.random.RandomState(3)
    B, T, N = 2, 5, 4
    emis = paddle.to_tensor(rng.rand(B, T, N).astype("float32"))
    trans = paddle.to_tensor(rng.rand(N + 2, N).astype("float32"))
    lens = paddle.to_tensor(np.array([3, 5], dtype=np.int64))
    path = text.crf_decoding(emis, trans, length=lens).numpy()
    assert (path[0, 3:] == 0).all()

    # label mode: a padded label equal to the carried tag must not score 1
    lab = paddle.to_tensor(np.zeros((B, T), dtype=np.int64))
    ok = text.crf_decoding(emis, trans, label=lab, length=lens).numpy()
    assert (ok[0, 3:] == 0).all()
