"""Elastic fault tolerance: shard-aware step checkpointing.

The contract under test (ISSUE 7): checkpoint -> kill -> restore resumes
BITWISE-equal (fp32) to an uninterrupted run for replicated, ZeRO-1,
ZeRO-2 and ZeRO-3 under gradient-accumulation windows on the 8-device
CPU mesh; a mid-window (accumulated-but-unconsumed grads) restore holds;
restore at a DIFFERENT dp degree re-flattens the shards (elastic
resume); and a kill injected at every checkpoint write stage never
leaves a manifest restore accepts (crash-consistency sweep).
"""
import gc
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import checkpoint, monitor, nn
from paddle_tpu.checkpoint import core as ckpt_core
from paddle_tpu.distributed import parallel_env
from paddle_tpu.testing import faults

DP = 8
K, ACC = 2, 2

rng = np.random.RandomState(7)
X1 = rng.rand(K, 16, 16).astype("float32")
Y1 = rng.randint(0, 8, (K, 16)).astype("int64")
X2 = rng.rand(K, 16, 16).astype("float32")
Y2 = rng.randint(0, 8, (K, 16)).astype("int64")


@pytest.fixture(autouse=True)
def _clean():
    faults.reset()
    yield
    faults.reset()
    parallel_env.set_mesh(None)
    gc.collect()  # drop sharded stores before the next test's mesh


def _build(stage, dp=DP, seed=11, acc=ACC, scaler=False):
    import jax
    mesh = parallel_env.make_mesh({"dp": dp}, devices=jax.devices()[:dp])
    parallel_env.set_mesh(mesh)
    paddle.seed(seed)
    m = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 8))
    opt = paddle.optimizer.AdamW(parameters=m.parameters(),
                                 learning_rate=0.05)
    if stage:
        opt._zero_enable(axis="dp", stage=stage)
    sc = paddle.amp.GradScaler(init_loss_scaling=128.0) if scaler else None

    def one(xb, yb):
        loss = nn.functional.cross_entropy(m(xb), yb)
        if sc is None:
            loss.backward()
            opt.step()
        else:
            sc.scale(loss).backward()
            sc.step(opt)
        opt.clear_grad()
        return loss

    step = paddle.jit.to_static(one, scan_steps=K, dp_axis="dp",
                                accumulate_steps=acc)
    return step, m, opt, sc


_CTRL = {}


def _control(stage=0, scaler=False):
    """Uninterrupted 2-call control of the SAME configuration (stages
    2/3 under accumulation reorder the gradient sum vs the replicated
    program — tolerance-level there by design — so "bitwise-equal to an
    uninterrupted run" is judged against the same stage)."""
    key = (stage, bool(scaler))
    if key not in _CTRL:
        s, m, _o, _sc = _build(stage, scaler=scaler)
        s(paddle.to_tensor(X1), paddle.to_tensor(Y1))
        l2 = s(paddle.to_tensor(X2), paddle.to_tensor(Y2)).numpy()
        params = [np.asarray(p._value).tobytes() for p in m.parameters()]
        _CTRL[key] = (l2.tobytes(), params)
        del s, m, _o, _sc
        gc.collect()
    return _CTRL[key]


@pytest.mark.parametrize("stage", [0, 1, 2, 3])
def test_bitwise_resume_matrix(stage, tmp_path):
    """Acceptance: checkpoint after call 1, rebuild FRESH objects (a
    different init seed proves the state really comes from the
    checkpoint), restore, run call 2 — losses and final params BITWISE
    equal the uninterrupted control, for every ZeRO stage under an
    accumulation window (params + moments + step count + RNG + lr all
    round-trip through the sharded stores)."""
    ctrl_l2, ctrl_params = _control(stage)
    sA, mA, oA, _ = _build(stage)
    sA(paddle.to_tensor(X1), paddle.to_tensor(Y1))
    checkpoint.CheckpointManager(str(tmp_path)).add_model(
        mA).add_optimizer(oA).save(1)
    del sA, mA, oA
    gc.collect()  # the "kill": nothing survives but the checkpoint

    sB, mB, oB, _ = _build(stage, seed=99)
    meta = checkpoint.CheckpointManager(str(tmp_path)).add_model(
        mB).add_optimizer(oB).restore()
    assert meta is not None and meta["step"] == 1
    l2 = sB(paddle.to_tensor(X2), paddle.to_tensor(Y2)).numpy()
    assert l2.tobytes() == ctrl_l2
    for p, ref in zip(mB.parameters(), ctrl_params):
        assert np.asarray(p._value).tobytes() == ref, (stage, p.name)
    del sB, mB, oB


def test_bitwise_resume_with_scaler(tmp_path):
    """GradScaler dynamic-scaling state (scale/good/bad counters) rides
    the checkpoint: the restored run's scaled losses stay bitwise."""
    ctrl_l2, ctrl_params = _control(1, scaler=True)
    sA, mA, oA, scA = _build(1, scaler=True)
    sA(paddle.to_tensor(X1), paddle.to_tensor(Y1))
    checkpoint.CheckpointManager(str(tmp_path)).add_model(
        mA).add_optimizer(oA).add_scaler(scA).save(1)
    del sA, mA, oA, scA
    gc.collect()
    sB, mB, oB, scB = _build(1, seed=99, scaler=True)
    checkpoint.CheckpointManager(str(tmp_path)).add_model(
        mB).add_optimizer(oB).add_scaler(scB).restore()
    assert float(scB._scale._value) == 128.0
    l2 = sB(paddle.to_tensor(X2), paddle.to_tensor(Y2)).numpy()
    assert l2.tobytes() == ctrl_l2
    for p, ref in zip(mB.parameters(), ctrl_params):
        assert np.asarray(p._value).tobytes() == ref, p.name
    del sB, mB, oB, scB


def test_mid_window_restore_eager(tmp_path):
    """Mid-accumulation-window restore: a checkpoint taken with
    accumulated-but-unconsumed gradients (backward ran, step deferred)
    hands the surviving @GRAD state back, and finishing the window after
    restore is bitwise-identical to the uninterrupted window."""
    xa = rng.rand(16, 16).astype("float32")
    ya = rng.randint(0, 8, 16).astype("int64")
    xb = rng.rand(16, 16).astype("float32")
    yb = rng.randint(0, 8, 16).astype("int64")

    def build(seed=11):
        paddle.seed(seed)
        m = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 8))
        opt = paddle.optimizer.AdamW(parameters=m.parameters(),
                                     learning_rate=0.05)
        return m, opt

    def micro(m, x, y):
        nn.functional.cross_entropy(
            m(paddle.to_tensor(x)), paddle.to_tensor(y)).backward()

    # control: both micro steps, one update — no interruption
    m0, o0 = build()
    micro(m0, xa, ya)
    micro(m0, xb, yb)
    o0.step()
    o0.clear_grad()
    ctrl = [np.asarray(p._value).tobytes() for p in m0.parameters()]

    # interrupted: checkpoint mid-window (after micro 1, before micro 2)
    mA, oA = build()
    micro(mA, xa, ya)
    assert any(p._grad is not None for p in mA.parameters())
    checkpoint.CheckpointManager(str(tmp_path)).add_model(
        mA).add_optimizer(oA).save(7)
    del mA, oA
    gc.collect()
    mB, oB = build(seed=99)
    checkpoint.CheckpointManager(str(tmp_path)).add_model(
        mB).add_optimizer(oB).restore()
    assert all(p._grad is not None for p in mB.parameters())
    micro(mB, xb, yb)
    oB.step()
    oB.clear_grad()
    for p, ref in zip(mB.parameters(), ctrl):
        assert np.asarray(p._value).tobytes() == ref, p.name


def test_zero_gacc_window_store_roundtrip(tmp_path):
    """The sharded ZeRO-2/3 window accumulator (``gacc``) is part of the
    accumulation-window phase and round-trips through per-rank shards
    bit-for-bit."""
    _s, m, opt, _ = _build(3)
    seeded = []
    for zb, sd in zip(opt._zero["buckets"], opt._zero["stores"]):
        st = sd["gacc"].tensor
        val = np.arange(np.prod(st._value.shape),
                        dtype=np.float32).reshape(st._value.shape)
        val[zb.rows - zb.pad_rows:] = 0.0  # padding rows carry no state
        st.set_value(val)
        seeded.append(val.tobytes())
    checkpoint.CheckpointManager(str(tmp_path)).add_model(
        m).add_optimizer(opt).save(1)
    for sd in opt._zero["stores"]:  # clobber, then restore
        sd["gacc"].tensor.set_value(
            np.zeros(sd["gacc"].tensor._value.shape, np.float32))
    checkpoint.CheckpointManager(str(tmp_path)).add_model(
        m).add_optimizer(opt).restore()
    for sd, ref in zip(opt._zero["stores"], seeded):
        assert np.asarray(sd["gacc"].tensor._value).tobytes() == ref
    del _s, m, opt


@pytest.mark.parametrize("stage,dp_from,dp_to", [
    (1, 8, 4), (3, 8, 4),   # shrink: PR-7's original direction
    (1, 4, 8), (3, 4, 8),   # GROW: the reform-up path's dependency —
                            # flat stores re-flatten to MORE shards
])
def test_elastic_resume_different_dp_degree(stage, dp_from, dp_to,
                                            tmp_path):
    """Elastic resume in BOTH directions: a dp=d_from checkpoint
    restores into a dp=d_to optimizer by re-flattening the shards —
    every materialized param AND moment is bitwise-identical to the
    d_from state, the stores live 1/d_to per rank, and continued
    training matches the d_from continuation to fp32 tolerance (the
    microbatch regrouping reorders the gradient mean). The grow
    direction (d_to > d_from) is what a pod re-forming UPWARD after a
    supervised respawn resumes through."""
    sA, mA, oA, _ = _build(stage, dp=dp_from, acc=None)
    sA(paddle.to_tensor(X1), paddle.to_tensor(Y1))
    checkpoint.CheckpointManager(str(tmp_path)).add_model(
        mA).add_optimizer(oA).save(1)
    pA = [np.asarray(p._value).copy() for p in mA.parameters()]
    momA = [np.asarray(oA._accumulators[("moment1", id(p))]._value).copy()
            for p in mA.parameters()]
    l2_A = sA(paddle.to_tensor(X2), paddle.to_tensor(Y2)).numpy()
    del sA, mA, oA
    gc.collect()

    sB, mB, oB, _ = _build(stage, dp=dp_to, seed=99, acc=None)
    meta = checkpoint.CheckpointManager(str(tmp_path)).add_model(
        mB).add_optimizer(oB).restore()
    assert meta["zero"]["opt"]["degree"] == dp_from \
        and oB._zero["degree"] == dp_to
    for p, ref in zip(mB.parameters(), pA):
        assert np.asarray(p._value).tobytes() == ref.tobytes(), p.name
    for p, ref in zip(mB.parameters(), momA):
        got = np.asarray(oB._accumulators[("moment1", id(p))]._value)
        assert got.tobytes() == ref.tobytes(), ("moment", p.name)
    for sd in oB._zero["stores"]:
        for slot in sd:
            arr = sd[slot].tensor._value
            assert len(arr.sharding.device_set) == dp_to
            assert arr.addressable_shards[0].data.shape[0] == \
                arr.shape[0] // dp_to
    l2_B = sB(paddle.to_tensor(X2), paddle.to_tensor(Y2)).numpy()
    np.testing.assert_allclose(l2_B, l2_A, rtol=1e-6)
    del sB, mB, oB


def test_zero3_restore_without_optimizer_rejected(tmp_path):
    """A ZeRO-3 checkpoint's params live in the optimizer's sharded
    stores; restoring with only the model registered would silently keep
    fresh-init weights — strict restore cross-checks coverage and raises."""
    _s, m, opt, _ = _build(3, acc=None)
    checkpoint.CheckpointManager(str(tmp_path)).add_model(
        m).add_optimizer(opt).save(1)
    del _s, opt
    gc.collect()
    with pytest.raises(checkpoint.StateMismatchError,
                       match="ZeRO-3 store view"):
        checkpoint.CheckpointManager(str(tmp_path)).add_model(m).restore()
    del m


def test_elastic_resume_rejects_config_mismatch(tmp_path):
    """Same degree-elasticity must NOT paper over a real config change:
    a different ZeRO stage or a missing _zero_enable fails loudly."""
    _s, m, opt, _ = _build(1, acc=None)
    checkpoint.CheckpointManager(str(tmp_path)).add_model(
        m).add_optimizer(opt).save(1)
    del _s, m, opt
    gc.collect()
    _s2, m2, o2, _ = _build(3, seed=99, acc=None)
    with pytest.raises(checkpoint.StateMismatchError, match="stage"):
        checkpoint.CheckpointManager(str(tmp_path)).add_model(
            m2).add_optimizer(o2).restore()
    del _s2, m2, o2
    gc.collect()
    paddle.seed(1)
    m3 = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 8))
    o3 = paddle.optimizer.AdamW(parameters=m3.parameters())
    with pytest.raises(checkpoint.StateMismatchError, match="ZeRO"):
        checkpoint.CheckpointManager(str(tmp_path)).add_model(
            m3).add_optimizer(o3).restore()


# -- crash consistency ------------------------------------------------------

@pytest.mark.chaos
def test_kill_point_sweep_never_accepts_torn_checkpoint(tmp_path):
    """Acceptance: a kill injected at EVERY checkpoint write stage
    leaves restore either on the previous checkpoint (stages before the
    atomic publish) or on the complete new one (stages after) — never on
    a torn one."""
    published_after = {"checkpoint/after_publish", "checkpoint/before_gc"}
    for kp in ckpt_core.KILL_POINTS:
        root = str(tmp_path / kp.replace("/", "_"))
        ckpt_core.write_checkpoint(root, 1, {"a.pkl": b"A" * 64},
                                   meta={"v": 1})
        faults.inject(kp)
        with pytest.raises(faults.FaultInjected):
            ckpt_core.write_checkpoint(root, 2, {"a.pkl": b"B" * 64},
                                       meta={"v": 2})
        faults.clear()
        step, payloads, meta = ckpt_core.read_checkpoint(root)
        if kp in published_after:
            assert step == 2 and payloads["a.pkl"] == b"B" * 64, kp
        else:
            assert step == 1 and payloads["a.pkl"] == b"A" * 64, kp
        # and the writer recovers: the next save publishes cleanly
        ckpt_core.write_checkpoint(root, 3, {"a.pkl": b"C" * 64})
        assert ckpt_core.read_checkpoint(root)[0] == 3, kp


@pytest.mark.chaos
def test_corrupt_payload_falls_back_and_counts(tmp_path):
    """A bit-flipped payload fails the manifest's content hash: auto
    restore skips to the previous valid checkpoint (counted), explicit
    restore of the corrupt step raises."""
    root = str(tmp_path)
    ckpt_core.write_checkpoint(root, 1, {"a.pkl": b"AAAA"})
    ckpt_core.write_checkpoint(root, 2, {"a.pkl": b"BBBB"})
    with open(os.path.join(root, ckpt_core.step_dirname(2), "a.pkl"),
              "r+b") as f:
        f.write(b"Z")
    monitor.stat_reset("checkpoint_corrupt_skipped_total")
    step, payloads, _meta = ckpt_core.read_checkpoint(root)
    assert step == 1 and payloads["a.pkl"] == b"AAAA"
    assert monitor.stat_get("checkpoint_corrupt_skipped_total") == 1
    with pytest.raises(checkpoint.CheckpointCorruptError):
        ckpt_core.read_checkpoint(root, step=2)


def test_gc_keeps_last_n_and_sweeps_staging(tmp_path):
    root = str(tmp_path)
    for i in range(5):
        ckpt_core.write_checkpoint(root, i, {"a.pkl": bytes([i])},
                                   keep_last_n=2)
    assert ckpt_core.valid_steps(root) == [3, 4]
    # our own abandoned staging dir (crashed earlier attempt) is swept;
    # a LIVE concurrent writer's staging dir survives (its publish
    # rename must not be yanked out from under it)
    mine = os.path.join(root, f".staging.step_0000000009.{os.getpid()}")
    os.makedirs(mine)
    import subprocess
    import sys
    peer = subprocess.Popen([sys.executable, "-c",
                             "import time; time.sleep(30)"])
    try:
        theirs = os.path.join(root, f".staging.step_0000000008.{peer.pid}")
        os.makedirs(theirs)
        ckpt_core.gc_checkpoints(root, 2)
        assert not os.path.exists(mine)
        assert os.path.exists(theirs)
    finally:
        peer.kill()
        peer.wait()
    ckpt_core.gc_checkpoints(root, 2)  # writer died: now it sweeps
    assert not os.path.exists(theirs)


def test_manager_restore_missing_returns_none(tmp_path):
    paddle.seed(0)
    m = nn.Linear(4, 2)
    mgr = checkpoint.CheckpointManager(str(tmp_path)).add_model(m)
    assert mgr.restore() is None
    assert mgr.latest_step() is None


def test_checkpoint_counters_and_manifest_meta(tmp_path):
    monitor.stat_reset("checkpoint_saves_total")
    monitor.stat_reset("checkpoint_restores_total")
    paddle.seed(0)
    m = nn.Linear(4, 2)
    opt = paddle.optimizer.Adam(parameters=m.parameters())
    mgr = checkpoint.CheckpointManager(str(tmp_path), keep_last_n=3)
    mgr.add_model(m).add_optimizer(opt)
    mgr.save(5, extra_meta={"epoch": 2})
    meta = mgr.restore()
    assert meta["step"] == 5 and meta["epoch"] == 2
    assert "model_model.pkl" in meta["components"]
    assert monitor.stat_get("checkpoint_saves_total") == 1
    assert monitor.stat_get("checkpoint_restores_total") == 1
    assert monitor.stat_get("checkpoint_bytes_written_total") > 0
