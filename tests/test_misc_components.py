"""Round-2 gap components: Program.clone(for_test), fleet Dataset ingestion
+ train_from_dataset, enforce errors, op version registry, custom C++ op ABI.

References: framework.py Program.clone, data_set.h:43/executor.py:1802,
platform/enforce.h, framework/op_version_registry.cc,
framework/custom_operator.cc:511.
"""
import os
import subprocess
import textwrap

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.static as static


class TestCloneForTest:
    def test_dropout_switches_off(self):
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [4, 8], "float32")
            out = nn.functional.dropout(x, p=0.5, training=True)
        exe = static.Executor()
        feed = np.ones((4, 8), np.float32)
        (train_out,) = exe.run(prog, feed={"x": feed}, fetch_list=[out])
        assert (np.asarray(train_out) == 0).any()  # some dropped

        eval_prog = prog.clone(for_test=True)
        (eval_out,) = exe.run(eval_prog, feed={"x": feed}, fetch_list=[out])
        np.testing.assert_allclose(np.asarray(eval_out), feed)  # identity

    def test_static_training_updates_running_stats(self):
        """Executor runs must move BN running stats (recorded stat-update
        op + buffer write-back; caught by review: stats were frozen at
        init under static training)."""
        paddle.seed(0)
        bn = nn.BatchNorm1D(4)
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [16, 4], "float32")
            bn.train()
            out = bn(x)
        exe = static.Executor()
        rng = np.random.RandomState(0)
        for _ in range(10):
            exe.run(prog, feed={"x": (rng.rand(16, 4) * 2 + 10)
                                .astype(np.float32)}, fetch_list=[out])
        mean = np.asarray(bn._mean.numpy())
        assert np.all(mean > 1.0), mean  # moved toward the ~11 input mean

    def test_batch_norm_uses_running_stats(self):
        paddle.seed(0)
        bn = nn.BatchNorm1D(4)
        # give running stats distinctive values
        bn._mean.set_value(np.full(4, 2.0, np.float32))
        bn._variance.set_value(np.full(4, 4.0, np.float32))

        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [8, 4], "float32")
            out = bn(x)
        exe = static.Executor()
        feed = np.random.RandomState(0).rand(8, 4).astype(np.float32)

        eval_prog = prog.clone(for_test=True)
        (eval_out,) = exe.run(eval_prog, feed={"x": feed}, fetch_list=[out])
        want = (feed - 2.0) / np.sqrt(4.0 + bn._epsilon)
        want = want * bn.weight.numpy() + bn.bias.numpy()
        np.testing.assert_allclose(np.asarray(eval_out), want, rtol=1e-4,
                                   atol=1e-5)
        # train-mode program instead normalizes by batch stats
        (train_out,) = exe.run(prog, feed={"x": feed}, fetch_list=[out])
        assert not np.allclose(np.asarray(train_out), want, atol=1e-3)


class TestFleetDataset:
    def _write_files(self, tmp_path, n_files=2, rows=6):
        paths = []
        rng = np.random.RandomState(0)
        for i in range(n_files):
            p = tmp_path / f"part-{i}.txt"
            lines = []
            for r in range(rows):
                feat = " ".join(f"{v:.4f}" for v in rng.rand(4))
                label = f"{rng.randint(0, 2)}"
                lines.append(f"{feat}\t{label}")
            p.write_text("\n".join(lines) + "\n")
            paths.append(str(p))
        return paths

    def test_load_shuffle_batches(self, tmp_path):
        from paddle_tpu.distributed.fleet import InMemoryDataset
        ds = InMemoryDataset()
        ds.init(batch_size=4, use_var=["feat", "label"])
        ds.set_filelist(self._write_files(tmp_path))
        n = ds.load_into_memory()
        assert n == 12 == ds.get_memory_data_size()
        before = [s[0].tolist() for s in ds._samples]
        ds.local_shuffle(seed=3)
        after = [s[0].tolist() for s in ds._samples]
        assert sorted(map(tuple, before)) == sorted(map(tuple, after))
        assert before != after
        batches = list(ds.batches())
        assert len(batches) == 3
        assert batches[0]["feat"].shape == (4, 4)
        ds.global_shuffle()  # single-process: local shuffle path
        assert ds.get_shuffle_data_size() == 12

    def test_train_from_dataset(self, tmp_path):
        from paddle_tpu.distributed.fleet import InMemoryDataset
        ds = InMemoryDataset()
        ds.init(batch_size=3, use_var=["feat", "label"])
        ds.set_filelist(self._write_files(tmp_path, n_files=1, rows=9))
        ds.load_into_memory()

        paddle.seed(1)
        prog = static.Program()
        with static.program_guard(prog):
            feat = static.data("feat", [None, 4], "float32")
            label = static.data("label", [None, 1], "float32")
            w = static.create_parameter([4, 1], "float32")
            pred = paddle.matmul(feat, w)
            loss = nn.functional.mse_loss(pred, label)
            opt = paddle.optimizer.SGD(learning_rate=0.1)
            opt.minimize(loss)
        exe = static.Executor()
        w0 = w.numpy().copy()
        out = exe.run(prog, feed={
            "feat": np.zeros((3, 4), np.float32),
            "label": np.zeros((3, 1), np.float32)}, fetch_list=[loss])
        exe.train_from_dataset(prog, ds, fetch_list=[loss])
        assert not np.allclose(w.numpy(), w0)  # trained over the files

    def test_queue_dataset_streams(self, tmp_path):
        from paddle_tpu.distributed.fleet import QueueDataset
        ds = QueueDataset()
        ds.init(batch_size=4, use_var=["feat", "label"])
        ds.set_filelist(self._write_files(tmp_path))
        with pytest.raises(RuntimeError):
            ds.load_into_memory()
        assert len(list(ds.batches())) == 3


class TestStaticSaveLoad:
    def test_training_resume_roundtrip(self, tmp_path):
        """static.save/load: persistables + optimizer accumulators resume
        training exactly (reference fluid/io.py save:1840/load:1948)."""
        def build():
            paddle.seed(11)
            prog = static.Program()
            with static.program_guard(prog):
                x = static.data("x", [4, 6], "float32")
                w = static.create_parameter([6, 3], "float32")
                loss = paddle.mean(paddle.matmul(x, w) ** 2)
                opt = paddle.optimizer.Adam(learning_rate=0.05)
                opt.minimize(loss)
            return prog, loss

        rng = np.random.RandomState(0)
        feeds = [rng.rand(4, 6).astype(np.float32) for _ in range(6)]
        exe = static.Executor()

        prog, loss = build()
        for f in feeds[:3]:
            exe.run(prog, feed={"x": f}, fetch_list=[loss])
        static.save(prog, str(tmp_path / "ckpt"))
        cont = [np.asarray(exe.run(prog, feed={"x": f},
                                   fetch_list=[loss])[0])
                for f in feeds[3:]]

        prog2, loss2 = build()
        static.load(prog2, str(tmp_path / "ckpt"))
        resumed = [np.asarray(exe.run(prog2, feed={"x": f},
                                      fetch_list=[loss2])[0])
                   for f in feeds[3:]]
        np.testing.assert_allclose(np.ravel(cont), np.ravel(resumed),
                                   rtol=1e-5)


class TestEnforce:
    def test_categories_and_callsite(self):
        from paddle_tpu.core import enforce as E
        with pytest.raises(E.InvalidArgumentError, match="INVALID_ARGUMENT"):
            E.enforce(False, "bad arg")
        with pytest.raises(E.OutOfRangeError):
            E.enforce_lt(5, 3, "index check", E.OutOfRangeError)
        try:
            E.enforce_eq(1, 2, "mismatch")
        except E.InvalidArgumentError as e:
            assert "lhs=1" in str(e) and "rhs=2" in str(e)
            assert "test_misc_components.py" in str(e)
        assert E.enforce_not_none(42) == 42
        with pytest.raises(E.NotFoundError):
            E.enforce_not_none(None, "missing thing")


class TestOpVersion:
    def test_registry_and_compat(self):
        from paddle_tpu.core import op_version as V
        assert V.get_op_version("cross_entropy") >= 1
        snap = V.snapshot()
        V.check_compatible(snap)  # self-compatible
        with pytest.raises(V.OpVersionError, match="newer op definitions"):
            V.check_compatible({"cross_entropy": 999})
        with pytest.raises(V.OpVersionError):
            V.register_op_version("cross_entropy", 0)

    def test_saved_artifact_carries_versions(self, tmp_path):
        from paddle_tpu.jit.io import save as jit_save
        from paddle_tpu.jit.export import ServedProgram
        from paddle_tpu.jit.to_static import InputSpec
        m = nn.Sequential(nn.Linear(4, 2))
        m.eval()
        prefix = str(tmp_path / "m")
        jit_save(m, prefix, input_spec=[InputSpec([None, 4], "float32")])
        sp = ServedProgram(prefix)
        assert sp.meta["op_versions"].get("cross_entropy", 0) >= 1


CUSTOM_OP_SRC = r"""
#include <cstdint>
extern "C" {
// y = x^2 + 1
void sq1_forward(const float* x, float* y, int64_t n) {
  for (int64_t i = 0; i < n; ++i) y[i] = x[i] * x[i] + 1.0f;
}
void sq1_backward(const float* x, const float* gy, float* gx, int64_t n) {
  for (int64_t i = 0; i < n; ++i) gx[i] = 2.0f * x[i] * gy[i];
}
// no backward exported for this one
void plain_forward(const float* x, float* y, int64_t n) {
  for (int64_t i = 0; i < n; ++i) y[i] = x[i] + 3.0f;
}
}
"""


class TestCustomOpABI:
    @pytest.fixture(scope="class")
    def so_path(self, tmp_path_factory):
        d = tmp_path_factory.mktemp("customop")
        src = d / "my_op.cc"
        src.write_text(CUSTOM_OP_SRC)
        so = d / "my_op.so"
        subprocess.run(["g++", "-O2", "-fPIC", "-shared", str(src),
                        "-o", str(so)], check=True)
        return str(so)

    def test_forward_and_grad(self, so_path):
        op = paddle.incubate.load_custom_op(so_path, "sq1")
        assert op.has_backward
        x = paddle.to_tensor(np.array([1.0, 2.0, -3.0], np.float32))
        x.stop_gradient = False
        y = op(x)
        np.testing.assert_allclose(y.numpy(), [2.0, 5.0, 10.0])
        y.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [2.0, 4.0, -6.0])

    def test_under_to_static(self, so_path):
        op = paddle.incubate.load_custom_op(so_path, "sq1")

        @paddle.jit.to_static
        def f(v):
            return op(v).sum()

        out = f(paddle.to_tensor(np.array([2.0, 3.0], np.float32)))
        np.testing.assert_allclose(float(out.numpy()), 5.0 + 10.0)

    def test_missing_symbols(self, so_path):
        from paddle_tpu.core.enforce import NotFoundError
        op = paddle.incubate.load_custom_op(so_path, "plain")
        assert not op.has_backward
        x = paddle.to_tensor(np.array([1.0], np.float32))
        np.testing.assert_allclose(op(x).numpy(), [4.0])
        with pytest.raises(NotFoundError):
            paddle.incubate.load_custom_op(so_path, "nonexistent")


def test_static_save_load_restores_scheduler(tmp_path):
    """LR scheduler epoch state must survive save/load (review finding:
    resumed schedules silently restarted at epoch 0)."""
    def build():
        paddle.seed(3)
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [2, 4], "float32")
            w = static.create_parameter([4, 2], "float32")
            loss = paddle.mean(paddle.matmul(x, w) ** 2)
            sched = paddle.optimizer.lr.StepDecay(learning_rate=0.1,
                                                  step_size=2, gamma=0.5)
            opt = paddle.optimizer.SGD(learning_rate=sched)
            opt.minimize(loss)
        return prog, loss, sched, opt

    exe = static.Executor()
    prog, loss, sched, opt = build()
    feed = np.ones((2, 4), np.float32)
    for _ in range(5):
        exe.run(prog, feed={"x": feed}, fetch_list=[loss])
        sched.step()
    lr_before = opt.get_lr()
    static.save(prog, str(tmp_path / "s"))

    prog2, loss2, sched2, opt2 = build()
    static.load(prog2, str(tmp_path / "s"))
    assert abs(opt2.get_lr() - lr_before) < 1e-8
    assert sched2.last_epoch == sched.last_epoch
