"""Control flow: while_loop / cond / case / switch_case.

Mirrors the reference's `test_while_loop_op.py` / `test_cond.py` /
`test_case.py` / `test_switch_case.py` coverage classes: output parity with
numpy, gradient checks (incl. closure weights), and behavior under
`@to_static` with data-dependent predicates.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.core import state
from paddle_tpu.jit import to_static


def t(x, stop_gradient=True, dtype=None):
    return Tensor(np.asarray(x), dtype=dtype, stop_gradient=stop_gradient)


# ---------------------------------------------------------------------------
# cond
# ---------------------------------------------------------------------------

class TestCond:
    def test_eager_concrete_pred(self):
        x = t([1.0, 2.0], stop_gradient=False)
        out = nn.cond(t(True), lambda: x * 2, lambda: x * 3)
        np.testing.assert_allclose(out.numpy(), [2.0, 4.0])
        out = nn.cond(t(False), lambda: x * 2, lambda: x * 3)
        np.testing.assert_allclose(out.numpy(), [3.0, 6.0])

    def test_eager_grad_through_taken_branch(self):
        x = t([1.0, 2.0], stop_gradient=False)
        out = nn.cond(t(True), lambda: (x * x).sum(), lambda: x.sum())
        out.backward()
        np.testing.assert_allclose(x.grad.numpy(), [2.0, 4.0])

    def test_traced_data_dependent(self):
        @to_static
        def f(x):
            # pred depends on data → must lower to lax.cond
            return nn.cond(x.sum() > 0, lambda: x * 2, lambda: x * -1)

        np.testing.assert_allclose(f(t([1.0, 2.0])).numpy(), [2.0, 4.0])
        np.testing.assert_allclose(f(t([-1.0, -2.0])).numpy(), [1.0, 2.0])

    def test_traced_grad_with_closure_weight(self):
        w = t([2.0, 3.0], stop_gradient=False)
        w.persistable = True
        uid = state.register(w)
        try:
            @to_static
            def f(x):
                out = nn.cond(x.sum() > 0,
                              lambda: (x * w).sum(),
                              lambda: (x - w).sum())
                out.backward()
                return out

            x = t([1.0, 2.0], stop_gradient=False)
            f(x)
            # taken branch: d(x*w)/dw = x
            np.testing.assert_allclose(w.grad.numpy(), [1.0, 2.0])
        finally:
            state.unregister(uid)

    def test_traced_multi_output(self):
        @to_static
        def f(x):
            a, b = nn.cond(x.sum() > 0,
                           lambda: (x + 1, x + 2),
                           lambda: (x - 1, x - 2))
            return a + b

        np.testing.assert_allclose(f(t([1.0])).numpy(), [5.0])
        np.testing.assert_allclose(f(t([-5.0])).numpy(), [-13.0])

    def test_mismatched_structures_raise(self):
        @to_static
        def f(x):
            return nn.cond(x.sum() > 0,
                           lambda: (x, x),
                           lambda: x)

        with pytest.raises(ValueError, match="different structures"):
            f(t([1.0]))


# ---------------------------------------------------------------------------
# while_loop
# ---------------------------------------------------------------------------

class TestWhileLoop:
    def test_eager_counter(self):
        i = t(0, dtype="int64")
        ten = t(10, dtype="int64")
        out = nn.while_loop(lambda i: i < ten, lambda i: [i + 1], [i])
        assert int(out[0].numpy()) == 10

    def test_eager_grad(self):
        x = t([1.0, 1.0], stop_gradient=False)
        i = t(0, dtype="int64")

        def body(i, acc):
            return [i + 1, acc * 2.0]

        out = nn.while_loop(lambda i, acc: i < t(3, dtype="int64"),
                            body, [i, x])
        loss = out[1].sum()
        loss.backward()
        np.testing.assert_allclose(x.grad.numpy(), [8.0, 8.0])

    def test_traced_nograd(self):
        @to_static
        def f(n):
            with paddle.no_grad():
                i = paddle.zeros([], dtype="int32")
                s = paddle.zeros([], dtype="float32")
                i, s = nn.while_loop(
                    lambda i, s: i < n,
                    lambda i, s: [i + 1, s + paddle.cast(i, "float32")],
                    [i, s])
            return s

        # sum of 0..n-1, with n data-dependent
        assert float(f(t(5, dtype="int32")).numpy()) == 10.0
        assert float(f(t(7, dtype="int32")).numpy()) == 21.0

    def test_traced_grad_rnn_style(self):
        """RNN over time steps via while_loop with a closure weight; grads
        must flow to the weight through the masked-scan lowering."""
        w = t(np.full((4, 4), 0.1, np.float32), stop_gradient=False)
        w.persistable = True
        uid = state.register(w)
        try:
            @to_static
            def step(x, n):
                h = paddle.zeros([2, 4], dtype="float32")
                i = paddle.zeros([], dtype="int32")

                def body(i, h):
                    # h_{t+1} = tanh(h W + x_t)
                    xt = x[:, :]  # same input each step (keeps shapes static)
                    return [i + 1, paddle.tanh(paddle.matmul(h, w) + xt)]

                i, h = nn.while_loop(lambda i, h: i < n, body, [i, h],
                                     maximum_trip_count=8)
                loss = h.sum()
                loss.backward()
                return loss

            x = t(np.ones((2, 4), np.float32))
            l3 = float(step(x, t(3, dtype="int32")).numpy())
            g3 = np.array(w.grad.numpy())
            assert np.abs(g3).sum() > 0  # grads reached the closure weight
            w.clear_grad()
            l5 = float(step(x, t(5, dtype="int32")).numpy())
            g5 = np.array(w.grad.numpy())
            # more steps → different loss and grads (data-dependent trip count)
            assert l3 != l5
            assert not np.allclose(g3, g5)
        finally:
            state.unregister(uid)

    def test_traced_grad_numeric_check(self):
        """Numeric-vs-analytic gradient through the masked-scan while."""
        w = t([0.5], stop_gradient=False)
        w.persistable = True
        uid = state.register(w)
        try:
            @to_static
            def f(n):
                i = paddle.zeros([], dtype="int32")
                acc = paddle.ones([1], dtype="float32")
                i, acc = nn.while_loop(
                    lambda i, a: i < n,
                    lambda i, a: [i + 1, a * w],
                    [i, acc], maximum_trip_count=6)
                loss = acc.sum()
                loss.backward()
                return loss

            n = t(3, dtype="int32")
            f(n)
            # loss = w^3 → dloss/dw = 3 w^2
            np.testing.assert_allclose(w.grad.numpy(), [3 * 0.5 ** 2],
                                       rtol=1e-5)
        finally:
            state.unregister(uid)

    def test_traced_grad_without_bound_raises(self):
        w = t([2.0], stop_gradient=False)
        w.persistable = True
        uid = state.register(w)
        try:
            @to_static
            def f(n):
                i = paddle.zeros([], dtype="int32")
                v = paddle.ones([1], dtype="float32")
                return nn.while_loop(lambda i, v: i < n,
                                     lambda i, v: [i + 1, v * w],
                                     [i, v])

            with pytest.raises(Exception, match="maximum_trip_count"):
                f(t(3, dtype="int32"))
        finally:
            state.unregister(uid)

    def test_traced_grad_truncation_poisons_with_nan(self):
        """If the bound is too small the loop must not silently truncate:
        float outputs are NaN-poisoned so monitoring catches it."""
        w = t([1.1], stop_gradient=False)
        w.persistable = True
        uid = state.register(w)
        try:
            @to_static
            def f(n):
                i = paddle.zeros([], dtype="int32")
                acc = paddle.ones([1], dtype="float32")
                i, acc = nn.while_loop(
                    lambda i, a: i < n,
                    lambda i, a: [i + 1, a * w],
                    [i, acc], maximum_trip_count=4)
                return acc

            ok = f(t(4, dtype="int32"))  # exactly at the bound: fine
            assert np.isfinite(ok.numpy()).all()
            bad = f(t(6, dtype="int32"))  # needs 6 > 4 trips: poisoned
            assert np.isnan(bad.numpy()).all()
        finally:
            state.unregister(uid)

    def test_bad_loop_vars(self):
        with pytest.raises(ValueError):
            nn.while_loop(lambda: True, lambda: [], [])


# ---------------------------------------------------------------------------
# case / switch_case
# ---------------------------------------------------------------------------

class TestCaseSwitch:
    def test_case_eager(self):
        x = t([1.0])
        out = nn.case([(t(False), lambda: x + 1), (t(True), lambda: x + 2)],
                      default=lambda: x + 9)
        np.testing.assert_allclose(out.numpy(), [3.0])
        out = nn.case([(t(False), lambda: x + 1), (t(False), lambda: x + 2)],
                      default=lambda: x + 9)
        np.testing.assert_allclose(out.numpy(), [10.0])

    def test_case_traced_first_true_wins(self):
        @to_static
        def f(x):
            return nn.case([(x.sum() > 0, lambda: x + 1),
                            (x.sum() > -10, lambda: x + 2)],
                           default=lambda: x + 9)

        np.testing.assert_allclose(f(t([1.0])).numpy(), [2.0])
        np.testing.assert_allclose(f(t([-1.0])).numpy(), [1.0])
        np.testing.assert_allclose(f(t([-100.0])).numpy(), [-91.0])

    def test_switch_case_eager(self):
        x = t([1.0])
        fns = {1: lambda: x * 1, 2: lambda: x * 2, 3: lambda: x * 3}
        out = nn.switch_case(t(2, dtype="int32"), fns)
        np.testing.assert_allclose(out.numpy(), [2.0])
        # unmatched → default = highest-key branch (reference semantics)
        out = nn.switch_case(t(7, dtype="int32"), fns)
        np.testing.assert_allclose(out.numpy(), [3.0])

    def test_switch_case_traced(self):
        @to_static
        def f(idx, x):
            return nn.switch_case(
                idx, {0: lambda: x + 10, 2: lambda: x + 20},
                default=lambda: x - 1)

        x = t([1.0])
        np.testing.assert_allclose(f(t(0, dtype="int32"), x).numpy(), [11.0])
        np.testing.assert_allclose(f(t(2, dtype="int32"), x).numpy(), [21.0])
        np.testing.assert_allclose(f(t(5, dtype="int32"), x).numpy(), [0.0])

    def test_switch_case_traced_grad(self):
        w = t([2.0], stop_gradient=False)
        w.persistable = True
        uid = state.register(w)
        try:
            @to_static
            def f(idx, x):
                out = nn.switch_case(
                    idx, {0: lambda: (x * w).sum(),
                          1: lambda: (x * w * w).sum()})
                out.backward()
                return out

            x = t([3.0])
            f(t(1, dtype="int32"), x)
            # d(x*w^2)/dw = 2xw = 12
            np.testing.assert_allclose(w.grad.numpy(), [12.0], rtol=1e-5)
        finally:
            state.unregister(uid)


# ---------------------------------------------------------------------------
# static program mode: constructs must stay data-dependent, not freeze to
# the build-time placeholder's branch
# ---------------------------------------------------------------------------

class TestStaticProgramControlFlow:
    def test_cond_in_program(self):
        import paddle_tpu.static as static
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [2], "float32")
            out = nn.cond(x.sum() > 0, lambda: x * 2, lambda: x * -1)
        exe = static.Executor()
        (r,) = exe.run(prog, feed={"x": np.array([1., 2.], np.float32)},
                       fetch_list=[out])
        np.testing.assert_allclose(np.asarray(r), [2.0, 4.0])
        (r,) = exe.run(prog, feed={"x": np.array([-1., -2.], np.float32)},
                       fetch_list=[out])
        np.testing.assert_allclose(np.asarray(r), [1.0, 2.0])

    def test_while_in_program(self):
        import paddle_tpu.static as static
        prog = static.Program()
        with static.program_guard(prog):
            n = static.data("n", [], "int32")
            i = paddle.zeros([], dtype="int32")
            s = paddle.zeros([], dtype="float32")
            with paddle.no_grad():
                i2, s2 = nn.while_loop(
                    lambda i, s: i < n,
                    lambda i, s: [i + 1, s + paddle.cast(i, "float32")],
                    [i, s])
        exe = static.Executor()
        (r,) = exe.run(prog, feed={"n": np.int32(5)}, fetch_list=[s2])
        assert float(np.asarray(r)) == 10.0
        (r,) = exe.run(prog, feed={"n": np.int32(7)}, fetch_list=[s2])
        assert float(np.asarray(r)) == 21.0

    def test_cond_branch_returning_feed_directly(self):
        """A branch that returns the feed tensor untouched must still see
        the fed value at replay, not the build placeholder."""
        import paddle_tpu.static as static
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [2], "float32")
            out = nn.cond(x.sum() > 0, lambda: x, lambda: x * -1)
        exe = static.Executor()
        (r,) = exe.run(prog, feed={"x": np.array([1., 2.], np.float32)},
                       fetch_list=[out])
        np.testing.assert_allclose(np.asarray(r), [1.0, 2.0])

    def test_switch_case_in_program(self):
        import paddle_tpu.static as static
        prog = static.Program()
        with static.program_guard(prog):
            idx = static.data("idx", [], "int32")
            x = static.data("x", [2], "float32")
            out = nn.switch_case(idx, {0: lambda: x + 10, 1: lambda: x * 5},
                                 default=lambda: x - 1)
        exe = static.Executor()
        feed_x = np.array([1., 2.], np.float32)
        (r,) = exe.run(prog, feed={"idx": np.int32(1), "x": feed_x},
                       fetch_list=[out])
        np.testing.assert_allclose(np.asarray(r), [5.0, 10.0])
        (r,) = exe.run(prog, feed={"idx": np.int32(9), "x": feed_x},
                       fetch_list=[out])
        np.testing.assert_allclose(np.asarray(r), [0.0, 1.0])


# ---------------------------------------------------------------------------
# TensorArray
# ---------------------------------------------------------------------------

class TestTensorArray:
    def test_write_read_length(self):
        arr = nn.create_array()
        nn.array_write(t([1.0]), t(0, dtype="int64"), arr)
        nn.array_write(t([2.0]), t(1, dtype="int64"), arr)
        assert int(nn.array_length(arr).numpy()) == 2
        np.testing.assert_allclose(nn.array_read(arr, t(1, dtype="int64")).numpy(),
                                   [2.0])
        nn.array_write(t([5.0]), t(0, dtype="int64"), arr)  # overwrite
        np.testing.assert_allclose(nn.array_read(arr, t(0, dtype="int64")).numpy(),
                                   [5.0])
