"""ZeRO-1/2 sharded data parallelism inside the scan step.

The contract under test: ``to_static(one_step, scan_steps=k,
dp_axis='dp')`` + ``optimizer._zero_enable()`` must be OBSERVABLY
identical to the replicated control — bitwise-equal per-inner-step losses
and final params on the 8-device CPU mesh — while the optimizer state
actually lives 1/dp per rank and the compiled HLO's gradient reduction is
bucketed reduce-scatter + param all-gather instead of per-param
all-reduce.
"""
import re

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import monitor, nn
from paddle_tpu.distributed import parallel_env

DP = 8


@pytest.fixture(autouse=True)
def _mesh():
    mesh = parallel_env.make_mesh({"dp": DP})
    parallel_env.set_mesh(mesh)
    yield mesh
    parallel_env.set_mesh(None)
    from paddle_tpu.distributed.fleet.base import topology
    topology.set_hybrid_communicate_group(None)


rng = np.random.RandomState(7)


def _mlp(bf16=False):
    m = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 8))
    if bf16:
        m.to("bfloat16")
    return m


def _build(zero_stage, k, bf16, comm_buffer_mb=None, seed=11):
    paddle.seed(seed)
    m = _mlp(bf16)
    opt = paddle.optimizer.AdamW(parameters=m.parameters(),
                                 learning_rate=0.05,
                                 multi_precision=bf16)
    if zero_stage:
        opt._zero_enable(axis="dp", stage=zero_stage,
                         comm_buffer_mb=comm_buffer_mb)

    def one(xb, yb):
        loss = nn.functional.cross_entropy(m(xb), yb)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    step = paddle.jit.to_static(one, scan_steps=k, dp_axis="dp")
    return step, m, opt


def _batches(k, batch=16):
    x = rng.rand(k, batch, 16).astype("float32")
    y = rng.randint(0, 8, (k, batch)).astype("int64")
    return paddle.to_tensor(x), paddle.to_tensor(y)


@pytest.mark.parametrize("stage", [1, 2])
@pytest.mark.parametrize("k", [1, 4])
@pytest.mark.parametrize("bf16", [False, True],
                         ids=["fp32", "bf16_master"])
def test_zero_bitwise_matches_replicated_control(stage, k, bf16):
    """Acceptance: zero{1,2} × scan_steps {1,4} × {fp32, bf16+master}
    sharded scan losses and final params equal the replicated control
    BITWISE (elementwise update math on a shard == on the whole)."""
    x, y = _batches(k)
    s0, m0, _ = _build(0, k, bf16)
    ref = s0(x, y).numpy()
    s1, m1, _ = _build(stage, k, bf16)
    got = s1(x, y).numpy()
    assert ref.tobytes() == got.tobytes(), (ref, got)
    for p0, p1 in zip(m0.parameters(), m1.parameters()):
        assert np.asarray(p0._value).tobytes() == \
            np.asarray(p1._value).tobytes(), p0.name
    # and through the donated carry on a second program call
    assert s0(x, y).numpy().tobytes() == s1(x, y).numpy().tobytes()


def test_zero_state_lives_sharded_1_over_dp():
    """Per-rank optimizer-state bytes shrink ~1/dp: every flat store is
    laid out PartitionSpec('dp', None) and each device holds rows/dp."""
    k = 2
    s1, _m, opt = _build(1, k, bf16=False)
    x, y = _batches(k)
    s1(x, y)
    stores = [sd[slot] for sd in opt._zero["stores"] for slot in sd]
    assert stores
    for st in stores:
        arr = st.tensor._value
        assert len(arr.sharding.device_set) == DP
        assert arr.addressable_shards[0].data.shape[0] == arr.shape[0] // DP
    # the accounting helper agrees: per-rank bytes are exactly 1/dp of
    # the stores' global footprint
    full = sum(int(np.prod(st.tensor._value.shape)) * 4 for st in stores)
    assert opt._zero_state_bytes() == full // DP


def test_zero_hlo_replaces_psum_with_scatter_gather():
    """The compiled program's reduction changes shape: control = one
    all-reduce per param grad; zero = one reduce-scatter per bucket + one
    all-gather per bucket (plus the scalar loss pmean)."""
    k = 2
    x, y = _batches(k)
    s0, _m0, _o0 = _build(0, k, bf16=False)
    s0(x, y)
    s1, _m1, _o1 = _build(1, k, bf16=False)
    s1(x, y)

    ctrl = {s["op"]: s for s in s0.collective_stats()}
    zero = {s["op"]: s for s in s1.collective_stats()}
    # control: per-param psum — at least one all-reduce per trainable
    # param (4: two weights + two biases) + the loss pmean
    assert ctrl["all-reduce"]["count"] >= 5
    assert "reduce-scatter" not in ctrl
    # zero: bucketed scatter/gather; only the scalar loss pmean remains
    assert zero["reduce-scatter"]["count"] >= 1
    assert zero["all-gather"]["count"] >= 1
    assert zero["all-reduce"]["bytes"] <= 8  # one f32 scalar
    assert zero["reduce-scatter"]["axis"] == "dp"

    # exported counters carry the (op, axis) labels
    for c in ('collective_bytes{op="reduce-scatter",axis="dp"}',
              'collective_count{op="reduce-scatter",axis="dp"}'):
        monitor.stat_reset(c)
    s1.export_collective_bytes()
    assert monitor.stat_get(
        'collective_bytes{op="reduce-scatter",axis="dp"}') > 0
    assert monitor.stat_get(
        'collective_count{op="reduce-scatter",axis="dp"}') >= 1


def test_zero_comm_buffer_size_buckets():
    """comm_buffer_mb caps the bucket payload: tiny cap → one bucket per
    param, one reduce-scatter each in the HLO."""
    k = 1
    s1, _m, opt = _build(1, k, bf16=False, comm_buffer_mb=0.0001)
    n_buckets = len(opt._zero["buckets"])
    assert n_buckets == 4  # 2 weights + 2 biases, each over the tiny cap
    x, y = _batches(k)
    first = s1(x, y).numpy()
    zero = {s["op"]: s for s in s1.collective_stats()}
    assert zero["reduce-scatter"]["count"] == n_buckets
    assert zero["all-gather"]["count"] == n_buckets
    # bitwise parity holds regardless of bucketing (fresh first calls on
    # both sides — state advances per call)
    s0, _m0, _o0 = _build(0, k, bf16=False)
    assert s0(x, y).numpy().tobytes() == first.tobytes()


def test_zero_partition_and_verifier():
    """The scan partition records the sharded carry and dp axis; the
    static-analysis pass accepts the build."""
    from paddle_tpu import analysis
    k = 2
    s1, _m, opt = _build(1, k, bf16=False)
    x, y = _batches(k)
    s1(x, y)
    part = s1._last_partition
    assert part["dp_axis"] == "dp"
    store_uids = {sd[slot].tensor._state_uid
                  for sd in opt._zero["stores"] for slot in sd}
    # every live store rides the carry as sharded, donated state
    assert store_uids <= set(part["sharded"])
    assert store_uids <= set(part["donated"])
    assert analysis.errors(s1.verify()) == []
    # seeded smell: a sharded store the program silently ignores
    part["skipped"] = list(part["skipped"]) + [sorted(store_uids)[0]]
    bad = s1.verify()
    assert any(f.rule == "sharded-state-skipped" and
               f.severity == "warning" for f in bad)
    # seeded hazard: a sharded grad surviving the dp carry
    part["donated_grads"] = list(part["donated_grads"]) + \
        [sorted(store_uids)[0]]
    bad = s1.verify()
    assert any(f.rule == "sharded-grad-carry" and f.severity == "error"
               for f in bad)


def test_verifier_flags_rank_divergent_bucket_order():
    """Two rank programs whose reduce-scatter sequences agree on op kind
    and axis but not payload (swapped bucket layout) must be flagged —
    that skew cross-matches different buckets on the wire."""
    from paddle_tpu import analysis, static
    from paddle_tpu.core.dispatch import call_op

    def rank_prog(bucket_bytes):
        prog = static.Program()
        with static.program_guard(prog):
            g = static.data("g", [4], "float32")
            out = g
            for nb in bucket_bytes:
                def _rs(v, _nb=nb):
                    return v
                _rs._collective_axis = "dp"
                _rs._collective_nbytes = nb
                out = call_op(_rs, out, op_name="c_reducescatter")
            paddle.sum(out)
        return prog

    ok = analysis.check_collective_order(
        [rank_prog([4096, 1024]), rank_prog([4096, 1024])],
        mesh_axes=("dp",))
    assert ok == []
    bad = analysis.check_collective_order(
        [rank_prog([4096, 1024]), rank_prog([1024, 4096])],
        mesh_axes=("dp",))
    assert any(f.rule == "collective-order-mismatch" and
               "bucket" in f.message for f in bad)


def test_zero_with_grad_scaler_parity():
    """GradScaler + ZeRO: found-inf evaluates over the reduced shard and
    the scaled update still matches the replicated-control scaler run."""
    k = 2
    x, y = _batches(k)

    def build(stage):
        paddle.seed(21)
        m = _mlp()
        opt = paddle.optimizer.AdamW(parameters=m.parameters(),
                                     learning_rate=0.05)
        if stage:
            opt._zero_enable(axis="dp", stage=stage)
        scaler = paddle.amp.GradScaler(init_loss_scaling=128.0)

        def one(xb, yb):
            loss = nn.functional.cross_entropy(m(xb), yb)
            scaler.scale(loss).backward()
            scaler.step(opt)
            opt.clear_grad()
            return loss

        return paddle.jit.to_static(one, scan_steps=k, dp_axis="dp"), m

    s0, m0 = build(0)
    s1, m1 = build(1)
    l0 = s0(x, y).numpy()
    l1 = s1(x, y).numpy()
    np.testing.assert_array_equal(l0, l1)
    for p0, p1 in zip(m0.parameters(), m1.parameters()):
        np.testing.assert_array_equal(np.asarray(p0._value),
                                      np.asarray(p1._value))


def test_zero_decay_fn_row_mask_and_missing_grads():
    """The two row-mask paths through the bound shard_map step: AdamW's
    apply_decay_param_fun becomes a per-row mask, and a param without a
    grad holds still — both bitwise vs the replicated control."""
    k = 2
    x, y = _batches(k)

    def build(stage):
        paddle.seed(17)
        m = _mlp()
        no_decay = {m[0].bias.name, m[2].bias.name}
        frozen = m[2].bias  # never receives a grad in this step
        opt = paddle.optimizer.AdamW(
            parameters=m.parameters(), learning_rate=0.05,
            apply_decay_param_fun=lambda n: n not in no_decay)
        if stage:
            opt._zero_enable(axis="dp", stage=stage)

        def one(xb, yb):
            loss = nn.functional.cross_entropy(m(xb), yb)
            loss.backward()
            frozen._grad = None  # simulate an unused head this step
            opt.step()
            opt.clear_grad()
            return loss

        return paddle.jit.to_static(one, scan_steps=k, dp_axis="dp"), m

    s0, m0 = build(0)
    s1, m1 = build(1)
    assert s0(x, y).numpy().tobytes() == s1(x, y).numpy().tobytes()
    for p0, p1 in zip(m0.parameters(), m1.parameters()):
        assert np.asarray(p0._value).tobytes() == \
            np.asarray(p1._value).tobytes(), p0.name


def test_overflow_skips_whole_update_zero_and_control():
    """An inf gradient must leave params AND moments AND masters exactly
    where they were — in the ZeRO shard path and the replicated scaler
    path alike (one poisoned moment NaNs every later step otherwise)."""
    for zero in (0, 1):
        paddle.seed(33)
        m = _mlp()
        opt = paddle.optimizer.AdamW(parameters=m.parameters(),
                                     learning_rate=0.05)
        if zero:
            opt._zero_enable(axis="dp", stage=1)
        scaler = paddle.amp.GradScaler(init_loss_scaling=8.0)
        params = list(m.parameters())
        before_p = [np.asarray(p._value).copy() for p in params]
        loss = nn.functional.cross_entropy(
            m(paddle.to_tensor(rng.rand(8, 16).astype("float32"))),
            paddle.to_tensor(rng.randint(0, 8, 8).astype("int64")))
        scaler.scale(loss).backward()
        params[0]._grad = params[0]._grad.at[0, 0].set(np.inf)
        scaler.step(opt)
        opt.clear_grad()
        for p, old in zip(params, before_p):
            np.testing.assert_array_equal(np.asarray(p._value), old)
        state = opt.state_dict()
        for k, v in state.items():
            if hasattr(v, "numpy"):
                assert np.all(np.isfinite(np.asarray(v.numpy(),
                                                     np.float32))), k
        # and a following finite step still moves the params
        loss = nn.functional.cross_entropy(
            m(paddle.to_tensor(rng.rand(8, 16).astype("float32"))),
            paddle.to_tensor(rng.randint(0, 8, 8).astype("int64")))
        scaler.scale(loss).backward()
        scaler.step(opt)
        opt.clear_grad()
        moved = any(not np.array_equal(np.asarray(p._value), old)
                    for p, old in zip(params, before_p))
        assert moved and all(
            np.all(np.isfinite(np.asarray(p._value, np.float32)))
            for p in params)


def test_zero_enable_conflicting_recall_raises():
    paddle.seed(6)
    m = _mlp()
    opt = paddle.optimizer.Adam(parameters=m.parameters())
    opt._zero_enable(axis="dp", stage=1)
    assert opt._zero_enable(axis="dp", stage=1) == opt._zero["n_sharded"]
    with pytest.raises(RuntimeError, match="already enabled"):
        opt._zero_enable(axis="dp", stage=2)


def test_zero_rejects_unsupported_configs():
    paddle.seed(5)
    m = _mlp()
    lamb = paddle.optimizer.Lamb(parameters=m.parameters())
    with pytest.raises(NotImplementedError, match="non-elementwise"):
        lamb._zero_enable(axis="dp")
    clip = paddle.nn.ClipGradByGlobalNorm(1.0)
    adam = paddle.optimizer.Adam(parameters=m.parameters(), grad_clip=clip)
    with pytest.raises(NotImplementedError, match="grad_clip"):
        adam._zero_enable(axis="dp")
    sgd = paddle.optimizer.SGD(parameters=m.parameters())
    with pytest.raises(ValueError, match="no axis"):
        sgd._zero_enable(axis="nope")


def test_dp_axis_requires_scan():
    with pytest.raises(ValueError, match="scan step"):
        paddle.jit.to_static(lambda x: x, dp_axis="dp")


# -- eager DataParallel comm-buffer fusion (satellite) ----------------------

def test_dataparallel_eager_bucketed_fusion():
    """DataParallel(comm_buffer_size=...) now actually buckets the eager
    grad fusion: counters record bucket count/bytes and the fused
    round-trip preserves gradients (world of one: allreduce == identity,
    mean divisor == 1)."""
    from paddle_tpu.distributed.parallel import DataParallel
    paddle.seed(9)
    m = _mlp()
    # tiny cap: one bucket per param; generous cap: one bucket total
    for cap_mb, want in ((1e-4, 4), (64, 1)):
        dp = DataParallel(m, comm_buffer_size=cap_mb,
                          last_comm_buffer_size=cap_mb)
        loss = dp(paddle.to_tensor(rng.rand(4, 16).astype("float32"))).sum()
        loss.backward()
        before = {p.name: np.asarray(p._grad).copy()
                  for p in m.parameters() if p._grad is not None}
        monitor.stat_reset("dp_fused_buckets")
        monitor.stat_reset("dp_fused_bytes")
        n = dp.apply_collective_grads()
        assert n == want
        assert monitor.stat_get("dp_fused_buckets") == want
        assert monitor.stat_get("dp_fused_bytes") > 0
        for p in m.parameters():
            if p.name in before:
                np.testing.assert_allclose(np.asarray(p._grad),
                                           before[p.name], rtol=1e-6)
        for p in m.parameters():
            p.clear_grad()


# -- reduce_scatter eager fallback validation (satellite) -------------------

def test_reduce_scatter_rejects_mismatched_shapes():
    import paddle_tpu.distributed as dist
    t = paddle.to_tensor(np.zeros(4, np.float32))
    lst = [paddle.to_tensor(np.zeros(4, np.float32)),
           paddle.to_tensor(np.zeros(5, np.float32))]
    with pytest.raises(ValueError, match="identical per-rank shapes"):
        dist.reduce_scatter(t, lst)
    lst2 = [paddle.to_tensor(np.zeros(4, np.float32)),
            paddle.to_tensor(np.zeros(4, np.int64))]
    with pytest.raises(ValueError, match="identical per-rank shapes"):
        dist.reduce_scatter(t, lst2)


def test_reduce_op_validation():
    import paddle_tpu.distributed as dist
    t = paddle.to_tensor(np.ones(4, np.float32))
    with pytest.raises(ValueError, match="unknown ReduceOp"):
        dist.all_reduce(t, op="bogus")
    with pytest.raises(ValueError, match="unknown ReduceOp"):
        dist.reduce_scatter(t, [t], op="bogus")
    with pytest.raises(NotImplementedError, match="not supported"):
        dist.reduce_scatter(t, [t], op=dist.ReduceOp.MAX)
