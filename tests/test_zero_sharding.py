"""ZeRO-1/2/3 sharded data parallelism inside the scan step.

The contract under test: ``to_static(one_step, scan_steps=k,
dp_axis='dp')`` + ``optimizer._zero_enable()`` must be OBSERVABLY
identical to the replicated control — bitwise-equal per-inner-step losses
and final params on the 8-device CPU mesh — while the optimizer state
(and, at stage 3, the parameters themselves) actually lives 1/dp per rank
and the compiled HLO's gradient reduction is bucketed reduce-scatter (+
param all-gather: after the update for stages 1/2, just-in-time before
the forward for stage 3) instead of per-param all-reduce. Gradient
accumulation windows (``accumulate_steps=a``) fire the reduce/update once
per window; the sharded global-norm clip psums per-shard square sums
(tolerance-level parity — the summation order differs from the per-param
control by design)."""
import re

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import monitor, nn
from paddle_tpu.distributed import parallel_env

DP = 8


@pytest.fixture(autouse=True)
def _mesh():
    mesh = parallel_env.make_mesh({"dp": DP})
    parallel_env.set_mesh(mesh)
    yield mesh
    parallel_env.set_mesh(None)
    from paddle_tpu.distributed.fleet.base import topology
    topology.set_hybrid_communicate_group(None)


rng = np.random.RandomState(7)


def _mlp(bf16=False):
    m = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 8))
    if bf16:
        m.to("bfloat16")
    return m


def _build(zero_stage, k, bf16, comm_buffer_mb=None, seed=11,
           accumulate=None, grad_clip=None, prefetch=None):
    paddle.seed(seed)
    m = _mlp(bf16)
    opt = paddle.optimizer.AdamW(parameters=m.parameters(),
                                 learning_rate=0.05,
                                 multi_precision=bf16,
                                 grad_clip=grad_clip)
    if zero_stage:
        opt._zero_enable(axis="dp", stage=zero_stage,
                         comm_buffer_mb=comm_buffer_mb, prefetch=prefetch)

    def one(xb, yb):
        loss = nn.functional.cross_entropy(m(xb), yb)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    step = paddle.jit.to_static(one, scan_steps=k, dp_axis="dp",
                                accumulate_steps=accumulate)
    return step, m, opt


def _batches(k, batch=16):
    x = rng.rand(k, batch, 16).astype("float32")
    y = rng.randint(0, 8, (k, batch)).astype("int64")
    return paddle.to_tensor(x), paddle.to_tensor(y)


_CTRL = {}


def _control_run(k, bf16):
    """Replicated-control reference for (k, bf16): batches, first-call
    losses, post-step params, second-call losses. Computed once and
    shared by the three stage parametrizations (same program, same
    data — rebuilding it per stage only burns compile time)."""
    key = (k, bf16)
    if key not in _CTRL:
        x, y = _batches(k)
        s0, m0, _ = _build(0, k, bf16)
        ref1 = s0(x, y).numpy().tobytes()
        params = [np.asarray(p._value).tobytes() for p in m0.parameters()]
        ref2 = s0(x, y).numpy().tobytes()
        _CTRL[key] = (x, y, ref1, params, ref2)
    return _CTRL[key]


@pytest.mark.parametrize("stage", [1, 2, 3])
@pytest.mark.parametrize("k", [1, 4])
@pytest.mark.parametrize("bf16", [False, True],
                         ids=["fp32", "bf16_master"])
def test_zero_bitwise_matches_replicated_control(stage, k, bf16):
    """Acceptance: zero{1,2,3} × scan_steps {1,4} × {fp32, bf16+master}
    sharded scan losses and final params equal the replicated control
    BITWISE (elementwise update math on a shard == on the whole; stage 3
    reads params through the just-in-time gathered store views)."""
    x, y, ref1, ctrl_params, ref2 = _control_run(k, bf16)
    s1, m1, _ = _build(stage, k, bf16)
    got = s1(x, y).numpy()
    assert ref1 == got.tobytes(), got
    for p1, ctrl in zip(m1.parameters(), ctrl_params):
        assert np.asarray(p1._value).tobytes() == ctrl, p1.name
    # and through the donated carry on a second program call
    assert ref2 == s1(x, y).numpy().tobytes()


def test_zero_state_lives_sharded_1_over_dp():
    """Per-rank optimizer-state bytes shrink ~1/dp: every flat store is
    laid out PartitionSpec('dp', None) and each device holds rows/dp —
    checked through shardcheck's residency verifier (shard shape AND the
    1/dp state-bytes accounting live in one place now)."""
    from paddle_tpu.analysis import check_zero_residency
    k = 2
    s1, _m, opt = _build(1, k, bf16=False)
    x, y = _batches(k)
    s1(x, y)
    stores = [sd[slot] for sd in opt._zero["stores"] for slot in sd]
    assert stores
    assert check_zero_residency(opt) == []
    # spot-check the verifier is looking at real shards, not vacuous
    arr = stores[0].tensor._value
    assert len(arr.sharding.device_set) == DP


def test_zero_hlo_replaces_psum_with_scatter_gather():
    """The compiled program's reduction changes shape: control = one
    all-reduce per param grad; zero = one reduce-scatter per bucket + one
    all-gather per bucket (plus the scalar loss pmean)."""
    k = 2
    x, y = _batches(k)
    s0, _m0, _o0 = _build(0, k, bf16=False)
    s0(x, y)
    s1, _m1, _o1 = _build(1, k, bf16=False)
    s1(x, y)

    ctrl = {s["op"]: s for s in s0.collective_stats()}
    zero = {s["op"]: s for s in s1.collective_stats()}
    # control: per-param psum — at least one all-reduce per trainable
    # param (4: two weights + two biases) + the loss pmean
    assert ctrl["all-reduce"]["count"] >= 5
    assert "reduce-scatter" not in ctrl
    # zero: bucketed scatter/gather; only the scalar loss pmean remains
    assert zero["all-reduce"]["bytes"] <= 8  # one f32 scalar
    assert zero["reduce-scatter"]["axis"] == "dp"
    # the exact scatter/gather multiset is shardcheck's budget contract:
    # the compiled per-execution counts must equal the predicted
    # (stage, k, buckets) schedule — no finding means they do
    from paddle_tpu.analysis import check_collective_budget
    assert check_collective_budget(s1) == []

    # exported counters carry the (op, axis) labels
    for c in ('collective_bytes{op="reduce-scatter",axis="dp"}',
              'collective_count{op="reduce-scatter",axis="dp"}'):
        monitor.stat_reset(c)
    s1.export_collective_bytes()
    assert monitor.stat_get(
        'collective_bytes{op="reduce-scatter",axis="dp"}') > 0
    assert monitor.stat_get(
        'collective_count{op="reduce-scatter",axis="dp"}') >= 1


def test_zero_comm_buffer_size_buckets():
    """comm_buffer_mb caps the bucket payload: tiny cap → one bucket per
    param, one reduce-scatter each in the HLO."""
    k = 1
    s1, _m, opt = _build(1, k, bf16=False, comm_buffer_mb=0.0001)
    n_buckets = len(opt._zero["buckets"])
    assert n_buckets == 4  # 2 weights + 2 biases, each over the tiny cap
    x, y = _batches(k)
    first = s1(x, y).numpy()
    # shardcheck reads the bucket count out of the partition provenance
    # and holds the compiled schedule to one rs+ag pair per bucket
    from paddle_tpu.analysis import (check_collective_budget,
                                     infer_zero_layout)
    layout = infer_zero_layout(s1)
    assert layout["stage"] == 1 and layout["n_buckets"] == n_buckets
    assert check_collective_budget(s1) == []
    # bitwise parity holds regardless of bucketing (fresh first calls on
    # both sides — state advances per call)
    s0, _m0, _o0 = _build(0, k, bf16=False)
    assert s0(x, y).numpy().tobytes() == first.tobytes()


def test_zero_partition_and_verifier():
    """The scan partition records the sharded carry and dp axis; the
    static-analysis pass accepts the build."""
    from paddle_tpu import analysis
    k = 2
    s1, _m, opt = _build(1, k, bf16=False)
    x, y = _batches(k)
    s1(x, y)
    part = s1._last_partition
    assert part["dp_axis"] == "dp"
    store_uids = {sd[slot].tensor._state_uid
                  for sd in opt._zero["stores"] for slot in sd}
    # every live store rides the carry as sharded, donated state
    assert store_uids <= set(part["sharded"])
    assert store_uids <= set(part["donated"])
    assert analysis.errors(s1.verify()) == []
    # seeded smell: a sharded store the program silently ignores
    part["skipped"] = list(part["skipped"]) + [sorted(store_uids)[0]]
    bad = s1.verify()
    assert any(f.rule == "sharded-state-skipped" and
               f.severity == "warning" for f in bad)
    # seeded hazard: a sharded grad surviving the dp carry
    part["donated_grads"] = list(part["donated_grads"]) + \
        [sorted(store_uids)[0]]
    bad = s1.verify()
    assert any(f.rule == "sharded-grad-carry" and f.severity == "error"
               for f in bad)


def test_verifier_flags_rank_divergent_bucket_order():
    """Two rank programs whose reduce-scatter sequences agree on op kind
    and axis but not payload (swapped bucket layout) must be flagged —
    that skew cross-matches different buckets on the wire. Swapped
    buckets are a pure permutation of the same collective multiset, so
    the checker diagnoses it as collective-schedule-skew (a
    deterministic reorder, e.g. pipelining enabled on one rank only)
    rather than raw per-position mismatches."""
    from paddle_tpu import analysis, static
    from paddle_tpu.core.dispatch import call_op

    def rank_prog(bucket_bytes):
        prog = static.Program()
        with static.program_guard(prog):
            g = static.data("g", [4], "float32")
            out = g
            for nb in bucket_bytes:
                def _rs(v, _nb=nb):
                    return v
                _rs._collective_axis = "dp"
                _rs._collective_nbytes = nb
                out = call_op(_rs, out, op_name="c_reducescatter")
            paddle.sum(out)
        return prog

    ok = analysis.check_collective_order(
        [rank_prog([4096, 1024]), rank_prog([4096, 1024])],
        mesh_axes=("dp",))
    assert ok == []
    bad = analysis.check_collective_order(
        [rank_prog([4096, 1024]), rank_prog([1024, 4096])],
        mesh_axes=("dp",))
    assert any(f.rule == "collective-schedule-skew" and
               f.severity == "error" for f in bad)
    # a genuinely divergent layout (different payload multiset) still
    # reports the per-position mismatch, not a schedule reorder
    bad2 = analysis.check_collective_order(
        [rank_prog([4096, 1024]), rank_prog([4096, 999])],
        mesh_axes=("dp",))
    assert any(f.rule == "collective-order-mismatch" and
               "bucket" in f.message for f in bad2)
    assert not any(f.rule == "collective-schedule-skew" for f in bad2)


def test_zero_with_grad_scaler_parity():
    """GradScaler + ZeRO: found-inf evaluates over the reduced shard and
    the scaled update still matches the replicated-control scaler run."""
    k = 2
    x, y = _batches(k)

    def build(stage):
        paddle.seed(21)
        m = _mlp()
        opt = paddle.optimizer.AdamW(parameters=m.parameters(),
                                     learning_rate=0.05)
        if stage:
            opt._zero_enable(axis="dp", stage=stage)
        scaler = paddle.amp.GradScaler(init_loss_scaling=128.0)

        def one(xb, yb):
            loss = nn.functional.cross_entropy(m(xb), yb)
            scaler.scale(loss).backward()
            scaler.step(opt)
            opt.clear_grad()
            return loss

        return paddle.jit.to_static(one, scan_steps=k, dp_axis="dp"), m

    s0, m0 = build(0)
    s1, m1 = build(1)
    l0 = s0(x, y).numpy()
    l1 = s1(x, y).numpy()
    np.testing.assert_array_equal(l0, l1)
    for p0, p1 in zip(m0.parameters(), m1.parameters()):
        np.testing.assert_array_equal(np.asarray(p0._value),
                                      np.asarray(p1._value))


@pytest.mark.parametrize("stage", [1, 3])
def test_zero_scaler_accumulation_window_parity(stage):
    """GradScaler across an accumulation window: grads stay scaled until
    the boundary, the found-inf check covers the whole window on the
    reduced shard, and losses/params match the replicated-control run of
    the same window."""
    k, a = 4, 2
    x, y = _batches(k)

    def build(zero):
        paddle.seed(23)
        m = _mlp()
        opt = paddle.optimizer.AdamW(parameters=m.parameters(),
                                     learning_rate=0.05)
        if zero:
            opt._zero_enable(axis="dp", stage=zero)
        scaler = paddle.amp.GradScaler(init_loss_scaling=128.0)

        def one(xb, yb):
            loss = nn.functional.cross_entropy(m(xb), yb)
            scaler.scale(loss).backward()
            scaler.step(opt)
            opt.clear_grad()
            return loss

        return paddle.jit.to_static(one, scan_steps=k, dp_axis="dp",
                                    accumulate_steps=a), m

    s0, m0 = build(0)
    s1, m1 = build(stage)
    l0 = s0(x, y).numpy()
    l1 = s1(x, y).numpy()
    np.testing.assert_allclose(l0, l1, rtol=1e-6)
    for p0, p1 in zip(m0.parameters(), m1.parameters()):
        np.testing.assert_allclose(np.asarray(p0._value),
                                   np.asarray(p1._value), rtol=1e-5,
                                   atol=1e-7, err_msg=p0.name)


def test_scaler_manual_unscale_in_window_rejected():
    """scaler.unscale_ inside an accumulation window would mix unscaled
    and scaled micro gradients (the next backward adds SCALED grads onto
    the unscaled sum) — rejected loudly at trace time on every path."""
    paddle.seed(31)
    m = _mlp()
    opt = paddle.optimizer.AdamW(parameters=m.parameters(),
                                 learning_rate=0.05)
    scaler = paddle.amp.GradScaler(init_loss_scaling=64.0)

    def one(xb, yb):
        loss = nn.functional.cross_entropy(m(xb), yb)
        scaler.scale(loss).backward()
        scaler.unscale_(opt)  # the eager clip workflow — not windowable
        scaler.step(opt)
        opt.clear_grad()
        return loss

    s = paddle.jit.to_static(one, scan_steps=2, dp_axis="dp",
                             accumulate_steps=2)
    x, y = _batches(2)
    with pytest.raises(RuntimeError, match="accumulation window"):
        s(x, y)


def test_zero_decay_fn_row_mask_and_missing_grads():
    """The two row-mask paths through the bound shard_map step: AdamW's
    apply_decay_param_fun becomes a per-row mask, and a param without a
    grad holds still — both bitwise vs the replicated control."""
    k = 2
    x, y = _batches(k)

    def build(stage):
        paddle.seed(17)
        m = _mlp()
        no_decay = {m[0].bias.name, m[2].bias.name}
        frozen = m[2].bias  # never receives a grad in this step
        opt = paddle.optimizer.AdamW(
            parameters=m.parameters(), learning_rate=0.05,
            apply_decay_param_fun=lambda n: n not in no_decay)
        if stage:
            opt._zero_enable(axis="dp", stage=stage)

        def one(xb, yb):
            loss = nn.functional.cross_entropy(m(xb), yb)
            loss.backward()
            frozen._grad = None  # simulate an unused head this step
            opt.step()
            opt.clear_grad()
            return loss

        return paddle.jit.to_static(one, scan_steps=k, dp_axis="dp"), m

    s0, m0 = build(0)
    s1, m1 = build(1)
    assert s0(x, y).numpy().tobytes() == s1(x, y).numpy().tobytes()
    for p0, p1 in zip(m0.parameters(), m1.parameters()):
        assert np.asarray(p0._value).tobytes() == \
            np.asarray(p1._value).tobytes(), p0.name


def test_overflow_skips_whole_update_zero_and_control():
    """An inf gradient must leave params AND moments AND masters exactly
    where they were — in the ZeRO shard path (stages 1 and 3, the latter
    through the eager store-view params) and the replicated scaler path
    alike (one poisoned moment NaNs every later step otherwise)."""
    for zero in (0, 1, 3):
        paddle.seed(33)
        m = _mlp()
        opt = paddle.optimizer.AdamW(parameters=m.parameters(),
                                     learning_rate=0.05)
        if zero:
            opt._zero_enable(axis="dp", stage=1)
        scaler = paddle.amp.GradScaler(init_loss_scaling=8.0)
        params = list(m.parameters())
        before_p = [np.asarray(p._value).copy() for p in params]
        loss = nn.functional.cross_entropy(
            m(paddle.to_tensor(rng.rand(8, 16).astype("float32"))),
            paddle.to_tensor(rng.randint(0, 8, 8).astype("int64")))
        scaler.scale(loss).backward()
        params[0]._grad = params[0]._grad.at[0, 0].set(np.inf)
        scaler.step(opt)
        opt.clear_grad()
        for p, old in zip(params, before_p):
            np.testing.assert_array_equal(np.asarray(p._value), old)
        state = opt.state_dict()
        for k, v in state.items():
            if hasattr(v, "numpy"):
                assert np.all(np.isfinite(np.asarray(v.numpy(),
                                                     np.float32))), k
        # and a following finite step still moves the params
        loss = nn.functional.cross_entropy(
            m(paddle.to_tensor(rng.rand(8, 16).astype("float32"))),
            paddle.to_tensor(rng.randint(0, 8, 8).astype("int64")))
        scaler.scale(loss).backward()
        scaler.step(opt)
        opt.clear_grad()
        moved = any(not np.array_equal(np.asarray(p._value), old)
                    for p, old in zip(params, before_p))
        assert moved and all(
            np.all(np.isfinite(np.asarray(p._value, np.float32)))
            for p in params)


def test_zero_enable_conflicting_recall_raises():
    paddle.seed(6)
    m = _mlp()
    opt = paddle.optimizer.Adam(parameters=m.parameters())
    opt._zero_enable(axis="dp", stage=1)
    assert opt._zero_enable(axis="dp", stage=1) == opt._zero["n_sharded"]
    with pytest.raises(RuntimeError, match="already enabled"):
        opt._zero_enable(axis="dp", stage=2)


def test_zero_rejects_unsupported_configs():
    """The remaining rejections stay loud AND name the issue that scoped
    them; ClipGradByGlobalNorm/ByValue and per-param lr are now routed
    through the flat-view path instead of rejected."""
    paddle.seed(5)
    m = _mlp()
    lamb = paddle.optimizer.Lamb(parameters=m.parameters())
    with pytest.raises(NotImplementedError, match="non-elementwise"):
        lamb._zero_enable(axis="dp")
    with pytest.raises(NotImplementedError, match="ISSUE 5"):
        lamb._zero_enable(axis="dp")
    # per-TENSOR-norm clip still can't reassemble on a flat shard
    clip = paddle.nn.ClipGradByNorm(1.0)
    adam = paddle.optimizer.Adam(parameters=m.parameters(), grad_clip=clip)
    with pytest.raises(NotImplementedError, match="ISSUE 5"):
        adam._zero_enable(axis="dp")
    # global-norm and value clip now enable fine
    for ok_clip in (paddle.nn.ClipGradByGlobalNorm(1.0),
                    paddle.nn.ClipGradByValue(1.0)):
        paddle.seed(5)
        m2 = _mlp()
        opt = paddle.optimizer.Adam(parameters=m2.parameters(),
                                    grad_clip=ok_clip)
        assert opt._zero_enable(axis="dp") > 0
    sgd = paddle.optimizer.SGD(parameters=m.parameters())
    with pytest.raises(ValueError, match="no axis"):
        sgd._zero_enable(axis="nope")


def test_zero3_param_residency_and_carry():
    """Stage 3: the flat sharded param store is the ONLY parameter
    residency — live Parameter objects are store views outside the
    framework-state registry, so no full parameter rides the donated
    carry; per-rank optimizer+param state bytes measure ~1/dp."""
    k = 2
    s3, m, opt = _build(3, k, bf16=False)
    x, y = _batches(k)
    before = [np.asarray(p._value).copy() for p in m.parameters()]
    s3(x, y)
    # params converted to views: unregistered, store-backed, readable
    for p, old in zip(m.parameters(), before):
        assert p._state_uid is None
        assert "_value" not in p.__dict__
        assert not np.array_equal(np.asarray(p._value), old), p.name
    pstores = [sd["param"] for sd in opt._zero["stores"]]
    assert pstores
    # shard shape AND the 1/dp state-bytes accounting — moment, master
    # and param stores alike — are shardcheck's residency contract
    from paddle_tpu.analysis import check_zero_residency
    assert check_zero_residency(opt) == []
    # the carry holds the sharded stores, not the params
    part = s3._last_partition
    store_uids = {sd[slot].tensor._state_uid
                  for sd in opt._zero["stores"] for slot in sd
                  if slot != "gacc"}
    assert store_uids <= set(part["donated"])
    assert store_uids <= set(part["sharded"])
    # eager writes round-trip through the store (checkpoint load path)
    p0 = list(m.parameters())[0]
    p0.set_value(np.zeros(p0.shape, np.float32))
    assert np.all(np.asarray(p0._value) == 0.0)
    # the verifier accepts the build (gacc skipping included)
    from paddle_tpu import analysis
    assert analysis.errors(s3.verify()) == []


def test_zero3_hlo_ag_fwd_rs_pattern():
    """Stage-3 compiled HLO, serial schedule (prefetch=False): params
    all-gather JUST-IN-TIME before the forward matmuls, the gradient
    reduce-scatter follows them, and no all-gather trails the update
    (refreshed params stay sharded). The pipelined default moves that
    gather to the tail of the previous iteration — so the body's first
    all-gather lands AFTER the reduce-scatter — without changing the
    per-execution collective counts.

    Deliberately the raw-HLO CANARY: every other collective-count
    assertion in this file rides shardcheck's budget verifier; this one
    keeps matching the compiled text directly so a parser regression in
    hlo_bytes/shardcheck cannot silently blind the whole suite."""
    k = 2
    s3, _m, opt = _build(3, k, bf16=False, prefetch=False)
    x, y = _batches(k)
    s3(x, y)
    hlo = s3.hlo_text()
    body = max((c for c in hlo.split("\n\n") if "reduce-scatter" in c),
               key=len, default=hlo)
    i_ag = body.index("all-gather")
    i_dot = body.index("dot(", i_ag)
    i_rs = body.index("reduce-scatter", i_dot)
    assert i_ag < i_dot < i_rs
    stats = {s["op"]: s for s in s3.collective_stats(per_execution=True)}
    n_buckets = len(opt._zero["buckets"])
    # exactly one gather (forward) + one reduce-scatter per bucket per
    # step — per-execution counts prove it through the scan trip count
    assert stats["all-gather"]["count"] == n_buckets * k
    assert stats["reduce-scatter"]["count"] == n_buckets * k
    assert stats.get("all-reduce", {"bytes": 0})["bytes"] <= 8 * k
    # pipelined twin: the prefetch slot is warmed by a tail gather, so
    # the loop body now ENDS with an all-gather (it feeds the NEXT
    # iteration's forward) while the collective budget stays identical
    sp, _mp, optp = _build(3, k, bf16=False, seed=11)
    sp(x, y)
    hlop = sp.hlo_text()
    bodyp = max((c for c in hlop.split("\n\n") if "reduce-scatter" in c),
                key=len, default=hlop)
    assert bodyp.rindex("all-gather") > bodyp.index("reduce-scatter")
    statsp = {s["op"]: s for s in sp.collective_stats(per_execution=True)}
    assert statsp["all-gather"]["count"] == stats["all-gather"]["count"]
    assert statsp["reduce-scatter"]["count"] == \
        stats["reduce-scatter"]["count"]


def test_accumulation_matches_big_batch():
    """a accumulated micro steps == one step on the a-times batch (up to
    dtype tolerance: the big batch sums losses in one reduction, the
    window sums a per-micro means — fp32 rtol 1e-5)."""
    a, bs = 4, 16
    # dedicated rng: the comparison tolerance is calibrated to THIS data,
    # so the inputs must not shift with whichever tests ran before
    drng = np.random.RandomState(42)
    xs = drng.rand(a, bs, 16).astype("float32")
    ys = drng.randint(0, 8, (a, bs)).astype("int64")
    s_acc, m_acc, _ = _build(0, a, bf16=False, accumulate=a)
    l_acc = s_acc(paddle.to_tensor(xs), paddle.to_tensor(ys)).numpy()

    s_big, m_big, _ = _build(0, 1, bf16=False)
    l_big = s_big(paddle.to_tensor(xs.reshape(1, a * bs, 16)),
                  paddle.to_tensor(ys.reshape(1, a * bs))).numpy()
    np.testing.assert_allclose(l_acc.mean(), l_big[0], rtol=1e-6)
    for p1, p2 in zip(m_acc.parameters(), m_big.parameters()):
        np.testing.assert_allclose(np.asarray(p1._value),
                                   np.asarray(p2._value), rtol=2e-4,
                                   atol=1e-6, err_msg=p1.name)


@pytest.mark.parametrize("stage", [1, 2, 3])
def test_zero_accumulation_matches_accumulating_control(stage):
    """zero{1,2,3} under an accumulation window vs the replicated control
    under the same window: stage 1 accumulates the same per-param local
    sums and reduces once (bitwise); stages 2/3 reduce every micro step
    into the sharded window accumulator — a different summation order, so
    tolerance-level parity."""
    k, a = 4, 2
    x, y = _batches(k)
    s0, m0, _ = _build(0, k, bf16=False, accumulate=a)
    ref = s0(x, y).numpy()
    s1, m1, _ = _build(stage, k, bf16=False, accumulate=a)
    got = s1(x, y).numpy()
    if stage <= 1:
        assert ref.tobytes() == got.tobytes(), (ref, got)
        for p0, p1 in zip(m0.parameters(), m1.parameters()):
            assert np.asarray(p0._value).tobytes() == \
                np.asarray(p1._value).tobytes(), p0.name
    else:
        # per-micro reduction reorders the accumulation sum: parity is
        # tolerance-level (fp32 ulps through AdamW's divide), and losses
        # after the first boundary inherit it
        np.testing.assert_allclose(ref, got, rtol=1e-6)
        for p0, p1 in zip(m0.parameters(), m1.parameters()):
            np.testing.assert_allclose(
                np.asarray(p0._value), np.asarray(p1._value),
                rtol=5e-5, atol=1e-6, err_msg=p0.name)


def test_zero1_accumulation_cuts_collective_bytes():
    """The headline wire saving: with accumulate_steps=a the compiled
    program fires exactly ONE reduce-scatter/all-gather pair per bucket
    per window — per-execution (trip-count-weighted) collective bytes
    drop exactly a× vs the per-step schedule, and the collective_bytes
    counters carry the same numbers."""
    k, a = 4, 4
    x, y = _batches(k)
    s_no, _m0, opt0 = _build(1, k, bf16=False)
    s_no(x, y)
    s_acc, _m1, opt1 = _build(1, k, bf16=False, accumulate=a)
    s_acc(x, y)
    n_buckets = len(opt1._zero["buckets"])
    no = {s["op"]: s for s in s_no.collective_stats(per_execution=True)}
    ac = {s["op"]: s for s in s_acc.collective_stats(per_execution=True)}
    # the a× count drop IS the predicted budget: nb*k per-step vs
    # nb*(k//a) per-window — assert through the predictor so these
    # numbers live in one place, then hold both builds to their budgets
    from paddle_tpu.analysis import (check_collective_budget,
                                     predict_collective_budget)
    per_step = predict_collective_budget(1, scan_steps=k,
                                         n_buckets=n_buckets)
    per_win = predict_collective_budget(1, scan_steps=k,
                                        accumulate_steps=a,
                                        n_buckets=n_buckets)
    for op in ("reduce-scatter", "all-gather"):
        assert no[op]["count"] == per_step[(op, "dp")] == n_buckets * k
        assert ac[op]["count"] == per_win[(op, "dp")] == n_buckets * (k // a)
        assert ac[op]["bytes"] * a == no[op]["bytes"], (op, no[op], ac[op])
    assert check_collective_budget(s_no) == []
    assert check_collective_budget(s_acc) == []
    # static (per-text) counts still see one op per bucket
    static = {s["op"]: s for s in s_acc.collective_stats()}
    assert static["reduce-scatter"]["count"] == n_buckets


def test_zero3_accumulation_uses_sharded_gacc():
    """Stages 2/3 fold every micro step's reduced mean shard into the
    sharded gacc store (no full gradient outlives a micro step); the
    window accumulator returns to zeros once the boundary update fires."""
    import gc
    k, a = 2, 2
    x, y = _batches(k)
    s3, _m, opt = _build(3, k, bf16=False, accumulate=a)
    s3(x, y)
    for sd in opt._zero["stores"]:
        g = np.asarray(sd["gacc"].tensor._value)
        assert g.shape[0] % DP == 0
        assert np.all(g == 0.0)  # consumed by the boundary update
    del s3, _m, opt
    gc.collect()  # drop the first optimizer's registered stores
    # the gacc stores ride the carry only under accumulation: the
    # non-accumulating build skips its OWN gacc without a verifier
    # warning (carry-optional exemption)
    s_plain, _m2, o2 = _build(3, k, bf16=False)
    s_plain(x, y)
    gacc_uids = {sd["gacc"].tensor._state_uid
                 for sd in o2._zero["stores"]}
    part = s_plain._last_partition
    assert gacc_uids <= set(part["skipped"])
    assert gacc_uids <= set(part["carry_optional"])
    from paddle_tpu import analysis
    findings = s_plain.verify()
    # THIS build's gacc stores are exempt from the stale-store warning
    # (other tests' leaked optimizers may legitimately still warn)
    warned_uids = {int(m.group(1)) for f in findings
                   if f.rule == "sharded-state-skipped"
                   for m in [re.search(r"state uid (\d+)", f.message)] if m}
    assert not (warned_uids & gacc_uids)
    assert analysis.errors(findings) == []


@pytest.mark.parametrize("stage", [1, 3])
def test_zero_global_norm_clip_vs_replicated(stage):
    """ClipGradByGlobalNorm over shards: the scale comes from a psum of
    per-shard square sums — same math as the per-param control up to
    summation order, so losses match exactly and params to fp32
    tolerance. ClipGradByValue is elementwise and stays bitwise."""
    k = 2
    x, y = _batches(k)
    s0, m0, _ = _build(0, k, bf16=False,
                       grad_clip=paddle.nn.ClipGradByGlobalNorm(0.02))
    l0 = s0(x, y).numpy()
    s1, m1, _ = _build(stage, k, bf16=False,
                       grad_clip=paddle.nn.ClipGradByGlobalNorm(0.02))
    l1 = s1(x, y).numpy()
    np.testing.assert_allclose(l0, l1, rtol=1e-6)
    for p0, p1 in zip(m0.parameters(), m1.parameters()):
        np.testing.assert_allclose(np.asarray(p0._value),
                                   np.asarray(p1._value), rtol=1e-5,
                                   atol=1e-7, err_msg=p0.name)
    # value clip: elementwise on the shard == elementwise on the whole
    sv0, mv0, _ = _build(0, k, bf16=False,
                         grad_clip=paddle.nn.ClipGradByValue(0.001))
    sv1, mv1, _ = _build(stage, k, bf16=False,
                         grad_clip=paddle.nn.ClipGradByValue(0.001))
    assert sv0(x, y).numpy().tobytes() == sv1(x, y).numpy().tobytes()
    for p0, p1 in zip(mv0.parameters(), mv1.parameters()):
        assert np.asarray(p0._value).tobytes() == \
            np.asarray(p1._value).tobytes(), p0.name


def test_zero_per_param_lr_bitwise():
    """A per-param lr scale becomes a [rows, 1] multiplier over the flat
    shard — bitwise vs the control's scalar per-param lr."""
    k = 2
    x, y = _batches(k)

    def build(stage):
        paddle.seed(13)
        m = _mlp()
        m[0].weight.optimize_attr = {"learning_rate": 0.5}
        opt = paddle.optimizer.AdamW(parameters=m.parameters(),
                                     learning_rate=0.05)
        if stage:
            opt._zero_enable(axis="dp", stage=stage)

        def one(xb, yb):
            loss = nn.functional.cross_entropy(m(xb), yb)
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss

        return paddle.jit.to_static(one, scan_steps=k, dp_axis="dp"), m

    s0, m0 = build(0)
    ref = s0(x, y).numpy()
    for stage in (1, 3):
        s1, m1 = build(stage)
        assert s1(x, y).numpy().tobytes() == ref.tobytes()
        for p0, p1 in zip(m0.parameters(), m1.parameters()):
            assert np.asarray(p0._value).tobytes() == \
                np.asarray(p1._value).tobytes(), (stage, p0.name)


def test_zero3_hook_leaves_unrelated_programs_alone():
    """The stage-3 materialize hook is LAZY: a trace that never reads the
    model's params issues no gathers, so the param/moment stores of a
    live stage-3 optimizer are not threaded into unrelated compiled
    programs (they stay skipped state, not read-only inputs)."""
    k = 1
    s3, _m, opt = _build(3, k, bf16=False)
    x, y = _batches(k)
    s3(x, y)
    store_uids = {sd[slot].tensor._state_uid
                  for sd in opt._zero["stores"] for slot in sd}

    # an independent model's step, traced while opt is alive
    paddle.seed(3)
    other = _mlp()
    oopt = paddle.optimizer.SGD(parameters=other.parameters(),
                                learning_rate=0.1)

    def one(xb, yb):
        loss = nn.functional.cross_entropy(other(xb), yb)
        loss.backward()
        oopt.step()
        oopt.clear_grad()
        return loss

    s_other = paddle.jit.to_static(one, scan_steps=k, dp_axis="dp")
    s_other(x, y)
    part = s_other._last_partition
    assert store_uids.isdisjoint(part["donated"])
    assert store_uids.isdisjoint(part["readonly"])
    assert store_uids <= set(part["skipped"])
    # and the stage-3 program still trains after the unrelated trace
    before = s3(x, y).numpy()
    assert np.isfinite(before).all()


def test_accumulate_steps_validation():
    with pytest.raises(ValueError, match="multiple of"):
        paddle.jit.to_static(lambda x: x, scan_steps=3, dp_axis="dp",
                             accumulate_steps=2)
    with pytest.raises(ValueError, match="scan step"):
        paddle.jit.to_static(lambda x: x, accumulate_steps=2)
    # a=1 degenerates to the plain scan
    sfn = paddle.jit.to_static(lambda x: x, scan_steps=2,
                               accumulate_steps=1)
    assert sfn._accumulate_steps is None


def test_collective_cadence_mismatch_flagged():
    """Window-stamped collectives: ranks agreeing on a per-window cadence
    verify clean; a per-step rank against a per-window rank is flagged as
    a cadence mismatch (not generic divergence) naming both cadences."""
    from paddle_tpu import analysis, static
    from paddle_tpu.core.dispatch import call_op

    def rank_prog(every):
        prog = static.Program()
        with static.program_guard(prog):
            g = static.data("g", [4], "float32")

            def _rs(v):
                return v
            _rs._collective_axis = "dp"
            _rs._collective_nbytes = 16
            _rs._collective_every = every
            out = call_op(_rs, g, op_name="c_reducescatter")
            paddle.sum(out)
        return prog

    ok = analysis.check_collective_order(
        [rank_prog(4), rank_prog(4)], mesh_axes=("dp",))
    assert ok == []
    bad = analysis.check_collective_order(
        [rank_prog(1), rank_prog(4)], mesh_axes=("dp",))
    assert any(f.rule == "collective-cadence-mismatch"
               and "per-window" in f.message for f in bad)


def test_zero3_ladder_twin_verifies_clean():
    """The zero3 analysis ladder twin (ag->fwd + window-gated rs, both
    ranks cadence-stamped) passes the full analyzer — the programs
    run_all's --write-baseline gate insists on."""
    from paddle_tpu.analysis import ladder
    findings, summary = ladder.verify_ladder(["zero3"])
    assert findings == []
    assert summary["zero3"] == [len(p.ops) for p, _ in
                                ladder.LADDER_BUILDERS["zero3"]()]


def test_dp_axis_requires_scan():
    with pytest.raises(ValueError, match="scan step"):
        paddle.jit.to_static(lambda x: x, dp_axis="dp")


# -- eager DataParallel comm-buffer fusion (satellite) ----------------------

def test_dataparallel_eager_bucketed_fusion():
    """DataParallel(comm_buffer_size=...) now actually buckets the eager
    grad fusion: counters record bucket count/bytes and the fused
    round-trip preserves gradients (world of one: allreduce == identity,
    mean divisor == 1)."""
    from paddle_tpu.distributed.parallel import DataParallel
    paddle.seed(9)
    m = _mlp()
    # tiny cap: one bucket per param; generous cap: one bucket total
    for cap_mb, want in ((1e-4, 4), (64, 1)):
        dp = DataParallel(m, comm_buffer_size=cap_mb,
                          last_comm_buffer_size=cap_mb)
        loss = dp(paddle.to_tensor(rng.rand(4, 16).astype("float32"))).sum()
        loss.backward()
        before = {p.name: np.asarray(p._grad).copy()
                  for p in m.parameters() if p._grad is not None}
        monitor.stat_reset("dp_fused_buckets")
        monitor.stat_reset("dp_fused_bytes")
        n = dp.apply_collective_grads()
        assert n == want
        assert monitor.stat_get("dp_fused_buckets") == want
        assert monitor.stat_get("dp_fused_bytes") > 0
        for p in m.parameters():
            if p.name in before:
                np.testing.assert_allclose(np.asarray(p._grad),
                                           before[p.name], rtol=1e-6)
        for p in m.parameters():
            p.clear_grad()


# -- reduce_scatter eager fallback validation (satellite) -------------------

def test_reduce_scatter_rejects_mismatched_shapes():
    import paddle_tpu.distributed as dist
    t = paddle.to_tensor(np.zeros(4, np.float32))
    lst = [paddle.to_tensor(np.zeros(4, np.float32)),
           paddle.to_tensor(np.zeros(5, np.float32))]
    with pytest.raises(ValueError, match="identical per-rank shapes"):
        dist.reduce_scatter(t, lst)
    lst2 = [paddle.to_tensor(np.zeros(4, np.float32)),
            paddle.to_tensor(np.zeros(4, np.int64))]
    with pytest.raises(ValueError, match="identical per-rank shapes"):
        dist.reduce_scatter(t, lst2)


def test_reduce_op_validation():
    import paddle_tpu.distributed as dist
    t = paddle.to_tensor(np.ones(4, np.float32))
    with pytest.raises(ValueError, match="unknown ReduceOp"):
        dist.all_reduce(t, op="bogus")
    with pytest.raises(ValueError, match="unknown ReduceOp"):
        dist.reduce_scatter(t, [t], op="bogus")
    with pytest.raises(NotImplementedError, match="not supported"):
        dist.reduce_scatter(t, [t], op=dist.ReduceOp.MAX)
