"""Observability v2: trace-context propagation, the structured run-log,
the crash flight recorder, and the multi-process trace merge tool.

Acceptance (ISSUE 8): a serving request under concurrent load and a PS
push surviving a retry each yield ONE connected trace (request -> batch
-> device step; client attempt -> server apply) reconstructible by
tools/trace_view.py from multi-process run-logs; a fired kill-point
leaves a readable flight-recorder dump whose last span names the kill
site (the chaos-tier twin lives in test_chaos.py).
"""
import json
import os
import sys
import threading

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.observability as obs
from paddle_tpu import _native, profiler
from paddle_tpu.observability import export as export_mod
from paddle_tpu.observability import flight, runlog
from paddle_tpu.observability import tracing as tracing_mod
from paddle_tpu.testing import faults

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))
import trace_view  # noqa: E402  (tools/ is not a package)


@pytest.fixture()
def tracing(tmp_path):
    """Tracing + run-log session writing into tmp_path; everything torn
    down (observability state is process-global)."""
    profiler.reset()
    flight.clear()
    obs.enable()
    log = obs.start_run(dir=str(tmp_path / "logs"), run_id="t",
                        rank=0)
    try:
        yield log
    finally:
        obs.stop_run()
        obs.disable()
        flight.uninstall()
        flight.clear()
        profiler.reset()
        faults.reset()


def _load(tmp_path):
    d = str(tmp_path / "logs")
    paths = [os.path.join(d, f) for f in sorted(os.listdir(d))]
    events, bad = trace_view.load_events(paths)
    assert bad == 0
    return events


# -- trace context ---------------------------------------------------------

def test_span_ids_nest_and_propagate(tracing):
    with obs.trace_span("outer", cat="user") as o:
        assert o.trace_id != 0 and o.parent_id == 0
        assert obs.trace_context() == (o.trace_id, o.span_id)
        with obs.trace_span("inner", cat="user") as i:
            assert i.trace_id == o.trace_id
            assert i.parent_id == o.span_id
            assert i.span_id not in (0, o.span_id)
    assert obs.trace_context() is None
    # distinct roots mint distinct traces
    with obs.trace_span("other", cat="user") as p:
        assert p.trace_id != o.trace_id


def test_attach_context_adopts_remote_parent(tracing):
    with tracing_mod.attach_context(0xabc, 0xdef):
        assert obs.trace_context() == (0xabc, 0xdef)
        with obs.trace_span("adopted", cat="user") as s:
            assert s.trace_id == 0xabc and s.parent_id == 0xdef
    assert obs.trace_context() is None


def test_mint_and_retrospective_record(tracing, tmp_path):
    with obs.trace_span("parent", cat="user") as p:
        tr, sp, pa = obs.mint_context()
        assert tr == p.trace_id and pa == p.span_id
    got = obs.record_span("retro", "user", 100, 200, trace_id=tr,
                          span_id=sp, parent_id=pa, foo="bar")
    assert got == (tr, sp)
    obs.stop_run()
    events = _load(tmp_path)
    rec = [e for e in events if e.get("name") == "retro"][0]
    assert rec["trace"] == f"{tr:016x}" and rec["parent"] == f"{pa:016x}"
    assert rec["attrs"]["foo"] == "bar"


def test_record_span_is_noop_when_disabled():
    obs.disable()
    assert obs.record_span("x", "user", 0, 1) is None


# -- run-log ----------------------------------------------------------------

def test_runlog_manifest_spans_events(tracing, tmp_path):
    with obs.trace_span("work", cat="user"):
        pass
    runlog.event("custom", value=7)
    obs.stop_run()
    events = _load(tmp_path)
    kinds = [e["kind"] for e in events]
    assert kinds[0] == "manifest"
    m = events[0]
    assert m["run_id"] == "t" and m["rank"] == 0
    assert m["pid"] == os.getpid()
    assert m["git_sha"] is None or len(m["git_sha"]) == 40
    assert "mono_ns" in m and "time" in m
    span = [e for e in events if e.get("name") == "work"][0]
    assert len(span["trace"]) == 16 and span["dur"] >= 0
    ev = [e for e in events if e.get("event") == "custom"][0]
    assert ev["value"] == 7


def test_runlog_env_activation(tmp_path, monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_RUNLOG_DIR", str(tmp_path / "envlogs"))
    profiler.reset()
    obs.enable()
    try:
        assert runlog.active() is not None
        assert str(tmp_path / "envlogs") in runlog.log_path()
    finally:
        obs.stop_run()
        obs.disable()
        profiler.reset()


def test_trace_view_merges_multi_rank_logs(tmp_path):
    """Two ranks' logs merge into one chrome trace with one process
    track each, spans aligned onto the wall clock."""
    profiler.reset()
    obs.enable()
    try:
        for rank in range(2):
            obs.start_run(dir=str(tmp_path / "logs"), run_id="mr",
                          rank=rank)
            with obs.trace_span(f"rank{rank}/step", cat="user"):
                pass
        obs.stop_run()
    finally:
        obs.disable()
        profiler.reset()
    events = _load(tmp_path)
    ct = trace_view.build_chrome_trace(events)
    names = {e["args"]["name"] for e in ct["traceEvents"]
             if e.get("ph") == "M"}
    assert any("rank0" in n for n in names), names
    assert any("rank1" in n for n in names), names
    xs = [e for e in ct["traceEvents"] if e["ph"] == "X"]
    assert {e["name"] for e in xs} == {"rank0/step", "rank1/step"}
    assert len({e["pid"] for e in xs}) == 2
    # wall-clock alignment applied (manifest anchors land spans near now)
    import time
    for e in xs:
        assert abs(e["ts"] / 1e6 - time.time()) < 3600


def test_trace_view_cli_and_stats(tmp_path, capsys):
    profiler.reset()
    obs.enable()
    obs.start_run(dir=str(tmp_path / "logs"), run_id="cli", rank=0)
    with obs.trace_span("a", cat="user"):
        pass
    runlog.event("checkpoint_publish", step=1)
    obs.stop_run()
    obs.disable()
    profiler.reset()
    d = str(tmp_path / "logs")
    logs = [os.path.join(d, f) for f in os.listdir(d)]
    out = str(tmp_path / "trace.json")
    assert trace_view.main(logs + ["-o", out]) == 0
    trace = json.load(open(out))
    assert any(e.get("name") == "a" for e in trace["traceEvents"])
    assert any(e.get("ph") == "i" for e in trace["traceEvents"])
    assert trace_view.main(logs + ["--stats"]) == 0
    text = capsys.readouterr().out
    assert "1 process log(s)" in text
    assert "checkpoint_publish=1" in text


# -- acceptance: serving request -> batch -> device step --------------------

def test_serving_connected_trace_under_concurrent_load(tracing, tmp_path):
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(8, 8), nn.ReLU(), nn.Linear(8, 4))
    model.eval()
    import paddle_tpu.serving as serving
    eng = serving.Engine.from_layer(model, [([None, 8], "float32")],
                                    bucket_ladder=(1, 4, 8),
                                    batch_timeout_ms=2.0)
    try:
        def client(seed):
            r = np.random.RandomState(seed)
            for _ in range(5):
                eng.predict(r.rand(r.randint(1, 4), 8)
                            .astype(np.float32))
        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    finally:
        eng.close()
    obs.stop_run()
    events = _load(tmp_path)
    reqs = [e for e in events if e.get("name") == "serving/request"]
    assert len(reqs) == 20
    multi = [e for e in events if e.get("name") == "serving/batch"
             and e["attrs"]["requests"] > 1]
    assert multi, "no coalesced batch under 4-thread load"
    # EVERY request's trace reaches its batch and device step
    for r in reqs:
        con = trace_view.connected_spans(events, r["trace"])
        names = {s["name"] for s in con}
        assert {"serving/request", "serving/queue_wait", "serving/batch",
                "serving/device_step"} <= names, (r["trace"], names)
    # a queue-wait span lives in its request's own trace, under the
    # request span (p99 decomposition per request)
    waits = [e for e in events if e.get("name") == "serving/queue_wait"]
    req_by_key = {(r["trace"], r["span"]): r for r in reqs}
    assert all((w["trace"], w.get("parent")) in req_by_key
               for w in waits)
    # chrome output carries flow arrows for the links
    ct = trace_view.build_chrome_trace(events,
                                       trace_filter=reqs[0]["trace"])
    assert {"s", "f"} <= {e["ph"] for e in ct["traceEvents"]}


# -- acceptance: PS push surviving a retry ---------------------------------

@pytest.mark.skipif(_native.lib() is None, reason="needs native runtime")
def test_ps_push_retry_single_connected_trace(tracing, tmp_path):
    from paddle_tpu.distributed.ps import PsClient, PsServer, TableConfig
    from paddle_tpu.distributed.ps.retry import RetryPolicy

    srv = PsServer([TableConfig(810, "dense", 8, "sgd", lr=0.1)], port=0)
    port = srv.start()
    cli = PsClient([f"127.0.0.1:{port}"],
                   retry_policy=RetryPolicy(max_attempts=4,
                                            base_delay_s=0.01, seed=3),
                   request_id_base=8_100_000)
    try:
        cli.register_dense(810, 8)
        cli.pull_dense_init(810, np.zeros(8, np.float32))
        srv.trace_spans()  # drain the init-call spans
        with faults.scoped("ps/call", exc=ConnectionError, times=1,
                           skip=0):
            with obs.trace_span("train/push", cat="user") as root:
                cli.push_dense_grad(810, np.ones(8, np.float32))
                root_trace = root.trace_id
        # peek WITHOUT draining: srv.stop() moves the ring into the
        # run-log, which is what trace_view reconstructs from
        server_spans = srv.trace_spans(drain=False)
    finally:
        cli.stop_servers()
        srv.stop()
    obs.stop_run()
    events = _load(tmp_path)
    attempts = [e for e in events
                if e.get("name") == "ps/attempt/push_dense_grad"]
    assert len(attempts) == 2  # injected failure + the retry that won
    assert attempts[0]["attrs"]["error"] == "ConnectionError"
    assert all(e["trace"] == f"{root_trace:016x}" for e in attempts)
    retry_ev = [e for e in events if e.get("event") == "ps_retry"]
    assert retry_ev and retry_ev[0]["op"] == "push_dense_grad"
    # the server applied ONCE, in the same trace, parented to the
    # attempt that reached it
    applies = [s for s in server_spans
               if s["name"] == "ps_server/push_dense_grad"]
    assert len(applies) == 1 and applies[0]["dup"] == 0
    assert applies[0]["trace"] == root_trace
    att_ids = {int(e["span"], 16) for e in attempts}
    assert applies[0]["parent"] in att_ids
    # connected through trace_view from the merged logs: the drain in
    # srv.stop() moved the server spans into the run-log already
    con = trace_view.connected_spans(events, f"{root_trace:016x}")
    names = {s["name"] for s in con}
    assert {"train/push", "ps/push_dense_grad",
            "ps/attempt/push_dense_grad",
            "ps_server/push_dense_grad"} <= names, names


@pytest.mark.skipif(_native.lib() is None, reason="needs native runtime")
def test_ps_dedup_ack_recorded_in_trace(tracing):
    """A duplicate push (response lost, client re-sends) records a
    server span marked dup — the retry is visible, the apply is not
    doubled."""
    import struct

    from paddle_tpu.distributed.ps import PsClient, PsServer, TableConfig
    from paddle_tpu.distributed.ps.client import (MAGIC, TRACE_FLAG,
                                                  OP_PUSH_DENSE_GRAD_ID)

    srv = PsServer([TableConfig(811, "dense", 4, "sum")], port=0)
    port = srv.start()
    cli = PsClient([f"127.0.0.1:{port}"], request_id_base=9_000_000)
    try:
        cli.register_dense(811, 4)
        cli.pull_dense_init(811, np.zeros(4, np.float32))
        srv.trace_spans()
        grad = np.ones(4, np.float32)
        # hand-built traced frame: trace ctx prefix + request id + grad,
        # sent twice with the SAME request id = a re-sent push whose
        # first response was lost. Tracing is disabled around the sends
        # so the client does not stack a SECOND auto-context prefix on
        # the hand-built one.
        payload = struct.pack("<QQ", 0x77, 0x88) + \
            struct.pack("<Q", 424242) + grad.tobytes()
        obs.disable()
        try:
            for _ in range(2):
                raw = cli._call_impl(0, OP_PUSH_DENSE_GRAD_ID
                                     | TRACE_FLAG, 811, 0, payload,
                                     idempotent=True)
                assert struct.unpack("<I", raw)[0] == 1
        finally:
            obs.enable()
        spans = srv.trace_spans()
        assert np.allclose(cli.pull_dense(811), 1.0)  # applied ONCE
    finally:
        cli.stop_servers()
        srv.stop()
    # both the apply and the dedup ack are in the ring... the python
    # client stamped its own live context; assert one dup span exists
    pushes = [s for s in spans
              if s["name"] == "ps_server/push_dense_grad"]
    assert len(pushes) == 2
    assert sorted(p["dup"] for p in pushes) == [0, 1]
    # the wire context is echoed verbatim into both server spans
    assert all(p["trace"] == 0x77 and p["parent"] == 0x88
               for p in pushes)


# -- flight recorder --------------------------------------------------------

def test_flight_dump_on_kill_point(tracing, tmp_path):
    flight.install(str(tmp_path / "flight"))
    faults.inject("demo/unit", times=1)
    with pytest.raises(faults.FaultInjected):
        faults.kill_point("demo/unit")
    p = flight.latest_dump()
    assert p is not None
    rec = json.load(open(p))
    assert rec["reason"] == "kill_point"
    assert rec["kill_point"] == "demo/unit"
    assert rec["spans"][-1]["name"] == "fault/demo/unit"
    assert rec["faults"]["fired"]["demo/unit"] == 1
    assert "counters" in rec["metrics"]
    # the fire is in the run-log too
    obs.stop_run()
    events = _load(tmp_path)
    ev = [e for e in events if e.get("event") == "fault_fired"]
    assert ev and ev[0]["point"] == "demo/unit"


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_flight_dump_on_thread_exception(tracing, tmp_path):
    flight.install(str(tmp_path / "flight"))
    before = flight.latest_dump()

    def boom():
        with obs.trace_span("worker/task", cat="user"):
            pass
        raise RuntimeError("worker died")

    t = threading.Thread(target=boom, name="doomed")
    t.start()
    t.join()
    p = flight.latest_dump()
    assert p is not None and p != before
    rec = json.load(open(p))
    assert rec["reason"] == "unhandled_thread_exception"
    assert rec["exception"]["type"] == "RuntimeError"
    assert any(s["name"] == "worker/task" for s in rec["spans"])


def test_flight_dump_is_atomic_and_bounded(tracing, tmp_path):
    flight.install(str(tmp_path / "flight"), ring=32)
    for i in range(50):
        with obs.trace_span(f"s{i}", cat="user"):
            pass
    p = flight.dump("manual")
    rec = json.load(open(p))
    assert len(rec["spans"]) <= 32
    assert rec["spans"][-1]["name"] == "s49"
    assert not [f for f in os.listdir(tmp_path / "flight")
                if f.endswith(".tmp")]


def test_flight_not_installed_is_noop(tmp_path):
    flight.uninstall()
    assert flight.dump("nope") is None
    assert flight.latest_dump(str(tmp_path)) is None


# -- checkpoint stages in the trace ----------------------------------------

def test_checkpoint_stage_spans_and_publish_event(tracing, tmp_path):
    from paddle_tpu import checkpoint
    root = str(tmp_path / "ckpt")
    checkpoint.write_checkpoint(root, 3, {"w.bin": b"x" * 128},
                                meta={"epoch": 1})
    obs.stop_run()
    events = _load(tmp_path)
    names = [e.get("name") for e in events if e.get("kind") == "span"]
    for stage in ("checkpoint/write_data", "checkpoint/write_manifest",
                  "checkpoint/publish", "checkpoint/save"):
        assert stage in names, names
    # stage spans are children inside the save span's trace
    save = [e for e in events if e.get("name") == "checkpoint/save"][0]
    stages = [e for e in events if e.get("name", "").startswith(
        "checkpoint/") and e["name"] != "checkpoint/save"
        and e.get("kind") == "span"]
    assert all(s["trace"] == save["trace"] for s in stages)
    pub = [e for e in events if e.get("event") == "checkpoint_publish"][0]
    assert pub["step"] == 3 and pub["bytes"] == 128 and pub["files"] == 1


# -- satellites -------------------------------------------------------------

def test_prometheus_label_value_escaping():
    assert export_mod.escape_label_value('a"b\\c\nd') == \
        'a\\"b\\\\c\\nd'
    lbl = export_mod.format_labels(table='t"1', op="pull\nsparse")
    assert lbl == '{table="t\\"1",op="pull\\nsparse"}'

    def odd_collector():
        return {"odd_metric" + export_mod.format_labels(
            name='we"ird\nvalue\\x'): 5}

    export_mod.register_collector("odd_test", odd_collector)
    try:
        text = export_mod.prometheus_text()
    finally:
        export_mod.unregister_collector("odd_test")
    line = [ln for ln in text.splitlines() if "odd_metric" in ln
            and not ln.startswith("#")]
    assert len(line) == 1  # ONE line: the newline was escaped
    assert '\\"' in line[0] and "\\n" in line[0]


def test_summary_window_env_and_ctor(monkeypatch):
    s = export_mod.Summary("w_test_a", window=16)
    assert s.window == 16
    monkeypatch.setenv("PADDLE_TPU_SUMMARY_WINDOW", "64")
    s2 = export_mod.Summary("w_test_b")
    assert s2.window == 64
    for i in range(100):
        s2.observe(float(i))
    assert s2.count == 100  # lifetime, beyond the window
    assert s2.quantiles()[0.5] >= 36.0  # only the last 64 in the ring
    assert s2.snapshot()["window"] == 64
    # the ring size is exported as a gauge next to the summary
    name = "w_gauge_test"
    export_mod.summary(name, window=32).observe(1.0)
    text = export_mod.prometheus_text()
    assert f"paddle_tpu_{name}_window 32" in text
    assert f"# TYPE paddle_tpu_{name}_window gauge" in text


def test_concurrent_scrapes_with_writer_threads(tracing):
    """Satellite: /metrics + /healthz scraped concurrently while worker
    threads hammer spans, counters and summaries — every response parses
    (no torn lines), no deadlock, bounded time."""
    from urllib.request import urlopen

    from paddle_tpu import monitor

    export_mod.register_health("scrape_test", lambda: {"status": "ok"})
    server = export_mod.start_http_server(port=0)
    stop = threading.Event()
    errors = []

    def writer(n):
        i = 0
        while not stop.is_set():
            with obs.trace_span(f"w{n}", cat="user"):
                monitor.stat_add("scrape_test_counter", 1)
            export_mod.summary("scrape_test_ms").observe(i % 7)
            export_mod.publish("scrape_test", {"x": float(i)})
            i += 1

    def scraper(path, check):
        try:
            for _ in range(20):
                body = urlopen(
                    f"http://127.0.0.1:{server.port}{path}",
                    timeout=10).read()
                check(body)
        except Exception as e:  # pragma: no cover - failure detail
            errors.append(f"{path}: {e!r}")

    def check_metrics(body):
        for ln in body.decode().splitlines():
            assert ln.startswith("#") or " " in ln, ln

    def check_health(body):
        assert json.loads(body)["status"] == "ok"

    writers = [threading.Thread(target=writer, args=(i,))
               for i in range(3)]
    scrapers = [threading.Thread(target=scraper,
                                 args=("/metrics", check_metrics)),
                threading.Thread(target=scraper,
                                 args=("/healthz", check_health)),
                threading.Thread(target=scraper, args=(
                    "/telemetry.json", lambda b: json.loads(b)))]
    try:
        for t in writers + scrapers:
            t.start()
        for t in scrapers:
            t.join(timeout=60)
            assert not t.is_alive(), "scraper deadlocked"
    finally:
        stop.set()
        for t in writers:
            t.join(timeout=10)
        server.stop()
        export_mod.unregister_health("scrape_test")
    assert not errors, errors


def test_span_leak_lint_rule(tmp_path):
    # paddle_tpu.analysis.lint the MODULE (the package re-exports a
    # lint() function under the same name)
    from paddle_tpu.analysis import lint as _  # noqa: F401
    import paddle_tpu.analysis.lint
    lint = sys.modules["paddle_tpu.analysis.lint"]
    src = tmp_path / "leaky.py"
    src.write_text(
        "from paddle_tpu.observability import tracing as t\n"
        "def ok():\n"
        "    with t.trace_span('a'):\n"
        "        pass\n"
        "    s = t.trace_span('b')\n"
        "    with s:\n"
        "        pass\n"
        "def factory():\n"
        "    return t.trace_span('c')\n"
        "def bare():\n"
        "    t.trace_span('leak')\n"
        "def assigned():\n"
        "    s = t.trace_span('leak2')\n"
        "    s.set_attr(x=1)\n")
    fs = [f for f in lint.lint_source(paths=[str(src)])
          if f.rule == "span-without-context-manager"]
    assert len(fs) == 2
    assert sorted(f.severity for f in fs) == ["error", "warning"]
    # the shipped instrumented paths stay clean under the default scan
    assert not [f for f in lint.lint_source()
                if f.rule == "span-without-context-manager"]


@pytest.mark.skipif(_native.lib() is None, reason="needs native runtime")
def test_drain_server_spans_over_the_wire(tracing, tmp_path):
    """ISSUE 9 satellite (PR-8 open item): a client of a REMOTE server
    drains the service-side span ring over the wire (op 17,
    ``PsClient.drain_server_spans``) into its OWN run-log — the full
    client→server trace then reconstructs from the client-side logs
    alone, no access to the server process needed."""
    from paddle_tpu.distributed.ps import PsClient, PsServer, TableConfig

    srv = PsServer([TableConfig(910, "dense", 8, "sgd", lr=0.1)], port=0)
    port = srv.start()
    cli = PsClient([f"127.0.0.1:{port}"], request_id_base=9_100_000)
    try:
        cli.register_dense(910, 8)
        cli.pull_dense_init(910, np.zeros(8, np.float32))
        with obs.trace_span("train/pull", cat="user") as root:
            cli.pull_dense(910)
            root_trace = root.trace_id
        # peek first (drain=False): rows come back, the ring keeps them
        peek = cli.drain_server_spans(to_runlog=False, drain=False)
        pulls = [r for r in peek if r["name"] == "ps_server/pull_dense"
                 and r["trace"] == root_trace]
        assert len(pulls) == 1
        assert pulls[0]["server"] == f"127.0.0.1:{port}"
        # the real drain records into the run-log AND empties the ring
        rows = cli.drain_server_spans()
        assert [r for r in rows if r["name"] == "ps_server/pull_dense"
                and r["trace"] == root_trace]
        again = cli.drain_server_spans(to_runlog=False)
        assert not [r for r in again
                    if r["name"] == "ps_server/pull_dense"]
    finally:
        cli.stop_servers()
        srv.stop()
    obs.stop_run()
    events = _load(tmp_path)
    # the wire-drained server span landed in the CLIENT's run-log on its
    # own ps_server track ...
    srv_spans = [e for e in events if e.get("process") == "ps_server"
                 and e.get("name") == "ps_server/pull_dense"]
    assert srv_spans and srv_spans[0]["trace"] == f"{root_trace:016x}"
    assert srv_spans[0]["attrs"]["server"] == f"127.0.0.1:{port}"
    # ... and trace_view connects root -> client attempt -> server apply
    con = trace_view.connected_spans(events, f"{root_trace:016x}")
    names = {s["name"] for s in con}
    assert {"train/pull", "ps/pull_dense",
            "ps/attempt/pull_dense", "ps_server/pull_dense"} <= names
