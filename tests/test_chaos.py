"""Deterministic fault injection: the chaos-test tier.

Drives the named kill-points (`paddle_tpu.testing.faults`) instrumented
into the PS RPC client, the serving engine's device step, and the
checkpoint writer (the checkpoint sweep lives in test_checkpoint.py):
injected connection errors must ride the bounded-backoff retry path,
injected latency must trip deadlines, overload must shed FAST, and a
failing device step must resolve every in-flight future without killing
the worker. Everything here is deterministic — counters, seeded jitter,
no real network flakes.
"""
import json
import struct
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import monitor, nn, serving
from paddle_tpu.distributed.ps import client as ps_client_mod
from paddle_tpu.distributed.ps.retry import (DeadlineExceeded, RetryPolicy,
                                             RetriesExhausted)
from paddle_tpu.observability import export as obs_export
from paddle_tpu.testing import faults

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


# -- the harness itself -----------------------------------------------------

class TestFaultHarness:
    def test_unarmed_kill_point_only_counts(self):
        n0 = faults.hits("x/y")
        faults.kill_point("x/y")
        assert faults.hits("x/y") == n0 + 1
        assert faults.fired("x/y") == 0

    def test_times_skip_and_clear(self):
        faults.inject("p", times=2, skip=1)
        faults.kill_point("p")  # skipped
        with pytest.raises(faults.FaultInjected):
            faults.kill_point("p")
        with pytest.raises(faults.FaultInjected):
            faults.kill_point("p")
        faults.kill_point("p")  # exhausted: disarmed
        assert faults.fired("p") == 2 and not faults.armed("p")

    def test_exception_instance_and_latency(self):
        faults.inject("q", exc=ValueError("boom"), latency_s=0.05)
        t0 = time.perf_counter()
        with pytest.raises(ValueError, match="boom"):
            faults.kill_point("q")
        assert time.perf_counter() - t0 >= 0.05

    def test_scoped(self):
        with faults.scoped("s", times=5):
            assert faults.armed("s")
        assert not faults.armed("s")


# -- retry policy -----------------------------------------------------------

class TestRetryPolicy:
    def test_backoff_grows_exponentially_with_bounded_jitter(self):
        pol = RetryPolicy(max_attempts=6, base_delay_s=0.1, max_delay_s=1.0,
                          multiplier=2.0, jitter=0.5, seed=7,
                          sleep=lambda s: None)
        delays = [pol.backoff_s(k) for k in range(2, 7)]
        for i, d in enumerate(delays):
            nominal = min(0.1 * 2.0 ** i, 1.0)
            assert 0.5 * nominal <= d <= 1.5 * nominal, (i, d)
        # seeded jitter replays bit-identically
        pol2 = RetryPolicy(max_attempts=6, base_delay_s=0.1,
                           max_delay_s=1.0, jitter=0.5, seed=7)
        assert delays == [pol2.backoff_s(k) for k in range(2, 7)]

    def test_run_retries_then_succeeds(self):
        sleeps = []
        pol = RetryPolicy(max_attempts=4, base_delay_s=0.01, seed=1,
                          sleep=sleeps.append)
        calls = [0]

        def fn():
            calls[0] += 1
            if calls[0] < 3:
                raise ConnectionError("nope")
            return "ok"

        monitor.stat_reset("ps_retry_total")
        assert pol.run(fn) == "ok"
        assert calls[0] == 3 and len(sleeps) == 2
        assert monitor.stat_get("ps_retry_total") == 2

    def test_exhaustion_chains_last_error(self):
        pol = RetryPolicy(max_attempts=2, base_delay_s=0.001, seed=1)
        with pytest.raises(RetriesExhausted, match="2 attempts"):
            pol.run(lambda: (_ for _ in ()).throw(ConnectionError("x")))

    def test_deadline_fails_fast_not_late(self):
        # clock injectable: the 3rd attempt's backoff would cross the
        # deadline -> DeadlineExceeded BEFORE sleeping, not after
        now = [0.0]
        pol = RetryPolicy(max_attempts=10, base_delay_s=0.4, jitter=0.0,
                          deadline_s=1.0, sleep=lambda s: None,
                          clock=lambda: now[0])

        def fn():
            now[0] += 0.3
            raise ConnectionError("down")

        with pytest.raises(DeadlineExceeded):
            pol.run(fn)
        assert now[0] < 1.5  # failed around the deadline, not attempts x base


# -- PS client under injected faults ---------------------------------------

def _ps_pair(tmp_scope="chaos", **cli_kw):
    from paddle_tpu.distributed.ps import PsClient, PsServer, TableConfig
    srv = PsServer([TableConfig(0, "dense", 0, "sgd", lr=1.0),
                    TableConfig(1000, "sparse", 4, "sgd", lr=1.0)], port=0)
    port = srv.start()
    cli_kw.setdefault("retry_policy",
                      RetryPolicy(max_attempts=4, base_delay_s=0.01,
                                  deadline_s=5.0, seed=3))
    cli_kw.setdefault("request_id_base", 7 << 40)
    cli = PsClient([f"127.0.0.1:{port}"], **cli_kw)
    cli.register_dense(0, 6)
    cli.register_sparse(1000, 4)
    return srv, cli


class TestPsChaos:
    def test_pull_retries_injected_connection_errors(self):
        srv, cli = _ps_pair()
        try:
            cli.pull_dense_init(0, np.zeros(6, np.float32))
            monitor.stat_reset("ps_retry_total")
            faults.inject("ps/call", exc=ConnectionError, times=2)
            v = cli.pull_dense(0)
            assert np.allclose(v, 0.0)
            assert monitor.stat_get("ps_retry_total") == 2
        finally:
            cli.stop_servers()
            srv.stop()
            cli.close()

    def test_push_retry_applies_exactly_once(self):
        """The headline idempotency contract: a push whose first attempt
        dies rides the retry path and the grad lands EXACTLY once."""
        srv, cli = _ps_pair()
        try:
            cli.pull_dense_init(0, np.zeros(6, np.float32))
            faults.inject("ps/call", exc=ConnectionError, times=1)
            cli.push_dense_grad(0, np.ones(6, np.float32))
            assert np.allclose(cli.pull_dense(0), -1.0)  # sgd lr=1
            # sparse too, through the sharded id'd push
            keys = np.array([3, 9], np.uint64)
            faults.inject("ps/call", exc=ConnectionError, times=1)
            cli.push_sparse_grad(1000, keys, np.ones((2, 4), np.float32))
            assert np.allclose(cli.pull_sparse(1000, keys), -1.0)
        finally:
            cli.stop_servers()
            srv.stop()
            cli.close()

    def test_duplicate_request_id_deduped_server_side(self):
        """Raw re-send of the SAME request id (a retry whose original
        DID land but whose response was lost) is acknowledged without
        being applied twice."""
        srv, cli = _ps_pair()
        try:
            cli.pull_dense_init(0, np.zeros(6, np.float32))
            payload = struct.pack("<Q", 424242) + \
                np.ones(6, np.float32).tobytes()
            for _ in range(3):
                cli._check_ok(cli._call(
                    0, ps_client_mod.OP_PUSH_DENSE_GRAD_ID, 0, 0, payload,
                    idempotent=True), 0)
            assert np.allclose(cli.pull_dense(0), -1.0)  # once, not thrice
        finally:
            cli.stop_servers()
            srv.stop()
            cli.close()

    def test_injected_latency_trips_call_deadline(self):
        srv, cli = _ps_pair(
            retry_policy=RetryPolicy(max_attempts=10, base_delay_s=0.01,
                                     deadline_s=0.15, seed=3))
        try:
            cli.pull_dense_init(0, np.zeros(6, np.float32))
            faults.inject("ps/call", exc=ConnectionError, latency_s=0.1,
                          times=5)
            t0 = time.monotonic()
            with pytest.raises(DeadlineExceeded):
                cli.pull_dense(0)
            assert time.monotonic() - t0 < 2.0  # fast-fail, not 10 retries
        finally:
            faults.clear()
            cli.stop_servers()
            srv.stop()
            cli.close()

    def test_barrier_stays_single_shot(self):
        """A barrier arrival must never be silently re-sent (it would
        double-count the worker): an injected failure surfaces raw."""
        srv, cli = _ps_pair()
        try:
            faults.inject("ps/call", exc=ConnectionError, times=1)
            with pytest.raises(ConnectionError, match="non-retriable"):
                cli.barrier(2)
            assert faults.fired("ps/call") == 1  # exactly one attempt
        finally:
            faults.clear()
            cli.stop_servers()
            srv.stop()
            cli.close()


# -- serving engine under injected faults -----------------------------------

def _engine(**kw):
    paddle.seed(0)
    m = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    m.eval()
    kw.setdefault("bucket_ladder", (1, 4))
    kw.setdefault("batch_timeout_ms", 1.0)
    return serving.Engine.from_layer(m, [([None, 8], "float32")], **kw)


_X2 = np.random.RandomState(0).rand(2, 8).astype(np.float32)
_X4 = np.random.RandomState(1).rand(4, 8).astype(np.float32)


class TestServingChaos:
    def test_device_step_failure_resolves_all_futures_no_hang(self):
        """Acceptance (satellite): injected device-step failures reach
        every in-flight future, no caller hangs, and the worker stays
        serviceable for subsequent requests."""
        eng = _engine(max_batch_size=4)
        try:
            eng.predict(_X2)  # warm
            monitor.stat_reset("serving_request_errors_total")
            faults.inject("serving/device_step",
                          exc=RuntimeError("chaos step"), times=1)
            futs = [eng.submit(_X2), eng.submit(_X2)]  # coalesce into one
            errs = 0
            for f in futs:
                with pytest.raises(RuntimeError, match="chaos step"):
                    f.result(timeout=30)
                errs += 1
            assert errs == 2
            assert monitor.stat_get("serving_request_errors_total") == 2
            # worker alive and serving
            out = eng.predict(_X2)
            assert out[0].shape == (2, 4)
            assert eng.health()["status"] == "ok"
        finally:
            eng.close()

    def test_close_during_in_flight_error_still_drains(self):
        """close() racing an erroring device step: the drain completes,
        every accepted future resolves (exceptionally or normally), and
        close returns instead of leaving callers blocked."""
        eng = _engine()
        try:
            eng.predict(_X2)
            faults.inject("serving/device_step", latency_s=0.1,
                          exc=RuntimeError("dying step"), times=1)
            futs = [eng.submit(_X4)]
            time.sleep(0.02)  # worker picks up the failing batch
            futs.append(eng.submit(_X2))  # queued behind the failure
        finally:
            eng.close(timeout=30)
        resolved = 0
        for f in futs:
            try:
                f.result(timeout=5)
                resolved += 1
            except RuntimeError:
                resolved += 1
        assert resolved == 2

    def test_overload_sheds_fast_and_counts(self):
        eng = _engine(max_pending=2)
        try:
            eng.predict(_X2)
            monitor.stat_reset("serving_shed_total")
            faults.inject("serving/device_step", latency_s=0.3, exc=None,
                          times=1)
            futs = [eng.submit(_X4)]  # occupies the worker
            time.sleep(0.05)
            shed = 0
            t0 = time.perf_counter()
            for _ in range(8):
                try:
                    futs.append(eng.submit(_X2))
                except serving.OverloadedError:
                    shed += 1
            assert time.perf_counter() - t0 < 0.2  # fast-fail, no queueing
            assert shed >= 6
            assert monitor.stat_get("serving_shed_total") == shed
            assert eng.stats()["shed"] == shed
            for f in futs:
                f.result(timeout=30)
        finally:
            eng.close()

    def test_queued_request_deadline_expires(self):
        eng = _engine(request_deadline_ms=5000)
        try:
            eng.predict(_X2)
            monitor.stat_reset("serving_deadline_expired_total")
            faults.inject("serving/device_step", latency_s=0.25, exc=None,
                          times=1)
            f_slow = eng.submit(_X4)  # full bucket: runs alone
            time.sleep(0.02)
            f_late = eng.submit(_X2, deadline_ms=50)
            with pytest.raises(serving.DeadlineExceeded):
                f_late.result(timeout=30)
            f_slow.result(timeout=30)
            assert monitor.stat_get("serving_deadline_expired_total") == 1
            assert eng.stats()["deadline_expired"] == 1
        finally:
            eng.close()

    def test_healthz_endpoint_reflects_engine_state(self):
        eng = _engine()
        srv = obs_export.start_http_server(0)
        try:
            url = f"http://127.0.0.1:{srv.port}/healthz"
            h = json.load(urllib.request.urlopen(url))
            assert h["status"] == "ok"
            comp = [c for c in h["components"].values()
                    if c.get("bucket_ladder")]
            assert comp and comp[0]["ready"] and comp[0]["pending"] == 0
            # a closed engine unregisters; a FAILING provider degrades
            eng.close()
            obs_export.register_health("probe_dead",
                                       lambda: {"status": "dead"})
            try:
                with pytest.raises(urllib.error.HTTPError) as exc:
                    urllib.request.urlopen(url)
                assert exc.value.code == 503
                body = json.load(exc.value)
                assert body["status"] == "degraded"
            finally:
                obs_export.unregister_health("probe_dead")
        finally:
            try:
                eng.close()
            except Exception:
                pass
            srv.stop()

    def test_concurrent_clients_with_fault_burst(self):
        """Mixed traffic while a fault burst hits: every request either
        succeeds or fails with the injected error — none hang, and the
        engine serves cleanly afterwards."""
        eng = _engine(max_batch_size=4)
        try:
            eng.predict(_X2)
            faults.inject("serving/device_step",
                          exc=RuntimeError("burst"), times=3, skip=1)
            ok, failed = [], []
            lock = threading.Lock()

            def client(i):
                r = np.random.RandomState(i)
                for _ in range(6):
                    try:
                        eng.predict(r.rand(1 + r.randint(3), 8)
                                    .astype(np.float32))
                        with lock:
                            ok.append(i)
                    except RuntimeError:
                        with lock:
                            failed.append(i)

            threads = [threading.Thread(target=client, args=(i,))
                       for i in range(4)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=60)
            assert not any(t.is_alive() for t in threads)
            assert len(ok) + len(failed) == 24
            assert failed  # the burst hit someone
            assert eng.predict(_X2)[0].shape == (2, 4)
        finally:
            eng.close()


# -- lint rule (satellite) --------------------------------------------------

def test_retry_without_backoff_lint_rule(tmp_path):
    """The CI lint flags retry loops with no backoff/deadline; fan-outs
    (loop var feeds the call) and paced loops stay clean. The default
    --source scan covers the RPC client paths."""
    from paddle_tpu.analysis import lint_source
    from paddle_tpu.analysis.lint import RPC_PATHS
    bad = tmp_path / "bad.py"
    bad.write_text(
        "def hammer(sock, msg):\n"
        "    while True:\n"
        "        try:\n"
        "            sock.sendall(msg)\n"
        "            return sock.recv(4)\n"
        "        except OSError:\n"
        "            continue\n"
        "def bounded(sock, msg):\n"
        "    for _ in range(5):\n"
        "        try:\n"
        "            return sock.sendall(msg)\n"
        "        except OSError:\n"
        "            pass\n"
        "def paced(sock, msg, policy):\n"
        "    for a in range(5):\n"
        "        try:\n"
        "            return sock.sendall(msg)\n"
        "        except OSError:\n"
        "            policy.sleep(a)\n"
        "def fanout(clients, msg):\n"
        "    for i in range(len(clients)):\n"
        "        try:\n"
        "            clients[i].sendall(msg)\n"
        "        except OSError:\n"
        "            pass\n")
    found = [f for f in lint_source(paths=[str(bad)])
             if f.rule == "retry-without-backoff"]
    assert [(f.severity, f.loc.rsplit(":", 1)[1]) for f in found] == \
        [("error", "2"), ("warning", "9")]
    # the shipped RPC paths are clean under the default scan
    repo_findings = [f for f in lint_source()
                     if f.rule == "retry-without-backoff"]
    assert repo_findings == [], repo_findings
    assert any(p.endswith("client.py") for p in RPC_PATHS)


# -- crash flight recorder (PR 8) -------------------------------------------

class TestFlightRecorder:
    """Acceptance (ISSUE 8): a kill-point fire leaves a readable,
    atomically-written flight-recorder dump whose last span matches the
    kill site — the black-box evidence that survives the process."""

    @pytest.fixture(autouse=True)
    def _obs(self, tmp_path):
        import paddle_tpu.observability as obs
        from paddle_tpu import profiler
        from paddle_tpu.observability import flight
        profiler.reset()
        flight.clear()
        obs.enable()
        flight.install(str(tmp_path / "flight"))
        yield obs
        obs.disable()
        flight.uninstall()
        flight.clear()
        profiler.reset()

    def test_checkpoint_kill_leaves_dump_at_kill_site(self, tmp_path):
        from paddle_tpu import checkpoint
        from paddle_tpu.observability import flight

        root = str(tmp_path / "ckpt")
        checkpoint.write_checkpoint(root, 1, {"w.bin": b"ok" * 64})
        faults.inject("checkpoint/manifest_partial", times=1)
        with pytest.raises(faults.FaultInjected):
            checkpoint.write_checkpoint(root, 2, {"w.bin": b"xx" * 64})
        path = flight.latest_dump()
        assert path is not None
        rec = json.load(open(path))
        assert rec["reason"] == "kill_point"
        assert rec["kill_point"] == "checkpoint/manifest_partial"
        # the LAST span in the ring is the kill site itself, and the
        # stage spans before it show how far the writer got
        assert rec["spans"][-1]["name"] == \
            "fault/checkpoint/manifest_partial"
        earlier = {s["name"] for s in rec["spans"][:-1]}
        assert "checkpoint/write_data" in earlier
        # fault state + metrics snapshot are embedded
        assert rec["faults"]["fired"]["checkpoint/manifest_partial"] == 1
        assert rec["metrics"]["counters"].get(
            "checkpoint_saves_total", 0) >= 1
        # and the torn write did NOT poison restore (PR-7 contract)
        got = checkpoint.read_checkpoint(root)
        assert got is not None and got[0] == 1

    def test_serving_device_step_kill_dump(self, tmp_path):
        import paddle_tpu.observability as obs
        from paddle_tpu.observability import flight

        paddle.seed(0)
        model = nn.Sequential(nn.Linear(4, 4))
        model.eval()
        eng = serving.Engine.from_layer(
            model, [([None, 4], "float32")], bucket_ladder=(1, 2),
            batch_timeout_ms=1.0)
        try:
            eng.predict(np.ones((1, 4), np.float32))  # healthy first
            faults.inject("serving/device_step", times=1)
            with pytest.raises(faults.FaultInjected):
                eng.predict(np.ones((1, 4), np.float32))
            rec = json.load(open(flight.latest_dump()))
            assert rec["kill_point"] == "serving/device_step"
            assert rec["spans"][-1]["name"] == \
                "fault/serving/device_step"
            # worker survived: the engine still serves
            out = eng.predict(np.ones((1, 4), np.float32))
            assert out[0].shape == (1, 4)
        finally:
            eng.close()
