"""API-freeze tooling (reference: tools/print_signatures.py +
check_api_compatible.py gating CI on paddle/fluid/API.spec)."""
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_api_surface_matches_spec():
    """The committed API.spec must match the live surface — a failing run
    means an API was removed/changed without refreshing the spec."""
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools",
                                      "check_api_compatible.py")],
        capture_output=True, text=True, cwd=REPO, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert r.returncode == 0, r.stdout[-4000:] + r.stderr[-2000:]


def test_checker_flags_removal(tmp_path):
    spec = os.path.join(REPO, "API.spec")
    with open(spec) as f:
        lines = f.readlines()
    # a fake frozen entry that no longer exists must fail the gate
    fake = "paddle_tpu.definitely_removed_api function(x)\n"
    alt = tmp_path / "API.spec"
    alt.write_text("".join(lines) + fake)
    code = (
        "import sys, importlib\n"
        f"sys.path.insert(0, {os.path.join(REPO, 'tools')!r})\n"
        f"sys.path.insert(0, {REPO!r})\n"
        "import print_signatures, check_api_compatible\n"
        f"print_signatures.SPEC_PATH = {str(alt)!r}\n"
        f"check_api_compatible.SPEC_PATH = {str(alt)!r}\n"
        "sys.exit(check_api_compatible.main())\n")
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, cwd=REPO, timeout=300,
                       env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert r.returncode == 1, r.stdout[-2000:]
    assert "REMOVED" in r.stdout
