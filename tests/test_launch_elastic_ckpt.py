"""Launcher + elastic manager + auto-checkpoint + fs utils.

Mirrors reference tests: test_launch_coverage / fleet launch tests (process
spawn + env contract), elastic unit tests (membership, re-rank), and
auto_checkpoint tests (epoch-resume).
"""
import json
import os
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed import launch
from paddle_tpu.distributed.fleet.elastic import (
    ElasticManager, ElasticStatus, FileKVStore,
)
from paddle_tpu.distributed.fleet.utils.fs import LocalFS
from paddle_tpu.incubate.auto_checkpoint import TrainEpochRange


def test_cluster_env_contract(tmp_path):
    """start_local_trainers sets the reference env contract on children."""
    script = tmp_path / "probe.py"
    script.write_text(textwrap.dedent("""
        import json, os, sys
        out = {k: os.environ.get(k) for k in (
            "PADDLE_TRAINER_ID", "PADDLE_TRAINERS_NUM",
            "PADDLE_CURRENT_ENDPOINT", "PADDLE_TRAINER_ENDPOINTS",
            "JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES",
            "JAX_PROCESS_ID")}
        path = os.environ["PROBE_OUT"] + os.environ["PADDLE_TRAINER_ID"]
        open(path, "w").write(json.dumps(out))
    """))
    eps = ["127.0.0.1:6170", "127.0.0.1:6171"]
    cluster = launch.get_cluster(["127.0.0.1"], "127.0.0.1", eps, 2)
    procs = launch.start_local_trainers(
        cluster, cluster.pods[0], str(script), [],
        envs={"PROBE_OUT": str(tmp_path / "out")})
    deadline = time.time() + 30
    while launch.watch_local_trainers(procs) and time.time() < deadline:
        time.sleep(0.1)
    got0 = json.loads((tmp_path / "out0").read_text())
    got1 = json.loads((tmp_path / "out1").read_text())
    assert got0["PADDLE_TRAINER_ID"] == "0"
    assert got1["PADDLE_TRAINER_ID"] == "1"
    assert got0["PADDLE_TRAINERS_NUM"] == "2"
    assert got0["PADDLE_TRAINER_ENDPOINTS"] == ",".join(eps)
    assert got1["PADDLE_CURRENT_ENDPOINT"] == eps[1]
    assert got0["JAX_COORDINATOR_ADDRESS"] == eps[0]
    assert got1["JAX_PROCESS_ID"] == "1"


def test_watch_aborts_all_on_failure(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("import sys, os\n"
                   "sys.exit(3 if os.environ['PADDLE_TRAINER_ID']=='0' "
                   "else (__import__('time').sleep(60) or 0))\n")
    eps = ["127.0.0.1:6270", "127.0.0.1:6271"]
    cluster = launch.get_cluster(["127.0.0.1"], "127.0.0.1", eps, 2)
    procs = launch.start_local_trainers(cluster, cluster.pods[0],
                                        str(bad), [])
    with pytest.raises(RuntimeError, match="rank 0 failed"):
        deadline = time.time() + 30
        while time.time() < deadline:
            procs = launch.watch_local_trainers(procs)
            if not procs:
                break
            time.sleep(0.1)
    # the sleeping rank was terminated too
    for tp in procs:
        assert tp.proc.poll() is not None or True  # already reaped


def test_launch_main_end_to_end(tmp_path):
    ok = tmp_path / "ok.py"
    ok.write_text("import os\n"
                  "open(os.environ['OUT'] + os.environ['PADDLE_TRAINER_ID'],"
                  " 'w').write('done')\n")
    os.environ["OUT"] = str(tmp_path / "r")
    try:
        rc = launch.main(["--nproc_per_node", "2", "--started_port", "6370",
                          str(ok)])
    finally:
        del os.environ["OUT"]
    assert rc == 0
    assert (tmp_path / "r0").exists() and (tmp_path / "r1").exists()


def test_elastic_membership_and_rerank(tmp_path):
    store = FileKVStore(str(tmp_path / "kv"))
    a = ElasticManager("host-a:6170", np=2, store=store, ttl=5,
                       heartbeat_interval=0.2)
    b = ElasticManager("host-b:6170", np=2, store=store, ttl=5,
                       heartbeat_interval=0.2)
    a.register()
    b.register()
    assert a.wait_ready(timeout=5)
    assert a.live_nodes() == ["host-a:6170", "host-b:6170"]
    assert a.rank() == 0 and b.rank() == 1
    # node b leaves -> membership changes, a re-ranks, status HOLD (below np)
    baseline = a.live_nodes()
    b.exit()
    status, nodes = a.watch(interval=0.1, baseline=baseline)
    assert status == ElasticStatus.HOLD
    assert nodes == ["host-a:6170"] and a.rank() == 0
    a.exit()


def test_elastic_ttl_expiry(tmp_path):
    store = FileKVStore(str(tmp_path / "kv"))
    m = ElasticManager("host-x:1", np=1, store=store, ttl=1,
                       heartbeat_interval=10)  # heartbeat slower than ttl
    store.put("nodes/host-x:1", "host-x:1")
    assert m.live_nodes() == ["host-x:1"]
    time.sleep(1.2)
    assert m.live_nodes() == []  # stale entry aged out


def test_auto_checkpoint_resume(tmp_path, monkeypatch):
    monkeypatch.setenv("PADDLE_AUTO_CHECKPOINT_DIR", str(tmp_path))
    monkeypatch.setenv("PADDLE_JOB_ID", "job_test")
    paddle.seed(0)
    model = paddle.nn.Linear(4, 2)
    opt = paddle.optimizer.Adam(parameters=model.parameters())

    run1 = []
    tr = TrainEpochRange(5, "demo").add_model(model).add_optimizer(opt)
    for epoch in tr:
        run1.append(epoch)
        if epoch == 2:
            break  # crash mid-epoch-2: its end-of-epoch save never runs

    # "restart": fresh objects, same job
    paddle.seed(123)
    model2 = paddle.nn.Linear(4, 2)
    opt2 = paddle.optimizer.Adam(parameters=model2.parameters())
    tr2 = TrainEpochRange(5, "demo").add_model(model2).add_optimizer(opt2)
    run2 = list(tr2)
    assert run1 == [0, 1, 2]
    assert run2 == [2, 3, 4]  # epoch 2 re-runs (it never completed)
    # weights restored from the epoch-1 checkpoint
    np.testing.assert_allclose(np.asarray(model2.weight.numpy()),
                               np.asarray(model.weight.numpy()))


def test_local_fs(tmp_path):
    fs = LocalFS()
    d = str(tmp_path / "a")
    fs.mkdirs(d)
    assert fs.is_dir(d)
    f = os.path.join(d, "x.txt")
    fs.touch(f)
    assert fs.is_file(f)
    dirs, files = fs.ls_dir(d)
    assert files == ["x.txt"] and dirs == []
    fs.mv(f, os.path.join(d, "y.txt"))
    assert not fs.is_exist(f) and fs.is_file(os.path.join(d, "y.txt"))
    fs.delete(d)
    assert not fs.is_exist(d)
