"""Launcher + elastic manager + auto-checkpoint + fs utils.

Mirrors reference tests: test_launch_coverage / fleet launch tests (process
spawn + env contract), elastic unit tests (membership, re-rank), and
auto_checkpoint tests (epoch-resume).
"""
import json
import os
import re
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed import launch
from paddle_tpu.distributed.fleet.elastic import (
    ElasticManager, ElasticStatus, FileKVStore,
)
from paddle_tpu.distributed.fleet.utils.fs import LocalFS
from paddle_tpu.incubate.auto_checkpoint import TrainEpochRange


def test_cluster_env_contract(tmp_path):
    """start_local_trainers sets the reference env contract on children."""
    script = tmp_path / "probe.py"
    script.write_text(textwrap.dedent("""
        import json, os, sys
        out = {k: os.environ.get(k) for k in (
            "PADDLE_TRAINER_ID", "PADDLE_TRAINERS_NUM",
            "PADDLE_CURRENT_ENDPOINT", "PADDLE_TRAINER_ENDPOINTS",
            "JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES",
            "JAX_PROCESS_ID")}
        path = os.environ["PROBE_OUT"] + os.environ["PADDLE_TRAINER_ID"]
        open(path, "w").write(json.dumps(out))
    """))
    eps = ["127.0.0.1:6170", "127.0.0.1:6171"]
    cluster = launch.get_cluster(["127.0.0.1"], "127.0.0.1", eps, 2)
    procs = launch.start_local_trainers(
        cluster, cluster.pods[0], str(script), [],
        envs={"PROBE_OUT": str(tmp_path / "out")})
    deadline = time.time() + 30
    while launch.watch_local_trainers(procs) and time.time() < deadline:
        time.sleep(0.1)
    got0 = json.loads((tmp_path / "out0").read_text())
    got1 = json.loads((tmp_path / "out1").read_text())
    assert got0["PADDLE_TRAINER_ID"] == "0"
    assert got1["PADDLE_TRAINER_ID"] == "1"
    assert got0["PADDLE_TRAINERS_NUM"] == "2"
    assert got0["PADDLE_TRAINER_ENDPOINTS"] == ",".join(eps)
    assert got1["PADDLE_CURRENT_ENDPOINT"] == eps[1]
    assert got0["JAX_COORDINATOR_ADDRESS"] == eps[0]
    assert got1["JAX_PROCESS_ID"] == "1"


def test_watch_aborts_all_on_failure(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("import sys, os\n"
                   "sys.exit(3 if os.environ['PADDLE_TRAINER_ID']=='0' "
                   "else (__import__('time').sleep(60) or 0))\n")
    eps = ["127.0.0.1:6270", "127.0.0.1:6271"]
    cluster = launch.get_cluster(["127.0.0.1"], "127.0.0.1", eps, 2)
    procs = launch.start_local_trainers(cluster, cluster.pods[0],
                                        str(bad), [])
    with pytest.raises(RuntimeError, match="rank 0 failed"):
        deadline = time.time() + 30
        while time.time() < deadline:
            procs = launch.watch_local_trainers(procs)
            if not procs:
                break
            time.sleep(0.1)
    # the sleeping rank was terminated too
    for tp in procs:
        assert tp.proc.poll() is not None or True  # already reaped


def test_launch_main_end_to_end(tmp_path):
    ok = tmp_path / "ok.py"
    ok.write_text("import os\n"
                  "open(os.environ['OUT'] + os.environ['PADDLE_TRAINER_ID'],"
                  " 'w').write('done')\n")
    os.environ["OUT"] = str(tmp_path / "r")
    try:
        rc = launch.main(["--nproc_per_node", "2", "--started_port", "6370",
                          str(ok)])
    finally:
        del os.environ["OUT"]
    assert rc == 0
    assert (tmp_path / "r0").exists() and (tmp_path / "r1").exists()


def test_elastic_membership_and_rerank(tmp_path):
    store = FileKVStore(str(tmp_path / "kv"))
    a = ElasticManager("host-a:6170", np=2, store=store, ttl=5,
                       heartbeat_interval=0.2)
    b = ElasticManager("host-b:6170", np=2, store=store, ttl=5,
                       heartbeat_interval=0.2)
    a.register()
    b.register()
    assert a.wait_ready(timeout=5)
    assert a.live_nodes() == ["host-a:6170", "host-b:6170"]
    assert a.rank() == 0 and b.rank() == 1
    # node b leaves -> membership changes, a re-ranks, status HOLD (below np)
    baseline = a.live_nodes()
    b.exit()
    status, nodes = a.watch(interval=0.1, baseline=baseline)
    assert status == ElasticStatus.HOLD
    assert nodes == ["host-a:6170"] and a.rank() == 0
    a.exit()


def test_elastic_ttl_expiry(tmp_path):
    store = FileKVStore(str(tmp_path / "kv"))
    m = ElasticManager("host-x:1", np=1, store=store, ttl=1,
                       heartbeat_interval=10)  # heartbeat slower than ttl
    store.put(f"{m.job_id}/nodes/host-x:1", "host-x:1")
    assert m.live_nodes() == ["host-x:1"]
    time.sleep(1.2)
    assert m.live_nodes() == []  # stale entry aged out


def test_auto_checkpoint_resume(tmp_path, monkeypatch):
    monkeypatch.setenv("PADDLE_AUTO_CHECKPOINT_DIR", str(tmp_path))
    monkeypatch.setenv("PADDLE_JOB_ID", "job_test")
    paddle.seed(0)
    model = paddle.nn.Linear(4, 2)
    opt = paddle.optimizer.Adam(parameters=model.parameters())

    run1 = []
    tr = TrainEpochRange(5, "demo").add_model(model).add_optimizer(opt)
    for epoch in tr:
        run1.append(epoch)
        if epoch == 2:
            break  # crash mid-epoch-2: its end-of-epoch save never runs

    # "restart": fresh objects, same job
    paddle.seed(123)
    model2 = paddle.nn.Linear(4, 2)
    opt2 = paddle.optimizer.Adam(parameters=model2.parameters())
    tr2 = TrainEpochRange(5, "demo").add_model(model2).add_optimizer(opt2)
    run2 = list(tr2)
    assert run1 == [0, 1, 2]
    assert run2 == [2, 3, 4]  # epoch 2 re-runs (it never completed)
    # weights restored from the epoch-1 checkpoint
    np.testing.assert_allclose(np.asarray(model2.weight.numpy()),
                               np.asarray(model.weight.numpy()))


def test_local_fs(tmp_path):
    fs = LocalFS()
    d = str(tmp_path / "a")
    fs.mkdirs(d)
    assert fs.is_dir(d)
    f = os.path.join(d, "x.txt")
    fs.touch(f)
    assert fs.is_file(f)
    dirs, files = fs.ls_dir(d)
    assert files == ["x.txt"] and dirs == []
    fs.mv(f, os.path.join(d, "y.txt"))
    assert not fs.is_exist(f) and fs.is_file(os.path.join(d, "y.txt"))
    fs.delete(d)
    assert not fs.is_exist(d)


class TestElasticFaultInjection:
    """Kill a worker mid-epoch; assert the survivor detects the fault,
    the replacement re-ranks in, and training resumes from the
    auto-checkpoint — the reference's etcd watch/re-rank/relaunch cycle
    (elastic.py:99,316) against the in-framework TCP KV service."""

    def _spawn_node(self, endpoint, kv_port, ckpt_dir, victim_epoch=-1):
        env = dict(os.environ)
        env.update({
            "ELASTIC_ENDPOINT": endpoint,
            "PADDLE_ELASTIC_KV_ENDPOINT": f"127.0.0.1:{kv_port}",
            "PADDLE_ELASTIC_NP": "2",
            "PADDLE_AUTO_CHECKPOINT_DIR": ckpt_dir,
            "PADDLE_JOB_ID": "elastic_fault_job",  # auto_checkpoint scope
            "PADDLE_ELASTIC_JOB_ID": "elastic_fault_job",  # KV key scope
            "VICTIM_EPOCH": str(victim_epoch),
            "JAX_PLATFORMS": "cpu",
        })
        fixture = os.path.join(os.path.dirname(__file__), "fixtures",
                               "elastic_node_fixture.py")
        script = ("import jax; jax.config.update('jax_platforms','cpu');"
                  "import runpy; runpy.run_path(%r, run_name='__main__')"
                  % fixture)
        return subprocess.Popen([sys.executable, "-c", script],
                                stdout=subprocess.PIPE,
                                stderr=subprocess.PIPE, text=True, env=env,
                                cwd=os.path.dirname(os.path.dirname(
                                    os.path.abspath(__file__))))

    @pytest.mark.slow  # ~17 s launcher relaunch e2e; rerank + resume
    # stay tier-1-covered by test_multiprocess_dist + test_checkpoint
    def test_kill_worker_rerank_relaunch_resume(self, tmp_path):
        from paddle_tpu.distributed.fleet.elastic import start_kv_server
        srv, kv_port = start_kv_server(host="127.0.0.1")
        try:
            ckpt = str(tmp_path / "ckpt")
            os.makedirs(ckpt, exist_ok=True)
            # endpoints sort: survivor keeps rank 0 after the re-rank
            n0 = self._spawn_node("127.0.0.1:20001", kv_port, ckpt)
            n1 = self._spawn_node("127.0.0.1:20002", kv_port, ckpt,
                                  victim_epoch=2)
            # victim dies mid-epoch 2 (communicate drains both pipes —
            # a full stderr buffer must not deadlock the child)
            out1, _err1 = n1.communicate(timeout=120)
            assert n1.returncode == 1
            # the "scheduler" waits for the dead node's lease to expire
            # (the survivor must observe the membership SHRINK first)
            from paddle_tpu.distributed.fleet.elastic import TcpKVStore
            import time as _time
            mon = TcpKVStore(f"127.0.0.1:{kv_port}")
            deadline = _time.time() + 30
            while _time.time() < deadline:
                if len(mon.list("elastic_fault_job/nodes/", ttl=3)) <= 1:
                    break
                _time.sleep(0.2)
            mon.close()
            # scheduler relaunches a replacement node
            n2 = self._spawn_node("127.0.0.1:20003", kv_port, ckpt)
            out0, err0 = n0.communicate(timeout=180)
            out2, err2 = n2.communicate(timeout=180)
            assert n0.returncode == 0, err0[-2000:]
            assert n2.returncode == 0, err2[-2000:]

            # victim trained epochs 0..2 as rank 1, then died (no DONE)
            assert "RANK 1 nodes=2" in out1 and "DONE" not in out1

            # survivor: detected the fault, re-ranked (still rank 0 by
            # sorted endpoints), resumed from checkpoint — NOT epoch 0
            assert "INTERRUPTED" in out0, out0
            resumes = re.findall(r"RESUME_FROM (\d+)", out0)
            assert resumes[0] == "0"
            assert int(resumes[1]) >= 1  # checkpoint resume, not restart
            assert out0.count("RANK 0") >= 2  # re-ranked after the fault
            assert "DONE" in out0

            # replacement: joined as rank 1, resumed from the job
            # checkpoint rather than epoch 0
            assert "RANK 1 nodes=2" in out2, out2
            m = re.search(r"RESUME_FROM (\d+)", out2)
            assert m and int(m.group(1)) >= 1, out2
            assert "DONE" in out2
        finally:
            srv.shutdown()
